"""Compiled query pipelines: whole-plan jit with static shapes.

The eager executor (physical/rel/executor.py) dispatches one XLA op at a
time; over a remote TPU every dispatch is a host round trip and every
data-dependent shape (boolean compaction, ``jnp.unique``) is a blocking sync.
This module is the TPU-first answer (SURVEY §7 "hard parts" item 2): a query
plan is traced ONCE into a single jitted program with *static shapes* —
filters keep rows and flip a validity mask instead of compacting, GROUP BY
factorizes via an in-trace lexsort with a static group-capacity bound, and
equi-joins probe a sorted build side via ``searchsorted`` — then the program
is cached keyed by (plan fingerprint, input table identity/shape). Steady
state is ONE device dispatch + one tiny flags transfer per query.

Runtime conditions XLA cannot express statically (group-count overflow,
non-unique build side, 64-bit hash collision) surface through a flags vector;
the host reacts by recompiling with a larger capacity or falling back to the
eager executor. Unsupported plan shapes (UDFs, scalar subqueries, windows,
host-bound string ops) are detected at trace time and cached as such, so the
fallback costs nothing at steady state.

The reference has no analogue — its dask graphs are dynamically scheduled
(SURVEY §2.3); this is the "compiled SPMD stages replace the dynamic
scheduler" design of SURVEY §5.
"""
from __future__ import annotations

import logging
import os
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import groupby as G
from ..ops.kernels import comparable_data, unify_string_codes
from ..plan.nodes import (
    LogicalAggregate, LogicalFilter, LogicalJoin, LogicalProject, LogicalSort,
    LogicalTableScan, LogicalUnion, LogicalValues, RelNode, RexCall,
    RexInputRef, RexLiteral, RexNode,
)
from ..table import Column, Scalar, Table
from .rex.evaluate import evaluate_predicate, evaluate_rex

logger = logging.getLogger(__name__)

_INT64_MIN = jnp.int64(-(2**63))
_U64_MAX = np.uint64(0xFFFFFFFFFFFFFFFF)
_GOLDEN = np.uint64(0x9E3779B97F4A7C15)

DEFAULT_GROUP_CAP = 4096
_CACHE_LIMIT = 128

# ops whose kernels are host-bound or non-deterministic: never compile
_DENY_OPS = {"RAND", "RAND_INTEGER"}

stats = {"compiles": 0, "hits": 0, "fallbacks": 0, "unsupported": 0,
         "recompiles": 0}


class Unsupported(Exception):
    """Plan (or expression) outside the compilable subset."""


# ---------------------------------------------------------------------------
# fingerprinting
# ---------------------------------------------------------------------------

def _fp_rex(rex: RexNode) -> str:
    if isinstance(rex, RexInputRef):
        return f"@{rex.index}"
    if isinstance(rex, RexLiteral):
        return f"L{rex.stype.name}:{rex.value!r}"
    if isinstance(rex, RexCall):
        if rex.op in _DENY_OPS:
            raise Unsupported(rex.op)
        extra = ""
        info = getattr(rex, "info", None)
        if info is not None:
            extra = f"!{getattr(info, 'name', info)}"
        return (f"C{rex.op}{extra}[" + ",".join(_fp_rex(o) for o in rex.operands)
                + f"]:{rex.stype.name}")
    raise Unsupported(type(rex).__name__)


def _fp_plan(rel: RelNode, context, scans: list) -> str:
    """Serialize the plan for cache keying; collects scan tables."""
    t = type(rel).__name__
    schema = ";".join(f"{f.name}:{f.stype.name}" for f in rel.schema)
    if isinstance(rel, LogicalTableScan):
        entry = context.schema[rel.schema_name].tables[rel.table_name]
        if entry.table is None:
            raise Unsupported("view scan")
        if entry.table.num_rows == 0:
            raise Unsupported("empty table")
        scans.append(((rel.schema_name, rel.table_name), entry.table))
        return f"Scan({rel.schema_name}.{rel.table_name})[{schema}]"
    if isinstance(rel, LogicalProject):
        body = ",".join(_fp_rex(e) for e in rel.exprs)
    elif isinstance(rel, LogicalFilter):
        body = _fp_rex(rel.condition)
    elif isinstance(rel, LogicalAggregate):
        for agg in rel.aggs:
            if agg.udaf is not None or agg.distinct:
                raise Unsupported("udaf/distinct agg")
            if agg.op in ("LISTAGG", "BIT_AND", "BIT_OR", "BIT_XOR"):
                raise Unsupported(agg.op)
        body = (f"g={rel.group_keys}|" + ",".join(
            f"{a.op}({a.args})f{a.filter_arg}" for a in rel.aggs))
    elif isinstance(rel, LogicalJoin):
        if rel.join_type not in ("INNER", "LEFT", "RIGHT", "SEMI", "ANTI"):
            raise Unsupported(rel.join_type)
        if getattr(rel, "null_aware", False):
            raise Unsupported("null-aware anti join")
        cond = "T" if rel.condition is None else _fp_rex(rel.condition)
        body = f"{rel.join_type}|{cond}"
    elif isinstance(rel, LogicalSort):
        body = (",".join(f"{c.index}{'a' if c.ascending else 'd'}"
                         f"{'nf' if c.effective_nulls_first else 'nl'}"
                         for c in rel.collation)
                + f"|o={rel.offset}|l={rel.limit}")
    elif isinstance(rel, LogicalUnion):
        body = f"all={rel.all}"
    elif isinstance(rel, LogicalValues):
        body = repr([[lit.value for lit in row] for row in rel.rows])
    else:
        raise Unsupported(type(rel).__name__)
    kids = ",".join(_fp_plan(i, context, scans) for i in rel.inputs)
    return f"{t}({body})[{schema}]<{kids}>"


def _fp_inputs(scans: list) -> tuple:
    out = []
    for _, tbl in scans:
        cols = tuple(
            (c.data.shape, str(c.data.dtype), c.mask is not None,
             id(c.dictionary) if c.dictionary is not None else 0)
            for c in tbl.columns)
        out.append((id(tbl), cols))
    return tuple(out)


# ---------------------------------------------------------------------------
# in-trace kernels
# ---------------------------------------------------------------------------

def _orderable_int64(x: jax.Array) -> jax.Array:
    """Total-order int64 key: floats via IEEE bit trick (-0.0 == +0.0)."""
    if jnp.issubdtype(x.dtype, jnp.floating):
        x = x.astype(jnp.float64) + 0.0  # canonicalize -0.0
        b = jax.lax.bitcast_convert_type(x, jnp.int64)
        return jnp.where(b < 0, (~b) ^ _INT64_MIN, b)
    if x.dtype == jnp.bool_:
        return x.astype(jnp.int64)
    return x.astype(jnp.int64)


def _mix64(z: jax.Array) -> jax.Array:
    z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return z ^ (z >> np.uint64(31))


class _VT:
    """A padded device table + row-validity mask (None = all rows valid)."""

    __slots__ = ("table", "valid")

    def __init__(self, table: Table, valid: Optional[jax.Array]):
        self.table = table
        self.valid = valid

    @property
    def n(self) -> int:
        return self.table.num_rows

    def vmask(self) -> jax.Array:
        if self.valid is None:
            return jnp.ones(self.n, dtype=bool)
        return self.valid


def _key_parts(cols: List[Column]) -> List[Tuple[jax.Array, jax.Array]]:
    """(orderable int64 data with NULL->INT64_MIN, null flag) per key column."""
    out = []
    for c in cols:
        d = _orderable_int64(comparable_data(c))
        if c.mask is not None:
            null = ~c.mask
            d = jnp.where(null, _INT64_MIN, d)
        else:
            null = jnp.zeros(d.shape[0], dtype=bool)
        out.append((d, null))
    return out


def _group_sort(parts, invalid_row: jax.Array) -> jax.Array:
    """Stable permutation: invalid rows last; keys null-first ascending."""
    arrays = []
    for d, null in reversed(parts):
        arrays.append(d)
        # NULL sorts first (matching the eager factorize); the flag also
        # disambiguates real INT64_MIN values from the NULL data sentinel
        arrays.append(jnp.where(null, jnp.int8(0), jnp.int8(1)))
    arrays.append(invalid_row.astype(jnp.int8))  # primary: valid rows first
    return jnp.lexsort(arrays)


def _traced_factorize(key_cols: List[Column], row_valid: Optional[jax.Array],
                      cap: int):
    """GROUP BY factorize inside a trace.

    Returns (codes[n] in [0..cap] where cap = trash slot for invalid rows and
    group overflow, first_rows[cap], num_groups device scalar). Group order
    matches the eager factorize (null-first, ascending per key).
    """
    n = len(key_cols[0])
    parts = _key_parts(key_cols)
    invalid = jnp.zeros(n, dtype=bool) if row_valid is None else ~row_valid
    perm = _group_sort(parts, invalid)

    valid_sorted = ~invalid[perm]
    boundary = jnp.zeros(n, dtype=bool).at[0].set(True)
    for d, null in parts:
        ds, ns = d[perm], null[perm]
        diff = jnp.concatenate([jnp.ones(1, bool),
                                (ds[1:] != ds[:-1]) | (ns[1:] != ns[:-1])])
        boundary = boundary | diff
    boundary = boundary & valid_sorted
    codes_sorted = jnp.cumsum(boundary.astype(jnp.int64)) - 1
    # last valid row's code + 1; if no valid rows, 0
    num_groups = jnp.where(valid_sorted.any(),
                           jnp.max(jnp.where(valid_sorted, codes_sorted, -1)) + 1,
                           0)
    codes_sorted = jnp.where(valid_sorted, jnp.minimum(codes_sorted, cap), cap)
    codes = jnp.zeros(n, dtype=jnp.int64).at[perm].set(codes_sorted)
    first = jax.ops.segment_min(jnp.arange(n, dtype=jnp.int64), codes, cap + 1)[:cap]
    return codes, first, num_groups


def _join_key_parts(lcols: List[Column], rcols: List[Column]):
    """Per-key canonical int64 arrays on a shared domain for both sides."""
    lparts, rparts = [], []
    for lc, rc in zip(lcols, rcols):
        if lc.stype.is_string or rc.stype.is_string:
            la, ra = unify_string_codes([lc, rc])
            la, ra = la.astype(jnp.int64), ra.astype(jnp.int64)
        else:
            dt = jnp.promote_types(lc.data.dtype, rc.data.dtype)
            la = _orderable_int64(lc.data.astype(dt))
            ra = _orderable_int64(rc.data.astype(dt))
        lparts.append(la)
        rparts.append(ra)
    return lparts, rparts


def _hash_parts(parts: List[jax.Array], key_valid: jax.Array) -> jax.Array:
    h = jnp.full(parts[0].shape, _GOLDEN, dtype=jnp.uint64)
    for p in parts:
        h = _mix64(h + p.astype(jnp.uint64) + _GOLDEN)
    h = jnp.where(h == _U64_MAX, _U64_MAX - np.uint64(1), h)
    return jnp.where(key_valid, h, _U64_MAX)


def _keys_valid(cols: List[Column], row_valid: Optional[jax.Array]) -> jax.Array:
    v = jnp.ones(len(cols[0]), dtype=bool) if row_valid is None else row_valid
    for c in cols:
        if c.mask is not None:
            v = v & c.mask
    return v


# ---------------------------------------------------------------------------
# the tracer
# ---------------------------------------------------------------------------

class _Tracer:
    def __init__(self, context, scan_tables: Dict[tuple, Table],
                 caps: Dict[str, int]):
        self.context = context
        self.scan_tables = scan_tables
        self.caps = caps
        self.fallback: List[jax.Array] = []      # device bools -> eager rerun
        self.ngroups: List[jax.Array] = []        # device ints, order = walk
        self.ngroup_caps: List[int] = []          # matching static caps
        self._agg_counter = 0

    # -- dispatch ----------------------------------------------------------
    def run(self, rel: RelNode) -> _VT:
        m = getattr(self, "_" + type(rel).__name__, None)
        if m is None:
            raise Unsupported(type(rel).__name__)
        return m(rel)

    # -- nodes -------------------------------------------------------------
    def _LogicalTableScan(self, rel: LogicalTableScan) -> _VT:
        t = self.scan_tables[(rel.schema_name, rel.table_name)]
        want = [f.name for f in rel.schema]
        if t.names != want:
            t = t.limit_to(want)
        return _VT(t, None)

    def _LogicalProject(self, rel: LogicalProject) -> _VT:
        src = self.run(rel.input)
        cols: List[Column] = []
        for rex, f in zip(rel.exprs, rel.schema):
            v = evaluate_rex(rex, src.table, None)
            if isinstance(v, Scalar):
                v = Column.from_scalar(v, src.n)
            cols.append(v)
        return _VT(Table([f.name for f in rel.schema], cols), src.valid)

    def _LogicalFilter(self, rel: LogicalFilter) -> _VT:
        src = self.run(rel.input)
        mask = evaluate_predicate(rel.condition, src.table, None)
        if isinstance(mask, bool):
            if mask:
                return src
            return _VT(src.table, jnp.zeros(src.n, dtype=bool))
        valid = mask if src.valid is None else (mask & src.valid)
        return _VT(src.table, valid)

    def _LogicalValues(self, rel: LogicalValues) -> _VT:
        from .rel.executor import _values
        return _VT(_values(rel, None), None)

    def _LogicalAggregate(self, rel: LogicalAggregate) -> _VT:
        src = self.run(rel.input)
        n = src.n
        out_cols: List[Column] = []
        out_names = [f.name for f in rel.schema]

        if not rel.group_keys:
            for j, agg in enumerate(rel.aggs):
                f = rel.schema[j]
                col = src.table.columns[agg.args[0]] if agg.args else None
                fmask = self._agg_filter(agg, src)
                out_cols.append(G.segment_aggregate(
                    agg.op, col, None, 1, f.stype, fmask, n))
            return _VT(Table(out_names, out_cols), None)

        tag = f"agg{self._agg_counter}"
        self._agg_counter += 1
        cap = min(self.caps.get(tag, DEFAULT_GROUP_CAP), n)
        key_cols = [src.table.columns[i] for i in rel.group_keys]
        codes, first, num_groups = _traced_factorize(key_cols, src.valid, cap)
        self.ngroups.append(num_groups)
        self.ngroup_caps.append(cap)

        safe_first = jnp.clip(first, 0, n - 1)
        for i, ki in enumerate(rel.group_keys):
            out_cols.append(src.table.columns[ki].take(safe_first))
        for j, agg in enumerate(rel.aggs):
            f = rel.schema[len(rel.group_keys) + j]
            col = src.table.columns[agg.args[0]] if agg.args else None
            fmask = self._agg_filter(agg, src)
            out_cols.append(G.segment_aggregate(
                agg.op, col, codes, cap + 1, f.stype, fmask, n).slice(0, cap))
        row_valid = jnp.arange(cap) < num_groups
        return _VT(Table(out_names, out_cols), row_valid)

    def _agg_filter(self, agg, src: _VT):
        """Combined FILTER-clause + row-validity mask (None = all rows)."""
        fmask = src.valid
        if agg.filter_arg is not None:
            fc = src.table.columns[agg.filter_arg]
            fm = fc.data.astype(bool) & fc.valid_mask()
            fmask = fm if fmask is None else (fmask & fm)
        return fmask

    def _LogicalSort(self, rel: LogicalSort) -> _VT:
        src = self.run(rel.input)
        n = src.n
        valid = src.valid
        table = src.table
        need_compact = rel.offset is not None or rel.limit is not None
        if rel.collation or (need_compact and valid is not None):
            arrays = []
            for c in reversed(rel.collation):
                col = table.columns[c.index]
                d = _orderable_int64(comparable_data(col))
                if not c.ascending:
                    # -INT64_MIN wraps; clamp before negating (merges the two
                    # most-negative keys — indistinguishable in practice)
                    d = -jnp.where(d == _INT64_MIN, _INT64_MIN + 1, d)
                if col.mask is not None:
                    nullkey = (~col.mask).astype(jnp.int8)
                    if c.effective_nulls_first:
                        nullkey = -nullkey
                    arrays.append(d)
                    arrays.append(nullkey)
                else:
                    arrays.append(d)
            if valid is not None:
                arrays.append((~valid).astype(jnp.int8))  # valid rows first
            perm = jnp.lexsort(arrays)
            table = table.take(perm)
            if valid is not None:
                count = jnp.sum(valid.astype(jnp.int64))
                valid = jnp.arange(n) < count
        start = rel.offset or 0
        stop = n if rel.limit is None else min(start + rel.limit, n)
        if start == 0 and stop == n:
            return _VT(table, valid)
        table = table.slice(start, stop)
        if valid is not None:
            count = jnp.sum(valid.astype(jnp.int64))
            valid = jnp.arange(stop - start) < (count - start)
        return _VT(table, valid)

    def _LogicalUnion(self, rel: LogicalUnion) -> _VT:
        from .rex.cast import cast_column
        parts = [self.run(i) for i in rel.inputs_]
        out_names = [f.name for f in rel.schema]
        cols: List[Column] = []
        for j, f in enumerate(rel.schema):
            pieces = []
            for p in parts:
                c = p.table.columns[j]
                if c.stype.name != f.stype.name:
                    c = cast_column(c, f.stype)
                pieces.append(c)
            cols.append(_concat_columns(pieces, f.stype))
        valids = [p.vmask() for p in parts]
        valid = (None if all(p.valid is None for p in parts)
                 else jnp.concatenate(valids))
        out = _VT(Table(out_names, cols), valid)
        if rel.all:
            return out
        # UNION DISTINCT: keep first occurrence of each distinct row
        n = out.n
        codes, first, _ = _traced_factorize(list(out.table.columns),
                                            out.valid, n)
        keep = jnp.clip(first, 0, n - 1)[codes] == jnp.arange(n)
        keep = keep & out.vmask()
        return _VT(out.table, keep)

    def _LogicalJoin(self, rel: LogicalJoin) -> _VT:
        from .rel.executor import _and_rex, _extract_equi_keys
        left = self.run(rel.left)
        right = self.run(rel.right)
        equi, residual = _extract_equi_keys(rel)
        jt = rel.join_type
        if not equi:
            raise Unsupported("non-equi/cross join")
        if residual and jt != "INNER":
            raise Unsupported("outer join with residual")

        lk = [k for k, _ in equi]
        rk = [k for _, k in equi]
        out_names = [f.name for f in rel.schema]

        if jt == "LEFT" or jt in ("SEMI", "ANTI"):
            probe, build, probe_is_left = left, right, True
            pk_cols = [left.table.columns[i] for i in lk]
            bk_cols = [right.table.columns[i] for i in rk]
        elif jt == "RIGHT":
            probe, build, probe_is_left = right, left, False
            pk_cols = [right.table.columns[i] for i in rk]
            bk_cols = [left.table.columns[i] for i in lk]
        else:  # INNER: probe the bigger side
            if left.n >= right.n:
                probe, build, probe_is_left = left, right, True
                pk_cols = [left.table.columns[i] for i in lk]
                bk_cols = [right.table.columns[i] for i in rk]
            else:
                probe, build, probe_is_left = right, left, False
                pk_cols = [right.table.columns[i] for i in rk]
                bk_cols = [left.table.columns[i] for i in lk]

        if probe_is_left:
            pparts, bparts = _join_key_parts(pk_cols, bk_cols)
        else:
            bparts, pparts = _join_key_parts(bk_cols, pk_cols)

        pvalid = _keys_valid(pk_cols, probe.valid)
        bvalid = _keys_valid(bk_cols, build.valid)
        ph = _hash_parts(pparts, pvalid)
        bh = _hash_parts(bparts, bvalid)

        nb = build.n
        order = jnp.argsort(bh)
        bh_sorted = bh[order]
        adj = (bh_sorted[1:] == bh_sorted[:-1]) & (bh_sorted[1:] != _U64_MAX)
        if jt in ("INNER", "LEFT", "RIGHT"):
            # build side must be unique on the key (covers hash collisions too)
            self.fallback.append(adj.any())
        else:
            # duplicates fine for SEMI/ANTI; only hash collisions are fatal
            coll = jnp.zeros((), dtype=bool)
            for bp in bparts:
                bps = bp[order]
                coll = coll | (adj & (bps[1:] != bps[:-1])).any()
            self.fallback.append(coll)

        pos = jnp.searchsorted(bh_sorted, ph, side="left", method="sort")
        in_range = pos < nb
        pos_c = jnp.minimum(pos, nb - 1)
        cand = order[pos_c]
        match = in_range & pvalid & (bh_sorted[pos_c] == ph)
        for pp, bp in zip(pparts, bparts):
            match = match & (pp == bp[cand])

        if jt == "SEMI":
            return _VT(probe.table.with_names(out_names),
                       probe.vmask() & match)
        if jt == "ANTI":
            return _VT(probe.table.with_names(out_names),
                       probe.vmask() & ~match)

        gathered = [c.take(cand) for c in build.table.columns]
        if jt in ("LEFT", "RIGHT"):
            gathered = [c.with_mask(c.valid_mask() & match) for c in gathered]
        if probe_is_left:
            cols = list(probe.table.columns) + gathered
        else:
            cols = gathered + list(probe.table.columns)
        pairs = Table(out_names, cols)

        if jt == "INNER":
            valid = probe.vmask() & match
            if residual:
                pred = evaluate_predicate(_and_rex(residual), pairs, None)
                if isinstance(pred, bool):
                    pred = jnp.full(pairs.num_rows, pred)
                valid = valid & pred
            return _VT(pairs, valid)
        # LEFT/RIGHT: every (valid) probe row survives
        return _VT(pairs, probe.valid)


def _concat_columns(pieces: List[Column], stype) -> Column:
    if stype.is_string:
        u = unify_string_codes(pieces)
        # object dtype: a '<U' dictionary would coerce None (NULL) to 'None'
        # on decode (Column._encode_strings uses object for the same reason)
        union = np.unique(np.concatenate(
            [c.dictionary.astype(str) for c in pieces])).astype(object)
        data = jnp.concatenate([a.astype(jnp.int32) for a in u])
        masks = None
        if any(p.mask is not None for p in pieces):
            masks = jnp.concatenate([p.valid_mask() for p in pieces])
        return Column(data, stype, masks, union)
    dt = pieces[0].data.dtype
    for p in pieces[1:]:
        dt = jnp.promote_types(dt, p.data.dtype)
    data = jnp.concatenate([p.data.astype(dt) for p in pieces])
    masks = None
    if any(p.mask is not None for p in pieces):
        masks = jnp.concatenate([p.valid_mask() for p in pieces])
    return Column(data, pieces[0].stype, masks)


# ---------------------------------------------------------------------------
# compile + execute
# ---------------------------------------------------------------------------

class _Compiled:
    __slots__ = ("fn", "scans", "spec", "meta", "caps", "key")

    def __init__(self, fn, scans, spec, meta, caps, key):
        self.fn = fn
        self.scans = scans      # [(key, Table)] strong refs keep ids unique
        self.spec = spec
        self.meta = meta        # filled during first trace
        self.caps = caps
        self.key = key


_cache: "OrderedDict[tuple, object]" = OrderedDict()
# learned state per (plan, inputs) key: escalated group caps and runtime
# verdicts, so steady state never repeats an overflow run or a known-eager
# compiled attempt
_learned_caps: Dict[tuple, Dict[str, int]] = {}
_runtime_eager: set = set()
_UNSUPPORTED = object()


def _flatten_tables(scans) -> List[jax.Array]:
    flat: List[jax.Array] = []
    for _, tbl in scans:
        for c in tbl.columns:
            flat.append(c.data)
            if c.mask is not None:
                flat.append(c.mask)
    return flat


def _build(plan: RelNode, context, scans, caps: Dict[str, int], key):
    """Create the jitted program for this plan + input spec."""
    spec = []
    for skey, tbl in scans:
        spec.append((skey, [(c.stype, c.mask is not None, c.dictionary)
                            for c in tbl.columns], tbl.names))
    meta: dict = {}

    def fn(*flat):
        i = 0
        tables: Dict[tuple, Table] = {}
        for skey, colspec, names in spec:
            cols = []
            for stype, has_mask, dictionary in colspec:
                data = flat[i]; i2 = i + 1
                mask = flat[i2] if has_mask else None
                i = i2 + 1 if has_mask else i2
                cols.append(Column(data, stype, mask, dictionary))
            tables[skey] = Table(names, cols)
        tr = _Tracer(context, tables, caps)
        out = tr.run(plan)
        n = out.n
        if out.valid is None:
            count = jnp.int64(n)
        else:
            count = jnp.sum(out.valid.astype(jnp.int64))
        fb = jnp.zeros((), dtype=bool)
        for f in tr.fallback:
            fb = fb | f
        flags = jnp.stack([fb.astype(jnp.int64), count]
                          + [g.astype(jnp.int64) for g in tr.ngroups])
        meta["names"] = list(out.table.names)
        meta["cols"] = [(c.stype, c.mask is not None, c.dictionary)
                        for c in out.table.columns]
        meta["has_valid"] = out.valid is not None
        meta["ngroup_caps"] = list(tr.ngroup_caps)
        meta["n_out"] = n
        outs: List[jax.Array] = [flags]
        for c in out.table.columns:
            outs.append(c.data)
            if c.mask is not None:
                outs.append(c.mask)
        if out.valid is not None:
            outs.append(out.valid)
        return tuple(outs)

    return _Compiled(jax.jit(fn), list(scans), spec, meta, dict(caps), key)


class _NeedsRecompile(Exception):
    def __init__(self, caps):
        self.caps = caps


def _materialize(entry: _Compiled, outs) -> Table:
    meta = entry.meta
    flags = np.asarray(outs[0])
    if flags[0]:
        stats["fallbacks"] += 1
        return None
    ngroups = flags[2:]
    new_caps = dict(entry.caps)
    grew = False
    for i, (ng, cap) in enumerate(zip(ngroups, meta["ngroup_caps"])):
        if ng > cap:
            need = 1 << (int(ng) - 1).bit_length()
            new_caps[f"agg{i}"] = max(need, cap * 2)
            grew = True
    if grew:
        raise _NeedsRecompile(new_caps)
    count = int(flags[1])
    idx = 1
    cols: List[Column] = []
    for stype, has_mask, dictionary in meta["cols"]:
        data = outs[idx]; idx += 1
        mask = None
        if has_mask:
            mask = outs[idx]; idx += 1
        cols.append(Column(data, stype, mask, dictionary))
    valid = outs[idx] if meta["has_valid"] else None
    t = Table(meta["names"], cols)
    if valid is not None and count < meta["n_out"]:
        rows = jnp.nonzero(valid, size=count)[0]
        t = t.take(rows)
    return t


def try_execute_compiled(plan: RelNode, context) -> Optional[Table]:
    """Execute via the compiled pipeline; None => caller should run eager."""
    if os.environ.get("DSQL_COMPILE", "1") == "0":
        return None
    scans: list = []
    try:
        plan_fp = _fp_plan(plan, context, scans)
    except Unsupported as e:
        logger.debug("not compilable: %s", e)
        stats["unsupported"] += 1
        return None
    base_key = (plan_fp, _fp_inputs(scans))
    if base_key in _runtime_eager:
        stats["fallbacks"] += 1
        return None
    caps: Dict[str, int] = dict(_learned_caps.get(base_key, {}))
    for _ in range(8):  # capacity-escalation bound
        key = (base_key, tuple(sorted(caps.items())))
        entry = _cache.get(key)
        if entry is _UNSUPPORTED:
            stats["unsupported"] += 1
            return None
        flat = _flatten_tables(scans)
        if entry is None:
            while len(_cache) >= _CACHE_LIMIT:
                _cache.popitem(last=False)
            try:
                entry = _build(plan, context, scans, caps, key)
                outs = entry.fn(*flat)  # first call traces & compiles
            except Unsupported as e:
                logger.debug("not compilable at trace time: %s", e)
                _cache[key] = _UNSUPPORTED
                stats["unsupported"] += 1
                return None
            except (jax.errors.TracerBoolConversionError,
                    jax.errors.TracerArrayConversionError,
                    jax.errors.ConcretizationTypeError,
                    NotImplementedError) as e:
                logger.debug("trace failed (%s); falling back", type(e).__name__)
                _cache[key] = _UNSUPPORTED
                stats["unsupported"] += 1
                return None
            stats["compiles"] += 1
            _cache[key] = entry
        else:
            stats["hits"] += 1
            _cache.move_to_end(key)
            outs = entry.fn(*flat)
        try:
            result = _materialize(entry, outs)
        except _NeedsRecompile as r:
            stats["recompiles"] += 1
            caps = r.caps
            _learned_caps[base_key] = dict(caps)
            continue
        if result is None:
            # runtime invariant failed (non-unique build / hash collision):
            # data is keyed into base_key, so the verdict is stable — go
            # straight to eager on every future call
            _runtime_eager.add(base_key)
        return result
    return None
