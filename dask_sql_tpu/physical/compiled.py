"""Compiled query pipelines: stage-graph jit with static shapes.

The eager executor (physical/rel/executor.py) dispatches one XLA op at a
time; over a remote TPU every dispatch is a host round trip and every
data-dependent shape (boolean compaction, ``jnp.unique``) is a blocking sync.
This module is the TPU-first answer (SURVEY §7 "hard parts" item 2): a query
plan is traced into jitted programs with *static shapes* — filters keep rows
and flip a validity mask instead of compacting, GROUP BY factorizes via an
in-trace lexsort with a static group-capacity bound, and equi-joins probe a
sorted build side via ``searchsorted`` — each program cached keyed by (plan
fingerprint, input shapes/dtypes + string-dictionary content). Steady state
is one device dispatch + one tiny flags transfer per program, and reloading
fresh data with the same layout never recompiles.

**Stage graphs bound program size.** XLA:TPU compile time grows
superlinearly with the number of fused heavy (join/aggregate/window)
pipelines in one program (~50 s at 2, never-finishes at 8-9 over the
tunneled TPU), so plans above a heavy-node budget are partitioned
(physical/stages.py) into a DAG of stages of at most ``DSQL_STAGE_HEAVY``
heavy nodes (default 6; legacy ``DSQL_SPLIT_HEAVY`` honored).  Stage
outputs materialize into padded power-of-2 capacity-class temp tables
(``__split__`` schema), keeping consumer program keys stable across runs.
Because stages keep the ordinary content-addressed cache key, structurally
shared pipelines across queries — TPC-H's repeated lineitem/orders
scan→filter→join prefixes — compile once and hit from then on
(``stats["cross_query_hits"]``); independent stages compile concurrently in
a small worker pool (``DSQL_COMPILE_WORKERS``, default 4 — XLA compilation
releases the GIL), turning a serial warmup wall into overlapped small
compiles.

Runtime conditions XLA cannot express statically (group-count overflow,
non-unique build side, 64-bit hash collision) surface through a flags vector;
the host reacts by recompiling with a larger capacity or falling back to the
eager executor. Unsupported plan shapes (UDFs, scalar subqueries, windows,
host-bound string ops) are detected at trace time and cached as such, so the
fallback costs nothing at steady state.

The reference has no analogue — its dask graphs are dynamically scheduled
(SURVEY §2.3); this is the "compiled SPMD stages replace the dynamic
scheduler" design of SURVEY §5.
"""
from __future__ import annotations

import hashlib
import threading as _threading
import logging
import math
import os
import re
import time
import weakref
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import groupby as G
from ..ops.kernels import (canon_f64, comparable_data, float_class,
                           key_parts as _key_parts, orderable_int64,
                           unify_string_codes)
from ..plan.nodes import (
    LogicalAggregate, LogicalFilter, LogicalJoin, LogicalProject, LogicalSort,
    LogicalTableScan, LogicalUnion, LogicalValues, LogicalWindow, RelNode,
    RexCall, RexInputRef, RexLiteral, RexNode, RexParam,
)
from ..runtime import (faults as _faults, kvstore as _kv,
                       program_store as _pstore, quarantine as _quar,
                       resilience as _res, result_cache as _rcache,
                       telemetry as _tel)
from ..table import dict_sort_order, Column, Scalar, Table
from .rex.evaluate import evaluate_predicate, evaluate_rex
from .stages import (StageGraph, annotate_stats as _annotate_stage_stats,
                     heavy_count as _heavy_count,
                     partition as _partition, stage_budget)

logger = logging.getLogger(__name__)

from ..ops.kernels import _INT64_MIN  # single sentinel source
_U64_MAX = np.uint64(0xFFFFFFFFFFFFFFFF)
_GOLDEN = np.uint64(0x9E3779B97F4A7C15)

DEFAULT_GROUP_CAP = 4096
_CACHE_LIMIT = 128

# ops whose kernels are host-bound or non-deterministic: never compile
_DENY_OPS = {"RAND", "RAND_INTEGER"}

# DEPRECATED read-through alias of the telemetry registry's counters
# (runtime/telemetry.py owns them now; names + meanings unchanged and
# covered by its stability contract): compiles/hits/fallbacks/unsupported/
# recompiles/compile_errors/exiled/split_hints, the stage-graph counters
# (stage_graphs/stage_compiles/stage_hits/cross_query_hits: plans
# partitioned, stage programs compiled/served from cache, and cache hits
# arriving from a DIFFERENT query than the one that compiled the program),
# and the resilience counters (retries/degradations/deadline_exceeded/
# fault_*).  Reads and ``dict(stats)`` snapshots keep working; increments
# in NEW code must go through ``telemetry.inc`` (atomic), never
# ``stats[k] += 1`` (an unlocked read-modify-write).
stats = _tel.CounterAlias()


class _ExecProfileAlias:
    """DEPRECATED thread-local view of the DSQL_TIME_DEVICE exec split.

    The old process-global dict raced: concurrent server queries clobbered
    each other's device/materialize timings.  Each query thread now owns
    its profile (telemetry.exec_profile()) and the authoritative numbers
    land on the query's span / QueryReport; this alias keeps the
    ``compiled.last_exec_profile`` surface readable per thread."""

    def get(self, key, default=None):
        return _tel.exec_profile().get(key, default)

    def pop(self, key, default=None):
        return _tel.exec_profile().pop(key, default)

    def __getitem__(self, key):
        return _tel.exec_profile()[key]

    def __setitem__(self, key, value):
        _tel.exec_profile()[key] = value

    def __contains__(self, key):
        return key in _tel.exec_profile()

    def __iter__(self):
        return iter(_tel.exec_profile())

    def __len__(self):
        return len(_tel.exec_profile())

    def keys(self):
        # dict(alias) goes through keys(); without it dict() would try to
        # consume the iterator as key-value PAIRS
        return _tel.exec_profile().keys()

    def items(self):
        return _tel.exec_profile().items()

    def clear(self):
        _tel.exec_profile().clear()

    def __repr__(self):  # pragma: no cover - debugging nicety
        return repr(_tel.exec_profile())


# DSQL_TIME_DEVICE=1 diagnostic: per-call split of the execute wall into
# dispatch+device-compute vs host materialize (see try_execute_compiled)
last_exec_profile = _ExecProfileAlias()


class Unsupported(Exception):
    """Plan (or expression) outside the compilable subset."""


# ---------------------------------------------------------------------------
# fingerprinting
# ---------------------------------------------------------------------------

def _fp_rex(rex: RexNode, context=None, scans=None, params=None) -> str:
    if params is None:
        params = []
    if isinstance(rex, RexInputRef):
        return f"@{rex.index}"
    if isinstance(rex, RexParam):
        # hoisted literal (plan/parameterize.py): identity is POSITION and
        # type, never the value — every literal variant of a shape shares
        # this fingerprint, and the value rides as a trailing jit argument.
        # The position is the node's index in THIS serialization walk, so
        # the ``params`` list accumulated alongside the text IS the
        # bound-argument order; any caller that serializes the same
        # (sub)plan recovers the same numbering.
        for i, p in enumerate(params):
            if p is rex:
                return f"P{i}:{rex.stype.name}"
        params.append(rex)
        return f"P{len(params) - 1}:{rex.stype.name}"
    if isinstance(rex, RexLiteral):
        return f"L{rex.stype.name}:{rex.value!r}"
    if isinstance(rex, RexCall):
        if rex.op in _DENY_OPS:
            raise Unsupported(rex.op)
        extra = ""
        info = getattr(rex, "info", None)
        if info is not None:
            extra = f"!{getattr(info, 'name', info)}"
        return (f"C{rex.op}{extra}["
                + ",".join(_fp_rex(o, context, scans, params)
                           for o in rex.operands)
                + f"]:{rex.stype.name}")
    from ..plan.nodes import RexScalarSubquery
    if isinstance(rex, RexScalarSubquery) and context is not None:
        # uncorrelated scalar subquery: the subplan joins the cache key and
        # its scans join the input spec; the tracer inlines it as a
        # broadcast 1-row result
        return ("S[" + _fp_plan(rex.plan, context, scans, params)
                + f"]:{rex.stype.name}")
    raise Unsupported(type(rex).__name__)


def _fp_plan(rel: RelNode, context, scans: list, params=None) -> str:
    """Serialize the plan for cache keying; collects scan tables (and the
    plan's RexParam nodes, in serialization order, into ``params``)."""
    if params is None:
        params = []
    t = type(rel).__name__
    schema = ";".join(f"{f.name}:{f.stype.name}" for f in rel.schema)
    if isinstance(rel, LogicalTableScan):
        # snapshot-pin-aware read (runtime/ingest.py): the compiled program
        # binds the tables captured at admission, not a mid-append swap
        entry = context.catalog_entry(rel.schema_name, rel.table_name)
        if entry.table is None:
            raise Unsupported("view scan")
        if entry.table.num_rows == 0:
            raise Unsupported("empty table")
        scans.append(((rel.schema_name, rel.table_name), entry.table,
                      entry.row_valid))
        rv = "+rv" if entry.row_valid is not None else ""
        return f"Scan({rel.schema_name}.{rel.table_name}{rv})[{schema}]"
    if isinstance(rel, LogicalProject):
        body = ",".join(_fp_rex(e, context, scans, params)
                        for e in rel.exprs)
    elif isinstance(rel, LogicalFilter):
        body = _fp_rex(rel.condition, context, scans, params)
    elif isinstance(rel, LogicalAggregate):
        for agg in rel.aggs:
            if agg.udaf is not None:
                raise Unsupported("udaf agg")
            if agg.distinct and (
                    agg.op not in ("COUNT", "SUM", "$SUM0", "AVG",
                                   "MIN", "MAX")
                    or agg.filter_arg is not None or not agg.args):
                # FILTER + DISTINCT: the first occurrence of a value may be
                # filtered away while a later duplicate passes — the
                # first-occurrence dedup mask would undercount
                raise Unsupported("distinct agg shape")
            if agg.op in ("LISTAGG", "BIT_AND", "BIT_OR", "BIT_XOR"):
                raise Unsupported(agg.op)
        body = (f"g={rel.group_keys}|" + ",".join(
            f"{a.op}{'d' if a.distinct else ''}({a.args})f{a.filter_arg}"
            for a in rel.aggs))
    elif isinstance(rel, LogicalJoin):
        if rel.join_type not in ("INNER", "LEFT", "RIGHT", "SEMI", "ANTI"):
            raise Unsupported(rel.join_type)
        # null-aware anti (NOT IN) compiles too; the flag joins the
        # fingerprint so it can't share a program with a plain anti join
        na = "N" if getattr(rel, "null_aware", False) else ""
        cond = ("T" if rel.condition is None
                else _fp_rex(rel.condition, context, scans, params))
        body = f"{rel.join_type}{na}|{cond}"
    elif isinstance(rel, LogicalSort):
        body = (",".join(f"{c.index}{'a' if c.ascending else 'd'}"
                         f"{'nf' if c.effective_nulls_first else 'nl'}"
                         for c in rel.collation)
                + f"|o={rel.offset}|l={rel.limit}")
    elif isinstance(rel, LogicalWindow):
        from ..ops.window import TRACE_SAFE_OPS
        for call in rel.calls:
            if call.op not in TRACE_SAFE_OPS:
                raise Unsupported(f"window op {call.op}")
        body = ";".join(
            f"{call.op}({call.args})p{call.partition}"
            + "o" + ",".join(f"{c.index}{'a' if c.ascending else 'd'}"
                             f"{'nf' if c.effective_nulls_first else 'nl'}"
                             for c in call.order)
            + f"f{call.frame!r}" for call in rel.calls)
    elif isinstance(rel, LogicalUnion):
        body = f"all={rel.all}"
    elif isinstance(rel, LogicalValues):
        body = repr([[lit.value for lit in row] for row in rel.rows])
    else:
        raise Unsupported(type(rel).__name__)
    kids = ",".join(_fp_plan(i, context, scans, params) for i in rel.inputs)
    return f"{t}({body})[{schema}]<{kids}>"


_dict_fp_memo: Dict[int, tuple] = {}


def _dict_fingerprint(arr) -> str:
    """Content hash of a string dictionary, memoized per array object.

    String dictionaries are embedded in the jitted program as constants, so
    they must join the cache key — but by CONTENT, not object identity:
    reloading the same data (new Table, equal dictionaries) must hit the
    cached program instead of recompiling.
    """
    key = id(arr)
    hit = _dict_fp_memo.get(key)
    if hit is not None and hit[0]() is arr:
        return hit[1]
    h = hashlib.blake2b(digest_size=16)
    h.update(str(len(arr)).encode())
    for s in arr:
        b = str(s).encode()
        # length prefix, not a separator: elements may contain any byte, so
        # a separator could make ["a\0", "b"] and ["a", "\0b"] collide
        h.update(str(len(b)).encode() + b":" + b)
    fp = h.hexdigest()
    _dict_fp_memo[key] = (
        weakref.ref(arr, lambda _r, k=key: _dict_fp_memo.pop(k, None)), fp)
    return fp


def _fp_inputs(scans: list) -> tuple:
    out = []
    for _, tbl, row_valid in scans:
        # keyed on shapes/dtypes + dictionary CONTENT (not table identity):
        # new data with the same layout reuses the compiled program; any
        # dictionary change reshapes the key because the dictionaries are
        # baked into the program as constants
        cols = tuple(
            (c.data.shape, str(c.data.dtype), c.mask is not None,
             None if c.dictionary is None else _dict_fingerprint(c.dictionary))
            for c in tbl.columns)
        out.append((cols, row_valid is not None))
    return tuple(out)


def _mesh_signature(context) -> str:
    """Sharding layout component of program identity: tracing under a
    device mesh lets GSPMD bake in a different partitioning, so a program
    (or persisted executable) compiled with a mesh must never be served to
    a mesh-less context or a different mesh shape — and vice versa."""
    mesh = getattr(context, "mesh", None)
    if mesh is None:
        return ""
    return "x".join(f"{n}:{s}"
                    for n, s in zip(mesh.axis_names, mesh.devices.shape))


# ---------------------------------------------------------------------------
# in-trace kernels
# ---------------------------------------------------------------------------

def _f64_hash_part(x: jax.Array) -> jax.Array:
    """Deterministic u64 encoding of f64 for hashing without a 64-bit
    bitcast: double-float (hi, lo) f32 split, each bitcast to i32 (supported
    on TPU). ~48 mantissa bits — lossy encodings only add hash collisions,
    which the join's collision flag catches; equality is verified on raw
    values."""
    x = canon_f64(x)
    hi = x.astype(jnp.float32)
    lo = (x - hi.astype(jnp.float64)).astype(jnp.float32)
    hi_b = jax.lax.bitcast_convert_type(hi, jnp.int32).astype(jnp.uint64)
    lo_b = jax.lax.bitcast_convert_type(lo, jnp.int32).astype(jnp.uint64)
    return (hi_b << np.uint64(32)) | (lo_b & np.uint64(0xFFFFFFFF))


def _mix64(z: jax.Array) -> jax.Array:
    z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return z ^ (z >> np.uint64(31))


class _VT:
    """A padded device table + row-validity mask (None = all rows valid).

    ``weight`` is the PRE-compaction row count (defaults to the physical
    row count): heuristics that pick sides by size — the INNER-join
    probe/build choice — must see the logical stream size, or a compacted
    fact side masquerades as small, becomes the build, and its duplicate
    keys trip the unique-build fallback."""

    __slots__ = ("table", "valid", "weight")

    def __init__(self, table: Table, valid: Optional[jax.Array],
                 weight: Optional[int] = None):
        self.table = table
        self.valid = valid
        self.weight = weight if weight is not None else table.num_rows

    @property
    def n(self) -> int:
        return self.table.num_rows

    def vmask(self) -> jax.Array:
        if self.valid is None:
            return jnp.ones(self.n, dtype=bool)
        return self.valid


def _hash_group_parts(parts) -> jax.Array:
    """Mix all group-key parts (data + class flags) into one u64 per row.

    Float parts ride the lossy double-float encoding (_f64_hash_part);
    any loss only ever ADDS collisions, which the caller detects against
    the raw parts and routes to the eager fallback."""
    h = jnp.full(parts[0][0].shape, _GOLDEN, dtype=jnp.uint64)
    for d, flag in parts:
        if jnp.issubdtype(d.dtype, jnp.floating):
            hp = _f64_hash_part(d)
        else:
            hp = d.astype(jnp.uint64)
        h = _mix64(h + hp + _GOLDEN)
        if flag is not None:
            h = _mix64(h + flag.astype(jnp.uint64) + _GOLDEN)
    return h


class _GroupSorted:
    """Group-sorted stream: the one factorize result both the aggregate and
    UNION DISTINCT paths consume (scatter-free; see ops/sorted_agg.py).

    ``collision`` is a traced scalar bool: True when a 64-bit key-hash
    collision may have interleaved two distinct groups (hash-combined sort
    path only); callers must append it to the tracer's fallback flags."""

    __slots__ = ("perm", "valid_sorted", "codes_sorted", "num_groups",
                 "starts", "ends", "first_rows", "n", "cap", "collision",
                 "payload_sorted")


def _group_sorted_codes(key_cols: List[Column],
                        row_valid: Optional[jax.Array],
                        cap: int,
                        payload: Tuple[jax.Array, ...] = ()) -> _GroupSorted:
    """Sort rows into group order and derive dense codes in sorted space.

    Invalid rows and groups beyond ``cap`` land in the trash slot ``cap``.
    Stable sort makes ``first_rows[g]`` the group's first original row.

    ``payload`` arrays ride the sort as extra variadic-sort operands and come
    back group-ordered in ``gs.payload_sorted``. On TPU a random n-element
    gather costs ~2x a whole extra sort operand (profiled on the bench
    workload: 32ms gather vs full 7ms u64 argsort at 1.8M rows), so callers
    should ship every column they need in sorted space through here rather
    than ``take(gs.perm)`` afterwards. Key parts also ride as payload, which
    makes boundary detection gather-free.

    With >2 key sort operands, all parts collapse into ONE u64 hash key
    (sort cost scales with key-operand count); group order is then hash
    order — unordered, as SQL allows; an explicit ORDER BY sorts above the
    aggregate anyway. Distinct keys sharing a hash would interleave; that is
    detected (adjacent equal-hash rows across a raw-key boundary) and
    reported via ``collision`` for the runtime fallback flag.
    """
    from ..ops import sorted_agg as sa

    from ..ops.pallas_kernels import _strategy_on_tpu as _on_tpu

    n = len(key_cols[0])
    parts = _key_parts(key_cols)
    invalid = jnp.zeros(n, dtype=bool) if row_valid is None else ~row_valid
    on_tpu = _on_tpu()
    n_operands = sum(2 if flag is not None else 1 for _, flag in parts)
    hashed = on_tpu and n_operands > 2

    # key operands, most significant first (invalid rows last; within a
    # part the class flag outranks the data: NULL first, NaN last)
    key_ops: List[jax.Array] = [invalid]
    if hashed:
        key_ops.append(_hash_group_parts(parts))
    else:
        for d, flag in parts:
            if flag is not None:
                key_ops.append(flag)
            key_ops.append(d)

    if not on_tpu:
        # CPU/GPU: XLA's variadic comparator sort is slow there and random
        # gathers are cheap — sort keys only, gather everything after
        perm = jnp.lexsort(tuple(reversed(key_ops)))
        valid_sorted = ~invalid[perm]
        payload_sorted = tuple(p[perm] for p in payload)
        parts_sorted = [(d[perm], None if flag is None else flag[perm])
                        for d, flag in parts]
    else:
        part_pay: List[jax.Array] = []
        if hashed:
            for d, flag in parts:
                part_pay.append(d)
                if flag is not None:
                    part_pay.append(flag)

        nk = len(key_ops)
        iota = jnp.arange(n, dtype=jnp.int64)
        outs = jax.lax.sort(tuple(key_ops) + (iota,) + tuple(part_pay)
                            + tuple(payload), num_keys=nk, is_stable=True)
        perm = outs[nk]
        valid_sorted = ~outs[0]
        payload_sorted = outs[nk + 1 + len(part_pay):]

        if hashed:
            it = iter(outs[nk + 1: nk + 1 + len(part_pay)])
            parts_sorted = [(next(it),
                             next(it) if flag is not None else None)
                            for _, flag in parts]
        else:
            it = iter(outs[1:nk])
            parts_sorted = [((next(it) if flag is not None else None),
                             next(it)) for _, flag in parts]
            parts_sorted = [(d, f) for f, d in parts_sorted]
    diff = jnp.zeros(n - 1, dtype=bool) if n > 1 else jnp.zeros(0, dtype=bool)
    for d, flag in parts_sorted:
        diff = diff | (d[1:] != d[:-1])
        if flag is not None:
            diff = diff | (flag[1:] != flag[:-1])
    boundary = jnp.concatenate([jnp.ones(min(n, 1), dtype=bool), diff])
    boundary = boundary & valid_sorted

    collision = jnp.zeros((), dtype=bool)
    if hashed:
        hs = outs[1]
        adj_pair = valid_sorted[1:] & valid_sorted[:-1]
        collision = (adj_pair & (hs[1:] == hs[:-1]) & boundary[1:]).any()

    codes_sorted = jnp.cumsum(boundary.astype(jnp.int64)) - 1
    # last valid row's code + 1; if no valid rows, 0
    num_groups = jnp.where(
        valid_sorted.any(),
        jnp.max(jnp.where(valid_sorted, codes_sorted, -1)) + 1, 0)
    codes_sorted = jnp.where(valid_sorted, jnp.minimum(codes_sorted, cap), cap)

    gs = _GroupSorted()
    gs.perm, gs.valid_sorted, gs.codes_sorted = perm, valid_sorted, codes_sorted
    gs.num_groups, gs.n, gs.cap = num_groups, n, cap
    gs.collision = collision
    gs.payload_sorted = payload_sorted
    gs.starts, gs.ends = sa.segment_bounds(codes_sorted, cap)
    gs.first_rows = perm[jnp.clip(gs.starts, 0, max(n - 1, 0))]
    return gs


def _traced_factorize(key_cols: List[Column], row_valid: Optional[jax.Array],
                      cap: int):
    """Original-row-order codes view of _group_sorted_codes (UNION DISTINCT
    needs codes per input row). The un-sort is a payload sort keyed on the
    permutation — half the cost of the argsort + random gather it replaces.

    Off-TPU the hash table produces row-order codes directly, with zero
    sorts; there is no ngroups escalation on this path (callers pass
    cap >= the worst case), so an unresolved table folds into the
    collision flag and reruns eager."""
    from ..ops.pallas_kernels import _strategy_on_tpu as _on_tpu
    if not _on_tpu():
        codes, first, ng, coll = _group_hashed_codes(key_cols, row_valid,
                                                     cap)
        return codes, first, ng, coll | (ng > cap)
    gs = _group_sorted_codes(key_cols, row_valid, cap)
    _, codes = jax.lax.sort((gs.perm, gs.codes_sorted), num_keys=1)
    return codes, gs.first_rows, gs.num_groups, gs.collision


STATIC_DOMAIN_CAP = 4096


def _try_static_codes(cols: List[Column]):
    """Direct group codes when every key has a statically-enumerable domain
    (dictionary-encoded strings, booleans). Returns (codes[n] int64 in
    [0, domain), domain, key_meta) or None; key_meta carries per-key
    (size, nullable) so slots decode back to key values without touching
    the data. Code order == eager group order (NULL slot first, then
    dictionary rank order)."""
    domain = 1
    parts: List[Tuple[jax.Array, int]] = []
    key_meta: List[Tuple[int, bool]] = []
    for c in cols:
        nullable = c.mask is not None
        if c.stype.is_string:
            size = len(c.dictionary)
            code = c.dict_ranks().data.astype(jnp.int64)
        elif c.data.dtype == jnp.bool_:
            size = 2
            code = c.data.astype(jnp.int64)
        else:
            return None
        if nullable:
            code = jnp.where(c.mask, code + 1, 0)
            size += 1
        size = max(size, 1)
        domain *= size
        if domain > STATIC_DOMAIN_CAP:
            return None
        parts.append((code, size))
        key_meta.append((size, nullable))
    combined = parts[0][0]
    for code, size in parts[1:]:
        combined = combined * size + code
    return combined, domain, key_meta


def _decode_static_keys(cols: List[Column], key_meta, domain: int
                        ) -> List[Column]:
    """Group-key output columns straight from the slot index: slot g encodes
    (rank+null) digits in mixed radix, so the key values are arithmetic on
    ``arange(domain)`` plus a static rank->dictionary-code gather — the row
    data is never touched."""
    g = jnp.arange(domain, dtype=jnp.int64)
    stride = domain
    out: List[Column] = []
    for c, (size, nullable) in zip(cols, key_meta):
        stride //= size
        code = (g // stride) % size
        mask = None
        if nullable:
            mask = code != 0
            code = jnp.maximum(code - 1, 0)
        if c.stype.is_string:
            # code is a sort RANK; order[rank] = dictionary index
            order = dict_sort_order(c.dictionary)
            data = jnp.take(jnp.asarray(order.astype(np.int32)), code)
            out.append(Column(data, c.stype, mask, c.dictionary))
        else:
            out.append(Column(code.astype(jnp.bool_), c.stype, mask))
    return out


def _join_key_parts(lcols: List[Column], rcols: List[Column]):
    """Per-key (hash part u64, raw verify array) on a shared domain.

    Hash parts may be lossy for f64 (double-float encoding); match
    verification always compares the raw arrays, so a lossy hash can only
    add collisions (caught by the collision flag), never wrong matches.
    """
    lparts, rparts = [], []
    for lc, rc in zip(lcols, rcols):
        if lc.stype.is_string or rc.stype.is_string:
            la, ra = unify_string_codes([lc, rc])
            la, ra = la.astype(jnp.int64), ra.astype(jnp.int64)
            lh, rh = la.astype(jnp.uint64), ra.astype(jnp.uint64)
        else:
            dt = jnp.promote_types(lc.data.dtype, rc.data.dtype)
            la = lc.data.astype(dt)
            ra = rc.data.astype(dt)
            if jnp.issubdtype(dt, jnp.floating):
                # verify arrays keep NaN as NaN (NaN joins nothing, matching
                # the eager path); only the hash canonicalizes NaN, and the
                # resulting extra collisions trip the conservative flags
                la = la.astype(jnp.float64) + 0.0
                ra = ra.astype(jnp.float64) + 0.0
                lh, rh = _f64_hash_part(la), _f64_hash_part(ra)
            else:
                la, ra = orderable_int64(la), orderable_int64(ra)
                lh, rh = la.astype(jnp.uint64), ra.astype(jnp.uint64)
        lparts.append((lh, la))
        rparts.append((rh, ra))
    return lparts, rparts


def _hash_parts(parts, key_valid: jax.Array) -> jax.Array:
    h = jnp.full(parts[0][0].shape, _GOLDEN, dtype=jnp.uint64)
    for hp, _ in parts:
        h = _mix64(h + hp + _GOLDEN)
    h = jnp.where(h == _U64_MAX, _U64_MAX - np.uint64(1), h)
    return jnp.where(key_valid, h, _U64_MAX)


def _keys_valid(cols: List[Column], row_valid: Optional[jax.Array]) -> jax.Array:
    v = jnp.ones(len(cols[0]), dtype=bool) if row_valid is None else row_valid
    for c in cols:
        if c.mask is not None:
            v = v & c.mask
    return v


# ---------------------------------------------------------------------------
# vectorized open-addressing hash table — the CPU/GPU hot path.
#
# XLA:CPU inverts the TPU cost model this engine's sort-centric kernels were
# built around: at 600k rows a u64 argsort costs ~354 ms and
# searchsorted(method='sort') ~751 ms, while gathers, scatters and
# segment_sum all cost ~1-2 ms (measured r3, this machine).  So off-TPU,
# joins and group-bys run on a hash table built with whole-array scatter
# rounds instead of any O(n log n) sort: each round, still-unresolved rows
# try to claim an EMPTY slot (scatter-min of row ids), and every row whose
# round slot now holds an equal-hash resident adopts that resident.  All
# rows of one key resolve together to one slot whose resident is the key's
# first row.  A lax.while_loop runs only as many rounds as the worst key
# chain needs (~log(keys)/log(1/load)).  u64 hash collisions between
# DISTINCT raw keys are detected by the caller comparing raw key parts
# against the resident's and routed to the runtime eager-fallback flag,
# exactly like the sort strategies' adjacency flags.
# ---------------------------------------------------------------------------

_HASH_MAX_ROUNDS = 64


def _hash_table_size(n_keys: int) -> int:
    """Power-of-2 table size at load factor <= 1/16.

    Generous sizing buys two things off-TPU: fewer claim rounds when
    hashing, and — the big one — direct addressing for sparse integer
    keys: TPC-H orderkeys span ~16x the row count, so a 16x table lets
    `key - lo` resolve in ONE round where a 4x table would fall back to
    multi-round hashing.  The cost is one table-sized fill (~2 ms at 32 MB
    on this machine), well under the rounds it saves.
    """
    return max(16, 1 << int(16 * max(n_keys, 1) - 1).bit_length())


def _single_int_part(parts):
    """The raw int64 array when the key is ONE non-nullable integer part
    (TPC-H's hot case: orderkey/partkey/custkey, non-null dictionary
    codes), else None.  Such keys get two shortcuts: ``_mix64`` is a
    BIJECTION on u64, so the hash is collision-free and raw-key
    verification is unnecessary; and the raw values drive the
    direct-address fast path below."""
    if len(parts) != 1 or parts[0][1] is not None:
        return None
    d = parts[0][0]
    if not jnp.issubdtype(d.dtype, jnp.integer):
        return None
    return d.astype(jnp.int64)


def _direct_info(raw: Optional[jax.Array], valid: jax.Array, size: int):
    """(raw, lo, fits) for direct addressing: when the runtime key range
    fits the table, round 0 gives every distinct key its OWN slot
    (``key - lo``), the while loop exits after one iteration, and the
    whole insert degenerates to one scatter + one gather.  The f64 span
    keeps the subtraction overflow-safe; any rounding slack is ~2^-53 of
    the span, far below the <= size threshold's granularity."""
    if raw is None:
        return None
    i64 = jnp.iinfo(jnp.int64)
    lo = jnp.min(jnp.where(valid, raw, i64.max))
    hi = jnp.max(jnp.where(valid, raw, i64.min))
    fits = (hi.astype(jnp.float64) - lo.astype(jnp.float64)) < size
    fits = fits & valid.any()
    return raw, lo, fits


def _combined_int_key(part_sides):
    """Mixed-radix combination of 2+ non-float key parts into ONE int64.

    ``part_sides``: per key part, a list of (data, flag_or_None, valid)
    triples — one per SIDE (group-by passes one side; joins pass build and
    probe, so radix ranges come from the union of both).  Per-part runtime
    ranges become radix strides; nullability flags ride as an extra binary
    digit.  Returns (keys: one i64 array per side, ok[traced bool scalar],
    span_prod[traced f64]) — ``ok`` means every stride product stayed
    below 2^62, making the combination INJECTIVE, so ``_mix64(key)`` is a
    collision-free hash and the key qualifies for direct addressing when
    ``span_prod`` also fits the table.  Where ~ok the combined values are
    meaningless and callers must keep the generic hash + raw verification.
    None when any part is floating (ranges don't express float equality
    classes).
    """
    for sides in part_sides:
        for d, _, _ in sides:
            if jnp.issubdtype(d.dtype, jnp.floating):
                return None
    i64 = jnp.iinfo(jnp.int64)
    n_sides = len(part_sides[0])
    keys = [jnp.zeros(part_sides[0][s][0].shape[0], dtype=jnp.int64)
            for s in range(n_sides)]
    span_prod = jnp.float64(1.0)
    ok = jnp.bool_(True)
    for sides in part_sides:
        lo = jnp.int64(i64.max)
        hi = jnp.int64(i64.min)
        any_v = jnp.bool_(False)
        svalids = []
        for d, flag, valid in sides:
            d = d.astype(jnp.int64)
            sv = valid if flag is None else (valid & (flag == 1))
            svalids.append(sv)
            lo = jnp.minimum(lo, jnp.min(jnp.where(sv, d, i64.max)))
            hi = jnp.maximum(hi, jnp.max(jnp.where(sv, d, i64.min)))
            any_v = any_v | sv.any()
        lo = jnp.where(any_v, lo, 0)
        hi = jnp.where(any_v, hi, 0)
        span_prod = span_prod * (hi.astype(jnp.float64)
                                 - lo.astype(jnp.float64) + 1.0)
        ok = ok & (span_prod < 2.0 ** 62)
        stride = hi - lo + 1
        has_flag = any(flag is not None for _, flag, _ in sides)
        if has_flag:
            span_prod = span_prod * 2.0
            ok = ok & (span_prod < 2.0 ** 62)
        for s, (d, flag, _) in enumerate(sides):
            d = d.astype(jnp.int64)
            # where ~ok these wrap harmlessly (the caller masks); where
            # ok, d - lo is in [0, span) and the product fits int64
            dn = jnp.where(svalids[s], d - lo, 0)
            k = keys[s] * stride + dn
            if has_flag:
                fl = (jnp.ones_like(dn) if flag is None
                      else flag.astype(jnp.int64))
                k = k * 2 + fl
            keys[s] = k
    return keys, ok, span_prod


def _slot_at_round(h: jax.Array, k, size: int, direct) -> jax.Array:
    s = (_mix64(h + (2 * k + 1).astype(jnp.uint64) * _GOLDEN)
         & jnp.uint64(size - 1)).astype(jnp.int32)
    if direct is not None:
        raw, lo, fits = direct
        d = jnp.clip(raw - lo, 0, size - 1).astype(jnp.int32)
        s = jnp.where((k == 0) & fits, d, s)
    return s


_TBL_EMPTY = jnp.iinfo(jnp.int64).max
_TBL_ROW_MASK = jnp.int64((1 << 32) - 1)


def _hash_table_insert(h: jax.Array, valid: jax.Array, size: int,
                       direct=None):
    """Resolve every valid row to one table slot per distinct u64 hash.

    Claims are priority-encoded as ``(round+1) << 32 | row`` and written
    with ONE scatter-min per round: earlier rounds always beat later ones
    and the smallest row wins within a round, so occupied slots are
    permanent and the claim is deterministic — with no table-sized
    temporary or merge per round (those dominated the profile at 4M-slot
    tables).

    Returns (slot[i32 per row], resident[i32 per row: the hash group's
    first row, n where unresolved], resolved[bool], table[i64 size-array:
    priority-encoded claim, _TBL_EMPTY where free], rounds used).
    """
    n = h.shape[0]
    n32 = jnp.int32(n)
    rows = jnp.arange(n, dtype=jnp.int64)

    def cond(st):
        k, _, _, _, active = st
        return (k < _HASH_MAX_ROUNDS) & active.any()

    def body(st):
        k, table, slot, resident, active = st
        s_k = _slot_at_round(h, k, size, direct)
        idx = jnp.where(active, s_k, size)
        val = ((k + 1).astype(jnp.int64) << 32) | rows
        table = table.at[idx].min(val, mode="drop")
        tv = table[s_k]
        res = (tv & _TBL_ROW_MASK).astype(jnp.int32)
        ok = (active & (tv != _TBL_EMPTY)
              & (h[jnp.clip(res, 0, n32 - 1)] == h))
        slot = jnp.where(ok, s_k, slot)
        resident = jnp.where(ok, res, resident)
        return k + 1, table, slot, resident, active & ~ok

    st = (jnp.int32(0), jnp.full(size, _TBL_EMPTY), jnp.zeros(n, jnp.int32),
          jnp.full(n, n32), valid)
    k, table, slot, resident, active = jax.lax.while_loop(cond, body, st)
    return slot, resident, valid & ~active, table, k


def _group_hashed_codes(key_cols: List[Column],
                        row_valid: Optional[jax.Array], cap: int):
    """Row-order dense group codes without any sort (CPU/GPU strategy).

    Returns (codes[i64 per row, trash slot == cap for invalid rows],
    first_rows[cap-sized original-row index per group], num_groups,
    collision).  num_groups comes back as cap+1 when the table could not
    resolve every key (more groups than cap, or pathological congestion),
    which rides the existing ngroups escalation: the caller recompiles
    with a doubled cap and therefore a doubled table.  Group numbering is
    hash-slot order — unordered, as SQL allows.
    """
    n = len(key_cols[0])
    parts = _key_parts(key_cols)
    h = _hash_group_parts(parts)
    valid = jnp.ones(n, bool) if row_valid is None else row_valid
    size = _hash_table_size(cap)
    single = _single_int_part(parts)
    direct = _direct_info(single, valid, size)
    combo_ok = None
    if single is None:
        combo = _combined_int_key([[(d, flag, valid)] for d, flag in parts])
        if combo is not None:
            # multi-part non-float keys: where the runtime radix product
            # fits, the combination is injective — collision-free mix hash
            # plus direct addressing when it also fits the table
            (key,), combo_ok, span_prod = combo
            h = jnp.where(combo_ok, _mix64(key.astype(jnp.uint64)), h)
            direct = (key, jnp.int64(0),
                      combo_ok & (span_prod <= jnp.float64(size)))
    slot, resident, resolved, table, _ = _hash_table_insert(h, valid, size,
                                                            direct)

    coll = jnp.zeros((), bool)
    if single is None:
        # true u64 collisions: a resident with equal hash, different raw key
        rc = jnp.clip(resident, 0, n - 1)
        for d, flag in parts:
            coll = coll | (resolved & (d[rc] != d)).any()
            if flag is not None:
                coll = coll | (resolved & (flag[rc] != flag)).any()
        if combo_ok is not None:
            # an injective combined key cannot collide; the raw check only
            # matters where the combination overflowed
            coll = coll & ~combo_ok
    # else: _mix64 over one int part is a bijection — collisions impossible

    # dense codes in first-occurrence order: rank the LEADER rows (a group's
    # resident is its first row) and read every row's code through its
    # resident — all O(n) ops, nothing table-sized
    leader = resolved & (resident == jnp.arange(n, dtype=resident.dtype))
    lrank = jnp.cumsum(leader.astype(jnp.int64)) - 1
    real_groups = jnp.sum(leader.astype(jnp.int64))
    unresolved = (valid & ~resolved).any()
    # congestion (true group count unknowable) reports the impossible value
    # n+1 — _check_flags reads any ng > input rows as "table saturated" and
    # jumps the cap hard; a RESOLVED overflow reports the exact count, so
    # the recompiled cap lands tight
    num_groups = jnp.where(unresolved, jnp.int64(n + 1), real_groups)

    codes_raw = lrank[jnp.clip(resident, 0, n - 1)]
    codes = jnp.where(resolved, jnp.minimum(codes_raw, cap), cap)
    fr_idx = jnp.where(leader & (codes < cap), codes, cap)
    first_rows = (jnp.full(cap, n, dtype=jnp.int64)
                  .at[fr_idx].min(jnp.arange(n, dtype=jnp.int64),
                                  mode="drop"))
    first_rows = jnp.clip(first_rows, 0, max(n - 1, 0))
    return codes, first_rows, num_groups, coll


# ---------------------------------------------------------------------------
# the tracer
# ---------------------------------------------------------------------------

class _Tracer:
    is_tracer = True   # routes RexScalarSubquery into traced_scalar_subquery

    def __init__(self, context, scan_tables: Dict[tuple, Table],
                 caps: Dict[str, int]):
        self.context = context
        self.scan_tables = scan_tables
        self.caps = caps
        self.fallback: List[jax.Array] = []      # device bools -> eager rerun
        self.ngroups: List[jax.Array] = []        # device ints, order = walk
        self.ngroup_caps: List[int] = []          # matching static caps
        self.agg_sites: List[Tuple[int, bool, str]] = []  # (rows, hashed, tag)
        self._agg_counter = 0
        self._cmp_counter = 0
        # filter nodes (by id) eligible for learned-capacity compaction —
        # computed by _compact_eligible over the whole plan before tracing
        self.compact_ok: set = set()
        # id(RexParam) -> traced 0-d scalar for the plan's hoisted literals
        # (set by _build's fn from the trailing jit arguments); None on
        # unparameterized programs — evaluate._eval_param then reads the
        # node's carried value, which only happens outside a param trace
        self.param_values: Optional[Dict[int, jax.Array]] = None

    def traced_scalar_subquery(self, rex, outer_table: Table) -> Column:
        """Inline an uncorrelated scalar subquery into this trace.

        Only statically-1-row subplans qualify (an ungrouped aggregate, or
        projections over one); anything with a runtime row count can't
        deliver SQL's 0-rows->NULL / >1-rows->error semantics in-program.
        The single value broadcasts to the outer table's length so NULL-ness
        rides the validity mask like any other column."""
        vt = self.run(rex.plan)
        if vt.valid is not None or vt.n != 1:
            raise Unsupported("scalar subquery with runtime row count")
        col = vt.table.columns[0]
        n = outer_table.num_rows
        d0 = col.data[0]
        data = jnp.broadcast_to(d0, (n,))
        valid0 = None if col.mask is None else col.mask[0]
        if jnp.issubdtype(col.data.dtype, jnp.floating):
            # the eager path coerces a NaN subquery result to NULL
            # (evaluate.py _eval_scalar_subquery); match it
            notnan = ~jnp.isnan(d0)
            valid0 = notnan if valid0 is None else (valid0 & notnan)
        mask = None if valid0 is None else jnp.broadcast_to(valid0, (n,))
        return Column(data, col.stype, mask, col.dictionary)

    # -- dispatch ----------------------------------------------------------
    def run(self, rel: RelNode) -> _VT:
        m = getattr(self, "_" + type(rel).__name__, None)
        if m is None:
            raise Unsupported(type(rel).__name__)
        return m(rel)

    # -- nodes -------------------------------------------------------------
    def _LogicalTableScan(self, rel: LogicalTableScan) -> _VT:
        t, valid = self.scan_tables[(rel.schema_name, rel.table_name)]
        want = [f.name for f in rel.schema]
        if t.names != want:
            t = t.limit_to(want)
        return _VT(t, valid)

    def _LogicalProject(self, rel: LogicalProject) -> _VT:
        src = self.run(rel.input)
        cols: List[Column] = []
        for rex, f in zip(rel.exprs, rel.schema):
            v = evaluate_rex(rex, src.table, self)
            if isinstance(v, Scalar):
                v = Column.from_scalar(v, src.n)
            cols.append(v)
        return _VT(Table([f.name for f in rel.schema], cols), src.valid,
                   weight=src.weight)

    def _LogicalFilter(self, rel: LogicalFilter) -> _VT:
        src = self.run(rel.input)
        mask = evaluate_predicate(rel.condition, src.table, self)
        if isinstance(mask, bool):
            if mask:
                return src
            return _VT(src.table, jnp.zeros(src.n, dtype=bool))
        valid = mask if src.valid is None else (mask & src.valid)
        out = _VT(src.table, valid, weight=src.weight)
        if id(rel) in self.compact_ok:
            out = self._maybe_compact(out)
        return out

    def _maybe_compact(self, vt: _VT) -> _VT:
        """Learned-capacity COMPACTION after a selective filter: static
        shapes mean a filter that drops 98% of lineitem still feeds all n
        masked rows into every join/sort above it — the single biggest
        steady-state tax vs the reference's dynamic partitions.  Compact to
        a power-of-2 capacity learned through the same flags/recompile
        machinery as group caps: cumsum + small gathers (~tens of ms)
        where every downstream sort then costs cap instead of n.  A
        learned cap >= n/2 disables the site (unselective filter)."""
        n = vt.n
        if n < (1 << 16):
            return vt  # small inputs: gathers save nothing
        tag = f"cmp{self._cmp_counter}"
        self._cmp_counter += 1
        default_cap = 1 << max(int((max(n // 4, 1) - 1)).bit_length(), 10)
        cap = min(self.caps.get(tag, default_cap), n)
        if cap * 2 >= n:
            return vt  # learned: not selective enough to pay the gathers
        mask = vt.vmask()
        count = mask.sum()
        idx = jnp.nonzero(mask, size=cap, fill_value=0)[0]
        row_valid = jnp.arange(cap) < count
        cols = [c.take(idx) for c in vt.table.columns]
        # count > cap rows were silently dropped: the flags check raises
        # _NeedsRecompile before any result materializes
        self.ngroups.append(count)
        self.ngroup_caps.append(cap)
        self.agg_sites.append((n, False, tag))
        return _VT(Table(list(vt.table.names), cols), row_valid,
                   weight=vt.weight)

    def _LogicalValues(self, rel: LogicalValues) -> _VT:
        from .rel.executor import _values
        return _VT(_values(rel, None), None)

    def _LogicalAggregate(self, rel: LogicalAggregate) -> _VT:
        src = self.run(rel.input)
        n = src.n
        out_cols: List[Column] = []
        out_names = [f.name for f in rel.schema]

        if not rel.group_keys:
            for j, agg in enumerate(rel.aggs):
                f = rel.schema[j]
                col = src.table.columns[agg.args[0]] if agg.args else None
                fmask = self._agg_filter(agg, src)
                if agg.distinct and agg.op not in ("MIN", "MAX"):
                    keep = self._distinct_keep([], agg, src)
                    fmask = keep if fmask is None else (fmask & keep)
                out_cols.append(G.whole_table_aggregate(
                    agg.op, col, fmask, f.stype, n))
            return _VT(Table(out_names, out_cols), None)

        key_cols = [src.table.columns[i] for i in rel.group_keys]
        static = self._static_domain_aggregate(rel, src, key_cols)
        if static is not None:
            return static

        # general path: group-sort once, then every aggregate is a prefix-sum
        # difference or segmented scan over the sorted stream — no scatter
        # (TPU scatter is serialized; see ops/sorted_agg.py)
        tag = f"agg{self._agg_counter}"
        self._agg_counter += 1
        cap = min(self.caps.get(tag, DEFAULT_GROUP_CAP), n)

        from ..ops.pallas_kernels import _strategy_on_tpu as _on_tpu
        if not _on_tpu():
            # CPU/GPU: hash-table codes + scatter segment aggregates — the
            # group sort this path replaces costs ~350 ms at 600k rows on
            # XLA:CPU while segment_sum costs ~2 ms
            return self._hashed_aggregate(rel, src, key_cols, cap, tag)

        # every column an aggregate reads rides the group sort as payload —
        # cheaper than a post-sort take(perm) random gather per column
        need: List[int] = []
        for agg in rel.aggs:
            for idx in (list(agg.args[:1])
                        + ([agg.filter_arg] if agg.filter_arg is not None
                           else [])):
                if idx not in need:
                    need.append(idx)
        payload: List[jax.Array] = []
        pay_slots: Dict[int, Tuple[int, Optional[int]]] = {}
        for idx in need:
            col = src.table.columns[idx]
            di = len(payload)
            payload.append(col.data)
            mi = None
            if col.mask is not None:
                mi = len(payload)
                payload.append(col.mask)
            pay_slots[idx] = (di, mi)

        # DISTINCT dedup masks: computed once per argument column and shipped
        # through the group sort as payload (not gathered by perm afterwards)
        keep_slots: Dict[int, int] = {}
        for agg in rel.aggs:
            if agg.distinct and agg.op not in ("MIN", "MAX"):
                ai = agg.args[0]
                if ai not in keep_slots:
                    keep_slots[ai] = len(payload)
                    payload.append(self._distinct_keep(key_cols, agg, src))

        gs = _group_sorted_codes(key_cols, src.valid, cap, tuple(payload))
        self.fallback.append(gs.collision)
        self.ngroups.append(gs.num_groups)
        self.ngroup_caps.append(cap)
        self.agg_sites.append((n, False, tag))

        for ki in rel.group_keys:
            out_cols.append(src.table.columns[ki].take(gs.first_rows))

        def _sorted_col(idx: int) -> Column:
            di, mi = pay_slots[idx]
            col = src.table.columns[idx]
            mask = gs.payload_sorted[mi] if mi is not None else None
            return Column(gs.payload_sorted[di], col.stype, mask,
                          col.dictionary)

        for j, agg in enumerate(rel.aggs):
            f = rel.schema[len(rel.group_keys) + j]
            col_s = _sorted_col(agg.args[0]) if agg.args else None
            vmask = gs.valid_sorted
            if col_s is not None and col_s.mask is not None:
                vmask = vmask & col_s.mask
            if agg.filter_arg is not None:
                fc = _sorted_col(agg.filter_arg)
                vmask = vmask & fc.data.astype(bool) & fc.valid_mask()
            if agg.distinct and agg.op not in ("MIN", "MAX"):
                # DISTINCT: only each (group keys, value) pair's first
                # occurrence contributes (MIN/MAX are dedup-invariant)
                vmask = vmask & gs.payload_sorted[keep_slots[agg.args[0]]]
            out_cols.append(G.sorted_segment_aggregate(
                agg.op, col_s, vmask, gs.codes_sorted, gs.starts, gs.ends,
                f.stype))
        row_valid = jnp.arange(cap) < gs.num_groups
        return _VT(Table(out_names, out_cols), row_valid)

    def _hashed_aggregate(self, rel, src: _VT, key_cols: List[Column],
                          cap: int, tag: str) -> _VT:
        """General GROUP BY off-TPU: hash-table group codes in original row
        order (no sort), then each aggregate is a segment_* scatter keyed on
        the dense codes — the same kernels the eager path uses
        (ops/groupby.py segment_aggregate), so semantics (exact decimals,
        NULL rules, string MIN/MAX ranks) are shared by construction.
        Invalid rows ride the trash segment ``cap``, sliced off afterwards.
        """
        n = src.n
        out_names = [f.name for f in rel.schema]
        codes, first_rows, num_groups, coll = _group_hashed_codes(
            key_cols, src.valid, cap)
        self.fallback.append(coll)
        self.ngroups.append(num_groups)
        self.ngroup_caps.append(cap)
        self.agg_sites.append((n, True, tag))

        out_cols: List[Column] = []
        for ki in rel.group_keys:
            out_cols.append(src.table.columns[ki].take(first_rows))

        def _trim(col: Column) -> Column:
            return Column(col.data[:cap], col.stype,
                          None if col.mask is None else col.mask[:cap],
                          col.dictionary)

        for j, agg in enumerate(rel.aggs):
            f = rel.schema[len(rel.group_keys) + j]
            col = src.table.columns[agg.args[0]] if agg.args else None
            fmask = self._agg_filter(agg, src)
            if agg.distinct and agg.op not in ("MIN", "MAX"):
                keep = self._distinct_keep(key_cols, agg, src)
                fmask = keep if fmask is None else (fmask & keep)
            out_cols.append(_trim(G.segment_aggregate(
                agg.op, col, codes, cap + 1, f.stype, filter_mask=fmask,
                n_rows=n)))
        row_valid = jnp.arange(cap) < num_groups
        return _VT(Table(out_names, out_cols), row_valid)

    def _static_domain_aggregate(self, rel, src: _VT, key_cols
                                 ) -> Optional[_VT]:
        """GROUP BY over a statically-enumerable key domain (dict-encoded
        strings / booleans): codes come straight from dictionary ranks — no
        sort, no scatter, no capacity escalation — and all reductions ride
        the MXU one-hot kernel (ops/pallas_kernels.py) on TPU. Key output
        columns are decoded from the slot index, so the data stream is
        touched exactly once. Returns None when the shape doesn't fit
        (non-MXU aggregates, non-enumerable keys, huge domains).

        This is the TPC-H Q1 shape: GROUP BY returnflag, linestatus.
        """
        from ..ops import pallas_kernels as pk
        static = _try_static_codes(key_cols)
        if static is None:
            return None
        codes, domain, key_meta = static
        if domain > 256:
            return None
        for agg in rel.aggs:
            col = src.table.columns[agg.args[0]] if agg.args else None
            if agg.op not in ("SUM", "$SUM0", "AVG", "COUNT") or agg.distinct:
                return None
            if col is not None and col.stype.is_string:
                return None
            if col is not None and col.data.dtype == jnp.bool_:
                return None

        n = src.n
        rv = src.valid
        kmask = jnp.ones(n, bool) if rv is None else rv

        out_names = [f.name for f in rel.schema]
        out_cols: List[Column] = _decode_static_keys(key_cols, key_meta,
                                                     domain)

        from ..types import exact_decimal_scale

        mxu_rows = [kmask.astype(jnp.float64)]  # row 0: occupancy counts
        row_classes = ["unit"]  # per-row grid for the limb MXU kernel
        slots = []
        for j, agg in enumerate(rel.aggs):
            f = rel.schema[len(rel.group_keys) + j]
            col = src.table.columns[agg.args[0]] if agg.args else None
            fmask = self._agg_filter(agg, src)
            # exact decimal money math rides the MXU too: integer-valued
            # f64 matmuls are exact below 2^53 (SF100 cents sums ~6e15)
            factor = 1.0
            if col is not None and agg.op in ("SUM", "$SUM0", "AVG"):
                ds = exact_decimal_scale(col.stype)
                if ds is not None:
                    factor = 10.0 ** ds
            if col is None:
                vmask = jnp.ones(n, bool) if fmask is None else fmask
                vrow = vmask.astype(jnp.float64)
                crow = vrow
                rc = "unit"
            elif agg.op == "COUNT":
                # COUNT(col): only the 0/1 count row is ever read — ship it
                # in the value slot too; no 2^53 magnitude guard (sums are
                # never used, so a huge BIGINT column must not fall back)
                vmask = col.valid_mask() if fmask is None \
                    else (col.valid_mask() & fmask)
                vrow = vmask.astype(jnp.float64)
                crow = vrow
                rc = "unit"
            else:
                vmask = col.valid_mask() if fmask is None \
                    else (col.valid_mask() & fmask)
                data = col.data.astype(jnp.float64)
                if factor != 1.0:
                    data = jnp.round(data * factor)
                vrow = jnp.where(vmask, data, 0.0)
                crow = vmask.astype(jnp.float64)
                is_int = factor != 1.0 or jnp.issubdtype(col.data.dtype,
                                                         jnp.integer)
                if is_int:
                    # the int grid is bit-exact only below 2^53; decimal
                    # scales are pre-gated (p<=15) but a raw BIGINT
                    # column's magnitude is data-dependent (initial= keeps
                    # the trace alive on 0-row inputs)
                    self.fallback.append(
                        jnp.max(jnp.abs(vrow), initial=0.0) >= 2.0 ** 53)
                rc = "int" if is_int else "float"
            slots.append((j, agg, f, len(mxu_rows), factor))
            mxu_rows.append(vrow)
            row_classes.append(rc)
            mxu_rows.append(crow)
            row_classes.append("unit")

        stack = jnp.stack(mxu_rows)
        red = pk.segmented_sums_dispatch(stack, codes, kmask, domain,
                                         row_classes=row_classes)
        occupancy = red[0] > 0

        from ..types import physical_dtype
        results: List[Optional[Column]] = [None] * len(rel.aggs)
        for j, agg, f, row0, factor in slots:
            sums, counts = red[row0], red[row0 + 1]
            has = counts > 0
            if agg.op == "COUNT":
                results[j] = Column(counts.astype(jnp.int64), f.stype, None)
            elif agg.op in ("$SUM0", "SUM"):
                out = sums
                if factor != 1.0:
                    # MXU sums of scaled decimals are integer-valued f64
                    # (exact below 2^53): unscale via the exact-quotient
                    # path, not a reciprocal-rewritten division
                    from ..ops.kernels import decimal_unscale
                    out = decimal_unscale(
                        sums.astype(jnp.int64),
                        int(round(math.log10(factor))))
                results[j] = Column(
                    out.astype(physical_dtype(f.stype)), f.stype,
                    None if agg.op == "$SUM0" else has)
            else:  # AVG
                results[j] = Column(sums / (jnp.maximum(counts, 1.0) * factor),
                                    f.stype, has)
        out_cols.extend(results)
        return _VT(Table(out_names, out_cols), occupancy)

    def _first_occurrence_keep(self, cols: List[Column],
                               row_valid: Optional[jax.Array]) -> jax.Array:
        """Row-space mask: True on the first valid row of each distinct
        column-tuple (the shared dedup primitive for UNION DISTINCT and
        DISTINCT aggregates). Appends the factorize collision flag."""
        n = len(cols[0])
        codes, first, _, coll = _traced_factorize(cols, row_valid, n)
        self.fallback.append(coll)
        return jnp.clip(first, 0, max(n - 1, 0))[codes] == jnp.arange(n)

    def _distinct_keep(self, key_cols: List[Column], agg, src: _VT
                       ) -> jax.Array:
        """First occurrence of each (group keys, argument value) combo."""
        return self._first_occurrence_keep(
            list(key_cols) + [src.table.columns[agg.args[0]]], src.valid)

    def _agg_filter(self, agg, src: _VT):
        """Combined FILTER-clause + row-validity mask (None = all rows)."""
        fmask = src.valid
        if agg.filter_arg is not None:
            fc = src.table.columns[agg.filter_arg]
            fm = fc.data.astype(bool) & fc.valid_mask()
            fmask = fm if fmask is None else (fmask & fm)
        return fmask

    def _LogicalSort(self, rel: LogicalSort) -> _VT:
        src = self.run(rel.input)
        n = src.n
        valid = src.valid
        table = src.table
        need_compact = rel.offset is not None or rel.limit is not None
        if rel.collation or (need_compact and valid is not None):
            arrays = []
            for c in reversed(rel.collation):
                col = table.columns[c.index]
                raw = comparable_data(col)
                if jnp.issubdtype(raw.dtype, jnp.floating):
                    d = canon_f64(raw)
                    # NaN sorts last in BOTH directions (XLA/eager semantics:
                    # -NaN is still NaN) — the flag is never negated
                    nanflag = jnp.isnan(raw).astype(jnp.int8)
                    if not c.ascending:
                        d = -d
                    arrays.append(d)
                    arrays.append(nanflag)
                else:
                    d = orderable_int64(raw)
                    if not c.ascending:
                        # -INT64_MIN wraps; clamp before negating (merges the
                        # two most-negative keys — unobservable in practice)
                        d = -jnp.where(d == _INT64_MIN, _INT64_MIN + 1, d)
                    arrays.append(d)
                if col.mask is not None:
                    nullkey = (~col.mask).astype(jnp.int8)
                    if c.effective_nulls_first:
                        nullkey = -nullkey
                    arrays.append(nullkey)
            if valid is not None:
                arrays.append((~valid).astype(jnp.int8))  # valid rows first
            perm = jnp.lexsort(arrays)
            table = table.take(perm)
            if valid is not None:
                count = jnp.sum(valid.astype(jnp.int64))
                valid = jnp.arange(n) < count
        start = rel.offset or 0
        stop = n if rel.limit is None else min(start + rel.limit, n)
        if start == 0 and stop == n:
            return _VT(table, valid)
        table = table.slice(start, stop)
        if valid is not None:
            count = jnp.sum(valid.astype(jnp.int64))
            valid = jnp.arange(stop - start) < (count - start)
        return _VT(table, valid)

    def _LogicalWindow(self, rel) -> _VT:
        from ..ops import window as W
        src = self.run(rel.input)
        names = list(src.table.names)
        cols = list(src.table.columns)
        for call in rel.calls:
            order = [(c.index, c.ascending, c.effective_nulls_first)
                     for c in call.order]
            col = W.compute_window(src.table, call.op, call.args,
                                   call.partition, order, call.frame,
                                   call.stype, row_valid=src.valid)
            cols.append(col)
            names.append(call.name)
        return _VT(Table(names, cols), src.valid)

    def _LogicalUnion(self, rel: LogicalUnion) -> _VT:
        from .rex.cast import cast_column
        parts = [self.run(i) for i in rel.inputs_]
        from ..ops.join import concat_columns
        out_names = [f.name for f in rel.schema]
        cols: List[Column] = []
        for j, f in enumerate(rel.schema):
            pieces = []
            for p in parts:
                c = p.table.columns[j]
                if c.stype.name != f.stype.name:
                    c = cast_column(c, f.stype)
                pieces.append(c)
            cols.append(concat_columns(pieces))
        valids = [p.vmask() for p in parts]
        valid = (None if all(p.valid is None for p in parts)
                 else jnp.concatenate(valids))
        out = _VT(Table(out_names, cols), valid)
        if rel.all:
            return out
        # UNION DISTINCT: keep first occurrence of each distinct row
        keep = self._first_occurrence_keep(list(out.table.columns),
                                           out.valid)
        return _VT(out.table, keep & out.vmask())

    def _LogicalJoin(self, rel: LogicalJoin) -> _VT:
        from .rel.executor import _and_rex, _extract_equi_keys
        left = self.run(rel.left)
        right = self.run(rel.right)
        equi, residual = _extract_equi_keys(rel)
        jt = rel.join_type
        if not equi:
            raise Unsupported("non-equi/cross join")

        lk = [k for k, _ in equi]
        rk = [k for _, k in equi]
        out_names = [f.name for f in rel.schema]

        if jt == "LEFT" or jt in ("SEMI", "ANTI"):
            probe, build, probe_is_left = left, right, True
            pk_cols = [left.table.columns[i] for i in lk]
            bk_cols = [right.table.columns[i] for i in rk]
        elif jt == "RIGHT":
            probe, build, probe_is_left = right, left, False
            pk_cols = [right.table.columns[i] for i in rk]
            bk_cols = [left.table.columns[i] for i in lk]
        else:  # INNER: probe the bigger side (by pre-compaction weight)
            if left.weight >= right.weight:
                probe, build, probe_is_left = left, right, True
                pk_cols = [left.table.columns[i] for i in lk]
                bk_cols = [right.table.columns[i] for i in rk]
            else:
                probe, build, probe_is_left = right, left, False
                pk_cols = [right.table.columns[i] for i in rk]
                bk_cols = [left.table.columns[i] for i in lk]

        if probe_is_left:
            pparts, bparts = _join_key_parts(pk_cols, bk_cols)
        else:
            bparts, pparts = _join_key_parts(bk_cols, pk_cols)

        exist_test = None
        if residual and jt in ("SEMI", "ANTI"):
            # a single carried candidate can't decide a per-PAIR residual,
            # but one of the form  build.x OP probe.y  (OP comparison) only
            # needs per-key build aggregates: exists x<>y <=> cnt>0 and
            # (min!=y or max!=y); exists x<y <=> min<y; etc. (TPC-H Q21's
            # NOT EXISTS .. l3.l_suppkey <> l1.l_suppkey). Anything else —
            # or float operands, whose NaN comparison semantics the
            # min/max reduction can't reproduce — stays eager.
            exist_test = self._residual_exist_test(rel, residual, probe,
                                                   build)
            if exist_test is None:
                raise Unsupported("semi/anti join with general residual")

        pvalid = _keys_valid(pk_cols, probe.valid)
        bvalid = _keys_valid(bk_cols, build.valid)
        ph = _hash_parts(pparts, pvalid)
        bh = _hash_parts(bparts, bvalid)

        from ..ops.pallas_kernels import _strategy_on_tpu as _on_tpu
        if _on_tpu():
            # sorted-probe join: one 2-channel build-side argsort + binary
            # search + row-id gathers, regardless of build width — so the
            # r1/r2 wide-build strategy switch is gone (no per-column sort
            # cost left for it to avoid)
            match, gathered = self._join_merge(jt, probe, build, pparts,
                                               bparts, pvalid, ph, bh,
                                               exist_test)
        else:
            # CPU/GPU: scatters and gathers cost ~1 ms where any 600k-row
            # sort costs 350-750 ms — hash-table join, no sort of either side
            match, gathered = self._join_hash_table(jt, probe, build,
                                                    pparts, bparts,
                                                    pvalid, ph, bh,
                                                    exist_test)

        if jt == "SEMI":
            return _VT(probe.table.with_names(out_names),
                       probe.vmask() & match, weight=probe.weight)
        if jt == "ANTI":
            keep = ~match
            if getattr(rel, "null_aware", False):
                # NOT IN: any NULL key on the build side empties the
                # result; NULL probe keys qualify only when the build is
                # EMPTY (x NOT IN (empty) is TRUE for every x — matches
                # ops/join.py:78-88 and PostgreSQL/SQLite)
                build_rows = build.vmask()
                build_has_null = (build_rows & ~bvalid).any()
                build_nonempty = build_rows.any()
                keep = (keep & ~build_has_null
                        & (pvalid | ~build_nonempty))
            return _VT(probe.table.with_names(out_names),
                       probe.vmask() & keep, weight=probe.weight)

        def _pairs(build_cols: List[Column]) -> Table:
            if probe_is_left:
                return Table(out_names,
                             list(probe.table.columns) + build_cols)
            return Table(out_names, build_cols + list(probe.table.columns))

        if residual:
            # ON-clause residual: evaluated on the candidate pair (real
            # probe values + the carried build candidate's values); where
            # the equi key already failed, the AND with match discards the
            # garbage verdict
            pred = evaluate_predicate(_and_rex(residual), _pairs(gathered),
                                      self)
            if isinstance(pred, bool):
                pred = jnp.full(probe.n, pred)
            match = match & pred

        if jt == "INNER":
            return _VT(_pairs(gathered), probe.vmask() & match,
                       weight=probe.weight)
        # LEFT/RIGHT: every (valid) probe row survives; the build side is
        # NULL wherever the full ON condition (equi + residual) failed
        gathered = [c.with_mask(c.valid_mask() & match) for c in gathered]
        return _VT(_pairs(gathered), probe.valid, weight=probe.weight)

    def _append_join_flags(self, jt, adj: jax.Array, raw_diffs) -> None:
        """Shared fallback policy for both join strategies. ``adj`` marks
        adjacent equal-hash build pairs in build-hash-sorted order;
        ``raw_diffs`` are the matching adjacent raw-key inequality masks.
        INNER/LEFT/RIGHT require a unique build key (adjacency of any kind
        covers hash collisions too); SEMI/ANTI tolerate duplicates, so only
        a genuine collision (equal hash, different raw key) is fatal."""
        if jt in ("INNER", "LEFT", "RIGHT"):
            self.fallback.append(adj.any())
        else:
            coll = jnp.zeros((), dtype=bool)
            for d in raw_diffs:
                coll = coll | (adj & d).any()
            self.fallback.append(coll)

    def _residual_exist_test(self, rel, residual, probe: _VT, build: _VT):
        """(op, x build Column, y probe Column) for a residual of the form
        ``build.x OP probe.y`` with OP a comparison; None otherwise.
        ``op`` is normalized so the test reads "exists build x with x OP y".
        Floats are excluded (NaN comparison semantics don't survive the
        min/max reduction)."""
        if len(residual) != 1:
            return None
        r = residual[0]
        if not (isinstance(r, RexCall) and r.op in ("<>", "<", "<=", ">", ">=")
                and len(r.operands) == 2
                and all(isinstance(o, RexInputRef) for o in r.operands)):
            return None
        nl = len(rel.left.schema)  # probe IS the left side for SEMI/ANTI
        a, b = r.operands
        if a.index < nl <= b.index:      # pred = y OP x -> exists x SWAP(OP) y
            y_col = probe.table.columns[a.index]
            x_col = build.table.columns[b.index - nl]
            op = {"<": ">", ">": "<", "<=": ">=", ">=": "<=", "<>": "<>"}[r.op]
        elif b.index < nl <= a.index:    # pred = x OP y
            x_col = build.table.columns[a.index - nl]
            y_col = probe.table.columns[b.index]
            op = r.op
        else:
            return None
        if x_col.stype.is_string != y_col.stype.is_string:
            return None
        for c in (x_col, y_col):
            if not c.stype.is_string and jnp.issubdtype(c.data.dtype,
                                                        jnp.floating):
                return None
        if not x_col.stype.is_string:
            # the min/max reduction runs in int64: uint64 values >= 2^63
            # would wrap on the cast and invert the ordering, and a MIXED
            # uint64/signed pair promotes to float64 (lossy above 2^53) —
            # only pairs whose promotion stays a signed integer are safe
            dt = jnp.promote_types(x_col.data.dtype, y_col.data.dtype)
            if dt == jnp.uint64 or jnp.issubdtype(dt, jnp.floating):
                return None
        return op, x_col, y_col

    def _join_merge(self, jt, probe: _VT, build: _VT, pparts, bparts,
                    pvalid: jax.Array, ph: jax.Array, bh: jax.Array,
                    exist_test=None):
        """Sorted-probe join, the TPU strategy: sort ONLY the build side's
        hashes (2-channel argsort at nb rows), locate each probe hash with
        ``searchsorted(method='sort')`` — ONE (nb+npr)-row 2-channel sort.
        The scan method looked cheaper on paper (log2(nb) HLO ops), but on
        TPU each of its ~21 iterations is an npr-row gather: 2.66 s at
        SF-1 Q12 shapes vs ~40 ms for the sort method (measured r4, this
        chip) — the scan was the whole reason join-heavy queries lost to
        pandas in BENCH_r04 try 1.  Raw keys verify via row-id gathers.

        History: r1/r2 shipped a "zero-gather" merge join that moved every
        build column through a variadic sort and an associative carry scan,
        justified by an eager-mode profile (32 ms per gather at 1.8M rows).
        That 32 ms was the per-op TUNNEL round trip, not the gather: inside
        a compiled program a 6M-row gather costs ~1 ms on the same chip
        (measured this round), while the payload formulation's compile time
        explodes superlinearly on XLA:TPU at SF-1 shapes (13-channel sort
        153 s; 2-channel associative_scan >15 min; whole two-join programs
        >35 min — uncompilable in practice).  The sorted probe compiles in
        seconds, sorts nb instead of nb+npr rows, and its gathers are noise.

        SEMI/ANTI residual exist-tests still use the payload variant
        (_join_merge_payload): per-run build aggregates need the sorted
        x-value stream, and those plans carry no build columns, so their
        channel count stays small.  Returns (match over probe rows, fetched
        build columns or None for SEMI/ANTI)."""
        if exist_test is not None:
            return self._join_merge_payload(jt, probe, build, pparts,
                                            bparts, pvalid, ph, bh,
                                            exist_test)
        nb, npr = build.n, probe.n
        if nb == 0:
            # a gather from a 0-row build would fail at trace time; an
            # empty build matches nothing (x NOT IN (empty) handled by the
            # caller's null-aware logic over this all-false match)
            self.fallback.append(jnp.zeros((), bool))
            match = jnp.zeros(npr, dtype=bool)
            if jt in ("SEMI", "ANTI"):
                return match, None
            # zero-filled columns, masked by the all-false match downstream
            # (same values the payload formulation's concat-of-zeros carried)
            return match, [
                Column(jnp.zeros(npr, dtype=c0.data.dtype), c0.stype,
                       None if c0.mask is None else jnp.zeros(npr, bool),
                       c0.dictionary)
                for c0 in build.table.columns]
        order = jnp.argsort(bh)
        bh_sorted = bh[order]
        # duplicate build keys / hash collisions appear as adjacent equal
        # hashes in sorted order (same flag policy as every strategy)
        adj = (bh_sorted[1:] == bh_sorted[:-1]) & (bh_sorted[1:] != _U64_MAX)
        raws_sorted = [braw[order] for _, braw in bparts]
        self._append_join_flags(
            jt, adj, [rs[1:] != rs[:-1] for rs in raws_sorted])

        pos = jnp.searchsorted(bh_sorted, ph, side="left", method="sort")
        in_range = pos < nb
        pos_c = jnp.minimum(pos, nb - 1)
        cand = order[pos_c]
        match = in_range & pvalid & (bh_sorted[pos_c] == ph)
        for (_, praw), (_, braw) in zip(pparts, bparts):
            match = match & (praw == braw[cand])
        if jt in ("SEMI", "ANTI"):
            return match, None
        return match, [c0.take(cand) for c0 in build.table.columns]

    def _join_merge_payload(self, jt, probe: _VT, build: _VT, pparts,
                            bparts, pvalid: jax.Array, ph: jax.Array,
                            bh: jax.Array, exist_test=None):
        """Payload-channel merge join (r1/r2 formulation), kept for the
        SEMI/ANTI residual exist-test path: per-run build aggregates need
        the sorted x-value stream and segmented scans. Returns (match over
        probe rows, carried build columns or None for SEMI/ANTI)."""
        nb, npr = build.n, probe.n
        m = nb + npr
        h_m = jnp.concatenate([bh, ph])
        flag_b = jnp.concatenate([jnp.ones(nb, bool), jnp.zeros(npr, bool)])
        idt = jnp.int32 if m < 2**31 else jnp.int64
        iota_m = jnp.arange(m, dtype=idt)
        raw_ch = [jnp.concatenate([braw, praw])
                  for (_, braw), (_, praw) in zip(bparts, pparts)]
        need_cols = jt in ("INNER", "LEFT", "RIGHT")
        col_ch: List[jax.Array] = []
        if need_cols:
            for c0 in build.table.columns:
                col_ch.append(jnp.concatenate(
                    [c0.data, jnp.zeros(npr, dtype=c0.data.dtype)]))
                if c0.mask is not None:
                    col_ch.append(jnp.concatenate(
                        [c0.mask, jnp.zeros(npr, dtype=bool)]))

        res_ch: List[jax.Array] = []
        if exist_test is not None:
            _, x_col, y_col = exist_test
            if x_col.stype.is_string:
                xd, yd = unify_string_codes([x_col, y_col])
            else:
                dt = jnp.promote_types(x_col.data.dtype, y_col.data.dtype)
                xd = x_col.data.astype(dt)
                yd = y_col.data.astype(dt)
            xd, yd = xd.astype(jnp.int64), yd.astype(jnp.int64)
            res_ch = [
                jnp.concatenate([xd, jnp.zeros(npr, dtype=jnp.int64)]),
                jnp.concatenate([x_col.valid_mask(),
                                 jnp.zeros(npr, dtype=bool)]),
                jnp.concatenate([jnp.zeros(nb, dtype=jnp.int64), yd]),
                jnp.concatenate([jnp.zeros(nb, dtype=bool),
                                 y_col.valid_mask()]),
            ]

        outs = jax.lax.sort((h_m, flag_b, iota_m, *raw_ch, *col_ch,
                             *res_ch),
                            num_keys=1, is_stable=True)
        hs, fbs, iotas = outs[0], outs[1], outs[2]
        raws = outs[3:3 + len(raw_ch)]
        ncol = len(col_ch)
        colss = outs[3 + len(raw_ch): 3 + len(raw_ch) + ncol]
        ress = outs[3 + len(raw_ch) + ncol:]

        # equal-hash build rows are contiguous (stable sort puts build rows
        # before same-hash probe rows), so duplicates/collisions show up as
        # adjacent build pairs — no scan needed for the flags
        adj = fbs[1:] & fbs[:-1] & (hs[1:] == hs[:-1]) & (hs[1:] != _U64_MAX)
        self._append_join_flags(jt, adj, [r[1:] != r[:-1] for r in raws])

        def carry_op(a, b):
            take = b[0]
            return tuple([a[0] | b[0]]
                         + [jnp.where(take, bv, av)
                            for av, bv in zip(a[1:], b[1:])])

        carried = jax.lax.associative_scan(
            carry_op, (fbs, *raws, *colss))
        has_b = carried[0]
        c_raws = carried[1:1 + len(raws)]
        c_cols = carried[1 + len(raws):]

        # a probe row matches iff the last build row at-or-before it has the
        # same raw key (equal raw => equal hash, and everything between them
        # in hash order then shares that hash)
        match_s = (~fbs) & has_b
        for cr, r in zip(c_raws, raws):
            match_s = match_s & (cr == r)

        if exist_test is not None:
            # per-hash-run build aggregates decide "exists build x OP y":
            # all build rows of a run precede its probe rows (stable sort),
            # so a probe's inclusive segmented scan covers the whole run
            from ..ops.window import segmented_cumsum, segmented_scan
            op_t = exist_test[0]
            xs, xvs, ys, yvs = ress
            run_start = jnp.concatenate(
                [jnp.ones(1, dtype=bool), hs[1:] != hs[:-1]])
            xv = xvs & fbs
            cnt = segmented_cumsum(xv.astype(jnp.int64), run_start)
            mn = segmented_scan(jnp.where(xv, xs, jnp.iinfo(jnp.int64).max),
                                run_start, jnp.minimum)
            mx = segmented_scan(jnp.where(xv, xs, jnp.iinfo(jnp.int64).min),
                                run_start, jnp.maximum)
            has_x = cnt > 0
            if op_t == "<>":
                ex = (mn != ys) | (mx != ys)
            elif op_t == "<":
                ex = mn < ys
            elif op_t == "<=":
                ex = mn <= ys
            elif op_t == ">":
                ex = mx > ys
            else:
                ex = mx >= ys
            match_s = match_s & has_x & ex & yvs

        un = jax.lax.sort((iotas, match_s, *c_cols), num_keys=1)
        match = un[1][nb:] & pvalid
        ub_cols = [o[nb:] for o in un[2:]]

        if not need_cols:
            return match, None
        gathered: List[Column] = []
        it = iter(ub_cols)
        for c0 in build.table.columns:
            data = next(it)
            mask = next(it) if c0.mask is not None else None
            gathered.append(Column(data, c0.stype, mask, c0.dictionary))
        return match, gathered

    def _join_hash_table(self, jt, probe: _VT, build: _VT, pparts, bparts,
                         pvalid: jax.Array, ph: jax.Array, bh: jax.Array,
                         exist_test=None):
        """Open-addressing hash join, the CPU/GPU strategy: insert build
        row ids into a power-of-2 table (empty-slot claim rounds, see
        _hash_table_insert), probe with one gather chain per round actually
        used.  Verification always compares raw key parts, so lossy hashes
        only add collisions — caught by the flags and rerun eager.  SEMI/
        ANTI residual exist-tests aggregate (count, min, max) per slot with
        cheap scatters, which the sorted-gather strategy could not express.
        """
        nb, npr = build.n, probe.n
        size = _hash_table_size(nb)
        bvalid = bh != _U64_MAX          # _hash_parts marks invalid keys
        # single integer-raw key (ints, dates, unified string codes): the
        # _mix64 rehash is a BIJECTION, so hash equality IS key equality —
        # no raw verification, no collision flag — and the raw values
        # enable the direct-address round-0 fast path
        bij = (len(bparts) == 1
               and jnp.issubdtype(bparts[0][1].dtype, jnp.integer))
        direct_b = direct_p = None
        combo_ok = None
        if bij:
            braw1 = bparts[0][1].astype(jnp.int64)
            praw1 = pparts[0][1].astype(jnp.int64)
            bh = _mix64(braw1.astype(jnp.uint64))   # clamp-free, clean
            ph = _mix64(praw1.astype(jnp.uint64))
            direct_b = _direct_info(braw1, bvalid, size)
            if direct_b is not None:
                direct_p = (praw1, direct_b[1], direct_b[2])
        else:
            # multi-part keys: mixed-radix combination over the UNION of
            # both sides' runtime ranges — injective where the radix
            # product fits (combo_ok), giving a collision-free hash and
            # direct addressing when it also fits the table
            combo = _combined_int_key(
                [[(braw, None, bvalid), (praw, None, pvalid)]
                 for (_, braw), (_, praw) in zip(bparts, pparts)])
            if combo is not None:
                (bkey, pkey), combo_ok, span_prod = combo
                bh = jnp.where(combo_ok,
                               _mix64(bkey.astype(jnp.uint64)), bh)
                ph = jnp.where(combo_ok,
                               _mix64(pkey.astype(jnp.uint64)), ph)
                fits = combo_ok & (span_prod <= jnp.float64(size))
                direct_b = (bkey, jnp.int64(0), fits)
                direct_p = (pkey, jnp.int64(0), fits)
        slot, resident, resolved, table, rounds = _hash_table_insert(
            bh, bvalid, size, direct_b)

        raw_mismatch = jnp.zeros((), bool)
        if not bij:
            rc0 = jnp.clip(resident, 0, nb - 1)
            for _, braw in bparts:
                raw_mismatch = raw_mismatch | (resolved
                                               & (braw[rc0] != braw)).any()
            if combo_ok is not None:
                # injective combined keys cannot collide; the raw check
                # only matters where the combination overflowed
                raw_mismatch = raw_mismatch & ~combo_ok
        unresolved = (bvalid & ~resolved).any()
        if jt in ("INNER", "LEFT", "RIGHT"):
            # these require a unique build key (same policy as the sort
            # strategies): any second row of a key resolves to a foreign
            # resident
            dup = (resolved
                   & (resident != jnp.arange(nb, dtype=resident.dtype))).any()
            self.fallback.append(raw_mismatch | dup | unresolved)
        else:
            self.fallback.append(raw_mismatch | unresolved)

        # probe: same slot sequence; a key resident at round k implies its
        # rounds 0..k slots are all occupied, so scanning the rounds the
        # insert used and taking the first equal-hash resident is complete
        nb32 = jnp.int32(nb)

        def probe_body(st):
            k, cand = st
            s_k = _slot_at_round(ph, k, size, direct_p)
            tv = table[s_k]
            r = (tv & _TBL_ROW_MASK).astype(jnp.int32)
            hit = (tv != _TBL_EMPTY) & (bh[jnp.clip(r, 0, nb32 - 1)] == ph)
            cand = jnp.where((cand == nb32) & hit, r, cand)
            return k + 1, cand

        def probe_cond(st):
            k, _ = st
            return k < rounds

        _, cand = jax.lax.while_loop(
            probe_cond, probe_body, (jnp.int32(0), jnp.full(npr, nb32)))
        found = cand < nb32
        cc = jnp.clip(cand, 0, nb - 1)
        match = found & pvalid
        if not bij:
            raw_eq = jnp.ones(npr, dtype=bool)
            for (_, praw), (_, braw) in zip(pparts, bparts):
                raw_eq = raw_eq & (praw == braw[cc])
            if combo_ok is not None:
                # hash equality is key equality where the combination held
                match = match & (combo_ok | raw_eq)
            else:
                match = match & raw_eq

        if exist_test is not None:
            # per-slot build aggregates decide "exists build x OP y"
            op_t, x_col, y_col = exist_test
            if x_col.stype.is_string:
                xd, yd = unify_string_codes([x_col, y_col])
            else:
                dt = jnp.promote_types(x_col.data.dtype, y_col.data.dtype)
                xd = x_col.data.astype(dt)
                yd = y_col.data.astype(dt)
            xd, yd = xd.astype(jnp.int64), yd.astype(jnp.int64)
            # aggregates are indexed by the group's RESIDENT row id (dense
            # in [0, nb)), not by table slot: nb-sized arrays instead of
            # table-sized ones, and the probe's candidate IS the resident
            xv = resolved & x_col.valid_mask()
            idx = jnp.where(xv, resident, nb)
            i64 = jnp.iinfo(jnp.int64)
            cnt = jnp.zeros(nb, jnp.int64).at[idx].add(1, mode="drop")
            mn = (jnp.full(nb, i64.max, jnp.int64)
                  .at[idx].min(xd, mode="drop"))
            mx = (jnp.full(nb, i64.min, jnp.int64)
                  .at[idx].max(xd, mode="drop"))
            cntp, mnp, mxp = cnt[cc], mn[cc], mx[cc]
            if op_t == "<>":
                ex = (mnp != yd) | (mxp != yd)
            elif op_t == "<":
                ex = mnp < yd
            elif op_t == "<=":
                ex = mnp <= yd
            elif op_t == ">":
                ex = mxp > yd
            else:
                ex = mxp >= yd
            match = match & (cntp > 0) & ex & y_col.valid_mask()

        if jt in ("SEMI", "ANTI"):
            return match, None
        return match, [c.take(cc) for c in build.table.columns]





# ---------------------------------------------------------------------------
# compile + execute
# ---------------------------------------------------------------------------

class _Compiled:
    __slots__ = ("fn", "spec", "meta", "caps", "key", "origin", "aot")

    def __init__(self, fn, spec, meta, caps, key, origin=None, aot=False):
        self.fn = fn
        self.spec = spec
        self.meta = meta        # filled during first trace
        self.caps = caps
        self.key = key
        self.origin = origin    # root-query fingerprint that compiled it
        self.aot = aot          # fn is an AOT jax.stages.Compiled (the
                                # serializable form the program store needs)


_cache: "OrderedDict[tuple, object]" = OrderedDict()
# learned state per (plan, inputs) key: escalated group caps and runtime
# verdicts, so steady state never repeats an overflow run or a known-eager
# compiled attempt; bounded like the program cache
_learned_caps: "OrderedDict[tuple, Dict[str, int]]" = OrderedDict()
_runtime_eager: "OrderedDict[tuple, bool]" = OrderedDict()
_LEARNED_LIMIT = 1024
_UNSUPPORTED = object()

# Optional write-through persistence for learned group caps
# (``DSQL_CAPS_FILE=/path.json``): a capacity-escalation recompile is cheap
# on XLA:CPU but costs 100-200 s per program over the tunneled TPU backend,
# so caps learned by one process (a bench stage child, a warmup run) must
# carry to the next.  Keys are hashes of the full program base key — plan
# fingerprint, input layout fingerprint, strategy — so a cap never applies
# to a different query, data layout, or backend strategy.
_caps_disk: Optional[Dict[str, Dict[str, int]]] = None
_caps_seed: Optional[Dict[str, Dict[str, int]]] = None


def _caps_disk_key(base_key) -> str:
    return _kv.digest_key(base_key)


def _caps_disk_read(path: str) -> Dict[str, Dict[str, int]]:
    """Tolerant caps-file read on the shared kvstore plumbing
    (runtime/kvstore.py — the same atomic-write/corrupt-tolerant
    discipline the quarantine store and the program store index use)."""
    return {k: {t: int(c) for t, c in v.items()}
            for k, v in _kv.read_json_dict(path).items()}


def _learned_caps_get(base_key) -> Dict[str, int]:
    caps = _learned_caps.get(base_key)
    if caps is not None:
        return dict(caps)
    key = None
    path = os.environ.get("DSQL_CAPS_FILE")
    if path:
        global _caps_disk
        if _caps_disk is None:
            _caps_disk = _caps_disk_read(path)
        key = _caps_disk_key(base_key)
        hit = _caps_disk.get(key)
        if hit:
            return dict(hit)
    # read-only seed (``DSQL_CAPS_SEED=/path.json``): caps and split hints
    # learned on one host, committed with the repo, consulted when neither
    # memory nor the writable caps file knows this program.  Keys are
    # content-based (plan + input-layout fingerprints), so a seed entry can
    # only ever match the same query over same-layout data — on any host.
    seed_path = os.environ.get("DSQL_CAPS_SEED")
    if seed_path:
        global _caps_seed
        if _caps_seed is None:
            _caps_seed = _caps_disk_read(seed_path)
        return dict(_caps_seed.get(key or _caps_disk_key(base_key), {}))
    return {}


def _learned_caps_put(base_key, caps: Dict[str, int]) -> None:
    _bounded_put(_learned_caps, base_key, dict(caps))
    path = os.environ.get("DSQL_CAPS_FILE")
    if not path:
        return
    global _caps_disk
    # read-merge-replace: concurrent writers (threaded warmup) can lose a
    # race, which only costs one re-learn — never corrupts (kvstore's
    # atomic replace; tmp name is per-thread so two warmup threads can't
    # interleave bytes)
    disk = _caps_disk_read(path)
    disk[_caps_disk_key(base_key)] = {k: int(v) for k, v in caps.items()}
    if _kv.atomic_write_json(path, disk):
        _caps_disk = disk


def _bounded_put(d: OrderedDict, key, value):
    while len(d) >= _LEARNED_LIMIT:
        d.popitem(last=False)
    d[key] = value


# ---------------------------------------------------------------------------
# persistent program store glue (runtime/program_store.py): a successfully
# compiled program's XLA executable is serialized to DSQL_PROGRAM_STORE so a
# fresh process (server restart, new bench child) loads it with ZERO
# recompilation; a compile-cache miss consults the store before paying XLA.
# ---------------------------------------------------------------------------

# stage-boundary temp names embed per-process table uids (_stage_table_name)
# but the compiled program is uid-independent — it depends only on plan
# shape and input layout.  For the cross-process store key, boundary names
# are rewritten to position-stable placeholders so two processes running
# the same query over the same-layout data address the same entry.
_BOUNDARY_NAME_RE = re.compile(r"__split__\.t[0-9a-f]{16}")


def _canonical_program_key(base_key):
    plan_fp = base_key[0]
    mapping: Dict[str, str] = {}

    def sub(m):
        return mapping.setdefault(m.group(0), f"__split__.#{len(mapping)}")

    return (_BOUNDARY_NAME_RE.sub(sub, plan_fp),) + tuple(base_key[1:])


def _pstore_digest(base_key) -> str:
    return _pstore.get_store().digest(_canonical_program_key(base_key))


def _profile_on() -> bool:
    """Device profiler armed?  Checked BEFORE importing runtime.profiler
    so a disabled profiler costs one env read and zero imports."""
    return os.environ.get("DSQL_PROFILE", "0").strip() not in ("", "0")


def _events_on() -> bool:
    """Watchtower event bus armed?  Same discipline as _profile_on —
    env checked BEFORE importing runtime.events."""
    return os.environ.get("DSQL_EVENTS", "0").strip() not in ("", "0")


def _pstore_put(entry: _Compiled, base_key, n_args: int, n_outs: int
                ) -> None:
    """Serialize + persist a freshly compiled program (best-effort; only
    AOT-compiled entries carry a serializable executable)."""
    store = _pstore.get_store()
    if not store.enabled() or not entry.aot:
        return
    try:
        from jax.experimental import serialize_executable as _se
        payload, _, _ = _se.serialize(entry.fn)
    except (KeyboardInterrupt, SystemExit):
        raise
    except Exception as e:
        _tel.inc("program_store_errors")
        logger.debug("program serialize failed (%s); not persisted", e)
        return
    rec = {
        "v": 1,
        "caps": {k: int(v) for k, v in entry.caps.items()},
        "spec": entry.spec,
        "meta": entry.meta,
        "payload": payload,
        "n_args": int(n_args),
        "n_outs": int(n_outs),
    }
    # XLA cost analysis rides the entry (missing-tolerant: backends
    # without a cost model simply omit the key) so a warm process has
    # cost estimates with zero recompilation (runtime/profiler.py)
    if _profile_on():
        try:
            from ..runtime import profiler as _prof
            cost = _prof.cost_summary(entry.fn)
            if cost is not None:
                rec["cost"] = cost
        except Exception:
            logger.debug("cost capture at store failed", exc_info=True)
    store.store(_pstore_digest(base_key), rec)


def _pstore_attempt(base_key, flat, query_fp: str = ""):
    """Load + execute this program from the persistent store.

    Returns (entry, outs, caps) on a hit — the executable deserialized
    with zero XLA compilation, its first execution already done — or None
    (miss, corrupt entry, fingerprint mismatch, arity drift), in which
    case the caller compiles normally.  The fn signature's pytree
    structure is flat tuples by construction (_build), so the arg/out
    treedefs are reconstructed from counts instead of being pickled.
    """
    store = _pstore.get_store()
    if not store.enabled():
        return None
    raw = store.load(_pstore_digest(base_key))
    if raw is None:
        return None
    try:
        import jax.tree_util as _jtu
        from jax.experimental import serialize_executable as _se
        if int(raw.get("v", 0)) != 1 or int(raw["n_args"]) != len(flat):
            raise ValueError("entry layout mismatch")
        in_tree = _jtu.tree_structure((tuple(range(len(flat))), {}))
        out_tree = _jtu.tree_structure(tuple(range(int(raw["n_outs"]))))
        fn = _se.deserialize_and_load(raw["payload"], in_tree, out_tree)
        caps = {str(k): int(v) for k, v in (raw.get("caps") or {}).items()}
        entry = _Compiled(fn, raw["spec"], raw["meta"], caps,
                          (base_key, tuple(sorted(caps.items()))), aot=True)
        outs = entry.fn(*flat)
    except (KeyboardInterrupt, SystemExit):
        raise
    except Exception as e:
        # a stored executable that won't deserialize or execute here is as
        # good as corrupt: count it, fall back to a normal compile
        _tel.inc("program_store_errors")
        logger.warning("program store load failed (%s: %s); recompiling",
                       type(e).__name__, str(e)[:120])
        return None
    _tel.inc("program_store_hits")
    _tel.annotate(program_store="hit")
    # the persisted cost analysis (when the storing process captured one)
    # seeds this process's model-vs-measured ledger without a recompile;
    # keyed under the ROOT query's fingerprint so the scheduler's
    # cost_model rung finds it
    if _profile_on():
        cost = raw.get("cost")
        if cost:
            try:
                from ..runtime import profiler as _prof
                _prof.record_program_cost(query_fp,
                                          _pstore_digest(base_key), cost)
                _tel.annotate(cost_flops=cost.get("flops"),
                              cost_bytes=cost.get("bytes"))
            except Exception:
                logger.debug("cost ledger seed failed", exc_info=True)
    return entry, outs, caps


# ---------------------------------------------------------------------------
# compile-worker backoff: BENCH_r05's 10 compile_errors coincided with
# 4-way concurrent XLA builds OOM-killing the shared remote compile helper.
# Consecutive compile failures halve the effective worker width (floor 1,
# DSQL_COMPILE_BACKOFF_AFTER failures per halving, counter
# ``compile_backoffs``) so warmup degrades to narrower concurrency instead
# of erroring; any successful compile restores the full width.
# ---------------------------------------------------------------------------

_compile_fail_streak = 0


def _backoff_after() -> int:
    try:
        return max(1, int(os.environ.get("DSQL_COMPILE_BACKOFF_AFTER", "2")))
    except ValueError:
        return 2


def _note_compile_result(ok: bool) -> None:
    global _compile_fail_streak
    after = _backoff_after()
    with _state_lock:
        if ok:
            _compile_fail_streak = 0
            return
        _compile_fail_streak += 1
        crossed = _compile_fail_streak % after == 0
    if crossed:
        _tel.inc("compile_backoffs")
        logger.warning(
            "%d consecutive compile failures; halving effective compile "
            "workers (now %d)", _compile_fail_streak, _compile_workers())


def _flatten_tables(scans) -> List[jax.Array]:
    flat: List[jax.Array] = []
    for _, tbl, row_valid in scans:
        for c in tbl.columns:
            flat.append(c.data)
            if c.mask is not None:
                flat.append(c.mask)
        if row_valid is not None:
            flat.append(row_valid)
    return flat


def _param_args(params) -> List[jax.Array]:
    """Bound-argument vector for a parameterized plan: one dtype-stable 0-d
    device scalar per hoisted literal, in FINGERPRINT order (``params`` is
    the list ``_fp_plan`` accumulated while serializing the plan — the
    ``P{i}`` positions in the key and these argument positions can never
    disagree).  The dtype comes from the declared SQL type, not the python
    value, so ``x > 5`` and ``x > 5000000000`` with the same declared type
    share a program while different declared types never do."""
    from ..types import physical_dtype
    return [jnp.asarray(p.value, dtype=physical_dtype(p.stype))
            for p in params]


def _maybe_parameterize(plan: RelNode, count: bool = True):
    """Hoist literals into runtime arguments (plan/parameterize.py) unless
    the DSQL_PARAM_PLANS kill switch is off.  Idempotent — re-entries from
    the degradation ladder / background compiles hoist nothing and count
    nothing; probes pass ``count=False`` so a tier prediction never
    inflates the execution counters."""
    from ..plan.parameterize import param_plans_enabled, parameterize_plan
    if not param_plans_enabled():
        return plan
    new, hoisted = parameterize_plan(plan)
    if hoisted and count:
        _tel.inc("param_plans")
        _tel.inc("param_literals_hoisted", hoisted)
    return new


def _build(plan: RelNode, context, scans, caps: Dict[str, int], key,
           origin=None, params=None):
    """Create the jitted program for this plan + input spec."""
    spec = []
    for skey, tbl, row_valid in scans:
        spec.append((skey, [(c.stype, c.mask is not None, c.dictionary)
                            for c in tbl.columns], tbl.names,
                     row_valid is not None))
    meta: dict = {}

    def fn(*flat):
        i = 0
        tables: Dict[tuple, Tuple[Table, Optional[jax.Array]]] = {}
        for skey, colspec, names, has_valid in spec:
            cols = []
            for stype, has_mask, dictionary in colspec:
                data = flat[i]; i2 = i + 1
                mask = flat[i2] if has_mask else None
                i = i2 + 1 if has_mask else i2
                cols.append(Column(data, stype, mask, dictionary))
            valid = None
            if has_valid:
                valid = flat[i]; i += 1
            tables[skey] = (Table(names, cols), valid)
        from ..ops.pallas_kernels import _strategy_on_tpu as _on_tpu
        tr = _Tracer(context, tables, caps)
        if params:
            # trailing args are the hoisted-literal scalars, in the same
            # order _fp_plan collected them; the rex evaluator resolves
            # each RexParam node to ITS traced scalar by node identity
            base = len(flat) - len(params)
            tr.param_values = {id(p): flat[base + j]
                               for j, p in enumerate(params)}
        if _on_tpu() and os.environ.get("DSQL_COMPACT", "1") != "0":
            # TPU only: off-TPU the hash kernels already cost O(valid rows)
            # and gathers/scatters are ~1 ms — compaction buys nothing there
            tr.compact_ok = _compact_eligible(plan)
        out = tr.run(plan)
        n = out.n
        if out.valid is None:
            count = jnp.int64(n)
        else:
            count = jnp.sum(out.valid.astype(jnp.int64))
        fb = jnp.zeros((), dtype=bool)
        for f in tr.fallback:
            fb = fb | f
        flags = jnp.stack([fb.astype(jnp.int64), count]
                          + [g.astype(jnp.int64) for g in tr.ngroups])
        meta["names"] = list(out.table.names)
        meta["cols"] = [(c.stype, c.mask is not None, c.dictionary)
                        for c in out.table.columns]
        meta["has_valid"] = out.valid is not None
        meta["ngroup_caps"] = list(tr.ngroup_caps)
        meta["agg_sites"] = list(tr.agg_sites)
        meta["n_out"] = n
        outs: List[jax.Array] = [flags]
        for c in out.table.columns:
            outs.append(c.data)
            if c.mask is not None:
                outs.append(c.mask)
        if out.valid is not None:
            outs.append(out.valid)
        return tuple(outs)

    return _Compiled(jax.jit(fn), spec, meta, dict(caps), key, origin)


class _NeedsRecompile(Exception):
    def __init__(self, caps):
        self.caps = caps


def _degrade_compile(plan: RelNode, context, base_key, key, exc: Exception,
                     err, split_limit: Optional[int]) -> Optional[Table]:
    """One rung down the declared ladder (resilience.LADDER) after a
    compile failure exhausted its in-rung retries.

    whole → stages: a plan with >1 heavy node re-runs as minimal bounded
    stages — the production crash pattern (remote helper SIGSEGV on fused
    sort-pipelines) indicts the oversized PROGRAM, not the plan.  On TPU
    the verdict persists ("__split__" in the learned caps) so later
    processes never re-crash the compiler.

    stages / unsplittable → eager: the interpreted executor answers
    (``None`` tells the caller to run it); with ``DSQL_EAGER_FALLBACK=0``
    the TYPED error surfaces instead — over a tunneled TPU the eager path
    is thousands of ~100 ms round trips, and failing fast beats wedging a
    benchmark behind one broken program.

    A FATAL (non-transient) verdict additionally exiles the program
    (_UNSUPPORTED) so steady state never re-pays a doomed compile; a
    transient failure leaves the cache slot empty — the next call gets a
    fresh attempt, because transient means exactly that.
    """
    from ..ops.pallas_kernels import _strategy_on_tpu as _on_tpu
    _tel.inc("degradations")
    if split_limit is None and _heavy_count(plan) > 1:
        _tel.inc("split_hints")
        _tel.annotate(degraded_to="stages")
        if _on_tpu():
            _learned_caps_put(base_key, {**_learned_caps_get(base_key),
                                         "__split__": 1})
        logger.warning(
            "program compile failed (%s); degrading to bounded stages",
            type(exc).__name__)
        return try_execute_compiled(plan, context, _split_limit=1)
    _tel.annotate(degraded_to="eager")
    if not isinstance(err, _res.TransientError):
        with _state_lock:
            _cache[key] = _UNSUPPORTED
        _tel.inc("exiled")
        # cross-process exile (runtime/quarantine.py): the FATAL verdict
        # persists keyed by plan + input layout + device fingerprint, so a
        # restarted process serves this plan eager WITHOUT re-paying the
        # doomed compile; expiry + half-open probes un-quarantine a fixed
        # engine eventually
        _quar.get_store().mark(_quar.program_key(base_key), "fatal",
                               reason=str(err)[:200])
    if os.environ.get("DSQL_EAGER_FALLBACK", "1") == "0":
        raise err if err is exc else err from exc
    logger.warning("compiled path failed for this plan (%s); using eager "
                   "executor", str(err)[:200])
    return None


SMALL_FETCH_BYTES = 8 << 20


def _compact_eligible(plan: RelNode) -> set:
    """ids of LogicalFilter nodes worth compacting: the TOPMOST filter of
    each filter chain with a SORT-SHAPED ancestor above — a join, window,
    or grouped aggregate, whose in-program sorts shrink with the row
    count.  A global aggregate is masked reductions only: compacting under
    it is pure gather overhead (TPC-H Q6 measured 0.15 s -> 0.61 s)."""
    out: set = set()

    def walk(rel: RelNode, sorty_above: bool, parent_is_filter: bool):
        is_filter = isinstance(rel, LogicalFilter)
        if is_filter and sorty_above and not parent_is_filter:
            out.add(id(rel))
        # global DISTINCT aggregates (except MIN/MAX, which are
        # dedup-invariant and skip _distinct_keep) still sort in-program
        # on TPU (_traced_factorize -> _group_sorted_codes), so they count
        sorty = sorty_above \
            or isinstance(rel, (LogicalJoin, LogicalWindow, LogicalSort)) \
            or (isinstance(rel, LogicalAggregate)
                and (rel.group_keys
                     or any(a.distinct and a.op not in ("MIN", "MAX")
                            for a in rel.aggs)))
        for i in rel.inputs:
            walk(i, sorty, is_filter)

    walk(plan, False, False)
    return out


def _check_flags(entry: _Compiled, flags) -> None:
    """Raise _NeedsRecompile on group-cap overflow; flags[0] => eager.
    Compaction sites (tag cmp*) additionally SHRINK: a cap far above the
    observed count recompiles once to a tight one (persisted, so future
    processes trace tight directly)."""
    meta = entry.meta
    ngroups = flags[2:]
    new_caps = dict(entry.caps)
    grew = False
    for i, (ng, cap) in enumerate(zip(ngroups, meta["ngroup_caps"])):
        n_rows, hashed, tag = meta["agg_sites"][i]
        if ng > cap:
            if hashed and int(ng) > n_rows:
                # ng = n+1 is the hashed path's SATURATED sentinel: the true
                # group count is unknowable from this run.  Jump hard (x16,
                # bounded by the input row count) instead of climbing a
                # doubling ladder — but not straight to n_rows: a tight cap
                # matters more at steady state (group outputs are cap-padded
                # downstream) than one extra recompile does at warmup.
                need = min(1 << (int(n_rows) - 1).bit_length(), cap * 16)
            else:
                need = 1 << (int(ng) - 1).bit_length()
            new_caps[tag] = max(need, cap * 2)
            grew = True
        elif tag.startswith("cmp"):
            tight = 1 << max(int(max(int(ng), 1) - 1).bit_length(), 10)
            if tight * 8 <= cap:
                # one recompile to the tight cap: every downstream sort in
                # the steady-state program shrinks by >= 8x
                new_caps[tag] = max(tight * 2, 1024)
                grew = True
    if grew:
        raise _NeedsRecompile(new_caps)


def _materialize(entry: _Compiled, outs) -> Table:
    _faults.maybe_fail("materialize")
    meta = entry.meta
    total_bytes = sum(int(getattr(o, "nbytes", 0)) for o in outs)
    if total_bytes <= SMALL_FETCH_BYTES:
        # small result: ONE blocking transfer for flags + all outputs, then
        # compact on host — over a remote TPU each extra sync is a full
        # tunnel round trip, so two-phase (flags, then data) costs double
        host = jax.device_get(list(outs))
        flags = host[0]
        if flags[0]:
            _tel.inc("fallbacks")
            return None
        _check_flags(entry, flags)
        count = int(flags[1])
        sel = None
        if meta["has_valid"]:
            valid = host[-1]
            if count < meta["n_out"]:
                sel = np.nonzero(valid)[0]
        idx = 1
        cols: List[Column] = []
        for stype, has_mask, dictionary in meta["cols"]:
            dev_data, data = outs[idx], host[idx]; idx += 1
            dev_mask = mask = None
            if has_mask:
                dev_mask, mask = outs[idx], host[idx]; idx += 1
            if sel is not None:
                # compaction changes the rows: host slices are authoritative
                # and the device copy is rebuilt lazily on upload
                data = data[sel]
                mask = mask[sel] if mask is not None else None
                dev_data = jnp.asarray(data)
                dev_mask = None if mask is None else jnp.asarray(mask)
            cols.append(Column(dev_data, stype, dev_mask, dictionary,
                               host_cache=(data, mask)))
        return Table(meta["names"], cols)

    flags = np.asarray(outs[0])
    if flags[0]:
        _tel.inc("fallbacks")
        return None
    _check_flags(entry, flags)
    count = int(flags[1])
    idx = 1
    cols: List[Column] = []
    for stype, has_mask, dictionary in meta["cols"]:
        data = outs[idx]; idx += 1
        mask = None
        if has_mask:
            mask = outs[idx]; idx += 1
        cols.append(Column(data, stype, mask, dictionary))
    valid = outs[idx] if meta["has_valid"] else None
    t = Table(meta["names"], cols)
    if valid is not None and count < meta["n_out"]:
        rows = jnp.nonzero(valid, size=count)[0]
        t = t.take(rows)
    return t


# ---------------------------------------------------------------------------
# stage-graph execution: XLA:TPU compile time grows superlinearly with the
# number of fused join/aggregate pipelines in one program — TPC-H Q2 (9
# heavy nodes after decorrelation) never finished compiling over the
# tunneled TPU (>27 min observed), while 2-join programs compile in tens of
# seconds.  Plans above the heavy-node budget (physical/stages.py,
# DSQL_STAGE_HEAVY / legacy DSQL_SPLIT_HEAVY) are partitioned into a DAG of
# bounded stages; every stage is traced and jitted as its own program with
# the stage output materialized into a padded power-of-2 capacity-class
# temp table (so the consumer's program key is stable across runs).  Stages
# keep the ordinary (plan fingerprint, input layout) program-cache key:
# structurally shared pipelines across queries — TPC-H's repeated
# lineitem/orders scan→filter→join prefixes — compile once and hit from
# then on (stats["cross_query_hits"]).  Independent stages execute
# concurrently in a small worker pool: XLA compilation releases the GIL, so
# a cold warmup becomes overlapped small compiles instead of one serial
# monolith.
# ---------------------------------------------------------------------------

_SPLIT_SCHEMA = "__split__"

_split_lock = _threading.Lock()
_split_refs: Dict[tuple, int] = {}
_state_lock = _threading.RLock()          # program cache + learned state
_inflight: Dict[tuple, object] = {}       # key -> Event: dedupe concurrent compiles


def _rex_scan_uids(rex, context) -> list:
    from ..plan.nodes import RexCall as _RC
    from ..plan.nodes import RexScalarSubquery as _RS
    if isinstance(rex, _RS):
        return _scan_uids(rex.plan, context)
    if isinstance(rex, _RC):
        return [u for o in rex.operands for u in _rex_scan_uids(o, context)]
    return []


def _scan_uids(rel: RelNode, context) -> list:
    """uids of every table a subtree scans (scalar-subquery plans included:
    they live in rex trees, not inputs, and their scans must contribute or
    the data-mutation race the stage digest closes reopens)."""
    if isinstance(rel, LogicalTableScan):
        if rel.schema_name in (_SPLIT_SCHEMA, "__spmd__"):
            # a boundary scan's NAME is already a content digest of its
            # producing subtree (scan uids folded in transitively) — and the
            # temp table may not be registered yet at partition time
            return [rel.table_name]
        entry = context.schema.get(rel.schema_name)
        tbl = (entry.tables[rel.table_name].table
               if entry is not None and rel.table_name in entry.tables
               else None)
        return [str(getattr(tbl, "uid", "?"))]
    out = [u for i in rel.inputs for u in _scan_uids(i, context)]
    from ..plan.nodes import (LogicalFilter as _LF, LogicalJoin as _LJ,
                              LogicalProject as _LP)
    if isinstance(rel, _LP):
        for e in rel.exprs:
            out.extend(_rex_scan_uids(e, context))
    elif isinstance(rel, _LF):
        out.extend(_rex_scan_uids(rel.condition, context))
    elif isinstance(rel, _LJ) and rel.condition is not None:
        out.extend(_rex_scan_uids(rel.condition, context))
    return out


def _stage_table_name(node: RelNode, context) -> str:
    """DETERMINISTIC temp-table name from the subtree's shape PLUS the
    scanned tables' uids: the name feeds the CONSUMER program's plan
    fingerprint, so a per-execution counter would recompile the consumer on
    every run (and leak dead cache entries) — but shape alone is not
    enough, since catalog data can mutate (INSERT / re-register) between
    two concurrent executions sharing a context.  With uids folded in,
    identical digests imply identical subplans over identical table
    OBJECTS, so a concurrent overwrite writes equal content and is
    harmless.  Across queries the digest is what makes shared subplans
    collide into ONE boundary name — the consumer-side half of cross-query
    stage reuse (and the key of the subplan result cache).

    The shape text is ``result_cache.canonical_plan``, not ``explain()``:
    the plan renderer elides VALUES row contents and scalar-subquery
    bodies, so two DIFFERENT subplans could share an explain() digest —
    unacceptable for a content address results are replayed from."""
    shape, _, _ = _rcache.canonical_plan(node, context)
    digest = hashlib.blake2s(
        (shape + "|"
         + ",".join(f.stype.name for f in node.schema) + "|"
         + ",".join(_scan_uids(node, context))).encode()
    ).hexdigest()[:16]
    return f"t{digest}"


def _make_boundary_scan(node: RelNode, context) -> LogicalTableScan:
    from ..plan.nodes import Field
    return LogicalTableScan(
        schema_name=_SPLIT_SCHEMA,
        table_name=_stage_table_name(node, context),
        schema=[Field(f"c{i}", f.stype)
                for i, f in enumerate(node.schema)])


def _partition_plan(plan: RelNode, budget: int, context) -> StageGraph:
    graph = _partition(plan, budget,
                       lambda sub: _make_boundary_scan(sub, context))
    _annotate_stage_stats(graph, context)
    return graph


def _pad_capacity(table: Table):
    """(padded table, row_valid): pad to a power-of-2 capacity with row
    validity.  Consumer programs are keyed on input SHAPES and a stage's
    true row count is data-dependent — capacity classes keep the key stable
    across runs, so reloading fresh data through the same stage never
    recompiles the consumer."""
    n = table.num_rows
    cap = 1 << max((max(n, 1) - 1).bit_length(), 6)
    table = table.with_names([f"c{i}" for i in range(table.num_columns)])
    if cap != n:
        pad = cap - n
        pcols = []
        for c in table.columns:
            data = jnp.concatenate(
                [c.data, jnp.zeros((pad,) + c.data.shape[1:],
                                   dtype=c.data.dtype)])
            mask = (None if c.mask is None else
                    jnp.concatenate([c.mask, jnp.zeros(pad, dtype=bool)]))
            pcols.append(Column(data, c.stype, mask, c.dictionary))
        table = Table(list(table.names), pcols)
    return table, jnp.arange(cap) < n


def _register_stage_table(context, name: str, table: Table) -> None:
    """Publish a stage output under __split__ (refcounted: concurrent
    queries on one context may share a boundary name; the digest guarantees
    equal content, so the overwrite is harmless)."""
    from ..datacontainer import TableEntry
    padded, row_valid = _pad_capacity(table)
    ref_key = (id(context), name)
    with _split_lock:
        if _SPLIT_SCHEMA not in context.schema:
            context.create_schema(_SPLIT_SCHEMA)
        context.schema[_SPLIT_SCHEMA].tables[name] = TableEntry(
            table=padded, row_valid=row_valid)
        _split_refs[ref_key] = _split_refs.get(ref_key, 0) + 1


def _unregister_stage_table(context, name: str) -> None:
    ref_key = (id(context), name)
    with _split_lock:
        refs = _split_refs.get(ref_key, 0) - 1
        if refs > 0:
            _split_refs[ref_key] = refs
            return
        _split_refs.pop(ref_key, None)
        sch = context.schema.get(_SPLIT_SCHEMA)
        if sch is not None:
            sch.tables.pop(name, None)


def _compile_workers(n_stages: Optional[int] = None) -> int:
    """Effective compile-pool width: the DSQL_COMPILE_WORKERS budget,
    halved once per DSQL_COMPILE_BACKOFF_AFTER consecutive compile
    failures (see _note_compile_result), capped by the stage count."""
    try:
        w = int(os.environ.get("DSQL_COMPILE_WORKERS", "4"))
    except ValueError:
        w = 4
    with _state_lock:
        halvings = _compile_fail_streak // _backoff_after()
    if halvings:
        w = max(1, w >> min(halvings, 8))
    if n_stages is not None:
        w = min(w, n_stages)
    return max(1, w)


def _record_stage_stats(st, idx: int, out: Table, query_fp: str,
                        stage_rows: Dict[int, int], wall_ms: float) -> None:
    """One flight-recorder stats record per executed stage (callers gate
    on DSQL_HISTORY_FILE or DSQL_PROFILE — the fully-disabled path never
    reaches here; with only the profiler armed, the span annotations and
    the measured-side ledger fold still happen but nothing is journaled).

    The digest is the stage's boundary-table content digest
    (_stage_table_name) — the canonical stage fingerprint the EWMA history
    keys on; the root stage (no boundary) keys under the query fingerprint.
    Capacity is the padded power-of-2 class _pad_capacity would
    materialize, so measured rows vs capacity shows the padding waste."""
    try:
        from ..runtime import flight_recorder as _fr

        rows_out = int(out.num_rows)
        stage_rows[idx] = rows_out
        rows_in = sum(stage_rows.get(d, 0) for d in st.deps)
        nbytes = 0
        for c in out.columns:
            nbytes += int(getattr(c.data, "nbytes", 0))
            if getattr(c, "mask", None) is not None:
                nbytes += int(getattr(c.mask, "nbytes", 0))
        digest = (st.scan.table_name if st.scan is not None
                  else f"root:{query_fp}")
        capacity = 1 << max((max(rows_out, 1) - 1).bit_length(), 6)
        # device time, when DSQL_TIME_DEVICE split it out onto child spans
        device_ms = 0.0
        sp = _tel.current_span()
        if sp is not None:
            for s in sp.walk():
                device_ms += float(s.attrs.get("device_ms", 0.0) or 0.0)
        # the span carries the measurements too: record_query sums
        # stage_bytes into the query's measured working set at close
        _tel.annotate(stage_digest=digest, stage_rows_in=rows_in,
                      stage_rows_out=rows_out, stage_capacity=capacity,
                      stage_bytes=nbytes, stage_wall_ms=round(wall_ms, 3))
        if _profile_on():
            # measured side of the model-vs-measured ledger: what the
            # stage actually touched, against the compile-time prediction
            from ..runtime import profiler as _prof
            _prof.record_measured(digest, nbytes=nbytes, wall_ms=wall_ms,
                                  device_ms=device_ms or None)
        if os.environ.get("DSQL_HISTORY_FILE"):
            _fr.record_stage(digest, rows_in=rows_in, rows_out=rows_out,
                             capacity=capacity, nbytes=nbytes,
                             wall_ms=wall_ms, device_ms=device_ms or None,
                             query_fp=query_fp)
        if _events_on():
            from ..runtime import events as _ev
            _ev.publish("stage.done", digest=digest, index=idx,
                        rows_out=rows_out, bytes=nbytes,
                        wall_ms=round(wall_ms, 3))
    except Exception:  # recording must never fail a stage
        _tel.inc("history_errors")
        logger.debug("stage stat capture failed", exc_info=True)


def _execute_stage_graph(graph: StageGraph, context, query_fp: str,
                         split_limit: Optional[int]) -> Optional[Table]:
    """Run a stage DAG: dependencies first, independent stages concurrently.

    Any stage that cannot run compiled (unsupported shape, runtime-flag
    fallback) fails the whole graph to the eager executor — partial staged
    execution would still pay the materialization round trips without the
    single-dispatch payoff.  Temp tables are unregistered on EVERY path,
    exceptions included.
    """
    with _tel.span("stage_graph", stages=len(graph.stages)):
        return _execute_stage_graph_inner(graph, context, query_fp,
                                          split_limit)


def _execute_stage_graph_inner(graph: StageGraph, context, query_fp: str,
                               split_limit: Optional[int]
                               ) -> Optional[Table]:
    _tel.inc("stage_graphs")
    stages = graph.stages
    nst = len(stages)
    root_idx = nst - 1
    registered: List[str] = []
    rt = _res.current()
    tel_trace = _tel.current_trace()
    tel_parent = _tel.current_span()
    # measured per-stage output rows (flight recorder only): a stage's
    # dependencies complete before it runs, so dependents read their
    # inputs' real row counts here.  Plain dict ops — GIL-atomic.
    stage_rows: Dict[int, int] = {}

    def run_stage_once(idx: int, attempt: int) -> Optional[Table]:
        _tel.inc("stage_execs")
        if attempt > 0:
            # the replay path is itself an injection site (checked FIRST,
            # so arming both sites sabotages the replay rather than just
            # re-firing the original), so CI can prove a sabotaged replay
            # still degrades cleanly
            _faults.maybe_fail("stage_replay")
        _faults.maybe_fail("stage_exec")
        st = stages[idx]
        # subplan result cache: a non-root stage's boundary name is a
        # content digest of its subtree (scan uids included), so an
        # OVERLAPPING query sharing the subplan replays the
        # materialized stage output and skips its device execution —
        # data reuse on top of the program reuse the stage cache gives
        skey = None
        cache = _rcache.get_cache()
        if st.scan is not None and cache.enabled():
            skey = _rcache.stage_key(st.scan.table_name)
            hit = cache.get(skey)
            if hit is not None:
                _tel.inc("result_cache_subplan_hits")
                _tel.annotate(subplan_cache="hit",
                              result_cache_tier=hit[1])
                return hit[0]
        out = _execute_single(st.plan, context, query_fp,
                              split_limit, in_stage=True)
        if skey is not None and out is not None:
            cache.put(skey, out)
        return out

    def run_stage(idx: int) -> Optional[Table]:
        # worker threads re-enter the query's supervision scope AND its
        # telemetry trace (thread locals do not cross pools).
        # Checkpointed stage replay: a transient failure re-executes ONLY
        # this stage — its dependencies' outputs are already materialized
        # as registered boundary temps, so the retry rescans them instead
        # of re-running the stages that produced them.  The failure
        # domain is one stage, not the graph (let alone the query).
        with _res.scoped(rt), _tel.scoped(tel_trace, tel_parent), \
                _tel.span("stage", index=idx):
            if stages[idx].est_rows is not None:
                _tel.annotate(stage_est_rows=stages[idx].est_rows)
            attempt = 0
            while True:
                _res.check("stage_exec")
                try:
                    t0s = time.perf_counter()
                    out = run_stage_once(idx, attempt)
                    if out is not None and (
                            os.environ.get("DSQL_HISTORY_FILE")
                            or _profile_on()):
                        _record_stage_stats(
                            stages[idx], idx, out, query_fp, stage_rows,
                            (time.perf_counter() - t0s) * 1e3)
                    return out
                except (KeyboardInterrupt, SystemExit):
                    raise
                except Exception as e:
                    err = _res.classify(e)
                    if err is None:
                        raise
                    if not isinstance(err, _res.TransientError):
                        raise err if err is e else err from e
                    attempt += 1
                    if attempt > _res.retry_max():
                        raise err if err is e else err from e
                    saved = len(registered)
                    _tel.inc("retries")
                    _tel.inc("stage_replays")
                    _tel.inc("stage_replay_saved_stages", saved)
                    _tel.annotate(stage_replays=attempt,
                                  stage_replay_saved=saved)
                    logger.warning(
                        "stage %d failed transiently (%s); replaying it "
                        "from %d materialized boundary stage(s) — retry "
                        "%d/%d", idx, str(err)[:200], saved, attempt,
                        _res.retry_max())
                    _res.backoff(attempt, "stage_exec")

    def stage_error(e: Exception) -> Optional[BaseException]:
        """None => degrade the whole graph to eager; else raise this.

        Only TRANSIENT failures degrade: a stage's own compile ladder
        already resolved everything recoverable inside _execute_single, so
        an exception escaping a stage is either a supervision verdict
        (deadline/cancel), a user error, or a broken invariant — all of
        which must surface typed, not silently re-run eager."""
        err = _res.classify(e)
        if err is None or not isinstance(err, _res.TransientError):
            return err if err is not None else e
        if os.environ.get("DSQL_EAGER_FALLBACK", "1") == "0":
            return err
        _tel.inc("degradations")
        _tel.annotate(degraded_to="eager")
        logger.warning("stage failed (%s); degrading graph to eager",
                       str(err)[:200])
        return None

    try:
        workers = _compile_workers(nst)
        if workers == 1:
            # serial: the list is already topological
            for idx, st in enumerate(stages):
                _res.check("stage_graph")
                try:
                    out = run_stage(idx)
                except (KeyboardInterrupt, SystemExit):
                    raise
                except (_res.DeadlineExceeded, _res.QueryCancelled):
                    raise
                except Exception as e:
                    raised = stage_error(e)
                    if raised is not None:
                        raise raised from (None if raised is e else e)
                    return None
                if out is None:
                    return None
                if idx == root_idx:
                    return out
                _register_stage_table(context, st.scan.table_name, out)
                registered.append(st.scan.table_name)
            return None  # unreachable: the root returns above

        from concurrent.futures import (FIRST_COMPLETED, ThreadPoolExecutor,
                                        wait as _fwait)
        pending = set(range(nst))
        done: set = set()
        futs: Dict[object, int] = {}
        failed = False
        aborted = False
        result: Optional[Table] = None
        pool = ThreadPoolExecutor(workers)
        try:
            while (pending or futs) and not failed:
                # cancellation/deadline must cut the GRAPH, not only the
                # stage bodies: abandon queued stages, orphan in-flight
                # compiles (the finally's shutdown(wait=False) leaves them
                # to finish in the background — their programs still land
                # in the cache for the next query)
                _res.check("stage_graph")
                for i in sorted(pending):
                    if all(d in done for d in stages[i].deps):
                        pending.discard(i)
                        futs[pool.submit(run_stage, i)] = i
                if not futs:
                    break
                # bounded wait so a cancel/deadline arriving mid-compile is
                # observed within ~100 ms instead of after the compile
                finished, _ = _fwait(list(futs), timeout=0.1,
                                     return_when=FIRST_COMPLETED)
                for f in finished:
                    i = futs.pop(f)
                    try:
                        out = f.result()
                    except (KeyboardInterrupt, SystemExit):
                        raise
                    except Exception as e:
                        raised = stage_error(e)
                        if raised is not None:
                            raise raised from (None if raised is e else e)
                        failed = True
                        continue
                    if out is None:
                        failed = True
                        continue
                    if i == root_idx:
                        result = out
                    else:
                        _register_stage_table(
                            context, stages[i].scan.table_name, out)
                        registered.append(stages[i].scan.table_name)
                    done.add(i)
        except BaseException:
            aborted = True
            raise
        finally:
            pool.shutdown(wait=not aborted, cancel_futures=aborted)
        return None if failed else result
    finally:
        for name in registered:
            _unregister_stage_table(context, name)


# ---------------------------------------------------------------------------
# tiered execution: first arrival must not pay the compile wall.  When a
# plan's stage programs are not yet available (in memory OR in the
# persistent program store), the query is answered IMMEDIATELY on the
# eager/interpreted tier (the RelExecutor machinery EXPLAIN ANALYZE uses)
# while the stage programs compile in background daemon threads bounded by
# the same DSQL_COMPILE_WORKERS width (and its failure backoff); the next
# arrival of the same plan shape runs compiled.  Flare's tiered
# native-compilation story (PAPERS.md).  The tier decision honors:
#   - the degradation ladder: DSQL_EAGER_FALLBACK=0 forbids the eager tier
#     entirely (there is no tier to serve from), so compiles stay
#     synchronous exactly as before;
#   - quarantine / exile / runtime verdicts: a plan with a standing
#     verdict is "decided" — it runs the normal path (which serves eager
#     with the proper counters) and never spawns background work;
#   - the workload manager: background compiles bypass admission entirely,
#     so they hold no scheduler slot and no memory-broker reservation.
# Disable with DSQL_TIERED=0 (tests pin this off; production default on).
# ---------------------------------------------------------------------------

_tier_lock = _threading.Lock()
_tier_done: "OrderedDict[tuple, bool]" = OrderedDict()  # attempted keys
_tier_inflight: set = set()
_tier_local = _threading.local()          # .bg guards recursion
_bg_sem: Optional[object] = None          # bounds concurrent bg compiles


def _tiering_enabled() -> bool:
    if os.environ.get("DSQL_TIERED", "1") == "0":
        return False
    # the eager tier IS the eager fallback; with it forbidden there is
    # nothing to serve the first arrival from
    if os.environ.get("DSQL_EAGER_FALLBACK", "1") == "0":
        return False
    return True


def _program_decided(base_key, scans) -> bool:
    """True when the normal path needs NO fresh XLA compile for this one
    program: an in-memory entry (or _UNSUPPORTED verdict), a runtime-eager
    exile, a standing quarantine verdict, or a persistent-store entry."""
    caps = _learned_caps_get(base_key)
    caps.pop("__split__", None)
    key = (base_key, tuple(sorted(caps.items())))
    runtime_key = (base_key, tuple(t.uid for _, t, _ in scans))
    with _state_lock:
        if key in _cache or runtime_key in _runtime_eager:
            return True
    qstore = _quar.get_store()
    if qstore.enabled() and _quar.program_key(base_key) in qstore.entries():
        # skip/half-open-probe semantics belong to the normal path
        return True
    return _pstore.get_store().contains(_pstore_digest(base_key))


def _probe_single(plan: RelNode, context, on_tpu: bool) -> bool:
    """Readiness of ONE program, keyed exactly as _execute_single will key
    it — including the off-TPU terminal-ORDER-BY peel (the host-sort
    program is compiled for ``plan.input``, not ``plan``)."""
    if not on_tpu and isinstance(plan, LogicalSort):
        plan = plan.input
    scans: list = []
    try:
        fp = _fp_plan(plan, context, scans)
    except Unsupported:
        return True  # needs no compile; the normal path serves it eager
    return _program_decided((fp, _fp_inputs(scans), on_tpu,
                             _mesh_signature(context)), scans)


def _programs_ready(plan: RelNode, context, base_key, budget: int) -> bool:
    """Would the normal compiled path answer without paying a fresh XLA
    compile?  Whole-plan programs are probed exactly; stage graphs are
    probed at their LEAF stages (deeper stages scan boundary temps that do
    not exist before execution) — with a warm store every stage hits, so
    all-leaves-warm is the right readiness signal."""
    on_tpu = base_key[2]
    heavy = _heavy_count(plan)
    if heavy <= budget:
        return _probe_single(plan, context, on_tpu)
    graph = _partition_plan(plan, budget, context)
    if len(graph.stages) <= 1:
        return _probe_single(plan, context, on_tpu)
    for st in graph.stages:
        if st.deps:
            continue
        if not _probe_single(st.plan, context, on_tpu):
            return False
    return True


def _background_compile(plan: RelNode, context, base_key,
                        trace_id: Optional[str] = None) -> None:
    """Compile (and once-execute) this plan's stage programs off the query
    path.  Runs in a daemon thread with fresh thread-locals: no deadline,
    no trace, no scheduler slot, no memory-broker reservation — exactly
    the full normal pipeline minus supervision, so learned caps, the
    program cache, quarantine interplay, and the persistent store all
    populate the same way a foreground compile would.  ``trace_id`` is the
    scheduling query's watchtower ID, captured at spawn time because a
    daemon thread's fresh thread-locals can't see the caller's trace."""
    _tier_local.bg = True
    trace = None
    try:
        with _bg_sem:
            # a daemon thread has fresh thread-locals: without its own
            # trace these compile spans ran OUTSIDE any QueryTrace and
            # never reached DSQL_CHROME_TRACE_DIR.  A dedicated
            # background_compile trace captures them; close_background_trace
            # exports it without counting a query or arming the slow log.
            trace = _tel.QueryTrace(f"<background-compile:{base_key[0][:48]}>")
            trace.root.name = "background_compile"
            if trace_id:
                trace.root.attrs["trace_id"] = trace_id
            try:
                with _tel.scoped(trace, trace.root):
                    try_execute_compiled(plan, context)
                _tel.inc("background_compiles_done")
                if _events_on():
                    from ..runtime import events as _ev
                    _ev.publish("compile.background.done", trace=trace_id,
                                plan=base_key[0][:48])
            except (KeyboardInterrupt, SystemExit):
                raise
            except BaseException as e:
                trace.root.attrs["error"] = type(e).__name__
                _tel.inc("background_compile_errors")
                if _events_on():
                    from ..runtime import events as _ev
                    _ev.publish("compile.background.error", trace=trace_id,
                                plan=base_key[0][:48],
                                error=type(e).__name__)
                logger.warning("background compile failed (%s: %s)",
                               type(e).__name__, str(e)[:200])
    finally:
        if trace is not None:
            try:
                _tel.close_background_trace(trace)
            except Exception:  # pragma: no cover - telemetry is advisory
                logger.debug("background trace close failed", exc_info=True)
        _tier_local.bg = False
        with _tier_lock:
            _tier_inflight.discard(base_key)
            _bounded_put(_tier_done, base_key, True)


def _tier_serve_eager(plan: RelNode, context, base_key, budget: int,
                      split_limit: Optional[int]) -> bool:
    """The tier decision: True => answer THIS arrival on the eager tier
    (the caller returns None) while the programs build in the background."""
    if split_limit is not None or not _tiering_enabled() \
            or getattr(_tier_local, "bg", False):
        return False
    global _bg_sem
    with _tier_lock:
        if base_key in _tier_done:
            return False  # background attempt finished; run the verdict
        if base_key in _tier_inflight:
            return True   # still compiling behind the scenes
    if _programs_ready(plan, context, base_key, budget):
        return False
    with _tier_lock:
        if base_key in _tier_done or base_key in _tier_inflight:
            return True
        _tier_inflight.add(base_key)
        if _bg_sem is None:
            _bg_sem = _threading.Semaphore(_compile_workers())
    # daemon threads (not a pool): process exit must never block on a
    # wedged XLA build, and the semaphore bounds real concurrency
    tid = None
    if _events_on():
        try:
            from ..runtime import events as _ev
            tid = _ev.current_trace_id()
        except Exception:
            tid = None
    _threading.Thread(target=_background_compile,
                      args=(plan, context, base_key, tid),
                      name="dsql-bg-compile", daemon=True).start()
    return True


def inflight_background_compiles() -> list:
    """Plan fingerprints currently compiling in background daemon threads
    (for ``system.active`` / ``/v1/engine``)."""
    with _tier_lock:
        return [k[0] for k in _tier_inflight]


def tier_probe(plan: RelNode, context) -> str:
    """Predict (without executing) which tier would answer this plan NOW:
    ``eager`` (not compilable / compile off), ``compiled`` (programs warm),
    ``eager-compiling`` (cold + tiering serves eager while building), or
    ``compiled-cold`` (tiering off: the arrival pays the compile)."""
    if os.environ.get("DSQL_COMPILE", "1") == "0":
        return "eager"
    from ..ops.pallas_kernels import _strategy_on_tpu as _on_tpu

    # the probe must key exactly as try_execute_compiled will: literals
    # hoist into params BEFORE fingerprinting (shape identity)
    plan = _maybe_parameterize(plan, count=False)
    scans: list = []
    try:
        plan_fp = _fp_plan(plan, context, scans)
    except Unsupported:
        return "eager"
    base_key = (plan_fp, _fp_inputs(scans), bool(_on_tpu()),
                    _mesh_signature(context))
    hint = _learned_caps_get(base_key).get("__split__")
    budget = stage_budget(int(hint) if hint is not None else None)
    try:
        if _programs_ready(plan, context, base_key, budget):
            return "compiled"
    except Exception:  # pragma: no cover - probe must never fail a query
        logger.debug("tier probe failed", exc_info=True)
        return "eager"
    with _tier_lock:
        inflight = base_key in _tier_inflight
    if inflight or _tiering_enabled():
        return "eager-compiling"
    return "compiled-cold"


def try_execute_compiled(plan: RelNode, context,
                         _split_limit: Optional[int] = None
                         ) -> Optional[Table]:
    """Execute via the compiled pipeline; None => caller should run eager.

    Plans within the heavy-node budget compile as ONE program (the common
    case).  Larger plans run as a stage graph of bounded programs —
    ``_split_limit`` overrides the budget (recursion from the degradation
    ladder's whole→stages rung and tests use it; cache keys line up with an
    explicit ``DSQL_STAGE_HEAVY`` run at the same value).
    """
    if os.environ.get("DSQL_COMPILE", "1") == "0":
        return None
    _res.check("compile_entry")
    from ..ops.pallas_kernels import _strategy_on_tpu as _on_tpu

    # parameterized plan identity: eligible literals hoist into runtime
    # arguments here, at the single entry of the compiled pipeline, so
    # every fingerprint below (whole-plan, stage subplans, program-store
    # digests, EWMA keys) sees the SHAPE while the values ride as trailing
    # jit args.  The eager/SPMD/result-cache paths never see this plan —
    # they key on values, which stays correct.
    plan = _maybe_parameterize(plan)
    scans: list = []
    try:
        plan_fp = _fp_plan(plan, context, scans)
    except Unsupported as e:
        logger.debug("not compilable: %s", e)
        _tel.inc("unsupported")
        return None
    base_key = (plan_fp, _fp_inputs(scans), bool(_on_tpu()),
                    _mesh_signature(context))

    budget_override = _split_limit
    heavy = _heavy_count(plan)
    if budget_override is None and heavy > 1:
        # learned budget hint: a plan whose whole program crashed the
        # remote TPU compiler (observed: helper SIGSEGV / silent loss on
        # TPC-H Q3's fused sort-pipeline) carries "__split__" in its
        # learned-caps entry, so every later process stages it immediately
        # instead of re-crashing the compiler
        hint = _learned_caps_get(base_key).get("__split__")
        if hint is not None:
            budget_override = int(hint)
    budget = stage_budget(budget_override)
    # tiered execution: a cold plan answers on the eager tier NOW while
    # its stage programs compile in the background; warm (or decided)
    # plans fall through to the normal compiled path
    if _tier_serve_eager(plan, context, base_key, budget, _split_limit):
        _tel.inc("served_eager_while_compiling")
        _tel.annotate(tier="eager-compiling")
        return None
    if heavy > budget:
        graph = _partition_plan(plan, budget, context)
        if len(graph.stages) > 1:
            return _execute_stage_graph(graph, context, plan_fp,
                                        _split_limit)
        # degenerate: nothing cuttable (one oversized node) — run whole
    return _execute_single(plan, context, plan_fp, _split_limit)


def _execute_single(plan: RelNode, context, query_fp: str,
                    split_limit: Optional[int] = None,
                    in_stage: bool = False) -> Optional[Table]:
    """Trace/compile/run ONE bounded program (a whole small plan or one
    stage of a graph); None => eager.  ``query_fp`` is the ROOT query's
    plan fingerprint — a cache hit whose entry was compiled under a
    different root is a cross-query stage reuse and is counted as such."""
    from ..ops.pallas_kernels import _strategy_on_tpu as _on_tpu

    scans: list = []
    params: list = []
    try:
        plan_fp = _fp_plan(plan, context, scans, params)
    except Unsupported as e:
        logger.debug("not compilable: %s", e)
        _tel.inc("unsupported")
        return None
    base_key = (plan_fp, _fp_inputs(scans), bool(_on_tpu()),
                    _mesh_signature(context))

    host_sort = None
    if not _on_tpu() and isinstance(plan, LogicalSort):
        # Terminal ORDER BY/LIMIT runs on the HOST off-TPU: the result is
        # fetched and compacted to its true row count by _materialize
        # anyway, and sorting those rows costs microseconds, while the
        # in-program device lexsort pays O(padded n) per collation key
        # (~8 ms per key per 100k padded rows on XLA:CPU — it dominated
        # Q2's profile).  On TPU the in-program sort stays: sorts are fast
        # there and everything before the single fetch should fuse.
        host_sort = plan
        plan = plan.input
        scans = []
        params = []
        try:
            plan_fp = _fp_plan(plan, context, scans, params)
        except Unsupported as e:
            logger.debug("not compilable: %s", e)
            _tel.inc("unsupported")
            return None
        # the backend joins the key: tracing picks backend-specific
        # strategies (merge vs gather join), and with content-based input
        # fingerprints a program — or an _UNSUPPORTED verdict — traced for
        # one backend could otherwise replay on another
        base_key = (plan_fp, _fp_inputs(scans), bool(_on_tpu()),
                    _mesh_signature(context))
    # runtime verdicts (non-unique build keys, hash collisions) depend on
    # NUMERIC data the layout fingerprint cannot see, so they are pinned to
    # the exact Tables via uid — a reload with corrected data must get a
    # fresh chance at the compiled path, not inherit the old dataset's exile
    runtime_key = (base_key, tuple(t.uid for _, t, _ in scans))
    with _state_lock:
        exiled_runtime = runtime_key in _runtime_eager
    if exiled_runtime:
        _tel.inc("fallbacks")
        return None
    caps: Dict[str, int] = _learned_caps_get(base_key)
    # "__split__" is the learned budget hint, not an aggregate-site cap: it
    # must not leak into the program cache key or _build's cap lookups
    caps.pop("__split__", None)
    # stats-derived starting caps for sites the engine has not yet LEARNED
    # (runtime/statistics.py): setdefault keeps learned/escalated caps
    # authoritative, and a too-small hint just trips the normal overflow
    # escalation below — never a wrong result
    from ..runtime import statistics as _stats
    hints = _stats.compiled_cap_hints(plan, context)
    for tag, cap in hints.items():
        if tag not in caps:
            caps[tag] = cap
            _tel.inc("stats_cap_hints")
            _tel.annotate(cap_hint=f"{tag}={cap}")
    store_tried = False  # one persistent-store attempt per call, tops
    for _ in range(8):  # capacity-escalation bound
        _res.check("execute")
        key = (base_key, tuple(sorted(caps.items())))
        my_event = None
        with _state_lock:
            entry = _cache.get(key)
            if entry is None:
                other = _inflight.get(key)
                if other is None:
                    my_event = _threading.Event()
                    _inflight[key] = my_event
        if entry is None and my_event is None:
            # another thread is compiling this exact program (concurrent
            # warmup of queries sharing a stage): wait for its verdict
            # instead of compiling a duplicate — but never past this
            # query's own deadline
            rem = None if _res.current() is None \
                else _res.current().remaining()
            other.wait(1800 if rem is None else max(min(rem, 1800), 1e-3))
            _res.check("compile_wait")
            with _state_lock:
                entry = _cache.get(key)
                if entry is None:
                    # builder failed transiently — take over the build
                    my_event = _threading.Event()
                    _inflight[key] = my_event
        if entry is _UNSUPPORTED:
            if my_event is not None:
                with _state_lock:
                    _inflight.pop(key, None)
                my_event.set()
            _tel.inc("unsupported")
            return None
        flat = _flatten_tables(scans)
        if params:
            # bound-argument vector: the hoisted literals, after the table
            # arrays — arity and treedef stay consistent everywhere flat
            # flows (jit call, AOT lower, store n_args, store replay)
            flat = flat + _param_args(params)
        outs = None
        if entry is None and not store_tried and _pstore.get_store().enabled():
            # persistent program store: a prior process compiled this exact
            # program (canonical plan + input layout + device + jax
            # version) — deserialize its XLA executable and run with ZERO
            # recompilation.  The stored caps supersede the local guess
            # (they were learned by actually running this program).
            store_tried = True
            with _tel.span("program_store_load"):
                got = _pstore_attempt(base_key, flat, query_fp)
            if got is not None:
                loaded, outs, caps = got
                if params:
                    # a stored program served this literal variant with
                    # zero compiles — the cross-process half of the
                    # one-program-per-shape guarantee
                    _tel.inc("param_plan_hits")
                if my_event is not None:
                    # release the in-flight claim taken under the caps we
                    # guessed before the load told us the real ones
                    with _state_lock:
                        _inflight.pop(key, None)
                    my_event.set()
                    my_event = None
                key = (base_key, tuple(sorted(caps.items())))
                loaded.key = key
                with _state_lock:
                    while len(_cache) >= _CACHE_LIMIT:
                        _cache.popitem(last=False)
                    _cache[key] = loaded
                entry = loaded
        if entry is None:
            degrade = None
            qstore = _quar.get_store()
            qkey = _quar.program_key(base_key)
            try:
                with _tel.span("compile"):
                    verdict = qstore.check(qkey) if qstore.enabled() else None
                    if verdict == "quarantined":
                        # cross-process exile: some process crashed or hung
                        # on this exact program (plan + layout + device) and
                        # the verdict is still live — serve eager with NO
                        # compile attempt (the finally releases the
                        # in-flight claim)
                        _tel.inc("quarantine_skips")
                        _tel.annotate(quarantined=True)
                        logger.warning(
                            "program is quarantined (crash/hang on a prior "
                            "process); skipping compile, serving eager")
                        return None
                    if verdict == "probe":
                        # half-open: this one caller re-attempts the compile
                        # while everyone else keeps skipping; success below
                        # lifts the verdict, failure re-arms it
                        _tel.inc("quarantine_probes")
                        _tel.annotate(quarantine_probe=True)
                    attempt = 0
                    while True:  # in-rung transient retries (resilience.LADDER)
                        try:
                            # the watchdog observes wall time from OUTSIDE
                            # the worker: a compile wedged inside XLA never
                            # reaches a cooperative check(), but its
                            # fingerprint still gets marked suspect (the
                            # injected compile fault stands in for such a
                            # stall, so it sits inside the watched section)
                            with _quar.get_watchdog().watch(
                                    qkey, label=plan_fp[:60]):
                                _faults.maybe_fail("compile")
                                entry = _build(plan, context, scans, caps,
                                               key, origin=query_fp,
                                               params=params)
                                if _pstore.get_store().enabled() \
                                        or _profile_on():
                                    # AOT lower+compile: same trace, same
                                    # XLA build, but the executable object
                                    # exists to serialize into the store —
                                    # and to read cost_analysis() from,
                                    # which is why the profiler forces it
                                    lowered = entry.fn.lower(*flat)
                                    entry.fn = lowered.compile()
                                    entry.aot = True
                                # first call traces+compiles (AOT: runs)
                                outs = entry.fn(*flat)
                            break
                        except Unsupported as e:
                            logger.debug("not compilable at trace time: %s", e)
                            with _state_lock:
                                _cache[key] = _UNSUPPORTED
                            _tel.inc("unsupported")
                            return None
                        except (KeyboardInterrupt, SystemExit):
                            raise
                        except Exception as e:
                            # trace-time concretization errors (host-bound
                            # kernels) and backend compile failures both land
                            # here, CLASSIFIED (runtime/resilience.py): a
                            # transient (tunnel drop, device OOM, injected
                            # fault) retries in-rung with backoff; anything
                            # else — and exhausted retries — walks the declared
                            # degradation ladder one rung down
                            err = _res.classify(e)
                            if err is None:
                                raise
                            if isinstance(err, (_res.DeadlineExceeded,
                                                _res.QueryCancelled)):
                                raise err if err is e else err from e
                            _tel.inc("compile_errors")
                            _note_compile_result(False)
                            attempt += 1
                            # retry annotation on the compile span itself:
                            # a report showing compile=120s attempts=3
                            # names its own bottleneck
                            _tel.annotate(attempts=attempt)
                            if (isinstance(err, _res.TransientError)
                                    and attempt <= _res.retry_max()):
                                _tel.inc("retries")
                                logger.warning(
                                    "transient compile failure (%s); retry "
                                    "%d/%d", str(err)[:200], attempt,
                                    _res.retry_max())
                                _res.backoff(attempt, "compile")
                                continue
                            # degrade OUTSIDE this try: the whole→stages rung
                            # re-enters try_execute_compiled, which must not
                            # find this key still in _inflight and wait on
                            # its own verdict
                            degrade = (e, err)
                            break
                if degrade is None:
                    _tel.inc("compiles")
                    _note_compile_result(True)
                    if params:
                        _tel.inc("param_plan_misses")
                    if in_stage:
                        _tel.inc("stage_compiles")
                    if qstore.enabled():
                        # a successful compile (half-open probe, or a
                        # watchdog trip that finished after all) lifts any
                        # surviving verdict — a fixed engine un-quarantines
                        # itself
                        qstore.clear(qkey)
                    with _state_lock:
                        while len(_cache) >= _CACHE_LIMIT:
                            _cache.popitem(last=False)
                        _cache[key] = entry
                    if _profile_on():
                        # compile-time XLA cost capture: predicted
                        # flops/bytes land on this span (EXPLAIN PROFILE
                        # reads them there) and in the profiler ledger
                        # under the ROOT query's fingerprint (the
                        # scheduler's cost_model rung reads it there)
                        try:
                            from ..runtime import profiler as _prof
                            cost = _prof.cost_summary(entry.fn)
                            if cost is not None:
                                _prof.record_program_cost(
                                    query_fp, _pstore_digest(base_key),
                                    cost)
                                _tel.annotate(cost_flops=cost["flops"],
                                              cost_bytes=cost["bytes"])
                        except Exception:
                            logger.debug("cost capture failed",
                                         exc_info=True)
                    # persist the executable so a FRESH process never
                    # re-pays this compile (best-effort; outside the
                    # watchdog — serialization cannot wedge XLA)
                    _pstore_put(entry, base_key, len(flat), len(outs))
            finally:
                if my_event is not None:
                    with _state_lock:
                        _inflight.pop(key, None)
                    my_event.set()
            if degrade is not None:
                return _degrade_compile(plan, context, base_key, key,
                                        degrade[0], degrade[1], split_limit)
        elif outs is None:  # in-memory hit (a store load already ran once)
            _tel.inc("hits")
            _tel.annotate(cache_hit=True)
            if params:
                _tel.inc("param_plan_hits")
            if in_stage:
                _tel.inc("stage_hits")
            if entry.origin is not None and entry.origin != query_fp:
                _tel.inc("cross_query_hits")
            if _profile_on():
                # warm path: replay the cost prediction captured at
                # compile/store time onto this execution's span, so a
                # profiled re-run (EXPLAIN PROFILE included) still shows
                # flops/bytes without recompiling
                try:
                    from ..runtime import profiler as _prof
                    c = (_prof.program_costs(query_fp)
                         .get(_pstore_digest(base_key)))
                    if c:
                        _tel.annotate(cost_flops=c.get("flops"),
                                      cost_bytes=c.get("bytes"))
                except Exception:
                    logger.debug("cost replay failed", exc_info=True)
            with _state_lock:
                _cache.move_to_end(key)
            if os.environ.get("DSQL_TIME_DEVICE"):
                # diagnostic split of exec wall: dispatch+device compute
                # (block_until_ready) vs host materialize/decode.  Costs
                # one extra device sync per call, so opt-in only.  The
                # scratchpad is THREAD-LOCAL (telemetry.exec_profile) and
                # the result lands on the query's own span — concurrent
                # server queries no longer clobber each other's split.
                t0 = time.perf_counter()
                outs = entry.fn(*flat)
                jax.block_until_ready(outs)
                t1 = time.perf_counter()
                prof = _tel.exec_profile()
                prof["device_ms"] = (t1 - t0) * 1e3
                prof["materialize_t0"] = t1
                _tel.annotate(device_ms=prof["device_ms"])
            else:
                outs = entry.fn(*flat)
        try:
            try:
                with _tel.span("materialize"):
                    result = _res.retry_transient(
                        lambda: _materialize(entry, outs),
                        site="materialize",
                        passthrough=(_NeedsRecompile,))
            finally:
                # pop the DSQL_TIME_DEVICE timestamp on EVERY path: a
                # _NeedsRecompile (or transfer failure) leaking it would
                # stamp a bogus materialize_ms onto a later untimed call
                prof = _tel.exec_profile()
                _mt0 = prof.pop("materialize_t0", None)
                if _mt0 is not None:
                    # the "materialize" span above already carries this
                    # wall; the scratchpad copy only serves the deprecated
                    # last_exec_profile read surface
                    prof["materialize_ms"] = \
                        (time.perf_counter() - _mt0) * 1e3
        except _NeedsRecompile as r:
            _tel.inc("recompiles")
            caps = r.caps
            _learned_caps_put(base_key, caps)
            continue
        except _res.TransientError as e:
            # host decode failed even after retries: one rung down — the
            # eager executor recomputes from the source tables
            _tel.inc("degradations")
            _tel.annotate(degraded_to="eager")
            if os.environ.get("DSQL_EAGER_FALLBACK", "1") == "0":
                raise
            logger.warning("materialize failed (%s); using eager executor",
                           str(e)[:200])
            return None
        if result is None:
            # runtime invariant failed (non-unique build / hash collision):
            # the verdict is stable for THESE tables (uid-keyed), so go
            # straight to eager on every future call against them
            with _state_lock:
                _bounded_put(_runtime_eager, runtime_key, True)
        elif host_sort is not None:
            from ..ops import sort as S
            if host_sort.collation:
                keys = [(c.index, c.ascending, c.effective_nulls_first)
                        for c in host_sort.collation]
                result = S.apply_sort(result, keys)
            result = S.apply_offset_limit(result, host_sort.offset,
                                          host_sort.limit)
        return result
    return None
