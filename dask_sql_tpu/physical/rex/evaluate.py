"""REX evaluator: bound expression tree -> Column/Scalar over a Table.

The reference dispatches expression nodes through a Pluggable registry
(/root/reference/dask_sql/physical/rex/convert.py:37-64) with plugins for
RexInputRef, RexLiteral and RexCall; this is the same shape with native rex
nodes.  New expression kinds register via ``RexExecutor.add_plugin``.
"""
from __future__ import annotations

from typing import Union

import numpy as np

from ...plan.nodes import (
    RexCall, RexInputRef, RexLiteral, RexNode, RexParam, RexScalarSubquery,
    RexUdf,
)
from ...table import Column, Scalar, Table
from ...utils import Pluggable
from .cast import cast_value
from .ops import OPERATION_MAPPING


class RexExecutor(Pluggable):
    """Dispatches on rex node class name — extension point for custom rex."""

    @classmethod
    def convert(cls, rex: RexNode, table: Table, executor) -> Union[Column, Scalar]:
        plugin = cls.get_plugin(type(rex).__name__)
        return plugin(rex, table, executor)


def _eval_input_ref(rex: RexInputRef, table: Table, executor):
    return table.columns[rex.index]


def _eval_literal(rex: RexLiteral, table: Table, executor):
    return Scalar(rex.value, rex.stype)


def _eval_param(rex: RexParam, table: Table, executor):
    """Hoisted literal (plan/parameterize.py).  Inside a compiled trace the
    value is the TRACED scalar the program received as a trailing jit
    argument (``compiled._build`` maps each param node to its arg); every
    other executor — eager, SPMD, stats probes — reads the node's carried
    value exactly like a literal, which is correct because those paths key
    their caches on values."""
    vals = getattr(executor, "param_values", None)
    if vals is not None:
        v = vals.get(id(rex))
        if v is not None:
            return Scalar(v, rex.stype)
    return Scalar(rex.value, rex.stype)


def _eval_call(rex: RexCall, table: Table, executor):
    if rex.op == "CAST":
        v = RexExecutor.convert(rex.operands[0], table, executor)
        return cast_value(v, rex.info, table.num_rows)
    args = [RexExecutor.convert(o, table, executor) for o in rex.operands]
    try:
        fn = OPERATION_MAPPING[rex.op]
    except KeyError:
        raise NotImplementedError(f"Operation {rex.op} not implemented") from None
    ctx = table
    return fn(args, rex.stype, ctx)


def _eval_scalar_subquery(rex: RexScalarSubquery, table: Table, executor):
    if getattr(executor, "is_tracer", False):
        # compiled mode: inline the subplan into the same trace; the result
        # broadcasts to a full-length column (NULL-ness must stay a traced
        # mask — Scalar's host-checked ``value is None`` can't carry it)
        return executor.traced_scalar_subquery(rex, table)
    sub = executor.execute(rex.plan)
    if sub.num_rows == 0:
        return Scalar(None, rex.stype)
    if sub.num_rows > 1:
        raise RuntimeError("Scalar subquery returned more than one row")
    col = sub.columns[0]
    vals = col.to_pylist()
    v = vals[0]
    if v is None or (isinstance(v, float) and np.isnan(v)):
        return Scalar(None, rex.stype)
    from ...types import python_value_to_physical
    return Scalar(python_value_to_physical(v, rex.stype), rex.stype)


def _eval_udf(rex: RexUdf, table: Table, executor):
    args = [RexExecutor.convert(o, table, executor) for o in rex.operands]
    n = table.num_rows
    # materialize host arrays; UDFs are arbitrary python (the reference ships
    # them to dask workers; here they run on host over gathered numpy data,
    # with jax-traceable UDFs free to return device arrays)
    host_args = []
    for a in args:
        if isinstance(a, Column):
            host_args.append(a.to_numpy())
        else:
            host_args.append(a.to_python())
    if rex.row_udf:
        import pandas as pd
        df = pd.DataFrame({f"a{i}": v for i, v in enumerate(host_args)})
        out = np.asarray([rex.func(row) for _, row in df.iterrows()])
    else:
        out = rex.func(*host_args)
    out = np.asarray(out)
    if np.isscalar(out) or out.ndim == 0:
        from ...types import python_value_to_physical
        return Scalar(python_value_to_physical(out.item(), rex.stype), rex.stype)
    col = Column.from_numpy(out)
    return cast_value(col, rex.stype, n)


RexExecutor.add_plugin("RexInputRef", _eval_input_ref)
RexExecutor.add_plugin("RexLiteral", _eval_literal)
RexExecutor.add_plugin("RexParam", _eval_param)
RexExecutor.add_plugin("RexCall", _eval_call)
RexExecutor.add_plugin("RexScalarSubquery", _eval_scalar_subquery)
RexExecutor.add_plugin("RexUdf", _eval_udf)


def evaluate_rex(rex: RexNode, table: Table, executor=None) -> Union[Column, Scalar]:
    return RexExecutor.convert(rex, table, executor)


def evaluate_predicate(rex: RexNode, table: Table, executor=None):
    """Evaluate a boolean rex to a row mask (NULL -> False, reference
    filter.py:29 fillna(False))."""
    import jax.numpy as jnp

    v = evaluate_rex(rex, table, executor)
    if isinstance(v, Scalar):
        return bool(v.value) if not v.is_null else False
    data = v.data.astype(bool)
    if v.mask is not None:
        data = data & v.mask
    return data
