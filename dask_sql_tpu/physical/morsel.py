"""Grace-hash partitioned joins: the morsel driver over the spill store.

physical/streaming.py lowers plans over ONE chunked table per split; a
join of TWO chunked tables (TPC-H Q3's orders ⋈ lineitem at SF10, both
bigger than HBM) had no strategy — ``StreamingUnsupported: a second
chunked table feeds the streamed subtree``.  This module adds the
classic grace-hash strategy on top of the spill store
(runtime/spill.py):

1. **Partition.**  Each side's subtree streams batch-by-batch exactly
   like a streaming split (same per-batch compiled program, same global
   dictionaries), but instead of accumulating partials the rows are
   hash-partitioned on the equi-join keys into P spill runs.
   ``partition_codes`` is the HOST analogue of parallel/exchange.py's
   partition-code convention — int64 codes, ``code in [0, P)`` routes a
   row to its partition, ``-1`` marks a dead slot (NULL equi-keys: an
   INNER equi-join can never match them, so they are dropped at the
   partitioner, mirroring the exchange's dead-slot handling).  The hash
   is streaming's ``_bucket_ids`` (dictionary CODES for strings — the
   chunked-source global-dictionary invariant makes equal values equal
   codes on both sides only when both sides scan the same dictionary;
   for cross-table joins the codes differ, so string keys hash their
   decoded VALUES instead).
2. **Join pairs.**  Equal keys land in the same partition index on both
   sides, so partition pair p⋈p is a complete sub-join.  Every pair
   loads to device padded to ONE shared capacity per side and runs
   under FIXED temp names (``grace_l``/``grace_r``, overwritten per
   pair like streaming's ``batch`` table) — one compile, P-1
   program-cache hits.  Pairs with an empty side are skipped entirely
   (the selective-filter win of grace hash).
3. **Output.**  Pair results append to an output spill run.  A small
   total materializes as a resident temp; a table-sized one re-enters
   the streaming pipeline as a ``SpillBackedSource`` chunked temp, so
   the GROUP BY above pipelines per-chunk partials through the
   partial/merge algebra and the full join result never materializes.

Skew: one shared pad capacity means a hot key inflates every pair.
Correctness is unaffected; the weakened device bound is reported loudly
(``morsel_skew_warnings``) — never silently (no-silent-caps policy).

Everything here is gated on ``DSQL_SPILL_MB > 0``: with spilling
disabled the streaming lowerer never dispatches to this module and the
pre-existing behavior (including its error messages) is byte-for-byte
unchanged.
"""
from __future__ import annotations

import logging
import os
from typing import List, Optional, Tuple

import numpy as np

from ..datacontainer import TableEntry
from ..io.chunked import ChunkedSource
from ..plan.nodes import (
    Field, LogicalFilter, LogicalJoin, LogicalProject, LogicalTableScan,
    RelNode, RexCall, RexInputRef,
)
from ..runtime import (faults as _faults, resilience as _res,
                       spill as _spill, telemetry as _tel)
from ..table import Column, Table
from . import streaming as _stream

logger = logging.getLogger(__name__)

#: fixed per-pair table names — overwritten each pair so every pair join
#: shares one plan fingerprint (fresh names would force P compiles)
GRACE_LEFT = "grace_l"
GRACE_RIGHT = "grace_r"

#: upper bound on partition count: P beyond this buys no memory headroom
#: (partitions only need to fit a batch) and costs per-pair overhead
MAX_PARTITIONS = max(int(os.environ.get("DSQL_GRACE_MAX_PARTITIONS",
                                        "256") or 256), 1)

#: a pair capacity beyond this multiple of batch_rows is reported as skew
SKEW_FACTOR = 4


# ---------------------------------------------------------------------------
# applicability
# ---------------------------------------------------------------------------

def equi_key_pairs(join: LogicalJoin) -> Optional[List[Tuple[int, int]]]:
    """``[(left_col, right_col), ...]`` for every top-level equality
    conjunct crossing the join boundary, or None when there is none to
    partition on.  Non-equi conjuncts are NOT rejected — the full
    original condition runs inside every pair join, so residuals stay
    exact; the equi subset only has to be non-empty."""
    if join.condition is None:
        return None
    nl = len(join.left.schema)
    pairs: List[Tuple[int, int]] = []

    def conjuncts(rex):
        if isinstance(rex, RexCall) and rex.op == "AND":
            for o in rex.operands:
                yield from conjuncts(o)
        else:
            yield rex

    for c in conjuncts(join.condition):
        if (isinstance(c, RexCall) and c.op == "=" and len(c.operands) == 2
                and all(isinstance(o, RexInputRef) for o in c.operands)):
            a, b = c.operands
            if a.index < nl <= b.index:
                pairs.append((a.index, b.index - nl))
            elif b.index < nl <= a.index:
                pairs.append((b.index, a.index - nl))
    return pairs or None


def _side_row_local(side: RelNode, context) -> bool:
    """True when the path from ``side`` down to its chunked scan passes
    only through nodes whose per-batch evaluation distributes over row
    unions — Project, Filter, and INNER joins whose other input is
    resident.  An Aggregate/Sort/Window/Union on the path makes
    batch-wise partitioning compute per-BATCH results (TPC-H Q17's
    AVG-per-partkey subquery would average each batch separately), so
    such sides must lower through the iterative one-subtree-at-a-time
    strategies first."""
    scans = _stream._chunked_scans(side, context)
    if len(scans) != 1:
        return False
    path = _stream._path_to(side, scans[0])
    if path is None:
        return False
    for node in path[:-1]:
        if isinstance(node, (LogicalProject, LogicalFilter)):
            continue
        if (isinstance(node, LogicalJoin) and node.join_type == "INNER"
                and not getattr(node, "null_aware", False)):
            continue
        return False
    return True


def grace_applicable(node: RelNode, context) -> bool:
    """True when ``node`` is an INNER equi-join with exactly one chunked
    scan on EACH side, both sides row-local above their scan, and
    spilling enabled — the shape the single-chunked streaming
    strategies cannot lower."""
    if not isinstance(node, LogicalJoin) or node.join_type != "INNER":
        return False
    if getattr(node, "null_aware", False):
        return False
    if not _spill.enabled():
        return False
    if not _side_row_local(node.left, context):
        return False
    if not _side_row_local(node.right, context):
        return False
    return equi_key_pairs(node) is not None


# ---------------------------------------------------------------------------
# host partitioning
# ---------------------------------------------------------------------------

_NAN_KEY_SALT = np.int64(-0x5851F42D4C957F2D)


def _canonical_int_keys(data: np.ndarray) -> np.ndarray:
    """Dtype-independent int64 image of a numeric key column: equal
    VALUES map to equal int64s whether the column arrived as int, bool,
    unsigned, or float (5 and 5.0 agree; -0.0 folds into +0.0; every NaN
    collapses to one salt)."""
    if data.dtype.kind != "f":
        return data.astype(np.int64, copy=False)
    d64 = data.astype(np.float64) + 0.0  # -0.0 -> +0.0
    isnan = np.isnan(d64)
    safe = np.where(isnan, 0.0, d64)
    integral = (np.isfinite(safe) & (np.floor(safe) == safe)
                & (np.abs(safe) < float(1 << 62)))
    as_int = np.clip(safe, -float(1 << 62), float(1 << 62)).astype(np.int64)
    canon = np.where(integral, as_int, safe.view(np.int64))
    return np.where(isnan, _NAN_KEY_SALT, canon)


def partition_codes(cols, keys: List[int], n_parts: int) -> np.ndarray:
    """Host analogue of parallel/exchange.py's partition codes: int64,
    ``code in [0, n_parts)`` routes the row, ``-1`` = dead slot (a NULL
    equi-key row — unmatched by any INNER equality, dropped here so it
    never costs spill bytes).  ``cols`` is the host-partial layout;
    string keys hash their decoded values (cross-table dictionaries
    need not agree), everything else hashes like ``_bucket_ids``."""
    total = len(cols[0][0]) if cols else 0
    hash_cols = list(cols)
    for k in keys:
        data, mask, stype, d = cols[k]
        if d is not None:
            # decode codes -> per-value stable hash: two tables' codes
            # for the same string differ, but the value hash does not
            vals = d[np.clip(data, 0, max(len(d) - 1, 0))]
            data = np.fromiter(
                (hash(v) & 0x7FFFFFFFFFFFFFFF for v in vals),
                count=len(vals), dtype=np.int64)
            d = None
        elif data.dtype.kind in "biuf":
            # _bucket_ids hashes floats by BIT PATTERN and ints by value;
            # a mixed-dtype equi-key (int okey joined to float okey) would
            # send 5 and 5.0 to different partitions and silently drop
            # their matches.  Worse, integral floats have all-zero low
            # mantissa bits, which collapses the FNV mix into a handful of
            # buckets.  Canonicalize every numeric key to a VALUE-equal
            # int64 — integral floats join the (well-mixed) integer
            # channel, non-integral floats keep their bit pattern, and
            # every NaN shares one salt (mask handles real NULLs).
            data = _canonical_int_keys(data)
        if mask is None:
            # _bucket_ids mixes mask PRESENCE into the hash; the two
            # sides must take the identical path or equal keys land in
            # different partitions — always hash with a mask
            mask = np.ones(len(data), dtype=bool)
        hash_cols[k] = (data, mask, stype, d)
    codes = _stream._bucket_ids(hash_cols, keys, n_parts) \
        if n_parts > 1 else np.zeros(total, dtype=np.int64)
    dead = None
    for k in keys:
        mask = cols[k][1]
        if mask is not None:
            dead = ~mask if dead is None else (dead | ~mask)
    if dead is not None:
        codes = np.where(dead, np.int64(-1), codes)
    return codes


def _partition_side(side: RelNode, scan: LogicalTableScan, source,
                    context, keys: List[int], P: int, runs: List[str],
                    store: "_spill.SpillStore"):
    """Stream one join side batch-by-batch and hash-partition its rows
    into the given spill runs.  Returns the host column layout
    ``(names, [(dtype, stype, dictionary), ...])`` for empty-partition
    reconstruction."""
    path = _stream._path_to(side, scan)
    below = _stream._stream_partial_plans(side, scan, path, context)
    layout = None
    for bi in range(source.n_batches):
        _res.check("grace_partition")
        with _tel.span("morsel_batch", index=bi):
            table, row_valid = _res.retry_transient(
                lambda: source.batch_table(bi), site="chunked_read")
            _tel.inc("stream_batches")
            _tel.inc("stream_batch_rows", table.num_rows)
            _stream._set_batch_entry(context, table, row_valid)
            result = _stream._run_resident(below, context)
            names, cols = _stream._host_partial(result)
            if layout is None:
                layout = (names, [(d.dtype, st, di)
                                  for d, _m, st, di in cols])
            codes = partition_codes(cols, keys, P)
            order = np.argsort(codes, kind="stable")
            bounds = np.searchsorted(codes[order], np.arange(P + 1))
            routed = 0
            for p in range(P):
                sel = order[bounds[p]:bounds[p + 1]]
                if not len(sel):
                    continue
                pcols = [(d[sel], None if m is None else m[sel], st, di)
                         for d, m, st, di in cols]
                store.put_host(runs[p], names, pcols)
                routed += len(sel)
            _tel.annotate(partial_rows=int(result.num_rows),
                          routed_rows=routed)
    if layout is None:  # a source with zero batches cannot occur via
        # from_pandas, but a defensive layout keeps the pair loop typed
        from ..types import physical_dtype
        layout = ([f.name for f in side.schema],
                  [(np.dtype(physical_dtype(f.stype)), f.stype,
                    np.array([""], dtype=object) if f.stype.is_string
                    else None) for f in side.schema])
    return layout


# ---------------------------------------------------------------------------
# pair materialization
# ---------------------------------------------------------------------------

def _set_grace_entry(context, name: str, run: Optional[str], layout,
                     cap: int, store: "_spill.SpillStore") -> int:
    """Materialize one partition (or a typed EMPTY side when run is
    None) as the fixed-name temp ``name``, padded to ``cap`` rows.
    Masks are ALWAYS synthesized and row_valid always passed so every
    pair shares one program fingerprint."""
    import jax.numpy as jnp

    _names, colmeta = layout
    if run is not None and store.has_run(run):
        chunks = [store.get_host_cols(run, i)
                  for i in range(store.n_chunks(run))]
        _cn, cols = _stream._concat_host(chunks)
    else:
        cols = [(np.zeros(0, dtype=dt), None, st, di)
                for dt, st, di in colmeta]
    n = len(cols[0][0]) if cols else 0
    pad = cap - n
    dev_cols = []
    for data, mask, stype, d in cols:
        if mask is None:
            mask = np.ones(n, dtype=bool)
        if pad:
            data = np.concatenate([data, np.zeros(pad, dtype=data.dtype)])
            mask = np.concatenate([mask, np.zeros(pad, dtype=bool)])
        dev_cols.append(Column(jnp.asarray(data), stype,
                               jnp.asarray(mask), d))
    table = Table([f"c{i}" for i in range(len(dev_cols))], dev_cols)
    row_valid = jnp.arange(cap) < n
    if _stream.STREAM_SCHEMA not in context.schema:
        context.create_schema(_stream.STREAM_SCHEMA)
    context.schema[_stream.STREAM_SCHEMA].tables[name] = TableEntry(
        table=table, row_valid=row_valid)
    return n


# ---------------------------------------------------------------------------
# the join output re-entering streaming
# ---------------------------------------------------------------------------

class SpillBackedSource(ChunkedSource):
    """A ChunkedSource whose batches live in a spill run: grace-hash
    join outputs re-enter the streaming pipeline as a chunked temp so
    the aggregate above streams per-chunk partials.  Chunks pad to one
    shared capacity with masks and row_valid ALWAYS present — uniform
    fingerprints across heterogeneous pair outputs mean one compile."""

    def __init__(self, store: "_spill.SpillStore", run: str, names,
                 stypes, dictionaries, n_rows: int, batch_rows: int):
        super().__init__(names, stypes, dictionaries, [], n_rows,
                         batch_rows)
        self._store = store
        self._run = run

    @property
    def n_batches(self) -> int:
        return self._store.n_chunks(self._run)

    def schema_table(self) -> Table:
        import jax.numpy as jnp

        from ..types import physical_dtype

        cols = []
        for ci, stype in enumerate(self.stypes):
            d = self.dictionaries[ci]
            if stype.is_string and d is None:
                d = np.array([""], dtype=object)
            cols.append(Column(jnp.zeros(1, dtype=physical_dtype(stype)),
                               stype, None, d))
        return Table(self.names, cols)

    def batch_table(self, i: int):
        import jax.numpy as jnp

        _faults.maybe_fail("chunked_read")
        _cnames, cols = self._store.get_host_cols(self._run, i)
        n = len(cols[0][0]) if cols else 0
        pad = self.batch_rows - n
        out_cols = []
        upload_bytes = 0
        for ci, (data, mask, _stype, d) in enumerate(cols):
            union = self.dictionaries[ci]
            if (union is not None and d is not None and d is not union
                    and not (len(d) == len(union) and (d == union).all())):
                # a pair result re-encoded its dictionary (eager-path
                # divergence): remap codes against the sorted union
                data = np.searchsorted(
                    union, d[np.clip(data, 0, len(d) - 1)]
                ).astype(np.int32)
            if mask is None:
                mask = np.ones(n, dtype=bool)
            if pad:
                data = np.concatenate([data,
                                       np.zeros(pad, dtype=data.dtype)])
                mask = np.concatenate([mask, np.zeros(pad, dtype=bool)])
            upload_bytes += int(data.nbytes) + int(mask.nbytes)
            out_cols.append(Column(jnp.asarray(data), self.stypes[ci],
                                   jnp.asarray(mask), union))
        row_valid = jnp.arange(self.batch_rows) < n
        _tel.annotate(upload_bytes=upload_bytes)
        return Table(self.names, out_cols), row_valid


def _union_dictionaries(store: "_spill.SpillStore", run: str,
                        n_chunks: int, n_cols: int) -> list:
    """Per-column dictionary for the output source: identical chunk
    dictionaries pass through; divergent ones union (sorted, so
    searchsorted remapping in batch_table stays valid)."""
    out = []
    for ci in range(n_cols):
        dicts = []
        for i in range(n_chunks):
            _n, _st, ds, _rows = store.chunk_meta(run, i)
            dicts.append(ds[ci])
        present = [d for d in dicts if d is not None]
        if not present:
            out.append(None)
            continue
        first = present[0]
        if all(d is first or (len(d) == len(first) and (d == first).all())
               for d in present):
            out.append(first)
        else:
            out.append(np.unique(
                np.concatenate([d.astype(object) for d in present])
            ).astype(object))
    return out


def _track_runs(context, runs: List[str]) -> None:
    lst = getattr(context, "_spill_runs", None)
    if lst is None:
        lst = context._spill_runs = []
    lst.extend(runs)


# ---------------------------------------------------------------------------
# the split
# ---------------------------------------------------------------------------

_grace_counter = [0]


def grace_join_split(join: LogicalJoin, context):
    """Lower one INNER join of two chunked sides via grace-hash
    partitioning; returns ``(join, replacement)`` for streaming's
    iterative rewrite loop."""
    store = _spill.get_store()
    _grace_counter[0] += 1
    tag = _grace_counter[0]

    lscan = _stream._chunked_scans(join.left, context)[0]
    rscan = _stream._chunked_scans(join.right, context)[0]
    lsrc = context.schema[lscan.schema_name].tables[lscan.table_name].chunked
    rsrc = context.schema[rscan.schema_name].tables[rscan.table_name].chunked
    pairs = equi_key_pairs(join)
    if pairs is None:  # grace_applicable guards this; belt and braces
        raise _stream.StreamingUnsupported(
            "join of two chunked tables has no equality key to "
            "partition on")
    lkeys = [p[0] for p in pairs]
    rkeys = [p[1] for p in pairs]

    # enough partitions that one partition ~ one batch of the larger side
    P = min(max(-(-int(lsrc.n_rows) // max(int(lsrc.batch_rows), 1)),
                -(-int(rsrc.n_rows) // max(int(rsrc.batch_rows), 1)),
                1), MAX_PARTITIONS)
    if os.environ.get("DSQL_AUTOPILOT", "0").strip() not in ("", "0"):
        # a skew-triggered autopilot hint re-partitions finer next run
        # (env checked before import; partition count never changes
        # results, only run sizes)
        from ..runtime import autopilot as _ap
        hp = _ap.current_hint("partitions")
        if hp:
            P = min(max(int(hp), 1), MAX_PARTITIONS)
    runs_l = [f"g{tag}:L{p}" for p in range(P)]
    runs_r = [f"g{tag}:R{p}" for p in range(P)]
    out_run = f"g{tag}:out"
    _track_runs(context, runs_l + runs_r + [out_run])

    with _tel.span("grace_join", partitions=P, spilled=True):
        _tel.inc("morsel_joins")
        if os.environ.get("DSQL_EVENTS", "0").strip() not in ("", "0"):
            try:
                from ..runtime import events as _ev
                _ev.publish("morsel.join", partitions=P)
            except Exception:  # pragma: no cover - bus is advisory
                pass
        llayout = _partition_side(join.left, lscan, lsrc, context, lkeys,
                                  P, runs_l, store)
        rlayout = _partition_side(join.right, rscan, rsrc, context, rkeys,
                                  P, runs_r, store)

        cap_l = max(max((store.run_rows(r) for r in runs_l), default=0), 1)
        cap_r = max(max((store.run_rows(r) for r in runs_r), default=0), 1)
        # partition skew ratio (max/mean over non-empty partitions), the
        # same attr the SPMD runner annotates — the query report and the
        # flight-recorder envelope surface one unified skew number
        sizes = [n for r in runs_l + runs_r
                 if (n := store.run_rows(r)) > 0]
        if sizes:
            _tel.annotate(skew_ratio=round(
                max(sizes) / (sum(sizes) / len(sizes)), 3))
        for cap, src in ((cap_l, lsrc), (cap_r, rsrc)):
            if cap > SKEW_FACTOR * max(int(src.batch_rows), 1):
                # a hot key concentrates rows in one partition; every
                # pair pads to it, weakening the device bound — loudly
                _tel.inc("morsel_skew_warnings")
                logger.warning(
                    "grace join: partition skew — largest partition %d "
                    "rows vs batch_rows %d; per-pair device working set "
                    "is ~%.1fx the configured bound", cap,
                    int(src.batch_rows),
                    cap / max(int(src.batch_rows), 1))

        lfields = [Field(f"c{i}", f.stype)
                   for i, f in enumerate(join.left.schema)]
        rfields = [Field(f"c{i}", f.stype)
                   for i, f in enumerate(join.right.schema)]
        pair_plan = LogicalJoin(
            left=LogicalTableScan(schema_name=_stream.STREAM_SCHEMA,
                                  table_name=GRACE_LEFT, schema=lfields),
            right=LogicalTableScan(schema_name=_stream.STREAM_SCHEMA,
                                   table_name=GRACE_RIGHT, schema=rfields),
            condition=join.condition, join_type="INNER",
            schema=list(join.schema))

        out_chunks = 0
        for p in range(P):
            _res.check("grace_pair")
            nl_rows = store.run_rows(runs_l[p])
            nr_rows = store.run_rows(runs_r[p])
            if nl_rows == 0 or nr_rows == 0:
                # an empty side means an empty pair join: skip the
                # device round trip entirely
                store.free_run(runs_l[p])
                store.free_run(runs_r[p])
                continue
            with _tel.span("grace_pair", index=p, left_rows=nl_rows,
                           right_rows=nr_rows):
                _set_grace_entry(context, GRACE_LEFT, runs_l[p],
                                 llayout, cap_l, store)
                _set_grace_entry(context, GRACE_RIGHT, runs_r[p],
                                 rlayout, cap_r, store)
                result = _stream._run_resident(pair_plan, context)
                _tel.inc("morsel_pairs")
                store.put_table(out_run, result)
                out_chunks += 1
            store.free_run(runs_l[p])
            store.free_run(runs_r[p])
        if out_chunks == 0:
            # no pair had rows on both sides — run ONE all-padded pair
            # so the output carries correctly-typed (empty) columns
            _set_grace_entry(context, GRACE_LEFT, None, llayout, cap_l,
                             store)
            _set_grace_entry(context, GRACE_RIGHT, None, rlayout, cap_r,
                             store)
            result = _stream._run_resident(pair_plan, context)
            _tel.inc("morsel_pairs")
            store.put_table(out_run, result)
            out_chunks = 1

        total_rows = store.run_rows(out_run)
        total_bytes = store.run_bytes(out_run)
        _tel.annotate(out_rows=total_rows, out_bytes=total_bytes)
        logger.debug("grace join: %d partitions -> %d output rows "
                     "(%d bytes, %d chunks)", P, total_rows, total_bytes,
                     out_chunks)

        if total_bytes <= _stream.PARTIAL_BYTES_BUDGET:
            partials = [store.get_host_cols(out_run, i)
                        for i in range(out_chunks)]
            names, cols = _stream._concat_host(partials)
            store.free_run(out_run)
            tmp = _stream._retype(
                _stream._host_cols_to_temp(names, cols, context),
                join.schema)
            return join, tmp

        # table-sized output: re-register as a chunked source (the
        # window-split pattern) so streaming keeps going above the join
        cap_out = max(max((store.chunk_meta(out_run, i)[3]
                           for i in range(out_chunks)), default=0), 1)
        dicts = _union_dictionaries(store, out_run, out_chunks,
                                    len(join.schema))
        src = SpillBackedSource(
            store, out_run, [f"c{i}" for i in range(len(join.schema))],
            [f.stype for f in join.schema], dicts, total_rows, cap_out)
        if _stream.STREAM_SCHEMA not in context.schema:
            context.create_schema(_stream.STREAM_SCHEMA)
        _stream._tmp_counter[0] += 1
        name = f"t{_stream._tmp_counter[0]}"
        context.schema[_stream.STREAM_SCHEMA].tables[name] = TableEntry(
            table=src.schema_table(), chunked=src)
        return join, LogicalTableScan(
            schema_name=_stream.STREAM_SCHEMA, table_name=name,
            schema=[Field(f"c{i}", f.stype)
                    for i, f in enumerate(join.schema)])
