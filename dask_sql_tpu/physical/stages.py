"""Stage-graph partitioning: bound the size of every compiled program.

XLA:TPU compile time grows superlinearly with the number of fused
join/aggregate pipelines in one program (physical/compiled.py module
docstring: ~50 s at 2 heavy nodes, ~400 s at 6, never-finishes at 8-9 over
the tunneled TPU).  This module partitions a logical plan into a DAG of
**stages**, each holding at most ``budget`` heavy nodes; the compiled
executor traces and jits every stage as its own program, materializing
stage outputs into padded capacity-class temp tables between them.

The partitioner is a pure bottom-up greedy walk and therefore
**deterministic** and **ancestor-independent**: the cuts made inside a
subtree depend only on that subtree, so two queries sharing a subplan
produce byte-identical stage plans for the shared part — their stage
programs share one cache entry (the cross-query reuse the compiled
executor's ``stats["cross_query_hits"]`` counter observes).

Heavy-node weights mirror the compile-cost model the old binary splitter
used: joins, grouped aggregates and windows weigh 1; a SEMI/ANTI join with
a non-equi residual lowers through the payload exist-test formulation and
weighs 2.  A single node can therefore exceed a budget of 1 — the bound
every program actually satisfies is ``max(budget, max node weight)``.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from ..plan.nodes import (LogicalAggregate, LogicalJoin, LogicalTableScan,
                          LogicalWindow, RelNode)

#: Heavy-node budget per compiled program.  The default sits at the measured
#: compile-time knee on the tunneled TPU (tens of seconds per program, never
#: minutes); override with ``DSQL_STAGE_HEAVY`` (or the legacy
#: ``DSQL_SPLIT_HEAVY``, kept for compatibility with existing bench configs
#: and learned "__split__" hints).
DEFAULT_STAGE_HEAVY = 6


def stage_budget(override: Optional[int] = None) -> int:
    """The heavy-node budget: explicit override > env knobs > default."""
    import os

    if override is not None:
        return max(1, int(override))
    for var in ("DSQL_STAGE_HEAVY", "DSQL_SPLIT_HEAVY"):
        v = os.environ.get(var)
        if v:
            return max(1, int(v))
    return DEFAULT_STAGE_HEAVY


def node_weight(rel: RelNode) -> int:
    """Compile-cost weight of ONE node (its subtree excluded)."""
    if isinstance(rel, LogicalJoin):
        # SEMI/ANTI with a non-equi residual lower through the payload
        # exist-test formulation whose compile cost dwarfs a plain
        # equi-join — TPC-H Q21 (two of them + two joins) SIGKILLs the
        # remote TPU compile helper as one program.  Plain equi SEMI/ANTI
        # (Q4/Q20) compile like ordinary joins and keep weight 1.  The
        # residual test is the SAME decomposition the lowering uses
        # (_extract_equi_keys), so heuristic and lowering cannot drift.
        if rel.join_type in ("SEMI", "ANTI") and rel.condition is not None:
            from .rel.executor import _extract_equi_keys
            _, residual = _extract_equi_keys(rel)
            if residual:
                return 2
        return 1
    if isinstance(rel, (LogicalAggregate, LogicalWindow)):
        return 1
    return 0


def heavy_count(rel: RelNode) -> int:
    """Total heavy weight of a subtree (the old compiled._heavy_count)."""
    return node_weight(rel) + sum(heavy_count(i) for i in rel.inputs)


@dataclass
class Stage:
    """One compiled program's plan plus its position in the DAG.

    ``plan`` is the stage subtree with deeper cuts replaced by boundary
    scans; ``scan`` is the boundary node CONSUMERS of this stage read
    through (None for the root stage, whose output is the query result);
    ``deps`` are indices into ``StageGraph.stages`` of the stages whose
    outputs this stage scans.

    The boundary scan's NAME is a content digest of the producing subtree
    (canonical shape + scanned-table uids, physical/compiled.py
    ``_stage_table_name``) and doubles as the stage output's **subplan
    result-cache key** (runtime/result_cache.py): equal names imply equal
    data, so an overlapping query sharing this subtree may replay the
    materialized output instead of re-executing the stage.
    """

    plan: RelNode
    deps: Tuple[int, ...]
    heavy: int
    scan: Optional[RelNode] = None
    #: statistics-estimated output rows (annotate_stats; None = unknown)
    est_rows: Optional[int] = None


@dataclass
class StageGraph:
    """Stages in topological order (every dep precedes its consumer);
    the last stage is the root and produces the query result."""

    stages: List[Stage]

    @property
    def root(self) -> Stage:
        return self.stages[-1]


def partition(plan: RelNode, budget: int,
              make_scan: Callable[[RelNode], RelNode]) -> StageGraph:
    """Cut ``plan`` into a StageGraph of stages of <= ``budget`` heavy nodes.

    ``make_scan(subtree)`` must return the boundary scan node consumers
    read the subtree's materialized output through (the compiled executor
    passes a ``__split__``-schema table scan named by a content digest of
    the subtree, which is what makes shared subtrees collide into shared
    stage programs across queries).

    Greedy bottom-up: children partition first; at each node, whole child
    subtrees are cut (largest heavy count first, index order on ties) until
    the enclosing count fits the budget.  Cuts never target weight-0
    subtrees — a pure scan/project chain compiles for free and cutting it
    would only pay a materialization round trip.
    """
    budget = max(1, int(budget))
    stages: List[Stage] = []
    scan_stage: Dict[int, int] = {}  # id(boundary scan node) -> stage index

    def stage_deps(rel: RelNode) -> Tuple[int, ...]:
        out: List[int] = []

        def w(r: RelNode) -> None:
            si = scan_stage.get(id(r))
            if si is not None:
                out.append(si)
                return  # a boundary scan is a leaf of THIS stage
            for i in r.inputs:
                w(i)

        w(rel)
        return tuple(dict.fromkeys(out))

    def cut(sub: RelNode, heavy: int) -> RelNode:
        scan = make_scan(sub)
        stages.append(Stage(plan=sub, deps=stage_deps(sub), heavy=heavy,
                            scan=scan))
        scan_stage[id(scan)] = len(stages) - 1
        return scan

    def walk(rel: RelNode) -> Tuple[RelNode, int]:
        kids = [walk(i) for i in rel.inputs]
        total = node_weight(rel) + sum(h for _, h in kids)
        if total > budget and kids:
            order = sorted(range(len(kids)), key=lambda j: (-kids[j][1], j))
            for j in order:
                if total <= budget:
                    break
                sub, h = kids[j]
                if h <= 0:
                    continue  # cutting free subtrees buys nothing
                kids[j] = (cut(sub, h), 0)
                total -= h
        if kids:
            rel = rel.with_inputs([k for k, _ in kids])
        return rel, total

    root_plan, root_heavy = walk(plan)
    stages.append(Stage(plan=root_plan, deps=stage_deps(root_plan),
                        heavy=root_heavy, scan=None))
    return StageGraph(stages)


def annotate_stats(graph: StageGraph, context) -> None:
    """Attach statistics-estimated output rows to every stage
    (runtime/statistics.py — filter selectivity from ingest min/max plus
    join/aggregate cardinality rules).  The estimate rides along to the
    stage spans and the flight recorder so padded-capacity waste
    (``stage_capacity`` vs ``stage_est_rows``) is visible before the
    first run ever measures it; unknown stays None and costs nothing.
    No-op when adaptive selection is off (DSQL_ADAPTIVE=0)."""
    from ..runtime import statistics as _stats

    if context is None or not _stats.adaptive_enabled():
        return
    for st in graph.stages:
        try:
            est = _stats.estimate_rows(st.plan, context)
        except Exception:
            est = None
        if est is not None:
            st.est_rows = int(est)
