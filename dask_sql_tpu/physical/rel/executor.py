"""Physical executor: logical plan -> device Table, via a plugin registry.

Mirrors the reference's RelConverter dispatch
(/root/reference/dask_sql/physical/rel/convert.py:35-58): each plan-node class
name maps to a plugin whose ``convert(node, executor)`` lowers it; users can
register new lowerings with ``RelExecutor.add_plugin`` without touching core
(the Pluggable contract, SURVEY §1).  Execution is eager per stage — the host
"driver" sequences compiled device kernels, mirroring the reference's
client/scheduler split with XLA in place of the dask task graph.
"""
from __future__ import annotations

import logging
from typing import List

import jax.numpy as jnp
import numpy as np

from ...ops import groupby as G
from ...ops import join as J
from ...ops import sort as S
from ...ops import window as W
from ...ops.kernels import mask_to_indices
from ...plan.nodes import (
    AggCall, LogicalAggregate, LogicalExcept, LogicalFilter, LogicalIntersect,
    LogicalJoin, LogicalProject, LogicalSample, LogicalSort, LogicalTableScan,
    LogicalUnion, LogicalValues, LogicalWindow, RelNode, RexCall, RexInputRef,
    RexLiteral,
)
from ...table import Column, Scalar, Table
from ...types import physical_dtype
from ...utils import Pluggable
from ..rex.evaluate import evaluate_predicate, evaluate_rex

logger = logging.getLogger(__name__)


class RelExecutor(Pluggable):
    """Plan-node class name -> physical plugin registry."""

    def __init__(self, context):
        self.context = context

    def execute(self, rel: RelNode) -> Table:
        # per-node deadline/cancel checkpoint: the eager path is the
        # ladder's last compute rung, and a query must not run past its
        # budget there either (runtime/resilience.py; no-op outside a scope)
        from ...runtime import resilience as _res, telemetry as _tel
        _res.check("eager")
        plugin = RelExecutor.get_plugin(type(rel).__name__)
        logger.debug("Executing %s", rel.node_name())
        rec = _tel.active_node_recorder()
        if rec is not None:
            # EXPLAIN ANALYZE instrumentation: per-node wall (inclusive of
            # children — the renderer derives self-time) + output rows
            import time as _time
            t0 = _time.perf_counter()
            result = plugin(rel, self)
            rec.add(rel, (_time.perf_counter() - t0) * 1e3,
                    int(getattr(result, "num_rows", 0) or 0))
            return result
        result = plugin(rel, self)
        return result


# ---------------------------------------------------------------------------
# core plugins
# ---------------------------------------------------------------------------

def _table_scan(rel: LogicalTableScan, ex: RelExecutor) -> Table:
    # catalog_entry (not a direct dict read): inside a snapshot pin
    # (runtime/ingest.py) this serves the entry captured at admission
    entry = ex.context.catalog_entry(rel.schema_name, rel.table_name)
    if entry.table is not None:
        t = entry.table
        if entry.row_valid is not None:
            # mesh-mode table: drop the divisibility padding rows (the
            # compiled executor consumes the mask directly instead)
            t = t.take(mask_to_indices(entry.row_valid))
    else:
        t = ex.execute(entry.plan)
    return t.limit_to([f.name for f in rel.schema]) if t.names != [f.name for f in rel.schema] else t


def _project(rel: LogicalProject, ex: RelExecutor) -> Table:
    src = ex.execute(rel.input)
    cols: List[Column] = []
    for rex, f in zip(rel.exprs, rel.schema):
        v = evaluate_rex(rex, src, ex)
        if isinstance(v, Scalar):
            v = Column.from_scalar(v, src.num_rows)
        cols.append(v)
    return Table([f.name for f in rel.schema], cols)


def _filter(rel: LogicalFilter, ex: RelExecutor) -> Table:
    src = ex.execute(rel.input)
    mask = evaluate_predicate(rel.condition, src, ex)
    if isinstance(mask, bool):
        # scalar condition shortcut (reference filter.py:14-31)
        return src if mask else src.slice(0, 0)
    return src.take(mask_to_indices(mask))


def _values(rel: LogicalValues, ex: RelExecutor) -> Table:
    ncols = len(rel.schema)
    cols = []
    for j, f in enumerate(rel.schema):
        vals = [row[j].value for row in rel.rows]
        mask = np.array([v is not None for v in vals])
        if f.stype.is_string:
            arr = np.array([v if v is not None else "" for v in vals], dtype=object)
            cols.append(Column._encode_strings(arr, mask if not mask.all() else None))
        else:
            arr = np.array([v if v is not None else 0 for v in vals])
            col = Column(jnp.asarray(arr.astype(physical_dtype(f.stype))), f.stype,
                         None if mask.all() else jnp.asarray(mask))
            cols.append(col)
    return Table([f.name for f in rel.schema], cols)


def _aggregate(rel: LogicalAggregate, ex: RelExecutor) -> Table:
    from ...runtime import statistics as _stats

    src = ex.execute(rel.input)
    n = src.num_rows
    key_cols = [src.columns[i] for i in rel.group_keys]

    if rel.group_keys:
        # stats-driven dispatch (runtime/statistics.py): the hash/sort
        # crossover plus the dense direct-index path; DSQL_ADAPTIVE=0 and
        # unknown stats both yield "hash" — the pre-stats factorize.
        variant, info = _stats.groupby_decision(rel, ex.context)
        hint = (info["lo"], info["hi"]) if "lo" in info else None
        codes, first, num_groups, used = G.group_codes(
            key_cols, variant=variant, dense_hint=hint)
        if used != "hash" or info:
            _stats.record_choice("groupby", used, **{
                k: v for k, v in info.items() if k not in ("lo", "hi")})
    else:
        codes, first, num_groups = None, None, 1

    out_cols: List[Column] = []
    out_names: List[str] = []

    # group key outputs: representative rows
    if rel.group_keys:
        rep = first
        for i, ki in enumerate(rel.group_keys):
            out_cols.append(src.columns[ki].take(rep))
            out_names.append(rel.schema[i].name)

    for j, agg in enumerate(rel.aggs):
        f = rel.schema[len(rel.group_keys) + j]
        col = src.columns[agg.args[0]] if agg.args else None
        filter_mask = None
        if agg.filter_arg is not None:
            fc = src.columns[agg.filter_arg]
            filter_mask = fc.data.astype(bool) & fc.valid_mask()

        if agg.udaf is not None:
            out_cols.append(_run_udaf(agg, col, codes, num_groups, filter_mask, src))
            out_names.append(f.name)
            continue

        if agg.distinct and col is not None:
            base_codes = codes if codes is not None else jnp.zeros(n, dtype=jnp.int64)
            rows = G.dedup_for_distinct_agg(base_codes, col, filter_mask)
            sub_col = col.take(rows)
            sub_codes = base_codes[rows] if codes is not None else None
            out_cols.append(G.segment_aggregate(
                agg.op, sub_col, sub_codes, num_groups, f.stype,
                None, int(rows.shape[0])))
        else:
            out_cols.append(G.segment_aggregate(
                agg.op, col, codes, num_groups, f.stype, filter_mask, n))
        out_names.append(f.name)

    if not rel.group_keys and not rel.aggs:
        return Table([], [])
    # DISTINCT (aggregate with no aggs): groups only
    return Table(out_names, out_cols)


def _run_udaf(agg: AggCall, col, codes, num_groups, filter_mask, src: Table) -> Column:
    """Custom aggregation: host groupby-apply (reference registers dask
    Aggregations, context.py:312-377; arbitrary python runs on host here)."""
    vals = col.to_numpy() if col is not None else np.zeros(src.num_rows)
    np_codes = np.asarray(codes) if codes is not None else np.zeros(len(vals), dtype=np.int64)
    keep = np.ones(len(vals), bool)
    if filter_mask is not None:
        keep = np.asarray(filter_mask)
    import pandas as pd
    s = pd.Series(vals[keep])
    g = pd.Series(np_codes[keep])
    result = s.groupby(g).apply(agg.udaf.func)
    out = np.zeros(num_groups, dtype=object)
    out[:] = None
    for k, v in result.items():
        out[int(k)] = v
    mask = np.array([v is not None for v in out])
    if agg.stype.is_string:
        return Column._encode_strings(
            np.where(mask, out, "").astype(object), mask if not mask.all() else None)
    arr = np.array([v if v is not None else 0 for v in out])
    return Column(jnp.asarray(arr.astype(physical_dtype(agg.stype))), agg.stype,
                  None if mask.all() else jnp.asarray(mask))


# the splitter lives in the PLAN layer (optimizer passes need it too, and
# plan -> physical imports would invert the layering); aliased here for the
# physical-layer call sites
from ...plan.optimizer import split_join_condition as _extract_equi_keys  # noqa: E402,E501


def _join(rel: LogicalJoin, ex: RelExecutor) -> Table:
    from ...runtime import statistics as _stats

    left = ex.execute(rel.left)
    right = ex.execute(rel.right)
    nl = len(left.names)
    equi, residual = _extract_equi_keys(rel)
    jt = rel.join_type

    def _key_variant(lk, rk) -> str:
        # stats-driven dense direct-index coding (codes = key - min) for a
        # single int key pair; "hash" = the pre-stats shared factorize
        variant, info = _stats.join_decision(
            rel, [left.columns[i] for i in lk],
            [right.columns[i] for i in rk], ex.context)
        if variant != "hash" or info:
            _stats.record_choice("join", variant, **info)
        return variant

    # disambiguate duplicate column names across sides (schema names win)
    out_names = [f.name for f in rel.schema]

    if jt in ("SEMI", "ANTI"):
        null_aware = getattr(rel, "null_aware", False)
        if not equi and residual:
            # correlated EXISTS with only non-equi predicates: pair expansion
            li, ri = J.cross_join_pairs(left.num_rows, right.num_rows)
            return _semi_anti_pairs(ex, left, right, li, ri, residual, jt)
        if not equi:
            # EXISTS: keep all if right non-empty
            if jt == "SEMI":
                return left if right.num_rows else left.slice(0, 0)
            return left.slice(0, 0) if right.num_rows else left
        lk = [k for k, _ in equi]
        rk = [k for _, k in equi]
        if residual:
            # equi + residual (e.g. decorrelated EXISTS with an inequality):
            # expand equi matches, apply residual, reduce to row existence
            assert not null_aware
            from ...ops.kernels import join_key_codes
            lcodes, rcodes = join_key_codes([left.columns[i] for i in lk],
                                            [right.columns[i] for i in rk],
                                            variant=_key_variant(lk, rk))
            li, ri, _counts = J._expand_matches(lcodes, rcodes)
            return _semi_anti_pairs(ex, left, right, li, ri, residual, jt)
        out, _ = J.join_tables(left, right, lk, rk, jt, null_aware,
                               variant=_key_variant(lk, rk))
        return out

    if not equi:
        # cross join or pure non-equi: pair expansion + residual filter
        li, ri = J.cross_join_pairs(left.num_rows, right.num_rows)
        lt, rt = left.take(li), right.take(ri)
        pairs = Table(out_names, lt.columns + rt.columns)
        if residual:
            cond = _and_rex(residual)
            keep = evaluate_predicate(cond, pairs, ex)
            if isinstance(keep, bool):
                keep = jnp.full(pairs.num_rows, keep)
            if jt == "INNER" or jt == "CROSS":
                return pairs.take(mask_to_indices(keep))
            return J.rejoin_outer(left, right, pairs, keep, li, ri, jt)
        return pairs

    lk = [k for k, _ in equi]
    rk = [k for _, k in equi]

    if not residual:
        out, _ = J.join_tables(left, right, lk, rk, jt,
                               variant=_key_variant(lk, rk))
        return out.with_names(out_names)

    # equi + residual: build inner pairs, filter, then outer recovery
    from ...ops.kernels import join_key_codes
    lcodes, rcodes = join_key_codes([left.columns[i] for i in lk],
                                    [right.columns[i] for i in rk],
                                    variant=_key_variant(lk, rk))
    li, ri, counts = J._expand_matches(lcodes, rcodes)
    lt, rt = left.take(li), right.take(ri)
    pairs = Table(out_names, lt.columns + rt.columns)
    cond = _and_rex(residual)
    keep = evaluate_predicate(cond, pairs, ex)
    if isinstance(keep, bool):
        keep = jnp.full(pairs.num_rows, keep)
    if jt == "INNER":
        return pairs.take(mask_to_indices(keep))
    return J.rejoin_outer(left, right, pairs, keep, li, ri, jt).with_names(out_names)


def _semi_anti_pairs(ex, left: Table, right: Table, li, ri,
                     residual, jt: str) -> Table:
    """SEMI/ANTI with residual predicates: evaluate the condition over the
    candidate (left, right) row pairs, then keep left rows with (SEMI) or
    without (ANTI) any surviving match."""
    lt, rt = left.take(li), right.take(ri)
    pairs = Table(
        [f"l{i}" for i in range(len(lt.names))]
        + [f"r{i}" for i in range(len(rt.names))],
        lt.columns + rt.columns)
    keep = evaluate_predicate(_and_rex(residual), pairs, ex)
    if isinstance(keep, bool):
        keep = jnp.full(pairs.num_rows, keep)
    matched = np.zeros(left.num_rows, dtype=bool)
    matched[np.asarray(li)[np.asarray(keep)]] = True
    want = matched if jt == "SEMI" else ~matched
    return left.take(jnp.asarray(np.flatnonzero(want)))


def _and_rex(rexes):
    from ...types import BOOLEAN
    out = rexes[0]
    for r in rexes[1:]:
        out = RexCall("AND", [out, r], BOOLEAN)
    return out


def _sort(rel: LogicalSort, ex: RelExecutor) -> Table:
    src = ex.execute(rel.input)
    if rel.collation:
        keys = [(c.index, c.ascending, c.effective_nulls_first) for c in rel.collation]
        src = S.apply_sort(src, keys)
    return S.apply_offset_limit(src, rel.offset, rel.limit)


def _union(rel: LogicalUnion, ex: RelExecutor) -> Table:
    tables = [ex.execute(i) for i in rel.inputs_]
    # align names/types to output schema (reference union.py:30-45)
    out_names = [f.name for f in rel.schema]
    aligned = []
    from ..rex.cast import cast_column
    for t in tables:
        cols = []
        for j, f in enumerate(rel.schema):
            c = t.columns[j]
            if c.stype.name != f.stype.name:
                c = cast_column(c, f.stype)
            cols.append(c)
        aligned.append(Table(out_names, cols))
    out = J.concat_tables(aligned)
    if not rel.all:
        rows = G.distinct_rows(out.columns)
        out = out.take(rows)
    return out


def _intersect(rel: LogicalIntersect, ex: RelExecutor) -> Table:
    a = ex.execute(rel.inputs_[0])
    b = ex.execute(rel.inputs_[1])
    a = a.take(G.distinct_rows(a.columns))
    # set-op equality: NULL matches NULL (IS NOT DISTINCT FROM) — a plain
    # equi-join would silently drop every NULL-bearing row (r2 oracle find)
    out, _ = J.join_tables(a, b, list(range(a.num_columns)),
                           list(range(b.num_columns)), "SEMI",
                           null_equal=True)
    return out.with_names([f.name for f in rel.schema])


def _except(rel: LogicalExcept, ex: RelExecutor) -> Table:
    a = ex.execute(rel.inputs_[0])
    b = ex.execute(rel.inputs_[1])
    a = a.take(G.distinct_rows(a.columns))
    out, _ = J.join_tables(a, b, list(range(a.num_columns)),
                           list(range(b.num_columns)), "ANTI",
                           null_equal=True)
    return out.with_names([f.name for f in rel.schema])


def _window(rel: LogicalWindow, ex: RelExecutor) -> Table:
    src = ex.execute(rel.input)
    names = list(src.names)
    cols = list(src.columns)
    for call in rel.calls:
        order = [(c.index, c.ascending, c.effective_nulls_first) for c in call.order]
        col = W.compute_window(src, call.op, call.args, call.partition, order,
                               call.frame, call.stype)
        cols.append(col)
        names.append(call.name)
    return Table(names, cols)


def _sample(rel: LogicalSample, ex: RelExecutor) -> Table:
    src = ex.execute(rel.input)
    import jax
    seed = rel.seed if rel.seed is not None else np.random.randint(0, 2**31)
    key = jax.random.PRNGKey(seed)
    frac = rel.percentage / 100.0
    # single-device table: SYSTEM (block-level) == BERNOULLI here; the
    # sharded path samples whole shards for SYSTEM (see parallel/)
    mask = jax.random.uniform(key, (src.num_rows,)) < frac
    return src.take(mask_to_indices(mask))


def _predict(rel, ex: RelExecutor) -> Table:
    src = ex.execute(rel.input)
    model, training_columns = ex.context._get_model(rel.model_name)
    import numpy as np
    X = np.column_stack([src.column(c).to_numpy().astype(np.float64)
                         for c in training_columns]) if training_columns else src.to_pandas()
    pred = model.predict(X)
    out = Column.from_numpy(np.asarray(pred))
    from ..rex.cast import cast_value
    out = cast_value(out, rel.schema[-1].stype, src.num_rows)
    return src.add_column(rel.schema[-1].name, out)


RelExecutor.add_plugin("LogicalTableScan", _table_scan)
RelExecutor.add_plugin("LogicalProject", _project)
RelExecutor.add_plugin("LogicalFilter", _filter)
RelExecutor.add_plugin("LogicalValues", _values)
RelExecutor.add_plugin("LogicalAggregate", _aggregate)
RelExecutor.add_plugin("LogicalJoin", _join)
RelExecutor.add_plugin("LogicalSort", _sort)
RelExecutor.add_plugin("LogicalUnion", _union)
RelExecutor.add_plugin("LogicalIntersect", _intersect)
RelExecutor.add_plugin("LogicalExcept", _except)
RelExecutor.add_plugin("LogicalWindow", _window)
RelExecutor.add_plugin("LogicalSample", _sample)
RelExecutor.add_plugin("LogicalPredict", _predict)
