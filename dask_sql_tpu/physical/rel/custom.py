"""Custom statement handlers: DDL, SHOW/DESCRIBE/ANALYZE, and SQL-driven ML.

Mirrors the reference's custom plugins
(/root/reference/dask_sql/physical/rel/custom/): one handler per statement AST
class, registered in a Pluggable dispatch — the same extension contract as the
rel/rex registries.  Handlers receive (statement, context, sql_text) and
return a device Table (for SHOW/ANALYZE/DESCRIBE metadata results) or None.
"""
from __future__ import annotations

import logging
import pickle
from typing import Optional

import numpy as np

from ...datacontainer import TableEntry
from ...sql import ast as A
from ...table import Table
from ...utils import Pluggable

logger = logging.getLogger(__name__)


class StatementDispatcher(Pluggable):
    """Statement AST class name -> handler registry."""


def _meta_table(data: dict) -> Table:
    return Table.from_pydict(data)


# ---------------------------------------------------------------------------
# schema DDL (reference custom/create_schema.py, drop_schema.py, switch_schema.py)
# ---------------------------------------------------------------------------

def _create_schema(stmt: A.CreateSchema, context, sql):
    if stmt.name in context.schema:
        if stmt.if_not_exists:
            return None
        if not stmt.or_replace:
            raise RuntimeError(f"A schema with the name {stmt.name} is already present.")
    context.create_schema(stmt.name)
    return None


def _drop_schema(stmt: A.DropSchema, context, sql):
    if stmt.name not in context.schema:
        if stmt.if_exists:
            return None
        raise RuntimeError(f"A schema with the name {stmt.name} is not present.")
    context.drop_schema(stmt.name)
    return None


def _use_schema(stmt: A.UseSchema, context, sql):
    if stmt.name not in context.schema:
        raise RuntimeError(f"A schema with the name {stmt.name} is not present.")
    context.schema_name = stmt.name
    return None


# ---------------------------------------------------------------------------
# table DDL (reference custom/create_table.py, create_table_as.py, drop_table.py)
# ---------------------------------------------------------------------------

def _create_table(stmt: A.CreateTable, context, sql):
    schema_name, name = context.fqn(stmt.name)
    if name in context.schema[schema_name].tables:
        if stmt.if_not_exists:
            return None
        if not stmt.or_replace:
            raise RuntimeError(f"A table with the name {name} is already present.")
    kwargs = dict(stmt.kwargs)
    try:
        location = kwargs.pop("location")
    except KeyError:
        raise AttributeError("Parameters must include a 'location' parameter.")
    fmt = kwargs.pop("format", None)
    persist = bool(kwargs.pop("persist", False))
    kwargs.pop("gpu", None)
    context.create_table(name, location, format=fmt, persist=persist,
                         schema_name=schema_name, **kwargs)
    return None


def _create_table_as(stmt: A.CreateTableAs, context, sql):
    schema_name, name = context.fqn(stmt.name)
    if name in context.schema[schema_name].tables:
        if stmt.if_not_exists:
            return None
        if not stmt.or_replace:
            raise RuntimeError(f"A table with the name {name} is already present.")
    plan = context._get_plan(stmt.query, sql)
    # overwriting a materialized view with CREATE [OR REPLACE] TABLE/VIEW AS
    # tears down its registry state — the replaced entry must never be
    # refreshed back over the new definition
    reg = context.__dict__.get("_matview_registry")
    if reg is not None:
        reg.discard_view(schema_name, name)
    if stmt.view:
        # views stay lazy: re-planned/executed per query (reference
        # CREATE VIEW = lazy dask graph, create_table_as.py:30-55)
        context.schema[schema_name].tables[name] = TableEntry(plan=plan)
        context.bump_table_epoch(schema_name, name)
        return None
    from .executor import RelExecutor
    table = RelExecutor(context).execute(plan)
    context.schema[schema_name].tables[name] = TableEntry(table=table)
    context.bump_table_epoch(schema_name, name)
    return None


def _create_matview(stmt: A.CreateMaterializedView, context, sql):
    from ...runtime import matview as _mv
    _mv.create_matview(context, stmt.name, stmt.query, sql,
                       if_not_exists=stmt.if_not_exists,
                       or_replace=stmt.or_replace)
    return None


def _drop_matview(stmt: A.DropMaterializedView, context, sql):
    from ...runtime import matview as _mv
    _mv.drop_matview(context, stmt.name, if_exists=stmt.if_exists)
    return None


def _refresh_matview(stmt: A.RefreshMaterializedView, context, sql):
    from ...runtime import matview as _mv
    _mv.refresh_matview(context, stmt.name)
    return None


def _insert_into(stmt: A.InsertInto, context, sql):
    """INSERT INTO: run the source query (VALUES lowers to a query too)
    through the normal execution path, then hand the rows to
    ``Context.append_rows`` — the delta-recording append seam."""
    from ...runtime.resilience import SchemaMismatch

    plan = context._get_plan(stmt.query, sql)
    rows = context._execute_query_plan(plan)
    schema_name, name = context.fqn(stmt.table)
    payload = rows
    if stmt.columns is not None:
        if len(stmt.columns) != rows.num_columns:
            raise SchemaMismatch(
                f"INSERT INTO {name} names {len(stmt.columns)} columns but "
                f"the source produces {rows.num_columns}.")
        entry = context.schema[schema_name].tables.get(name)
        if entry is not None and entry.table is not None:
            import pandas as pd
            df = rows.to_pandas()
            df.columns = [c.lower() for c in stmt.columns]
            target = list(entry.table.names)
            unknown = [c for c in df.columns
                       if c not in {t.lower() for t in target}]
            if unknown:
                raise SchemaMismatch(
                    f"INSERT INTO {name} names columns {unknown} that the "
                    f"table does not have (columns: {target}).")
            # unnamed target columns fill NULL
            payload = pd.DataFrame(
                {t: (df[t.lower()] if t.lower() in df.columns
                     else pd.Series([None] * len(df)))
                 for t in target})
    context.append_rows(name, payload, schema_name=schema_name)
    return None


def _drop_table(stmt: A.DropTable, context, sql):
    schema_name, name = context.fqn(stmt.name)
    if name not in context.schema[schema_name].tables:
        if stmt.if_exists:
            return None
        raise RuntimeError(f"A table with the name {name} is not present.")
    context.drop_table(name, schema_name=schema_name)
    return None


# ---------------------------------------------------------------------------
# SHOW / DESCRIBE / ANALYZE (reference custom/schemas.py, tables.py,
# columns.py, show_models.py, describe_model.py, analyze.py)
# ---------------------------------------------------------------------------

def _show_schemas(stmt: A.ShowSchemas, context, sql):
    names = list(context.schema.keys()) + ["information_schema"]
    if stmt.like:
        import re
        from ..rex.ops import sql_like_to_regex
        rx = re.compile(sql_like_to_regex(stmt.like))
        names = [n for n in names if rx.match(n)]
    return _meta_table({"Schema": np.array(names, dtype=object)})


def _show_tables(stmt: A.ShowTables, context, sql):
    schema_name = stmt.schema or context.schema_name
    if schema_name not in context.schema:
        raise AttributeError(f"Schema {schema_name} is not defined.")
    names = list(context.schema[schema_name].tables.keys())
    return _meta_table({"Table": np.array(names, dtype=object)})


def _show_columns(stmt: A.ShowColumns, context, sql):
    resolved = context.resolve_table(stmt.table)
    if resolved is None:
        raise AttributeError(f"Table {'.'.join(stmt.table)} is not defined.")
    _, _, fields, _ = resolved
    return _meta_table({
        "Column": np.array([f.name for f in fields], dtype=object),
        "Type": np.array([str(f.stype).lower() for f in fields], dtype=object),
        "Extra": np.array([""] * len(fields), dtype=object),
        "Comment": np.array([""] * len(fields), dtype=object),
    })


def _describe_table(stmt: A.DescribeTable, context, sql):
    return _show_columns(A.ShowColumns(table=stmt.table), context, sql)


def _show_models(stmt: A.ShowModels, context, sql):
    names = list(context.schema[context.schema_name].models.keys())
    return _meta_table({"Models": np.array(names, dtype=object)})


def _describe_model(stmt: A.DescribeModel, context, sql):
    info = context.resolve_model(stmt.name)
    if info is None:
        raise RuntimeError(f"A model with the name {'.'.join(stmt.name)} is not present.")
    model, training_columns = info
    params = model.get_params() if hasattr(model, "get_params") else {}
    params["training_columns"] = list(training_columns)
    keys = np.array(list(params.keys()), dtype=object)
    vals = np.array([str(v) for v in params.values()], dtype=object)
    return _meta_table({"Params": keys, "Value": vals})


def _analyze_table(stmt: A.AnalyzeTable, context, sql):
    """ANALYZE TABLE: describe()-style statistics (reference analyze.py:42-59)."""
    resolved = context.resolve_table(stmt.table)
    if resolved is None:
        raise AttributeError(f"Table {'.'.join(stmt.table)} is not defined.")
    schema_name, table_name, fields, _ = resolved
    entry = context.schema[schema_name].tables[table_name]
    from .executor import RelExecutor
    table = entry.table if entry.table is not None else RelExecutor(context).execute(entry.plan)
    columns = stmt.columns or table.names
    df = table.limit_to(columns).to_pandas()
    stats = df.describe(include="all")
    import pandas as pd
    extra = pd.DataFrame({c: [str(table.column(c).stype).lower()] for c in columns},
                         index=["data_type"])
    name_row = pd.DataFrame({c: [c] for c in columns}, index=["col_name"])
    out = pd.concat([stats, extra, name_row])
    out = out.reset_index().rename(columns={"index": "statistic"})
    # stringify mixed-type statistic rows for a clean device table
    for c in columns:
        out[c] = out[c].astype(object).where(out[c].notna(), None)
        out[c] = out[c].map(lambda v: str(v) if v is not None else None)
    return Table.from_pandas(out)


# ---------------------------------------------------------------------------
# ML statements (reference custom/create_model.py, predict.py,
# create_experiment.py, export_model.py, drop_model.py)
# ---------------------------------------------------------------------------

def _drop_model(stmt: A.DropModel, context, sql):
    schema_name, name = context.fqn(stmt.name)
    if name not in context.schema[schema_name].models:
        if stmt.if_exists:
            return None
        raise RuntimeError(f"A model with the name {name} is not present.")
    del context.schema[schema_name].models[name]
    return None


def _create_model(stmt: A.CreateModel, context, sql):
    from ...models.training import create_model
    return create_model(stmt, context, sql)


def _create_experiment(stmt: A.CreateExperiment, context, sql):
    from ...models.training import create_experiment
    return create_experiment(stmt, context, sql)


def _export_model(stmt: A.ExportModel, context, sql):
    from ...models.training import export_model
    return export_model(stmt, context, sql)


def _explain(stmt: A.ExplainStatement, context, sql):
    plan = context._get_plan(stmt.query, sql)
    if getattr(stmt, "profile", False):
        return _explain_profile(plan, context)
    if not getattr(stmt, "analyze", False):
        lines = plan.explain().splitlines()
        # predicted adaptive operator choices (runtime/statistics.py):
        # what the dispatch WOULD pick for this plan and the stats
        # driving it — EXPLAIN ANALYZE prints the measured ones instead
        from ...runtime import statistics as _stats
        lines.extend(_stats.explain_lines(plan, context))
        return _meta_table({"PLAN": np.array(lines, dtype=object)})
    return _explain_analyze(plan, context)


def _explain_profile(plan, context):
    """EXPLAIN PROFILE: run the plan through the NORMAL engine path — the
    tier dispatch, scheduler admission and SPMD/compiled execution a plain
    run would take (unlike EXPLAIN ANALYZE's instrumented eager run) — and
    render the device-level profile captured on its spans: per-stage
    flops / bytes / device-ms, shard skew, collective bytes by kind,
    per-device HBM and the cost-model error (runtime/profiler.py).

    Zero-cost when the profiler is disarmed: the query is NOT executed;
    only the plan and a pointer at ``DSQL_PROFILE`` print.
    """
    import os
    import time as _time

    from ...runtime import telemetry as _tel

    lines = plan.explain().splitlines()
    if os.environ.get("DSQL_PROFILE", "0").strip() in ("", "0"):
        lines.append("-- profile: disabled (set DSQL_PROFILE=1)")
        return _meta_table({"PLAN": np.array(lines, dtype=object)})

    from ...runtime import profiler as _prof

    # the result cache would short-circuit a previously-run query into a
    # replay with no stages to profile; profiling means MEASURING an
    # execution, so the lookup (not the store) is bypassed for this run
    context._rc_bypass = True
    t0 = _time.perf_counter()
    try:
        with _tel.span("profile_exec") as sp:
            result = context._execute_query_plan(plan)
    finally:
        context._rc_bypass = False
    wall_ms = (_time.perf_counter() - t0) * 1e3
    rows_out = int(getattr(result, "num_rows", 0) or 0)
    spans = list(sp.walk()) if sp is not None else []

    def stat(ss, key, conv=float):
        """Sum ``key`` over spans, or None when no span carried it."""
        tot, seen = 0, False
        for s in ss:
            v = s.attrs.get(key)
            if v:
                tot, seen = tot + conv(v), True
        return tot if seen else None

    def fmt(v):
        if v is None:
            return "n/a"
        return f"{v:.3f}" if isinstance(v, float) else str(v)

    tier = next((str(s.attrs.get("tier")) for s in spans
                 if s.attrs.get("tier")), None)
    lines.append(f"-- profile: wall={wall_ms:.3f}ms rows_out={rows_out}"
                 + (f" tier={tier}" if tier else ""))
    # the admission estimate this run was charged under — "cost_model"
    # here is the profiler's own estimate rung closing the loop
    for s in spans:
        if s.name == "queued":
            lines.append(
                f"-- estimate: source={s.attrs.get('est_source', '?')} "
                f"bytes={s.attrs.get('est_bytes', 0)}")
            break
    # per-stage rows: compiled stage-graph spans and SPMD stage spans;
    # a single-program plan renders one whole-plan row instead
    stage_spans = [s for s in spans if s.name in ("stage", "spmd_stage")]
    targets = stage_spans or ([sp] if sp is not None else [])
    for s in targets:
        ss = list(s.walk())
        cbytes = stat(ss, "cost_bytes")
        mbytes = stat(ss, "stage_bytes", int)
        err = _prof.cost_error(cbytes, mbytes)
        label = ("whole" if s is sp
                 else f"{s.name}[{s.attrs.get('index', '?')}]")
        lines.append(
            f"-- stage {label}: flops={fmt(stat(ss, 'cost_flops'))} "
            f"bytes={fmt(cbytes)} measured_bytes={fmt(mbytes)} "
            f"device_ms={fmt(stat(ss, 'device_ms'))} "
            f"wall_ms={s.wall_ms:.3f} "
            f"rows={fmt(stat(ss, 'stage_rows_out', int))} "
            f"skew={fmt(stat([s], 'skew_ratio'))} "
            f"cost_err={fmt(err)}")
    # shard/partition skew + collective bytes by kind, query-wide
    skews = [float(s.attrs.get("skew_ratio")) for s in spans
             if s.attrs.get("skew_ratio") is not None]
    if skews:
        lines.append(f"-- skew_ratio: {max(skews):.3f}")
    coll = []
    for attr, kind in (("spmd_exchange_bytes", "all_to_all"),
                       ("spmd_all_gather_bytes", "all_gather"),
                       ("spmd_psum_bytes", "psum")):
        v = stat(spans, attr, int)
        if v:
            coll.append(f"{kind}={v}")
    if coll:
        lines.append("-- collectives: " + " ".join(coll))
    # query-wide cost-model error (predicted XLA bytes vs result +
    # materialized stage bytes — the flight-recorder definition)
    total_pred = stat(spans, "cost_bytes")
    res_bytes = sum(int(getattr(c.data, "nbytes", 0) or 0)
                    for c in (getattr(result, "columns", None) or []))
    total_meas = (stat(spans, "stage_bytes", int) or 0) + res_bytes
    err = _prof.cost_error(total_pred, total_meas)
    if err is not None:
        lines.append(f"-- cost_model_error: {err:.4f}")
    # per-device HBM truth (zeros on backends without memory_stats)
    for d in _prof.device_memory_rows():
        lines.append(
            f"-- device {d['id']}: platform={d['platform']} "
            f"kind={d['kind']} hbm_in_use={d['bytes_in_use']} "
            f"hbm_peak={d['peak_bytes_in_use']} "
            f"hbm_limit={d['bytes_limit']}")
    return _meta_table({"PLAN": np.array(lines, dtype=object)})


def _explain_analyze(plan, context):
    """EXPLAIN ANALYZE: execute the plan INSTRUMENTED and render the tree
    annotated with measured per-node wall-time and row counts.

    Per-node attribution requires per-node dispatch, so the plan runs
    through the eager executor under a NodeRecorder (the compiled path
    fuses the whole plan into one XLA program — its phase split lives in
    the QueryReport / ``stage`` spans instead, like Postgres
    instrumentation vs JIT-compiled expressions).  Chunked (out-of-HBM)
    plans stream as usual; the recorder then captures the resident
    per-batch/merge subplans the streamer actually dispatched.
    """
    import time as _time

    from ...runtime import result_cache as _rc, telemetry as _tel

    # result-cache probe BEFORE executing: the analyzed run always executes
    # for real (per-node instrumentation is the point), but the tree should
    # say what a plain run of this plan would have done
    cache = _rc.get_cache()
    ckey = _rc.plan_key(plan, context) if cache.enabled() else None
    if not cache.enabled():
        cache_line = "-- cache: disabled"
    elif ckey is None:
        cache_line = "-- cache: uncacheable (volatile or chunked plan)"
    else:
        tier = cache.probe(ckey)
        cache_line = (f"-- cache: hit tier={tier}" if tier is not None
                      else "-- cache: miss")

    # execution-tier probe BEFORE executing, mirroring the cache probe:
    # the analyzed run itself is always eager (per-node instrumentation),
    # so report what tier a plain run would answer on
    try:
        from ..compiled import tier_probe
        exec_tier = tier_probe(plan, context)
    except Exception:
        exec_tier = "eager"

    from ...runtime import statistics as _stats

    snap0 = _tel.REGISTRY.counters()
    t0 = _time.perf_counter()
    with _stats.capture() as choices, _tel.record_nodes() as rec:
        if getattr(context, "_has_chunked", False):
            from ..streaming import (execute_streaming,
                                     plan_references_chunked)
            if plan_references_chunked(plan, context):
                result = execute_streaming(plan, context)
            else:
                from .executor import RelExecutor
                result = RelExecutor(context).execute(plan)
        else:
            from .executor import RelExecutor
            result = RelExecutor(context).execute(plan)
    wall_ms = (_time.perf_counter() - t0) * 1e3
    snap1 = _tel.REGISTRY.counters()

    def annotate(node):
        r = rec.get(node)
        if r is None:
            return "[not executed]"
        total_ms, rows, calls = r[0], r[1], r[2]
        child_ms = 0.0
        for child in node.inputs:
            cr = rec.get(child)
            if cr is not None:
                child_ms += cr[0]
        self_ms = max(total_ms - child_ms, 0.0)
        extra = f" calls={calls}" if calls > 1 else ""
        return (f"[rows={rows} time={total_ms:.3f}ms "
                f"self={self_ms:.3f}ms{extra}]")

    # the instrumented result is a valid materialization: populate so the
    # NEXT plain run of this query hits
    if ckey is not None and result is not None:
        cache.put(ckey, result)

    lines = plan.explain(annotate=annotate).splitlines()
    rows_out = int(getattr(result, "num_rows", 0) or 0)
    lines.append(f"-- analyzed: wall={wall_ms:.3f}ms rows_out={rows_out} "
                 f"nodes={len(rec.records)}")
    lines.append(cache_line)
    # the adaptive operator choices the analyzed run ACTUALLY took
    # (vs the predictions plain EXPLAIN prints)
    for op, variant, info in choices:
        lines.append("-- operator: " + _stats.format_choice(op, variant,
                                                            info))
    store_hits = (snap1.get("program_store_hits", 0)
                  - snap0.get("program_store_hits", 0))
    tier_line = f"-- tier: {exec_tier}"
    if store_hits:
        tier_line += f" program_store_hits=+{store_hits}"
    lines.append(tier_line)
    delta = {k: snap1[k] - snap0.get(k, 0) for k in snap1
             if snap1[k] != snap0.get(k, 0)}
    # out-of-core marker: this run hash-partitioned inputs to spill tiers
    # (grace join) — name the partition count and where the bytes went
    if delta.get("spill_partitions"):
        lines.append(
            f"-- spilled: partitions=+{delta['spill_partitions']} "
            f"pairs=+{delta.get('morsel_pairs', 0)} "
            f"host_bytes=+{delta.get('spill_bytes_host', 0)} "
            f"disk_bytes=+{delta.get('spill_bytes_disk', 0)}")
    if delta:
        lines.append("-- counters: " + " ".join(
            f"{k}=+{v}" for k, v in sorted(delta.items())))
    return _meta_table({"PLAN": np.array(lines, dtype=object)})


# ---------------------------------------------------------------------------
# PREPARE / EXECUTE / DEALLOCATE (server-side prepared statements; pairs
# with parameterized plan identity — plan/parameterize.py — so every
# EXECUTE of one prepared shape reuses a single compiled program)
# ---------------------------------------------------------------------------

def _prepare(stmt: A.PrepareStatement, context, sql):
    context._prepared[stmt.name.lower()] = stmt
    return None


def _execute_prepared(stmt: A.ExecuteStatement, context, sql):
    from ...runtime import telemetry as _tel

    prep = context._prepared.get(stmt.name.lower())
    if prep is None:
        raise RuntimeError(
            f"Prepared statement {stmt.name!r} does not exist.")
    if len(stmt.params) < prep.num_params:
        raise RuntimeError(
            f"Prepared statement {stmt.name!r} requires {prep.num_params} "
            f"parameters, {len(stmt.params)} given.")
    plan = context._get_plan(prep.query, sql, params=stmt.params)
    _tel.inc("prepared_executes")
    return context._execute_query_plan(plan)


def _deallocate(stmt: A.DeallocateStatement, context, sql):
    if stmt.name is None:
        context._prepared.clear()
        return None
    if context._prepared.pop(stmt.name.lower(), None) is None:
        raise RuntimeError(
            f"Prepared statement {stmt.name!r} does not exist.")
    return None


StatementDispatcher.add_plugin("CreateSchema", _create_schema)
StatementDispatcher.add_plugin("DropSchema", _drop_schema)
StatementDispatcher.add_plugin("UseSchema", _use_schema)
StatementDispatcher.add_plugin("CreateTable", _create_table)
StatementDispatcher.add_plugin("CreateTableAs", _create_table_as)
StatementDispatcher.add_plugin("DropTable", _drop_table)
StatementDispatcher.add_plugin("CreateMaterializedView", _create_matview)
StatementDispatcher.add_plugin("DropMaterializedView", _drop_matview)
StatementDispatcher.add_plugin("RefreshMaterializedView", _refresh_matview)
StatementDispatcher.add_plugin("InsertInto", _insert_into)
StatementDispatcher.add_plugin("ShowSchemas", _show_schemas)
StatementDispatcher.add_plugin("ShowTables", _show_tables)
StatementDispatcher.add_plugin("ShowColumns", _show_columns)
StatementDispatcher.add_plugin("DescribeTable", _describe_table)
StatementDispatcher.add_plugin("ShowModels", _show_models)
StatementDispatcher.add_plugin("DescribeModel", _describe_model)
StatementDispatcher.add_plugin("AnalyzeTable", _analyze_table)
StatementDispatcher.add_plugin("DropModel", _drop_model)
StatementDispatcher.add_plugin("CreateModel", _create_model)
StatementDispatcher.add_plugin("CreateExperiment", _create_experiment)
StatementDispatcher.add_plugin("ExportModel", _export_model)
StatementDispatcher.add_plugin("ExplainStatement", _explain)
StatementDispatcher.add_plugin("PrepareStatement", _prepare)
StatementDispatcher.add_plugin("ExecuteStatement", _execute_prepared)
StatementDispatcher.add_plugin("DeallocateStatement", _deallocate)
