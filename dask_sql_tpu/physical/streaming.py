"""Streaming (out-of-HBM) execution over chunked tables.

The reference runs every query out-of-core by construction (partitioned dask
dataframes, input_utils/convert.py:38-62).  Here the compiled whole-plan-jit
executor wants resident device tables, so tables bigger than HBM register as
``ChunkedSource`` (io/chunked.py) and this module lowers plans over them by
ITERATIVE REWRITING: while the plan still references a chunked scan, find a
streamable SPLIT whose subtree contains exactly that one scan, execute the
subtree batch-by-batch, materialize its (small) result as a resident temp,
and substitute it back.  Split strategies, tried innermost-first:

  * aggregate: everything below the lowest aggregate runs PER BATCH (same
    shapes + shared dictionaries => one compile, N-1 program-cache hits);
    partials merge by algebra (SUM/$SUM0->SUM, COUNT->$SUM0, MIN/MAX->self,
    AVG->(sum,count)+final divide);
  * distinct aggregate: when every call is DISTINCT on one argument (or a
    dedup-invariant MIN/MAX of it), the per-batch plan is a group-by
    DEDUP of (group keys, argument); the final aggregate re-deduplicates
    across batches by construction;
  * top-k: a LIMIT-ed sort streams as per-batch top-(limit+offset), then
    top-k of the concatenated partials;
  * semi/anti key-set: a SEMI/ANTI join whose BUILD (right) side holds the
    chunked scan streams the build as a per-batch DEDUP of the join-key
    (and residual-referenced) columns — semi-join semantics only need key
    existence, so the join then runs resident against the merged key set;
  * window regroup: a window with PARTITION BY streams its input per batch
    to host, hash-buckets the rows on the partition keys (whole partitions
    land in one bucket), and runs the window resident per equal-capacity
    bucket — one compile, N-1 cache hits; a table-sized window output
    re-registers as a chunked source so streaming continues above it.

Joins on a streamed path keep the build (resident) side fixed: subtrees
not containing the chunked scan are materialized ONCE into temp tables and
reused across batches.  Multiple chunked scans (e.g. TPC-H Q17/Q21 reading
lineitem two or three times) lower one subtree per iteration.  An INNER
equi-join with a chunked scan on BOTH sides — which no single-scan
strategy covers — lowers via the grace-hash partitioned join in
physical/morsel.py when spilling is enabled (DSQL_SPILL_MB > 0).

Partial results accumulate on HOST (one batch resident on device at a
time); when their total size exceeds ``DSQL_STREAM_PARTIAL_BYTES`` the
aggregate merge runs on host via pandas instead of materializing a device
temp (the out-of-device-memory path for high-cardinality GROUP BYs), and
key-set/dedup partials deduplicate incrementally after every batch so the
host working set is bounded by the DISTINCT count, not the row count.

Under ``Context(mesh=...)`` each uploaded batch is row-sharded over the
mesh and the per-batch compiled program executes as a GSPMD program — the
streaming and distributed axes compose (the reference's model is
out-of-core AND distributed at once, input_utils/convert.py:38-62).

Plans outside every strategy (a window without PARTITION BY over the
chunked scan, no aggregate/limit split, chunked on the NULL-extended side
of an outer join) raise ``StreamingUnsupported`` with a reason — never a
silent wrong answer on schema stubs.
"""
from __future__ import annotations

import logging
import os
import threading
from typing import List, Optional, Tuple

import numpy as np

from ..datacontainer import TableEntry
from ..plan.nodes import (
    AggCall, Field, LogicalAggregate, LogicalFilter, LogicalJoin,
    LogicalProject, LogicalSort, LogicalTableScan, LogicalWindow, RelNode,
    RexCall, RexInputRef,
)
from ..runtime import (faults as _faults, resilience as _res,
                       telemetry as _tel)
from ..table import Table
from ..types import BIGINT, DOUBLE

logger = logging.getLogger(__name__)

STREAM_SCHEMA = "__stream__"
BATCH_TABLE = "batch"

_MERGEABLE = {"SUM", "$SUM0", "COUNT", "MIN", "MAX", "AVG"}

# above this many accumulated partial bytes the merge happens on host
PARTIAL_BYTES_BUDGET = int(os.environ.get("DSQL_STREAM_PARTIAL_BYTES",
                                          str(1 << 30)))


class StreamingUnsupported(_res.UserError):
    """Plan shape the streaming executor cannot run out-of-core.

    A typed UserError (still a RuntimeError via the taxonomy base): the
    message always names the remedy, and the server maps it to a
    USER_ERROR payload instead of a stringified internal exception."""


# ---------------------------------------------------------------------------
# plan inspection
# ---------------------------------------------------------------------------

def _is_chunked_scan(rel: RelNode, context) -> bool:
    if not isinstance(rel, LogicalTableScan):
        return False
    entry = context.schema.get(rel.schema_name, None)
    entry = entry.tables.get(rel.table_name) if entry else None
    return entry is not None and getattr(entry, "chunked", None) is not None


def _chunked_scans(plan: RelNode, context) -> List[LogicalTableScan]:
    out = []

    def walk(rel: RelNode):
        if isinstance(rel, LogicalTableScan):
            if _is_chunked_scan(rel, context):
                out.append(rel)
            return
        for i in rel.inputs:
            walk(i)
        # scalar-subquery plans hide extra scans inside rex trees
        from ..plan.nodes import RexScalarSubquery

        def walk_rex(rex):
            if isinstance(rex, RexScalarSubquery):
                walk(rex.plan)
            for o in getattr(rex, "operands", []) or []:
                walk_rex(o)

        if isinstance(rel, LogicalProject):
            for e in rel.exprs:
                walk_rex(e)
        elif isinstance(rel, LogicalFilter):
            walk_rex(rel.condition)
        elif isinstance(rel, LogicalJoin) and rel.condition is not None:
            walk_rex(rel.condition)

    walk(plan)
    return out


def plan_references_chunked(plan: RelNode, context) -> bool:
    return bool(_chunked_scans(plan, context))


def _path_to(plan: RelNode, target: RelNode) -> Optional[List[RelNode]]:
    """Nodes from root to target (inclusive), by identity."""
    if plan is target:
        return [plan]
    for i in plan.inputs:
        sub = _path_to(i, target)
        if sub is not None:
            return [plan] + sub
    return None


def _replace(plan: RelNode, old: RelNode, new: RelNode) -> RelNode:
    if plan is old:
        return new
    if not plan.inputs:
        return plan
    return plan.with_inputs([_replace(i, old, new) for i in plan.inputs])


# ---------------------------------------------------------------------------
# execution plumbing
# ---------------------------------------------------------------------------

def _run_resident(plan: RelNode, context) -> Table:
    from .compiled import try_execute_compiled
    from .rel.executor import RelExecutor

    result = try_execute_compiled(plan, context)
    if result is None:
        result = RelExecutor(context).execute(plan)
    return result


_tmp_counter = [0]

# execute_streaming serialization (see its docstring): one streaming query
# at a time per process; depth per context id so only the outermost frame
# of a same-thread nesting pops the temp schema
_EXEC_LOCK = threading.RLock()
_exec_depth: dict = {}


def _register_temp(context, table: Table, row_valid=None) -> LogicalTableScan:
    """Register a materialized table under __stream__ and return its scan."""
    if STREAM_SCHEMA not in context.schema:
        context.create_schema(STREAM_SCHEMA)
    _tmp_counter[0] += 1
    name = f"t{_tmp_counter[0]}"
    # intermediate schemas may carry duplicate/empty names; ordinals are what
    # matter downstream, so names are sanitized for catalog registration
    names = [f"c{i}" for i in range(table.num_columns)]
    table = table.with_names(names)
    context.schema[STREAM_SCHEMA].tables[name] = TableEntry(
        table=table, row_valid=row_valid)
    fields = [Field(n, c.stype) for n, c in zip(names, table.columns)]
    return LogicalTableScan(schema_name=STREAM_SCHEMA, table_name=name,
                            schema=fields)


def _register_temp_typed(context, table: Table, fields) -> LogicalTableScan:
    """Register a temp table and return its scan RE-TYPED to ``fields``'
    stypes (temp registration sanitizes names; ordinals carry meaning)."""
    return _retype(_register_temp(context, table), fields)


def _retype(scan: LogicalTableScan, fields) -> LogicalTableScan:
    return LogicalTableScan(
        schema_name=scan.schema_name, table_name=scan.table_name,
        schema=[Field(f2.name, f1.stype)
                for f1, f2 in zip(fields, scan.schema)])


def _set_batch_entry(context, table: Table, row_valid) -> None:
    if STREAM_SCHEMA not in context.schema:
        context.create_schema(STREAM_SCHEMA)
    if context.mesh is not None:
        # streaming x mesh: the uploaded batch is row-sharded over the mesh
        # so the per-batch program executes as a GSPMD program — out-of-core
        # AND distributed at once, like the reference's partitioned model
        from ..parallel.mesh import shard_table_with_validity
        table, shard_valid = shard_table_with_validity(table, context.mesh)
        if row_valid is not None:
            import jax.numpy as jnp
            n = len(shard_valid) if shard_valid is not None else table.num_rows
            rv = jnp.zeros(n, dtype=bool).at[:len(row_valid)].set(row_valid)
            row_valid = rv if shard_valid is None else (rv & shard_valid)
        else:
            row_valid = shard_valid
    context.schema[STREAM_SCHEMA].tables[BATCH_TABLE] = TableEntry(
        table=table, row_valid=row_valid)


def _cleanup(context) -> None:
    context.schema.pop(STREAM_SCHEMA, None)
    # grace-hash joins (physical/morsel.py) spill partition/output runs;
    # free them even on the error path so a failed query leaks no bytes
    runs = getattr(context, "_spill_runs", None)
    if runs:
        from ..runtime import spill as _spill
        store = _spill.get_store()
        for r in runs:
            store.free_run(r)
        runs.clear()


def _stream_partial_plans(subtree: RelNode, scan: LogicalTableScan,
                          path: List[RelNode], context) -> RelNode:
    """The per-batch subtree: ``subtree`` with (a) the chunked scan replaced
    by the batch scan and (b) off-path join subtrees pre-materialized.
    ``path`` is any root-to-scan node list covering the subtree."""
    path_ids = {id(p) for p in path}

    def rebuild(rel: RelNode) -> RelNode:
        if rel is scan:
            fields = list(scan.schema)
            return LogicalTableScan(schema_name=STREAM_SCHEMA,
                                    table_name=BATCH_TABLE, schema=fields)
        if id(rel) not in path_ids:
            # off the streamed path: resident — materialize once
            if isinstance(rel, LogicalTableScan):
                if _is_chunked_scan(rel, context):
                    raise StreamingUnsupported(
                        "a second chunked table feeds the streamed subtree")
                return rel
            t = _run_resident(rel, context)
            return _register_temp_typed(context, t, rel.schema)
        if isinstance(rel, LogicalJoin):
            left_on = any(id(rel.left) == id(p) for p in path) or rel.left is scan
            jt = rel.join_type
            ok = (jt == "INNER"
                  or (jt in ("LEFT", "SEMI", "ANTI") and left_on)
                  or (jt == "RIGHT" and not left_on))
            if not ok:
                raise StreamingUnsupported(
                    f"{jt} join with the chunked table on the NULL-extended "
                    "side cannot stream (every build row must see all probe "
                    "rows)")
        if isinstance(rel, LogicalWindow):
            # a window executed per batch sees only that batch's slice of
            # each partition — _find_split handles windows with their own
            # regrouping split, so one on the streamed path here is a plan
            # shape that must not run (it would be silently wrong)
            raise StreamingUnsupported(
                "window function on the streamed path cannot run per batch")
        return rel.with_inputs([rebuild(i) for i in rel.inputs])

    return rebuild(subtree)


def _partial_and_merge_aggs(agg: LogicalAggregate):
    """(partial_aggs, partial_fields, merge_aggs, post_exprs, needs_project)

    Partial layout: one column per non-AVG call, (sum, count) for AVG.
    Merge layout mirrors the partial layout; post_exprs map the merged
    columns back to agg.schema (the AVG division happens here).
    """
    gk = len(agg.group_keys)
    partial_aggs: List[AggCall] = []
    partial_fields: List[Field] = []
    merge_aggs: List[AggCall] = []
    post_exprs: List = []
    needs_project = False
    agg_fields = agg.schema[gk:]
    for call, field in zip(agg.aggs, agg_fields):
        if call.udaf is not None or call.distinct:
            raise StreamingUnsupported(
                f"{'DISTINCT ' if call.distinct else ''}{call.op} does not "
                "merge across batches")
        if call.op not in _MERGEABLE:
            raise StreamingUnsupported(f"aggregate {call.op} does not merge")
        base = gk + len(partial_aggs)
        if call.op == "AVG":
            needs_project = True
            s_st = field.stype if field.stype.name in ("DOUBLE", "FLOAT",
                                                       "DECIMAL") else DOUBLE
            partial_aggs.append(AggCall("SUM", list(call.args), False, s_st,
                                        f"{field.name}$sum",
                                        filter_arg=call.filter_arg))
            partial_aggs.append(AggCall("COUNT", list(call.args), False,
                                        BIGINT, f"{field.name}$cnt",
                                        filter_arg=call.filter_arg))
            partial_fields.append(Field(f"{field.name}$sum", s_st))
            partial_fields.append(Field(f"{field.name}$cnt", BIGINT))
            merge_aggs.append(AggCall("SUM", [base], False, s_st,
                                      f"{field.name}$sum"))
            merge_aggs.append(AggCall("$SUM0", [base + 1], False, BIGINT,
                                      f"{field.name}$cnt"))
            post_exprs.append(("avg", base, base + 1, field))
        else:
            merge_op = {"SUM": "SUM", "$SUM0": "$SUM0", "COUNT": "$SUM0",
                        "MIN": "MIN", "MAX": "MAX"}[call.op]
            partial_aggs.append(AggCall(call.op, list(call.args), False,
                                        field.stype, field.name,
                                        filter_arg=call.filter_arg))
            partial_fields.append(Field(field.name, field.stype))
            merge_aggs.append(AggCall(merge_op, [base], False, field.stype,
                                      field.name))
            post_exprs.append(("ref", base, None, field))
    return partial_aggs, partial_fields, merge_aggs, post_exprs, needs_project


def _distinct_dedup_shape(agg: LogicalAggregate) -> Optional[int]:
    """The single argument column index when this aggregate can stream as a
    per-batch dedup: every call is DISTINCT on that one argument, or a
    dedup-invariant MIN/MAX of it.  (Mixed distinct arguments or plain
    SUM/COUNT alongside a DISTINCT cannot share one dedup stream.)"""
    arg: Optional[int] = None
    for call in agg.aggs:
        if call.udaf is not None or not call.args:
            return None
        a = call.args[0]
        if call.distinct:
            if call.op not in ("COUNT", "SUM", "AVG", "MIN", "MAX"):
                return None
        elif call.op not in ("MIN", "MAX"):
            return None
        if call.filter_arg is not None:
            return None
        if arg is None:
            arg = a
        elif arg != a:
            return None
    return arg


# ---------------------------------------------------------------------------
# host-side partial accumulation
# ---------------------------------------------------------------------------

def _host_partial(result: Table) -> tuple:
    """Fetch a partial result to host NOW: streaming's memory bound is one
    batch resident at a time, so partial outputs must not pin device
    buffers across iterations. Returns (names, per-col host tuples).

    The device→host fetch is the ``host_transfer`` fault site: over a
    tunneled TPU it is a network round trip, so transient drops retry with
    backoff (the device buffers stay alive until the fetch lands)."""
    import jax

    def fetch():
        _faults.maybe_fail("host_transfer")
        bufs = []
        for c in result.columns:
            bufs.append(c.data)
            if c.mask is not None:
                bufs.append(c.mask)
        return jax.device_get(bufs) if bufs else []

    host = iter(_res.retry_transient(fetch, site="host_transfer"))
    cols = []
    for c in result.columns:
        data = next(host)
        mask = next(host) if c.mask is not None else None
        cols.append((np.asarray(data), None if mask is None
                     else np.asarray(mask), c.stype, c.dictionary))
    return (list(result.names), cols)


def _partial_bytes(partials: List[tuple]) -> int:
    total = 0
    for _, cols in partials:
        for data, mask, _, _ in cols:
            total += data.nbytes + (mask.nbytes if mask is not None else 0)
    return total


def _concat_host(partials: List[tuple]):
    """Concatenate host partials column-wise; returns (names, cols) in the
    _host_partial layout.  Dictionaries must agree (they do when every
    batch ran the same program over the shared global dictionaries); a
    diverging eager batch triggers a decode + re-encode."""
    from ..table import Column
    import jax.numpy as jnp

    names, first_cols = partials[0]
    ncols = len(first_cols)
    out = []
    for ci in range(ncols):
        per = [p[1][ci] for p in partials]
        stype, d0 = per[0][2], per[0][3]
        same_dict = all(
            d is d0 or (d is not None and d0 is not None
                        and len(d) == len(d0) and (d == d0).all())
            for _, _, _, d in per)
        if not same_dict:
            decoded = np.concatenate([
                d[np.clip(data, 0, len(d) - 1)].astype(object)
                for data, _, _, d in per])
            col = Column.from_numpy(decoded)
            mask_parts = [m if m is not None else np.ones(len(data), bool)
                          for data, m, _, _ in per]
            mask = np.concatenate(mask_parts)
            data = np.asarray(col.data)
            host_mask = np.asarray(col.valid_mask()) & mask
            out.append((data, host_mask if not host_mask.all() else None,
                        col.stype, col.dictionary))
            continue
        data = np.concatenate([data for data, _, _, _ in per])
        if any(m is not None for _, m, _, _ in per):
            mask = np.concatenate(
                [m if m is not None else np.ones(len(dd), bool)
                 for dd, m, _, _ in per])
        else:
            mask = None
        out.append((data, mask, stype, d0))
    return names, out


def _host_cols_to_temp(names, cols, context) -> LogicalTableScan:
    import jax.numpy as jnp

    from ..table import Column

    device_cols = []
    for data, mask, stype, d in cols:
        device_cols.append(Column(jnp.asarray(data), stype,
                                  None if mask is None else jnp.asarray(mask),
                                  d))
    t = Table([f"c{i}" for i in range(len(cols))], device_cols)
    return _register_temp(context, t)


def _dedup_host(names, cols):
    """Row-dedup host partials (NULL-aware): the incremental bound for
    key-set and distinct-dedup streams."""
    if not cols or not len(cols[0][0]):
        return names, cols
    keys = []
    for data, mask, _, _ in cols:
        if data.dtype.kind in "fc":
            # NaN needs its own channel: nan_to_num would merge NaN with 0
            keys.append(np.nan_to_num(data, nan=0.0))
            keys.append(np.isnan(data))
        else:
            keys.append(data)
        keys.append(np.ones(len(data), bool) if mask is None else mask)
    order = np.lexsort(tuple(reversed(keys)))
    stacked = [k[order] for k in keys]
    n = len(order)
    diff = np.zeros(n, dtype=bool)
    diff[0] = True
    for k in stacked:
        diff[1:] |= k[1:] != k[:-1]
    keep = order[diff]
    keep.sort()
    out = []
    for data, mask, stype, d in cols:
        out.append((data[keep], None if mask is None else mask[keep],
                    stype, d))
    return names, out


def _merge_aggregate_on_host(names, cols, gk: int, merge_aggs, group_fields,
                             context) -> LogicalTableScan:
    """Out-of-device-memory final merge: pandas group-by over the host
    partials (the partial algebra is SUM/$SUM0/MIN/MAX only), then a small
    device temp of the merged result."""
    import pandas as pd

    frame = {}
    for i, (data, mask, stype, d) in enumerate(cols):
        if d is not None:
            vals = d[np.clip(data, 0, len(d) - 1)].astype(object)
            s = pd.Series(vals)
            if mask is not None:
                s = s.where(mask, other=None)
        elif data.dtype.kind in "iu":
            # masked integers ride pandas' NULLABLE Int64, never float64:
            # a NaN round-trip would corrupt BIGINT sums above 2^53
            s = pd.Series(data.astype(np.int64), dtype="Int64")
            if mask is not None:
                s[~mask] = pd.NA
        else:
            s = pd.Series(data)
            if mask is not None:
                s = s.where(mask, other=np.nan)
        frame[f"c{i}"] = s
    df = pd.DataFrame(frame)
    key_cols = [f"c{i}" for i in range(gk)]

    def _sum_null(s):
        # SUM over only-NULL partials stays NULL (pandas' default sum -> 0)
        return s.sum(min_count=1)

    agg_map = {}
    for j, call in enumerate(merge_aggs):
        col = f"c{gk + j}"
        agg_map[col] = {"SUM": _sum_null, "$SUM0": "sum", "MIN": "min",
                        "MAX": "max"}[call.op]
    merged = (df.groupby(key_cols, dropna=False, sort=False)
                .agg(agg_map).reset_index())
    from ..table import Column as _C, Table as _T
    from ..types import physical_dtype
    t = _T.from_pandas(merged)
    # restore the partial stypes where the physical representation agrees
    # (pandas widens e.g. DECIMAL-typed f64 to plain float64): downstream
    # reads types off the scan schema AND the columns — keep them aligned
    expected = ([f.stype for f in group_fields]
                + [a.stype for a in merge_aggs])
    fixed = []
    for c, est in zip(t.columns, expected):
        if (c.stype.name != est.name
                and c.data.dtype == physical_dtype(est)):
            c = _C(c.data, est, c.mask, c.dictionary)
        fixed.append(c)
    t = _T(list(t.names), fixed)
    return _register_temp(context, t)


# ---------------------------------------------------------------------------
# batch loop
# ---------------------------------------------------------------------------

def _run_batches(partial_plan: RelNode, source, context,
                 dedup_each_batch: bool = False) -> List[tuple]:
    from .compiled import try_execute_compiled
    from .rel.executor import RelExecutor

    acc: List[tuple] = []
    for bi in range(source.n_batches):
        # per-batch checkpoint: a cancelled/over-deadline query must stop
        # between batches, not grind through the remaining uploads
        _res.check("stream_batch")
        with _tel.span("stream_batch", index=bi):
            table, row_valid = _res.retry_transient(
                lambda: source.batch_table(bi), site="chunked_read")
            _tel.inc("stream_batches")
            _tel.inc("stream_batch_rows", table.num_rows)
            _set_batch_entry(context, table, row_valid)
            result = try_execute_compiled(partial_plan, context)
            if result is None:
                result = RelExecutor(context).execute(partial_plan)
            # fetch the (small, post-aggregate) partial to host NOW: at
            # most one batch stays resident on device — the whole point of
            # streaming
            acc.append(_host_partial(result))
            _tel.annotate(partial_rows=result.num_rows)
        if dedup_each_batch and len(acc) > 1:
            names, cols = _dedup_host(*_concat_host(acc))
            acc = [(names, cols)]
        logger.debug("streamed batch %d/%d -> %d partial rows", bi + 1,
                     source.n_batches, result.num_rows)
    return acc


# ---------------------------------------------------------------------------
# split strategies — each streams ONE subtree and returns (old_subtree,
# replacement node)
# ---------------------------------------------------------------------------

def _stream_aggregate_split(agg: LogicalAggregate, scan, path, source,
                            context) -> RelNode:
    gk = len(agg.group_keys)
    dedup_arg = None
    if any(c.distinct for c in agg.aggs):
        dedup_arg = _distinct_dedup_shape(agg)
        if dedup_arg is None:
            raise StreamingUnsupported(
                "DISTINCT aggregates mixed with non-dedup-invariant calls "
                "do not merge across batches")

    below = _stream_partial_plans(agg.inputs[0], scan, path, context)
    group_fields = agg.schema[:gk]

    if dedup_arg is not None:
        # per-batch dedup of (group keys, argument); the final aggregate's
        # own DISTINCT re-deduplicates across batches
        in_fields = below.schema
        dd_fields = [Field(f.name, f.stype) for f in group_fields]
        dd_fields.append(Field("arg", in_fields[dedup_arg].stype))
        partial_plan = LogicalAggregate(
            input=below, group_keys=list(agg.group_keys) + [dedup_arg],
            aggs=[], schema=dd_fields)
        partials = _run_batches(partial_plan, source, context,
                                dedup_each_batch=True)
        names, cols = _dedup_host(*_concat_host(partials))
        ptmp = _retype(_host_cols_to_temp(names, cols, context), dd_fields)
        final_aggs = [
            AggCall(c.op, [gk], c.distinct, c.stype, c.name)
            for c in agg.aggs]
        return agg, LogicalAggregate(input=ptmp,
                                     group_keys=list(range(gk)),
                                     aggs=final_aggs,
                                     schema=list(agg.schema))

    (partial_aggs, partial_fields, merge_aggs, post_exprs,
     needs_project) = _partial_and_merge_aggs(agg)
    partial_schema = list(group_fields) + partial_fields
    partial_plan = LogicalAggregate(input=below,
                                    group_keys=list(agg.group_keys),
                                    aggs=partial_aggs, schema=partial_schema)

    partials = _run_batches(partial_plan, source, context)

    names, cols = _concat_host(partials)
    merge_schema = list(group_fields) + [
        Field(a.name, a.stype) for a in merge_aggs]
    if gk > 0 and _partial_bytes(partials) > PARTIAL_BYTES_BUDGET:
        # high-cardinality GROUP BY: merging on device would materialize a
        # temp bigger than the budget — merge on host instead (global
        # aggregates have one-row-per-batch partials: device merge always)
        logger.info("streaming: %d partial bytes exceed budget; merging "
                    "on host", _partial_bytes(partials))
        merge = _retype(_merge_aggregate_on_host(
            names, cols, gk, merge_aggs, group_fields, context),
            merge_schema)
        final: RelNode = merge
    else:
        ptmp = _retype(_host_cols_to_temp(names, cols, context),
                       partial_schema)
        final = LogicalAggregate(input=ptmp,
                                 group_keys=list(range(gk)),
                                 aggs=merge_aggs, schema=merge_schema)
    if needs_project:
        exprs = [RexInputRef(i, f.stype) for i, f in enumerate(group_fields)]
        for kind, i, j, field in post_exprs:
            if kind == "ref":
                exprs.append(RexInputRef(i, field.stype))
            else:
                num = RexInputRef(i, merge_schema[i].stype)
                den = RexCall("CAST", [RexInputRef(j, BIGINT)], DOUBLE,
                              info=DOUBLE)
                exprs.append(RexCall("/", [num, den], field.stype))
        final = LogicalProject(input=final, exprs=exprs,
                               schema=list(agg.schema))
    return agg, final


def _stream_topk_split(sort: LogicalSort, scan, path, source,
                       context) -> RelNode:
    keep = (sort.limit or 0) + (sort.offset or 0)
    below = _stream_partial_plans(sort.inputs[0], scan, path, context)
    partial_plan = LogicalSort(input=below, collation=sort.collation,
                               offset=0, limit=keep,
                               schema=list(sort.schema))
    partials = _run_batches(partial_plan, source, context)

    names, cols = _concat_host(partials)
    ptmp = _retype(_host_cols_to_temp(names, cols, context), sort.schema)
    final = LogicalSort(input=ptmp, collation=sort.collation,
                        offset=sort.offset, limit=sort.limit,
                        schema=list(sort.schema))
    return sort, final


def _bucket_ids(cols, keys: List[int], n_buckets: int) -> np.ndarray:
    """FNV-style row hash of the partition-key columns (host numpy).
    String columns hash their dictionary CODES — all batches share the
    global dictionaries (io/chunked.py invariant), so equal values have
    equal codes; floats canonicalize NaN into its own channel."""
    total = len(cols[0][0]) if cols else 0
    if n_buckets <= 1:
        return np.zeros(total, dtype=np.int64)
    h = np.zeros(total, dtype=np.uint64)
    P = np.uint64(1099511628211)
    NAN_SALT = np.uint64(0x9E3779B97F4A7C15)
    for k in keys:
        data, mask, _, _ = cols[k]
        if data.dtype.kind == "f":
            isnan = np.isnan(data)
            # + 0.0 folds -0.0 into +0.0 — the resident engine's key_parts
            # canonicalization groups the two zeros as one partition
            canon = np.where(isnan, 0.0, data).astype(np.float64) + 0.0
            part = canon.view(np.uint64) ^ (isnan.astype(np.uint64)
                                            * NAN_SALT)
        else:
            part = data.astype(np.int64, copy=False).view(np.uint64)
        if mask is not None:
            # data under a NULL slot is arbitrary in this engine (gathers
            # leave garbage there; ops/kernels.py key_parts sentinels it
            # the same way) — canonicalize so every NULL key hashes alike
            part = np.where(mask, part, np.uint64(0))
            h = (h ^ mask.astype(np.uint64)) * P
        h = (h ^ part) * P
    return (h % np.uint64(n_buckets)).astype(np.int64)


def _stream_window_split(win: LogicalWindow, scan, path, source, context):
    """Window over a chunked scan: stream the below-window subtree per
    batch, regroup the (host) rows into hash buckets of the PARTITION BY
    keys, and run the window resident per bucket — every partition lands
    wholly inside one bucket, so any ORDER BY / frame inside it is exact
    (the reference runs windows per partition over partitioned input by
    construction, window.py:207-414 + input_utils/convert.py:38-62).
    Buckets pad to one shared capacity => one compile, N-1 cache hits."""
    common: Optional[set] = None
    for call in win.calls:
        if not call.partition:
            raise StreamingUnsupported(
                "window without PARTITION BY over a chunked table needs the "
                "whole input resident at once")
        common = (set(call.partition) if common is None
                  else common & set(call.partition))
    if not common:
        raise StreamingUnsupported(
            "window calls share no PARTITION BY column to regroup on")
    keys = sorted(common)

    below = _stream_partial_plans(win.inputs[0], scan, path, context)
    # the bare below-window subtree per batch: _materialize compacts
    # padding, so host partials hold exactly the real rows
    partials = _run_batches(below, source, context)
    names, cols = _concat_host(partials)
    total = len(cols[0][0]) if cols else 0

    n_buckets = max(1, -(-total // max(int(source.batch_rows), 1)))
    ids = _bucket_ids(cols, keys, n_buckets)
    # one stable argsort + boundary search, not an O(rows x buckets) scan
    order = np.argsort(ids, kind="stable")
    bounds = np.searchsorted(ids[order], np.arange(n_buckets + 1))
    selections = [order[bounds[b]:bounds[b + 1]]
                  for b in range(n_buckets) if bounds[b] < bounds[b + 1]]
    if not selections:
        selections = [np.arange(0)]
    cap = max(len(s) for s in selections)
    if cap > 2 * int(source.batch_rows):
        # hash skew / one giant partition: the largest bucket (and the
        # shared capacity every bucket pads to) exceeds the streaming batch
        # size, weakening the out-of-core bound to ~cap resident rows.
        # Correctness is unaffected (partitions must stay whole, so the
        # bound genuinely cannot be tighter than the largest partition) —
        # but it must never weaken SILENTLY (no-silent-caps policy).
        logger.warning(
            "streaming window: partition skew — largest bucket %d rows vs "
            "batch_rows %d; device working set for the window step is "
            "~%.1fx the configured bound", cap, int(source.batch_rows),
            cap / max(int(source.batch_rows), 1))

    import jax.numpy as jnp

    from ..table import Column as _Col

    fields = [Field(f.name, f.stype) for f in below.schema]
    batch_scan = LogicalTableScan(schema_name=STREAM_SCHEMA,
                                  table_name=BATCH_TABLE, schema=fields)
    win_plan = LogicalWindow(input=batch_scan, calls=list(win.calls),
                             schema=list(win.schema))

    out_parts: List[tuple] = []
    for sel in selections:
        pad = cap - len(sel)
        bcols = []
        for data, mask, stype, d in cols:
            bd = data[sel]
            bm = mask[sel] if mask is not None else None
            if pad:
                bd = np.concatenate([bd, np.zeros(pad, dtype=bd.dtype)])
                if bm is not None:
                    bm = np.concatenate([bm, np.zeros(pad, dtype=bool)])
            bcols.append(_Col(jnp.asarray(bd), stype,
                              None if bm is None else jnp.asarray(bm), d))
        btable = Table(list(names), bcols)
        # ALWAYS pass row_valid: the compiled-program cache keys on its
        # presence, so the one full (pad==0) bucket would otherwise trace
        # a second program — a second multi-minute compile over the tunnel
        row_valid = jnp.arange(cap) < len(sel)
        with _tel.span("stream_batch", bucket_rows=len(sel)):
            _set_batch_entry(context, btable, row_valid)
            result = _run_resident(win_plan, context)
            _tel.inc("stream_batches")
            out_parts.append(_host_partial(result))
        logger.debug("window bucket -> %d rows", result.num_rows)

    out_names, out_cols = _concat_host(out_parts)
    if _partial_bytes(out_parts) <= PARTIAL_BYTES_BUDGET:
        tmp = _retype(_host_cols_to_temp(out_names, out_cols, context),
                      win.schema)
        return win, tmp
    # table-sized window output: re-register as a CHUNKED source so the
    # strategies above the window keep streaming instead of materializing
    from ..io.chunked import ChunkedSource

    br = max(int(source.batch_rows), 1)
    out_total = len(out_cols[0][0]) if out_cols else 0
    batches = []
    for s0 in range(0, max(out_total, 1), br):
        batches.append([(data[s0:s0 + br],
                         None if mask is None else mask[s0:s0 + br])
                        for data, mask, _, _ in out_cols])
    src = ChunkedSource([f"c{i}" for i in range(len(out_cols))],
                        [f.stype for f in win.schema],
                        [d for _, _, _, d in out_cols],
                        batches, out_total, br)
    if STREAM_SCHEMA not in context.schema:
        context.create_schema(STREAM_SCHEMA)
    _tmp_counter[0] += 1
    name = f"t{_tmp_counter[0]}"
    context.schema[STREAM_SCHEMA].tables[name] = TableEntry(
        table=src.schema_table(), chunked=src)
    # sanitized c{i} names on BOTH the source and the scan: downstream
    # nodes reference ordinals, and the executor matches scan fields to
    # table columns by name (same contract as _register_temp)
    return win, LogicalTableScan(
        schema_name=STREAM_SCHEMA, table_name=name,
        schema=[Field(f"c{i}", f.stype)
                for i, f in enumerate(win.schema)])


def _semi_build_refs(join: LogicalJoin) -> Optional[List[int]]:
    """Right-side column indices the SEMI/ANTI join condition references,
    or None when the condition has a shape the key-set rewrite can't remap."""
    nl = len(join.left.schema)
    refs: List[int] = []
    ok = [True]

    def walk(rex):
        if isinstance(rex, RexInputRef):
            if rex.index >= nl and (rex.index - nl) not in refs:
                refs.append(rex.index - nl)
            return
        if isinstance(rex, RexCall):
            for o in rex.operands:
                walk(o)
            return
        from ..plan.nodes import RexLiteral
        if isinstance(rex, RexLiteral):
            return
        ok[0] = False

    if join.condition is not None:
        walk(join.condition)
    if not ok[0]:
        return None
    return sorted(refs)


def _remap_condition(rex, nl: int, refs: List[int]):
    """Rewrite right-side input refs to the key-set table's ordinals."""
    if isinstance(rex, RexInputRef):
        if rex.index >= nl:
            return RexInputRef(nl + refs.index(rex.index - nl), rex.stype)
        return rex
    if isinstance(rex, RexCall):
        return RexCall(rex.op, [_remap_condition(o, nl, refs)
                                for o in rex.operands], rex.stype,
                       info=getattr(rex, "info", None))
    return rex


def _stream_keyset_split(join: LogicalJoin, scan, source, context):
    """SEMI/ANTI with the chunked scan on the BUILD (right) side: stream the
    build as a dedup of the condition-referenced columns; existence
    semantics are preserved under dedup."""
    refs = _semi_build_refs(join)
    if refs is None:
        raise StreamingUnsupported(
            "semi/anti condition too complex for the key-set rewrite")
    right = join.right
    sub_path = _path_to(right, scan)
    below = _stream_partial_plans(right, scan, sub_path, context)
    # dedup of the referenced columns, per batch
    dd_fields = [Field(f"k{i}", right.schema[r].stype)
                 for i, r in enumerate(refs)]
    partial_plan = LogicalAggregate(input=below, group_keys=list(refs),
                                    aggs=[], schema=dd_fields)
    partials = _run_batches(partial_plan, source, context,
                            dedup_each_batch=True)
    names, cols = _dedup_host(*_concat_host(partials))
    ptmp = _retype(_host_cols_to_temp(names, cols, context), dd_fields)
    nl = len(join.left.schema)
    new_cond = (None if join.condition is None
                else _remap_condition(join.condition, nl, refs))
    new_join = LogicalJoin(left=join.left, right=ptmp, condition=new_cond,
                           join_type=join.join_type,
                           schema=list(join.schema))
    if hasattr(join, "null_aware"):
        # NOT IN's null-aware anti semantics survive the key-set rewrite:
        # a NULL key among the deduped build rows poisons exactly as the
        # full build side would
        new_join.null_aware = join.null_aware  # type: ignore[attr-defined]
    return join, new_join


# ---------------------------------------------------------------------------
# the iterative lowering loop
# ---------------------------------------------------------------------------

def _find_split(plan: RelNode, scan: LogicalTableScan, context):
    """(kind, node, path) for the innermost streamable split above ``scan``
    whose subtree contains no OTHER chunked scan."""
    path = _path_to(plan, scan)
    if path is None:
        raise StreamingUnsupported(
            "chunked table referenced inside a scalar subquery cannot "
            "stream; materialize the subquery first")
    # innermost-first: walk up from the scan
    for node in reversed(path[:-1]):
        if isinstance(node, LogicalWindow):
            if len(_chunked_scans(node, context)) == 1:
                return "window", node, path
        elif isinstance(node, LogicalAggregate):
            if len(_chunked_scans(node, context)) == 1:
                return "agg", node, path
        elif isinstance(node, LogicalSort) and node.limit is not None:
            if len(_chunked_scans(node, context)) == 1:
                return "topk", node, path
        elif (isinstance(node, LogicalJoin)
              and node.join_type in ("SEMI", "ANTI")):
            right_has = _path_to(node.right, scan) is not None
            if right_has and len(_chunked_scans(node.right, context)) == 1:
                return "keyset", node, path
        elif isinstance(node, LogicalJoin):
            # TWO chunked sides: no single-scan strategy applies — the
            # grace-hash partitioned join (physical/morsel.py) does,
            # when spilling is enabled and an equi-key exists
            from . import morsel as _morsel
            if _morsel.grace_applicable(node, context):
                return "grace", node, path
    raise StreamingUnsupported(
        "no aggregate or LIMIT above the chunked scan — the full result "
        "would be as large as the table; add a GROUP BY or LIMIT")


def _rewrite_rex_subqueries(rex, context):
    from ..plan.nodes import RexScalarSubquery

    if isinstance(rex, RexScalarSubquery):
        if plan_references_chunked(rex.plan, context):
            return RexScalarSubquery(_lower_chunked(rex.plan, context),
                                     rex.stype)
        return rex
    if isinstance(rex, RexCall):
        ops = [_rewrite_rex_subqueries(o, context) for o in rex.operands]
        if all(a is b for a, b in zip(ops, rex.operands)):
            return rex
        return RexCall(rex.op, ops, rex.stype,
                       info=getattr(rex, "info", None))
    return rex


def _lower_subqueries(plan: RelNode, context) -> RelNode:
    """Chunked scans hidden inside scalar-subquery rex plans lower
    recursively (TPC-H Q15: WHERE total = (SELECT MAX(...) FROM revenue)
    with revenue built over chunked lineitem)."""
    new_inputs = [_lower_subqueries(i, context) for i in plan.inputs]
    if any(a is not b for a, b in zip(new_inputs, plan.inputs)):
        plan = plan.with_inputs(new_inputs)
    if isinstance(plan, LogicalProject):
        exprs = [_rewrite_rex_subqueries(e, context) for e in plan.exprs]
        if any(a is not b for a, b in zip(exprs, plan.exprs)):
            plan = LogicalProject(input=plan.input, exprs=exprs,
                                  schema=plan.schema)
    elif isinstance(plan, LogicalFilter) and plan.condition is not None:
        cond = _rewrite_rex_subqueries(plan.condition, context)
        if cond is not plan.condition:
            plan = LogicalFilter(input=plan.input, condition=cond,
                                 schema=plan.schema)
    elif isinstance(plan, LogicalJoin) and plan.condition is not None:
        cond = _rewrite_rex_subqueries(plan.condition, context)
        if cond is not plan.condition:
            plan = plan.with_inputs([plan.left, plan.right])
            plan.condition = cond
    return plan


def _lower_chunked(plan: RelNode, context) -> RelNode:
    """Rewrite until no chunked scans remain (the iterative loop)."""
    for _ in range(16):  # bound: each iteration removes >= 1 chunked scan
        plan = _lower_subqueries(plan, context)
        scans = _chunked_scans(plan, context)
        if not scans:
            return plan
        last_err = None
        replaced = False
        for scan in scans:
            entry = context.schema[scan.schema_name].tables[scan.table_name]
            source = entry.chunked
            try:
                kind, node, path = _find_split(plan, scan, context)
                if kind == "agg":
                    old, new = _stream_aggregate_split(
                        node, scan, path, source, context)
                elif kind == "topk":
                    old, new = _stream_topk_split(node, scan, path,
                                                  source, context)
                elif kind == "window":
                    old, new = _stream_window_split(node, scan, path,
                                                    source, context)
                elif kind == "grace":
                    from . import morsel as _morsel
                    old, new = _morsel.grace_join_split(node, context)
                else:
                    old, new = _stream_keyset_split(node, scan, source,
                                                    context)
            except StreamingUnsupported as e:
                last_err = e
                continue
            plan = _replace(plan, old, new)
            replaced = True
            break
        if not replaced:
            raise last_err or StreamingUnsupported(
                "no streamable split found")
    raise StreamingUnsupported("chunked lowering did not converge")


def execute_streaming(plan: RelNode, context) -> Table:
    """Lower a plan referencing chunked tables by iterative subtree
    streaming, then run the rewritten (chunk-free) plan resident.

    Serialized under a module lock: the executor stages temps and the
    shared ``__batch__`` entry in the per-context ``__stream__`` schema,
    and two interleaved queries would clobber each other's entries (the
    loser dies on a KeyError mid-plan — or worse, reads the other
    query's batch).  Streaming queries are whole-table scans fighting
    for the same HBM anyway; serializing them costs little.  The depth
    counter keeps a nested streaming execution (e.g. a lazy view's plan
    executed mid-lowering on the same thread) from popping the outer
    query's temps: only the outermost frame cleans up."""
    with _EXEC_LOCK:
        key = id(context)
        _exec_depth[key] = _exec_depth.get(key, 0) + 1
        try:
            lowered = _lower_chunked(plan, context)
            result = _run_resident(lowered, context)
        finally:
            _exec_depth[key] -= 1
            if _exec_depth[key] == 0:
                del _exec_depth[key]
                _cleanup(context)
    # temp-table scans carry sanitized column names (c0, c1, ...); the
    # user-visible names are the plan root's schema, always
    return result.with_names([f.name for f in plan.schema])
