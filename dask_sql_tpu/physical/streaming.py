"""Streaming (out-of-HBM) execution over chunked tables.

The reference runs every query out-of-core by construction (partitioned dask
dataframes, input_utils/convert.py:38-62).  Here the compiled whole-plan-jit
executor wants resident device tables, so tables bigger than HBM register as
``ChunkedSource`` (io/chunked.py) and this module executes plans over them
in the classic two-phase shape:

  1. the plan is SPLIT at the lowest aggregate (or top-k sort) above the
     chunked scan: everything below runs PER BATCH through the ordinary
     compiled pipeline (same shapes + shared dictionaries => one compile,
     N-1 program-cache hits), everything above runs once on the merged
     partials;
  2. partial aggregates merge by algebra: SUM/$SUM0 -> SUM, COUNT -> SUM,
     MIN/MAX -> MIN/MAX, AVG -> (SUM, COUNT) partials + a final division;
     top-k merges as top-k of concatenated per-batch top-k;
  3. joins on the streamed path keep the build (resident) side fixed:
     subtrees not containing the chunked scan are materialized ONCE into
     temp tables and reused across batches (build-side resident,
     probe-side streamed).

Plans outside this shape (two chunked scans, chunked on the NULL-extended
side of an outer join, distinct/custom aggregates, global sorts without
LIMIT) raise ``StreamingUnsupported`` with a reason — never a silent wrong
answer on schema stubs.
"""
from __future__ import annotations

import logging
from typing import List, Optional, Tuple

import numpy as np

from ..datacontainer import TableEntry
from ..plan.nodes import (
    AggCall, Field, LogicalAggregate, LogicalFilter, LogicalJoin,
    LogicalProject, LogicalSort, LogicalTableScan, RelNode, RexCall,
    RexInputRef,
)
from ..table import Table
from ..types import BIGINT, DOUBLE

logger = logging.getLogger(__name__)

STREAM_SCHEMA = "__stream__"
BATCH_TABLE = "batch"

_MERGEABLE = {"SUM", "$SUM0", "COUNT", "MIN", "MAX", "AVG"}


class StreamingUnsupported(RuntimeError):
    """Plan shape the streaming executor cannot run out-of-core."""


# ---------------------------------------------------------------------------
# plan inspection
# ---------------------------------------------------------------------------

def _chunked_scans(plan: RelNode, context) -> List[LogicalTableScan]:
    out = []

    def walk(rel: RelNode):
        if isinstance(rel, LogicalTableScan):
            entry = context.schema[rel.schema_name].tables.get(rel.table_name)
            if entry is not None and getattr(entry, "chunked", None) is not None:
                out.append(rel)
            return
        for i in rel.inputs:
            walk(i)
        # scalar-subquery plans hide extra scans inside rex trees
        from ..plan.nodes import RexScalarSubquery

        def walk_rex(rex):
            if isinstance(rex, RexScalarSubquery):
                walk(rex.plan)
            for o in getattr(rex, "operands", []) or []:
                walk_rex(o)

        if isinstance(rel, LogicalProject):
            for e in rel.exprs:
                walk_rex(e)
        elif isinstance(rel, LogicalFilter):
            walk_rex(rel.condition)
        elif isinstance(rel, LogicalJoin) and rel.condition is not None:
            walk_rex(rel.condition)

    walk(plan)
    return out


def plan_references_chunked(plan: RelNode, context) -> bool:
    return bool(_chunked_scans(plan, context))


def _path_to(plan: RelNode, target: RelNode) -> Optional[List[RelNode]]:
    """Nodes from root to target (inclusive), by identity."""
    if plan is target:
        return [plan]
    for i in plan.inputs:
        sub = _path_to(i, target)
        if sub is not None:
            return [plan] + sub
    return None


def _replace(plan: RelNode, old: RelNode, new: RelNode) -> RelNode:
    if plan is old:
        return new
    if not plan.inputs:
        return plan
    return plan.with_inputs([_replace(i, old, new) for i in plan.inputs])


# ---------------------------------------------------------------------------
# execution
# ---------------------------------------------------------------------------

def _run_resident(plan: RelNode, context) -> Table:
    from .compiled import try_execute_compiled
    from .rel.executor import RelExecutor

    result = try_execute_compiled(plan, context)
    if result is None:
        result = RelExecutor(context).execute(plan)
    return result


_tmp_counter = [0]


def _register_temp(context, table: Table, row_valid=None) -> LogicalTableScan:
    """Register a materialized table under __stream__ and return its scan."""
    if STREAM_SCHEMA not in context.schema:
        context.create_schema(STREAM_SCHEMA)
    _tmp_counter[0] += 1
    name = f"t{_tmp_counter[0]}"
    # intermediate schemas may carry duplicate/empty names; ordinals are what
    # matter downstream, so names are sanitized for catalog registration
    names = [f"c{i}" for i in range(table.num_columns)]
    table = table.with_names(names)
    context.schema[STREAM_SCHEMA].tables[name] = TableEntry(
        table=table, row_valid=row_valid)
    fields = [Field(n, c.stype) for n, c in zip(names, table.columns)]
    return LogicalTableScan(schema_name=STREAM_SCHEMA, table_name=name,
                            schema=fields)


def _set_batch_entry(context, table: Table, row_valid) -> None:
    if STREAM_SCHEMA not in context.schema:
        context.create_schema(STREAM_SCHEMA)
    context.schema[STREAM_SCHEMA].tables[BATCH_TABLE] = TableEntry(
        table=table, row_valid=row_valid)


def _cleanup(context) -> None:
    context.schema.pop(STREAM_SCHEMA, None)


def _check_join_streamable(join: LogicalJoin, chunked_on_left: bool) -> None:
    jt = join.join_type
    ok = (jt == "INNER"
          or (jt in ("LEFT", "SEMI", "ANTI") and chunked_on_left)
          or (jt == "RIGHT" and not chunked_on_left))
    if not ok:
        raise StreamingUnsupported(
            f"{jt} join with the chunked table on the NULL-extended side "
            "cannot stream (every build row must see all probe rows)")


def _stream_partial_plans(split: RelNode, scan: LogicalTableScan,
                          path: List[RelNode], context) -> RelNode:
    """The per-batch subtree: split.input with (a) the chunked scan replaced
    by the batch scan and (b) off-path join subtrees pre-materialized."""
    path_ids = {id(p) for p in path}

    def rebuild(rel: RelNode) -> RelNode:
        if rel is scan:
            entry = context.schema[scan.schema_name].tables[scan.table_name]
            fields = list(scan.schema)
            return LogicalTableScan(schema_name=STREAM_SCHEMA,
                                    table_name=BATCH_TABLE, schema=fields)
        if id(rel) not in path_ids:
            # off the streamed path: resident — materialize once
            if isinstance(rel, LogicalTableScan):
                e = context.schema[rel.schema_name].tables[rel.table_name]
                if getattr(e, "chunked", None) is not None:
                    raise StreamingUnsupported(
                        "more than one chunked table in the plan")
                return rel
            t = _run_resident(rel, context)
            tmp = _register_temp(context, t)
            # keep this subtree's field stypes (names are sanitized)
            tmp = LogicalTableScan(
                schema_name=tmp.schema_name, table_name=tmp.table_name,
                schema=[Field(f2.name, f1.stype)
                        for f1, f2 in zip(rel.schema, tmp.schema)])
            return tmp
        if isinstance(rel, LogicalJoin):
            left_on = any(id(rel.left) == id(p) for p in path) or rel.left is scan
            _check_join_streamable(rel, chunked_on_left=left_on)
        return rel.with_inputs([rebuild(i) for i in rel.inputs])

    return rebuild(split.inputs[0] if not isinstance(split, LogicalTableScan)
                   else split)


def _partial_and_merge_aggs(agg: LogicalAggregate):
    """(partial_aggs, partial_fields, merge_aggs, post_exprs, needs_project)

    Partial layout: one column per non-AVG call, (sum, count) for AVG.
    Merge layout mirrors the partial layout; post_exprs map the merged
    columns back to agg.schema (the AVG division happens here).
    """
    gk = len(agg.group_keys)
    partial_aggs: List[AggCall] = []
    partial_fields: List[Field] = []
    merge_aggs: List[AggCall] = []
    post_exprs: List = []
    needs_project = False
    agg_fields = agg.schema[gk:]
    for call, field in zip(agg.aggs, agg_fields):
        if call.udaf is not None or call.distinct:
            raise StreamingUnsupported(
                f"{'DISTINCT ' if call.distinct else ''}{call.op} does not "
                "merge across batches")
        if call.op not in _MERGEABLE:
            raise StreamingUnsupported(f"aggregate {call.op} does not merge")
        base = gk + len(partial_aggs)
        if call.op == "AVG":
            needs_project = True
            s_st = field.stype if field.stype.name in ("DOUBLE", "FLOAT",
                                                       "DECIMAL") else DOUBLE
            partial_aggs.append(AggCall("SUM", list(call.args), False, s_st,
                                        f"{field.name}$sum",
                                        filter_arg=call.filter_arg))
            partial_aggs.append(AggCall("COUNT", list(call.args), False,
                                        BIGINT, f"{field.name}$cnt",
                                        filter_arg=call.filter_arg))
            partial_fields.append(Field(f"{field.name}$sum", s_st))
            partial_fields.append(Field(f"{field.name}$cnt", BIGINT))
            merge_aggs.append(AggCall("SUM", [base], False, s_st,
                                      f"{field.name}$sum"))
            merge_aggs.append(AggCall("$SUM0", [base + 1], False, BIGINT,
                                      f"{field.name}$cnt"))
            post_exprs.append(("avg", base, base + 1, field))
        else:
            merge_op = {"SUM": "SUM", "$SUM0": "$SUM0", "COUNT": "$SUM0",
                        "MIN": "MIN", "MAX": "MAX"}[call.op]
            partial_aggs.append(AggCall(call.op, list(call.args), False,
                                        field.stype, field.name,
                                        filter_arg=call.filter_arg))
            partial_fields.append(Field(field.name, field.stype))
            merge_aggs.append(AggCall(merge_op, [base], False, field.stype,
                                      field.name))
            post_exprs.append(("ref", base, None, field))
    return partial_aggs, partial_fields, merge_aggs, post_exprs, needs_project


def _host_partial(result: Table) -> tuple:
    """Fetch a partial result to host NOW: streaming's memory bound is one
    batch resident at a time, so partial outputs must not pin device
    buffers across iterations. Returns (names, per-col host tuples)."""
    import jax

    bufs = []
    for c in result.columns:
        bufs.append(c.data)
        if c.mask is not None:
            bufs.append(c.mask)
    host = iter(jax.device_get(bufs) if bufs else [])
    cols = []
    for c in result.columns:
        data = next(host)
        mask = next(host) if c.mask is not None else None
        cols.append((np.asarray(data), None if mask is None
                     else np.asarray(mask), c.stype, c.dictionary))
    return (list(result.names), cols)


def _concat_partials_to_temp(partials: List[tuple], context
                             ) -> LogicalTableScan:
    """Concatenate host partial results into one temp device table,
    preserving stypes and dictionaries (all batches ran the same program
    over the same shared dictionaries, so per-column dictionaries agree —
    verified, with a re-encode fallback if an eager batch diverged)."""
    import jax.numpy as jnp

    from ..table import Column

    names, first_cols = partials[0]
    ncols = len(first_cols)
    cols = []
    for ci in range(ncols):
        per = [p[1][ci] for p in partials]
        stype, d0 = per[0][2], per[0][3]
        same_dict = all(
            d is d0 or (d is not None and d0 is not None
                        and len(d) == len(d0) and (d == d0).all())
            for _, _, _, d in per)
        if not same_dict:
            # decode + re-encode under a fresh unified dictionary
            decoded = np.concatenate([
                d[np.clip(data, 0, len(d) - 1)].astype(object)
                for data, _, _, d in per])
            col = Column.from_numpy(decoded)
            mask_parts = [m if m is not None else np.ones(len(data), bool)
                          for data, m, _, _ in per]
            if any(p[1] is not None for p in per):
                col = col.with_mask(jnp.asarray(np.concatenate(mask_parts))
                                    & col.valid_mask())
            cols.append(col)
            continue
        data = np.concatenate([data for data, _, _, _ in per])
        if any(m is not None for _, m, _, _ in per):
            mask = np.concatenate(
                [m if m is not None else np.ones(len(dd), bool)
                 for dd, m, _, _ in per])
            mask = jnp.asarray(mask)
        else:
            mask = None
        cols.append(Column(jnp.asarray(data), stype, mask, d0))
    t = Table([f"c{i}" for i in range(ncols)], cols)
    return _register_temp(context, t)


def execute_streaming(plan: RelNode, context) -> Table:
    """Run a plan that references exactly one chunked table."""
    scans = _chunked_scans(plan, context)
    if len(scans) != 1:
        raise StreamingUnsupported(
            f"{len(scans)} chunked scans in one plan (exactly 1 supported; "
            "correlated subqueries over the chunked table re-scan it)")
    scan = scans[0]
    entry = context.schema[scan.schema_name].tables[scan.table_name]
    source = entry.chunked

    path = _path_to(plan, scan)
    if path is None:
        # the scan lives inside a scalar-subquery rex plan, which rel-input
        # traversal cannot reach (it would re-scan the table per outer row)
        raise StreamingUnsupported(
            "chunked table referenced inside a scalar subquery cannot "
            "stream; materialize the subquery first")
    # lowest aggregate above the scan; or a LIMIT-ed sort (top-k)
    split: Optional[RelNode] = None
    for node in reversed(path[:-1]):
        if isinstance(node, LogicalAggregate):
            split = node
            break
        if isinstance(node, LogicalSort) and node.limit is not None:
            split = node
            break
    if split is None:
        raise StreamingUnsupported(
            "no aggregate or LIMIT above the chunked scan — the full result "
            "would be as large as the table; add a GROUP BY or LIMIT")

    try:
        if isinstance(split, LogicalAggregate):
            result = _stream_aggregate(plan, split, scan, path, source,
                                       context)
        else:
            result = _stream_topk(plan, split, scan, path, source, context)
    finally:
        _cleanup(context)
    # temp-table scans carry sanitized column names (c0, c1, ...); the
    # user-visible names are the plan root's schema, always
    return result.with_names([f.name for f in plan.schema])


def _run_batches(partial_plan: RelNode, source, context) -> List[tuple]:
    from .compiled import try_execute_compiled
    from .rel.executor import RelExecutor

    out = []
    for bi in range(source.n_batches):
        table, row_valid = source.batch_table(bi)
        _set_batch_entry(context, table, row_valid)
        result = try_execute_compiled(partial_plan, context)
        if result is None:
            result = RelExecutor(context).execute(partial_plan)
        # fetch the (small, post-aggregate) partial to host NOW: at most one
        # batch stays resident on device — the whole point of streaming
        out.append(_host_partial(result))
        logger.debug("streamed batch %d/%d -> %d partial rows", bi + 1,
                     source.n_batches, result.num_rows)
    return out


def _stream_aggregate(plan, agg: LogicalAggregate, scan, path, source,
                      context) -> Table:
    gk = len(agg.group_keys)
    (partial_aggs, partial_fields, merge_aggs, post_exprs,
     needs_project) = _partial_and_merge_aggs(agg)

    below = _stream_partial_plans(agg, scan, path, context)
    group_fields = agg.schema[:gk]
    partial_schema = list(group_fields) + partial_fields
    partial_plan = LogicalAggregate(input=below,
                                    group_keys=list(agg.group_keys),
                                    aggs=partial_aggs, schema=partial_schema)

    partials = _run_batches(partial_plan, source, context)

    ptmp = _concat_partials_to_temp(partials, context)
    ptmp = LogicalTableScan(
        schema_name=ptmp.schema_name, table_name=ptmp.table_name,
        schema=[Field(f2.name, f1.stype)
                for f1, f2 in zip(partial_schema, ptmp.schema)])

    merge_schema = list(group_fields) + [
        Field(a.name, a.stype) for a in merge_aggs]
    merge = LogicalAggregate(input=ptmp,
                             group_keys=list(range(gk)),
                             aggs=merge_aggs, schema=merge_schema)
    final: RelNode = merge
    if needs_project:
        exprs = [RexInputRef(i, f.stype) for i, f in enumerate(group_fields)]
        for kind, i, j, field in post_exprs:
            if kind == "ref":
                exprs.append(RexInputRef(i, field.stype))
            else:
                num = RexInputRef(i, merge_schema[i].stype)
                den = RexCall("CAST", [RexInputRef(j, BIGINT)], DOUBLE,
                              info=DOUBLE)
                exprs.append(RexCall("/", [num, den], field.stype))
        final = LogicalProject(input=merge, exprs=exprs,
                               schema=list(agg.schema))

    rewritten = _replace(plan, agg, final)
    return _run_resident(rewritten, context)


def _stream_topk(plan, sort: LogicalSort, scan, path, source,
                 context) -> Table:
    keep = (sort.limit or 0) + (sort.offset or 0)
    below = _stream_partial_plans(sort, scan, path, context)
    partial_plan = LogicalSort(input=below, collation=sort.collation,
                               offset=0, limit=keep,
                               schema=list(sort.schema))
    partials = _run_batches(partial_plan, source, context)

    ptmp = _concat_partials_to_temp(partials, context)
    ptmp = LogicalTableScan(
        schema_name=ptmp.schema_name, table_name=ptmp.table_name,
        schema=[Field(f2.name, f1.stype)
                for f1, f2 in zip(sort.schema, ptmp.schema)])
    final = LogicalSort(input=ptmp, collation=sort.collation,
                        offset=sort.offset, limit=sort.limit,
                        schema=list(sort.schema))
    rewritten = _replace(plan, sort, final)
    return _run_resident(rewritten, context)
