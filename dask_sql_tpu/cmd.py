def cmd_loop(*a, **k):
    raise NotImplementedError
