def run_server(*a, **k):
    raise NotImplementedError
