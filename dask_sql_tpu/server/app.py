"""Presto-wire-protocol HTTP server.

Re-implements the reference server (/root/reference/dask_sql/server/app.py):
``POST /v1/statement`` submits SQL, ``GET /v1/status/{uuid}`` polls,
``DELETE /v1/cancel/{uuid}`` cancels, ``GET /v1/empty`` returns an empty
result — with async execution via a thread pool + futures registry mirroring
the reference's dask-client future_list (app.py:69-95).

Built on stdlib http.server (FastAPI/uvicorn are not in this image); the wire
format matches the reference's responses.py so presto/trino clients work.
"""
from __future__ import annotations

import json
import logging
import threading
import uuid as uuid_mod
from concurrent.futures import Future, ThreadPoolExecutor
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

logger = logging.getLogger(__name__)


# ---------------------------------------------------------------------------
# presto wire responses (reference server/responses.py)
# ---------------------------------------------------------------------------

def _stats(state: str) -> dict:
    """Placeholder stats, parity with reference responses.py:11-49."""
    return {
        "state": state, "queued": False, "scheduled": False, "nodes": 0,
        "totalSplits": 0, "queuedSplits": 0, "runningSplits": 0,
        "completedSplits": 0, "cpuTimeMillis": 0, "wallTimeMillis": 0,
        "queuedTimeMillis": 0, "elapsedTimeMillis": 0, "processedRows": 0,
        "processedBytes": 0, "peakMemoryBytes": 0,
    }


_TYPE_MAP = {
    "BOOLEAN": "boolean", "TINYINT": "tinyint", "SMALLINT": "smallint",
    "INTEGER": "integer", "BIGINT": "bigint", "FLOAT": "real",
    "DOUBLE": "double", "DECIMAL": "decimal", "VARCHAR": "varchar",
    "CHAR": "char", "DATE": "date", "TIMESTAMP": "timestamp",
    "TIME": "time", "INTERVAL_DAY_TIME": "interval day to second",
    "INTERVAL_YEAR_MONTH": "interval year to month", "NULL": "unknown",
}


def _columns_payload(table) -> list:
    cols = []
    for name, col in zip(table.names, table.columns):
        t = _TYPE_MAP.get(col.stype.name, "varchar")
        cols.append({
            "name": name, "type": t,
            "typeSignature": {"rawType": t, "arguments": []},
        })
    return cols


def _data_payload(table) -> list:
    rows = []
    for row in table.to_pylist():
        out = []
        for v in row:
            if hasattr(v, "isoformat"):
                v = v.isoformat(sep=" ") if hasattr(v, "date") else v.isoformat()
            elif hasattr(v, "item"):
                v = v.item()
            out.append(v)
        rows.append(out)
    return rows


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------

class _AppState:
    def __init__(self, context):
        self.context = context
        self.pool = ThreadPoolExecutor(max_workers=4)
        self.future_list: Dict[str, Future] = {}
        self.lock = threading.Lock()


def _make_handler(state: _AppState, base_url: str):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):
            logger.debug("server: " + fmt, *args)

        def _send(self, code: int, payload: Optional[dict]):
            body = json.dumps(payload or {}).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        # GET /v1/empty  |  GET /v1/status/{uuid}
        def do_GET(self):
            if self.path.rstrip("/") == "/v1/empty":
                self._send(200, {
                    "id": "empty", "infoUri": base_url,
                    "columns": [], "data": [], "stats": _stats("FINISHED"),
                })
                return
            if self.path.startswith("/v1/status/"):
                uid = self.path[len("/v1/status/"):].strip("/")
                fut = state.future_list.get(uid)
                if fut is None:
                    self._send(404, _error_payload("Unknown query id", uid))
                    return
                if not fut.done():
                    self._send(200, {
                        "id": uid, "infoUri": base_url,
                        "nextUri": f"{base_url}/v1/status/{uid}",
                        "partialCancelUri": f"{base_url}/v1/cancel/{uid}",
                        "stats": _stats("RUNNING"),
                    })
                    return
                try:
                    table = fut.result()
                except Exception as e:
                    del state.future_list[uid]
                    self._send(200, _error_payload(str(e), uid))
                    return
                del state.future_list[uid]
                payload = {
                    "id": uid, "infoUri": base_url, "stats": _stats("FINISHED"),
                }
                if table is not None and table.num_columns:
                    payload["columns"] = _columns_payload(table)
                    payload["data"] = _data_payload(table)
                self._send(200, payload)
                return
            self._send(404, {"error": "not found"})

        # POST /v1/statement
        def do_POST(self):
            if self.path.rstrip("/") != "/v1/statement":
                self._send(404, {"error": "not found"})
                return
            length = int(self.headers.get("Content-Length", 0))
            sql = self.rfile.read(length).decode()
            uid = str(uuid_mod.uuid4())
            fut = state.pool.submit(state.context.sql, sql)
            state.future_list[uid] = fut
            self._send(200, {
                "id": uid, "infoUri": base_url,
                "nextUri": f"{base_url}/v1/status/{uid}",
                "partialCancelUri": f"{base_url}/v1/cancel/{uid}",
                "stats": _stats("QUEUED"),
            })

        # DELETE /v1/cancel/{uuid}
        def do_DELETE(self):
            if self.path.startswith("/v1/cancel/"):
                uid = self.path[len("/v1/cancel/"):].strip("/")
                fut = state.future_list.pop(uid, None)
                if fut is None:
                    self._send(404, _error_payload("Unknown query id", uid))
                    return
                fut.cancel()
                self._send(200, None)
                return
            self._send(404, {"error": "not found"})

    return Handler


def _error_payload(message: str, uid: str) -> dict:
    """reference responses.py:119-139 ErrorResults shape."""
    return {
        "id": uid, "infoUri": "", "stats": _stats("FAILED"),
        "error": {
            "message": message, "errorCode": 1,
            "errorName": "GENERIC_ERROR", "errorType": "USER_ERROR",
            "errorLocation": {"lineNumber": 1, "columnNumber": 1},
        },
    }


def run_server(context=None, host: str = "0.0.0.0", port: int = 8080,
               startup: bool = False, log_level=None, blocking: bool = True):
    """Start the SQL server (reference server/app.py:97-183).

    With ``blocking=False`` returns the (started) server object for tests.
    """
    if log_level:
        logging.basicConfig(level=log_level)
    from ..context import Context

    context = context or Context()
    if startup:
        context.sql("SELECT 1 + 1")

    state = _AppState(context)
    # bind first so port=0 (ephemeral) yields correct nextUri links
    server = ThreadingHTTPServer((host, port), _make_handler(state, ""))
    base_url = f"http://{host}:{server.server_port}"
    server.RequestHandlerClass = _make_handler(state, base_url)
    server.app_state = state
    context.server = server
    if not blocking:
        t = threading.Thread(target=server.serve_forever, daemon=True)
        t.start()
        return server
    try:
        logger.info("dask-sql-tpu server listening on %s", base_url)
        server.serve_forever()
    except KeyboardInterrupt:
        server.shutdown()
    return server


def main():  # pragma: no cover - console entry
    import argparse

    parser = argparse.ArgumentParser(description="dask-sql-tpu presto server")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=8080)
    parser.add_argument("--startup", action="store_true")
    parser.add_argument("--log-level", default=None)
    args = parser.parse_args()
    run_server(host=args.host, port=args.port, startup=args.startup,
               log_level=args.log_level)


if __name__ == "__main__":  # pragma: no cover
    main()
