"""Presto-wire-protocol HTTP server.

Re-implements the reference server (/root/reference/dask_sql/server/app.py):
``POST /v1/statement`` submits SQL, ``GET /v1/status/{uuid}`` polls,
``DELETE /v1/cancel/{uuid}`` cancels, ``GET /v1/empty`` returns an empty
result — with async execution via a thread pool + futures registry mirroring
the reference's dask-client future_list (app.py:69-95).  Submission runs
through the workload manager (runtime/scheduler.py): every POST claims an
admission seat (priority from the ``X-DSQL-Priority`` header), a saturated
system answers 429 + ``Retry-After`` immediately, ``queuedTimeMillis`` and
``queuedSplits``/``runningSplits`` report the scheduler's real measurements,
and the pool is sized by ``DSQL_SERVER_WORKERS`` (default: the scheduler's
concurrency limit) instead of a hardcoded width.  ``GET /metrics``
exposes the engine's telemetry registry (runtime/telemetry.py) in
Prometheus text format — the same counters previously only reachable via
``physical.compiled.stats`` — and per-query wire stats carry the query's
phase breakdown from its QueryReport.

**Graceful drain.**  SIGTERM/SIGINT (handlers installed by the blocking
``run_server`` path; tests and embedders use ``server.drain_async()``)
flips the workload manager into draining: new ``POST /v1/statement``
requests answer **503 + Retry-After** (typed
``resilience.ServerDraining``), in-flight queries finish — and their
results stay fetchable — within ``DSQL_DRAIN_TIMEOUT_S``, stragglers get
typed cancellation, then the listener closes and the process can exit.
The ``server_draining`` gauge is 1 for the duration and the drain itself
records a ``drain`` span in a QueryReport.  ``ERROR_WIRE_MATRIX`` below
pins the full taxonomy → (submit-time HTTP status, errorType, errorName)
mapping; tests assert it row by row.

Built on stdlib http.server (FastAPI/uvicorn are not in this image); the wire
format matches the reference's responses.py so presto/trino clients work.
"""
from __future__ import annotations

import json
import logging
import math
import os
import threading
import time
import uuid as uuid_mod
from concurrent.futures import Future, ThreadPoolExecutor
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

from ..runtime import (faults as _faults, resilience as _res,
                       scheduler as _sched, telemetry as _tel)

logger = logging.getLogger(__name__)


# ---------------------------------------------------------------------------
# taxonomy -> wire mapping (audited; tests/unit/test_error_wire_matrix.py
# asserts every row).  The submit-time status is what POST /v1/statement
# answers when the verdict is known BEFORE a query id exists (admission /
# drain); verdicts raised later ride the Presto convention — HTTP 200 with
# a FAILED payload carrying errorType/errorName/errorCode — exactly like
# the reference server.
# ---------------------------------------------------------------------------

ERROR_WIRE_MATRIX = {
    # class name: (submit-time HTTP status, errorType, errorName)
    "UserError": (200, "USER_ERROR", "GENERIC_USER_ERROR"),
    "QueryCancelled": (200, "USER_ERROR", "USER_CANCELED"),
    "TransientError": (200, "INTERNAL_ERROR", "TRANSIENT_ERROR"),
    "FatalError": (200, "INTERNAL_ERROR", "GENERIC_INTERNAL_ERROR"),
    "FaultInjected": (200, "INTERNAL_ERROR", "FAULT_INJECTED"),
    "FatalFaultInjected": (200, "INTERNAL_ERROR", "FAULT_INJECTED"),
    "DeadlineExceeded": (200, "INSUFFICIENT_RESOURCES",
                         "EXCEEDED_TIME_LIMIT"),
    "AdmissionRejected": (429, "INSUFFICIENT_RESOURCES", "QUERY_QUEUE_FULL"),
    "AdmissionTimeout": (429, "INSUFFICIENT_RESOURCES",
                         "QUERY_QUEUE_TIMEOUT"),
    # tenant quotas / circuit breakers (runtime/tenancy.py) and the
    # burn-driven load shed (runtime/scheduler.py) ride the 429 +
    # Retry-After path of their AdmissionRejected parent
    "TenantQuotaExceeded": (429, "INSUFFICIENT_RESOURCES",
                            "TENANT_QUOTA_EXCEEDED"),
    "TenantCircuitOpen": (429, "INSUFFICIENT_RESOURCES",
                          "TENANT_CIRCUIT_OPEN"),
    "LoadShedRejected": (429, "INSUFFICIENT_RESOURCES", "SLO_LOAD_SHED"),
    # continuous ingestion (runtime/ingest.py): a write whose batch the
    # memory broker cannot absorb rides the 429 + Retry-After path; a
    # batch that does not fit the target table schema is the writer's
    # mistake — 400, never a retry
    "IngestBackpressure": (429, "INSUFFICIENT_RESOURCES",
                           "INGEST_BACKPRESSURE"),
    "SchemaMismatch": (400, "USER_ERROR", "SCHEMA_MISMATCH"),
    "ServerDraining": (503, "INSUFFICIENT_RESOURCES",
                       "SERVER_SHUTTING_DOWN"),
    "SpillError": (200, "INTERNAL_ERROR", "SPILL_ERROR"),
    "SpillCorrupt": (200, "INTERNAL_ERROR", "SPILL_CORRUPT"),
}


def _events_on() -> bool:
    """Watchtower gate (runtime/events.py): checked BEFORE any import so
    DSQL_EVENTS=0 keeps the wire byte-identical — no trace headers, no
    /v1/events route, no module import."""
    return os.environ.get("DSQL_EVENTS", "0").strip() not in ("", "0")


def _tenancy_on() -> bool:
    """Tenancy gate (runtime/tenancy.py): same env-before-import
    discipline — DSQL_TENANCY=0 keeps the module un-imported and the
    wire byte-identical (no tenant section, no tenant claims)."""
    return os.environ.get("DSQL_TENANCY", "1").strip() not in ("", "0")


def _fleet_on() -> bool:
    """Fleet-plane gate (runtime/fleet.py): checked BEFORE any import so
    an unset DSQL_FLEET_DIR keeps the module un-imported, /v1/fleet on
    the generic 404, and every wire byte byte-identical."""
    return bool(os.environ.get("DSQL_FLEET_DIR"))


def _ingest_on() -> bool:
    """Continuous-ingestion gate (runtime/ingest.py): DSQL_INGEST_DIR
    arms, DSQL_INGEST=0 kills — both checked BEFORE any import so the
    unarmed wire (no /v1/ingest route, no engine section) stays
    byte-identical with the module absent."""
    return bool(os.environ.get("DSQL_INGEST_DIR")) and \
        os.environ.get("DSQL_INGEST", "1").strip() not in ("0", "false")


def _page_rows() -> int:
    """Result-paging threshold (``DSQL_RESULT_PAGE_ROWS``): results with
    more rows spool into SpillStore pages of this many rows; 0 restores
    the old single-shot payload bit-for-bit."""
    try:
        return max(int(os.environ.get("DSQL_RESULT_PAGE_ROWS", "")
                       or 10_000), 0)
    except ValueError:
        return 10_000


def _result_ttl_s() -> float:
    """Reaper TTL (``DSQL_RESULT_TTL_S``): finished-but-never-collected
    queries and abandoned result spools are garbage-collected this many
    seconds after their last touch (0 disables reaping — the historical
    leak-forever behavior)."""
    try:
        return max(float(os.environ.get("DSQL_RESULT_TTL_S", "") or 600.0),
                   0.0)
    except ValueError:
        return 600.0


def submit_status(exc: Exception) -> int:
    """HTTP status for a verdict raised at the POST boundary: 503 while
    draining, 429 on saturation, 200 otherwise (the error then travels in
    the Presto payload)."""
    if isinstance(exc, _res.ServerDraining):
        return 503
    if isinstance(exc, _res.AdmissionRejected):
        return 429
    if isinstance(exc, _res.SchemaMismatch):
        return 400
    return 200


# ---------------------------------------------------------------------------
# presto wire responses (reference server/responses.py)
# ---------------------------------------------------------------------------

def _stats(state: str, info: Optional["_QueryInfo"] = None) -> dict:
    """Wire-shape of reference responses.py:11-49, but FILLED: the reference
    hardcodes zeros; here cpu/wall/queued times, processed rows/bytes, the
    compile-vs-cache-hit split and device peak memory come from the actual
    execution (physical/compiled.py stats + timers)."""
    out = {
        "state": state, "queued": state == "QUEUED", "scheduled": True,
        "nodes": 1, "totalSplits": 1, "queuedSplits": int(state == "QUEUED"),
        "runningSplits": int(state == "RUNNING"),
        "completedSplits": int(state == "FINISHED"),
        "cpuTimeMillis": 0, "wallTimeMillis": 0,
        "queuedTimeMillis": 0, "elapsedTimeMillis": 0, "processedRows": 0,
        "processedBytes": 0, "peakMemoryBytes": 0,
    }
    # live saturation from the workload manager's gauges (not the old
    # per-query 0/1 constants): presto clients polling ANY query see the
    # process-wide queue depth and running count
    mgr = _sched.get_manager()
    if mgr.enabled():
        out["queuedSplits"] = mgr.queue_depth()
        out["runningSplits"] = mgr.running_count()
    if info is not None:
        now = time.monotonic()
        started = info.started or now
        finished = info.finished or now
        if info.queued_ms is not None:
            # the scheduler's own timestamps: seat claim at POST ->
            # admission grant (covers pool wait + admission-queue wait)
            out["queuedTimeMillis"] = int(info.queued_ms)
        else:
            out["queuedTimeMillis"] = int(1000 * (started - info.submitted))
        out["wallTimeMillis"] = int(1000 * max(finished - started, 0))
        out["elapsedTimeMillis"] = int(1000 * (finished - info.submitted))
        out["cpuTimeMillis"] = int(1000 * info.cpu_sec)
        out["processedRows"] = info.rows
        out["processedBytes"] = info.bytes
        out["peakMemoryBytes"] = info.peak_memory
        out["compiledPrograms"] = info.compiles
        out["programCacheHits"] = info.cache_hits
        # result-cache verdict from the query's own QueryReport (exact,
        # span-attributed — not a process-global counter diff)
        out["cacheHit"] = bool(info.cache_hit)
        if info.cache_tier:
            out["cacheTier"] = info.cache_tier
        if info.subplan_cache_hits:
            out["subplanCacheHits"] = info.subplan_cache_hits
        # execution tier (tiered execution, physical/compiled.py):
        # "compiled" / "eager" / "eager-compiling", plus the persistent
        # program-store loads this query was served warm from
        if info.tier:
            out["tier"] = info.tier
        if info.program_store_hits:
            out["programStoreHits"] = info.program_store_hits
        # adaptive operator choices this query's dispatch took
        # (runtime/statistics.py record_choice, via the QueryReport)
        if info.operators:
            out["operatorChoices"] = list(info.operators)
        if info.phases:
            # per-query phase breakdown from the query's own QueryReport
            # (race-free: the report is thread-local to the worker that
            # ran the query, not a process-global snapshot)
            out["phaseMillis"] = {k: round(v, 3)
                                  for k, v in info.phases.items()}
        # end-to-end trace ID (watchtower, DSQL_EVENTS=1): the same ID
        # the X-DSQL-Trace header carries, so payload-only clients can
        # still join wire stats to span trees / envelopes / events
        if info.trace_id:
            out["traceId"] = info.trace_id
    return out


class _QueryInfo:
    __slots__ = ("submitted", "started", "finished", "cpu_sec", "rows",
                 "bytes", "peak_memory", "compiles", "cache_hits", "phases",
                 "cache_hit", "cache_tier", "subplan_cache_hits",
                 "queued_ms", "tier", "program_store_hits", "operators",
                 "trace_id")

    def __init__(self):
        self.submitted = time.monotonic()
        self.started = None
        self.finished = None
        self.cpu_sec = 0.0
        self.rows = 0
        self.bytes = 0
        self.peak_memory = 0
        self.compiles = 0
        self.cache_hits = 0
        self.phases = {}
        self.cache_hit = False
        self.cache_tier = None
        self.subplan_cache_hits = 0
        self.queued_ms = None
        self.tier = None
        self.program_store_hits = 0
        self.operators = []
        self.trace_id = None


def _run_tracked(context, sql: str, info: _QueryInfo,
                 cancel: Optional[threading.Event] = None,
                 seat: Optional[_sched.Seat] = None,
                 trace_id: Optional[str] = None,
                 params: Optional[list] = None,
                 grant=None):
    from ..physical import compiled
    from contextlib import nullcontext

    # the ingress trace ID rides into the worker thread: trace_scope's
    # watchtower hook picks it up and stamps the span tree, so the ID on
    # the POST response and the ID in the trace/envelope/events agree.
    # trace_id is only ever non-None when DSQL_EVENTS is armed.
    if trace_id:
        from ..runtime import events as _ev
        tid_scope = _ev.trace_id_scope(trace_id)
    else:
        tid_scope = nullcontext()

    # the POST-time tenant pre-claim rides in the same way: tenancy's
    # admission (wrapping the plan execution) consumes it exactly once —
    # mirroring the scheduler seat — so the token spent at the server
    # boundary is the only token this query costs.  grant is only ever
    # non-None when DSQL_TENANCY is armed.
    if grant is not None:
        from ..runtime import tenancy as _ten
        g_scope = _ten.grant_scope(grant)
    else:
        g_scope = nullcontext()

    info.started = time.monotonic()
    c0 = dict(compiled.stats)
    # thread_time, not process_time: concurrent pool queries must not
    # inflate each other's cpu accounting
    cpu0 = time.thread_time()
    _sched.clear_thread_queued_ms()
    try:
        # the cancel token joins the query's supervision scope
        # (runtime/resilience.py): DELETE /v1/cancel sets it and the
        # execution layers abandon queued stages / orphan in-flight
        # compiles at their next checkpoint, instead of running to the end
        # behind a fut.cancel() that cannot stop a started future.
        # seat_scope hands the POST-time admission pre-claim to the
        # workload manager, which consumes its timestamp + priority.
        with tid_scope, g_scope, _sched.seat_scope(seat), \
                _res.query_scope(cancel=cancel):
            table = context.sql(sql, params=params)
    finally:
        if grant is not None:
            # a grant the query never consumed (DDL, pre-plan failure)
            # still holds a concurrency slot — give it back (idempotent:
            # a consumed grant was already released with its outcome)
            from ..runtime import tenancy as _ten
            _ten.get_registry().release(grant)
        info.cpu_sec = time.thread_time() - cpu0
        info.finished = time.monotonic()
        info.compiles = compiled.stats["compiles"] - c0["compiles"]
        info.cache_hits = compiled.stats["hits"] - c0["hits"]
        # measured queue time from the scheduler's own timestamps; a DDL
        # statement (no plan execution) leaves the seat unconsumed — give
        # its queue position back
        info.queued_ms = _sched.thread_queued_ms()
        _sched.get_manager().release_seat(seat)
        # the report of the trace that just closed ON THIS THREAD — the
        # per-query phase split concurrent queries cannot clobber
        report = _tel.last_report()
        if report is not None:
            info.phases = dict(report.phases)
            cache = getattr(report, "cache", None) or {}
            info.cache_hit = bool(cache.get("hit"))
            info.cache_tier = cache.get("tier")
            info.subplan_cache_hits = int(cache.get("subplan_hits", 0))
            info.tier = getattr(report, "tier", None)
            info.program_store_hits = int(
                (report.counters or {}).get("program_store_hits", 0))
            info.operators = list(getattr(report, "operators", ()) or ())
    if table is not None and getattr(table, "num_columns", 0):
        info.rows = table.num_rows
        info.bytes = sum(int(getattr(c.data, "nbytes", 0))
                         for c in table.columns)
    try:
        import jax
        # sum peaks over EVERY local device: on a real mesh the query's
        # working set is sharded, so device 0 alone understates (or on an
        # idle coordinator, misses entirely) the true footprint
        peak = 0
        for d in jax.local_devices():
            try:
                mem = d.memory_stats() or {}
            except Exception:
                mem = {}
            peak += int(mem.get("peak_bytes_in_use", 0) or 0)
        info.peak_memory = peak
    except Exception as e:  # telemetry only; never fail the query over it
        logger.debug("memory_stats unavailable: %s", e)
    return table


_TYPE_MAP = {
    "BOOLEAN": "boolean", "TINYINT": "tinyint", "SMALLINT": "smallint",
    "INTEGER": "integer", "BIGINT": "bigint", "FLOAT": "real",
    "DOUBLE": "double", "DECIMAL": "decimal", "VARCHAR": "varchar",
    "CHAR": "char", "DATE": "date", "TIMESTAMP": "timestamp",
    "TIME": "time", "INTERVAL_DAY_TIME": "interval day to second",
    "INTERVAL_YEAR_MONTH": "interval year to month", "NULL": "unknown",
}


def _columns_payload(table) -> list:
    cols = []
    for name, col in zip(table.names, table.columns):
        t = _TYPE_MAP.get(col.stype.name, "varchar")
        cols.append({
            "name": name, "type": t,
            "typeSignature": {"rawType": t, "arguments": []},
        })
    return cols


def _data_payload(table) -> list:
    rows = []
    for row in table.to_pylist():
        out = []
        for v in row:
            if hasattr(v, "isoformat"):
                v = v.isoformat(sep=" ") if hasattr(v, "date") else v.isoformat()
            elif hasattr(v, "item"):
                v = v.item()
            out.append(v)
        rows.append(out)
    return rows


# ---------------------------------------------------------------------------
# result spooling (ISSUE 17): large finished results page through the
# SpillStore instead of riding one giant /v1/status payload
# ---------------------------------------------------------------------------

#: one SpillStore run per page — the store frees whole runs only, and
#: per-page runs are what lets "pages free as fetched" actually free
_RESULT_RUN_FMT = "__result__{uid}__p{page}"


class _Spool:
    """One spooled (paged) result.

    Page 0 goes out inline with the final ``/v1/status`` response (so
    the classic poll loop still sees columns+data); pages ``1..n-1``
    live in the SpillStore as JSON-encoded uint8 chunks — byte-exact
    with what ``_data_payload`` would have sent, and flushable to disk
    under the store's ordinary host budget.  ``next_page`` is the lowest
    page not yet freed: fetching page ``p`` frees everything below it
    (clients may retry the page they are on after a network hiccup), and
    the terminal page ``n`` carries no data, no ``nextUri``, and drops
    the spool."""

    __slots__ = ("uid", "columns", "pages", "page_bytes", "next_page",
                 "trace_id", "created", "last_access")

    def __init__(self, uid: str, columns: list, pages: int,
                 page_bytes: Dict[int, int],
                 trace_id: Optional[str] = None):
        self.uid = uid
        self.columns = columns
        self.pages = pages              # data pages (page 0 included)
        self.page_bytes = page_bytes    # stored page -> payload bytes
        self.next_page = 1              # page 0 served inline
        self.trace_id = trace_id
        self.created = time.monotonic()
        self.last_access = self.created

    def live_bytes(self) -> int:
        return sum(v for p, v in self.page_bytes.items()
                   if p >= self.next_page)

    def live_pages(self) -> int:
        return max(self.pages - self.next_page, 0)


def _spool_result(state: "_AppState", uid: str, table,
                  info: Optional[_QueryInfo]):
    """Spool ``table`` into pages; returns ``(spool, page0_rows)`` or
    None when the result is small enough / paging is off / the spool
    path faulted — the caller then serves the classic single-shot
    payload (degraded, never broken)."""
    pr = _page_rows()
    if (pr <= 0 or table is None or not getattr(table, "num_columns", 0)
            or int(table.num_rows) <= pr):
        return None
    import numpy as np
    from ..runtime import spill as _spill
    store = _spill.get_store()
    stored = []
    try:
        _faults.maybe_fail("result_spool")
        data = _data_payload(table)
        n_pages = (len(data) + pr - 1) // pr
        page_bytes: Dict[int, int] = {}
        for p in range(1, n_pages):
            chunk = data[p * pr:(p + 1) * pr]
            body = json.dumps(chunk, separators=(",", ":"),
                              default=str).encode()
            run = _RESULT_RUN_FMT.format(uid=uid, page=p)
            store.put_host(run, ["body"],
                           [(np.frombuffer(body, dtype=np.uint8).copy(),
                             None, "bytes", None)], rows=len(chunk))
            stored.append(run)
            page_bytes[p] = len(body)
    except Exception as e:
        for run in stored:
            store.free_run(run)
        logger.warning("result spool failed for %s (%s); serving the "
                       "unpaged response", uid, e)
        return None
    spool = _Spool(uid, _columns_payload(table), n_pages, page_bytes,
                   trace_id=getattr(info, "trace_id", None))
    with state.lock:
        state.spools[uid] = spool
    _tel.inc("result_spooled")
    _tel.inc("result_pages_spooled", len(stored))
    state.publish_spool_gauges()
    return spool, data[:pr]


# ---------------------------------------------------------------------------
# GET /v1/engine: one live snapshot of the whole engine
# ---------------------------------------------------------------------------

def _spill_section(counters: dict) -> dict:
    """Out-of-core occupancy for /v1/engine: store tiers (live bytes +
    device peak) plus the cumulative partition/flush counters, so an
    operator can tell a query is running out-of-core — and which tier is
    absorbing it — without attaching a profiler."""
    from ..runtime import spill as _spill

    stats = _spill.get_store().stats()
    return {
        "enabled": stats["enabled"],
        "runs": stats["runs"],
        "chunks": stats["chunks"],
        "deviceBytes": stats["device_bytes"],
        "hostBytes": stats["host_bytes"],
        "diskBytes": stats["disk_bytes"],
        "peakDeviceBytes": stats["peak_device_bytes"],
        "partitions": int(counters.get("spill_partitions", 0)),
        "flushes": int(counters.get("spill_flushes", 0)),
        "morselJoins": int(counters.get("morsel_joins", 0)),
    }


def _engine_snapshot(state: "_AppState") -> dict:
    """Everything an operator needs in one poll: in-flight queries with
    per-stage progress (flight recorder's live registry), scheduler queue
    depths, memory-ledger occupancy, cache tiers, quarantine verdicts,
    program-store stats, and the history ring's location."""
    from ..physical import compiled as _compiled
    from ..runtime import flight_recorder as _fr
    from ..runtime import program_store as _pstore
    from ..runtime import quarantine as _quar
    from ..runtime import result_cache as _rc

    mgr = _sched.get_manager()
    counters = _tel.REGISTRY.counters()
    with state.lock:
        server_queries = [
            {"id": uid,
             "state": ("FINISHED" if fut.done() else
                       "QUEUED" if (state.query_info.get(uid) is not None
                                    and state.query_info[uid].started is None)
                       else "RUNNING")}
            for uid, fut in state.future_list.items()]
    pstore = _pstore.get_store()
    qstore = _quar.get_store()
    out = {
        "pid": os.getpid(),
        "active": _fr.active_snapshot(),
        "serverQueries": server_queries,
        "scheduler": {
            "enabled": mgr.enabled(),
            "limit": mgr.limit(),
            "queueDepth": mgr.queue_depth(),
            "running": mgr.running_count(),
            "waiting": mgr.waiting_snapshot(),
            "draining": mgr.draining(),
        },
        "memory": {
            "budgetBytes": mgr.ledger.budget(),
            "reservedBytes": mgr.ledger.reserved_bytes(),
        },
        "cache": _rc.get_cache().stats(),
        "spill": _spill_section(counters),
        "quarantine": {
            "enabled": qstore.enabled(),
            "entries": len(qstore.entries()) if qstore.enabled() else 0,
        },
        "programStore": {
            "enabled": pstore.enabled(),
            "entries": len(pstore.entries()) if pstore.enabled() else 0,
            "bytes": pstore.total_bytes() if pstore.enabled() else 0,
        },
        "backgroundCompiles": {
            "inflight": len(_compiled.inflight_background_compiles()),
            "done": int(counters.get("background_compiles_done", 0)),
            "errors": int(counters.get("background_compile_errors", 0)),
        },
        "history": {
            "enabled": _fr.enabled(),
            "file": _fr.history_path() or "",
            "records": int(counters.get("history_records", 0)),
        },
        "devices": _devices_section(),
        "profile": _profile_section(),
        "slo": _slo_section(),
    }
    # feature-gated sections: absent with the kill switches thrown, so
    # DSQL_RESULT_PAGE_ROWS=0 / DSQL_TENANCY=0 keep /v1/engine pre-PR
    if _page_rows() > 0 or state.spools:
        out["results"] = state.spools_snapshot()
    if _tenancy_on():
        from ..runtime import tenancy as _ten
        out["tenants"] = _ten.get_registry().snapshot()
    if _fleet_on():
        from ..runtime import fleet as _fleet
        out["fleet"] = {"replica": _fleet.replica_id(),
                        "dir": _fleet.fleet_dir() or ""}
    if os.environ.get("DSQL_AUTOPILOT", "0").strip() not in ("", "0"):
        try:
            from ..runtime import autopilot as _ap
            out["autopilot"] = _ap.engine_section()
        except Exception:
            logger.debug("autopilot engine section failed", exc_info=True)
    if _ingest_on():
        try:
            from ..runtime import ingest as _ing
            out["ingest"] = _ing.engine_section(state.context)
        except Exception:
            logger.debug("ingest engine section failed", exc_info=True)
    return out


def _devices_section() -> list:
    """Per-local-device HBM rows (jax read directly — no profiler import,
    so the disabled-profiler zero-import guarantee holds for /v1/engine)."""
    rows = []
    try:
        import jax
        devices = jax.local_devices()
    except Exception:
        return rows
    for d in devices:
        try:
            mem = d.memory_stats() or {}
        except Exception:
            mem = {}
        rows.append({
            "id": int(getattr(d, "id", len(rows))),
            "platform": str(getattr(d, "platform", "")),
            "kind": str(getattr(d, "device_kind", "")),
            "bytesInUse": int(mem.get("bytes_in_use", 0) or 0),
            "peakBytesInUse": int(mem.get("peak_bytes_in_use", 0) or 0),
            "bytesLimit": int(mem.get("bytes_limit", 0) or 0),
        })
    return rows


def _profile_section() -> dict:
    """The device profiler's own stats — imported ONLY when armed."""
    if os.environ.get("DSQL_PROFILE", "0").strip() in ("", "0"):
        return {"enabled": False}
    try:
        from ..runtime import profiler as _prof
        return _prof.engine_section()
    except Exception as e:
        logger.debug("profiler section unavailable: %s", e)
        return {"enabled": False}


def _slo_section() -> dict:
    """Per-class SLO burn rates + live anomaly flags (runtime/events.py)
    — imported ONLY when the watchtower is armed, like the profiler."""
    if not _events_on():
        return {"enabled": False}
    try:
        from ..runtime import events as _ev
        return _ev.engine_section()
    except Exception as e:
        logger.debug("slo section unavailable: %s", e)
        return {"enabled": False}


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------

def _server_workers() -> int:
    """Worker-thread count: ``DSQL_SERVER_WORKERS``, defaulting to the
    workload manager's concurrency limit (the pool no longer needs its own
    magic width — the scheduler owns saturation policy; the pool just has
    to keep every grantable slot busy).  4 when the scheduler is off,
    matching the historical hardcoded pool."""
    raw = os.environ.get("DSQL_SERVER_WORKERS", "")
    try:
        if raw and int(raw) > 0:
            return int(raw)
    except ValueError:
        pass
    mgr = _sched.get_manager()
    return mgr.limit() if mgr.enabled() else 4


class _AppState:
    def __init__(self, context):
        self.context = context
        self.pool = ThreadPoolExecutor(max_workers=_server_workers())
        self.future_list: Dict[str, Future] = {}
        self.query_info: Dict[str, _QueryInfo] = {}
        self.cancel_events: Dict[str, threading.Event] = {}
        self.seats: Dict[str, _sched.Seat] = {}
        self.spools: Dict[str, _Spool] = {}
        self.lock = threading.Lock()
        self.drained = threading.Event()     # set when a drain completed
        # result/registry reaper (ISSUE 17): GCs never-collected results,
        # abandoned spools and their registry entries after
        # DSQL_RESULT_TTL_S — the fix for the historical future_list /
        # query_info / seats leak when a client submits and walks away
        self._reaper = threading.Thread(target=self._reap_loop,
                                        name="dsql-result-reaper",
                                        daemon=True)
        self._reaper.start()

    def forget(self, uid: str) -> tuple:
        """The one true cleanup for a query's registry entries — status
        collection, cancel, and the reaper all come through here (the
        4-line pop block used to be duplicated across the status paths).
        Hands an unconsumed admission seat back (idempotent) and returns
        ``(future, info, cancel_event)`` for callers that still need
        them — all None when the uid was already forgotten."""
        with self.lock:
            fut = self.future_list.pop(uid, None)
            info = self.query_info.pop(uid, None)
            cancel = self.cancel_events.pop(uid, None)
            seat = self.seats.pop(uid, None)
        _sched.get_manager().release_seat(seat)
        return fut, info, cancel

    # -- spool bookkeeping --------------------------------------------------
    def publish_spool_gauges(self) -> None:
        with self.lock:
            pages = sum(s.live_pages() for s in self.spools.values())
            nbytes = sum(s.live_bytes() for s in self.spools.values())
        _tel.REGISTRY.set_gauge("result_spool_pages", pages)
        _tel.REGISTRY.set_gauge("result_spool_bytes", nbytes)

    def advance_spool(self, uid: str, page: int) -> None:
        """The client fetched ``page``: every page below it was received,
        so free their SpillStore runs (pages free as fetched)."""
        with self.lock:
            spool = self.spools.get(uid)
            if spool is None:
                return
            lo = spool.next_page
            spool.next_page = max(spool.next_page, page)
        if lo < page:
            from ..runtime import spill as _spill
            store = _spill.get_store()
            for p in range(max(lo, 1), page):
                store.free_run(_RESULT_RUN_FMT.format(uid=uid, page=p))
        self.publish_spool_gauges()

    def drop_spool(self, uid: str) -> bool:
        """Free a spool and every page it still holds (terminal page,
        cancel, reaper)."""
        with self.lock:
            spool = self.spools.pop(uid, None)
        if spool is None:
            return False
        from ..runtime import spill as _spill
        store = _spill.get_store()
        for p in range(max(spool.next_page, 1), spool.pages):
            store.free_run(_RESULT_RUN_FMT.format(uid=uid, page=p))
        self.publish_spool_gauges()
        return True

    def spools_snapshot(self) -> dict:
        with self.lock:
            return {
                "enabled": _page_rows() > 0,
                "pageRows": _page_rows(),
                "ttlS": _result_ttl_s(),
                "spools": len(self.spools),
                "livePages": sum(s.live_pages()
                                 for s in self.spools.values()),
                "liveBytes": sum(s.live_bytes()
                                 for s in self.spools.values()),
            }

    # -- reaper -------------------------------------------------------------
    def _reap_loop(self) -> None:
        while not self.drained.wait(0.25):
            try:
                self.reap_once()
            except Exception:
                logger.exception("result reaper tick failed")

    def reap_once(self, now: Optional[float] = None) -> int:
        """One reaper tick: forget finished-but-never-collected queries
        and abandoned spools older than ``DSQL_RESULT_TTL_S``.  Returns
        how many entries were reaped (tests drive this directly)."""
        ttl = _result_ttl_s()
        if ttl <= 0:
            return 0
        now = time.monotonic() if now is None else now
        with self.lock:
            dead_spools = [uid for uid, s in self.spools.items()
                           if now - s.last_access > ttl]
            dead_queries = []
            for uid, fut in self.future_list.items():
                if not fut.done():
                    continue
                info = self.query_info.get(uid)
                done_at = getattr(info, "finished", None) or \
                    getattr(info, "submitted", None) or now
                if now - done_at > ttl:
                    dead_queries.append(uid)
        reaped = 0
        for uid in dead_queries:
            fut, _info, _cancel = self.forget(uid)
            if fut is not None:
                # consume the outcome so an abandoned failure does not
                # warn at interpreter shutdown
                try:
                    fut.exception(timeout=0)
                except Exception:
                    pass
                reaped += 1
                logger.info("reaped never-collected query %s", uid)
        for uid in dead_spools:
            if self.drop_spool(uid):
                reaped += 1
                logger.info("reaped abandoned result spool %s", uid)
        if reaped:
            _tel.inc("result_reaped", reaped)
        return reaped


# ---------------------------------------------------------------------------
# graceful drain (SIGTERM/SIGINT)
# ---------------------------------------------------------------------------

def _drain_and_shutdown(server, state: _AppState,
                        reason: str = "drain") -> None:
    """Drain this server, then stop it.

    New admissions are refused the instant the workload manager flips to
    draining (POST answers 503 + Retry-After); in-flight queries finish —
    and their results stay fetchable, the status poll deletes a query's
    entry only once the client collected it — within
    ``DSQL_DRAIN_TIMEOUT_S``.  Stragglers past the budget get TYPED
    cancellation (``QueryCancelled`` at their next checkpoint), never an
    abandoned thread.  The whole procedure runs under a ``drain`` span so
    the shutdown leaves a QueryReport behind, and it is itself a fault
    site (``drain``, runtime/faults.py) — an injected fault there is
    swallowed, because a broken drain step must never wedge process exit.
    """
    mgr = _sched.get_manager()
    timeout = _sched.drain_timeout_s()
    mgr.begin_drain()
    logger.warning("%s: draining server (timeout %.0f s, %d in flight)",
                   reason, timeout, len(state.future_list))
    if _events_on():
        try:
            from ..runtime import events as _ev
            _ev.publish("server.drain", reason=reason,
                        in_flight=len(state.future_list),
                        timeout_s=timeout)
        except Exception:
            pass
    try:
        with _tel.trace_scope(f"<drain:{reason}>"):
            with _tel.span("drain", reason=reason, timeout_s=timeout):
                try:
                    _faults.maybe_fail("drain")
                except Exception as e:
                    logger.warning(
                        "injected drain fault (%s); continuing shutdown", e)
                deadline = time.monotonic() + timeout
                while state.future_list and time.monotonic() < deadline:
                    time.sleep(0.05)
                stragglers = list(state.future_list.keys())
                if stragglers:
                    _tel.annotate(cancelled=len(stragglers))
                    logger.warning(
                        "drain timeout: typed-cancelling %d in-flight "
                        "quer%s", len(stragglers),
                        "y" if len(stragglers) == 1 else "ies")
                    for ev in list(state.cancel_events.values()):
                        ev.set()
                    grace = time.monotonic() + 2.0
                    while (any(not f.done()
                               for f in list(state.future_list.values()))
                           and time.monotonic() < grace):
                        time.sleep(0.05)
    finally:
        if _ingest_on():
            # micro-batched rows acked BUFFERED are not yet in the WAL;
            # a graceful drain commits them (WAL + apply) before the
            # process exits — only a crash may lose buffered (never
            # committed) batches
            try:
                from ..runtime import ingest as _ing
                log = _ing.get_log(state.context)
                if log is not None:
                    log.flush_all()
            except Exception:
                logger.exception("ingest flush during drain failed")
        try:
            server.shutdown()
            server.server_close()
        except Exception:
            logger.exception("server shutdown failed during drain")
        state.pool.shutdown(wait=False, cancel_futures=True)
        # reset the process-global flag: in production the process exits
        # right after; in tests this restores the shared manager
        mgr.end_drain()
        state.drained.set()
        logger.warning("drain complete; server stopped")


def install_drain_handlers(server) -> dict:
    """Install SIGTERM/SIGINT handlers that drain ``server`` gracefully.

    Only possible from the main thread (a ``signal`` module restriction);
    returns the previous handlers so a caller (tests) can restore them, or
    ``{}`` when installation was not possible.  The handler itself only
    SPAWNS the drain thread — signal context must stay non-blocking."""
    import signal

    state = server.app_state

    def handler(signum, frame):
        threading.Thread(
            target=_drain_and_shutdown,
            args=(server, state, signal.Signals(signum).name),
            daemon=True).start()

    prev: dict = {}
    try:
        for sig in (signal.SIGTERM, signal.SIGINT):
            prev[sig] = signal.signal(sig, handler)
    except ValueError:
        logger.debug("not the main thread; drain signal handlers not "
                     "installed (use server.drain_async())")
        return {}
    return prev


def _make_handler(state: _AppState, base_url: str):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):
            logger.debug("server: " + fmt, *args)

        def _send(self, code: int, payload: Optional[dict],
                  headers: Optional[dict] = None):
            body = json.dumps(payload or {}).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _req_trace(self) -> Optional[str]:
            """Sanitized client-supplied ``X-DSQL-Trace``, or None
            (always None with the watchtower off — no import)."""
            if not _events_on():
                return None
            from ..runtime import events as _ev
            return _ev.sanitize_trace_id(self.headers.get("X-DSQL-Trace"))

        def _trace_headers(self,
                           info: Optional[_QueryInfo] = None,
                           tid: Optional[str] = None) -> Optional[dict]:
            """``X-DSQL-Trace`` response header for EVERY wire path
            (success and the full ERROR_WIRE_MATRIX): the query's minted
            ID when known, else the client's echoed back.  None (no
            header at all) when the watchtower is off."""
            if not _events_on():
                return None
            tid = tid or (getattr(info, "trace_id", None)
                          if info is not None else None) or \
                self._req_trace()
            return {"X-DSQL-Trace": tid} if tid else None

        # GET /metrics | GET /v1/engine | GET /v1/empty | GET /v1/status/{uuid}
        def do_GET(self):
            if self.path.rstrip("/").split("?")[0] == "/metrics":
                # Prometheus text exposition of the engine's telemetry
                # registry: the same counters previously only reachable
                # in-process via physical.compiled.stats.  With a fleet
                # dir armed every series carries a replica label, so a
                # scraper summing across replicas never mixes series
                labels = None
                if _fleet_on():
                    from ..runtime import fleet as _fleet
                    labels = {"replica": _fleet.replica_id()}
                body = _tel.REGISTRY.render_prometheus(labels).encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            if self.path.rstrip("/").split("?")[0] == "/v1/engine":
                try:
                    payload = _engine_snapshot(state)
                except Exception:
                    logger.exception("/v1/engine snapshot failed")
                    self._send(500, {"error": "snapshot failed"})
                    return
                self._send(200, payload)
                return
            if (self.path.rstrip("/").split("?")[0] == "/v1/fleet"
                    and _fleet_on()):
                # the aggregated fleet snapshot (runtime/fleet.py):
                # per-replica heartbeat rows + fleet-wide sums + merged
                # SLO + promoted anomalies.  Unset fleet dir falls
                # through to the generic 404 — byte-identical wire.
                try:
                    from ..runtime import fleet as _fleet
                    payload = _fleet.snapshot()
                except Exception:
                    logger.exception("/v1/fleet snapshot failed")
                    self._send(500, {"error": "fleet snapshot failed"})
                    return
                self._send(200, payload)
                return
            if (self.path.rstrip("/").split("?")[0] == "/v1/events"
                    and _events_on()):
                # live event streaming: JSON lines newer than ?cursor=,
                # long-polling up to ?timeout_ms= for the first arrival.
                # With the watchtower off this path falls through to the
                # generic 404 below — byte-identical pre-PR behavior.
                self._serve_events()
                return
            if self.path.rstrip("/") == "/v1/empty":
                self._send(200, {
                    "id": "empty", "infoUri": base_url,
                    "columns": [], "data": [], "stats": _stats("FINISHED"),
                })
                return
            if self.path.startswith("/v1/status/"):
                uid = self.path[len("/v1/status/"):].strip("/")
                fut = state.future_list.get(uid)
                if fut is None:
                    # a spooled result already collected its page 0: a
                    # re-poll answers FINISHED with columns and the
                    # nextUri of the lowest uncollected page (no data —
                    # rows travel on /v1/result only, once each)
                    with state.lock:
                        spool = state.spools.get(uid)
                    if spool is not None:
                        spool.last_access = time.monotonic()
                        self._send(200, {
                            "id": uid, "infoUri": base_url,
                            "nextUri": (f"{base_url}/v1/result/{uid}/"
                                        f"{spool.next_page}"),
                            "columns": spool.columns,
                            "stats": _stats("FINISHED"),
                        }, headers=self._trace_headers(
                            tid=spool.trace_id))
                        return
                    self._send(404, _error_payload("Unknown query id", uid),
                               headers=self._trace_headers())
                    return
                info = state.query_info.get(uid)
                if not fut.done():
                    self._send(200, {
                        "id": uid, "infoUri": base_url,
                        "nextUri": f"{base_url}/v1/status/{uid}",
                        "partialCancelUri": f"{base_url}/v1/cancel/{uid}",
                        "stats": _stats("RUNNING", info),
                    }, headers=self._trace_headers(info))
                    return
                try:
                    table = fut.result()
                except Exception as e:
                    state.forget(uid)
                    _tel.inc("server_query_errors")
                    self._send(200, _error_payload(str(e), uid, exc=e),
                               headers=self._trace_headers(info))
                    return
                spooled = _spool_result(state, uid, table, info)
                state.forget(uid)
                if spooled is not None:
                    # page 0 inline + a REAL nextUri: the rest of the
                    # result pages through GET /v1/result/{uid}/{page}
                    spool, page0 = spooled
                    self._send(200, {
                        "id": uid, "infoUri": base_url,
                        "nextUri": f"{base_url}/v1/result/{uid}/1",
                        "columns": spool.columns,
                        "data": page0,
                        "stats": _stats("FINISHED", info),
                    }, headers=self._trace_headers(info))
                    return
                payload = {
                    "id": uid, "infoUri": base_url,
                    "stats": _stats("FINISHED", info),
                }
                if table is not None and table.num_columns:
                    payload["columns"] = _columns_payload(table)
                    payload["data"] = _data_payload(table)
                self._send(200, payload,
                           headers=self._trace_headers(info))
                return
            if self.path.startswith("/v1/result/"):
                parts = self.path[len("/v1/result/"):].strip("/").split("/")
                page = -1
                if len(parts) == 2:
                    try:
                        page = int(parts[1])
                    except ValueError:
                        page = -1
                if page < 0:
                    self._send(404, {"error": "not found"})
                    return
                self._serve_result_page(parts[0], page)
                return
            self._send(404, {"error": "not found"})

        def _serve_result_page(self, uid: str, page: int):
            """GET /v1/result/{uid}/{page}: one spooled page.  Pages are
            served in order; fetching page p frees every page below it,
            a page below ``next_page`` is 410 Gone (collected and
            freed), and the terminal page (== page count) answers empty
            data with no nextUri and drops the spool."""
            with state.lock:
                spool = state.spools.get(uid)
            if spool is None:
                self._send(404, _error_payload(
                    "Unknown or expired result id", uid),
                    headers=self._trace_headers())
                return
            spool.last_access = time.monotonic()
            hdrs = self._trace_headers(tid=spool.trace_id)
            if page < spool.next_page or page > spool.pages:
                self._send(410, _error_payload(
                    f"result page {page} of {uid} already collected "
                    f"(pages free as fetched; next is "
                    f"{spool.next_page})", uid), headers=hdrs)
                return
            if page == spool.pages:
                # terminal page: no data, no nextUri — the client has
                # everything, free whatever is left
                state.drop_spool(uid)
                _tel.inc("result_pages_served")
                self._send(200, {
                    "id": uid, "infoUri": base_url,
                    "columns": spool.columns, "data": [],
                    "stats": _stats("FINISHED"),
                }, headers=hdrs)
                return
            from ..runtime import spill as _spill
            try:
                _names, cols = _spill.get_store().get_host_cols(
                    _RESULT_RUN_FMT.format(uid=uid, page=page), 0)
                rows = json.loads(cols[0][0].tobytes().decode())
            except Exception as e:
                logger.exception("result page fetch failed: %s/%d",
                                 uid, page)
                self._send(500, _error_payload(
                    f"result page fetch failed: {e}", uid, exc=e),
                    headers=hdrs)
                return
            state.advance_spool(uid, page)
            _tel.inc("result_pages_served")
            self._send(200, {
                "id": uid, "infoUri": base_url,
                "nextUri": f"{base_url}/v1/result/{uid}/{page + 1}",
                "columns": spool.columns, "data": rows,
                "stats": _stats("FINISHED"),
            }, headers=hdrs)

        def _serve_events(self):
            """GET /v1/events?cursor=N&timeout_ms=M&limit=K — newline-
            delimited JSON events with ``seq > cursor``; the next cursor
            travels in ``X-DSQL-Cursor`` (and on each event's ``seq``).
            A draining process answers immediately with whatever is
            buffered instead of holding the long-poll open.

            ``?fleet=1`` (fleet dir armed) switches to the MERGED
            cross-replica stream (runtime/fleet.py): events from every
            replica's ring k-way-merged in timestamp order, cursored by
            the composite ``replica:seq;...`` string instead of one
            integer."""
            from urllib.parse import parse_qs, urlparse
            from ..runtime import events as _ev

            q = parse_qs(urlparse(self.path).query)

            def qint(name: str, default: int) -> int:
                try:
                    return int(q.get(name, [default])[0])
                except (ValueError, TypeError, IndexError):
                    return default

            limit = min(max(qint("limit", 500), 1), 5000)
            timeout_s = min(max(qint("timeout_ms", 0), 0) / 1e3, 30.0)
            if _sched.get_manager().draining():
                timeout_s = 0.0
            fleet_mode = (q.get("fleet", ["0"])[0] not in ("", "0")
                          and _fleet_on())
            if fleet_mode:
                from ..runtime import fleet as _fleet
                raw_cursor = q.get("cursor", [""])[0]
                evs, nxt = _fleet.read_merged_since(
                    raw_cursor, limit=limit, timeout_s=timeout_s)
            else:
                cursor = max(qint("cursor", 0), 0)
                evs, nxt = _ev.read_since(cursor, limit=limit,
                                          timeout_s=timeout_s)
            body = b"".join(
                json.dumps(e, separators=(",", ":"), default=str).encode()
                + b"\n" for e in evs)
            self.send_response(200)
            self.send_header("Content-Type", "application/x-ndjson")
            self.send_header("Content-Length", str(len(body)))
            self.send_header("X-DSQL-Cursor", str(nxt))
            self.end_headers()
            self.wfile.write(body)

        # POST /v1/statement | POST /v1/ingest (armed subsystems only)
        def do_POST(self):
            if self.path.rstrip("/") == "/v1/ingest" and _ingest_on():
                self._do_ingest()
                return
            if self.path.rstrip("/") != "/v1/statement":
                self._send(404, {"error": "not found"})
                return
            length = int(self.headers.get("Content-Length", 0))
            sql = self.rfile.read(length).decode()
            _tel.inc("server_queries")
            uid = str(uuid_mod.uuid4())
            # JSON envelope with server-side parameters: a
            # ``Content-Type: application/json`` body of
            # ``{"sql": "...", "params": [...]}`` binds positional ?/$n
            # markers (Context.sql(params=...)); a plain body stays the
            # raw SQL text it always was
            params = None
            ctype = (self.headers.get("Content-Type") or "")
            if ctype.split(";")[0].strip().lower() == "application/json":
                try:
                    payload = json.loads(sql)
                    sql = payload["sql"]
                    params = payload.get("params")
                except (ValueError, TypeError, KeyError):
                    _tel.inc("server_query_errors")
                    self._send(400, _error_payload(
                        'Invalid JSON statement body (expected '
                        '{"sql": "...", "params": [...]})', uid),
                        headers=self._trace_headers())
                    return
                if params is not None and not isinstance(params, list):
                    _tel.inc("server_query_errors")
                    self._send(400, _error_payload(
                        '"params" must be a JSON array', uid),
                        headers=self._trace_headers())
                    return
            mgr = _sched.get_manager()
            # watchtower ingress: honor the client's X-DSQL-Trace or mint
            # one HERE, before any verdict, so success AND every
            # ERROR_WIRE_MATRIX path return the same correlation ID.
            # tid stays None with DSQL_EVENTS off (no header emitted).
            tid = None
            if _events_on():
                from ..runtime import events as _ev
                tid = self._req_trace() or _ev.mint_trace_id()

            def reject(e: _res.AdmissionRejected) -> None:
                hdrs = {"Retry-After":
                        str(max(int(math.ceil(e.retry_after_s)), 1))}
                hdrs.update(self._trace_headers(tid=tid) or {})
                if tid:
                    from ..runtime import events as _ev
                    _ev.publish("server.rejected", trace=tid,
                                error=type(e).__name__,
                                retry_after_s=round(e.retry_after_s, 3))
                self._send(submit_status(e), _error_payload(str(e), uid,
                                                            exc=e),
                           headers=hdrs)

            # drain gate first (independent of the scheduler subsystem
            # being enabled): a draining process refuses new work with 503
            # so the load balancer retries elsewhere, while GET/DELETE keep
            # serving in-flight queries to completion
            if mgr.draining():
                _tel.inc("server_drain_rejects")
                reject(mgr._drain_verdict())
                return
            # tenant pre-claim FIRST (runtime/tenancy.py, X-DSQL-Tenant
            # header): a tenant over its rate/concurrency quota or with
            # an open circuit gets its typed 429 before a scheduler seat
            # or queue position is spent on it.  grant stays None with
            # DSQL_TENANCY=0 (no import — wire byte-identical).
            grant = None
            if _tenancy_on():
                from ..runtime import tenancy as _ten
                try:
                    grant = _ten.get_registry().claim(
                        self.headers.get("X-DSQL-Tenant"))
                except _res.AdmissionRejected as e:
                    _tel.inc("server_throttled")
                    reject(e)
                    return
            # admission pre-claim at POST time: when every slot AND queue
            # position is taken the client gets an immediate 429 with a
            # Retry-After hint, instead of the query disappearing into an
            # unbounded thread-pool backlog
            priority = _sched.normalize_priority(
                self.headers.get("X-DSQL-Priority"))
            try:
                seat = mgr.claim_seat(priority)
            except _res.AdmissionRejected as e:
                if grant is not None:
                    from ..runtime import tenancy as _ten
                    _ten.get_registry().release(grant)
                _tel.inc("server_drain_rejects"
                         if isinstance(e, _res.ServerDraining)
                         else "server_throttled")
                reject(e)
                return
            info = _QueryInfo()
            info.trace_id = tid
            cancel = threading.Event()
            state.query_info[uid] = info
            state.cancel_events[uid] = cancel
            if seat is not None:
                state.seats[uid] = seat
            fut = state.pool.submit(_run_tracked, state.context, sql, info,
                                    cancel, seat, tid, params, grant)
            state.future_list[uid] = fut
            self._send(200, {
                "id": uid, "infoUri": base_url,
                "nextUri": f"{base_url}/v1/status/{uid}",
                "partialCancelUri": f"{base_url}/v1/cancel/{uid}",
                "stats": _stats("QUEUED", info),
            }, headers=self._trace_headers(tid=tid))

        def _do_ingest(self):
            """POST /v1/ingest (runtime/ingest.py; route 404s unarmed):
            one WAL-committed append per request.  Body::

                {"table": "t", "rows": [[...], ...] | {"col": [...]},
                 "schema": "root"?}

            Tenant-tagged (X-DSQL-Tenant) and quota-governed exactly like
            a statement; the writer's typed verdicts ride the audited
            wire — 429 + Retry-After on quota/backpressure, 400 on a
            schema mismatch, 503 draining."""
            _tel.inc("server_ingest_requests")
            uid = str(uuid_mod.uuid4())
            tid = None
            if _events_on():
                from ..runtime import events as _ev
                tid = self._req_trace() or _ev.mint_trace_id()

            def reject(e: _res.AdmissionRejected) -> None:
                hdrs = {"Retry-After":
                        str(max(int(math.ceil(e.retry_after_s)), 1))}
                hdrs.update(self._trace_headers(tid=tid) or {})
                if tid:
                    from ..runtime import events as _ev
                    _ev.publish("server.rejected", trace=tid,
                                error=type(e).__name__,
                                retry_after_s=round(e.retry_after_s, 3))
                self._send(submit_status(e),
                           _error_payload(str(e), uid, exc=e), headers=hdrs)

            mgr = _sched.get_manager()
            if mgr.draining():
                _tel.inc("server_drain_rejects")
                reject(mgr._drain_verdict())
                return
            length = int(self.headers.get("Content-Length", 0))
            try:
                payload = json.loads(self.rfile.read(length).decode())
                table = payload["table"]
                rows = payload["rows"]
                schema_name = payload.get("schema") or None
                if not isinstance(rows, (list, dict)):
                    raise TypeError("rows must be a list or dict")
            except Exception:
                self._send(400, _error_payload(
                    'Invalid ingest body (expected {"table": "...", '
                    '"rows": [[...], ...] | {"col": [...]}, '
                    '"schema": "..."?})', uid),
                    headers=self._trace_headers(tid=tid))
                return
            grant = None
            if _tenancy_on():
                from ..runtime import tenancy as _ten
                try:
                    grant = _ten.get_registry().claim(
                        self.headers.get("X-DSQL-Tenant"))
                except _res.AdmissionRejected as e:
                    _tel.inc("server_throttled")
                    reject(e)
                    return
            outcome = None  # rejects feed neither breaker nor counts
            try:
                if isinstance(rows, list):
                    rows = [tuple(r) if isinstance(r, list) else r
                            for r in rows]
                n = state.context.append_rows(table, rows,
                                              schema_name=schema_name)
                outcome = "ok"
                self._send(200, {
                    "id": uid,
                    "table": table,
                    "state": "COMMITTED" if n else "BUFFERED",
                    "rows": int(n),
                    "epoch": state.context.table_epoch(
                        schema_name or state.context.schema_name,
                        str(table)),
                }, headers=self._trace_headers(tid=tid))
            except _res.AdmissionRejected as e:
                # backpressure/quota mid-commit: honest Retry-After
                _tel.inc("server_throttled")
                reject(e)
            except Exception as e:
                outcome = "error"
                err = _res.classify(e, default=_res.UserError)
                if err is None:  # control-flow: re-raise untouched
                    raise
                self._send(submit_status(err),
                           _error_payload(str(err), uid, exc=err),
                           headers=self._trace_headers(tid=tid))
            finally:
                if grant is not None:
                    from ..runtime import tenancy as _ten
                    _ten.get_registry().release(grant, outcome=outcome)

        # DELETE /v1/cancel/{uuid}
        def do_DELETE(self):
            if self.path.startswith("/v1/cancel/"):
                uid = self.path[len("/v1/cancel/"):].strip("/")
                # forget() pops every registry dict and hands an
                # unconsumed admission pre-claim back (a query cancelled
                # while still in the pool backlog never reaches
                # _run_tracked — its seat must not hold a queue position
                # forever; idempotent: a consumed seat is a no-op)
                fut, info, cancel = state.forget(uid)
                # a cancel can also target a spooled result mid-page:
                # drop the spool and free its remaining pages
                dropped = state.drop_spool(uid)
                if fut is None and not dropped:
                    self._send(404, _error_payload("Unknown query id", uid),
                               headers=self._trace_headers())
                    return
                if fut is None:
                    _tel.inc("server_cancels")
                    self._send(200, None, headers=self._trace_headers())
                    return
                # REAL cancellation, not just fut.cancel() (which is a
                # no-op once the future started): the cancel token makes
                # the running query raise QueryCancelled at its next
                # checkpoint — queued stages are abandoned and in-flight
                # compiles orphaned (physical/compiled.py stage graph)
                if cancel is not None:
                    cancel.set()
                fut.cancel()
                _tel.inc("server_cancels")
                tid = getattr(info, "trace_id", None)
                if tid and _events_on():
                    from ..runtime import events as _ev
                    _ev.publish("server.cancel", trace=tid, id=uid)
                self._send(200, None, headers=self._trace_headers(tid=tid))
                return
            self._send(404, {"error": "not found"})

    return Handler


def _error_payload(message: str, uid: str, exc: Exception = None) -> dict:
    """reference responses.py:119-139 ErrorResults shape: the reference's
    QueryError fills errorLocation from the parse error's position
    (``error.from_line + 1``/``from_col + 1``); our ParsingException
    carries 1-based (line, col) directly.

    Failures ride the typed taxonomy (runtime/resilience.py) onto the
    wire: ``errorType`` is USER_ERROR / INTERNAL_ERROR /
    INSUFFICIENT_RESOURCES and ``errorCode``/``errorName`` carry the
    classified verdict (EXCEEDED_TIME_LIMIT, EXCEEDED_MEMORY_LIMIT,
    USER_CANCELED, TRANSIENT_ERROR, ...) — not a stringified exception.
    Unrecognized exceptions escaping ``Context.sql`` classify as user
    errors at this boundary, preserving the reference's errorName
    (``str(type(exc))``) for them."""
    line = getattr(exc, "line", None)
    col = getattr(exc, "col", None)
    error_type, error_code = "USER_ERROR", 0
    error_name = str(type(exc)) if exc is not None else "GENERIC_ERROR"
    if exc is not None:
        err = _res.classify(exc, default=_res.UserError)
        if isinstance(err, _res.ResilienceError):
            error_type = err.error_type
            error_code = err.error_code
            if (isinstance(err, (_res.TransientError, _res.FatalError,
                                 _res.DeadlineExceeded, _res.QueryCancelled))
                    or err is exc):
                # engine verdicts use the taxonomy name; wrapped user
                # exceptions keep their own class name (reference shape)
                error_name = err.error_name
    return {
        "id": uid, "infoUri": "", "stats": _stats("FAILED"),
        "error": {
            "message": message, "errorCode": error_code,
            "errorName": error_name,
            "errorType": error_type,
            "errorLocation": {
                "lineNumber": line if isinstance(line, int) else 1,
                "columnNumber": col if isinstance(col, int) else 1,
            },
        },
    }


def run_server(context=None, host: str = "0.0.0.0", port: int = 8080,
               startup: bool = False, log_level=None, blocking: bool = True):
    """Start the SQL server (reference server/app.py:97-183).

    With ``blocking=False`` returns the (started) server object for tests.
    """
    if log_level:
        logging.basicConfig(level=log_level)
    from ..context import Context

    # fleet plane: arm before serving so the heartbeat registers this
    # replica even when an embedder passed a pre-built context (the
    # Context.__init__ hook is idempotent with this one)
    if _fleet_on():
        from ..runtime import fleet as _fleet
        _fleet.ensure_armed()
    context = context or Context()
    # continuous ingestion: arm on the serving context before the first
    # request — opens the WAL, replays committed batches for registered
    # tables, starts the micro-batch flusher (idempotent with the
    # Context.__init__ hook; env checked before the import)
    if _ingest_on():
        from ..runtime import ingest as _ing
        _ing.ensure_armed(context)
    if startup:
        context.sql("SELECT 1 + 1")

    state = _AppState(context)
    # bind first so port=0 (ephemeral) yields correct nextUri links
    server = ThreadingHTTPServer((host, port), _make_handler(state, ""))
    base_url = f"http://{host}:{server.server_port}"
    server.RequestHandlerClass = _make_handler(state, base_url)
    server.app_state = state
    # drain surface for embedders/tests (the signal handlers below call
    # the same procedure): returns immediately; state.drained (also
    # exposed as server.drained_event) is set when the drain completed
    server.drain_async = lambda reason="drain": threading.Thread(
        target=_drain_and_shutdown, args=(server, state, reason),
        daemon=True).start()
    server.drained_event = state.drained
    context.server = server
    if not blocking:
        t = threading.Thread(target=server.serve_forever, daemon=True)
        t.start()
        return server
    install_drain_handlers(server)
    try:
        logger.info("dask-sql-tpu server listening on %s", base_url)
        server.serve_forever()
    except KeyboardInterrupt:
        server.shutdown()
    return server


def main():  # pragma: no cover - console entry
    import argparse

    parser = argparse.ArgumentParser(description="dask-sql-tpu presto server")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=8080)
    parser.add_argument("--startup", action="store_true")
    parser.add_argument("--log-level", default=None)
    args = parser.parse_args()
    run_server(host=args.host, port=args.port, startup=args.startup,
               log_level=args.log_level)


if __name__ == "__main__":  # pragma: no cover
    main()
