"""Out-of-HBM table source: host-resident encoded batches, device-streamed.

The reference's entire execution model is out-of-core partitioned dataframes
(dask_sql over dd.DataFrame; ingestion partitioning at
/root/reference/dask_sql/input_utils/pandaslike.py:22, cluster persist at
input_utils/convert.py:59-60).  The TPU-first analogue: a table larger than
HBM lives on the HOST as already-encoded columnar batches (numpy: numeric
data + int32 string codes), and the streaming executor
(physical/streaming.py) uploads one fixed-size batch at a time, running the
same compiled program per batch.

Two invariants make per-batch execution compile ONCE instead of per batch:

- every batch is padded to exactly ``batch_rows`` with a row-validity mask
  (same machinery as mesh-mode padding), so all batches share shapes;
- string dictionaries are GLOBAL across batches (two-pass: union the
  per-batch uniques, then encode against the sorted union), so the program
  cache's dictionary-content fingerprint matches for every batch.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..runtime import faults as _faults, telemetry as _tel
from ..runtime.resilience import UserError
from ..table import Column, Table, host_encode_series

DEFAULT_BATCH_ROWS = 1 << 22  # 4M rows/batch ~= a few hundred MB on device


class ChunkedInputError(UserError, ValueError):
    """Unrepresentable input shape (typed for the resilience taxonomy;
    still a ValueError for callers predating the taxonomy)."""


class ChunkedSource:
    """Host-side encoded columnar batches with a shared schema."""

    def __init__(self, names: Sequence[str], stypes, dictionaries,
                 batches: List[list], n_rows: int, batch_rows: int):
        self.names = list(names)
        self.stypes = list(stypes)
        self.dictionaries = list(dictionaries)
        self.batches = batches          # list of [(data, mask), ...] per col
        self.n_rows = n_rows
        self.batch_rows = batch_rows

    # ------------------------------------------------------------ building
    @staticmethod
    def from_pandas(df, batch_rows: int = DEFAULT_BATCH_ROWS,
                    _precomputed_dicts: Optional[dict] = None
                    ) -> "ChunkedSource":
        """Encode a pandas frame into host batches (shared dictionaries)."""
        import pandas as pd  # noqa: F401

        n = len(df)
        batch_rows = max(int(batch_rows), 1)
        dicts = {}
        if _precomputed_dicts:
            dicts.update(_precomputed_dicts)
        from ..table import string_uniques

        # pass 1: global sorted dictionary per string-ish column (including
        # categoricals — their per-batch category order must not leak)
        for name in df.columns:
            if name in dicts:
                continue
            s = df[name]
            is_cat = isinstance(s.dtype, pd.CategoricalDtype)
            if s.dtype == object or is_cat or str(s.dtype) in ("string", "str"):
                if str(s.dtype) in ("string", "str"):
                    vals = s.to_numpy(dtype=object, na_value=None)
                else:
                    vals = s.astype(object).to_numpy()
                dicts[name] = string_uniques(vals)
        # pass 2: encode per batch against the shared dictionaries
        starts = list(range(0, max(n, 1), batch_rows))
        batches: List[list] = []
        names = list(df.columns)
        stypes: list = [None] * len(names)
        dictionaries: list = [None] * len(names)
        for s0 in starts:
            chunk = df.iloc[s0:s0 + batch_rows]
            enc = []
            for ci, name in enumerate(names):
                data, mask, stype, dictionary = host_encode_series(
                    chunk[name], dictionary=dicts.get(name))
                stypes[ci] = stype
                if dictionary is not None:
                    dictionaries[ci] = dictionary
                enc.append((data, mask))
            batches.append(enc)
        return ChunkedSource(names, stypes, dictionaries, batches, n,
                             batch_rows)

    @staticmethod
    def from_parquet(path: str, batch_rows: int = DEFAULT_BATCH_ROWS
                     ) -> "ChunkedSource":
        """Two-pass parquet ingestion that never materializes the whole file
        as one pandas frame: pass 1 unions per-row-group string uniques into
        global dictionaries, pass 2 encodes row groups into host batches."""
        import pyarrow.parquet as pq

        import pyarrow.types as patypes

        def _needs_global_dict(t) -> bool:
            # Any arrow type whose pandas conversion yields object values
            # must share ONE dictionary across row groups, or merged batches
            # decode against piece-0 codes (silent wrong results).  Covers
            # string/large_string/string_view, binary/large_binary/
            # fixed_size_binary/binary_view, and dictionary-of-any.
            for pred in ("is_string", "is_large_string", "is_string_view",
                         "is_binary", "is_large_binary",
                         "is_fixed_size_binary", "is_binary_view",
                         "is_dictionary"):
                fn = getattr(patypes, pred, None)
                if fn is not None and fn(t):
                    return True
            return False

        pf = pq.ParquetFile(path)
        schema = pf.schema_arrow
        for f in schema:
            if patypes.is_nested(f.type):
                raise ChunkedInputError(
                    f"from_parquet: column {f.name!r} has nested arrow type "
                    f"{f.type} — not representable as a columnar SQL type")
        str_cols = [f.name for f in schema if _needs_global_dict(f.type)]
        from ..table import string_uniques

        uniques = {c: [] for c in str_cols}
        if str_cols:
            for rg in range(pf.num_row_groups):
                tbl = pf.read_row_group(rg, columns=str_cols)
                for c in str_cols:
                    vals = tbl.column(c).to_pandas().astype(object).to_numpy()
                    uniques[c].append(string_uniques(vals))
        dicts = {c: np.unique(np.concatenate(u)).astype(object)
                 for c, u in uniques.items() if u}

        pieces = []
        source = None
        for batch in pf.iter_batches(batch_size=batch_rows):
            df = batch.to_pandas()
            piece = ChunkedSource.from_pandas(df, batch_rows=batch_rows,
                                              _precomputed_dicts=dicts)
            pieces.append(piece)
        if not pieces:
            df = pf.read().to_pandas()
            return ChunkedSource.from_pandas(df, batch_rows=batch_rows)
        source = pieces[0]
        for extra in pieces[1:]:
            for ci, name in enumerate(source.names):
                a, b = source.dictionaries[ci], extra.dictionaries[ci]
                if a is b:
                    continue
                if (a is None) != (b is None) or (
                        a is not None and not np.array_equal(a, b)):
                    # A column type slipped past _needs_global_dict and got
                    # per-piece local dictionaries; mixing their codes would
                    # silently decode wrong values.
                    raise ChunkedInputError(
                        f"from_parquet: column {name!r} produced differing "
                        "per-piece dictionaries; its arrow type needs a "
                        "global dictionary pass")
            source.batches.extend(extra.batches)
            source.n_rows += extra.n_rows
        # iter_batches can emit a short non-final batch at row-group edges;
        # re-batching keeps the fixed-size invariant the compiler relies on
        source._rebatch()
        return source

    def _rebatch(self) -> None:
        """Normalize to fixed-size batches after concatenating pieces.

        Incremental: source pieces stream through a per-column carry
        buffer and are RELEASED as they are consumed, so the transient
        footprint is bounded by one output batch plus one input piece —
        the table is never materialized as full contiguous host arrays
        (which would defeat out-of-core parquet ingestion at exactly the
        table sizes chunking exists for)."""
        if all(len(b[0][0]) == self.batch_rows for b in self.batches[:-1]):
            return
        cols = len(self.names)
        has_mask = [any(b[ci][1] is not None for b in self.batches)
                    for ci in range(cols)]
        dtypes = [self.batches[0][ci][0].dtype for ci in range(cols)]
        out: List[list] = []
        pending: List[list] = [[] for _ in range(cols)]  # (data, mask)
        pending_rows = 0

        def emit(k: int) -> None:
            nonlocal pending_rows
            enc = []
            for ci in range(cols):
                frags = pending[ci]
                datas, masks, got = [], [], 0
                while got < k:
                    data, mask = frags[0]
                    take = min(k - got, len(data))
                    datas.append(data[:take])
                    if has_mask[ci]:
                        masks.append(mask[:take] if mask is not None
                                     else np.ones(take, dtype=bool))
                    if take == len(data):
                        frags.pop(0)
                    else:
                        frags[0] = (data[take:],
                                    None if mask is None else mask[take:])
                    got += take
                data = (datas[0] if len(datas) == 1
                        else np.concatenate(datas))
                mask = None
                if has_mask[ci]:
                    mask = (masks[0] if len(masks) == 1
                            else np.concatenate(masks))
                enc.append((data, mask))
            pending_rows -= k
            out.append(enc)

        src = self.batches
        for bi in range(len(src)):
            piece = src[bi]
            src[bi] = None  # release: the carry buffer bounds memory
            n = len(piece[0][0]) if piece else 0
            for ci in range(cols):
                pending[ci].append(piece[ci])
            pending_rows += n
            while pending_rows >= self.batch_rows:
                emit(self.batch_rows)
        if pending_rows:
            emit(pending_rows)
        if not out:
            # zero-row table: keep the one-empty-batch invariant
            out.append([(np.zeros(0, dtype=dtypes[ci]), None)
                        for ci in range(cols)])
        self.batches = out

    # ----------------------------------------------------------- consuming
    @property
    def n_batches(self) -> int:
        return len(self.batches)

    def schema_table(self) -> Table:
        """A 1-row stub carrying names/stypes/dictionaries for BINDING only —
        the streaming executor intercepts execution before any path could
        compute on it (context guards this)."""
        import jax.numpy as jnp

        cols = []
        for ci, stype in enumerate(self.stypes):
            dtype = (self.batches[0][ci][0].dtype if self.batches
                     else np.float64)
            dictionary = self.dictionaries[ci]
            if stype.is_string and dictionary is None:
                dictionary = np.array([""], dtype=object)
            cols.append(Column(jnp.zeros(1, dtype=dtype), stype, None,
                               dictionary))
        return Table(self.names, cols)

    def batch_table(self, i: int) -> Tuple[Table, Optional["object"]]:
        """Device Table for batch i, padded to batch_rows (+ row_valid).

        The host→device upload is the ``chunked_read`` fault site: the
        consumer (physical/streaming.py _run_batches) retries transients —
        the encoded host batch is immutable, so a re-upload is safe."""
        import jax.numpy as jnp

        _faults.maybe_fail("chunked_read")
        enc = self.batches[i]
        n = len(enc[0][0]) if enc else 0
        pad = self.batch_rows - n
        cols = []
        upload_bytes = 0
        for ci, (data, mask) in enumerate(enc):
            if pad:
                data = np.concatenate(
                    [data, np.zeros(pad, dtype=data.dtype)])
                if mask is not None:
                    mask = np.concatenate([mask, np.zeros(pad, dtype=bool)])
            upload_bytes += int(data.nbytes) + (
                int(mask.nbytes) if mask is not None else 0)
            dev = jnp.asarray(data)
            m = None if mask is None else jnp.asarray(mask)
            cols.append(Column(dev, self.stypes[ci], m,
                               self.dictionaries[ci]))
        row_valid = None
        if pad:
            row_valid = jnp.arange(self.batch_rows) < n
        # upload size rides the enclosing stream_batch span: per-batch
        # host→device traffic is the streaming mode's dominant cost over a
        # tunneled TPU, so a slow batch should name its own byte count
        _tel.annotate(upload_bytes=upload_bytes)
        return Table(self.names, cols), row_valid
