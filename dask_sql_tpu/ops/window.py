"""Window-function kernels: sharded segmented scans instead of gather-to-one.

The reference collapses each PARTITION BY group to a single pandas partition
via groupby().apply (/root/reference/dask_sql/physical/rel/logical/
window.py:152-205) — a scalability cliff SURVEY §5 calls out.  Here windows
are computed as sorted segmented scans: lexsort by (partition, order keys),
run prefix-scan kernels, gather back to row order.

Everything on the main path is jit-trace-safe (no host syncs, static
shapes, no scatters): the compiled whole-plan executor
(physical/compiled.py) calls ``compute_window`` directly inside its trace;
only NTILE/LAG/LEAD/NTH_VALUE read their constant arguments from column
data on the host and stay eager-only.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..table import dict_sort_order, Column, Scalar, Table
from ..types import SqlType, physical_dtype
from .kernels import (append_lexsort_operands, comparable_data, key_parts)

# window ops whose kernels are fully trace-safe (the compiled executor's
# supported subset; the rest read host constants)
TRACE_SAFE_OPS = frozenset({
    "ROW_NUMBER", "RANK", "DENSE_RANK", "PERCENT_RANK", "CUME_DIST",
    "COUNT", "SUM", "$SUM0", "AVG", "MIN", "MAX",
    "FIRST_VALUE", "LAST_VALUE", "SINGLE_VALUE",
})


def _segment_starts(codes_sorted: jax.Array) -> jax.Array:
    n = codes_sorted.shape[0]
    if n == 0:
        return jnp.zeros(0, dtype=bool)
    first = jnp.ones(1, dtype=bool)
    rest = codes_sorted[1:] != codes_sorted[:-1]
    return jnp.concatenate([first, rest])


def _segment_ids(starts: jax.Array) -> jax.Array:
    return jnp.cumsum(starts.astype(jnp.int64)) - 1


def _adjacent_diff(channels, n: int) -> jax.Array:
    """Row 0 True; row i True iff ANY channel differs from row i-1.
    Channels are already sorted streams — boundary detection without
    post-sort gathers (group equality == equality of every sort channel)."""
    if n == 0:
        return jnp.zeros(0, dtype=bool)
    diff = jnp.zeros(n - 1, dtype=bool)
    for ch in channels:
        diff = diff | (ch[1:] != ch[:-1])
    return jnp.concatenate([jnp.ones(1, dtype=bool), diff])


def segmented_cumsum(x: jax.Array, starts: jax.Array) -> jax.Array:
    """Inclusive prefix sum that resets at segment starts (trace-safe:
    log-depth segmented scan, no data-dependent shapes)."""
    return segmented_scan(x, starts, jnp.add)


def segmented_scan(x: jax.Array, starts: jax.Array, combine) -> jax.Array:
    """Generic inclusive segmented scan via associative_scan on (flag, value)."""

    def op(a, b):
        fa, va = a
        fb, vb = b
        return (fa | fb, jnp.where(fb, vb, combine(va, vb)))

    flags = starts
    _, out = jax.lax.associative_scan(op, (flags, x))
    return out


def window_frame_sums(x: jax.Array, start: jax.Array, end: jax.Array):
    """Moving SUM/COUNT over per-row frame bounds using one prefix sum.

    ``start``/``end`` are PER-ROW inclusive positions in sorted order
    (already clipped to the row's segment); an empty frame is
    ``end < start`` and sums to 0.
    """
    n = x.shape[0]
    prefix = jnp.cumsum(x)
    end_c = jnp.clip(end, 0, n - 1)
    start_c = jnp.clip(start, 0, n - 1)
    upper = prefix[end_c]
    lower = jnp.where(start_c > 0, prefix[jnp.maximum(start_c - 1, 0)], 0)
    return jnp.where(end < start, 0, upper - lower)


def compute_window(table: Table, op: str, arg_cols: List[int],
                   partition_cols: List[int],
                   order_keys: List[Tuple[int, bool, bool]],
                   frame, stype: SqlType,
                   row_valid: Optional[jax.Array] = None) -> Column:
    """Compute one window call; returns a column aligned with table rows.

    ``row_valid`` (compiled-executor mode): invalid/padding rows sort into
    their own trailing segment so they never contaminate real partitions;
    their outputs are garbage and must be masked by the caller's validity.
    """
    n = table.num_rows
    if n == 0:
        return Column(jnp.zeros(0, dtype=physical_dtype(stype)), stype)

    from .pallas_kernels import _strategy_on_tpu as _on_tpu
    on_tpu = _on_tpu()

    # 1. sort by (validity, partition, order keys) — trace-safe: partitions
    # come from key-part comparisons, not a factorize. Arrays are built
    # least-significant-first (jnp.lexsort order); the argument column rides
    # the sort as a payload operand on TPU, where a random n-element gather
    # costs ~2x a whole extra sort operand (profiled on the join path).
    arrays = []
    for idx, asc, nulls_first in reversed(order_keys):
        col = table.columns[idx]
        data = comparable_data(col)
        if jnp.issubdtype(data.dtype, jnp.integer):
            data = data.astype(jnp.int64)
        if not asc:
            data = -data if not jnp.issubdtype(data.dtype, jnp.bool_) else ~data
        if col.mask is not None:
            nullkey = (~col.mask).astype(jnp.int8)
            arrays.append(data)
            arrays.append(nullkey if not nulls_first else -nullkey)
        else:
            arrays.append(data)
    n_ord_ops = len(arrays)
    part_parts = key_parts([table.columns[i] for i in partition_cols]) \
        if partition_cols else []
    append_lexsort_operands(arrays, list(reversed(part_parts)))
    if row_valid is not None:
        arrays.append((~row_valid).astype(jnp.int8))  # invalid rows last

    pay: List[jax.Array] = []
    arg_slot = None
    arg_col0 = table.columns[arg_cols[0]] if arg_cols else None
    if arg_col0 is not None and op != "NTILE":
        arg_slot = (len(pay), arg_col0.mask is not None)
        pay.append(arg_col0.data)
        if arg_col0.mask is not None:
            pay.append(arg_col0.mask)

    keys_msf = list(reversed(arrays))  # most significant first
    if not keys_msf:
        perm = jnp.arange(n)
        keys_sorted: List[jax.Array] = []
        pay_sorted = list(pay)
    elif on_tpu:
        iota = jnp.arange(n, dtype=jnp.int64)
        outs = jax.lax.sort(tuple(keys_msf) + (iota,) + tuple(pay),
                            num_keys=len(keys_msf), is_stable=True)
        perm = outs[len(keys_msf)]
        keys_sorted = list(outs[:len(keys_msf)])
        pay_sorted = list(outs[len(keys_msf) + 1:])
    else:
        perm = jnp.lexsort(tuple(arrays))
        keys_sorted = [k[perm] for k in keys_msf]
        pay_sorted = [p[perm] for p in pay]

    def sorted_arg() -> Column:
        di, has_mask = arg_slot
        return Column(pay_sorted[di], arg_col0.stype,
                      pay_sorted[di + 1] if has_mask else None,
                      arg_col0.dictionary)

    # 2. segment starts from adjacent diffs over the SORTED partition (and
    # validity) channels — no gathers; tie groups reuse the order channels
    n_seg_ops = len(keys_msf) - n_ord_ops
    starts = _adjacent_diff(keys_sorted[:n_seg_ops], n)
    tie = _adjacent_diff(keys_sorted[n_seg_ops:], n) & ~starts \
        if order_keys else jnp.zeros(n, dtype=bool)
    pos = jnp.arange(n)
    # per-row segment bounds via forward/backward segmented scans
    seg_start = segmented_scan(pos, starts, jnp.minimum)
    # reversed-stream segment starts: original row i is last-of-segment iff
    # i == n-1 or starts[i+1]; flipping that gives the reverse-scan flags
    ends_flags = jnp.concatenate([jnp.ones(1, bool), jnp.flip(starts[1:])])
    seg_end = jnp.flip(segmented_scan(jnp.flip(pos), ends_flags, jnp.maximum))
    row_in_seg = pos - seg_start

    # peer-group (tie) bounds under the ORDER BY keys: SQL's default frame
    # and RANGE CURRENT ROW are PEER-inclusive (PostgreSQL/SQLite agree;
    # treating them as row bounds was the r4 oracle-caught bug)
    _frame_consumers = ("COUNT", "SUM", "$SUM0", "AVG", "MIN", "MAX",
                        "FIRST_VALUE", "LAST_VALUE", "NTH_VALUE",
                        "SINGLE_VALUE", "CUME_DIST")
    if order_keys:
        tie_start = segmented_scan(jnp.where(tie | starts, pos, -1), starts,
                                   jnp.maximum)
        if op in _frame_consumers:
            # two extra passes — only ops that read frame bounds (or
            # CUME_DIST) pay them; rank/navigation ops skip
            is_last_of_tie = jnp.concatenate([tie[1:] | starts[1:],
                                              jnp.ones(1, bool)])
            tie_end = _backward_fill_positions(pos, is_last_of_tie, seg_end)
        else:
            tie_end = seg_end
    else:
        tie_start, tie_end = seg_start, seg_end

    def _value_bound(delta: float, side: str) -> jax.Array:
        """RANGE <offset> PRECEDING/FOLLOWING: positions by ORDER BY value.
        Works on the TRANSFORMED sort channel (DESC already negated), so
        the frame is uniformly [t-delta_lo, t+delta_hi] in sorted space; a
        per-segment float offset larger than the global value span makes
        one globally sorted composite, so a single searchsorted respects
        segment boundaries by construction."""
        if len(order_keys) != 1:
            raise NotImplementedError(
                "RANGE offset frame requires exactly one ORDER BY key")
        kcol = table.columns[order_keys[0][0]]
        if kcol.mask is not None:
            raise NotImplementedError(
                "RANGE offset frame over a nullable ORDER BY key")
        t = keys_sorted[n_seg_ops]
        if not (jnp.issubdtype(t.dtype, jnp.integer)
                or jnp.issubdtype(t.dtype, jnp.floating)):
            raise NotImplementedError(
                "RANGE offset frame requires a numeric ORDER BY key")
        tf = t.astype(jnp.float64)
        # real = finite values of VALID rows: compiled-mode padding rows
        # carry arbitrary gather garbage, and NaN order keys sort last
        # within their segment — either would inflate the composite offset
        # (destroying float64 precision for real rows) or break the global
        # sortedness searchsorted requires.  Replace both with max_real+1:
        # still sorted, real rows' bounds unaffected up to the documented
        # edge that a NaN "peer of NaN" may absorb near-max neighbors.
        # (Limitation: int64 keys above 2^53 lose ULPs here — ns-epoch
        # timestamps order correctly but offset frames on them are
        # approximate.)
        real = jnp.isfinite(tf)
        if row_valid is not None:
            real = real & (keys_sorted[0] == 0)  # invalid rows sort last
        any_real = real.any()
        lo_r = jnp.min(jnp.where(real, tf, jnp.inf))
        hi_r = jnp.max(jnp.where(real, tf, -jnp.inf))
        lo_r = jnp.where(any_real, lo_r, 0.0)
        hi_r = jnp.where(any_real, hi_r, 0.0)
        # -inf sorted first in its segment -> clamp low; +inf/NaN/garbage
        # sorted last -> clamp high: per-segment order is preserved
        neg = jnp.isneginf(tf)
        tf_c = jnp.where(real, tf,
                         jnp.where(neg, lo_r - 1.0, hi_r + 1.0))
        span = hi_r - lo_r + 2.0
        big = span + jnp.float64(abs(delta) + 1.0)
        seg_id = jnp.cumsum(starts.astype(jnp.int64)).astype(jnp.float64)
        g = tf_c + seg_id * big
        method = "sort" if on_tpu else "scan"
        if side == "start":
            return jnp.searchsorted(g, g + delta, side="left", method=method)
        return jnp.searchsorted(g, g + delta, side="right",
                                method=method) - 1

    def _resolve_bound(bound, which: str, kind: str):
        """(positions, kind) for one frame bound; kind in
        'unb' | 'fixed' (row offset) | 'var' (peer/value positions)."""
        tag, nval = bound
        if tag == "UNBOUNDED_PRECEDING":
            return seg_start, "unb"
        if tag == "UNBOUNDED_FOLLOWING":
            return seg_end, "unb"
        if tag == "CURRENT":
            if kind == "RANGE":
                # peers of the current row; with no ORDER BY every
                # partition row is a peer (tie bounds = segment bounds)
                return (tie_start if which == "lo" else tie_end), "var"
            return pos, "fixed"
        delta = -float(nval) if tag == "PRECEDING" else float(nval)
        if kind == "ROWS":
            off = int(delta)
            arr = pos + off
            arr = (jnp.maximum(arr, seg_start) if which == "lo"
                   else jnp.minimum(arr, seg_end))
            return arr, "fixed"
        return _value_bound(delta, "start" if which == "lo" else "end"), "var"

    # resolve the frame to per-row inclusive [fstart, fend] positions
    if frame is None:
        if order_keys and op not in ("ROW_NUMBER", "RANK", "DENSE_RANK"):
            # SQL default: RANGE UNBOUNDED PRECEDING .. CURRENT ROW
            fstart, lo_kind = seg_start, "unb"
            fend, hi_kind = tie_end, "var"
        else:
            fstart, lo_kind = seg_start, "unb"
            fend, hi_kind = seg_end, "unb"
        lo_off, hi_off = None, None
    else:
        kind = frame[0]
        fstart, lo_kind = _resolve_bound(frame[1], "lo", kind)
        fend, hi_kind = _resolve_bound(frame[2], "hi", kind)
        # row offsets kept for the MIN/MAX fixed-width fast path
        lo_off = (int(-frame[1][1]) if frame[1][0] == "PRECEDING"
                  else int(frame[1][1]) if frame[1][0] == "FOLLOWING" else 0)
        hi_off = (int(-frame[2][1]) if frame[2][0] == "PRECEDING"
                  else int(frame[2][1]) if frame[2][0] == "FOLLOWING" else 0)

    def scatter_back(sorted_vals, mask_sorted=None):
        # un-sort to original row order: payload sort on TPU, argsort +
        # gather elsewhere (mirrors the join/groupby backend split)
        if on_tpu:
            chs = ((perm, sorted_vals) if mask_sorted is None
                   else (perm, sorted_vals, mask_sorted))
            outs2 = jax.lax.sort(chs, num_keys=1)
            out = outs2[1]
            m = outs2[2] if mask_sorted is not None else None
        else:
            inv_perm = jnp.argsort(perm)
            out = sorted_vals[inv_perm]
            m = None if mask_sorted is None else mask_sorted[inv_perm]
        return Column(out.astype(physical_dtype(stype)) if not stype.is_string else out,
                      stype, m)

    if op == "ROW_NUMBER":
        return scatter_back(row_in_seg + 1)

    if op in ("RANK", "DENSE_RANK", "PERCENT_RANK", "CUME_DIST"):
        # rank = position of the first row of the current tie group
        # (tie_start/tie_end hoisted above, shared with frame resolution)
        rank = tie_start - seg_start + 1
        if op == "RANK":
            return scatter_back(rank)
        if op == "PERCENT_RANK":
            seg_len = seg_end - seg_start + 1
            pr = jnp.where(seg_len > 1, (rank - 1) / jnp.maximum(seg_len - 1, 1), 0.0)
            return scatter_back(pr)
        if op == "CUME_DIST":
            seg_len = seg_end - seg_start + 1
            # number of rows with order key <= current = end of tie group
            return scatter_back((tie_end - seg_start + 1) / seg_len)
        # DENSE_RANK: count of tie-group starts up to here within segment
        dr = segmented_cumsum((tie | starts).astype(jnp.int64), starts)
        return scatter_back(dr)

    if op == "NTILE":
        k = int(np.asarray(table.columns[arg_cols[0]].data)[0]) if arg_cols else 1
        seg_len = seg_end - seg_start + 1
        out = (row_in_seg * k) // jnp.maximum(seg_len, 1) + 1
        return scatter_back(out)

    if op in ("LAG", "LEAD"):
        col = table.columns[arg_cols[0]]
        offset = 1
        if len(arg_cols) > 1:
            offset = int(np.asarray(table.columns[arg_cols[1]].data)[0])
        shift = -offset if op == "LAG" else offset
        src = pos + shift
        valid = (src >= seg_start) & (src <= seg_end)
        src = jnp.clip(src, 0, n - 1)
        sorted_col = sorted_arg()
        gathered = sorted_col.take(src)
        m = gathered.valid_mask() & valid
        out = scatter_back(gathered.data, m)
        if col.stype.is_string:
            return Column(out.data.astype(jnp.int32), stype, out.mask, col.dictionary)
        return out

    if op in ("FIRST_VALUE", "LAST_VALUE", "NTH_VALUE"):
        # frame-aware (the standard applies the window frame to these):
        # FIRST_VALUE = first frame row, LAST_VALUE = last frame row —
        # under the default frame that is the segment start / the current
        # row's LAST PEER (not the current row: ties share a value)
        col = sorted_arg()
        in_frame = fend >= fstart
        if op == "FIRST_VALUE":
            src = fstart
        elif op == "LAST_VALUE":
            src = fend
        else:
            k = int(np.asarray(table.columns[arg_cols[1]].data)[0])
            src = fstart + (k - 1)
            in_frame = in_frame & (src <= fend)
            src = jnp.minimum(src, jnp.maximum(fend, fstart))
        src = jnp.clip(src, 0, n - 1)
        gathered = col.take(src)
        m = gathered.valid_mask() & in_frame
        out = scatter_back(gathered.data, m)
        if col.stype.is_string:
            return Column(out.data.astype(jnp.int32), stype, out.mask, col.dictionary)
        return out

    # aggregate window functions
    if op in ("COUNT",):
        if arg_cols:
            col = sorted_arg()
            x = col.valid_mask().astype(jnp.int64)
        else:
            x = jnp.ones(n, dtype=jnp.int64)
        out = window_frame_sums(x, fstart, fend)
        return scatter_back(out)

    if op in ("SUM", "$SUM0", "AVG"):
        col = sorted_arg()
        valid = col.valid_mask()
        data = jnp.where(valid, col.data, 0)
        if jnp.issubdtype(data.dtype, jnp.integer):
            data = data.astype(jnp.int64)
        else:
            data = data.astype(jnp.float64)
        s = window_frame_sums(data, fstart, fend)
        c = window_frame_sums(valid.astype(jnp.int64), fstart, fend)
        if op == "AVG":
            out = s / jnp.maximum(c, 1)
            return scatter_back(out, (c > 0))
        if op == "$SUM0":
            return scatter_back(s)
        return scatter_back(s, (c > 0))

    if op in ("MIN", "MAX"):
        col = sorted_arg()
        valid = col.valid_mask()
        data = comparable_data(col)
        if jnp.issubdtype(data.dtype, jnp.integer):
            data = data.astype(jnp.int64)
            sentinel = jnp.iinfo(jnp.int64).max if op == "MIN" else jnp.iinfo(jnp.int64).min
        else:
            data = data.astype(jnp.float64)
            sentinel = jnp.inf if op == "MIN" else -jnp.inf
        x = jnp.where(valid, data, sentinel)
        combine = jnp.minimum if op == "MIN" else jnp.maximum
        if lo_kind == "unb" and hi_kind == "unb":
            # whole partition: segment reduce then broadcast
            total = segmented_scan(x, starts, combine)
            out = total[seg_end]
        elif lo_kind == "unb":
            # UNBOUNDED PRECEDING .. bound: prefix scan + one gather (an
            # O(n) shift loop here would build an O(n^2) trace); fend may
            # be peer- or value-based — the gather covers all cases
            fwd = segmented_scan(x, starts, combine)
            out = fwd[jnp.clip(fend, seg_start, seg_end)]
        elif hi_kind == "unb":
            # bound .. UNBOUNDED FOLLOWING: suffix scan + one gather
            bwd = jnp.flip(segmented_scan(jnp.flip(x), ends_flags, combine))
            out = bwd[jnp.clip(fstart, seg_start, seg_end)]
        elif lo_kind == "var" or hi_kind == "var":
            raise NotImplementedError(
                "MIN/MAX over a RANGE frame bounded on both sides")
        else:
            # bounded frame: van Herk two-scan sliding window — O(n) for any
            # frame width w. Width-w blocks get prefix/suffix scans; an
            # UNCLIPPED frame [a, a+w-1] spans at most two blocks, so
            # combine(blocksuffix[a], blockprefix[b]) covers it exactly.
            # Frames clipped by a segment edge lose the alignment guarantee,
            # so those rows select from plain segment scans instead.
            w = max(hi_off - lo_off + 1, 1)
            a_raw = pos + lo_off
            b_raw = pos + hi_off
            low_clip = a_raw < seg_start
            high_clip = b_raw > seg_end
            block_flags = (pos % w) == 0
            fwd_vh = segmented_scan(x, starts | block_flags, combine)
            rev_block = jnp.flip((pos % w) == (w - 1))
            rev_block = rev_block.at[0].set(True)
            bwd_vh = jnp.flip(segmented_scan(jnp.flip(x),
                                             ends_flags | rev_block, combine))
            fwd_seg = segmented_scan(x, starts, combine)
            bwd_seg = jnp.flip(segmented_scan(jnp.flip(x), ends_flags,
                                              combine))
            a_s = jnp.clip(a_raw, 0, n - 1)
            b_s = jnp.clip(b_raw, 0, n - 1)
            vh = combine(bwd_vh[a_s], fwd_vh[b_s])
            cum = fwd_seg[jnp.clip(b_raw, seg_start, seg_end)]
            suf = bwd_seg[jnp.clip(a_raw, seg_start, seg_end)]
            tot = fwd_seg[seg_end]
            out = jnp.where(low_clip & high_clip, tot,
                            jnp.where(low_clip, cum,
                                      jnp.where(high_clip, suf, vh)))
            in_frame_cnt = window_frame_sums(valid.astype(jnp.int64),
                                             fstart, fend)
            m = in_frame_cnt > 0
            if col.stype.is_string:
                return _ranks_to_string(scatter_back(out, m), table.columns[arg_cols[0]], stype)
            return scatter_back(out, m)
        c = window_frame_sums(valid.astype(jnp.int64), fstart, fend)
        m = c > 0
        if col.stype.is_string:
            return _ranks_to_string(scatter_back(out, m),
                                    table.columns[arg_cols[0]], stype)
        return scatter_back(out, m)

    if op == "SINGLE_VALUE":
        col = sorted_arg()
        src = seg_start
        g = col.take(src)
        out = scatter_back(g.data, g.mask)
        if col.stype.is_string:
            return Column(out.data.astype(jnp.int32), stype, out.mask, col.dictionary)
        return out

    raise NotImplementedError(f"Window function {op}")


def _ranks_to_string(rank_col: Column, orig: Column, stype: SqlType) -> Column:
    order = dict_sort_order(orig.dictionary)
    inv = jnp.asarray(order.astype(np.int64))
    safe = jnp.clip(rank_col.data.astype(jnp.int64), 0, len(order) - 1)
    codes = jnp.take(inv, safe).astype(jnp.int32)
    return Column(codes, stype, rank_col.mask, orig.dictionary)


def _backward_fill_positions(pos, is_last, seg_end):
    """For each row, position of the last row of its tie group."""
    n = pos.shape[0]
    # reverse scan: propagate next is_last position backwards
    rev = jnp.flip(jnp.where(is_last, pos, -1))
    rev_filled = jax.lax.associative_scan(
        lambda a, b: jnp.where(b >= 0, b, a), rev)
    # associative_scan is forward; combined op keeps latest valid
    filled = jnp.flip(rev_filled)
    return jnp.where(filled >= 0, filled, seg_end)
