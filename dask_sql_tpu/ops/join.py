"""Equi-join kernels: sort-probe pair expansion on device.

TPU-native replacement for the reference's join lowering
(/root/reference/dask_sql/physical/rel/logical/join.py:20-313): the reference
splits the condition into equi pairs + residual filter (join.py:245-284),
delegates equi joins to dask's shuffle merge, hand-builds a partition-pair
cross-join graph for non-equi (join.py:111-152), filters NULL keys
(join.py:224-235) and patches lost outer rows (join.py:174-194).

Here: keys factorize onto a shared domain (kernels.join_key_codes), the build
side is sorted by code, probes binary-search their run, and matched pairs are
materialized with a cumsum expansion — all jnp ops; sizes sync to host once
per join (eager stage execution).
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..table import Column, Table
from .kernels import join_key_codes, mask_to_indices


def _expand_matches(lcodes: jax.Array, rcodes: jax.Array
                    ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Compute matching (left_row, right_row) index pairs for equi keys.

    Returns (left_idx, right_idx, left_match_count).  Code -1 never matches.
    """
    order = jnp.argsort(rcodes, stable=True)
    sorted_r = rcodes[order]
    start = jnp.searchsorted(sorted_r, lcodes, side="left")
    stop = jnp.searchsorted(sorted_r, lcodes, side="right")
    counts = jnp.where(lcodes >= 0, stop - start, 0)
    total = int(counts.sum())
    offsets = jnp.cumsum(counts)
    idx = jnp.arange(total)
    li = jnp.searchsorted(offsets, idx, side="right")
    prev = jnp.where(li > 0, offsets[jnp.maximum(li - 1, 0)], 0)
    within = idx - prev
    rpos = start[li] + within
    ri = order[rpos]
    return li, ri, counts


def join_tables(left: Table, right: Table, left_keys: List[int],
                right_keys: List[int], join_type: str,
                null_aware_anti: bool = False,
                null_equal: bool = False,
                variant: str = "hash") -> Tuple[Table, Optional[jax.Array]]:
    """Equi-join two tables.

    Returns (joined_table, matched_pair_row_origin) where the joined table has
    left columns then right columns.  For SEMI/ANTI only left columns.
    Outer-join unmatched rows are appended after the matched pairs with NULLs
    on the other side.

    ``variant="dense"`` (stats-driven) takes the direct-index key coding —
    ``codes = key - min``, no shared-domain sort — when the key pair is a
    single integer column; see kernels.join_key_codes.
    """
    nl, nr = left.num_rows, right.num_rows
    if left_keys:
        lcodes, rcodes = join_key_codes(
            [left.columns[i] for i in left_keys],
            [right.columns[i] for i in right_keys],
            null_equal=null_equal, variant=variant,
        )
    else:
        # cross join: all pairs
        lcodes = jnp.zeros(nl, dtype=jnp.int64)
        rcodes = jnp.zeros(nr, dtype=jnp.int64)

    if join_type == "SEMI":
        li, ri, counts = _expand_matches(lcodes, rcodes)
        keep = mask_to_indices(counts > 0)
        return left.take(keep), None
    if join_type == "ANTI":
        li, ri, counts = _expand_matches(lcodes, rcodes)
        if null_aware_anti:
            # NOT IN semantics: if the build side contains any NULL key,
            # nothing qualifies; rows with NULL probe keys qualify only
            # when the build side is EMPTY (x NOT IN (empty) is TRUE for
            # every x, NULL included — PostgreSQL/SQLite agree).
            build_has_null = bool((rcodes < 0).any()) if nr else False
            if build_has_null:
                return left.take(jnp.zeros(0, dtype=jnp.int64)), None
            keep = mask_to_indices((counts == 0)
                                   & ((lcodes >= 0) | (nr == 0)))
        else:
            keep = mask_to_indices(counts == 0)
        return left.take(keep), None

    li, ri, counts = _expand_matches(lcodes, rcodes)
    return _assemble(left, right, li, ri, counts, rcodes, join_type)


def _assemble(left: Table, right: Table, li, ri, counts, rcodes,
              join_type: str) -> Tuple[Table, Optional[jax.Array]]:
    nl, nr = left.num_rows, right.num_rows
    n_pairs = int(li.shape[0])

    lt = left.take(li)
    rt = right.take(ri)

    extra_left = extra_right = None
    if join_type in ("LEFT", "FULL"):
        extra_left = mask_to_indices(counts == 0)
    if join_type in ("RIGHT", "FULL"):
        matched_r = jnp.zeros(nr, dtype=bool)
        if n_pairs:
            matched_r = matched_r.at[ri].set(True)
        extra_right = mask_to_indices(~matched_r)

    parts_l, parts_r = [lt], [rt]
    if extra_left is not None and int(extra_left.shape[0]):
        parts_l.append(left.take(extra_left))
        parts_r.append(_null_table(right, int(extra_left.shape[0])))
    if extra_right is not None and int(extra_right.shape[0]):
        parts_l.append(_null_table(left, int(extra_right.shape[0])))
        parts_r.append(right.take(extra_right))

    lfull = concat_tables(parts_l) if len(parts_l) > 1 else parts_l[0]
    rfull = concat_tables(parts_r) if len(parts_r) > 1 else parts_r[0]
    out = Table(lfull.names + rfull.names, lfull.columns + rfull.columns)
    return out, None


def rejoin_outer(left: Table, right: Table, pairs_table: Table,
                 keep_pairs: jax.Array, li: jax.Array, ri: jax.Array,
                 join_type: str) -> Table:
    """Apply a residual filter to matched pairs, then restore unmatched outer
    rows (the reference's lost-row recovery, join.py:174-194)."""
    kept = mask_to_indices(keep_pairs)
    surviving = pairs_table.take(kept)
    parts = [surviving]
    if join_type in ("LEFT", "FULL"):
        has = jnp.zeros(left.num_rows, dtype=bool)
        lk = li[kept]
        if int(lk.shape[0]):
            has = has.at[lk].set(True)
        missing = mask_to_indices(~has)
        if int(missing.shape[0]):
            lt = left.take(missing)
            rt = _null_table(right, int(missing.shape[0]))
            parts.append(Table(lt.names + rt.names, lt.columns + rt.columns))
    if join_type in ("RIGHT", "FULL"):
        has = jnp.zeros(right.num_rows, dtype=bool)
        rk = ri[kept]
        if int(rk.shape[0]):
            has = has.at[rk].set(True)
        missing = mask_to_indices(~has)
        if int(missing.shape[0]):
            lt = _null_table(left, int(missing.shape[0]))
            rt = right.take(missing)
            parts.append(Table(lt.names + rt.names, lt.columns + rt.columns))
    return concat_tables(parts) if len(parts) > 1 else parts[0]


def _null_table(src: Table, n: int) -> Table:
    from ..table import Scalar
    cols = []
    for c in src.columns:
        null_col = Column.from_scalar(Scalar(None, c.stype), n)
        if c.stype.is_string:
            null_col = Column(null_col.data, c.stype, null_col.mask, c.dictionary)
        cols.append(null_col)
    return Table(list(src.names), cols)


def concat_tables(tables: List[Table]) -> Table:
    """Row-wise concatenation with dictionary merging for strings."""
    if len(tables) == 1:
        return tables[0]
    names = tables[0].names
    out_cols = []
    for ci in range(len(names)):
        cols = [t.columns[ci] for t in tables]
        out_cols.append(concat_columns(cols))
    return Table(list(names), out_cols)


def concat_columns(cols: List[Column]) -> Column:
    t0 = cols[0]
    if t0.stype.is_string:
        dicts = [c.dictionary.astype(str) for c in cols]
        union = np.unique(np.concatenate(dicts))
        datas = []
        for c, d in zip(cols, dicts):
            remap = np.searchsorted(union, d).astype(np.int32)
            datas.append(jnp.take(jnp.asarray(remap), jnp.clip(c.data, 0, max(len(d) - 1, 0))))
        data = jnp.concatenate(datas)
        masks = _concat_masks(cols)
        return Column(data, t0.stype, masks, union.astype(object))
    dt = cols[0].data.dtype
    for c in cols[1:]:
        dt = jnp.promote_types(dt, c.data.dtype)
    data = jnp.concatenate([c.data.astype(dt) for c in cols])
    return Column(data, t0.stype, _concat_masks(cols))


def _concat_masks(cols: List[Column]):
    if all(c.mask is None for c in cols):
        return None
    return jnp.concatenate([c.valid_mask() for c in cols])


def cross_join_pairs(nl: int, nr: int) -> Tuple[jax.Array, jax.Array]:
    li = jnp.repeat(jnp.arange(nl), nr)
    ri = jnp.tile(jnp.arange(nr), nl)
    return li, ri
