"""Pallas TPU kernels for the engine's hot loops.

The flagship kernel is a fused masked segmented reduction: SQL's
``SELECT agg(x) ... GROUP BY k`` with a small static group domain (Q1 shape).
Instead of XLA scatter-adds (slow on TPU) or a sort-based factorize, each
row block builds its one-hot group matrix in VMEM and contracts it against
the value rows on the MXU:

    out[a, g] += sum_i vals[a, i] * (codes[i] == g & mask[i])

The one-hot never touches HBM — it exists per block in VMEM — so the kernel
is bandwidth-bound on the value stream alone, the MXU does the reduction,
and the grid accumulates partials into the (A, G) output block across steps.

The reference has no analogue (its groupby is a dask tree reduction over
pandas partitions, aggregate.py:325-361); this is the SURVEY §7 "pallas
kernels where XLA ops are awkward" item for groupby.

On non-TPU backends the kernel runs in interpreter mode (tests), keeping one
code path.
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl


def _x64_scope(enabled: bool):
    """Context manager toggling x64 tracing: ``jax.enable_x64`` where it
    exists, the ``jax.experimental`` spelling on older jax (0.4.x)."""
    if hasattr(jax, "enable_x64"):
        return jax.enable_x64(enabled)
    from jax.experimental import enable_x64 as _e
    return _e(enabled)

BLOCK = 1024       # rows per grid step (lane-aligned multiple of 128)
GROUP_TILE = 128   # group-axis padding (last-dim tile width)


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _backend_is_tpu() -> bool:
    """The UNPATCHED hardware truth, gating pallas ``interpret=`` only:
    tests monkeypatch ``_on_tpu`` to force kernel strategies on CPU, but a
    non-interpret ``pallas_call`` on a non-TPU backend is a hard error
    (jax 0.4.x: "Only interpret mode is supported on CPU backend") — the
    interpret decision must never be fooled by a strategy override."""
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _strategy_on_tpu() -> bool:
    """Which KERNEL STRATEGY to trace — sort-based merge join / payload-
    through-sort groupby (TPU-shaped: no scatters) vs hash-table join /
    scatter groupby (host-shaped: scatters are ~1 ms where sorts are
    hundreds).  Distinct from ``_on_tpu`` (the hardware truth, which gates
    pallas ``interpret=``): ``DSQL_STRATEGY=tpu|host`` forces a strategy on
    either backend — the driver bench uses ``host`` on the tunneled TPU
    because the merge join's variadic sorts compile ~8x slower there
    (~200 s/query) while the hash program compiles in ~25 s."""
    s = os.environ.get("DSQL_STRATEGY", "auto").lower()
    if s == "tpu":
        return True
    if s in ("host", "cpu"):
        return False
    return _on_tpu()


def _seg_matmul_kernel(codes_ref, mask_ref, vals_ref, out_ref):
    """One grid step: accumulate this row block's per-group partial sums."""
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    codes = codes_ref[:]                      # (1, BLOCK) int32
    mask = mask_ref[:]                        # (1, BLOCK) int32 0/1
    g = out_ref.shape[1]
    # mask arrives as int32 and the masking is arithmetic (multiply), not a
    # bool select: Mosaic supports neither minor-dim insertion nor select_n
    # on 1-bit types
    onehot = (codes.reshape(-1, 1)
              == jax.lax.broadcasted_iota(jnp.int32, (codes.shape[1], g), 1)
              ).astype(out_ref.dtype)
    onehot = onehot * mask.reshape(-1, 1).astype(out_ref.dtype)
    out_ref[:] += jnp.dot(vals_ref[:].astype(out_ref.dtype), onehot,
                          preferred_element_type=out_ref.dtype)


def _seg_matmul_perblock_kernel(codes_ref, mask_ref, vals_ref, out_ref):
    """One grid step: THIS block's per-group partial sums, written to the
    step's own output ROWS (out is (grid*A, g) 2D; step i owns rows
    [i*A, (i+1)*A) — no cross-step accumulation).  Exactness contract: with
    |vals| <= 4095 and BLOCK_EXACT rows, each f32 partial is an integer
    < 2**24 and therefore exact; the caller accumulates the per-block row
    slices in f64."""
    codes = codes_ref[:]                      # (1, BLOCK_EXACT) int32
    mask = mask_ref[:]                        # (1, BLOCK_EXACT) int32 0/1
    g = out_ref.shape[1]
    # mask arrives as int32 and the masking is arithmetic (f32 multiply),
    # not a bool select: Mosaic supports neither minor-dim insertion nor
    # select_n on 1-bit types
    onehot = (codes.reshape(-1, 1)
              == jax.lax.broadcasted_iota(jnp.int32, (codes.shape[1], g), 1)
              ).astype(jnp.float32)
    onehot = onehot * mask.reshape(-1, 1).astype(jnp.float32)
    out_ref[:] = jnp.dot(vals_ref[:].astype(jnp.float32), onehot,
                         preferred_element_type=jnp.float32)


# rows per grid step of the limb kernel: BLOCK_EXACT * 4095 < 2**24 keeps
# every per-block limb partial exactly representable in f32
BLOCK_EXACT = 4096
# rows per outer slab: bounds the transient limb expansion (up to 14 limb
# rows per value row at 4 bytes) to ~56*A MB instead of 14x the full column
SLAB_EXACT = 1 << 20
_LIMBS = 7          # 7 x 12-bit limbs: capacity 2**84 per decomposed value
_LIMB_BASE = 4096.0
# limbs needed per row class; 'unit' rows (0/1 indicators, COUNT streams)
# are their own limb 0, 'int' rows are gated < 2**53 (5x12 = 60 bits),
# 'float' rows are runtime-normalized to < 2**83 (see below)
_CLASS_LIMBS = {"unit": 1, "int": 5, "float": _LIMBS}


def _exact_pow2(n: jax.Array) -> jax.Array:
    """``2.0**n`` for integer ``n`` in [-1022, 1023] as an EXACT f64 power
    of two, traced TPU-safely.  Neither standard spelling qualifies:
    ``ldexp``/``frexp`` on f64 lower to an s64 bitcast-convert the TPU X64
    rewrite does not implement (hard compile failure on v5e), and XLA's
    ``exp2`` is exp(n*ln2)-based — off by ulps even at integer arguments,
    which would silently break the fixed-point grid's exactness contract.
    Binary exponentiation instead: every factor (2**(2**i)) and every
    partial product is itself a power of two, so every multiply is exact;
    the negative half divides 1 by the positive power (exact for normal
    powers of two)."""
    n = n.astype(jnp.int32)
    mag = jnp.abs(n)
    out = jnp.ones(jnp.shape(n), jnp.float64)
    base = jnp.float64(2.0)
    for i in range(10):          # covers |n| <= 1023
        out = jnp.where(((mag >> i) & 1) == 1, out * base, out)
        if i < 9:
            base = base * base   # 2**(2**(i+1)), up to 2**512 — finite
    return jnp.where(n >= 0, out, 1.0 / out)


def _segmented_sums_limbs(vals: jax.Array, codes: jax.Array,
                          mask: jax.Array, num_groups: int,
                          row_classes, interpret: bool) -> jax.Array:
    """Masked segmented sums of f64 rows as fixed-point MXU contractions.

    The f64 scan this replaces (``segmented_sums_xla_blocked``) was the
    single most expensive device op in the TPC-H Q1/Q5 profiles (~0.4-1.2 s
    per query: 64-bit emulation inside a ~1500-step sequential lax.scan,
    with minutes-long compiles to match).  Here every value decomposes into
    sign-split 12-bit limbs on a fixed-point grid, each limb row is a
    per-block one-hot MXU contraction in f32 (integer partials < 2**24:
    exact), per-block partials accumulate in f64 (limb totals < 2**35:
    exact), and limbs recombine with exact power-of-two weights.

    Per-row grid choice by ``row_classes[i]``:
    - ``"unit"``: 0/1 streams (COUNT, occupancy, NaN/Inf indicators) — one
      limb, no negative half.  Bit-exact always.
    - ``"int"``: integer-valued rows (scaled decimals, int columns) on the
      unit grid — 5 limbs cover the caller-guaranteed |v| < 2**53, and the
      result is BIT-EXACT whenever sum(|v|) <= 2**53 (the same contract the
      old scan's f64 adds could only approximate).
    - ``"float"``: arbitrary f64 rows — scaled by the exact power of two
      2**(83-e) (e = exponent of the row's runtime max |v|), floor-truncated
      to the limb grid, summed exactly there, unscaled exactly.  Total
      truncation error is n * 2**(e-83) <= 2**(e-60) at n = 2**23 rows —
      below one ulp of the row maximum, i.e. tighter than ANY f64
      accumulation order, for data of any magnitude.
    """
    a, n = vals.shape
    cls = list(row_classes)
    assert len(cls) == a, (len(cls), a)
    if n == 0:
        return jnp.zeros((a, num_groups), jnp.float64)
    g_pad = max(GROUP_TILE, -(-num_groups // GROUP_TILE) * GROUP_TILE)
    cap_bits = 12 * _LIMBS - 1
    # per-row EXACT power-of-two scale: 1 for unit/int rows; ~2**(83-e)
    # for float rows.  NO frexp/ldexp here: on f64 they lower to an s64
    # bitcast-convert the TPU X64 rewrite does not implement (verified on
    # v5e), which killed every f64 static-domain aggregate at compile.
    # Instead e comes from floor(log2(absmax)) — within 1 ulp of the true
    # exponent, so TWO bits of slack in cap_bits bound absmax < 2**e
    # conservatively — and 2**k is built with exp2 of an integer-valued
    # f64, which is an exact power of two.  The slack costs <= 2 bits of
    # limb headroom (error bound ~4x, still far below one ulp of the row
    # maximum).  absmax is taken over MASK-CONTRIBUTING values only: the
    # engine filters by validity mask without compaction, so a huge value
    # in a filtered-out row must not coarsen the grid for the whole row
    # (it would truncate all valid contributions to 0 — silently wrong).
    is_float = np.asarray([c == "float" for c in cls])
    if is_float.any():
        absmax = jnp.max(
            jnp.where(mask.astype(bool)[None, :], jnp.abs(vals), 0.0),
            axis=1)
        e = jnp.floor(jnp.log2(jnp.maximum(absmax, 1e-300))
                      ).astype(jnp.int32) + 2
        k = jnp.where(jnp.asarray(is_float) & (absmax > 0),
                      jnp.clip(cap_bits - e, -940, 1000), 0)
        k = k.astype(jnp.int32)
        scale = _exact_pow2(k)       # multiplying by these is exact
        inv = _exact_pow2(-k)
    else:
        k = jnp.zeros((a,), jnp.int32)
        scale = inv = jnp.ones((a,), jnp.float64)
    # static (row, sign, limb) layout of the limb matrix
    layout = []
    for i, c in enumerate(cls):
        for s in ((1,) if c == "unit" else (1, -1)):
            for lk in range(_CLASS_LIMBS[c]):
                layout.append((i, s, lk))
    ar = len(layout)
    # Mosaic tile rule: the output block's row count must be divisible by 8
    # (f32 (8, 128) tiling) — pad with zero limb rows
    ar_pad = -(-ar // 8) * 8
    out = jnp.zeros((ar, num_groups), dtype=jnp.float64)
    slab = max(BLOCK_EXACT, min(SLAB_EXACT, -(-n // BLOCK_EXACT) * BLOCK_EXACT))
    for s0 in range(0, n, slab):
        s1 = min(s0 + slab, n)
        ns = s1 - s0
        ns_pad = -(-ns // BLOCK_EXACT) * BLOCK_EXACT
        c = codes[s0:s1].astype(jnp.int32)
        m = mask[s0:s1]
        # zero masked-out values BEFORE scaling: the grid is sized for the
        # contributing values only, so a filtered-out outlier could
        # overflow to inf under the scale and poison the f32 limbs as NaN
        v = (jnp.where(m.astype(bool)[None, :], vals[:, s0:s1], 0.0)
             * scale[:, None])
        if ns_pad != ns:
            v = jnp.pad(v, ((0, 0), (0, ns_pad - ns)))
            c = jnp.pad(c, (0, ns_pad - ns))
            m = jnp.pad(m, (0, ns_pad - ns))
        # sign-split limb extraction; every step is exact f64 integer
        # arithmetic (power-of-two divides, floors, Sterbenz subtractions)
        halves = {}
        for i, c_i in enumerate(cls):
            halves[(i, 1)] = jnp.floor(jnp.maximum(v[i], 0.0))
            if c_i != "unit":
                halves[(i, -1)] = jnp.floor(jnp.maximum(-v[i], 0.0))
        rows = []
        prev = None
        for (i, s, lk) in layout:
            if lk == 0:
                rem = halves[(i, s)]
            else:
                rem = prev  # floor(rem / 4096) from the previous limb
            q = jnp.floor(rem / _LIMB_BASE)
            rows.append((rem - q * _LIMB_BASE).astype(jnp.float32))
            prev = q
        limb = jnp.stack(rows)                        # (ar, ns_pad) f32
        if ar_pad != ar:
            limb = jnp.concatenate(
                [limb, jnp.zeros((ar_pad - ar, ns_pad), jnp.float32)], axis=0)
        grid = ns_pad // BLOCK_EXACT
        # x64 tracing breaks the Mosaic lowering (i64 index maps fail to
        # legalize); the kernel is pure f32/i32, so trace the compiled call
        # in 32-bit scope (interpret mode keeps the caller's setting)
        import contextlib
        scope = (contextlib.nullcontext() if interpret
                 else _x64_scope(False))
        with scope:
            per = pl.pallas_call(
                _seg_matmul_perblock_kernel,
                grid=(grid,),
                in_specs=[
                    pl.BlockSpec((1, BLOCK_EXACT), lambda i: (0, i)),
                    pl.BlockSpec((1, BLOCK_EXACT), lambda i: (0, i)),
                    pl.BlockSpec((ar_pad, BLOCK_EXACT), lambda i: (0, i)),
                ],
                out_specs=pl.BlockSpec((ar_pad, g_pad), lambda i: (i, 0)),
                out_shape=jax.ShapeDtypeStruct((grid * ar_pad, g_pad),
                                               jnp.float32),
                interpret=interpret,
            )(c.reshape(1, ns_pad), m.astype(jnp.int32).reshape(1, ns_pad),
              limb)
        per = per.reshape(grid, ar_pad, g_pad)[:, :ar]
        out = out + per.astype(jnp.float64).sum(0)[:, :num_groups]
    # recombine: T_limb * (+-4096**lk / scale_row); every weight is an exact
    # power of two, so every product is exact, and the 2-14 adds per row run
    # Neumaier-compensated — the recombined value is within ~1 ulp of the
    # exact fixed-point total (for int/unit rows below 2**53 it IS exact:
    # integer terms, integer running sums)
    sums = [jnp.zeros((num_groups,), jnp.float64)] * a
    comp = [jnp.zeros((num_groups,), jnp.float64)] * a
    for r, (i, s, lk) in enumerate(layout):
        # 2**(12*lk - k[i]) replaces ldexp(inv[i], 12*lk) — the combined
        # exponent stays in [-1000, 1012], inside _exact_pow2's range
        term = out[r] * (_exact_pow2(jnp.int32(12 * lk) - k[i]) * s)
        t = sums[i] + term
        comp[i] = comp[i] + jnp.where(
            jnp.abs(sums[i]) >= jnp.abs(term),
            (sums[i] - t) + term, (term - t) + sums[i])
        sums[i] = t
    return jnp.stack([s + c for s, c in zip(sums, comp)])


def segmented_sums_fixedpoint(vals: jax.Array, codes: jax.Array,
                              mask: jax.Array, num_groups: int, *,
                              row_classes=None,
                              interpret: bool | None = None) -> jax.Array:
    """Limb-decomposed MXU segmented sums (see _segmented_sums_limbs) with
    non-finite safety: values are sanitized and NaN/Inf indicator rows
    (class 'unit' — 0/1 by construction) are summed alongside, then IEEE
    semantics reassembled."""
    if interpret is None:
        interpret = not _backend_is_tpu()
    a = vals.shape[0]
    cls = ["float"] * a if row_classes is None else list(row_classes)

    def backend(v, c, m, g):
        flags = cls + ["unit"] * (v.shape[0] - a)
        return _segmented_sums_limbs(v, c, m, g, flags, interpret)

    return _nonfinite_safe(backend)(vals, codes, mask, num_groups)


def segmented_sums_exact(vals: jax.Array, codes: jax.Array, mask: jax.Array,
                         num_groups: int, *, interpret: bool | None = None
                         ) -> jax.Array:
    """Exact integer-grid segmented sums: the all-'int' special case of
    segmented_sums_fixedpoint (bit-exact whenever sum(|v|) <= 2**53)."""
    return segmented_sums_fixedpoint(
        vals, codes, mask, num_groups,
        row_classes=["int"] * vals.shape[0], interpret=interpret)


def segmented_sums(vals: jax.Array, codes: jax.Array, mask: jax.Array,
                   num_groups: int, *, interpret: bool | None = None
                   ) -> jax.Array:
    """Masked segmented sums of A value rows over a static group domain.

    vals: (A, n) float; codes: (n,) ints in [0, num_groups); mask: (n,) bool.
    Returns (A, num_groups) sums of vals[:, i] over rows with codes[i]==g and
    mask[i]. Jit/trace-safe; static shapes only.

    Non-finite safety: the one-hot contraction computes vals * 0 for other
    groups, and NaN/Inf * 0 == NaN would poison every group. The kernel
    therefore sums sanitized values and per-group NaN/+Inf/-Inf indicator
    rows, and reconstitutes IEEE semantics afterwards.
    """
    if interpret is None:
        interpret = not _backend_is_tpu()
    return _nonfinite_safe(
        lambda v, c, m, g: _segmented_sums_finite(v, c, m, g, interpret)
    )(vals, codes, mask, num_groups)


def _segmented_sums_finite(vals: jax.Array, codes: jax.Array, mask: jax.Array,
                           num_groups: int, interpret: bool) -> jax.Array:
    a, n = vals.shape
    g_pad = max(GROUP_TILE, -(-num_groups // GROUP_TILE) * GROUP_TILE)
    n_pad = -(-n // BLOCK) * BLOCK
    if n_pad != n:
        vals = jnp.pad(vals, ((0, 0), (0, n_pad - n)))
        codes = jnp.pad(codes, (0, n_pad - n))
        mask = jnp.pad(mask, (0, n_pad - n))  # padded rows masked out
    codes = codes.astype(jnp.int32).reshape(1, n_pad)
    mask = mask.astype(jnp.int32).reshape(1, n_pad)
    out_dtype = vals.dtype if jnp.issubdtype(vals.dtype, jnp.floating) \
        else jnp.float64
    grid = n_pad // BLOCK
    # x64 tracing breaks the Mosaic lowering (i64 index maps fail to
    # legalize); trace the compiled call in 32-bit scope.  Interpret mode
    # (tests, f64 oracle dtypes) keeps the caller's x64 setting — the
    # 32-bit scope would silently canonicalize its f64 output to f32.
    import contextlib
    scope = (contextlib.nullcontext() if interpret
             else _x64_scope(False))
    with scope:
        out = pl.pallas_call(
            _seg_matmul_kernel,
            grid=(grid,),
            in_specs=[
                pl.BlockSpec((1, BLOCK), lambda i: (0, i)),
                pl.BlockSpec((1, BLOCK), lambda i: (0, i)),
                pl.BlockSpec((a, BLOCK), lambda i: (0, i)),
            ],
            out_specs=pl.BlockSpec((a, g_pad), lambda i: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((a, g_pad), out_dtype),
            interpret=interpret,
        )(codes, mask, vals)
    return out[:, :num_groups]


@functools.partial(jax.jit, static_argnames=("num_groups", "interpret"))
def segmented_sums_jit(vals, codes, mask, num_groups, interpret=None):
    return segmented_sums(vals, codes, mask, num_groups, interpret=interpret)


def segmented_sums_xla_blocked(vals: jax.Array, codes: jax.Array,
                               mask: jax.Array, num_groups: int,
                               block: int = 4096) -> jax.Array:
    """One-hot contraction via an XLA scan over row blocks.

    Same math as the pallas kernel but in plain XLA: Mosaic has no 64-bit
    support, so this is the f64 path on TPU (X64 emulation is exact). The
    per-block one-hot lives only inside the scan body — peak memory is one
    (block, G) tile, not (n, G). Callers handle non-finite values
    (segmented_sums_dispatch wraps with the sanitize/indicator machinery).
    """
    a, n = vals.shape
    out_dtype = vals.dtype if jnp.issubdtype(vals.dtype, jnp.floating) \
        else jnp.float64
    n_pad = -(-max(n, 1) // block) * block
    if n_pad != n:
        vals = jnp.pad(vals, ((0, 0), (0, n_pad - n)))
        codes = jnp.pad(codes, (0, n_pad - n))
        mask = jnp.pad(mask, (0, n_pad - n))
    nb = n_pad // block
    vb = vals.reshape(a, nb, block).transpose(1, 0, 2).astype(out_dtype)
    cb = codes.astype(jnp.int32).reshape(nb, block)
    mb = mask.reshape(nb, block)

    def step(acc, xs):
        v, c, m = xs
        onehot = (c[:, None]
                  == jax.lax.broadcasted_iota(jnp.int32, (block, num_groups), 1))
        onehot = jnp.where(m[:, None], onehot, False).astype(out_dtype)
        return acc + jnp.dot(v, onehot, preferred_element_type=out_dtype), None

    acc0 = jnp.zeros((a, num_groups), dtype=out_dtype)
    out, _ = jax.lax.scan(step, acc0, (vb, cb, mb))
    return out


def segmented_sums_dispatch(vals: jax.Array, codes: jax.Array,
                            mask: jax.Array, num_groups: int,
                            row_classes=None) -> jax.Array:
    """Backend policy for the static-domain groupby reduction.

    - DSQL_PALLAS=force: pallas kernels (interpreted off-TPU) — test hook.
    - TPU + 32-bit floats: the accumulate-in-place pallas MXU kernel.
    - TPU + 64-bit: the fixed-point limb kernel (_segmented_sums_limbs) —
      bit-exact on unit/int rows, sub-ulp on float rows, and ~40x cheaper
      than the sequential f64 scan it replaced (the scan was the top device
      op in the TPC-H Q1/Q5 profiles, and its 64-bit-emulated matmul loop
      also dominated query compile time).
    - otherwise (CPU/GPU): XLA scatter segment-sum, which is fine there.
    Non-finite safety is applied once for every backend.
    """
    import os

    forced = os.environ.get("DSQL_PALLAS") == "force"
    if forced or (_on_tpu() and vals.dtype != jnp.float32):
        return segmented_sums_fixedpoint(
            vals, codes, mask, num_groups, row_classes=row_classes,
            interpret=not _backend_is_tpu())
    if _on_tpu():
        return segmented_sums(vals, codes, mask, num_groups,
                              interpret=not _backend_is_tpu())
    return reference_segmented_sums(vals, codes, mask, num_groups)


def _nonfinite_safe(backend):
    """Wrap a sanitized-sum backend with NaN/Inf indicator reassembly."""
    def wrapped(vals, codes, mask, num_groups):
        if not jnp.issubdtype(vals.dtype, jnp.floating):
            return backend(vals, codes, mask, num_groups)
        from .sorted_agg import ieee_reassemble
        a = vals.shape[0]
        isnan = jnp.isnan(vals)
        ispos = jnp.isposinf(vals)
        isneg = jnp.isneginf(vals)
        clean = jnp.where(isnan | ispos | isneg, 0.0, vals)
        stacked = jnp.concatenate([
            clean, isnan.astype(vals.dtype), ispos.astype(vals.dtype),
            isneg.astype(vals.dtype)])
        sums = backend(stacked, codes, mask, num_groups)
        return ieee_reassemble(sums[:a], sums[a:2 * a], sums[2 * a:3 * a],
                               sums[3 * a:])
    return wrapped


def reference_segmented_sums(vals, codes, mask, num_groups):
    """XLA scatter-based oracle for tests (where, not multiply, so masked
    NaN rows contribute nothing)."""
    out_dtype = vals.dtype if jnp.issubdtype(vals.dtype, jnp.floating) \
        else jnp.float64
    return jnp.stack([
        jax.ops.segment_sum(
            jnp.where(mask, vals[i].astype(out_dtype), 0), codes, num_groups)
        for i in range(vals.shape[0])])
