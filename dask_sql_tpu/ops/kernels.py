"""Shared device kernel primitives: key factorization, dictionary unification,
civil-date arithmetic.

These are the building blocks the physical operators compose: SQL groupby/
join/sort all reduce to "turn key columns into dense integer codes, then run
integer kernels on device".  The reference delegates the equivalents to
pandas/dask internals (hash-based groupby/merge); here they are explicit
XLA-friendly array programs.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..table import Column
from ..types import SqlType


# ---------------------------------------------------------------------------
# factorization: columns -> dense int codes
# ---------------------------------------------------------------------------

def unify_string_codes(cols: List[Column]) -> List[jax.Array]:
    """Re-code string columns onto their sorted dictionary union.

    The union dictionary is sorted, so code order == lexicographic order:
    equality AND comparisons on the returned codes are string-correct.
    """
    dicts = [c.dictionary.astype(str) for c in cols]
    union = np.unique(np.concatenate(dicts))
    out = []
    for c, d in zip(cols, dicts):
        remap = np.searchsorted(union, d).astype(np.int64)
        out.append(jnp.take(jnp.asarray(remap), jnp.clip(c.data, 0, len(d) - 1)))
    return out


def comparable_data(col: Column) -> jax.Array:
    """Numeric array whose order matches SQL ordering for this column."""
    if col.stype.is_string:
        return col.dict_ranks().data.astype(jnp.int64)
    if col.data.dtype == jnp.bool_:
        return col.data.astype(jnp.int64)
    return col.data


def factorize_columns(cols: List[Column], *, null_as_group: bool = True
                      ) -> Tuple[jax.Array, jax.Array, int]:
    """Multi-column factorize: rows -> dense codes 0..G-1.

    Returns (codes, representative_row_per_group, num_groups).  Rows where any
    key is NULL either form their own groups keyed by the null pattern
    (``null_as_group=True``, SQL GROUP BY semantics — reference
    physical/utils/groupby.py:8-34) or get code -1 (join-key semantics where
    NULL never matches, reference join.py:224-235).
    """
    n = len(cols[0])
    per_col_codes = []
    for c in cols:
        data = comparable_data(c)
        if c.mask is not None:
            # distinct value for nulls: use code 0 for null, shift others by 1
            uniq, inv = jnp.unique(jnp.where(c.mask, data, data.min() if n else 0),
                                   return_inverse=True)
            inv = jnp.where(c.mask, inv + 1, 0)
        else:
            uniq, inv = jnp.unique(data, return_inverse=True)
            inv = inv + 1
        per_col_codes.append(inv.reshape(-1).astype(jnp.int64))

    combined = per_col_codes[0]
    for c in per_col_codes[1:]:
        m = int(c.max()) + 1 if n else 1
        combined = combined * m + c

    uniq_codes, codes = jnp.unique(combined, return_inverse=True)
    codes = codes.reshape(-1)
    num_groups = int(uniq_codes.shape[0])

    if not null_as_group:
        any_null = jnp.zeros(n, dtype=bool)
        for c in cols:
            if c.mask is not None:
                any_null = any_null | ~c.mask
        codes = jnp.where(any_null, -1, codes)

    # representative (first) row per group
    first = jnp.full(num_groups, n, dtype=jnp.int64)
    valid = codes >= 0
    first = first.at[jnp.where(valid, codes, 0)].min(
        jnp.where(valid, jnp.arange(n), n))
    return codes, first, num_groups


def join_key_codes(left: List[Column], right: List[Column],
                   null_equal: bool = False, variant: str = "hash"
                   ) -> Tuple[jax.Array, jax.Array]:
    """Factorize left+right key columns on a shared domain.

    Returns int64 codes for each side; -1 marks rows with NULL keys (never
    match, reference join.py:220-235).  ``null_equal=True`` switches to
    set-operation equality (SQL "IS NOT DISTINCT FROM"): NULL gets its own
    shared code and matches NULL — INTERSECT/EXCEPT require it (a row
    (NULL, 'x') present on both sides IS in the intersection).

    ``variant="dense"`` (stats-driven, runtime/statistics.py): a single
    integer key pair skips the shared-domain unique/sort entirely —
    ``codes = key - min`` is already a valid shared coding (equal keys get
    equal codes, NULL keeps its sentinel).  Falls back to the factorize
    path when not applicable, so the flag can never change results.
    """
    if variant == "dense":
        out = _dense_join_codes(left, right, null_equal)
        if out is not None:
            return out
    nl = len(left[0]) if left else 0
    combined_cols = []
    for lc, rc in zip(left, right):
        if lc.stype.is_string or rc.stype.is_string:
            lcodes, rcodes = unify_string_codes([lc, rc])
            data = jnp.concatenate([lcodes, rcodes])
        else:
            ldata = lc.data
            rdata = rc.data
            dt = jnp.promote_types(ldata.dtype, rdata.dtype)
            data = jnp.concatenate([ldata.astype(dt), rdata.astype(dt)])
        mask = None
        if lc.mask is not None or rc.mask is not None:
            lm = lc.valid_mask()
            rm = rc.valid_mask()
            mask = jnp.concatenate([lm, rm])
        combined_cols.append((data, mask))

    per = []
    for data, mask in combined_cols:
        uniq, inv = jnp.unique(data, return_inverse=True)
        inv = inv.reshape(-1).astype(jnp.int64)
        if mask is not None:
            if null_equal:
                # NULL becomes code 0, one shared bucket; real values shift
                inv = jnp.where(mask, inv + 1, 0)
            else:
                inv = jnp.where(mask, inv, -1)
        per.append(inv)

    combined = per[0]
    bad = per[0] < 0
    for c in per[1:]:
        m = int(c.max()) + 1 if c.shape[0] else 1
        m = max(m, 1)
        combined = combined * m + jnp.maximum(c, 0)
        bad = bad | (c < 0)
    combined = jnp.where(bad, -1, combined)
    return combined[:nl], combined[nl:]


def _dense_join_codes(left: List[Column], right: List[Column],
                      null_equal: bool):
    """Direct shared coding for one integer key pair: ``code = key - lo``
    (``+1`` with NULL as shared code 0 under ``null_equal``).  No unique,
    no sort — two reductions for ``lo`` are the only synced work.  None
    when not applicable (multi-column, strings, floats, empty)."""
    if len(left) != 1 or len(right) != 1:
        return None
    lc, rc = left[0], right[0]
    for c in (lc, rc):
        if c.stype.is_string or not jnp.issubdtype(c.data.dtype,
                                                   jnp.integer):
            return None
    nl, nr = len(lc), len(rc)
    if nl + nr == 0:
        return None
    imax = jnp.iinfo(jnp.int64).max
    imin = jnp.iinfo(jnp.int64).min
    los, his = [], []
    for c in (lc, rc):
        if not len(c):
            continue
        data = c.data.astype(jnp.int64)
        if c.mask is not None:
            los.append(int(jnp.where(c.mask, data, imax).min()))
            his.append(int(jnp.where(c.mask, data, imin).max()))
        else:
            los.append(int(data.min()))
            his.append(int(data.max()))
    los = [v for v in los if v != imax]
    his = [v for v in his if v != imin]
    if not los or not his:
        return None  # all keys NULL on both sides
    lo, hi = min(los), max(his)
    if hi - lo >= 2 ** 62:
        # adversarial int64 spread: key - lo could overflow; the
        # factorize path handles those (rare) layouts
        return None
    shift = 1 if null_equal else 0
    out = []
    for c in (lc, rc):
        codes = c.data.astype(jnp.int64) - lo + shift
        if c.mask is not None:
            codes = jnp.where(c.mask, codes, 0 if null_equal else -1)
        out.append(codes)
    return out[0], out[1]


# ---------------------------------------------------------------------------
# compaction (filter -> gather indices)
# ---------------------------------------------------------------------------

def mask_to_indices(mask: jax.Array) -> jax.Array:
    """Boolean mask -> row indices (host-synced size; eager execution only)."""
    count = int(mask.sum())
    return jnp.nonzero(mask, size=count)[0]


# ---------------------------------------------------------------------------
# civil-date arithmetic (Howard Hinnant's algorithms, pure integer ops)
# ---------------------------------------------------------------------------

US_PER_DAY = 86_400_000_000


def civil_from_days(z: jax.Array):
    """days-since-epoch -> (year, month, day), vectorized integer math."""
    z = z.astype(jnp.int64) + 719468
    era = jnp.floor_divide(z, 146097)
    doe = z - era * 146097
    yoe = jnp.floor_divide(doe - doe // 1460 + doe // 36524 - doe // 146096, 365)
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = jnp.floor_divide(5 * doy + 2, 153)
    d = doy - jnp.floor_divide(153 * mp + 2, 5) + 1
    m = mp + jnp.where(mp < 10, 3, -9)
    y = y + (m <= 2)
    return y, m, d


def days_from_civil(y: jax.Array, m: jax.Array, d: jax.Array) -> jax.Array:
    y = y.astype(jnp.int64) - (m <= 2)
    era = jnp.floor_divide(y, 400)
    yoe = y - era * 400
    mp = jnp.where(m > 2, m - 3, m + 9)
    doy = jnp.floor_divide(153 * mp + 2, 5) + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


def timestamp_to_days(us: jax.Array) -> jax.Array:
    return jnp.floor_divide(us.astype(jnp.int64), US_PER_DAY)


def timestamp_time_of_day_us(us: jax.Array) -> jax.Array:
    return us.astype(jnp.int64) - timestamp_to_days(us) * US_PER_DAY


def extract_field(field: str, days: jax.Array, tod_us: Optional[jax.Array]):
    """EXTRACT implementation over (days, time-of-day) pair.

    ``tod_us`` is None for DATE columns.  Field names follow Calcite/postgres
    (reference rex op: call.py:474-513).
    """
    y, m, d = civil_from_days(days)
    f = field.upper()
    if f == "YEAR":
        return y
    if f == "MONTH":
        return m
    if f == "DAY" or f == "DAYOFMONTH":
        return d
    if f == "QUARTER":
        return (m - 1) // 3 + 1
    if f == "DECADE":
        return jnp.floor_divide(y, 10)
    if f == "CENTURY":
        return jnp.floor_divide(y + 99, 100)
    if f == "MILLENNIUM":
        return jnp.floor_divide(y + 999, 1000)
    if f in ("DOW", "DAYOFWEEK"):
        # postgres DOW: 0=Sunday..6=Saturday ; epoch day 0 = Thursday(4)
        return jnp.mod(days + 4, 7)
    if f == "ISODOW":
        return jnp.mod(days + 3, 7) + 1
    if f in ("DOY", "DAYOFYEAR"):
        jan1 = days_from_civil(y, jnp.ones_like(m), jnp.ones_like(d))
        return days - jan1 + 1
    if f == "WEEK":
        # ISO week number
        isodow = jnp.mod(days + 3, 7) + 1
        thursday = days - isodow + 4
        ty, _, _ = civil_from_days(thursday)
        jan1 = days_from_civil(ty, jnp.ones_like(m), jnp.ones_like(d))
        return jnp.floor_divide(thursday - jan1, 7) + 1
    if f == "EPOCH":
        base = days.astype(jnp.int64) * 86400
        if tod_us is not None:
            base = base + tod_us // 1_000_000
        return base
    if tod_us is None:
        tod_us = jnp.zeros_like(days, dtype=jnp.int64)
    if f == "HOUR":
        return tod_us // 3_600_000_000
    if f == "MINUTE":
        return (tod_us // 60_000_000) % 60
    if f == "SECOND":
        return (tod_us // 1_000_000) % 60
    if f == "MILLISECOND":
        return (tod_us // 1000) % 60_000
    if f == "MICROSECOND":
        return tod_us % 60_000_000
    raise NotImplementedError(f"EXTRACT field {field}")


def trunc_date(unit: str, days: jax.Array, tod_us: Optional[jax.Array]):
    """FLOOR(ts TO unit): returns (days, tod_us)."""
    u = unit.upper()
    y, m, d = civil_from_days(days)
    one = jnp.ones_like(m)
    zeros = None if tod_us is None else jnp.zeros_like(tod_us)
    if u == "YEAR":
        return days_from_civil(y, one, one), zeros
    if u == "QUARTER":
        qm = ((m - 1) // 3) * 3 + 1
        return days_from_civil(y, qm, one), zeros
    if u == "MONTH":
        return days_from_civil(y, m, one), zeros
    if u == "WEEK":
        isodow = jnp.mod(days + 3, 7) + 1
        return days - (isodow - 1), zeros
    if u == "DAY":
        return days, zeros
    if tod_us is None:
        return days, None
    if u == "HOUR":
        return days, (tod_us // 3_600_000_000) * 3_600_000_000
    if u == "MINUTE":
        return days, (tod_us // 60_000_000) * 60_000_000
    if u == "SECOND":
        return days, (tod_us // 1_000_000) * 1_000_000
    if u == "MILLISECOND":
        return days, (tod_us // 1000) * 1000
    raise NotImplementedError(f"FLOOR unit {unit}")


# ---------------------------------------------------------------------------
# trace-safe total-order keys (shared by the compiled executor and windows):
# no 64-bit bitcasts (the TPU X64 rewrite lacks them); floats stay raw f64
# with NULL/NaN class flags
# ---------------------------------------------------------------------------

_INT64_MIN = jnp.int64(-(2**63))


def float_class(x: jax.Array, null: Optional[jax.Array]) -> jax.Array:
    """0 = NULL (first), 1 = ordinary value, 2 = NaN (last)."""
    cls = jnp.where(jnp.isnan(x), jnp.int8(2), jnp.int8(1))
    if null is not None:
        cls = jnp.where(null, jnp.int8(0), cls)
    return cls


def canon_f64(x: jax.Array) -> jax.Array:
    """Canonical f64 sort/equality key: -0.0 -> +0.0, NaN -> 0 (class flag
    disambiguates). No i64 bitcast — the TPU X64 rewrite can't do it."""
    x = x.astype(jnp.float64) + 0.0
    return jnp.where(jnp.isnan(x), 0.0, x)




def decimal_unscale(s_int: jax.Array, scale: int) -> jax.Array:
    """Correctly-rounded ``s_int / 10**scale`` under jit.

    XLA rewrites division by a constant into multiplication by its (inexact)
    reciprocal, which mis-rounds the final decimal result by one ulp
    (observed on XLA:CPU: 2505363390/100 -> ...3633.900000002). Splitting
    into an exact integer quotient plus a sub-unit remainder keeps any
    reciprocal error far below the result's rounding granularity.
    """
    if scale == 0:
        return s_int.astype(jnp.float64)
    f = 10 ** scale
    q = s_int // f
    r = s_int - q * f
    return q.astype(jnp.float64) + r.astype(jnp.float64) / float(f)


def orderable_int64(x: jax.Array) -> jax.Array:
    """int64 key for non-float comparable data (ints, bools, dict ranks,
    dates) — comparable_data already made the order numeric."""
    return x.astype(jnp.int64)


def key_parts(cols: List[Column]) -> List[Tuple[jax.Array, Optional[jax.Array]]]:
    """(data, optional class flag) per key column for grouping/dedup.

    data is canonical f64 for float columns (no 64-bit bitcast on TPU) or
    int64 with a NULL sentinel otherwise; the int8 class flag orders
    NULL(0) < values(1) < NaN(2) and disambiguates sentinel collisions.
    flag is None for non-nullable integer-like keys — nothing to
    disambiguate, and every flag array is one more lexsort operand over
    the whole stream. Equality of (data, flag) == SQL group equality
    (-0.0 == +0.0, NaNs grouped together, NULLs grouped together).
    """
    out = []
    for c in cols:
        raw = comparable_data(c)
        null = (~c.mask) if c.mask is not None else None
        if jnp.issubdtype(raw.dtype, jnp.floating):
            d = canon_f64(raw)
            flag = float_class(raw, null)
            if null is not None:
                d = jnp.where(null, 0.0, d)
        else:
            d = orderable_int64(raw)
            if null is not None:
                d = jnp.where(null, _INT64_MIN, d)
                flag = jnp.where(null, jnp.int8(0), jnp.int8(1))
            else:
                flag = None
        out.append((d, flag))
    return out




def append_lexsort_operands(arrays: list, parts) -> None:
    """Append key-part lexsort operands (data + optional class flag) in
    least-to-most-significant order for ``jnp.lexsort`` consumers."""
    for d, flag in reversed(parts):
        arrays.append(d)
        if flag is not None:
            arrays.append(flag)


