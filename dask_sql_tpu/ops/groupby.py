"""Segmented aggregation kernels: SQL GROUP BY on device.

TPU-native replacement for the reference's groupby lowering
(/root/reference/dask_sql/physical/rel/logical/aggregate.py:19-361 and the
NULL-group trick in physical/utils/groupby.py:8-34): keys factorize to dense
codes (NULLs form their own group), then every aggregate is a
``jax.ops.segment_*`` reduction — no shuffle, no per-group python.
"""
from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..table import dict_sort_order, Column, Scalar, Table
from ..types import SqlType, exact_decimal_scale, physical_dtype
from .kernels import comparable_data, decimal_unscale, factorize_columns


def group_codes(key_cols: List[Column], variant: str = "hash",
                dense_hint=None):
    """Factorize group keys into dense codes 0..G-1.

    Returns (codes, first_row_per_group, G, used_variant).  ``variant``
    comes from the stats crossover (runtime/statistics.py): "hash" is the
    status-quo ``factorize_columns`` (jnp.unique), "sorted" is one stable
    lexsort + boundary scan, "dense" is the direct-index path
    (``codes = key - min``, no hashing, no sort) for a single small-domain
    int key.  All three produce IDENTICAL group numbering (ascending key
    order, NULL groups first) and identical representative rows, so the
    dispatch can never change results — a variant that doesn't apply falls
    through to the next ("dense" → "sorted" needs a single int key;
    "sorted" and "hash" always apply)."""
    if not key_cols:
        return None, None, 1, "none"
    if variant == "dense":
        out = _dense_group_codes(key_cols, dense_hint)
        if out is not None:
            return (*out, "dense")
        variant = "sorted"
    if variant == "sorted":
        out = _sorted_group_codes(key_cols)
        if out is not None:
            return (*out, "sorted")
    return (*factorize_columns(key_cols, null_as_group=True), "hash")


#: hard ceiling on dense direct-index slots even under DSQL_FORCE_GROUPBY
_DENSE_HARD_CAP = 1 << 22


def _dense_group_codes(key_cols: List[Column], dense_hint=None):
    """Direct-index factorize for ONE integer key: slot = key - lo (+1
    when NULLs exist, which take slot 0 — matching factorize's NULL-first
    group order), occupied slots compact to dense codes via a cumsum
    remap.  O(n + domain), scatter-based — an eager-path variant (the
    compiled TPU path keeps its scatter-free sorted codes).  Returns None
    when not applicable (caller falls through)."""
    if len(key_cols) != 1:
        return None
    c = key_cols[0]
    if c.stype.is_string or not jnp.issubdtype(c.data.dtype, jnp.integer):
        return None
    n = len(c)
    if n == 0:
        return None
    data = c.data.astype(jnp.int64)
    # data under NULL rows is garbage — min/max must see valid rows only
    if c.mask is not None:
        if not bool(c.mask.any()):
            return None
        imax = jnp.iinfo(jnp.int64).max
        imin = jnp.iinfo(jnp.int64).min
        vlo = int(jnp.min(jnp.where(c.mask, data, imax)))
        vhi = int(jnp.max(jnp.where(c.mask, data, imin)))
    else:
        vlo = int(data.min())
        vhi = int(data.max())
    if dense_hint is not None:
        lo, hi = int(dense_hint[0]), int(dense_hint[1])
        # stale stats guard: rows outside the hinted domain void the hint
        if vlo < lo or vhi > hi:
            lo, hi = vlo, vhi
    else:
        lo, hi = vlo, vhi
    domain = hi - lo + 1
    if domain <= 0 or domain > _DENSE_HARD_CAP:
        return None
    has_null = c.mask is not None and bool((~c.mask).any())
    shift = 1 if has_null else 0
    slots = jnp.clip(data - lo, 0, domain - 1) + shift
    if has_null:
        slots = jnp.where(c.mask, slots, 0)
    occ = jnp.zeros(domain + shift, dtype=jnp.int64).at[slots].add(1)
    present = occ > 0
    # compact: occupied slot k -> dense code rank(k); ascending slot order
    # IS ascending key order (NULL slot 0 first) — factorize's numbering
    remap = jnp.cumsum(present.astype(jnp.int64)) - 1
    num_groups = int(remap[-1]) + 1
    codes = remap[slots]
    first = jnp.full(num_groups, n, dtype=jnp.int64).at[codes].min(
        jnp.arange(n, dtype=jnp.int64))
    return codes, first, num_groups


def _sorted_group_codes(key_cols: List[Column]):
    """Sort-based factorize: ONE stable lexsort over the key columns, then
    group boundaries fall out of adjacent-row comparisons — no hash table,
    no per-column unique.  Profitable when groups are few and fat (the
    hash/sort crossover).  Group numbering matches factorize exactly:
    per-column ordering is (null-flag, comparable value) with NULLs first,
    columns major-to-minor in key order, and the stable sort makes each
    group's first sorted row its minimum original row index.

    Returns None for floating-point keys (NaN != NaN would split NaN
    groups where unique's total order would not) — the caller falls back
    to factorize."""
    n = len(key_cols[0])
    if n == 0:
        return None
    keys = []  # significance order: col0 flag, col0 value, col1 flag, ...
    for c in key_cols:
        data = comparable_data(c)
        if jnp.issubdtype(data.dtype, jnp.floating):
            return None
        if c.mask is not None:
            keys.append(c.mask.astype(jnp.int8))      # NULL(0) first
            keys.append(jnp.where(c.mask, data, data[0]))
        else:
            keys.append(data)
    # jnp.lexsort sorts by the LAST key first -> pass minor-to-major
    order = jnp.lexsort(tuple(reversed(keys)))
    diff = jnp.zeros(max(n - 1, 0), dtype=bool)
    for k in keys:
        ks = k[order]
        diff = diff | (ks[1:] != ks[:-1])
    boundary = jnp.concatenate([jnp.ones(1, dtype=bool), diff])
    codes_sorted = jnp.cumsum(boundary.astype(jnp.int64)) - 1
    num_groups = int(codes_sorted[-1]) + 1
    codes = jnp.zeros(n, dtype=jnp.int64).at[order].set(codes_sorted)
    starts = jnp.nonzero(boundary, size=num_groups)[0]
    first = order[starts]
    return codes, first, num_groups


def _masked(col: Column, extra_mask: Optional[jax.Array]):
    data = col.data
    valid = col.valid_mask()
    if extra_mask is not None:
        valid = valid & extra_mask
    return data, valid


def _decimal_exact_result(op: str, s_int, count, dscale: int,
                          out_type: SqlType) -> Column:
    """Shared tail of the exact scaled-int64 SUM/$SUM0/AVG paths: unscale
    via the exact-quotient route and apply the SQL NULL rules (SUM over no
    rows -> NULL, $SUM0 -> 0, AVG -> NULL)."""
    has_any = count > 0
    if op in ("SUM", "$SUM0"):
        s = decimal_unscale(s_int, dscale).astype(physical_dtype(out_type))
        return Column(s, out_type, None if op == "$SUM0" else has_any)
    mean = s_int.astype(jnp.float64) / (jnp.maximum(count, 1) * 10.0 ** dscale)
    return Column(mean, out_type, has_any)


def _decimal_scaled_ints(data, dscale: int):
    """Round f64 decimal data onto its integer grid (int64 'cents')."""
    return jnp.round(data.astype(jnp.float64) * 10.0 ** dscale
                     ).astype(jnp.int64)


def segment_aggregate(op: str, col: Optional[Column], codes: Optional[jax.Array],
                      num_groups: int, out_type: SqlType,
                      filter_mask: Optional[jax.Array] = None,
                      n_rows: int = 0) -> Column:
    """One aggregate over segments. ``codes=None`` means whole-table (1 group)."""
    if codes is None:
        codes = jnp.zeros(n_rows if col is None else len(col), dtype=jnp.int64)
        num_groups = 1

    if op in ("COUNT", "REGR_COUNT"):
        if col is None:
            ones = jnp.ones(codes.shape[0], dtype=jnp.int64)
            if filter_mask is not None:
                ones = jnp.where(filter_mask, ones, 0)
            out = jax.ops.segment_sum(ones, codes, num_groups)
        else:
            data, valid = _masked(col, filter_mask)
            out = jax.ops.segment_sum(valid.astype(jnp.int64), codes, num_groups)
        return Column(out, out_type, None)

    assert col is not None, f"{op} requires an argument"
    data, valid = _masked(col, filter_mask)
    count = jax.ops.segment_sum(valid.astype(jnp.int64), codes, num_groups)
    has_any = count > 0

    if op in ("SUM", "$SUM0", "AVG", "STDDEV", "STDDEV_POP", "STDDEV_SAMP",
              "VAR_POP", "VAR_SAMP", "VARIANCE"):
        dscale = exact_decimal_scale(col.stype) if op in ("SUM", "$SUM0",
                                                          "AVG") else None
        if dscale is not None:
            # exact scaled-int64 money math: order-independent, bit-stable
            iwork = jnp.where(valid, _decimal_scaled_ints(data, dscale), 0)
            s_int = jax.ops.segment_sum(iwork, codes, num_groups)
            return _decimal_exact_result(op, s_int, count, dscale, out_type)
        work = data.astype(jnp.float64) if not jnp.issubdtype(data.dtype, jnp.integer) else data.astype(jnp.int64)
        work = jnp.where(valid, work, 0)
        s = jax.ops.segment_sum(work, codes, num_groups)
        if op == "SUM":
            return Column(s.astype(physical_dtype(out_type)), out_type,
                          has_any)
        if op == "$SUM0":
            return Column(s.astype(physical_dtype(out_type)), out_type, None)
        mean = s.astype(jnp.float64) / jnp.maximum(count, 1)
        if op == "AVG":
            return Column(mean, out_type, has_any)
        sq = jnp.where(valid, data.astype(jnp.float64) ** 2, 0.0)
        s2 = jax.ops.segment_sum(sq, codes, num_groups)
        var_pop = s2 / jnp.maximum(count, 1) - mean**2
        var_pop = jnp.maximum(var_pop, 0.0)
        if op == "VAR_POP":
            return Column(var_pop, out_type, has_any)
        denom = jnp.maximum(count - 1, 1)
        var_samp = (s2 - count * mean**2) / denom
        var_samp = jnp.maximum(var_samp, 0.0)
        ok = count > 1
        if op in ("VAR_SAMP", "VARIANCE"):
            return Column(var_samp, out_type, ok)
        if op == "STDDEV_POP":
            return Column(jnp.sqrt(var_pop), out_type,
                          has_any)
        return Column(jnp.sqrt(var_samp), out_type, ok)

    if op in ("MIN", "MAX"):
        if col.stype.is_string:
            ranked = col.dict_ranks()
            rdata = ranked.data.astype(jnp.int64)
            sentinel = jnp.iinfo(jnp.int64).max if op == "MIN" else jnp.iinfo(jnp.int64).min
            work = jnp.where(valid, rdata, sentinel)
            f = jax.ops.segment_min if op == "MIN" else jax.ops.segment_max
            out_ranks = f(work, codes, num_groups)
            # map ranks back to dictionary codes
            order = dict_sort_order(col.dictionary)
            inv = jnp.asarray(order.astype(np.int64))
            safe = jnp.clip(out_ranks, 0, len(order) - 1)
            out_codes = jnp.take(inv, safe).astype(jnp.int32)
            return Column(out_codes, out_type,
                          has_any, col.dictionary)
        if jnp.issubdtype(data.dtype, jnp.floating):
            sentinel = jnp.inf if op == "MIN" else -jnp.inf
        elif data.dtype == jnp.bool_:
            data = data.astype(jnp.int64)
            sentinel = 1 if op == "MIN" else 0
        else:
            info = jnp.iinfo(data.dtype)
            sentinel = info.max if op == "MIN" else info.min
        work = jnp.where(valid, data, sentinel)
        f = jax.ops.segment_min if op == "MIN" else jax.ops.segment_max
        out = f(work, codes, num_groups)
        out = out.astype(physical_dtype(out_type))
        return Column(out, out_type, has_any)

    if op in ("EVERY", "BOOL_AND"):
        work = jnp.where(valid, data.astype(bool), True)
        out = jax.ops.segment_min(work.astype(jnp.int32), codes, num_groups) > 0
        return Column(out, out_type, has_any)
    if op in ("BOOL_OR", "ANY"):
        work = jnp.where(valid, data.astype(bool), False)
        out = jax.ops.segment_max(work.astype(jnp.int32), codes, num_groups) > 0
        return Column(out, out_type, has_any)

    if op in ("ANY_VALUE", "SINGLE_VALUE", "FIRST_VALUE", "LAST_VALUE"):
        n = codes.shape[0]
        idx = jnp.arange(n)
        if op == "LAST_VALUE":
            work = jnp.where(valid, idx, -1)
            pick = jax.ops.segment_max(work, codes, num_groups)
        else:
            work = jnp.where(valid, idx, n)
            pick = jax.ops.segment_min(work, codes, num_groups)
        safe = jnp.clip(pick, 0, max(n - 1, 0))
        out = col.take(safe)
        return out.with_mask(out.valid_mask() & has_any)

    if op in ("BIT_AND", "BIT_OR", "BIT_XOR"):
        # no XLA segment primitive for bit ops: host reduceat over sorted codes
        np_codes = np.asarray(codes)
        np_data = np.asarray(data)
        np_valid = np.asarray(valid)
        order = np.argsort(np_codes, kind="stable")
        sc, sd, sv = np_codes[order], np_data[order], np_valid[order]
        ident = {"BIT_AND": -1, "BIT_OR": 0, "BIT_XOR": 0}[op]
        sd = np.where(sv, sd, ident)
        ufn = {"BIT_AND": np.bitwise_and, "BIT_OR": np.bitwise_or,
               "BIT_XOR": np.bitwise_xor}[op]
        starts = np.searchsorted(sc, np.arange(num_groups))
        out = np.full(num_groups, ident, dtype=np_data.dtype)
        present = np.zeros(num_groups, bool)
        if len(sd):
            seg = ufn.reduceat(sd, np.minimum(starts, len(sd) - 1))
            counts = np.diff(np.append(starts, len(sd)))
            present = counts > 0
            out = np.where(present, seg, ident)
        has = np.asarray(has_any)
        return Column(jnp.asarray(out).astype(physical_dtype(out_type)), out_type,
                      None if has.all() else jnp.asarray(has))

    if op == "LISTAGG":
        np_codes = np.asarray(codes)
        vals = col.decode() if col.stype.is_string else col.to_numpy().astype(object)
        np_valid = np.asarray(valid)
        outs = [[] for _ in range(num_groups)]
        for c, v, ok in zip(np_codes, vals, np_valid):
            if ok:
                outs[int(c)].append(str(v))
        strs = np.array([",".join(o) if o else None for o in outs], dtype=object)
        return Column._encode_strings(strs, None)

    raise NotImplementedError(f"Aggregate {op}")


def distinct_rows(cols: List[Column]) -> jax.Array:
    """Row indices of first occurrences of each distinct key combination."""
    codes, first, G = factorize_columns(cols, null_as_group=True)
    return jnp.sort(first)


def dedup_for_distinct_agg(group_codes_arr: jax.Array, value_col: Column,
                           filter_mask: Optional[jax.Array]):
    """Keep one row per (group, value) pair for DISTINCT aggregates.

    Returns (row_indices, new_codes) to aggregate over.
    """
    vals_codes, _, _ = factorize_columns([value_col], null_as_group=True)
    m = int(vals_codes.max()) + 1 if vals_codes.shape[0] else 1
    pair = group_codes_arr * m + vals_codes
    keep = value_col.valid_mask()
    if filter_mask is not None:
        keep = keep & filter_mask
    # make invalid rows unique-but-droppable: set pair=-1-row to dedupe safely
    n = pair.shape[0]
    pair = jnp.where(keep, pair, -1 - jnp.arange(n, dtype=pair.dtype))
    uniq, first_idx = np.unique(np.asarray(pair), return_index=True)
    rows = jnp.asarray(np.sort(first_idx[uniq >= 0]))
    return rows


# ---------------------------------------------------------------------------
# scatter-free aggregation over group-sorted rows (TPU hot path, used by the
# compiled executor — physical/compiled.py). See ops/sorted_agg.py for the
# primitive layer and the rationale (TPU scatter is serialized).
# ---------------------------------------------------------------------------

def sorted_segment_aggregate(op: str, col_sorted: Optional[Column],
                             valid_sorted: Optional[jax.Array],
                             codes_sorted: jax.Array, starts: jax.Array,
                             ends: jax.Array, out_type: SqlType) -> Column:
    """One aggregate over a group-sorted stream, gathers/scans only.

    ``col_sorted`` is the argument column already permuted into group order
    (None for COUNT(*)); ``valid_sorted`` is the combined row-validity +
    FILTER-clause + value-nullability mask in the same order.
    """
    from . import sorted_agg as sa

    n = codes_sorted.shape[0]
    if valid_sorted is None:
        valid_sorted = jnp.ones(n, dtype=bool)

    if op in ("COUNT", "REGR_COUNT"):
        return Column(sa.seg_count(valid_sorted, starts, ends), out_type, None)

    assert col_sorted is not None, f"{op} requires an argument"
    data = col_sorted.data
    count = sa.seg_count(valid_sorted, starts, ends)
    has_any = count > 0

    if op in ("SUM", "$SUM0", "AVG", "STDDEV", "STDDEV_POP", "STDDEV_SAMP",
              "VAR_POP", "VAR_SAMP", "VARIANCE"):
        dscale = exact_decimal_scale(col_sorted.stype) if op in (
            "SUM", "$SUM0", "AVG") else None
        if dscale is not None:
            idata = _decimal_scaled_ints(data, dscale)
            s_int = sa.seg_sum(idata, valid_sorted, codes_sorted, starts,
                               ends).astype(jnp.int64)
            return _decimal_exact_result(op, s_int, count, dscale, out_type)
        s = sa.seg_sum(data, valid_sorted, codes_sorted, starts, ends)
        if op == "SUM":
            return Column(s.astype(physical_dtype(out_type)), out_type, has_any)
        if op == "$SUM0":
            return Column(s.astype(physical_dtype(out_type)), out_type, None)
        mean = s.astype(jnp.float64) / jnp.maximum(count, 1)
        if op == "AVG":
            return Column(mean, out_type, has_any)
        sq = data.astype(jnp.float64) ** 2
        s2 = sa.seg_sum(sq, valid_sorted, codes_sorted, starts, ends)
        var_pop = jnp.maximum(s2 / jnp.maximum(count, 1) - mean**2, 0.0)
        if op == "VAR_POP":
            return Column(var_pop, out_type, has_any)
        denom = jnp.maximum(count - 1, 1)
        var_samp = jnp.maximum((s2 - count * mean**2) / denom, 0.0)
        ok = count > 1
        if op in ("VAR_SAMP", "VARIANCE"):
            return Column(var_samp, out_type, ok)
        if op == "STDDEV_POP":
            return Column(jnp.sqrt(var_pop), out_type, has_any)
        return Column(jnp.sqrt(var_samp), out_type, ok)

    if op in ("MIN", "MAX"):
        if col_sorted.stype.is_string:
            ranked = col_sorted.dict_ranks().data.astype(jnp.int64)
            f = sa.seg_min if op == "MIN" else sa.seg_max
            out_ranks = f(ranked, valid_sorted, codes_sorted, ends)
            order = dict_sort_order(col_sorted.dictionary)
            inv = jnp.asarray(order.astype(np.int64))
            safe = jnp.clip(out_ranks, 0, len(order) - 1)
            return Column(jnp.take(inv, safe).astype(jnp.int32), out_type,
                          has_any, col_sorted.dictionary)
        f = sa.seg_min if op == "MIN" else sa.seg_max
        out = f(data, valid_sorted, codes_sorted, ends)
        return Column(out.astype(physical_dtype(out_type)), out_type, has_any)

    if op in ("EVERY", "BOOL_AND"):
        out = sa.seg_min(jnp.where(valid_sorted, data.astype(bool), True)
                         .astype(jnp.int32),
                         jnp.ones(n, bool), codes_sorted, ends) > 0
        return Column(out, out_type, has_any)
    if op in ("BOOL_OR", "ANY"):
        out = sa.seg_max(jnp.where(valid_sorted, data.astype(bool), False)
                         .astype(jnp.int32),
                         jnp.ones(n, bool), codes_sorted, ends) > 0
        return Column(out, out_type, has_any)

    if op in ("ANY_VALUE", "SINGLE_VALUE", "FIRST_VALUE", "LAST_VALUE"):
        if op == "LAST_VALUE":
            pos = sa.seg_last_valid_pos(valid_sorted, codes_sorted, ends)
        else:
            pos = sa.seg_first_valid_pos(valid_sorted, codes_sorted, ends)
        safe = jnp.clip(pos, 0, max(n - 1, 0))
        out = col_sorted.take(safe)
        return out.with_mask(out.valid_mask() & has_any)

    raise NotImplementedError(f"Sorted aggregate {op}")


def whole_table_aggregate(op: str, col: Optional[Column],
                          fmask: Optional[jax.Array], out_type: SqlType,
                          n_rows: int) -> Column:
    """Ungrouped aggregate as direct vector reductions — no segment ops.

    The eager path routes this through segment_sum with one segment, whose
    scatter lowering is pathological on TPU; a masked jnp.sum/min/max is a
    single fast reduction.
    """
    def _valid(c: Optional[Column]) -> jax.Array:
        v = jnp.ones(n_rows, dtype=bool) if fmask is None else fmask
        if c is not None and c.mask is not None:
            v = v & c.mask
        return v

    if op in ("COUNT", "REGR_COUNT"):
        v = _valid(col)
        return Column(jnp.sum(v.astype(jnp.int64)).reshape(1), out_type, None)

    assert col is not None, f"{op} requires an argument"
    valid = _valid(col)
    data = col.data
    count = jnp.sum(valid.astype(jnp.int64))
    has_any = (count > 0).reshape(1)

    if op in ("SUM", "$SUM0", "AVG", "STDDEV", "STDDEV_POP", "STDDEV_SAMP",
              "VAR_POP", "VAR_SAMP", "VARIANCE"):
        dscale = exact_decimal_scale(col.stype) if op in ("SUM", "$SUM0",
                                                          "AVG") else None
        if dscale is not None:
            iwork = jnp.where(valid, _decimal_scaled_ints(data, dscale), 0)
            s_int = jnp.sum(iwork).reshape(1)
            return _decimal_exact_result(op, s_int, count, dscale, out_type)
        if jnp.issubdtype(data.dtype, jnp.floating):
            work = jnp.where(valid, data.astype(jnp.float64), 0.0)
        else:
            work = jnp.where(valid, data.astype(jnp.int64), 0)
        s = jnp.sum(work).reshape(1)
        if op == "SUM":
            return Column(s.astype(physical_dtype(out_type)), out_type, has_any)
        if op == "$SUM0":
            return Column(s.astype(physical_dtype(out_type)), out_type, None)
        mean = s.astype(jnp.float64) / jnp.maximum(count, 1)
        if op == "AVG":
            return Column(mean, out_type, has_any)
        s2 = jnp.sum(jnp.where(valid, data.astype(jnp.float64) ** 2, 0.0)
                     ).reshape(1)
        var_pop = jnp.maximum(s2 / jnp.maximum(count, 1) - mean**2, 0.0)
        if op == "VAR_POP":
            return Column(var_pop, out_type, has_any)
        denom = jnp.maximum(count - 1, 1)
        var_samp = jnp.maximum((s2 - count * mean**2) / denom, 0.0)
        ok = (count > 1).reshape(1)
        if op in ("VAR_SAMP", "VARIANCE"):
            return Column(var_samp, out_type, ok)
        if op == "STDDEV_POP":
            return Column(jnp.sqrt(var_pop), out_type, has_any)
        return Column(jnp.sqrt(var_samp), out_type, ok)

    if op in ("MIN", "MAX"):
        if col.stype.is_string:
            ranked = col.dict_ranks().data.astype(jnp.int64)
            sent = jnp.iinfo(jnp.int64).max if op == "MIN" \
                else jnp.iinfo(jnp.int64).min
            work = jnp.where(valid, ranked, sent)
            r = (jnp.min(work) if op == "MIN" else jnp.max(work)).reshape(1)
            order = dict_sort_order(col.dictionary)
            inv = jnp.asarray(order.astype(np.int64))
            safe = jnp.clip(r, 0, len(order) - 1)
            return Column(jnp.take(inv, safe).astype(jnp.int32), out_type,
                          has_any, col.dictionary)
        if jnp.issubdtype(data.dtype, jnp.floating):
            sent = jnp.inf if op == "MIN" else -jnp.inf
        elif data.dtype == jnp.bool_:
            data = data.astype(jnp.int64)
            sent = 1 if op == "MIN" else 0
        else:
            info = jnp.iinfo(data.dtype)
            sent = info.max if op == "MIN" else info.min
        work = jnp.where(valid, data, sent)
        out = (jnp.min(work) if op == "MIN" else jnp.max(work)).reshape(1)
        return Column(out.astype(physical_dtype(out_type)), out_type, has_any)

    if op in ("EVERY", "BOOL_AND"):
        out = jnp.all(jnp.where(valid, data.astype(bool), True)).reshape(1)
        return Column(out, out_type, has_any)
    if op in ("BOOL_OR", "ANY"):
        out = jnp.any(jnp.where(valid, data.astype(bool), False)).reshape(1)
        return Column(out, out_type, has_any)

    if op in ("ANY_VALUE", "SINGLE_VALUE", "FIRST_VALUE", "LAST_VALUE"):
        idx = jnp.arange(n_rows, dtype=jnp.int64)
        if op == "LAST_VALUE":
            pos = jnp.max(jnp.where(valid, idx, -1)).reshape(1)
        else:
            pos = jnp.min(jnp.where(valid, idx, n_rows)).reshape(1)
        out = col.take(jnp.clip(pos, 0, max(n_rows - 1, 0)))
        return out.with_mask(out.valid_mask() & has_any)

    raise NotImplementedError(f"Whole-table aggregate {op}")
