"""IPython %%sql magic (reference /root/reference/dask_sql/integrations/ipython.py).

``auto_include=True`` scans the caller's namespace for pandas DataFrames and
registers them as tables before each query (reference context.py:771-788).
"""
from __future__ import annotations


def ipython_integration(context, auto_include: bool = False):
    try:
        from IPython.core.magic import register_line_cell_magic
    except ImportError:
        raise ImportError("IPython is not installed")

    def sql(line, cell=None):
        query = cell if cell is not None else line
        if auto_include:
            import pandas as pd
            ip = _get_ipython()
            if ip is not None:
                for name, val in ip.user_ns.items():
                    if isinstance(val, pd.DataFrame) and not name.startswith("_"):
                        context.create_table(name, val)
        return context.sql(query).to_pandas()

    sql.__name__ = "sql"
    register_line_cell_magic(sql)


def _get_ipython():
    try:
        from IPython import get_ipython
        return get_ipython()
    except ImportError:
        return None
