"""Interactive SQL REPL (reference: /root/reference/dask_sql/cmd.py:21-156).

``dask-sql-tpu`` console entry point: prompt_toolkit session with SQL pygments
highlighting; ``--load-test-data`` registers a synthetic timeseries table like
the reference's ``dask.datasets.timeseries``.
"""
from __future__ import annotations

import argparse
import logging
from typing import Optional


def _make_test_data():
    import numpy as np
    import pandas as pd

    rng = np.random.RandomState(42)
    n = 30 * 24 * 60  # a month of minutes
    return pd.DataFrame({
        "timestamp": pd.date_range("2000-01-01", periods=n, freq="min"),
        "id": rng.randint(800, 1200, n),
        "name": rng.choice(list("ABCDEFGH"), n),
        "x": rng.uniform(-1, 1, n),
        "y": rng.uniform(-1, 1, n),
    })


def cmd_loop(context=None, client=None, startup: bool = False,
             log_level=None):
    """Run the REPL loop (reference cmd.py:48-110)."""
    if log_level:
        logging.basicConfig(level=log_level)
    from .context import Context

    context = context or Context()
    if startup:
        context.sql("SELECT 1 + 1")

    try:
        from prompt_toolkit import PromptSession
        from prompt_toolkit.lexers import PygmentsLexer
        from pygments.lexers.sql import SqlLexer
        session = PromptSession(lexer=PygmentsLexer(SqlLexer))
        prompt = lambda: session.prompt("(dask-sql-tpu) > ")  # noqa: E731
    except ImportError:
        prompt = lambda: input("(dask-sql-tpu) > ")  # noqa: E731

    while True:
        try:
            text = prompt()
        except (EOFError, KeyboardInterrupt):
            break
        text = text.rstrip(";").strip()
        if not text:
            continue
        if text.lower() in ("quit", "exit"):
            break
        try:
            result = context.sql(text)
            if result is not None and result.num_columns:
                print(result.to_pandas())
        except Exception as e:  # pragma: no cover - interactive
            print(f"{type(e).__name__}: {e}")


def main():  # pragma: no cover - console entry
    parser = argparse.ArgumentParser(description="dask-sql-tpu REPL")
    parser.add_argument("--load-test-data", action="store_true",
                        help="Register a synthetic timeseries table 'timeseries'")
    parser.add_argument("--startup", action="store_true",
                        help="Run a first query at startup to warm compilation")
    parser.add_argument("--log-level", default=None)
    args = parser.parse_args()

    from .context import Context
    context = Context()
    if args.load_test_data:
        context.create_table("timeseries", _make_test_data())
    cmd_loop(context=context, startup=args.startup, log_level=args.log_level)


if __name__ == "__main__":  # pragma: no cover
    main()
