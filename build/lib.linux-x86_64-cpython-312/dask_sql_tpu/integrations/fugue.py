"""Fugue integration: this engine as a fugue SQLEngine.

Mirror of the reference's integration surface
(/root/reference/dask_sql/integrations/fugue.py:19-132): a ``SQLEngine``
whose ``select`` routes fugue dataframes through a fresh ``Context``, an
``ExecutionEngine`` that installs it as the default SQL engine, and an
``fsql_tpu`` workflow helper that registers results back into a Context.
Fugue is an optional dependency (reference setup.py:99); everything here is
import-gated so the module loads (and the rest of the package works) without
it.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

from ..context import Context

try:
    import fugue
    import fugue.execution.execution_engine as _fee
    from fugue.workflow.workflow import FugueSQLWorkflow, WorkflowDataFrame

    _HAS_FUGUE = True
except ImportError:  # pragma: no cover - fugue not in this image
    fugue = None
    _HAS_FUGUE = False


def _require_fugue():
    if not _HAS_FUGUE:
        raise ImportError(
            "The fugue integration requires the 'fugue' package "
            "(pip install fugue)")


if _HAS_FUGUE:  # pragma: no cover - mirrors reference fugue.py:23-67

    class TpuSQLEngine(_fee.SQLEngine):
        """Fugue SQL engine backed by this TPU engine (reference
        DaskSQLEngine, fugue.py:23-47)."""

        def select(self, dfs, statement: str):
            c = Context()
            for k, v in dfs.items():
                c.create_table(k, self.execution_engine.to_df(v).as_pandas())
            df = c.sql(statement, return_futures=False)
            return self.execution_engine.to_df(df)

    class TpuSQLExecutionEngine(fugue.NativeExecutionEngine):
        """Execution engine with TpuSQLEngine as default SQL engine
        (reference DaskSQLExecutionEngine, fugue.py:50-67)."""

        def __init__(self, *args, **kwargs):
            super().__init__(*args, **kwargs)
            self._default_sql_engine = TpuSQLEngine(self)

        @property
        def default_sql_engine(self):
            return self._default_sql_engine

else:  # placeholders that explain themselves

    class TpuSQLEngine:  # type: ignore[no-redef]
        def __init__(self, *args, **kwargs):
            _require_fugue()

    class TpuSQLExecutionEngine:  # type: ignore[no-redef]
        def __init__(self, *args, **kwargs):
            _require_fugue()


def fsql_tpu(sql: str, ctx: Optional[Context] = None, register: bool = False,
             fugue_conf: Any = None) -> Dict[str, Any]:
    """Run a fugue-SQL workflow against this engine's tables (reference
    fsql_dask, fugue.py:70-132). Named steps come back as pandas frames;
    ``register=True`` re-registers them on ``ctx``."""
    _require_fugue()
    dag = FugueSQLWorkflow()
    dfs = ({} if ctx is None else
           {k: dag.df(entry.table.to_pandas())
            for k, entry in ctx.schema[ctx.schema_name].tables.items()
            if entry.table is not None})
    result = dag._sql(sql, **dfs)
    dag.run(TpuSQLExecutionEngine(conf=fugue_conf))

    result_dfs = {k: v.result.native for k, v in result.items()
                  if isinstance(v, WorkflowDataFrame)}
    if register and ctx is not None:
        for k, v in result_dfs.items():
            ctx.create_table(k, v)
    return result_dfs
