"""IPython %%sql magic (reference /root/reference/dask_sql/integrations/ipython.py).

``auto_include=True`` scans the caller's namespace for pandas DataFrames and
registers them as tables before each query (reference context.py:771-788).
``_register_syntax_highlighting`` builds a CodeMirror mimetype out of the
LIVE operator registry — keyword and function lists stay in lockstep with
what the engine actually accepts (reference ipython.py:91-133).
"""
from __future__ import annotations

import json

# keywords of the SQL dialect + the custom-statement grammar (native/parser)
KEYWORDS = [
    "select", "from", "where", "group", "by", "having", "order", "limit",
    "offset", "fetch", "first", "next", "rows", "only", "as", "on", "using",
    "join", "inner", "left", "right", "full", "outer", "cross", "union",
    "all", "distinct", "case", "when", "then", "else", "end", "and", "or",
    "not", "in", "exists", "between", "like", "similar", "is", "escape",
    "over", "partition", "range", "preceding", "following", "current",
    "row", "unbounded", "with", "values", "interval", "cast", "filter",
    "nulls", "asc", "desc", "tablesample", "system", "bernoulli",
    # custom statements (native grammar; reference config.fmpp:46-60)
    "create", "drop", "show", "describe", "analyze", "use", "table",
    "tables", "schema", "schemas", "columns", "model", "models",
    "experiment", "predict", "export", "view", "if", "replace", "compute",
    "statistics", "for",
]


def ipython_integration(context, auto_include: bool = False,
                        disable_highlighting: bool = False):
    try:
        from IPython.core.magic import register_line_cell_magic
    except ImportError:
        raise ImportError("IPython is not installed")

    def sql(line, cell=None):
        query = cell if cell is not None else line
        if auto_include:
            import pandas as pd
            ip = _get_ipython()
            if ip is not None:
                for name, val in ip.user_ns.items():
                    if isinstance(val, pd.DataFrame) and not name.startswith("_"):
                        context.create_table(name, val)
        return context.sql(query).to_pandas()

    sql.__name__ = "sql"
    register_line_cell_magic(sql)
    if not disable_highlighting:
        _register_syntax_highlighting()


def highlighting_mime_type() -> dict:
    """CodeMirror sql-mode mimetype dict from the live engine registries."""
    from ..physical.rex.ops import OPERATION_MAPPING
    from ..types import _PHYSICAL

    def as_set(items):
        return {str(k).lower(): True for k in items}

    return {
        "name": "sql",
        "keywords": as_set(KEYWORDS + list(OPERATION_MAPPING)),
        "builtin": as_set(_PHYSICAL.keys()),
        "atoms": as_set(["false", "true", "null"]),
        "dateSQL": as_set(["time"]),
        "support": as_set(["ODBCdotTable", "doubleQuote", "zerolessFloat"]),
    }


def highlighting_js() -> str:
    """The javascript payload registering the dask-sql-tpu CodeMirror mode."""
    return (
        'require(["codemirror/lib/codemirror"]);\n'
        'CodeMirror.defineMIME("text/x-dasksql", '
        + json.dumps(highlighting_mime_type())
        + ');\n'
        'CodeMirror.modeInfo.push({name: "Dask SQL (TPU)", '
        'mime: "text/x-dasksql", mode: "sql"});\n'
        "IPython.CodeCell.options_default.highlight_modes"
        "['magic_text/x-dasksql'] = {'reg': ['^%%sql']};\n"
        "IPython.notebook.events.on('kernel_ready.Kernel', () => {\n"
        "  IPython.notebook.get_cells().map(cell =>\n"
        "    cell.code_mirror ? cell.auto_highlight() : cell);\n"
        "});\n"
    )


def _register_syntax_highlighting() -> None:
    """Ship the CodeMirror mode to the frontend (no-op without IPython)."""
    try:
        from IPython.core import display
    except ImportError:
        return
    display.display_javascript(highlighting_js(), raw=True)


def _get_ipython():
    try:
        from IPython import get_ipython
        return get_ipython()
    except ImportError:
        return None
