from .parser import parse_sql, parse_one  # noqa: F401
from .lexer import tokenize, Token, LexError  # noqa: F401
