"""Bridge: native parser JSON -> the AST dataclasses in ``ast.py``.

The C++ parser (native/parser.cpp) serializes each AST node as
``{"t": "<ClassName>", <field>: <value>, ...}`` with field names identical to
the dataclasses, so reconstruction is mechanical; the only special cases are
tuple-valued fields (pos, frame bounds, sample, whens, projections, ctes) and
the ``{"__map__": [...]}`` encoding of SQL MAP kwargs values (whose keys may
be non-strings, which JSON objects cannot carry).
"""
from __future__ import annotations

from typing import Any, List, Optional

from ..utils import ParsingException
from . import ast as A

_NODE_TYPES = {
    name: getattr(A, name)
    for name in (
        "Literal", "IntervalLiteral", "ColumnRef", "Star", "Param", "Call",
        "Case", "Cast", "InList", "Between", "Like", "IsNull", "IsBool",
        "IsDistinctFrom", "Subquery", "TableRef", "SubqueryRelation",
        "JoinRelation", "PredictRelation", "SortKey", "Select", "SetOp",
        "ValuesQuery", "QueryStatement", "CreateTable", "CreateTableAs",
        "DropTable", "CreateSchema", "DropSchema", "UseSchema", "ShowSchemas",
        "ShowTables", "ShowColumns", "ShowModels", "DescribeModel",
        "AnalyzeTable", "CreateModel", "DropModel", "CreateExperiment",
        "ExportModel", "DescribeTable", "ExplainStatement", "WindowSpec",
    )
}


def _tuple2(v):
    return tuple(v) if v is not None else None


def _convert_kwarg_value(v):
    if isinstance(v, dict):
        if "__map__" in v and len(v) == 1:
            items = [_convert_kwarg_value(x) for x in v["__map__"]]
            return dict(zip(items[0::2], items[1::2]))
        return {k: _convert_kwarg_value(x) for k, x in v.items()}
    if isinstance(v, list):
        return [_convert_kwarg_value(x) for x in v]
    return v


def _convert(v: Any) -> Any:
    """Recursively convert a JSON value into AST nodes."""
    if isinstance(v, dict):
        t = v.get("t")
        cls = _NODE_TYPES.get(t)
        if cls is None:
            raise ValueError(f"unknown native AST node type: {t!r}")
        fields = {}
        orig_name = None
        for key, val in v.items():
            if key == "t":
                continue
            if key == "orig":
                orig_name = val
                continue
            if key == "pos":
                fields["pos"] = tuple(val)
            elif key == "kwargs":
                fields["kwargs"] = _convert_kwarg_value(val)
            elif key == "projections":
                fields["projections"] = [( _convert(e), a) for e, a in val]
            elif key == "ctes":
                fields["ctes"] = [(name, _convert(q)) for name, q in val]
            elif key == "whens":
                fields["whens"] = [(_convert(c), _convert(x)) for c, x in val]
            elif key == "rows":
                fields["rows"] = [[_convert(e) for e in row] for row in val]
            elif key == "frame":
                fields["frame"] = (
                    None if val is None
                    else (val[0], _tuple2(val[1]), _tuple2(val[2]))
                )
            elif key == "sample":
                fields["sample"] = _tuple2(val)
            elif key == "using":
                fields["using"] = val  # list, "NATURAL", or None
            elif isinstance(val, dict):
                fields[key] = _convert(val)
            elif isinstance(val, list) and key in (
                "args", "values", "partition_by", "order_by", "group_by",
            ):
                fields[key] = [_convert(x) for x in val]
            else:
                fields[key] = val
        node = cls(**fields)
        if orig_name is not None:
            node.original_name = orig_name
        return node
    return v


def json_to_statements(envelope: dict, sql: str) -> Optional[List[A.Statement]]:
    """Convert the native parser's JSON envelope to AST statements.

    Raises ParsingException for parse errors (same shape as the Python
    parser's); returns None only if the envelope is malformed.
    """
    if "error" in envelope:
        e = envelope["error"]
        raise ParsingException(sql, e["msg"], e["line"], e["col"],
                               max(1, e.get("width", 1)))
    if "ok" not in envelope:
        return None
    return [_convert(stmt) for stmt in envelope["ok"]]
