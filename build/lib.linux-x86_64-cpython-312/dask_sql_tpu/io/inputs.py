"""Input ingestion plugins: anything -> device Table.

Mirrors the reference's input_utils package
(/root/reference/dask_sql/input_utils/): ``InputUtil.to_table`` probes
registered plugins in order (convert.py:66-79); plugins cover native tables,
pandas-likes, dict/record data, and file locations by extension
(location.py:10-34).  Hive/Intake/SQLAlchemy plugins exist as gated stubs —
their optional dependencies are not in this image.
"""
from __future__ import annotations

import os
from typing import Any, List, Optional

import numpy as np

from ..table import Table
from ..utils import Pluggable


class InputUtil(Pluggable):
    """Probes input plugins in registration order (reference convert.py:38-79)."""

    @classmethod
    def to_table(cls, input_item: Any, **kwargs) -> Table:
        if isinstance(input_item, list):
            from ..ops.join import concat_tables
            return concat_tables([cls.to_table(i, **kwargs) for i in input_item])
        for plugin in cls.get_plugins():
            if plugin.is_correct_input(input_item, **kwargs):
                return plugin.to_table(input_item, **kwargs)
        raise ValueError(f"Do not understand the input type {type(input_item)}")


class BaseInputPlugin:
    def is_correct_input(self, input_item, **kwargs) -> bool:
        raise NotImplementedError

    def to_table(self, input_item, **kwargs) -> Table:
        raise NotImplementedError


class DeviceTableInputPlugin(BaseInputPlugin):
    """Already a device Table (analogue of DaskInputPlugin, dask.py:8)."""

    def is_correct_input(self, input_item, **kwargs):
        return isinstance(input_item, Table)

    def to_table(self, input_item, **kwargs):
        return input_item


class PandasLikeInputPlugin(BaseInputPlugin):
    """pandas DataFrame / Series (reference pandaslike.py:12)."""

    def is_correct_input(self, input_item, **kwargs):
        import pandas as pd
        return isinstance(input_item, (pd.DataFrame, pd.Series))

    def to_table(self, input_item, **kwargs):
        import pandas as pd
        if isinstance(input_item, pd.Series):
            input_item = input_item.to_frame()
        return Table.from_pandas(input_item)


class DictInputPlugin(BaseInputPlugin):
    """dict of column -> values, numpy structured arrays."""

    def is_correct_input(self, input_item, **kwargs):
        return isinstance(input_item, dict)

    def to_table(self, input_item, **kwargs):
        return Table.from_pydict(input_item)


class ArrowInputPlugin(BaseInputPlugin):
    def is_correct_input(self, input_item, **kwargs):
        try:
            import pyarrow as pa
            return isinstance(input_item, pa.Table)
        except ImportError:
            return False

    def to_table(self, input_item, **kwargs):
        return Table.from_pandas(input_item.to_pandas())


class LocationInputPlugin(BaseInputPlugin):
    """File path -> reader by extension (reference location.py:10-34)."""

    def is_correct_input(self, input_item, **kwargs):
        return isinstance(input_item, str)

    def to_table(self, input_item: str, file_format: Optional[str] = None,
                 **kwargs) -> Table:
        import pandas as pd

        if not file_format:
            file_format = os.path.splitext(input_item)[1].lstrip(".")
        file_format = (file_format or "").lower()
        read_kwargs = {k: v for k, v in kwargs.items()
                       if k not in ("persist", "schema_name", "statistics",
                                    "gpu", "table_name")}
        if file_format in ("csv", "tsv", "txt"):
            if file_format == "tsv" and "sep" not in read_kwargs:
                read_kwargs["sep"] = "\t"
            df = pd.read_csv(input_item, **read_kwargs)
        elif file_format in ("parquet", "pq"):
            df = pd.read_parquet(input_item, **read_kwargs)
        elif file_format == "json":
            df = pd.read_json(input_item, **read_kwargs)
        elif file_format in ("feather", "arrow"):
            df = pd.read_feather(input_item, **read_kwargs)
        elif file_format == "orc":
            df = pd.read_orc(input_item, **read_kwargs)
        else:
            raise AttributeError(f"Do not understand input format {file_format}")
        return Table.from_pandas(df)


class HiveInputPlugin(BaseInputPlugin):
    """Hive metastore tables via any DB-API-ish cursor (io/hive.py holds the
    DESCRIBE FORMATTED machinery, reference hive.py:25-284)."""

    def is_correct_input(self, input_item, **kwargs):
        from .hive import HiveInput
        return HiveInput.is_hive_like(input_item, **kwargs)

    def to_table(self, input_item, **kwargs):
        from .hive import HiveInput
        return HiveInput.to_table(input_item, **kwargs)


class IntakeCatalogInputPlugin(BaseInputPlugin):
    """Intake catalogs (reference intake.py:14-34): the named catalog entry
    is read into pandas and encoded to a device Table.  Accepts a Catalog
    object or, with ``file_format="intake"``, a catalog path/URL."""

    @staticmethod
    def _intake():
        try:
            import intake
            return intake
        except ImportError:
            return None

    def is_correct_input(self, input_item, file_format=None, **kwargs):
        if file_format == "intake":
            # claimed even without intake installed, so to_table raises the
            # actionable ImportError instead of LocationInputPlugin's
            # "do not understand input format"
            return True
        intake = self._intake()
        return (intake is not None
                and isinstance(input_item, intake.catalog.Catalog))

    def to_table(self, input_item, table_name=None, file_format=None,
                 **kwargs):
        intake = self._intake()
        if intake is None:
            raise ImportError("Intake ingestion requires intake")
        table_name = kwargs.pop("intake_table_name", table_name)
        catalog_kwargs = kwargs.pop("catalog_kwargs", {})
        if isinstance(input_item, str):
            input_item = intake.open_catalog(input_item, **catalog_kwargs)
        # the reference materializes to dask (intake.py:34 `.to_dask()`);
        # here the source reads to pandas and uploads to the device
        read_kwargs = {k: v for k, v in kwargs.items()
                       if k not in ("persist", "schema_name", "statistics",
                                    "gpu")}
        source = input_item[table_name](**read_kwargs) if read_kwargs \
            else input_item[table_name]
        return Table.from_pandas(source.read())
