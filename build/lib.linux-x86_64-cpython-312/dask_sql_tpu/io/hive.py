"""Hive metastore ingestion: DESCRIBE FORMATTED -> columnar device Table.

TPU-native counterpart of the reference's HiveInputPlugin
(/root/reference/dask_sql/input_utils/hive.py:25-284): the same
state-machine parse of ``DESCRIBE FORMATTED`` / ``SHOW PARTITIONS`` output,
the same InputFormat -> reader mapping and partition-column synthesis — but
duck-typed over any DB-API-ish cursor (``execute`` + ``fetchall`` on either
the cursor or the execute result), so it works with pyhive, sqlalchemy
connections, or any test double, none of which need to be importable.
Files land in pandas and then in a device ``Table``.
"""
from __future__ import annotations

import ast
import glob as _glob
import logging
import os
from typing import Any, Dict, Optional, Tuple

import pandas as pd

from ..table import Table

logger = logging.getLogger(__name__)

# hive type name -> pandas-friendly dtype cast (reference uses
# sql_to_python_type; we cast on the pandas side before device upload)
_HIVE_TYPES = {
    "TINYINT": "int8", "SMALLINT": "int16", "INT": "int32", "INTEGER": "int32",
    "BIGINT": "int64", "FLOAT": "float32", "DOUBLE": "float64",
    "DECIMAL": "float64", "NUMERIC": "float64", "BOOLEAN": "bool",
    "STRING": "object", "VARCHAR": "object", "CHAR": "object",
    "DATE": "datetime64[ns]", "TIMESTAMP": "datetime64[ns]",
    "BINARY": "object",
}


def _hive_cast(df: pd.DataFrame, col: str, hive_type: str) -> pd.DataFrame:
    base = hive_type.upper().split("(")[0].strip()
    dtype = _HIVE_TYPES.get(base)
    if dtype is None:
        logger.warning("Unknown hive type %s for column %s", hive_type, col)
        return df
    if df[col].dtype != dtype:
        try:
            df[col] = df[col].astype(dtype)
        except (TypeError, ValueError):
            logger.warning("Could not cast %s to %s", col, dtype)
    return df


def _fetch_all(cursor, sql: str):
    """pyhive fetches on the cursor, sqlalchemy on the execute result
    (reference hive.py:270-284)."""
    result = cursor.execute(sql)
    try:
        return result.fetchall()
    except AttributeError:
        return cursor.fetchall()


def parse_hive_table_description(
    cursor, schema: str, table_name: str, partition: Optional[str] = None
) -> Tuple[Dict, Dict, Dict, Dict]:
    """State-machine parse of DESCRIBE FORMATTED output
    (reference hive.py:173-253). Returns (columns, table, storage,
    partitions) information dicts, insertion-ordered."""
    _fetch_all(cursor, f"USE {schema}")
    if partition:
        rows = _fetch_all(
            cursor, f"DESCRIBE FORMATTED {table_name} PARTITION ({partition})")
    else:
        rows = _fetch_all(cursor, f"DESCRIBE FORMATTED {table_name}")

    table_information: Dict = {}
    column_information: Dict = {}
    storage_information: Dict = {}
    partition_information: Dict = {}
    mode = "column"
    last_field = None

    for key, value, value2 in rows:
        key = key.strip().rstrip(":") if key else ""
        value = value.strip() if value else ""
        value2 = value2.strip() if value2 else ""

        if key == "# col_name":
            continue
        if key in ("# Detailed Table Information",
                   "# Detailed Partition Information"):
            mode = "table"
        elif key == "# Storage Information":
            mode = "storage"
        elif key == "# Partition Information":
            mode = "partition"
        elif key.startswith("#"):
            mode = None
        elif key:
            if not value:
                value = dict()
            target = {"column": column_information, "storage":
                      storage_information, "table": table_information,
                      "partition": partition_information}.get(mode)
            if target is not None:
                target[key] = value
                last_field = target[key]
        elif value and isinstance(last_field, dict):
            last_field[value] = value2

    return (column_information, table_information, storage_information,
            partition_information)


def parse_hive_partition_description(cursor, schema: str, table_name: str):
    """SHOW PARTITIONS -> ['key=value/key2=value2', ...]
    (reference hive.py:255-268)."""
    _fetch_all(cursor, f"USE {schema}")
    return [row[0] for row in _fetch_all(cursor,
                                         f"SHOW PARTITIONS {table_name}")]


def _normalize_location(loc: str) -> str:
    if loc.startswith("dbfs:/") and not loc.startswith("dbfs://"):
        loc = f"dbfs://{loc.lstrip('dbfs:')}"
    if loc.startswith("file:"):
        loc = loc[len("file:"):]
    # skip dot/underscore files (_SUCCESS etc., reference hive.py:99-103)
    return os.path.join(loc, "[A-Za-z0-9-]*")


def _expand_files(pattern: str):
    """Glob expansion: fsspec for remote URIs (hdfs://, s3://, dbfs://),
    stdlib glob for local paths. Returns (filesystem_or_None, paths)."""
    if "://" in pattern:
        import fsspec
        fs, _, paths = fsspec.get_fs_token_paths(pattern)
        return fs, (paths or [pattern])
    return None, (sorted(_glob.glob(pattern)) or [pattern])


def _read_location(location: str, fmt: str, column_information: Dict,
                   storage_information: Dict, **kwargs) -> pd.DataFrame:
    pattern = _normalize_location(location)
    fs, paths = _expand_files(pattern)

    import contextlib

    @contextlib.contextmanager
    def _open(p):
        if fs is None:
            yield p
        else:
            with fs.open(p, "rb") as f:
                yield f

    def _read_all(reader):
        out = []
        for p in paths:
            with _open(p) as f:
                out.append(reader(f))
        return out

    if fmt in ("TextInputFormat", "SequenceFileInputFormat"):
        sep = storage_information.get("Storage Desc Params", {}) \
            .get("field.delim", ",")
        frames = _read_all(
            lambda f: pd.read_csv(f, sep=sep, header=None, **kwargs))
    elif fmt in ("ParquetInputFormat", "MapredParquetInputFormat"):
        # restrict to the metastore's columns: partition directories like
        # .../col=3/ would otherwise surface as extra columns and the
        # positional rename below would mislabel data (reference hive.py:115)
        kwargs.setdefault("columns", list(column_information.keys()))
        frames = _read_all(lambda f: pd.read_parquet(f, **kwargs))
    elif fmt == "OrcInputFormat":
        frames = _read_all(lambda f: pd.read_orc(f, **kwargs))
    elif fmt == "JsonInputFormat":
        frames = _read_all(lambda f: pd.read_json(f, lines=True, **kwargs))
    else:
        raise AttributeError(f"Do not understand hive's table format {fmt}")
    df = pd.concat(frames, ignore_index=True) if len(frames) > 1 else frames[0]
    df = df.rename(columns=dict(zip(df.columns, column_information.keys())))
    for col, hive_type in column_information.items():
        df = _hive_cast(df, col, hive_type)
    return df


def hive_table_to_pandas(cursor, table_name: str, schema: str = "default",
                         **kwargs) -> pd.DataFrame:
    """Load a hive table (all partitions) into pandas
    (reference HiveInputPlugin.to_dc, hive.py:39-175)."""
    (column_information, table_information, storage_information,
     partition_information) = parse_hive_table_description(
        cursor, schema, table_name)

    if "InputFormat" in storage_information:
        fmt = storage_information["InputFormat"].split(".")[-1]
    elif "InputFormat" in table_information:  # databricks layout
        fmt = table_information["InputFormat"].split(".")[-1]
    else:
        raise RuntimeError(
            "Do not understand the output of 'DESCRIBE FORMATTED <table>'")

    if partition_information:
        partitions = parse_hive_partition_description(cursor, schema,
                                                      table_name)
        frames = []
        for partition in partitions:
            (part_cols, part_table, _, _) = parse_hive_table_description(
                cursor, schema, table_name, partition=partition)
            df = _read_location(part_table["Location"], fmt, part_cols,
                                storage_information, **kwargs)
            values = ast.literal_eval(part_table["Partition Value"])
            for i, (pkey, ptype) in enumerate(partition_information.items()):
                df[pkey] = values[i]
                df = _hive_cast(df, pkey, ptype)
            frames.append(df)
        return pd.concat(frames, ignore_index=True)

    return _read_location(table_information["Location"], fmt,
                          column_information, storage_information, **kwargs)


class HiveInput:
    """Duck-typed hive ingestion (registered as an input plugin)."""

    @staticmethod
    def is_hive_like(input_item: Any, **kwargs) -> bool:
        if kwargs.get("format") == "hive" or kwargs.get("file_format") == "hive":
            return True
        mod = type(input_item).__module__ or ""
        if mod.startswith("pyhive"):
            return True
        # sqlalchemy: only a Connection is a hive-capable cursor (reference
        # hive.py:28-36); Engines/Sessions etc. must not be claimed here
        if mod.startswith("sqlalchemy"):
            return (type(input_item).__name__ == "Connection"
                    and hasattr(input_item, "execute"))
        return False

    @staticmethod
    def to_table(input_item: Any, *, table_name: Optional[str] = None,
                 **kwargs) -> Table:
        name = kwargs.pop("hive_table_name", table_name)
        schema = kwargs.pop("hive_schema_name", "default")
        kwargs.pop("format", None)
        kwargs.pop("file_format", None)
        df = hive_table_to_pandas(input_item, name, schema, **kwargs)
        return Table.from_pandas(df)
