"""SQL logical type system and mappings to JAX/numpy physical types.

TPU-native re-design of the reference's type mapping layer
(/root/reference/dask_sql/mappings.py:1-300).  The reference maps SQL types to
pandas/numpy dtypes (including pandas nullable extension dtypes); here every
logical type maps to a *fixed-width device dtype* plus an explicit validity
mask, because TPUs have no NaN-as-null story for ints and XLA wants static,
uniform buffers:

- BOOLEAN            -> bool_
- TINYINT..BIGINT    -> int8/int16/int32/int64
- FLOAT/DOUBLE       -> float32/float64
- DECIMAL(p, s)      -> float64 (documented precision compromise, like the
                        reference's DECIMAL->float64, mappings.py:64)
- VARCHAR/CHAR       -> int32 dictionary codes + host-side dictionary
- DATE               -> int32 days since Unix epoch
- TIMESTAMP          -> int64 microseconds since Unix epoch
- TIME               -> int64 microseconds since midnight
- INTERVAL day-time  -> int64 milliseconds (Calcite's representation)
- INTERVAL year-month-> int64 months
"""
from __future__ import annotations

import datetime
from dataclasses import dataclass, field
from typing import Any, Optional

import numpy as np


@dataclass(frozen=True)
class SqlType:
    """A logical SQL type. ``name`` is the canonical upper-case SQL name."""

    name: str
    precision: Optional[int] = None
    scale: Optional[int] = None
    nullable: bool = True

    def __str__(self) -> str:
        if self.name == "DECIMAL" and self.precision is not None:
            return f"DECIMAL({self.precision}, {self.scale or 0})"
        return self.name

    # -- classification helpers -------------------------------------------
    @property
    def is_numeric(self) -> bool:
        return self.name in _NUMERIC

    @property
    def is_integer(self) -> bool:
        return self.name in _INTEGER

    @property
    def is_floating(self) -> bool:
        return self.name in ("FLOAT", "DOUBLE", "REAL", "DECIMAL")

    @property
    def is_string(self) -> bool:
        return self.name in ("VARCHAR", "CHAR")

    @property
    def is_temporal(self) -> bool:
        return self.name in ("DATE", "TIMESTAMP", "TIMESTAMP_WITH_LOCAL_TIME_ZONE", "TIME")

    @property
    def is_interval(self) -> bool:
        return self.name in ("INTERVAL_DAY_TIME", "INTERVAL_YEAR_MONTH")

    @property
    def is_boolean(self) -> bool:
        return self.name == "BOOLEAN"

    def with_nullable(self, nullable: bool) -> "SqlType":
        return SqlType(self.name, self.precision, self.scale, nullable)


_INTEGER = {"TINYINT", "SMALLINT", "INTEGER", "BIGINT"}
_NUMERIC = _INTEGER | {"FLOAT", "REAL", "DOUBLE", "DECIMAL"}

# Canonical singletons
BOOLEAN = SqlType("BOOLEAN")
TINYINT = SqlType("TINYINT")
SMALLINT = SqlType("SMALLINT")
INTEGER = SqlType("INTEGER")
BIGINT = SqlType("BIGINT")
FLOAT = SqlType("FLOAT")
DOUBLE = SqlType("DOUBLE")
VARCHAR = SqlType("VARCHAR")
DATE = SqlType("DATE")
TIMESTAMP = SqlType("TIMESTAMP")
TIME = SqlType("TIME")
INTERVAL_DAY_TIME = SqlType("INTERVAL_DAY_TIME")
INTERVAL_YEAR_MONTH = SqlType("INTERVAL_YEAR_MONTH")
NULLTYPE = SqlType("NULL")


def decimal(precision: int = 38, scale: int = 0) -> SqlType:
    return SqlType("DECIMAL", precision, scale)


def exact_decimal_scale(stype: SqlType):
    """Scale for EXACT scaled-int64 aggregation, or None.

    DECIMAL(p<=15, 0<=s<=9) sums fit int64 at any realistic row count
    (SF100 money sums are ~6e15 'cents' < 2^53 < 2^63): SUM/AVG over such
    columns accumulate in integers — bit-stable across runs and matching a
    true decimal engine exactly, unlike the f64 fold the reference uses
    (mappings.py:64 maps DECIMAL to float64 end to end).

    The precision gate is 15, not 18: values are STORED as f64, so an
    individual value must be exactly representable in the 53-bit mantissa
    (10^15 < 2^53 < 10^16) or the scaled-int conversion already misrounds
    before any summation happens.
    """
    if stype.name != "DECIMAL" or stype.scale is None:
        return None
    if not (0 <= stype.scale <= 9):
        return None
    if stype.precision is not None and stype.precision > 15:
        return None
    return stype.scale


# ---------------------------------------------------------------------------
# logical type -> physical numpy dtype (device representation)
# ---------------------------------------------------------------------------

_PHYSICAL: dict[str, np.dtype] = {
    "BOOLEAN": np.dtype(np.bool_),
    "TINYINT": np.dtype(np.int8),
    "SMALLINT": np.dtype(np.int16),
    "INTEGER": np.dtype(np.int32),
    "BIGINT": np.dtype(np.int64),
    "FLOAT": np.dtype(np.float32),
    "REAL": np.dtype(np.float32),
    "DOUBLE": np.dtype(np.float64),
    "DECIMAL": np.dtype(np.float64),
    "VARCHAR": np.dtype(np.int32),  # dictionary codes
    "CHAR": np.dtype(np.int32),
    "DATE": np.dtype(np.int32),
    "TIMESTAMP": np.dtype(np.int64),
    "TIMESTAMP_WITH_LOCAL_TIME_ZONE": np.dtype(np.int64),
    "TIME": np.dtype(np.int64),
    "INTERVAL_DAY_TIME": np.dtype(np.int64),
    "INTERVAL_YEAR_MONTH": np.dtype(np.int64),
    "NULL": np.dtype(np.float64),
}


def physical_dtype(stype: SqlType) -> np.dtype:
    return _PHYSICAL[stype.name]


# ---------------------------------------------------------------------------
# numpy/pandas dtype -> logical SQL type  (reference: mappings.py:17-41)
# ---------------------------------------------------------------------------

def sql_type_from_numpy(dtype) -> SqlType:
    dtype = np.dtype(dtype) if not isinstance(dtype, np.dtype) else dtype
    kind = dtype.kind
    if kind == "b":
        return BOOLEAN
    if kind == "i":
        return {1: TINYINT, 2: SMALLINT, 4: INTEGER, 8: BIGINT}[dtype.itemsize]
    if kind == "u":
        # SQL has no unsigned types: widen
        return {1: SMALLINT, 2: INTEGER, 4: BIGINT, 8: BIGINT}[dtype.itemsize]
    if kind == "f":
        return FLOAT if dtype.itemsize <= 4 else DOUBLE
    if kind == "M":
        return TIMESTAMP
    if kind == "m":
        return INTERVAL_DAY_TIME
    if kind in ("U", "S", "O"):
        return VARCHAR
    raise NotImplementedError(f"No SQL type for numpy dtype {dtype}")


# ---------------------------------------------------------------------------
# type promotion for arithmetic / comparison / set operations
# ---------------------------------------------------------------------------

_NUM_ORDER = ["TINYINT", "SMALLINT", "INTEGER", "BIGINT", "FLOAT", "REAL", "DOUBLE", "DECIMAL"]


def promote(a: SqlType, b: SqlType) -> SqlType:
    """Least common supertype for binary operations."""
    if a.name == b.name:
        if a.name == "DECIMAL":
            return SqlType(
                "DECIMAL",
                max(a.precision or 38, b.precision or 38),
                max(a.scale or 0, b.scale or 0),
            )
        return SqlType(a.name)
    if a.name == "NULL":
        return SqlType(b.name, b.precision, b.scale)
    if b.name == "NULL":
        return SqlType(a.name, a.precision, a.scale)
    if a.is_numeric and b.is_numeric:
        ia, ib = _NUM_ORDER.index(a.name), _NUM_ORDER.index(b.name)
        winner = _NUM_ORDER[max(ia, ib)]
        if winner == "DECIMAL":
            # decimal vs float -> double; decimal vs int -> decimal
            other = a if winner == b.name else b
            if other.name in ("FLOAT", "REAL", "DOUBLE"):
                return DOUBLE
            d = a if a.name == "DECIMAL" else b
            return SqlType("DECIMAL", d.precision, d.scale)
        return SqlType(winner)
    if a.is_string and b.is_string:
        return VARCHAR
    if a.is_temporal and b.is_temporal:
        return TIMESTAMP if "TIMESTAMP" in (a.name, b.name) else SqlType(a.name)
    # date/timestamp +- interval
    if a.is_temporal and b.is_interval:
        return SqlType(a.name)
    if b.is_temporal and a.is_interval:
        return SqlType(b.name)
    if a.is_boolean and b.is_boolean:
        return BOOLEAN
    # string vs anything: compare as the other type (SQL implicit cast)
    if a.is_string:
        return SqlType(b.name, b.precision, b.scale)
    if b.is_string:
        return SqlType(a.name, a.precision, a.scale)
    raise TypeError(f"Cannot promote {a} and {b}")


def parse_type_name(name: str, precision=None, scale=None) -> SqlType:
    """Map a SQL type name as written (``INT``, ``STRING``...) to a SqlType."""
    n = name.upper()
    aliases = {
        "INT": "INTEGER",
        "STRING": "VARCHAR",
        "TEXT": "VARCHAR",
        "REAL": "FLOAT",
        "FLOAT4": "FLOAT",
        "FLOAT8": "DOUBLE",
        "DOUBLE PRECISION": "DOUBLE",
        "NUMERIC": "DECIMAL",
        "DEC": "DECIMAL",
        "BOOL": "BOOLEAN",
        "INT2": "SMALLINT",
        "INT4": "INTEGER",
        "INT8": "BIGINT",
        "LONG": "BIGINT",
        "DATETIME": "TIMESTAMP",
    }
    n = aliases.get(n, n)
    if n == "DECIMAL":
        return SqlType("DECIMAL", precision or 38, scale or 0)
    if n in ("VARCHAR", "CHAR") and precision is not None:
        return SqlType(n, precision)
    if n not in _PHYSICAL:
        raise NotImplementedError(f"Unknown SQL type: {name}")
    return SqlType(n)


# ---------------------------------------------------------------------------
# python scalar <-> SQL value conversion (reference: mappings.py:103-190)
# ---------------------------------------------------------------------------

_EPOCH = datetime.datetime(1970, 1, 1)
_EPOCH_DATE = datetime.date(1970, 1, 1)


def python_value_to_physical(value: Any, stype: SqlType):
    """Convert a python literal to its physical (device) representation."""
    if value is None:
        return None
    n = stype.name
    if n == "DATE":
        if isinstance(value, datetime.datetime):
            value = value.date()
        if isinstance(value, datetime.date):
            return (value - _EPOCH_DATE).days
        if isinstance(value, str):
            return (datetime.date.fromisoformat(value) - _EPOCH_DATE).days
        return int(value)
    if n in ("TIMESTAMP", "TIMESTAMP_WITH_LOCAL_TIME_ZONE"):
        if isinstance(value, str):
            value = datetime.datetime.fromisoformat(value)
        if isinstance(value, datetime.datetime):
            if value.tzinfo is not None:
                value = value.astimezone(datetime.timezone.utc).replace(tzinfo=None)
            return int((value - _EPOCH).total_seconds() * 1_000_000)
        if isinstance(value, datetime.date):
            return int((datetime.datetime.combine(value, datetime.time()) - _EPOCH).total_seconds() * 1_000_000)
        if isinstance(value, np.datetime64):
            return int(value.astype("datetime64[us]").astype(np.int64))
        return int(value)
    if n == "TIME":
        if isinstance(value, str):
            value = datetime.time.fromisoformat(value)
        if isinstance(value, datetime.time):
            return ((value.hour * 60 + value.minute) * 60 + value.second) * 1_000_000 + value.microsecond
        return int(value)
    if n == "INTERVAL_DAY_TIME":
        if isinstance(value, datetime.timedelta):
            return int(value.total_seconds() * 1000)
        if isinstance(value, np.timedelta64):
            return int(value.astype("timedelta64[ms]").astype(np.int64))
        return int(value)
    if n == "BOOLEAN":
        return bool(value)
    if n in _INTEGER or n == "INTERVAL_YEAR_MONTH":
        return int(value)
    if stype.is_floating:
        return float(value)
    return value


def physical_to_python_value(value: Any, stype: SqlType) -> Any:
    """Convert a physical scalar back to a rich python value."""
    if value is None:
        return None
    n = stype.name
    if n == "DATE":
        return _EPOCH_DATE + datetime.timedelta(days=int(value))
    if n in ("TIMESTAMP", "TIMESTAMP_WITH_LOCAL_TIME_ZONE"):
        return _EPOCH + datetime.timedelta(microseconds=int(value))
    if n == "TIME":
        us = int(value)
        return datetime.time(us // 3_600_000_000, us // 60_000_000 % 60, us // 1_000_000 % 60, us % 1_000_000)
    if n == "INTERVAL_DAY_TIME":
        return datetime.timedelta(milliseconds=int(value))
    if n == "BOOLEAN":
        return bool(value)
    if stype.is_integer or n == "INTERVAL_YEAR_MONTH":
        return int(value)
    if stype.is_floating:
        return float(value)
    return value
