"""dask_sql_tpu: a TPU-native distributed SQL query engine.

Brand-new implementation of the capability surface of dask-sql
(/root/reference): a ``Context`` catalog + SQL entry point, a native SQL
parser/planner with rule-based optimization, and a plugin-registry physical
layer — lowering relational algebra to compiled JAX/XLA columnar kernels over
mesh-sharded ``jax.Array`` tables instead of lazy Dask dataframe graphs.
"""

# SQL semantics need BIGINT/DOUBLE: enable 64-bit JAX before anything imports
# jax.numpy.  (TPU-hot kernels downcast explicitly where it matters.)
import os as _os

import jax as _jax

_jax.config.update("jax_enable_x64", True)

# AOT program cache (``DSQL_XLA_CACHE=/path``): the reference pays no compile
# step (lazy dask graphs, SURVEY §3.1); ours is XLA, where a single program
# costs ~40-200 s to compile over the tunneled TPU backend but loads from the
# persistent cache in ~0.3 s (measured).  Every executable is persisted
# (min size/time thresholds off) because on the TPU path program count is
# small and each one is expensive.  Best-effort: any backend that rejects
# serialization just compiles as usual.
if _os.environ.get("DSQL_XLA_CACHE"):
    try:
        _jax.config.update("jax_compilation_cache_dir",
                           _os.environ["DSQL_XLA_CACHE"])
        _jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        _jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    except Exception:  # pragma: no cover - depends on jax version
        pass

from .context import Context  # noqa: E402
from .cmd import cmd_loop  # noqa: E402
from .server.app import run_server  # noqa: E402

__version__ = "0.1.0"

__all__ = ["Context", "cmd_loop", "run_server", "__version__"]
