"""Scalar operation library: REX op name -> device kernel.

TPU-native re-implementation of the reference's ~70-operator mapping
(/root/reference/dask_sql/physical/rex/core/call.py:685-762): logic and
comparisons with three-valued NULL semantics, SQL truncating division
(call.py:120-144), CASE (147), CAST (183), IS [NOT] TRUE/FALSE/NULL/DISTINCT
(206-284), LIKE/SIMILAR-to-regex transpilation (287-385), POSITION/SUBSTRING/
TRIM/OVERLAY (388-473), EXTRACT's datetime fields (474-513), datetime-aware
CEIL/FLOOR (516), seeded RAND (558-639), plus the math/string function set.

Value model: every op takes a list of Column/Scalar args plus the
binder-inferred result type and returns Column or Scalar.  Numeric work runs
on device via jnp; string work runs on the (small) host dictionary with a
device gather to map results back to rows.
"""
from __future__ import annotations

import math
import re
from typing import Callable, List, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from ...ops.kernels import (
    US_PER_DAY, civil_from_days, days_from_civil, extract_field,
    timestamp_time_of_day_us, timestamp_to_days, trunc_date,
    unify_string_codes,
)
from ...table import Column, Scalar
from ...types import (
    BOOLEAN, DOUBLE, SqlType, VARCHAR, physical_dtype,
    python_value_to_physical,
)

Value = Union[Column, Scalar]


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

def is_string_value(v: Value) -> bool:
    return v.stype.is_string or (isinstance(v, Scalar) and isinstance(v.value, str))


def combine_masks(*vals: Value) -> Optional[jax.Array]:
    mask = None
    for v in vals:
        if isinstance(v, Column) and v.mask is not None:
            mask = v.mask if mask is None else (mask & v.mask)
    return mask


def all_null_column(length: int, stype: SqlType) -> Column:
    return Column.from_scalar(Scalar(None, stype), length)


def _data(v: Value):
    """jnp array or python scalar for numeric computation."""
    if isinstance(v, Column):
        return v.data
    return v.value


def _length(args: List[Value]) -> Optional[int]:
    for a in args:
        if isinstance(a, Column):
            return len(a)
    return None


def _any_null_scalar(args: List[Value]) -> bool:
    return any(isinstance(a, Scalar) and a.is_null for a in args)


def _decode_value(v: Value, n: int) -> np.ndarray:
    """Host object array of strings/None for any value."""
    if isinstance(v, Column):
        if v.stype.is_string:
            return v.decode()
        return v.to_numpy().astype(object)
    return np.array([v.value] * n, dtype=object)


def encode_strings(values: np.ndarray, mask: Optional[np.ndarray] = None) -> Column:
    return Column._encode_strings(values, mask)


# ---------------------------------------------------------------------------
# elementwise numeric ops
# ---------------------------------------------------------------------------

def numeric_op(fn: Callable, py_fn: Optional[Callable] = None,
               cast_to_result: bool = True):
    """Lift a jnp elementwise function into the Column/Scalar value model
    with NULL propagation."""

    def op(args: List[Value], stype: SqlType, ctx) -> Value:
        n = _length(args)
        if _any_null_scalar(args):
            if n is None:
                return Scalar(None, stype)
            return all_null_column(n, stype)
        if n is None:
            vals = [a.value for a in args]
            out = (py_fn or fn)(*vals)
            if stype.is_integer and out is not None and not isinstance(out, bool):
                out = int(out)
            return Scalar(out, stype)
        data = [_data(a) for a in args]
        out = fn(*data)
        if cast_to_result and not stype.is_string:
            out = out.astype(physical_dtype(stype))
        return Column(out, stype, combine_masks(*args))

    return op


def sql_div(a, b):
    """SQL division: truncates toward zero for integers (reference
    SQLDivisionOperator, call.py:120-144)."""
    if jnp.issubdtype(jnp.result_type(a, b), jnp.integer):
        q = jnp.floor_divide(jnp.abs(a), jnp.abs(b))
        return (jnp.sign(a) * jnp.sign(b) * q).astype(jnp.result_type(a, b))
    return a / b


def _py_div(a, b):
    if isinstance(a, int) and isinstance(b, int):
        return int(a / b) if b != 0 else None
    if b == 0:
        # match the COLUMN path's IEEE semantics (jnp a/0.0 -> ±inf, 0/0 ->
        # nan; the reference's pandas substrate does the same) instead of
        # raising ZeroDivisionError on the scalar-literal path
        with np.errstate(divide="ignore", invalid="ignore"):
            return float(np.float64(a) / np.float64(b))
    return a / b


# ---------------------------------------------------------------------------
# temporal arithmetic
# ---------------------------------------------------------------------------

def add_months(days: jax.Array, months) -> jax.Array:
    y, m, d = civil_from_days(days)
    total = (y * 12 + (m - 1)) + months
    ny = jnp.floor_divide(total, 12)
    nm = total - ny * 12 + 1
    # clamp day to month length
    nm_next = jnp.where(nm == 12, 1, nm + 1)
    ny_next = jnp.where(nm == 12, ny + 1, ny)
    month_len = days_from_civil(ny_next, nm_next, jnp.ones_like(d)) - days_from_civil(
        ny, nm, jnp.ones_like(d))
    nd = jnp.minimum(d, month_len)
    return days_from_civil(ny, nm, nd)


def temporal_plus_minus(sign: int):
    def op(args: List[Value], stype: SqlType, ctx) -> Value:
        a, b = args
        n = _length(args)
        if _any_null_scalar(args):
            return all_null_column(n, stype) if n is not None else Scalar(None, stype)
        at, bt = a.stype, b.stype
        mask = combine_masks(a, b)
        # temporal - temporal -> interval ms
        if at.is_temporal and bt.is_temporal:
            av = _to_us(a)
            bv = _to_us(b)
            out = (av - bv) // 1000
            return _wrap(out, stype, mask, n)
        if at.is_interval and bt.is_temporal:
            a, b = b, a
            at, bt = bt, at
        if at.is_temporal and bt.is_interval:
            if bt.name == "INTERVAL_YEAR_MONTH":
                months = _data(b) * sign
                if at.name == "DATE":
                    out = add_months(_as_array(_data(a), n), months)
                else:
                    us = _as_array(_data(a), n)
                    days = timestamp_to_days(us)
                    tod = timestamp_time_of_day_us(us)
                    out = add_months(days, months) * US_PER_DAY + tod
                return _wrap(out, stype, mask, n)
            ms = _data(b) * sign
            if at.name == "DATE" and stype.name == "DATE":
                out = _as_array(_data(a), n).astype(jnp.int64) + ms // 86_400_000
            elif at.name == "DATE":
                out = _as_array(_data(a), n).astype(jnp.int64) * US_PER_DAY + ms * 1000
            else:
                out = _as_array(_data(a), n) + ms * 1000
            return _wrap(out, stype, mask, n)
        if at.is_interval and bt.is_interval:
            out = _data(a) + sign * _data(b)
            return _wrap(out, stype, mask, n)
        # plain numeric
        out = _data(a) + sign * _data(b)
        return _wrap(out, stype, mask, n)

    return op


def _to_us(v: Value):
    if v.stype.name == "DATE":
        return _data(v) * US_PER_DAY if isinstance(v, Scalar) else v.data.astype(jnp.int64) * US_PER_DAY
    return _data(v)


def _as_array(x, n):
    if isinstance(x, jax.Array) and x.ndim > 0:
        return x
    return jnp.full(n or 1, x)


def _wrap(out, stype, mask, n) -> Value:
    if isinstance(out, jax.Array) and out.ndim > 0:
        return Column(out.astype(physical_dtype(stype)), stype, mask)
    return Scalar(python_value_to_physical(out, stype) if not isinstance(out, (int, float, bool)) else out, stype)


# ---------------------------------------------------------------------------
# comparisons (string-aware)
# ---------------------------------------------------------------------------

_CMP_FNS = {
    "=": (lambda a, b: a == b),
    "<>": (lambda a, b: a != b),
    "<": (lambda a, b: a < b),
    "<=": (lambda a, b: a <= b),
    ">": (lambda a, b: a > b),
    ">=": (lambda a, b: a >= b),
}


def comparison(op_name: str):
    fn = _CMP_FNS[op_name]

    def op(args: List[Value], stype: SqlType, ctx) -> Value:
        a, b = args
        n = _length(args)
        if _any_null_scalar(args):
            return all_null_column(n, BOOLEAN) if n is not None else Scalar(None, BOOLEAN)
        if is_string_value(a) or is_string_value(b):
            return _string_compare(fn, a, b, n)
        da, db = _data(a), _data(b)
        # temporal mixed units
        if a.stype.name == "DATE" and b.stype.name in ("TIMESTAMP", "TIMESTAMP_WITH_LOCAL_TIME_ZONE"):
            da = da * US_PER_DAY if not isinstance(da, jax.Array) else da.astype(jnp.int64) * US_PER_DAY
        if b.stype.name == "DATE" and a.stype.name in ("TIMESTAMP", "TIMESTAMP_WITH_LOCAL_TIME_ZONE"):
            db = db * US_PER_DAY if not isinstance(db, jax.Array) else db.astype(jnp.int64) * US_PER_DAY
        if n is None:
            return Scalar(bool(fn(da, db)), BOOLEAN)
        out = fn(da, db)
        return Column(out, BOOLEAN, combine_masks(a, b))

    return op


def _string_compare(fn, a: Value, b: Value, n: Optional[int]) -> Value:
    if n is None:
        return Scalar(bool(fn(a.value, b.value)), BOOLEAN)
    if isinstance(a, Column) and isinstance(b, Column) and a.stype.is_string and b.stype.is_string:
        ca, cb = unify_string_codes([a, b])
        return Column(fn(ca, cb), BOOLEAN, combine_masks(a, b))
    # column vs scalar
    if isinstance(a, Scalar):
        a, b = b, a
        flip = {jnp.less: jnp.greater}  # not used; use swapped comparison below
        # re-derive fn with swapped args
        fn_orig = fn
        fn = lambda x, y: fn_orig(y, x)  # noqa: E731
    col, scal = a, b
    if col.stype.is_string:
        d = col.dictionary.astype(str)
        per_dict = fn(d, str(scal.value))
        out = jnp.take(jnp.asarray(per_dict),
                       jnp.clip(col.data, 0, len(d) - 1))
        return Column(out, BOOLEAN, col.mask)
    # numeric column vs string scalar: cast scalar
    try:
        v = float(scal.value)
    except (TypeError, ValueError):
        return Column(jnp.zeros(len(col), bool), BOOLEAN, col.mask)
    return Column(fn(col.data, v), BOOLEAN, col.mask)


# ---------------------------------------------------------------------------
# boolean logic: three-valued AND/OR/NOT
# ---------------------------------------------------------------------------

def _to_bool_parts(v: Value, n: int):
    """Returns (value_array, known_array) for Kleene logic."""
    if isinstance(v, Scalar):
        if v.is_null:
            return jnp.zeros(n, bool), jnp.zeros(n, bool)
        return jnp.full(n, bool(v.value)), jnp.ones(n, bool)
    data = v.data.astype(bool)
    known = v.valid_mask()
    return data & known, known


def logical_and(args, stype, ctx):
    n = _length(args)
    if n is None:
        vals = [a.value for a in args]
        if any(v is False for v in vals):
            return Scalar(False, BOOLEAN)
        if any(v is None for v in vals):
            return Scalar(None, BOOLEAN)
        return Scalar(True, BOOLEAN)
    va, ka = _to_bool_parts(args[0], n)
    vb, kb = _to_bool_parts(args[1], n)
    out = va & vb
    # known if: both known, or either is a known False
    known = (ka & kb) | (ka & ~va) | (kb & ~vb)
    mask = known
    return Column(out, BOOLEAN, mask)


def logical_or(args, stype, ctx):
    n = _length(args)
    if n is None:
        vals = [a.value for a in args]
        if any(v is True for v in vals):
            return Scalar(True, BOOLEAN)
        if any(v is None for v in vals):
            return Scalar(None, BOOLEAN)
        return Scalar(False, BOOLEAN)
    va, ka = _to_bool_parts(args[0], n)
    vb, kb = _to_bool_parts(args[1], n)
    out = va | vb
    known = (ka & kb) | (ka & va) | (kb & vb)
    mask = known
    return Column(out, BOOLEAN, mask)


def logical_not(args, stype, ctx):
    (a,) = args
    if isinstance(a, Scalar):
        return Scalar(None if a.is_null else (not bool(a.value)), BOOLEAN)
    return Column(~a.data.astype(bool), BOOLEAN, a.mask)


# ---------------------------------------------------------------------------
# IS ... predicates (never null)
# ---------------------------------------------------------------------------

def is_null(args, stype, ctx):
    (a,) = args
    if isinstance(a, Scalar):
        return Scalar(a.is_null, BOOLEAN)
    return Column(~a.valid_mask(), BOOLEAN, None)


def is_not_null(args, stype, ctx):
    (a,) = args
    if isinstance(a, Scalar):
        return Scalar(not a.is_null, BOOLEAN)
    return Column(a.valid_mask(), BOOLEAN, None)


def _is_bool(value: bool, negated: bool):
    def op(args, stype, ctx):
        (a,) = args
        if isinstance(a, Scalar):
            r = (not a.is_null) and bool(a.value) == value
            return Scalar((not r) if negated else r, BOOLEAN)
        r = a.valid_mask() & (a.data.astype(bool) == value)
        if negated:
            r = ~r
        return Column(r, BOOLEAN, None)

    return op


def is_distinct_from(negated: bool):
    def op(args, stype, ctx):
        a, b = args
        n = _length(args)
        if n is None:
            an, bn = a.is_null, b.is_null
            if an or bn:
                distinct = an != bn
            else:
                distinct = a.value != b.value
            return Scalar((not distinct) if negated else distinct, BOOLEAN)
        eq = comparison("=")( [a, b], BOOLEAN, ctx)
        ev, ek = _to_bool_parts(eq if isinstance(eq, Column) else Column.from_scalar(eq, n), n)
        a_null = ~a.valid_mask() if isinstance(a, Column) else jnp.full(n, a.is_null)
        b_null = ~b.valid_mask() if isinstance(b, Column) else jnp.full(n, b.is_null)
        both_null = a_null & b_null
        either_null = a_null | b_null
        distinct = jnp.where(either_null, ~both_null, ~(ev & ek))
        if negated:
            distinct = ~distinct
        return Column(distinct, BOOLEAN, None)

    return op


# ---------------------------------------------------------------------------
# CASE / COALESCE / NULLIF / GREATEST / LEAST
# ---------------------------------------------------------------------------

def _cast_value_to(v: Value, stype: SqlType, n: Optional[int]) -> Value:
    from .cast import cast_value  # local import to avoid cycle
    return cast_value(v, stype, n)


def case_op(args: List[Value], stype: SqlType, ctx) -> Value:
    n = _length(args)
    *pairs, else_v = args
    if n is None:
        for i in range(0, len(pairs), 2):
            c = pairs[i]
            if not c.is_null and bool(c.value):
                return _cast_value_to(pairs[i + 1], stype, None)
        return _cast_value_to(else_v, stype, None)
    else_c = _as_col(_cast_value_to(else_v, stype, n), n, stype)
    out_data = else_c.data
    out_valid = else_c.valid_mask()
    taken = jnp.zeros(n, bool)
    for i in range(0, len(pairs), 2):
        cond = pairs[i]
        val = _as_col(_cast_value_to(pairs[i + 1], stype, n), n, stype)
        cv, ck = _to_bool_parts(cond if isinstance(cond, Column) else Column.from_scalar(cond, n), n)
        sel = cv & ck & ~taken
        out_data = jnp.where(sel, val.data, out_data)
        out_valid = jnp.where(sel, val.valid_mask(), out_valid)
        taken = taken | sel
    mask = out_valid
    dictionary = else_c.dictionary
    if stype.is_string:
        # string CASE: fall back to host path for dictionary merge
        vals = np.where(np.asarray(taken), "", "")  # placeholder
        return _string_case(pairs, else_v, n, stype)
    return Column(out_data, stype, mask)


def _string_case(pairs, else_v, n, stype):
    sel_done = np.zeros(n, bool)
    out = np.array([None] * n, dtype=object)
    for i in range(0, len(pairs), 2):
        cond, val = pairs[i], pairs[i + 1]
        cv, ck = _to_bool_parts(cond if isinstance(cond, Column) else Column.from_scalar(cond, n), n)
        sel = np.asarray(cv & ck) & ~sel_done
        vals = _decode_value(val, n)
        out[sel] = vals[sel]
        sel_done |= sel
    ev = _decode_value(else_v, n)
    out[~sel_done] = ev[~sel_done]
    mask = np.array([o is not None for o in out])
    return encode_strings(np.where(mask, out, ""), mask if not mask.all() else None)


def coalesce_op(args: List[Value], stype: SqlType, ctx) -> Value:
    n = _length(args)
    if n is None:
        for a in args:
            if not a.is_null:
                return _cast_value_to(a, stype, None)
        return Scalar(None, stype)
    if stype.is_string:
        out = np.array([None] * n, dtype=object)
        filled = np.zeros(n, bool)
        for a in args:
            vals = _decode_value(a, n)
            avail = np.array([v is not None for v in vals]) & ~filled
            out[avail] = vals[avail]
            filled |= avail
        mask = filled
        return encode_strings(np.where(mask, out, ""), mask if not mask.all() else None)
    cols = [_as_col(_cast_value_to(a, stype, n), n, stype) for a in args]
    out = cols[0].data
    valid = cols[0].valid_mask()
    for c in cols[1:]:
        out = jnp.where(valid, out, c.data)
        valid = valid | c.valid_mask()
    return Column(out, stype, valid)


def nullif_op(args, stype, ctx):
    a, b = args
    n = _length(args)
    eq = comparison("=")([a, b], BOOLEAN, ctx)
    if n is None:
        if not eq.is_null and eq.value:
            return Scalar(None, stype)
        return a
    ac = _as_col(a, n, stype)
    ev, ek = _to_bool_parts(eq if isinstance(eq, Column) else Column.from_scalar(eq, n), n)
    new_mask = ac.valid_mask() & ~(ev & ek)
    return ac.with_mask(new_mask)


def greatest_least(is_greatest: bool):
    def op(args, stype, ctx):
        n = _length(args)
        # SQL GREATEST returns NULL if any argument is NULL (Calcite) — but
        # postgres skips nulls; Calcite semantics: null if any null.
        if n is None:
            vals = [a.value for a in args]
            if any(v is None for v in vals):
                return Scalar(None, stype)
            return Scalar(max(vals) if is_greatest else min(vals), stype)
        cols = [_as_col(_cast_value_to(a, stype, n), n, stype) for a in args]
        out = cols[0].data
        for c in cols[1:]:
            out = jnp.maximum(out, c.data) if is_greatest else jnp.minimum(out, c.data)
        return Column(out, stype, combine_masks(*cols))

    return op


def _as_col(v: Value, n: int, stype: SqlType = None) -> Column:
    if isinstance(v, Column):
        return v
    return Column.from_scalar(v, n)


# ---------------------------------------------------------------------------
# IN list
# ---------------------------------------------------------------------------

def in_list_op(args: List[Value], stype: SqlType, ctx) -> Value:
    expr, *values = args
    n = _length([expr])
    out = None
    for v in values:
        eq = comparison("=")([expr, v], BOOLEAN, ctx)
        out = eq if out is None else logical_or([out, eq], BOOLEAN, ctx)
    if out is None:
        return Scalar(False, BOOLEAN)
    return out


# ---------------------------------------------------------------------------
# LIKE / SIMILAR / regex  (reference transpiler: call.py:287-385)
# ---------------------------------------------------------------------------

def sql_like_to_regex(pattern: str, escape: Optional[str] = None) -> str:
    out = []
    i = 0
    esc = escape
    while i < len(pattern):
        c = pattern[i]
        if esc and c == esc and i + 1 < len(pattern):
            out.append(re.escape(pattern[i + 1]))
            i += 2
            continue
        if c == "%":
            out.append(".*")
        elif c == "_":
            out.append(".")
        else:
            out.append(re.escape(c))
        i += 1
    return "^" + "".join(out) + "$"


def sql_similar_to_regex(pattern: str, escape: Optional[str] = None) -> str:
    """SIMILAR TO: SQL regex flavor — % and _ wildcards plus POSIX-ish groups."""
    out = []
    i = 0
    while i < len(pattern):
        c = pattern[i]
        if escape and c == escape and i + 1 < len(pattern):
            out.append(re.escape(pattern[i + 1]))
            i += 2
            continue
        if c == "%":
            out.append(".*")
        elif c == "_":
            out.append(".")
        else:
            out.append(c)  # pass through regex metacharacters
        i += 1
    return "^" + "".join(out) + "$"


def like_op(kind: str):
    def op(args: List[Value], stype: SqlType, ctx) -> Value:
        expr, pattern, *rest = args
        escape = rest[0].value if rest else None
        if isinstance(pattern, Column):
            # per-row patterns: host path
            n = len(pattern)
            vals = _decode_value(expr, n)
            pats = _decode_value(pattern, n)
            out = np.zeros(n, bool)
            mask = np.ones(n, bool)
            for i, (v, p) in enumerate(zip(vals, pats)):
                if v is None or p is None:
                    mask[i] = False
                    continue
                rx = sql_like_to_regex(p, escape) if kind != "SIMILAR" else sql_similar_to_regex(p, escape)
                flags = re.IGNORECASE if kind == "ILIKE" else 0
                out[i] = re.match(rx, str(v), flags) is not None
            return Column(jnp.asarray(out), BOOLEAN,
                          None if mask.all() else jnp.asarray(mask))
        if pattern.is_null or (isinstance(expr, Scalar) and expr.is_null):
            n = _length(args)
            return all_null_column(n, BOOLEAN) if n is not None else Scalar(None, BOOLEAN)
        pat = str(pattern.value)

        def _regex_bitmap(d):
            rx = (sql_similar_to_regex(pat, escape) if kind == "SIMILAR"
                  else sql_like_to_regex(pat, escape))
            flags = re.IGNORECASE if kind == "ILIKE" else 0
            compiled = re.compile(rx, flags)
            return np.array([compiled.match(s) is not None for s in d])

        if isinstance(expr, Scalar):
            return Scalar(bool(_regex_bitmap([str(expr.value)])[0]), BOOLEAN)
        from ...ops.strings_fast import (DEVICE_STRING_THRESHOLD,
                                         device_like_bitmap, dict_as_str,
                                         like_bitmap_vectorized)
        if expr.stype.is_string:
            dct = expr.dictionary
            if len(dct) >= DEVICE_STRING_THRESHOLD:
                # past the dictionary cliff: chunk matching runs on device
                # over the memoized bytes matrix.  Under the whole-plan
                # tracer this executes EAGERLY (dct is concrete) and the
                # resulting D-bool bitmap bakes into the program as a
                # constant — sound because the program cache is keyed on
                # dictionary content, and D bools are tiny next to the
                # bytes matrix itself
                per_dev = device_like_bitmap(dct, pat, escape, kind)
                if per_dev is not None:
                    from ...ops import strings_fast as _sf
                    _sf.stats["device_bitmaps"] += 1
                    out = jnp.take(per_dev,
                                   jnp.clip(expr.data, 0, len(dct) - 1))
                    return Column(out, BOOLEAN, expr.mask)
            d = dict_as_str(dct)
            per = like_bitmap_vectorized(d, pat, escape, kind)
            if per is None:
                per = _regex_bitmap(d)
            out = jnp.take(jnp.asarray(per), jnp.clip(expr.data, 0, len(d) - 1))
            return Column(out, BOOLEAN, expr.mask)
        d = expr.to_numpy().astype(str)
        per = like_bitmap_vectorized(d, pat, escape, kind)
        if per is None:
            per = _regex_bitmap(d)
        return Column(jnp.asarray(per), BOOLEAN, expr.mask)

    return op


# ---------------------------------------------------------------------------
# string functions (dictionary-path)
# ---------------------------------------------------------------------------

def map_dictionary(col: Column, fn: Callable[[np.ndarray], np.ndarray],
                   stype: SqlType) -> Column:
    """Apply fn over the dictionary, map back to rows via device gather."""
    d = col.dictionary.astype(str)
    res = fn(d)
    if stype.is_string:
        res = np.asarray(res, dtype=object)
        newdict, newcodes = np.unique(res.astype(str), return_inverse=True)
        codes = jnp.take(jnp.asarray(newcodes.astype(np.int32)),
                         jnp.clip(col.data, 0, len(d) - 1))
        return Column(codes, VARCHAR, col.mask, newdict.astype(object))
    arr = np.asarray(res)
    out = jnp.take(jnp.asarray(arr.astype(physical_dtype(stype))),
                   jnp.clip(col.data, 0, len(d) - 1))
    return Column(out, stype, col.mask)


def string_unary(fn_one: Callable[[str], object]):
    """Lift a python str->value function into the value model."""

    def op(args: List[Value], stype: SqlType, ctx) -> Value:
        (a,) = args
        if isinstance(a, Scalar):
            if a.is_null:
                return Scalar(None, stype)
            return Scalar(fn_one(str(a.value)), stype)
        return map_dictionary(a, lambda d: np.array([fn_one(s) for s in d], dtype=object),
                              stype)

    return op


def string_nary(fn_row: Callable[..., object]):
    """N-ary string function; scalar extra args ride along; any column
    combination falls back to the host path (rare)."""

    def op(args: List[Value], stype: SqlType, ctx) -> Value:
        n = _length(args)
        if _any_null_scalar(args):
            return all_null_column(n, stype) if n is not None else Scalar(None, stype)
        if n is None:
            return Scalar(fn_row(*[a.value for a in args]), stype)
        str_cols = [a for a in args if isinstance(a, Column) and a.stype.is_string]
        non_str_cols = [a for a in args if isinstance(a, Column) and not a.stype.is_string]
        if len(str_cols) == 1 and not non_str_cols:
            col = str_cols[0]
            fixed = [a.value if isinstance(a, Scalar) else None for a in args]
            pos = [i for i, a in enumerate(args) if isinstance(a, Column)][0]

            def apply_dict(d):
                out = []
                for s in d:
                    row = list(fixed)
                    row[pos] = s
                    out.append(fn_row(*row))
                return np.array(out, dtype=object)

            return map_dictionary(col, apply_dict, stype)
        # general host path
        host = [_decode_value(a, n) for a in args]
        out = []
        mask = np.ones(n, bool)
        for i in range(n):
            row = [h[i] for h in host]
            if any(v is None for v in row):
                mask[i] = False
                out.append(None)
            else:
                out.append(fn_row(*row))
        if stype.is_string:
            return encode_strings(
                np.array([o if o is not None else "" for o in out], dtype=object),
                mask if not mask.all() else None)
        arr = np.array([o if o is not None else 0 for o in out])
        return Column(jnp.asarray(arr.astype(physical_dtype(stype))), stype,
                      None if mask.all() else jnp.asarray(mask))

    return op


def _substring(s, start, length=None):
    start = int(start)
    begin = max(start - 1, 0) if start > 0 else max(len(s) + start, 0) if start < 0 else 0
    if start <= 0:
        # SQL: position counts from 1; nonpositive start shifts window
        begin = 0
        if length is not None:
            length = length + (start - 1)
            if length <= 0:
                return ""
    if length is None:
        return s[begin:]
    return s[begin : begin + max(int(length), 0)]


def _trim(side, chars, s):
    chars = chars or " "
    if side == "LEADING":
        return s.lstrip(chars)
    if side == "TRAILING":
        return s.rstrip(chars)
    return s.strip(chars)


def _overlay(s, repl, start, length=None):
    start = int(start)
    if length is None:
        length = len(repl)
    return s[: start - 1] + repl + s[start - 1 + int(length):]


def _split_part(s, delim, idx):
    parts = s.split(delim)
    i = int(idx)
    if 1 <= i <= len(parts):
        return parts[i - 1]
    return ""


def concat_op(args: List[Value], stype: SqlType, ctx) -> Value:
    # || : NULL-propagating two-arg concat; CONCAT() ignores nulls in some
    # dialects but Calcite CONCAT propagates — keep propagation.
    def fn(*vals):
        return "".join(str(v) for v in vals)
    return string_nary(fn)(args, stype, ctx)


# ---------------------------------------------------------------------------
# EXTRACT / datetime ops
# ---------------------------------------------------------------------------

def extract_op(args: List[Value], stype: SqlType, ctx) -> Value:
    field_v, src = args
    field = str(field_v.value)
    n = _length([src])
    if isinstance(src, Scalar):
        if src.is_null:
            return Scalar(None, stype)
        arr = jnp.asarray([src.value])
        col = Column(arr, src.stype)
        res = extract_op([field_v, col], stype, ctx)
        return Scalar(int(np.asarray(res.data)[0]), stype)
    if src.stype.name == "DATE":
        days = src.data.astype(jnp.int64)
        tod = None
    elif src.stype.is_temporal:
        days = timestamp_to_days(src.data)
        tod = timestamp_time_of_day_us(src.data)
    elif src.stype.is_interval:
        ms = src.data
        out = {"DAY": ms // 86_400_000, "HOUR": (ms // 3_600_000) % 24,
               "MINUTE": (ms // 60_000) % 60, "SECOND": (ms // 1000) % 60,
               "EPOCH": ms // 1000}.get(field.upper())
        if out is None:
            raise NotImplementedError(f"EXTRACT {field} from interval")
        return Column(out.astype(jnp.int64), stype, src.mask)
    else:
        raise TypeError(f"EXTRACT from {src.stype}")
    out = extract_field(field, days, tod)
    return Column(out.astype(jnp.int64), stype, src.mask)


def floor_ceil_op(is_floor: bool):
    def op(args: List[Value], stype: SqlType, ctx) -> Value:
        if len(args) == 2 and isinstance(args[1], Scalar) and args[1].stype.name == "SYMBOL":
            src, unit = args[0], str(args[1].value)
            n = _length([src])
            if isinstance(src, Scalar):
                if src.is_null:
                    return Scalar(None, stype)
                col = Column(jnp.asarray([src.value]), src.stype)
                r = op([col, args[1]], stype, ctx)
                return Scalar(int(np.asarray(r.data)[0]), stype)
            if src.stype.name == "DATE":
                days, _ = trunc_date(unit, src.data.astype(jnp.int64), None)
                out = days
                if not is_floor:
                    out = _ceil_date(unit, src.data.astype(jnp.int64), days, None, None)
                return Column(out.astype(physical_dtype(stype)), stype, src.mask)
            days = timestamp_to_days(src.data)
            tod = timestamp_time_of_day_us(src.data)
            fdays, ftod = trunc_date(unit, days, tod)
            floored = fdays * US_PER_DAY + (ftod if ftod is not None else 0)
            if is_floor:
                return Column(floored.astype(jnp.int64), stype, src.mask)
            out = _ceil_date(unit, days, fdays, tod, floored)
            return Column(out.astype(jnp.int64), stype, src.mask)
        (a,) = args[:1]
        fn = jnp.floor if is_floor else jnp.ceil
        pyfn = math.floor if is_floor else math.ceil
        return numeric_op(fn, pyfn)([a], stype, ctx)

    return op


def _ceil_date(unit, days, floored_days, tod, floored_us):
    """CEIL(ts TO unit) = floor(ts) if already aligned else floor + 1 unit."""
    u = unit.upper()
    if floored_us is None:
        aligned = days == floored_days
        if u == "YEAR":
            y, m, d = civil_from_days(days)
            return jnp.where(aligned, days, days_from_civil(y + 1, jnp.ones_like(m), jnp.ones_like(d)))
        if u == "MONTH":
            return jnp.where(aligned, days, add_months(floored_days, 1))
        if u == "WEEK":
            return jnp.where(aligned, days, floored_days + 7)
        return days
    orig = days * US_PER_DAY + tod
    aligned = orig == floored_us
    if u == "YEAR":
        y, m, d = civil_from_days(days)
        nxt = days_from_civil(y + 1, jnp.ones_like(m), jnp.ones_like(d)) * US_PER_DAY
        return jnp.where(aligned, orig, nxt)
    if u == "MONTH":
        nxt = add_months(timestamp_to_days(floored_us), 1) * US_PER_DAY
        return jnp.where(aligned, orig, nxt)
    step = {"DAY": US_PER_DAY, "HOUR": 3_600_000_000, "MINUTE": 60_000_000,
            "SECOND": 1_000_000, "WEEK": 7 * US_PER_DAY}.get(u)
    if step is None:
        raise NotImplementedError(f"CEIL unit {unit}")
    return jnp.where(aligned, orig, floored_us + step)


# ---------------------------------------------------------------------------
# random (seeded, reference call.py:558-639)
# ---------------------------------------------------------------------------

def rand_op(args: List[Value], stype: SqlType, ctx) -> Value:
    seed = int(args[0].value) if args else np.random.randint(0, 2**31)
    key = jax.random.PRNGKey(seed)
    out = jax.random.uniform(key, (ctx.num_rows,), dtype=jnp.float64)
    return Column(out, DOUBLE, None)


def rand_integer_op(args: List[Value], stype: SqlType, ctx) -> Value:
    if len(args) == 2:
        seed = int(args[0].value)
        bound = int(args[1].value)
    else:
        seed = np.random.randint(0, 2**31)
        bound = int(args[0].value)
    key = jax.random.PRNGKey(seed)
    out = jax.random.randint(key, (ctx.num_rows,), 0, bound)
    return Column(out.astype(jnp.int32), stype, None)


# ---------------------------------------------------------------------------
# CAST — see cast.py; registered in the mapping there to avoid cycles
# ---------------------------------------------------------------------------

def _search_op(args, stype, ctx):
    """SEARCH(x, Sarg): range-set membership — produced by our optimizer for
    range predicates (Calcite Sarg equivalent, reference literal.py:12-71)."""
    expr, ranges = args
    # ranges is a Scalar holding a list of (lo, lo_open, hi, hi_open) tuples
    out = None
    for lo, lo_open, hi, hi_open in ranges.value:
        conds = []
        if lo is not None:
            conds.append(comparison(">" if lo_open else ">=")(
                [expr, Scalar(lo, expr.stype)], BOOLEAN, ctx))
        if hi is not None:
            conds.append(comparison("<" if hi_open else "<=")(
                [expr, Scalar(hi, expr.stype)], BOOLEAN, ctx))
        if not conds:
            piece = Scalar(True, BOOLEAN)
        else:
            piece = conds[0]
            for c in conds[1:]:
                piece = logical_and([piece, c], BOOLEAN, ctx)
        out = piece if out is None else logical_or([out, piece], BOOLEAN, ctx)
    return out if out is not None else Scalar(False, BOOLEAN)


# ===========================================================================
# THE MAPPING  (reference: RexCallPlugin.OPERATION_MAPPING call.py:685-762)
# ===========================================================================

OPERATION_MAPPING = {
    # logic
    "AND": logical_and,
    "OR": logical_or,
    "NOT": logical_not,
    # comparison
    "=": comparison("="),
    "<>": comparison("<>"),
    "<": comparison("<"),
    "<=": comparison("<="),
    ">": comparison(">"),
    ">=": comparison(">="),
    # arithmetic
    "+": temporal_plus_minus(+1),
    "-": temporal_plus_minus(-1),
    "*": numeric_op(lambda a, b: a * b, lambda a, b: a * b),
    "/": numeric_op(sql_div, _py_div),
    "%": numeric_op(lambda a, b: jnp.sign(a) * (jnp.abs(a) % jnp.abs(b)),
                    lambda a, b: math.copysign(abs(a) % abs(b), a)),
    "MOD": numeric_op(lambda a, b: jnp.sign(a) * (jnp.abs(a) % jnp.abs(b)),
                      lambda a, b: math.copysign(abs(a) % abs(b), a)),
    "NEGATE": numeric_op(lambda a: -a, lambda a: -a),
    # is-ness
    "IS_NULL": is_null,
    "IS_NOT_NULL": is_not_null,
    "IS_TRUE": _is_bool(True, False),
    "IS_NOT_TRUE": _is_bool(True, True),
    "IS_FALSE": _is_bool(False, False),
    "IS_NOT_FALSE": _is_bool(False, True),
    "IS_DISTINCT_FROM": is_distinct_from(False),
    "IS_NOT_DISTINCT_FROM": is_distinct_from(True),
    # conditional
    "CASE": case_op,
    "COALESCE": coalesce_op,
    "IFNULL": coalesce_op,
    "NVL": coalesce_op,
    "NULLIF": nullif_op,
    "GREATEST": greatest_least(True),
    "LEAST": greatest_least(False),
    "IN_LIST": in_list_op,
    "SEARCH": _search_op,
    # pattern matching
    "LIKE": like_op("LIKE"),
    "ILIKE": like_op("ILIKE"),
    "SIMILAR": like_op("SIMILAR"),
    # math
    "ABS": numeric_op(jnp.abs, abs),
    "SQRT": numeric_op(jnp.sqrt, math.sqrt),
    "EXP": numeric_op(jnp.exp, math.exp),
    "LN": numeric_op(jnp.log, math.log),
    "LOG10": numeric_op(jnp.log10, math.log10),
    "LOG": numeric_op(lambda a, b=None: jnp.log(a) if b is None else jnp.log(b) / jnp.log(a),
                      lambda a, b=None: math.log(a) if b is None else math.log(b, a)),
    "POWER": numeric_op(jnp.power, math.pow),
    "POW": numeric_op(jnp.power, math.pow),
    "SIN": numeric_op(jnp.sin, math.sin),
    "COS": numeric_op(jnp.cos, math.cos),
    "TAN": numeric_op(jnp.tan, math.tan),
    "ASIN": numeric_op(jnp.arcsin, math.asin),
    "ACOS": numeric_op(jnp.arccos, math.acos),
    "ATAN": numeric_op(jnp.arctan, math.atan),
    "ATAN2": numeric_op(jnp.arctan2, math.atan2),
    "SINH": numeric_op(jnp.sinh, math.sinh),
    "COSH": numeric_op(jnp.cosh, math.cosh),
    "TANH": numeric_op(jnp.tanh, math.tanh),
    "COT": numeric_op(lambda a: 1.0 / jnp.tan(a), lambda a: 1.0 / math.tan(a)),
    "DEGREES": numeric_op(jnp.degrees, math.degrees),
    "RADIANS": numeric_op(jnp.radians, math.radians),
    "SIGN": numeric_op(jnp.sign, lambda a: (a > 0) - (a < 0)),
    "CBRT": numeric_op(jnp.cbrt, lambda a: a ** (1.0 / 3.0)),
    "ROUND": numeric_op(
        lambda a, d=None: jnp.round(a) if d is None else jnp.round(a * (10.0 ** d)) / (10.0 ** d),
        lambda a, d=None: round(a) if d is None else round(a, int(d))),
    "TRUNCATE": numeric_op(
        lambda a, d=None: jnp.trunc(a) if d is None else jnp.trunc(a * (10.0 ** d)) / (10.0 ** d),
        lambda a, d=None: math.trunc(a) if d is None else math.trunc(a * 10 ** d) / 10 ** d),
    "PI": lambda args, stype, ctx: Scalar(math.pi, DOUBLE),
    "FLOOR": floor_ceil_op(True),
    "CEIL": floor_ceil_op(False),
    "CEILING": floor_ceil_op(False),
    "RAND": rand_op,
    "RANDOM": rand_op,
    "RAND_INTEGER": rand_integer_op,
    # strings
    "||": concat_op,
    "CONCAT": concat_op,
    "UPPER": string_unary(str.upper),
    "LOWER": string_unary(str.lower),
    "INITCAP": string_unary(lambda s: re.sub(r"[a-zA-Z]+", lambda m: m.group(0).capitalize(), s)),
    "REVERSE": string_unary(lambda s: s[::-1]),
    "CHAR_LENGTH": string_unary(len),
    "CHARACTER_LENGTH": string_unary(len),
    "LENGTH": string_unary(len),
    "OCTET_LENGTH": string_unary(lambda s: len(s.encode())),
    "ASCII": string_unary(lambda s: ord(s[0]) if s else 0),
    "CHR": numeric_op(None, None) if False else string_nary(lambda c: chr(int(c))),
    "SUBSTRING": string_nary(_substring),
    "SUBSTR": string_nary(_substring),
    "TRIM": string_nary(_trim),
    "LTRIM": string_nary(lambda s, c=" ": s.lstrip(c)),
    "RTRIM": string_nary(lambda s, c=" ": s.rstrip(c)),
    "BTRIM": string_nary(lambda s, c=" ": s.strip(c)),
    "POSITION": string_nary(lambda needle, hay: hay.find(needle) + 1),
    "STRPOS": string_nary(lambda hay, needle: hay.find(needle) + 1),
    "OVERLAY": string_nary(_overlay),
    "REPLACE": string_nary(lambda s, old, new: s.replace(old, new)),
    "REPEAT": string_nary(lambda s, n_: s * int(n_)),
    "LEFT": string_nary(lambda s, n_: s[: int(n_)] if n_ >= 0 else s[: max(len(s) + int(n_), 0)]),
    "RIGHT": string_nary(lambda s, n_: s[-int(n_):] if n_ > 0 else (s[-(len(s) + int(n_)):] if len(s) + int(n_) > 0 else "")),
    "LPAD": string_nary(lambda s, n_, p=" ": s[: int(n_)] if len(s) >= int(n_) else (p * int(n_))[: int(n_) - len(s)] + s),
    "RPAD": string_nary(lambda s, n_, p=" ": s[: int(n_)] if len(s) >= int(n_) else s + (p * int(n_))[: int(n_) - len(s)]),
    "SPLIT_PART": string_nary(_split_part),
    "TRANSLATE": string_nary(lambda s, frm, to: s.translate(str.maketrans(frm, to[: len(frm)].ljust(len(frm))))),
    "REGEXP_REPLACE": string_nary(lambda s, p, r: re.sub(p, r, s)),
    # datetime
    "EXTRACT": extract_op,
    "YEAR": lambda args, stype, ctx: extract_op([Scalar("YEAR", SqlType("SYMBOL")), args[0]], stype, ctx),
    "MONTH": lambda args, stype, ctx: extract_op([Scalar("MONTH", SqlType("SYMBOL")), args[0]], stype, ctx),
    "DAY": lambda args, stype, ctx: extract_op([Scalar("DAY", SqlType("SYMBOL")), args[0]], stype, ctx),
    "HOUR": lambda args, stype, ctx: extract_op([Scalar("HOUR", SqlType("SYMBOL")), args[0]], stype, ctx),
    "MINUTE": lambda args, stype, ctx: extract_op([Scalar("MINUTE", SqlType("SYMBOL")), args[0]], stype, ctx),
    "SECOND": lambda args, stype, ctx: extract_op([Scalar("SECOND", SqlType("SYMBOL")), args[0]], stype, ctx),
    "QUARTER": lambda args, stype, ctx: extract_op([Scalar("QUARTER", SqlType("SYMBOL")), args[0]], stype, ctx),
    "DAYOFWEEK": lambda args, stype, ctx: extract_op([Scalar("DOW", SqlType("SYMBOL")), args[0]], stype, ctx),
    "DAYOFMONTH": lambda args, stype, ctx: extract_op([Scalar("DAY", SqlType("SYMBOL")), args[0]], stype, ctx),
    "DAYOFYEAR": lambda args, stype, ctx: extract_op([Scalar("DOY", SqlType("SYMBOL")), args[0]], stype, ctx),
    "WEEK": lambda args, stype, ctx: extract_op([Scalar("WEEK", SqlType("SYMBOL")), args[0]], stype, ctx),
}
