"""CAST between logical types (reference: CastOperation call.py:183-204 and
the dissimilar-type cast suppression in mappings.py:218-257)."""
from __future__ import annotations

import datetime
from typing import Optional, Union

import jax.numpy as jnp
import numpy as np

from ...ops.kernels import US_PER_DAY, timestamp_to_days
from ...table import Column, Scalar
from ...types import SqlType, physical_dtype, python_value_to_physical

Value = Union[Column, Scalar]


def cast_value(v: Value, target: SqlType, n: Optional[int] = None) -> Value:
    if isinstance(v, Scalar):
        return _cast_scalar(v, target)
    return cast_column(v, target)


def _cast_scalar(v: Scalar, target: SqlType) -> Scalar:
    if v.is_null:
        return Scalar(None, target)
    sv = v.value
    sn, tn = v.stype.name, target.name
    if sn == tn:
        return Scalar(sv, target)
    if v.stype.is_string:
        return Scalar(_parse_string_scalar(str(sv), target), target)
    if target.is_string:
        return Scalar(_format_value(sv, v.stype), target)
    if tn == "DATE" and sn in ("TIMESTAMP", "TIMESTAMP_WITH_LOCAL_TIME_ZONE"):
        return Scalar(int(sv) // US_PER_DAY, target)
    if sn == "DATE" and tn in ("TIMESTAMP", "TIMESTAMP_WITH_LOCAL_TIME_ZONE"):
        return Scalar(int(sv) * US_PER_DAY, target)
    if target.name == "BOOLEAN":
        return Scalar(bool(sv), target)
    if target.is_integer:
        return Scalar(int(sv), target)
    if target.is_floating:
        return Scalar(float(sv), target)
    return Scalar(python_value_to_physical(sv, target), target)


def _parse_string_scalar(s: str, target: SqlType):
    tn = target.name
    if target.is_string:
        return s
    if tn == "BOOLEAN":
        return s.strip().lower() in ("t", "true", "1", "yes", "y")
    if target.is_integer:
        return int(float(s))
    if target.is_floating:
        return float(s)
    return python_value_to_physical(s.strip(), target)


def _format_value(v, stype: SqlType) -> str:
    from ...types import physical_to_python_value

    py = physical_to_python_value(v, stype)
    if isinstance(py, bool):
        return "true" if py else "false"
    if isinstance(py, float) and py == int(py) and abs(py) < 1e15:
        # SQL renders exact floats plainly
        return repr(py)
    if isinstance(py, datetime.datetime):
        return py.isoformat(sep=" ")
    return str(py)


def cast_column(col: Column, target: SqlType) -> Column:
    sn, tn = col.stype.name, target.name
    if tn == "DECIMAL" and col.stype.is_numeric and target.scale is not None \
            and 0 <= target.scale <= 9 and not (
                sn == "DECIMAL" and col.stype.scale == target.scale):
        # CAST to DECIMAL(p, s) QUANTIZES (rounds to s decimals) so the
        # scaled-int64 exact-aggregation contract holds on the values.
        # Rounding is jnp.round = half-even over the f64 representation —
        # the reference's pandas substrate behaves identically (and our
        # ROUND op matches); a true decimal engine's half-up can differ by
        # one unit in the last place on exact halves.
        f = 10.0 ** target.scale
        data = jnp.round(col.data.astype(jnp.float64) * f) / f
        return Column(data, target, col.mask)
    if sn == tn or (col.stype.is_string and target.is_string):
        return Column(col.data, target, col.mask, col.dictionary)
    if col.stype.is_string:
        return _cast_string_column(col, target)
    if target.is_string:
        vals = np.asarray(col.to_numpy())
        strs = np.array(
            [None if _is_na(x) else _format_value(python_value_to_physical(x, col.stype), col.stype)
             for x in vals.tolist()],
            dtype=object,
        )
        return Column._encode_strings(strs, None)
    if sn == "DATE" and tn in ("TIMESTAMP", "TIMESTAMP_WITH_LOCAL_TIME_ZONE"):
        return Column(col.data.astype(jnp.int64) * US_PER_DAY, target, col.mask)
    if sn in ("TIMESTAMP", "TIMESTAMP_WITH_LOCAL_TIME_ZONE") and tn == "DATE":
        return Column(timestamp_to_days(col.data).astype(jnp.int32), target, col.mask)
    if target.name == "BOOLEAN":
        return Column(col.data != 0, target, col.mask)
    dtype = physical_dtype(target)
    data = col.data
    if target.is_integer and data.dtype.kind == "f":
        # float->int truncation parity with the reference (mappings.py:291-297)
        data = jnp.trunc(jnp.where(jnp.isnan(data), 0.0, data))
    return Column(data.astype(dtype), target, col.mask)


def _cast_string_column(col: Column, target: SqlType) -> Column:
    d = col.dictionary.astype(str)
    parsed = []
    bad = np.zeros(len(d), bool)
    for i, s in enumerate(d):
        try:
            parsed.append(_parse_string_scalar(s, target))
        except (ValueError, TypeError):
            parsed.append(0)
            bad[i] = True
    arr = np.asarray(parsed, dtype=physical_dtype(target))
    data = jnp.take(jnp.asarray(arr), jnp.clip(col.data, 0, len(d) - 1))
    mask = col.mask
    if bad.any():
        okay = jnp.take(jnp.asarray(~bad), jnp.clip(col.data, 0, len(d) - 1))
        mask = okay if mask is None else (mask & okay)
    return Column(data, target, mask)


def _is_na(x) -> bool:
    if x is None:
        return True
    if isinstance(x, float) and np.isnan(x):
        return True
    if isinstance(x, np.datetime64) and np.isnat(x):
        return True
    return False
