"""Device mesh helpers: the SPMD substrate replacing dask.distributed.

The reference scales by partitioned dataframes on a dynamic task scheduler
(SURVEY §2.3); here tables shard row-wise over a 1-D ``jax.sharding.Mesh``
axis ("data" — the SQL analogue of data parallelism), and per-query-stage
compiled SPMD programs use XLA collectives over ICI instead of task shuffles:
``all_to_all`` for hash exchange (join/groupby/sort), ``psum``/``all_gather``
for aggregations and small build-side broadcasts, ``ppermute`` for
sort/window boundary exchange.  Multi-host attaches via
``jax.distributed.initialize`` + the same mesh spanning hosts (DCN).
"""
from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

ROW_AXIS = "data"


def default_mesh(n_devices: Optional[int] = None) -> Mesh:
    """1-D row mesh over the first n devices (all by default)."""
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (ROW_AXIS,))


def row_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P(ROW_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def pad_to_multiple(n: int, k: int) -> int:
    return ((n + k - 1) // k) * k


def shard_table_with_validity(table, mesh: Mesh):
    """Mesh-mode catalog placement: pad rows to device-count divisibility,
    row-shard every column, and return a row-validity mask (same sharding)
    marking the real rows. Column NULL masks are untouched — padding
    visibility is a TABLE property (COUNT(*) must not see pad rows), which
    the compiled executor's validity-mask pipeline consumes directly
    (physical/compiled.py _VT)."""
    import jax.numpy as jnp

    from ..table import Column, Table

    n = table.num_rows
    k = mesh.devices.size
    padded = pad_to_multiple(max(n, 1), k)
    sh = row_sharding(mesh)
    pad = padded - n
    cols = []
    for c in table.columns:
        data = c.data
        mask = c.mask
        if pad:
            data = jnp.concatenate([data, jnp.zeros(pad, dtype=data.dtype)])
            if mask is not None:
                mask = jnp.concatenate([mask, jnp.zeros(pad, dtype=bool)])
        data = jax.device_put(data, sh)
        if mask is not None:
            mask = jax.device_put(mask, sh)
        cols.append(Column(data, c.stype, mask, c.dictionary))
    row_valid = jax.device_put(
        jnp.arange(padded) < n, sh) if pad else None
    return Table(list(table.names), cols), row_valid


def shard_table(table, mesh: Mesh):
    """Place every column row-sharded on the mesh (pads to divisibility).

    Returns (padded_table, valid_row_count).  Padding rows are masked invalid
    so kernels that respect masks ignore them; count-style kernels must slice
    to ``valid_row_count``.
    """
    import jax.numpy as jnp

    from ..table import Column, Table

    n = table.num_rows
    k = mesh.devices.size
    padded = pad_to_multiple(max(n, 1), k)
    sh = row_sharding(mesh)
    cols = []
    for c in table.columns:
        data = c.data
        mask = c.valid_mask() if (c.mask is not None or padded != n) else None
        if padded != n:
            pad = padded - n
            data = jnp.concatenate([data, jnp.zeros(pad, dtype=data.dtype)])
            if mask is not None:
                mask = jnp.concatenate([mask, jnp.zeros(pad, dtype=bool)])
        data = jax.device_put(data, sh)
        if mask is not None:
            mask = jax.device_put(mask, sh)
        cols.append(Column(data, c.stype, mask, c.dictionary))
    return Table(list(table.names), cols), n


def init_multihost(coordinator_address: Optional[str] = None,
                   num_processes: Optional[int] = None,
                   process_id: Optional[int] = None) -> Mesh:
    """Attach this host to a multi-host mesh (DCN) and return the row mesh.

    The reference attaches a `dask.distributed.Client` to an external
    scheduler (SURVEY §2.3, fixtures.py:291-297); the SPMD equivalent is
    ``jax.distributed.initialize`` — every host runs the same driver
    program, the mesh spans all hosts' devices, and XLA routes collectives
    over ICI within a slice and DCN across slices. On a single host (or
    under test) this degrades to the local mesh.
    """
    if coordinator_address is not None:
        try:
            jax.distributed.initialize(coordinator_address=coordinator_address,
                                       num_processes=num_processes,
                                       process_id=process_id)
        except RuntimeError as e:
            # already initialized: degrade to the existing mesh, as promised
            if "already" not in str(e).lower():
                raise
    return default_mesh()
