"""High-cardinality string matching: vectorized host bitmaps + a device
bytes-matrix LIKE kernel.

The baseline string path walks the (small) dictionary with per-entry Python
regex — perfect at TPC-H cardinalities, a cliff at ~1M distinct values
(reference semantics: call.py:287-385's LIKE transpiler).  Two escape
hatches, picked per call:

- ``like_bitmap_vectorized``: LIKE patterns made of literal chunks
  separated by ``%`` (no ``_``) evaluate over the whole dictionary with
  ``np.strings`` kernels (startswith / endswith / find-with-array-starts) —
  one C pass per chunk instead of one Python regex call per entry.
- ``device_like_bitmap``: above ``DSQL_DEVICE_STRING_THRESHOLD`` distinct
  values the dictionary is padded into a device-resident ``[D, L]`` uint8
  bytes matrix (built once per dictionary, memoized) and chunk matching
  runs as shifted byte comparisons on the accelerator; the per-entry bool
  bitmap comes back and rows map via the usual code gather.

Both produce the same per-dictionary-entry bitmap the regex path produces;
callers fall back to regex for patterns outside the chunk grammar
(``_`` wildcards, SIMILAR TO).
"""
from __future__ import annotations

import os
import weakref
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

DEVICE_STRING_THRESHOLD = int(
    os.environ.get("DSQL_DEVICE_STRING_THRESHOLD", str(1 << 15)))
_MAX_DEVICE_STR_LEN = 128

stats = {"device_bitmaps": 0}   # observability for tests/benchmarks


def parse_like_chunks(pattern: str, escape: Optional[str]
                      ) -> Optional[Tuple[List[str], bool, bool]]:
    """(chunks, anchor_start, anchor_end) for %-separated literal patterns;
    None when the pattern needs full regex (``_`` wildcard)."""
    chunks: List[str] = []
    cur: List[str] = []
    i = 0
    n = len(pattern)
    ends_wild = False
    while i < n:
        c = pattern[i]
        if escape and c == escape and i + 1 < n:
            cur.append(pattern[i + 1])
            ends_wild = False
            i += 2
            continue
        if c == "_":
            return None
        if c == "%":
            if cur:
                chunks.append("".join(cur))
                cur = []
            ends_wild = True
        else:
            cur.append(c)
            ends_wild = False
        i += 1
    if cur:
        chunks.append("".join(cur))
    anchor_start = bool(pattern) and pattern[0] != "%"
    anchor_end = bool(pattern) and not ends_wild
    return chunks, anchor_start, anchor_end


def like_bitmap_vectorized(d: np.ndarray, pattern: str,
                           escape: Optional[str],
                           kind: str) -> Optional[np.ndarray]:
    """Per-dictionary-entry LIKE bitmap via np.strings; None = not eligible."""
    if kind == "SIMILAR":
        return None
    parsed = parse_like_chunks(pattern, escape)
    if parsed is None:
        return None
    chunks, anchor_start, anchor_end = parsed
    s = np.asarray(d, dtype=str)
    if kind == "ILIKE":
        s = np.strings.lower(s)
        chunks = [c.lower() for c in chunks]
    D = len(s)
    if not chunks:
        if pattern == "":
            return np.strings.str_len(s) == 0  # LIKE '' matches only ''
        return np.ones(D, dtype=bool)  # '%', '%%', ... match everything
    if len(chunks) == 1 and anchor_start and anchor_end:
        return s == chunks[0]
    ok = np.ones(D, dtype=bool)
    slen = np.strings.str_len(s)
    pos = np.zeros(D, dtype=np.int64)
    last = len(chunks) - 1
    for i, chunk in enumerate(chunks):
        if i == 0 and anchor_start:
            ok &= np.strings.startswith(s, chunk)
            pos = np.full(D, len(chunk), dtype=np.int64)
            continue
        if i == last and anchor_end:
            ok &= np.strings.endswith(s, chunk)
            ok &= (slen - len(chunk)) >= pos
            continue
        idx = np.strings.find(s, chunk, pos, slen)
        ok &= idx >= 0
        pos = np.where(idx >= 0, idx + len(chunk), pos)
    return ok


# ---------------------------------------------------------------------------
# device bytes-matrix path
# ---------------------------------------------------------------------------

# id(dictionary) -> (weakref, np str-dtype copy): the object->U astype over
# a large dictionary costs more than the matching itself — convert once
_str_memo: dict = {}


def dict_as_str(dictionary: np.ndarray) -> np.ndarray:
    key = id(dictionary)
    hit = _str_memo.get(key)
    if hit is not None and hit[0]() is dictionary:
        return hit[1]
    s = np.asarray(dictionary, dtype=str)
    _str_memo[key] = (
        weakref.ref(dictionary, lambda _r, k=key: _str_memo.pop(k, None)), s)
    return s


# id(dictionary) -> (weakref, device_bytes [D, L] uint8, lens [D] int32,
#                    all_ascii)
_matrix_memo: dict = {}


def _bytes_matrix(dictionary: np.ndarray):
    """Device-resident padded bytes matrix for a dictionary, or None when
    the dictionary holds strings too long for the fixed-width layout."""
    key = id(dictionary)
    hit = _matrix_memo.get(key)
    if hit is not None and hit[0]() is dictionary:
        return hit[1], hit[2], hit[3]
    encoded = [str(v).encode("utf-8") for v in dictionary]
    L = max((len(b) for b in encoded), default=1)
    if L > _MAX_DEVICE_STR_LEN:
        return None
    L = max(L, 1)
    D = len(encoded)
    mat = np.zeros((D, L), dtype=np.uint8)
    lens = np.empty(D, dtype=np.int32)
    for i, b in enumerate(encoded):
        lens[i] = len(b)
        mat[i, :len(b)] = np.frombuffer(b, dtype=np.uint8)
    all_ascii = bool((mat < 128).all())
    dev_mat = jnp.asarray(mat)
    dev_lens = jnp.asarray(lens)
    _matrix_memo[key] = (
        weakref.ref(dictionary, lambda _r, k=key: _matrix_memo.pop(k, None)),
        dev_mat, dev_lens, all_ascii)
    return dev_mat, dev_lens, all_ascii


def _chunk_occurrences(B: jax.Array, lens: jax.Array, chunk: bytes):
    """occ[d, j]: chunk matches B[d] at byte offset j (window within len)."""
    D, L = B.shape
    m = len(chunk)
    if m > L:
        # chunk longer than every dictionary string: no row matches; w=1
        # keeps downstream argmax/take shapes valid (all-False column)
        return jnp.zeros((D, 1), dtype=bool), 1
    w = L - m + 1
    acc = jnp.ones((D, w), dtype=bool)
    for k, byte in enumerate(chunk):
        acc = acc & (B[:, k:k + w] == np.uint8(byte))
    win_ok = (jnp.arange(w)[None, :] + m) <= lens[:, None]
    return acc & win_ok, w


def device_like_bitmap(dictionary: np.ndarray, pattern: str,
                       escape: Optional[str], kind: str
                       ) -> Optional[jax.Array]:
    """Per-dictionary-entry LIKE bitmap computed ON DEVICE; None when the
    pattern/dictionary is outside the device grammar (regex fallback)."""
    if kind == "SIMILAR":
        return None
    parsed = parse_like_chunks(pattern, escape)
    if parsed is None:
        return None
    chunks, anchor_start, anchor_end = parsed
    built = _bytes_matrix(dictionary)
    if built is None:
        return None
    B, lens, all_ascii = built
    if kind == "ILIKE":
        if not (all_ascii and pattern.isascii()):
            return None  # non-ASCII case folding needs the host path
        B = jnp.where((B >= 65) & (B <= 90), B + 32, B)
        chunks = [c.lower() for c in chunks]
    try:
        enc = [c.encode("utf-8") for c in chunks]
    except UnicodeEncodeError:  # pragma: no cover
        return None
    D = B.shape[0]
    if not enc:
        if pattern == "":
            return lens == 0  # LIKE '' matches only ''
        return jnp.ones(D, dtype=bool)
    ok = jnp.ones(D, dtype=bool)
    pos = jnp.zeros(D, dtype=jnp.int32)
    last = len(enc) - 1
    for i, chunk in enumerate(enc):
        m = len(chunk)
        if i == 0 and anchor_start and i == last and anchor_end:
            # exact equality: prefix match + exact length
            occ, _ = _chunk_occurrences(B, lens, chunk)
            ok = ok & occ[:, 0] & (lens == m)
            continue
        occ, w = _chunk_occurrences(B, lens, chunk)
        if i == 0 and anchor_start:
            ok = ok & occ[:, 0]
            pos = jnp.full(D, m, dtype=jnp.int32)
            continue
        if i == last and anchor_end:
            at = jnp.clip(lens - m, 0, w - 1)
            end_hit = jnp.take_along_axis(occ, at[:, None].astype(jnp.int32),
                                          axis=1)[:, 0]
            ok = ok & end_hit & (lens - m >= pos)
            continue
        cand = occ & (jnp.arange(w)[None, :] >= pos[:, None])
        found = cand.any(axis=1)
        idx = jnp.argmax(cand, axis=1)
        ok = ok & found
        pos = jnp.where(found, idx + m, pos).astype(jnp.int32)
    return ok
