"""Sort kernels: multi-key ORDER BY with NULLS FIRST/LAST on device.

TPU-native replacement for the reference's distributed sort
(/root/reference/dask_sql/physical/utils/sort.py:9-106): where the reference
does set_index + per-partition mergesort with NaN splicing, here every key
becomes a numeric array whose order matches SQL order (strings via dictionary
ranks) and one ``jnp.lexsort`` produces the permutation.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp

from ..table import Column, Table
from .kernels import comparable_data


def sort_indices(table: Table,
                 keys: List[Tuple[int, bool, bool]]) -> jax.Array:
    """Stable permutation for ORDER BY.

    ``keys`` = [(column_index, ascending, nulls_first), ...] in priority order.
    """
    arrays = []
    # jnp.lexsort: LAST key is primary -> feed reversed priority
    for idx, ascending, nulls_first in reversed(keys):
        col = table.columns[idx]
        data = comparable_data(col)
        if jnp.issubdtype(data.dtype, jnp.integer):
            data = data.astype(jnp.int64)
        if not ascending:
            data = _negate(data)
        # null ordering: add an explicit null-rank key *after* (lower priority
        # handled by lexsort order) — actually nulls dominate: use two arrays
        if col.mask is not None:
            nullkey = (~col.mask).astype(jnp.int8)
            if not nulls_first:
                arrays.append(data)
                arrays.append(nullkey)      # higher priority: valid first
            else:
                arrays.append(data)
                arrays.append(_negate(nullkey))
        else:
            arrays.append(data)
    if not arrays:
        return jnp.arange(table.num_rows)
    return jnp.lexsort(arrays)


def _negate(data: jax.Array) -> jax.Array:
    if jnp.issubdtype(data.dtype, jnp.floating):
        # reverse order incl. proper NaN handling: NaN sorts last in lexsort;
        # map to -inf trick not needed since SQL nulls are masks, NaN is a value
        return -data
    return -data.astype(jnp.int64)


def apply_sort(table: Table, keys: List[Tuple[int, bool, bool]]) -> Table:
    if table.num_rows <= 1 or not keys:
        return table
    perm = sort_indices(table, keys)
    return table.take(perm)


def apply_offset_limit(table: Table, offset: Optional[int],
                       limit: Optional[int]) -> Table:
    """Reference: LogicalSortPlugin._apply_offset (sort.py:64-120)."""
    start = offset or 0
    stop = table.num_rows if limit is None else min(start + limit, table.num_rows)
    if start == 0 and stop == table.num_rows:
        return table
    return table.slice(start, stop)
