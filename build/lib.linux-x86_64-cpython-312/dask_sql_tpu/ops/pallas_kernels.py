"""Pallas TPU kernels for the engine's hot loops.

The flagship kernel is a fused masked segmented reduction: SQL's
``SELECT agg(x) ... GROUP BY k`` with a small static group domain (Q1 shape).
Instead of XLA scatter-adds (slow on TPU) or a sort-based factorize, each
row block builds its one-hot group matrix in VMEM and contracts it against
the value rows on the MXU:

    out[a, g] += sum_i vals[a, i] * (codes[i] == g & mask[i])

The one-hot never touches HBM — it exists per block in VMEM — so the kernel
is bandwidth-bound on the value stream alone, the MXU does the reduction,
and the grid accumulates partials into the (A, G) output block across steps.

The reference has no analogue (its groupby is a dask tree reduction over
pandas partitions, aggregate.py:325-361); this is the SURVEY §7 "pallas
kernels where XLA ops are awkward" item for groupby.

On non-TPU backends the kernel runs in interpreter mode (tests), keeping one
code path.
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

BLOCK = 1024       # rows per grid step (lane-aligned multiple of 128)
GROUP_TILE = 128   # group-axis padding (last-dim tile width)


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _strategy_on_tpu() -> bool:
    """Which KERNEL STRATEGY to trace — sort-based merge join / payload-
    through-sort groupby (TPU-shaped: no scatters) vs hash-table join /
    scatter groupby (host-shaped: scatters are ~1 ms where sorts are
    hundreds).  Distinct from ``_on_tpu`` (the hardware truth, which gates
    pallas ``interpret=``): ``DSQL_STRATEGY=tpu|host`` forces a strategy on
    either backend — the driver bench uses ``host`` on the tunneled TPU
    because the merge join's variadic sorts compile ~8x slower there
    (~200 s/query) while the hash program compiles in ~25 s."""
    s = os.environ.get("DSQL_STRATEGY", "auto").lower()
    if s == "tpu":
        return True
    if s in ("host", "cpu"):
        return False
    return _on_tpu()


def _seg_matmul_kernel(codes_ref, mask_ref, vals_ref, out_ref):
    """One grid step: accumulate this row block's per-group partial sums."""
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    codes = codes_ref[:]                      # (1, BLOCK) int32
    mask = mask_ref[:]                        # (1, BLOCK) bool
    g = out_ref.shape[1]
    onehot = (codes.reshape(-1, 1)
              == jax.lax.broadcasted_iota(jnp.int32, (codes.shape[1], g), 1))
    onehot = jnp.where(mask.reshape(-1, 1), onehot, False)
    onehot = onehot.astype(out_ref.dtype)
    out_ref[:] += jnp.dot(vals_ref[:].astype(out_ref.dtype), onehot,
                          preferred_element_type=out_ref.dtype)


def segmented_sums(vals: jax.Array, codes: jax.Array, mask: jax.Array,
                   num_groups: int, *, interpret: bool | None = None
                   ) -> jax.Array:
    """Masked segmented sums of A value rows over a static group domain.

    vals: (A, n) float; codes: (n,) ints in [0, num_groups); mask: (n,) bool.
    Returns (A, num_groups) sums of vals[:, i] over rows with codes[i]==g and
    mask[i]. Jit/trace-safe; static shapes only.

    Non-finite safety: the one-hot contraction computes vals * 0 for other
    groups, and NaN/Inf * 0 == NaN would poison every group. The kernel
    therefore sums sanitized values and per-group NaN/+Inf/-Inf indicator
    rows, and reconstitutes IEEE semantics afterwards.
    """
    if interpret is None:
        interpret = not _on_tpu()
    return _nonfinite_safe(
        lambda v, c, m, g: _segmented_sums_finite(v, c, m, g, interpret)
    )(vals, codes, mask, num_groups)


def _segmented_sums_finite(vals: jax.Array, codes: jax.Array, mask: jax.Array,
                           num_groups: int, interpret: bool) -> jax.Array:
    a, n = vals.shape
    g_pad = max(GROUP_TILE, -(-num_groups // GROUP_TILE) * GROUP_TILE)
    n_pad = -(-n // BLOCK) * BLOCK
    if n_pad != n:
        vals = jnp.pad(vals, ((0, 0), (0, n_pad - n)))
        codes = jnp.pad(codes, (0, n_pad - n))
        mask = jnp.pad(mask, (0, n_pad - n))  # padded rows masked out
    codes = codes.astype(jnp.int32).reshape(1, n_pad)
    mask = mask.reshape(1, n_pad)
    out_dtype = vals.dtype if jnp.issubdtype(vals.dtype, jnp.floating) \
        else jnp.float64
    grid = n_pad // BLOCK
    out = pl.pallas_call(
        _seg_matmul_kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((1, BLOCK), lambda i: (0, i)),
            pl.BlockSpec((1, BLOCK), lambda i: (0, i)),
            pl.BlockSpec((a, BLOCK), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((a, g_pad), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((a, g_pad), out_dtype),
        interpret=interpret,
    )(codes, mask, vals)
    return out[:, :num_groups]


@functools.partial(jax.jit, static_argnames=("num_groups", "interpret"))
def segmented_sums_jit(vals, codes, mask, num_groups, interpret=None):
    return segmented_sums(vals, codes, mask, num_groups, interpret=interpret)


def segmented_sums_xla_blocked(vals: jax.Array, codes: jax.Array,
                               mask: jax.Array, num_groups: int,
                               block: int = 4096) -> jax.Array:
    """One-hot contraction via an XLA scan over row blocks.

    Same math as the pallas kernel but in plain XLA: Mosaic has no 64-bit
    support, so this is the f64 path on TPU (X64 emulation is exact). The
    per-block one-hot lives only inside the scan body — peak memory is one
    (block, G) tile, not (n, G). Callers handle non-finite values
    (segmented_sums_dispatch wraps with the sanitize/indicator machinery).
    """
    a, n = vals.shape
    out_dtype = vals.dtype if jnp.issubdtype(vals.dtype, jnp.floating) \
        else jnp.float64
    n_pad = -(-max(n, 1) // block) * block
    if n_pad != n:
        vals = jnp.pad(vals, ((0, 0), (0, n_pad - n)))
        codes = jnp.pad(codes, (0, n_pad - n))
        mask = jnp.pad(mask, (0, n_pad - n))
    nb = n_pad // block
    vb = vals.reshape(a, nb, block).transpose(1, 0, 2).astype(out_dtype)
    cb = codes.astype(jnp.int32).reshape(nb, block)
    mb = mask.reshape(nb, block)

    def step(acc, xs):
        v, c, m = xs
        onehot = (c[:, None]
                  == jax.lax.broadcasted_iota(jnp.int32, (block, num_groups), 1))
        onehot = jnp.where(m[:, None], onehot, False).astype(out_dtype)
        return acc + jnp.dot(v, onehot, preferred_element_type=out_dtype), None

    acc0 = jnp.zeros((a, num_groups), dtype=out_dtype)
    out, _ = jax.lax.scan(step, acc0, (vb, cb, mb))
    return out


def segmented_sums_dispatch(vals: jax.Array, codes: jax.Array,
                            mask: jax.Array, num_groups: int) -> jax.Array:
    """Backend policy for the static-domain groupby reduction.

    - DSQL_PALLAS=force: pallas kernel (interpreted off-TPU) — test hook.
    - TPU + 32-bit floats: the pallas MXU kernel.
    - TPU + 64-bit: XLA blocked contraction (Mosaic has no 64-bit types).
    - otherwise (CPU/GPU): XLA scatter segment-sum, which is fine there.
    Non-finite safety is applied here once for every backend.
    """
    import os

    forced = os.environ.get("DSQL_PALLAS") == "force"
    if forced:
        return segmented_sums(vals, codes, mask, num_groups,
                              interpret=not _on_tpu())
    if _on_tpu():
        if vals.dtype == jnp.float32:
            return segmented_sums(vals, codes, mask, num_groups,
                                  interpret=False)
        return _nonfinite_safe(segmented_sums_xla_blocked)(
            vals, codes, mask, num_groups)
    return reference_segmented_sums(vals, codes, mask, num_groups)


def _nonfinite_safe(backend):
    """Wrap a sanitized-sum backend with NaN/Inf indicator reassembly."""
    def wrapped(vals, codes, mask, num_groups):
        if not jnp.issubdtype(vals.dtype, jnp.floating):
            return backend(vals, codes, mask, num_groups)
        from .sorted_agg import ieee_reassemble
        a = vals.shape[0]
        isnan = jnp.isnan(vals)
        ispos = jnp.isposinf(vals)
        isneg = jnp.isneginf(vals)
        clean = jnp.where(isnan | ispos | isneg, 0.0, vals)
        stacked = jnp.concatenate([
            clean, isnan.astype(vals.dtype), ispos.astype(vals.dtype),
            isneg.astype(vals.dtype)])
        sums = backend(stacked, codes, mask, num_groups)
        return ieee_reassemble(sums[:a], sums[a:2 * a], sums[2 * a:3 * a],
                               sums[3 * a:])
    return wrapped


def reference_segmented_sums(vals, codes, mask, num_groups):
    """XLA scatter-based oracle for tests (where, not multiply, so masked
    NaN rows contribute nothing)."""
    out_dtype = vals.dtype if jnp.issubdtype(vals.dtype, jnp.floating) \
        else jnp.float64
    return jnp.stack([
        jax.ops.segment_sum(
            jnp.where(mask, vals[i].astype(out_dtype), 0), codes, num_groups)
        for i in range(vals.shape[0])])
