"""Scatter-free segmented aggregation over group-sorted rows.

``jax.ops.segment_*`` lowers to scatter, which TPUs execute painfully
(serialized updates); measured on the bench workload a single 120k-row
segment_sum cost ~250ms on-chip. Everything here uses the TPU-fast
primitives instead: cumulative sums, ``searchsorted`` gathers, and
log-depth ``associative_scan`` — no scatter anywhere.

Layout contract: rows are sorted by group code ascending (invalid rows
sorted past all real codes), so segment g occupies the half-open range
[starts[g], ends[g]) given by binary search. Aggregates are prefix-sum
differences (SUM/COUNT family) or segmented scans (MIN/MAX/first/last).

Non-finite safety for sums: a NaN in the value stream would poison every
later group through the running prefix; sums are computed over sanitized
values plus NaN/+Inf/-Inf indicator counts and reassembled with IEEE
semantics (shared with the pallas MXU kernel, ops/pallas_kernels.py).
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

US = jnp.uint64


def ieee_reassemble(clean: jax.Array, nan_c: jax.Array, pos_c: jax.Array,
                    neg_c: jax.Array) -> jax.Array:
    """Recombine a sanitized sum with non-finite indicator counts."""
    out = jnp.where(pos_c > 0, jnp.inf, clean)
    out = jnp.where(neg_c > 0, -jnp.inf, out)
    out = jnp.where((pos_c > 0) & (neg_c > 0), jnp.nan, out)
    return jnp.where(nan_c > 0, jnp.nan, out)


def segment_bounds(codes_sorted: jax.Array, cap: int
                   ) -> Tuple[jax.Array, jax.Array]:
    """[starts, ends) of each group slot in the sorted code stream.

    Codes are DENSE ranks 0..ngroups-1 (ascending; slot ``cap`` is the
    invalid-row trash region), so the k-th group boundary in the stream IS
    the start of slot k. A single-operand sort of the boundary positions is
    ~10x cheaper on TPU than searchsorted's (n+cap)-element key+payload sort
    (measured on the bench workload: 57ms -> 4ms at 1.8M rows).
    """
    n = codes_sorted.shape[0]
    valid = codes_sorted < cap
    boundary = valid & jnp.concatenate(
        [jnp.ones(1, dtype=bool), codes_sorted[1:] != codes_sorted[:-1]])
    pos = jnp.where(boundary, jnp.arange(n, dtype=jnp.int64), n)
    pos = jnp.sort(pos)
    if n < cap:
        pos = jnp.concatenate([pos, jnp.full(cap - n, n, dtype=jnp.int64)])
    starts = pos[:cap]
    nvalid = jnp.sum(valid.astype(jnp.int64))
    # empty slots (>= ngroups) collapse to [nvalid, nvalid), matching the
    # previous searchsorted contract
    ends = jnp.minimum(
        jnp.concatenate([starts[1:], jnp.full(1, n, dtype=jnp.int64)]), nvalid)
    starts = jnp.minimum(starts, nvalid)
    return starts, ends


def _prefix(x: jax.Array) -> jax.Array:
    """Exclusive-prefix-friendly cumsum: prefix[i] = sum(x[:i])."""
    return jnp.concatenate([jnp.zeros(1, dtype=x.dtype), jnp.cumsum(x)])


def seg_count(valid: jax.Array, starts: jax.Array, ends: jax.Array
              ) -> jax.Array:
    p = _prefix(valid.astype(jnp.int64))
    return p[ends] - p[starts]


def seg_sum(values: jax.Array, valid: jax.Array, codes_sorted: jax.Array,
            starts: jax.Array, ends: jax.Array) -> jax.Array:
    """Masked segmented sum.

    Integers ride the exact prefix-sum difference (int64 modular arithmetic
    cancels exactly). Floats use the segmented SCAN instead: a global
    prefix would mix group magnitudes — one 1e18 group catastrophically
    cancels every later group's sum — and the per-group scan also keeps
    NaN/Inf confined to their own group for free (the scan resets at each
    boundary), matching per-group sequential accumulation exactly.
    """
    if jnp.issubdtype(values.dtype, jnp.floating):
        v = jnp.where(valid, values.astype(jnp.float64), 0.0)
        return seg_reduce_scan_codes(v, jnp.ones(v.shape[0], bool),
                                     codes_sorted, ends, jnp.add, 0.0,
                                     starts=starts)
    work = jnp.where(valid, values.astype(jnp.int64), 0)
    p = _prefix(work)
    return p[ends] - p[starts]


def _segmented_scan(values: jax.Array, segment_start: jax.Array, combine):
    """Inclusive segmented scan: resets at segment starts. Returns the
    running reduction; element ends[g]-1 holds segment g's total."""

    def op(a, b):
        av, af = a
        bv, bf = b
        return jnp.where(bf, bv, combine(av, bv)), af | bf

    out, _ = jax.lax.associative_scan(op, (values, segment_start))
    return out


def seg_reduce_scan_codes(values: jax.Array, valid: jax.Array,
                          codes_sorted: jax.Array, ends: jax.Array,
                          combine, identity,
                          starts: Optional[jax.Array] = None) -> jax.Array:
    """Segmented reduction via log-depth scan over the sorted stream; start
    flags come from comparing adjacent sorted codes — fully scatter-free.
    With ``starts`` given, empty slots return ``identity`` instead of the
    neighbouring segment's total (the gather at ends-1 lands in the
    previous segment when ends == starts)."""
    n = values.shape[0]
    if n == 0:
        return jnp.full(ends.shape, identity, dtype=values.dtype)
    flags = jnp.concatenate([
        jnp.ones(1, dtype=bool), codes_sorted[1:] != codes_sorted[:-1]])
    work = jnp.where(valid, values, identity)
    scanned = _segmented_scan(work, flags, combine)
    pos = jnp.clip(ends - 1, 0, n - 1)
    out = scanned[pos]
    if starts is not None:
        out = jnp.where(ends > starts, out,
                        jnp.asarray(identity, dtype=out.dtype))
    return out


def seg_min(values, valid, codes_sorted, ends):
    if jnp.issubdtype(values.dtype, jnp.floating):
        ident = jnp.inf
    elif values.dtype == jnp.bool_:
        values, ident = values.astype(jnp.int64), 1
    else:
        ident = jnp.iinfo(values.dtype).max
    return seg_reduce_scan_codes(values, valid, codes_sorted, ends,
                                 jnp.minimum, ident)


def seg_max(values, valid, codes_sorted, ends):
    if jnp.issubdtype(values.dtype, jnp.floating):
        ident = -jnp.inf
    elif values.dtype == jnp.bool_:
        values, ident = values.astype(jnp.int64), 0
    else:
        ident = jnp.iinfo(values.dtype).min
    return seg_reduce_scan_codes(values, valid, codes_sorted, ends,
                                 jnp.maximum, ident)


def seg_first_valid_pos(valid: jax.Array, codes_sorted: jax.Array,
                        ends: jax.Array) -> jax.Array:
    """Sorted-stream position of each segment's first valid row (n if none)."""
    n = valid.shape[0]
    idx = jnp.where(valid, jnp.arange(n, dtype=jnp.int64), n)
    return seg_reduce_scan_codes(idx, jnp.ones(n, bool), codes_sorted, ends,
                                 jnp.minimum, n)


def seg_last_valid_pos(valid: jax.Array, codes_sorted: jax.Array,
                       ends: jax.Array) -> jax.Array:
    n = valid.shape[0]
    idx = jnp.where(valid, jnp.arange(n, dtype=jnp.int64), -1)
    return seg_reduce_scan_codes(idx, jnp.ones(n, bool), codes_sorted, ends,
                                 jnp.maximum, -1)
