"""Window-function kernels: sharded segmented scans instead of gather-to-one.

The reference collapses each PARTITION BY group to a single pandas partition
via groupby().apply (/root/reference/dask_sql/physical/rel/logical/
window.py:152-205) — a scalability cliff SURVEY §5 calls out.  Here windows
are computed as sorted segmented scans: lexsort by (partition, order keys),
run prefix-scan kernels, gather back to row order.

Everything on the main path is jit-trace-safe (no host syncs, static
shapes, no scatters): the compiled whole-plan executor
(physical/compiled.py) calls ``compute_window`` directly inside its trace;
only NTILE/LAG/LEAD/NTH_VALUE read their constant arguments from column
data on the host and stay eager-only.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..table import dict_sort_order, Column, Scalar, Table
from ..types import SqlType, physical_dtype
from .kernels import (append_lexsort_operands, comparable_data, key_parts)

# window ops whose kernels are fully trace-safe (the compiled executor's
# supported subset; the rest read host constants)
TRACE_SAFE_OPS = frozenset({
    "ROW_NUMBER", "RANK", "DENSE_RANK", "PERCENT_RANK", "CUME_DIST",
    "COUNT", "SUM", "$SUM0", "AVG", "MIN", "MAX",
    "FIRST_VALUE", "LAST_VALUE", "SINGLE_VALUE",
})


def _segment_starts(codes_sorted: jax.Array) -> jax.Array:
    n = codes_sorted.shape[0]
    if n == 0:
        return jnp.zeros(0, dtype=bool)
    first = jnp.ones(1, dtype=bool)
    rest = codes_sorted[1:] != codes_sorted[:-1]
    return jnp.concatenate([first, rest])


def _segment_ids(starts: jax.Array) -> jax.Array:
    return jnp.cumsum(starts.astype(jnp.int64)) - 1


def _adjacent_diff(channels, n: int) -> jax.Array:
    """Row 0 True; row i True iff ANY channel differs from row i-1.
    Channels are already sorted streams — boundary detection without
    post-sort gathers (group equality == equality of every sort channel)."""
    if n == 0:
        return jnp.zeros(0, dtype=bool)
    diff = jnp.zeros(n - 1, dtype=bool)
    for ch in channels:
        diff = diff | (ch[1:] != ch[:-1])
    return jnp.concatenate([jnp.ones(1, dtype=bool), diff])


def segmented_cumsum(x: jax.Array, starts: jax.Array) -> jax.Array:
    """Inclusive prefix sum that resets at segment starts (trace-safe:
    log-depth segmented scan, no data-dependent shapes)."""
    return segmented_scan(x, starts, jnp.add)


def segmented_scan(x: jax.Array, starts: jax.Array, combine) -> jax.Array:
    """Generic inclusive segmented scan via associative_scan on (flag, value)."""

    def op(a, b):
        fa, va = a
        fb, vb = b
        return (fa | fb, jnp.where(fb, vb, combine(va, vb)))

    flags = starts
    _, out = jax.lax.associative_scan(op, (flags, x))
    return out


def window_frame_sums(x: jax.Array, seg_start: jax.Array, seg_end: jax.Array,
                      lo: Optional[int], hi: Optional[int]):
    """Moving SUM/COUNT over ROWS frames using prefix sums.

    lo/hi are row offsets relative to current (negative = preceding); None =
    unbounded on that side. seg_start/seg_end are PER-ROW positions of the
    row's segment bounds in sorted order.
    """
    n = x.shape[0]
    prefix = jnp.cumsum(x)
    idx = jnp.arange(n)
    start = seg_start if lo is None else jnp.maximum(idx + lo, seg_start)
    end = seg_end if hi is None else jnp.minimum(idx + hi, seg_end)
    end = jnp.minimum(end, n - 1)
    start = jnp.maximum(start, 0)
    upper = prefix[end]
    lower = jnp.where(start > 0, prefix[jnp.maximum(start - 1, 0)], 0)
    empty = end < start
    return jnp.where(empty, 0, upper - lower)


def compute_window(table: Table, op: str, arg_cols: List[int],
                   partition_cols: List[int],
                   order_keys: List[Tuple[int, bool, bool]],
                   frame, stype: SqlType,
                   row_valid: Optional[jax.Array] = None) -> Column:
    """Compute one window call; returns a column aligned with table rows.

    ``row_valid`` (compiled-executor mode): invalid/padding rows sort into
    their own trailing segment so they never contaminate real partitions;
    their outputs are garbage and must be masked by the caller's validity.
    """
    n = table.num_rows
    if n == 0:
        return Column(jnp.zeros(0, dtype=physical_dtype(stype)), stype)

    from .pallas_kernels import _strategy_on_tpu as _on_tpu
    on_tpu = _on_tpu()

    # 1. sort by (validity, partition, order keys) — trace-safe: partitions
    # come from key-part comparisons, not a factorize. Arrays are built
    # least-significant-first (jnp.lexsort order); the argument column rides
    # the sort as a payload operand on TPU, where a random n-element gather
    # costs ~2x a whole extra sort operand (profiled on the join path).
    arrays = []
    for idx, asc, nulls_first in reversed(order_keys):
        col = table.columns[idx]
        data = comparable_data(col)
        if jnp.issubdtype(data.dtype, jnp.integer):
            data = data.astype(jnp.int64)
        if not asc:
            data = -data if not jnp.issubdtype(data.dtype, jnp.bool_) else ~data
        if col.mask is not None:
            nullkey = (~col.mask).astype(jnp.int8)
            arrays.append(data)
            arrays.append(nullkey if not nulls_first else -nullkey)
        else:
            arrays.append(data)
    n_ord_ops = len(arrays)
    part_parts = key_parts([table.columns[i] for i in partition_cols]) \
        if partition_cols else []
    append_lexsort_operands(arrays, list(reversed(part_parts)))
    if row_valid is not None:
        arrays.append((~row_valid).astype(jnp.int8))  # invalid rows last

    pay: List[jax.Array] = []
    arg_slot = None
    arg_col0 = table.columns[arg_cols[0]] if arg_cols else None
    if arg_col0 is not None and op != "NTILE":
        arg_slot = (len(pay), arg_col0.mask is not None)
        pay.append(arg_col0.data)
        if arg_col0.mask is not None:
            pay.append(arg_col0.mask)

    keys_msf = list(reversed(arrays))  # most significant first
    if not keys_msf:
        perm = jnp.arange(n)
        keys_sorted: List[jax.Array] = []
        pay_sorted = list(pay)
    elif on_tpu:
        iota = jnp.arange(n, dtype=jnp.int64)
        outs = jax.lax.sort(tuple(keys_msf) + (iota,) + tuple(pay),
                            num_keys=len(keys_msf), is_stable=True)
        perm = outs[len(keys_msf)]
        keys_sorted = list(outs[:len(keys_msf)])
        pay_sorted = list(outs[len(keys_msf) + 1:])
    else:
        perm = jnp.lexsort(tuple(arrays))
        keys_sorted = [k[perm] for k in keys_msf]
        pay_sorted = [p[perm] for p in pay]

    def sorted_arg() -> Column:
        di, has_mask = arg_slot
        return Column(pay_sorted[di], arg_col0.stype,
                      pay_sorted[di + 1] if has_mask else None,
                      arg_col0.dictionary)

    # 2. segment starts from adjacent diffs over the SORTED partition (and
    # validity) channels — no gathers; tie groups reuse the order channels
    n_seg_ops = len(keys_msf) - n_ord_ops
    starts = _adjacent_diff(keys_sorted[:n_seg_ops], n)
    tie = _adjacent_diff(keys_sorted[n_seg_ops:], n) & ~starts \
        if order_keys else jnp.zeros(n, dtype=bool)
    pos = jnp.arange(n)
    # per-row segment bounds via forward/backward segmented scans
    seg_start = segmented_scan(pos, starts, jnp.minimum)
    # reversed-stream segment starts: original row i is last-of-segment iff
    # i == n-1 or starts[i+1]; flipping that gives the reverse-scan flags
    ends_flags = jnp.concatenate([jnp.ones(1, bool), jnp.flip(starts[1:])])
    seg_end = jnp.flip(segmented_scan(jnp.flip(pos), ends_flags, jnp.maximum))
    row_in_seg = pos - seg_start

    # frame bounds as offsets
    lo_off, hi_off = _frame_offsets(op, frame, bool(order_keys))

    def scatter_back(sorted_vals, mask_sorted=None):
        # un-sort to original row order: payload sort on TPU, argsort +
        # gather elsewhere (mirrors the join/groupby backend split)
        if on_tpu:
            chs = ((perm, sorted_vals) if mask_sorted is None
                   else (perm, sorted_vals, mask_sorted))
            outs2 = jax.lax.sort(chs, num_keys=1)
            out = outs2[1]
            m = outs2[2] if mask_sorted is not None else None
        else:
            inv_perm = jnp.argsort(perm)
            out = sorted_vals[inv_perm]
            m = None if mask_sorted is None else mask_sorted[inv_perm]
        return Column(out.astype(physical_dtype(stype)) if not stype.is_string else out,
                      stype, m)

    if op == "ROW_NUMBER":
        return scatter_back(row_in_seg + 1)

    if op in ("RANK", "DENSE_RANK", "PERCENT_RANK", "CUME_DIST"):
        # rank = position of the first row of the current tie group:
        # propagate the last tie/segment start forward within the segment
        tie_start = segmented_scan(jnp.where(tie | starts, pos, -1), starts,
                                   jnp.maximum)
        rank = tie_start - seg_start + 1
        if op == "RANK":
            return scatter_back(rank)
        if op == "PERCENT_RANK":
            seg_len = seg_end - seg_start + 1
            pr = jnp.where(seg_len > 1, (rank - 1) / jnp.maximum(seg_len - 1, 1), 0.0)
            return scatter_back(pr)
        if op == "CUME_DIST":
            seg_len = seg_end - seg_start + 1
            # number of rows with order key <= current = end of tie group
            is_last_of_tie = jnp.concatenate([tie[1:] | starts[1:], jnp.ones(1, bool)])
            tie_end = _backward_fill_positions(pos, is_last_of_tie, seg_end)
            return scatter_back((tie_end - seg_start + 1) / seg_len)
        # DENSE_RANK: count of tie-group starts up to here within segment
        dr = segmented_cumsum((tie | starts).astype(jnp.int64), starts)
        return scatter_back(dr)

    if op == "NTILE":
        k = int(np.asarray(table.columns[arg_cols[0]].data)[0]) if arg_cols else 1
        seg_len = seg_end - seg_start + 1
        out = (row_in_seg * k) // jnp.maximum(seg_len, 1) + 1
        return scatter_back(out)

    if op in ("LAG", "LEAD"):
        col = table.columns[arg_cols[0]]
        offset = 1
        if len(arg_cols) > 1:
            offset = int(np.asarray(table.columns[arg_cols[1]].data)[0])
        shift = -offset if op == "LAG" else offset
        src = pos + shift
        valid = (src >= seg_start) & (src <= seg_end)
        src = jnp.clip(src, 0, n - 1)
        sorted_col = sorted_arg()
        gathered = sorted_col.take(src)
        m = gathered.valid_mask() & valid
        out = scatter_back(gathered.data, m)
        if col.stype.is_string:
            return Column(out.data.astype(jnp.int32), stype, out.mask, col.dictionary)
        return out

    if op in ("FIRST_VALUE", "LAST_VALUE", "NTH_VALUE"):
        col = sorted_arg()
        if op == "FIRST_VALUE":
            src = seg_start
        elif op == "LAST_VALUE":
            # default frame = up to CURRENT ROW when ORDER BY present
            if order_keys and frame is None:
                src = pos
            else:
                src = seg_end
        else:
            k = int(np.asarray(table.columns[arg_cols[1]].data)[0])
            src = seg_start + (k - 1)
            src = jnp.minimum(src, seg_end)
        gathered = col.take(src)
        out = scatter_back(gathered.data,
                           gathered.mask if gathered.mask is not None else None)
        if col.stype.is_string:
            return Column(out.data.astype(jnp.int32), stype, out.mask, col.dictionary)
        return out

    # aggregate window functions
    if op in ("COUNT",):
        if arg_cols:
            col = sorted_arg()
            x = col.valid_mask().astype(jnp.int64)
        else:
            x = jnp.ones(n, dtype=jnp.int64)
        out = window_frame_sums(x, seg_start, seg_end, lo_off, hi_off)
        return scatter_back(out)

    if op in ("SUM", "$SUM0", "AVG"):
        col = sorted_arg()
        valid = col.valid_mask()
        data = jnp.where(valid, col.data, 0)
        if jnp.issubdtype(data.dtype, jnp.integer):
            data = data.astype(jnp.int64)
        else:
            data = data.astype(jnp.float64)
        s = window_frame_sums(data, seg_start, seg_end, lo_off, hi_off)
        c = window_frame_sums(valid.astype(jnp.int64), seg_start, seg_end,
                              lo_off, hi_off)
        if op == "AVG":
            out = s / jnp.maximum(c, 1)
            return scatter_back(out, (c > 0))
        if op == "$SUM0":
            return scatter_back(s)
        return scatter_back(s, (c > 0))

    if op in ("MIN", "MAX"):
        col = sorted_arg()
        valid = col.valid_mask()
        data = comparable_data(col)
        if jnp.issubdtype(data.dtype, jnp.integer):
            data = data.astype(jnp.int64)
            sentinel = jnp.iinfo(jnp.int64).max if op == "MIN" else jnp.iinfo(jnp.int64).min
        else:
            data = data.astype(jnp.float64)
            sentinel = jnp.inf if op == "MIN" else -jnp.inf
        x = jnp.where(valid, data, sentinel)
        combine = jnp.minimum if op == "MIN" else jnp.maximum
        if lo_off is None and hi_off == 0:
            out = segmented_scan(x, starts, combine)
        elif lo_off is None and hi_off is None:
            # whole partition: segment reduce then broadcast
            total = segmented_scan(x, starts, combine)
            out = total[seg_end]
        elif lo_off is None:
            # UNBOUNDED PRECEDING .. k: prefix scan + one gather (an O(n)
            # shift loop here would build an O(n^2) trace)
            fwd = segmented_scan(x, starts, combine)
            out = fwd[jnp.clip(pos + hi_off, seg_start, seg_end)]
        elif hi_off is None:
            # k .. UNBOUNDED FOLLOWING: suffix scan + one gather
            bwd = jnp.flip(segmented_scan(jnp.flip(x), ends_flags, combine))
            out = bwd[jnp.clip(pos + lo_off, seg_start, seg_end)]
        else:
            # bounded frame: van Herk two-scan sliding window — O(n) for any
            # frame width w. Width-w blocks get prefix/suffix scans; an
            # UNCLIPPED frame [a, a+w-1] spans at most two blocks, so
            # combine(blocksuffix[a], blockprefix[b]) covers it exactly.
            # Frames clipped by a segment edge lose the alignment guarantee,
            # so those rows select from plain segment scans instead.
            w = max(hi_off - lo_off + 1, 1)
            a_raw = pos + lo_off
            b_raw = pos + hi_off
            low_clip = a_raw < seg_start
            high_clip = b_raw > seg_end
            block_flags = (pos % w) == 0
            fwd_vh = segmented_scan(x, starts | block_flags, combine)
            rev_block = jnp.flip((pos % w) == (w - 1))
            rev_block = rev_block.at[0].set(True)
            bwd_vh = jnp.flip(segmented_scan(jnp.flip(x),
                                             ends_flags | rev_block, combine))
            fwd_seg = segmented_scan(x, starts, combine)
            bwd_seg = jnp.flip(segmented_scan(jnp.flip(x), ends_flags,
                                              combine))
            a_s = jnp.clip(a_raw, 0, n - 1)
            b_s = jnp.clip(b_raw, 0, n - 1)
            vh = combine(bwd_vh[a_s], fwd_vh[b_s])
            cum = fwd_seg[jnp.clip(b_raw, seg_start, seg_end)]
            suf = bwd_seg[jnp.clip(a_raw, seg_start, seg_end)]
            tot = fwd_seg[seg_end]
            out = jnp.where(low_clip & high_clip, tot,
                            jnp.where(low_clip, cum,
                                      jnp.where(high_clip, suf, vh)))
            in_frame_cnt = window_frame_sums(valid.astype(jnp.int64),
                                             seg_start, seg_end, lo_off, hi_off)
            m = in_frame_cnt > 0
            if col.stype.is_string:
                return _ranks_to_string(scatter_back(out, m), table.columns[arg_cols[0]], stype)
            return scatter_back(out, m)
        c = window_frame_sums(valid.astype(jnp.int64), seg_start, seg_end,
                              lo_off, hi_off)
        m = c > 0
        if col.stype.is_string:
            return _ranks_to_string(scatter_back(out, m),
                                    table.columns[arg_cols[0]], stype)
        return scatter_back(out, m)

    if op == "SINGLE_VALUE":
        col = sorted_arg()
        src = seg_start
        g = col.take(src)
        out = scatter_back(g.data, g.mask)
        if col.stype.is_string:
            return Column(out.data.astype(jnp.int32), stype, out.mask, col.dictionary)
        return out

    raise NotImplementedError(f"Window function {op}")


def _ranks_to_string(rank_col: Column, orig: Column, stype: SqlType) -> Column:
    order = dict_sort_order(orig.dictionary)
    inv = jnp.asarray(order.astype(np.int64))
    safe = jnp.clip(rank_col.data.astype(jnp.int64), 0, len(order) - 1)
    codes = jnp.take(inv, safe).astype(jnp.int32)
    return Column(codes, stype, rank_col.mask, orig.dictionary)


def _frame_offsets(op: str, frame, has_order: bool):
    """Map a frame spec to (lo, hi) row offsets (None = unbounded)."""
    if frame is None:
        if has_order and op not in ("ROW_NUMBER", "RANK", "DENSE_RANK"):
            return None, 0          # default: UNBOUNDED PRECEDING .. CURRENT
        return None, None           # whole partition
    kind, lo, hi = frame
    def conv(b, default):
        tag, n = b
        if tag == "UNBOUNDED_PRECEDING":
            return None
        if tag == "UNBOUNDED_FOLLOWING":
            return None
        if tag == "CURRENT":
            return 0
        if tag == "PRECEDING":
            return -int(n)
        return int(n)
    lo_v = conv(lo, None)
    hi_v = conv(hi, 0)
    if lo[0] == "UNBOUNDED_FOLLOWING":
        lo_v = None
    return lo_v, hi_v


def _backward_fill_positions(pos, is_last, seg_end):
    """For each row, position of the last row of its tie group."""
    n = pos.shape[0]
    # reverse scan: propagate next is_last position backwards
    rev = jnp.flip(jnp.where(is_last, pos, -1))
    rev_filled = jax.lax.associative_scan(
        lambda a, b: jnp.where(b >= 0, b, a), rev)
    # associative_scan is forward; combined op keeps latest valid
    filled = jnp.flip(rev_filled)
    return jnp.where(filled >= 0, filled, seg_end)
