"""Operator/function metadata: names, classification, result-type inference.

The operator vocabulary mirrors the reference's scalar-op library
(/root/reference/dask_sql/physical/rex/core/call.py:685-762) and aggregation
mapping (physical/rel/logical/aggregate.py:91-117), plus the window ops
(physical/rel/logical/window.py:220-231).  Implementations live in
``physical/rex/ops.py``; this module is what the binder consults for typing.
"""
from __future__ import annotations

from typing import List

from ..types import (
    BIGINT, BOOLEAN, DATE, DOUBLE, INTEGER, INTERVAL_DAY_TIME, NULLTYPE,
    SqlType, TIMESTAMP, VARCHAR, promote,
)

# ---------------------------------------------------------------------------
# classification
# ---------------------------------------------------------------------------

AGGREGATE_FUNCTIONS = {
    "COUNT", "SUM", "$SUM0", "AVG", "MIN", "MAX", "ANY_VALUE", "EVERY",
    "SINGLE_VALUE", "BIT_AND", "BIT_OR", "BIT_XOR", "STDDEV", "STDDEV_POP",
    "STDDEV_SAMP", "VAR_POP", "VAR_SAMP", "VARIANCE", "REGR_COUNT",
    "BOOL_AND", "BOOL_OR", "LISTAGG",
}

WINDOW_ONLY_FUNCTIONS = {
    "ROW_NUMBER", "RANK", "DENSE_RANK", "PERCENT_RANK", "CUME_DIST", "NTILE",
    "LAG", "LEAD", "FIRST_VALUE", "LAST_VALUE", "NTH_VALUE",
}


def is_aggregate(op: str) -> bool:
    return op in AGGREGATE_FUNCTIONS


def is_window_only(op: str) -> bool:
    return op in WINDOW_ONLY_FUNCTIONS


# ---------------------------------------------------------------------------
# result-type inference for scalar calls
# ---------------------------------------------------------------------------

_COMPARISONS = {"=", "<>", "<", "<=", ">", ">="}
_BOOL_OPS = {"AND", "OR", "NOT", "LIKE", "ILIKE", "SIMILAR", "REGEXP",
             "IS_NULL", "IS_NOT_NULL", "IS_TRUE", "IS_NOT_TRUE", "IS_FALSE",
             "IS_NOT_FALSE", "IS_DISTINCT_FROM", "IS_NOT_DISTINCT_FROM",
             "IN_LIST", "BETWEEN", "EXISTS"}

_STRING_RESULT = {
    "||", "CONCAT", "UPPER", "LOWER", "INITCAP", "SUBSTRING", "SUBSTR",
    "TRIM", "LTRIM", "RTRIM", "BTRIM", "OVERLAY", "REPLACE", "REPEAT",
    "REVERSE", "LEFT", "RIGHT", "LPAD", "RPAD", "CHR", "SPLIT_PART",
    "REGEXP_REPLACE", "TO_CHAR", "TRANSLATE",
}

_INT_RESULT = {"CHAR_LENGTH", "CHARACTER_LENGTH", "LENGTH", "POSITION",
               "STRPOS", "ASCII", "OCTET_LENGTH", "SIGN_INT"}

_BIGINT_RESULT = {"EXTRACT", "YEAR", "MONTH", "DAY", "HOUR", "MINUTE",
                  "SECOND", "QUARTER", "DAYOFWEEK", "DAYOFMONTH", "DAYOFYEAR",
                  "WEEK", "TIMESTAMPDIFF", "DATEDIFF"}

_DOUBLE_RESULT = {
    "SQRT", "EXP", "LN", "LOG10", "LOG", "POWER", "POW", "SIN", "COS", "TAN",
    "ASIN", "ACOS", "ATAN", "ATAN2", "SINH", "COSH", "TANH", "COT", "DEGREES",
    "RADIANS", "CBRT", "RAND", "RANDOM", "PI",
}

_SAME_AS_ARG = {"NEGATE", "ABS", "FLOOR", "CEIL", "CEILING", "ROUND",
                "TRUNCATE", "TRUNC", "SIGN"}


def infer_call_type(op: str, arg_types: List[SqlType]) -> SqlType:
    nullable = any(t.nullable for t in arg_types) if arg_types else False
    if op in _COMPARISONS or op in _BOOL_OPS:
        return BOOLEAN
    if op in _STRING_RESULT:
        return VARCHAR
    if op in _INT_RESULT:
        return INTEGER
    if op in _BIGINT_RESULT:
        return BIGINT
    if op in _DOUBLE_RESULT:
        return DOUBLE
    if op in _SAME_AS_ARG:
        t = arg_types[0]
        if op in ("FLOOR", "CEIL", "CEILING") and len(arg_types) == 2:
            return t  # datetime FLOOR(d TO unit)
        if t.name == "NULL":
            return DOUBLE
        return SqlType(t.name, t.precision, t.scale)
    if op == "MOD" or op == "%":
        return promote(arg_types[0], arg_types[1])
    if op in ("+", "-"):
        a, b = arg_types
        # temporal arithmetic
        if a.is_temporal and b.is_interval:
            if b.name == "INTERVAL_YEAR_MONTH":
                return SqlType(a.name)
            return SqlType(a.name)
        if b.is_temporal and a.is_interval and op == "+":
            return SqlType(b.name)
        if a.is_temporal and b.is_temporal and op == "-":
            return INTERVAL_DAY_TIME
        if a.is_interval and b.is_interval:
            return SqlType(a.name)
        return promote(a, b)
    if op == "*":
        a, b = arg_types
        if a.is_interval or b.is_interval:
            return SqlType(a.name if a.is_interval else b.name)
        return promote(a, b)
    if op == "/":
        a, b = arg_types
        if a.is_interval:
            return SqlType(a.name)
        t = promote(a, b)
        # SQL integer division stays integral (reference SQLDivisionOperator,
        # call.py:120-144 truncates int results)
        return t
    if op in ("COALESCE", "IFNULL", "NVL", "GREATEST", "LEAST", "NULLIF", "CASE"):
        ts = [t for t in arg_types if t.name != "NULL"]
        if not ts:
            return NULLTYPE
        out = ts[0]
        for t in ts[1:]:
            out = promote(out, t)
        return out
    if op in ("CURRENT_DATE",):
        return DATE
    if op in ("CURRENT_TIMESTAMP", "NOW", "LOCALTIMESTAMP", "CURRENT_TIME", "LOCALTIME"):
        return TIMESTAMP
    if op == "LAST_DAY":
        return DATE
    if op == "DATE_TRUNC":
        return TIMESTAMP
    if op == "TIMESTAMPADD":
        return arg_types[-1]
    if op == "RAND_INTEGER":
        return INTEGER
    if op == "ROW":
        return arg_types[0] if arg_types else NULLTYPE
    if op == "SEARCH":
        return BOOLEAN
    if op == "CAST":
        raise AssertionError("CAST typed by binder directly")
    raise KeyError(op)


def infer_agg_type(op: str, arg_types: List[SqlType]) -> SqlType:
    if op in ("COUNT", "REGR_COUNT", "ROW_NUMBER", "RANK", "DENSE_RANK", "NTILE"):
        return SqlType("BIGINT", nullable=False)
    if op in ("SUM", "$SUM0"):
        t = arg_types[0]
        if t.is_integer:
            return BIGINT
        if t.name == "DECIMAL":
            return SqlType("DECIMAL", t.precision, t.scale)
        return DOUBLE
    if op in ("AVG", "STDDEV", "STDDEV_POP", "STDDEV_SAMP", "VAR_POP",
              "VAR_SAMP", "VARIANCE", "PERCENT_RANK", "CUME_DIST"):
        return DOUBLE
    if op in ("EVERY", "BOOL_AND", "BOOL_OR"):
        return BOOLEAN
    if op == "LISTAGG":
        return VARCHAR
    if op in ("MIN", "MAX", "ANY_VALUE", "SINGLE_VALUE", "BIT_AND", "BIT_OR",
              "BIT_XOR", "FIRST_VALUE", "LAST_VALUE", "NTH_VALUE", "LAG", "LEAD"):
        t = arg_types[0]
        return SqlType(t.name, t.precision, t.scale)
    raise KeyError(op)
