"""LogicalPredict: plan node for ``FROM PREDICT(MODEL m, <query>)``.

The reference implements PREDICT as a custom SqlNode plugin that re-enters the
SQL machinery with a temp table (/root/reference/dask_sql/physical/rel/custom/
predict.py:12-117); here it is a first-class plan node so it composes with the
optimizer and any outer operators.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import List

from .nodes import Field, RelNode


@dataclass
class LogicalPredict(RelNode):
    input: RelNode = None
    model_name: List[str] = field(default_factory=list)
    schema: List[Field] = field(default_factory=list)

    @property
    def inputs(self):
        return [self.input]

    def with_inputs(self, inputs):
        return LogicalPredict(inputs[0], self.model_name, self.schema)

    def _explain_line(self):
        return f"LogicalPredict(model=[{'.'.join(self.model_name)}])"
