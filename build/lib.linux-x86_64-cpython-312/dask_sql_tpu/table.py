"""Columnar device tables: the TPU-native answer to the reference's DataContainer.

The reference wraps a lazy dask DataFrame plus a frontend/backend column-name
mapping (/root/reference/dask_sql/datacontainer.py:14-191) because renaming
dask columns costs task-graph nodes.  Here a table is an ordered list of
``Column`` objects, each wrapping one ``jax.Array`` on device; renames and
projections are free dict surgery on the host, so no front/back mapping layer
is needed — ``Table.rename``/``limit_to`` give the same API shape with O(1)
cost.

Null handling: every column may carry a boolean validity ``mask`` (True =
valid).  TPUs have no NaN-for-int story and XLA wants uniform static buffers,
so masks are explicit companion arrays, unlike the reference's pandas nullable
dtypes (mappings.py:67-83).

Strings are dictionary-encoded at ingestion: ``data`` holds int32 codes into a
host-side numpy ``dictionary`` of unique values.  String kernels operate on
the (small) dictionary on host and on codes on device — the TPU never touches
variable-length bytes.  Code -1 is reserved for null strings' code slot (the
mask is still authoritative).
"""
from __future__ import annotations

import datetime
import itertools
from dataclasses import dataclass, replace
from typing import Any, Iterable, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from .types import (
    SqlType,
    BOOLEAN,
    DOUBLE,
    VARCHAR,
    NULLTYPE,
    physical_dtype,
    physical_to_python_value,
    python_value_to_physical,
    sql_type_from_numpy,
)


# ---------------------------------------------------------------------------
# Scalar
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class Scalar:
    """A typed SQL scalar in physical representation. ``value is None`` = NULL."""

    value: Any
    stype: SqlType

    @property
    def is_null(self) -> bool:
        return self.value is None

    def to_python(self):
        return physical_to_python_value(self.value, self.stype)


NULL = Scalar(None, NULLTYPE)


# ---------------------------------------------------------------------------
# Column
# ---------------------------------------------------------------------------

class Column:
    """One device column: jax data + optional validity mask + logical type."""

    __slots__ = ("data", "mask", "stype", "dictionary", "host_cache")

    def __init__(
        self,
        data: jax.Array,
        stype: SqlType,
        mask: Optional[jax.Array] = None,
        dictionary: Optional[np.ndarray] = None,
        host_cache: Optional[tuple] = None,
    ):
        self.data = data
        self.stype = stype
        self.mask = mask
        self.dictionary = dictionary
        # (np_data, np_mask_or_None): set when a host copy already exists
        # (e.g. the compiled executor's single-fetch materialization) so
        # to_numpy/to_pandas skip the device round trip
        self.host_cache = host_cache
        if stype.is_string and dictionary is None:
            raise ValueError("string columns require a dictionary")

    # -- constructors ------------------------------------------------------
    @staticmethod
    def from_numpy(values: np.ndarray, stype: Optional[SqlType] = None,
                   mask: Optional[np.ndarray] = None) -> "Column":
        data, m, st, dictionary = host_encode_numpy(values, stype, mask)
        return Column(jnp.asarray(data), st, _as_mask(m), dictionary)

    @staticmethod
    def _encode_strings(values: np.ndarray, mask: Optional[np.ndarray]) -> "Column":
        data, m, st, dictionary = _host_encode_strings(values, mask)
        return Column(jnp.asarray(data), st, _as_mask(m), dictionary)

    @staticmethod
    def from_scalar(scalar: Scalar, length: int) -> "Column":
        stype = scalar.stype
        if scalar.is_null:
            if stype.name == "NULL":
                stype = DOUBLE
            data = jnp.zeros(length, dtype=physical_dtype(stype))
            if stype.is_string:
                return Column(data.astype(jnp.int32), stype,
                              jnp.zeros(length, dtype=bool), np.array([""], dtype=object))
            return Column(data, stype, jnp.zeros(length, dtype=bool))
        if stype.is_string:
            return Column(jnp.zeros(length, dtype=jnp.int32), stype, None,
                          np.array([scalar.value], dtype=object))
        return Column(jnp.full(length, scalar.value, dtype=physical_dtype(stype)), stype, None)

    # -- basics ------------------------------------------------------------
    def __len__(self) -> int:
        return int(self.data.shape[0])

    @property
    def has_nulls(self) -> bool:
        return self.mask is not None

    def valid_mask(self) -> jax.Array:
        """Always-materialized validity mask."""
        if self.mask is None:
            return jnp.ones(self.data.shape[0], dtype=bool)
        return self.mask

    def null_count(self) -> int:
        if self.mask is None:
            return 0
        return int((~self.mask).sum())

    def _drop_allvalid_mask(self) -> "Column":
        """Materialization-boundary normalization: all-True mask -> None.

        Computation paths carry masks unconditionally (sync-free, traceable);
        only here, where the host is about to look at the data anyway, is the
        one-off ``mask.all()`` sync acceptable.
        """
        if self.mask is not None and bool(np.asarray(self.mask).all()):
            return Column(self.data, self.stype, None, self.dictionary)
        return self

    def with_mask(self, mask: Optional[jax.Array]) -> "Column":
        # no all-valid -> None normalization here: that would be a blocking
        # host sync per call (and a trace breaker under jit); materialization
        # (to_numpy) drops all-valid masks instead
        return Column(self.data, self.stype, mask, self.dictionary)

    def cast_data(self, data: jax.Array, stype: Optional[SqlType] = None) -> "Column":
        return Column(data, stype or self.stype, self.mask, self.dictionary)

    def take(self, indices: jax.Array) -> "Column":
        """Gather rows by position (device gather)."""
        data = jnp.take(self.data, indices, axis=0)
        mask = None if self.mask is None else jnp.take(self.mask, indices, axis=0)
        return Column(data, self.stype, mask, self.dictionary)

    def slice(self, start: int, stop: int) -> "Column":
        data = self.data[start:stop]
        mask = None if self.mask is None else self.mask[start:stop]
        return Column(data, self.stype, mask, self.dictionary)

    # -- dictionary helpers ------------------------------------------------
    def decode(self) -> np.ndarray:
        """Host numpy array of python objects (strings/None) for a string column."""
        assert self.stype.is_string
        codes = np.asarray(self.data)
        out = self.dictionary[np.clip(codes, 0, len(self.dictionary) - 1)]
        if self.mask is not None:
            out = out.copy()
            out[~np.asarray(self.mask)] = None
        return out

    def dict_ranks(self) -> "Column":
        """Map codes to sort-order ranks so ORDER BY / comparisons work on device.

        The dictionary produced at encode time is sorted (np.unique), but
        derived columns can have unsorted dictionaries — compute rank array on
        host (dictionary is small) and gather on device.
        """
        assert self.stype.is_string
        order = dict_sort_order(self.dictionary)
        ranks = np.empty(len(order), dtype=np.int32)
        ranks[order] = np.arange(len(order), dtype=np.int32)
        data = jnp.take(jnp.asarray(ranks), jnp.clip(self.data, 0, len(ranks) - 1))
        return Column(data, SqlType("INTEGER"), self.mask)

    # -- host conversion ---------------------------------------------------
    def to_numpy(self) -> np.ndarray:
        """Host representation with rich types; nulls become None/NaN/NaT."""
        if self.host_cache is not None:
            hd, hm = self.host_cache
            self = Column(hd, self.stype,
                          None if hm is None else hm, self.dictionary)
        self = self._drop_allvalid_mask()
        n = self.stype.name
        if self.stype.is_string:
            return self.decode()
        data = np.asarray(self.data)
        if n == "DATE":
            out = data.astype("datetime64[D]")
            if self.mask is not None:
                out[~np.asarray(self.mask)] = np.datetime64("NaT")
            return out
        if n in ("TIMESTAMP", "TIMESTAMP_WITH_LOCAL_TIME_ZONE"):
            out = data.astype("datetime64[us]")
            if self.mask is not None:
                out[~np.asarray(self.mask)] = np.datetime64("NaT")
            return out
        if n == "INTERVAL_DAY_TIME":
            out = data.astype("timedelta64[ms]")
            if self.mask is not None:
                out[~np.asarray(self.mask)] = np.timedelta64("NaT")
            return out
        if n == "TIME":
            from .types import physical_to_python_value
            vals = [physical_to_python_value(int(v), self.stype) for v in data.tolist()]
            out = np.array(vals, dtype=object)
            if self.mask is not None:
                out[~np.asarray(self.mask)] = None
            return out
        if self.mask is not None:
            if data.dtype.kind == "f":
                out = data.copy()
                out[~np.asarray(self.mask)] = np.nan
                return out
            # ints/bools with nulls -> object array with None
            out = data.astype(object)
            out[~np.asarray(self.mask)] = None
            return out
        return data

    def to_pylist(self) -> list:
        np_vals = self.to_numpy()
        out = []
        for v in np_vals.tolist():
            out.append(v)
        return out

    def __repr__(self):
        return f"Column({self.stype}, len={len(self)}, nulls={self.null_count()})"


def dict_sort_order(dictionary: np.ndarray) -> np.ndarray:
    """Dictionary indices in string sort order: order[rank] = dict index.

    The single source of truth for string collation — group ordering,
    MIN/MAX, and static-domain key decoding must all agree on it.
    """
    return np.argsort(dictionary.astype(str), kind="stable")


def _as_mask(mask) -> Optional[jax.Array]:
    if mask is None:
        return None
    mask = np.asarray(mask, dtype=bool)
    if mask.all():
        return None
    return jnp.asarray(mask)


# ---------------------------------------------------------------------------
# Table
# ---------------------------------------------------------------------------

class Table:
    """An ordered, named collection of equal-length Columns."""

    __slots__ = ("names", "columns", "uid")

    _uid_counter = itertools.count()

    def __init__(self, names: Sequence[str], columns: Sequence[Column]):
        assert len(names) == len(columns)
        self.names = list(names)
        self.columns = list(columns)
        # monotonic identity: unlike id(), never reused after GC — the
        # compiled-query cache keys on it (physical/compiled.py)
        self.uid = next(Table._uid_counter)

    # -- constructors ------------------------------------------------------
    @staticmethod
    def from_pandas(df) -> "Table":
        import pandas as pd

        names, cols = [], []
        for name in df.columns:
            s = df[name]
            names.append(str(name))
            cols.append(_series_to_column(s))
        return Table(names, cols)

    @staticmethod
    def from_pydict(data: dict) -> "Table":
        names, cols = [], []
        for k, v in data.items():
            names.append(k)
            if isinstance(v, Column):
                cols.append(v)
            else:
                arr = np.asarray(v) if not _has_none(v) else np.asarray(v, dtype=object)
                if arr.dtype.kind == "O" and not _all_strings(arr):
                    arr2, mask = _denull(v)
                    cols.append(Column.from_numpy(arr2, mask=mask))
                else:
                    cols.append(Column.from_numpy(arr))
        return Table(names, cols)

    # -- basics ------------------------------------------------------------
    @property
    def num_rows(self) -> int:
        if not self.columns:
            return 0
        return len(self.columns[0])

    @property
    def num_columns(self) -> int:
        return len(self.columns)

    def column(self, name: str) -> Column:
        return self.columns[self.names.index(name)]

    def __contains__(self, name: str) -> bool:
        return name in self.names

    def limit_to(self, names: Iterable[str]) -> "Table":
        """Project to a subset/reordering of columns (reference:
        datacontainer.py:53 ColumnContainer.limit_to) — O(1), no device work."""
        names = list(names)
        return Table(names, [self.column(n) for n in names])

    def rename(self, mapping: dict) -> "Table":
        return Table([mapping.get(n, n) for n in self.names], self.columns)

    def with_names(self, names: Sequence[str]) -> "Table":
        assert len(names) == len(self.columns)
        return Table(list(names), self.columns)

    def add_column(self, name: str, col: Column) -> "Table":
        return Table(self.names + [name], self.columns + [col])

    def take(self, indices: jax.Array) -> "Table":
        return Table(self.names, [c.take(indices) for c in self.columns])

    def slice(self, start: int, stop: int) -> "Table":
        return Table(self.names, [c.slice(start, stop) for c in self.columns])

    def head(self, n: int) -> "Table":
        return self.slice(0, min(n, self.num_rows))

    def schema(self) -> list:
        return list(zip(self.names, [c.stype for c in self.columns]))

    # -- host conversion ---------------------------------------------------
    def to_pandas(self):
        import pandas as pd

        # fetch every device buffer in ONE transfer: per-column np.asarray
        # would pay a tunnel round trip each over a remote TPU; columns with
        # a host cache (compiled-executor results) need no fetch at all
        buffers = []
        for col in self.columns:
            if col.host_cache is not None:
                continue
            buffers.append(col.data)
            if col.mask is not None:
                buffers.append(col.mask)
        fetched = iter(jax.device_get(buffers) if buffers else [])
        data = {}
        for name, col in zip(self.names, self.columns):
            if col.host_cache is not None:
                data[name] = col.to_numpy()
                continue
            host_data = next(fetched)
            host_mask = next(fetched) if col.mask is not None else None
            host_col = Column(host_data, col.stype, host_mask, col.dictionary)
            data[name] = host_col.to_numpy()
        df = pd.DataFrame(data, columns=list(self.names))
        return df

    def to_pylist(self) -> list:
        cols = [c.to_pylist() for c in self.columns]
        return [list(row) for row in zip(*cols)] if cols else []

    def __repr__(self):
        parts = ", ".join(f"{n}: {c.stype}" for n, c in zip(self.names, self.columns))
        return f"Table[{self.num_rows} rows]({parts})"


_PANDAS_NULLABLE_NUMPY = {
    "Int8": np.int8, "Int16": np.int16, "Int32": np.int32, "Int64": np.int64,
    "UInt8": np.uint8, "UInt16": np.uint16, "UInt32": np.uint32, "UInt64": np.uint64,
    "Float32": np.float32, "Float64": np.float64, "boolean": np.bool_,
}


def host_encode_numpy(values: np.ndarray, stype: Optional[SqlType] = None,
                      mask: Optional[np.ndarray] = None,
                      dictionary: Optional[np.ndarray] = None):
    """Ingestion encoding on HOST arrays: (data, mask, stype, dictionary).

    The single source of truth for ingestion semantics — `Column.from_numpy`
    is this plus a device upload, and the chunked/out-of-core reader
    (io/chunked.py) uses it directly so batches stay host-side until their
    turn to stream through the device. ``dictionary``: optional pre-built
    SORTED global dictionary for string columns (shared across batches so
    every batch compiles to the same program)."""
    values = np.asarray(values)
    if values.dtype.kind == "O" and (stype is None or not stype.is_string):
        import decimal as _decimal

        isna = np.array([v is None or (isinstance(v, float)
                                       and np.isnan(v)) for v in values])
        present = values[~isna]
        if len(present) and all(isinstance(v, _decimal.Decimal)
                                and v.is_finite() for v in present):
            # ALL-finite decimal.Decimal columns ingest as DECIMAL(p, s)
            # with p measured from the data: f64 storage + a typed scale, so
            # SUM/AVG take the exact scaled-int64 path when every value fits
            # the f64 mantissa exactly (types.exact_decimal_scale gates at
            # p<=15 since 10^15 < 2^53).  Mixed or non-finite object columns
            # keep the generic path.
            scale = 0
            int_digits = 1
            for v in present:
                t = v.as_tuple()
                scale = max(scale, -int(t.exponent))
                int_digits = max(int_digits, len(t.digits) + int(t.exponent))
            precision = int_digits + scale
            data = np.array([0.0 if na else float(v)
                             for v, na in zip(values, isna)], dtype=np.float64)
            m = (~isna if mask is None
                 else (np.asarray(mask, bool) & ~isna))
            if m.all():
                m = None
            from .types import decimal as _mk_decimal
            if scale > 9 or precision > 15:
                # outside the exact-int64/f64-mantissa envelope: typed
                # honestly (so the exact path declines), unquantized f64
                return data, m, _mk_decimal(max(precision, 16), scale), None
            return data, m, _mk_decimal(15, scale), None
    if stype is None:
        stype = sql_type_from_numpy(values.dtype)
    if values.dtype.kind in ("O", "U", "S") or stype.is_string:
        return _host_encode_strings(values, mask, dictionary)
    if values.dtype.kind == "M":
        vals = values.astype("datetime64[us]").astype(np.int64)
        na = np.isnat(values)
        if na.any():
            mask = ~na if mask is None else (mask & ~na)
        return vals, mask, stype, None
    if values.dtype.kind == "m":
        vals = values.astype("timedelta64[ms]").astype(np.int64)
        na = np.isnat(values)
        if na.any():
            mask = ~na if mask is None else (mask & ~na)
        return vals, mask, stype, None
    if values.dtype.kind == "f":
        # NaN means NULL on ingestion (pandas semantics: the reference's
        # dask frames treat NaN as missing, mappings.py:67-83)
        na = np.isnan(values)
        if na.any():
            mask = ~na if mask is None else (np.asarray(mask, bool) & ~na)
            values = np.where(na, 0.0, values)
    dtype = physical_dtype(stype)
    return values.astype(dtype, copy=False), mask, stype, None


def _decode_bytes_objects(values: np.ndarray) -> np.ndarray:
    """bytes values become str via utf-8/surrogateescape so binary columns
    behave as strings end to end (SQL literals are strings; repr-strings
    like \"b'aa'\" would leak otherwise).  Must be applied identically in
    the dictionary pass and the encode pass to stay self-consistent."""
    if any(isinstance(v, (bytes, bytearray)) for v in values):
        values = np.array(
            [v.decode("utf-8", "surrogateescape")
             if isinstance(v, (bytes, bytearray)) else v for v in values],
            dtype=object)
    return values


def string_uniques(values: np.ndarray) -> np.ndarray:
    """Sorted unique strings of an object array (NULLs -> \"\"), the shared
    null-semantics for ingestion and the chunked reader's dictionary pass."""
    values = _decode_bytes_objects(np.asarray(values, dtype=object))
    isna = np.array([v is None or (isinstance(v, float) and np.isnan(v))
                     for v in values])
    safe = np.where(isna, "", values).astype(str)
    return np.unique(safe).astype(object)


def _host_encode_strings(values: np.ndarray, mask: Optional[np.ndarray],
                         dictionary: Optional[np.ndarray] = None):
    values = _decode_bytes_objects(np.asarray(values, dtype=object))
    isna = np.array([v is None or (isinstance(v, float) and np.isnan(v)) for v in values])
    safe = np.where(isna, "", values).astype(str)
    if dictionary is None:
        dictionary, codes = np.unique(safe, return_inverse=True)
        dictionary = dictionary.astype(object)
    else:
        # shared global dictionary (sorted): encode via binary search.  The
        # two-pass chunked reader guarantees membership; verify anyway — an
        # absent value would silently take a neighbor's code otherwise.
        dict_str = dictionary.astype(str)
        codes = np.searchsorted(dict_str, safe)
        clipped = np.clip(codes, 0, len(dict_str) - 1)
        if not np.array_equal(dict_str[clipped], safe):
            missing = np.unique(safe[dict_str[clipped] != safe])[:5]
            raise ValueError(
                "string batch contains values absent from the shared "
                f"dictionary (first few: {missing.tolist()!r}); the "
                "dictionary pass missed this column's values")
        codes = clipped
    codes = codes.astype(np.int32)
    if isna.any():
        m = ~isna if mask is None else (np.asarray(mask, bool) & ~isna)
    else:
        m = mask
    return codes, m, VARCHAR, dictionary


def host_encode_series(s, dictionary: Optional[np.ndarray] = None):
    """Host-side encoding of a pandas Series: (data, mask, stype, dict)."""
    import pandas as pd

    dtype = s.dtype
    # pandas nullable extension dtypes (Int64, boolean, Float64, ...)
    if str(dtype) in _PANDAS_NULLABLE_NUMPY:
        arr = s.array
        mask = ~np.asarray(arr.isna())
        vals = arr.to_numpy(dtype=_PANDAS_NULLABLE_NUMPY[str(dtype)], na_value=0)
        return host_encode_numpy(vals, mask=mask if not mask.all() else None,
                                 dictionary=dictionary)
    if str(dtype) in ("string", "str") or (
        hasattr(pd, "StringDtype") and isinstance(dtype, pd.StringDtype)
    ):
        vals = s.to_numpy(dtype=object, na_value=None)
        return host_encode_numpy(vals, dictionary=dictionary)
    if isinstance(dtype, pd.CategoricalDtype):
        if dictionary is not None:
            # a shared global dictionary overrides the per-batch categories:
            # chunked sources must not mix batch-local codes with a global
            # dictionary (arrow row groups may carry differing categories)
            vals = s.astype(object).to_numpy()
            return host_encode_numpy(vals, dictionary=dictionary)
        cats = s.cat.categories.to_numpy(dtype=object)
        codes = s.cat.codes.to_numpy().astype(np.int32)
        mask = codes >= 0
        if mask.all():
            mask = None
        return np.where(codes < 0, 0, codes).astype(np.int32), mask, VARCHAR, cats
    if dtype.kind == "M":
        # tz-aware -> convert to UTC naive
        if getattr(dtype, "tz", None) is not None:
            s = s.dt.tz_convert("UTC").dt.tz_localize(None)
        return host_encode_numpy(s.to_numpy(), dictionary=dictionary)
    return host_encode_numpy(s.to_numpy(), dictionary=dictionary)


def _series_to_column(s) -> Column:
    data, mask, stype, dictionary = host_encode_series(s)
    return Column(jnp.asarray(data), stype, _as_mask(mask), dictionary)


def _has_none(v) -> bool:
    try:
        return any(x is None for x in v)
    except TypeError:
        return False


def _all_strings(arr) -> bool:
    return all(isinstance(x, str) for x in arr.tolist())


def _denull(v):
    vals = list(v)
    mask = np.array([x is not None for x in vals])
    if all(isinstance(x, str) or x is None for x in vals):
        arr = np.array(["" if x is None else x for x in vals], dtype=object)
        return arr, mask
    arr = np.array([0 if x is None else x for x in vals])
    return arr, mask
