"""Small bridge: run a bound query AST through plan+execute (used by ML
statements, which hold the inner SELECT as AST instead of re-stringifying it
the way the reference must, create_model.py:157-158)."""
from __future__ import annotations

from ..table import Table


def run_query(context, query_ast, sql: str) -> Table:
    from ..physical.rel.executor import RelExecutor

    plan = context._get_plan(query_ast, sql)
    return RelExecutor(context).execute(plan)
