"""Driver benchmark: groupby+join throughput through the SQL engine on TPU.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The workload is the BASELINE.md config set: TPC-H Q1 (heavy groupby), Q6 (scan
filter) and Q3 (join+groupby) over generated TPC-H data, run end-to-end
through Context.sql on the default JAX platform (the real TPU chip under the
driver; CPU elsewhere).  ``vs_baseline`` compares against pandas executing the
same queries on the same host (the reference's single-partition execution
substrate), as the reference publishes no numbers of its own (BASELINE.md).
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np
import pandas as pd


# SF0.3 puts ~1.8M lineitem rows on device: large enough that the
# TPU's compute advantage outweighs the per-query host-sync floor
SF = float(os.environ.get("BENCH_SF", "0.3"))
REPS = int(os.environ.get("BENCH_REPS", "3"))
PLATFORM_PROBE_TIMEOUT = float(os.environ.get("BENCH_PLATFORM_TIMEOUT", "180"))


def _ensure_usable_platform():
    """Pin JAX to a platform that actually initializes.

    The default platform may be a tunneled TPU whose backend init can hang
    indefinitely if the tunnel is down; probing in a subprocess with a timeout
    guarantees bench.py always emits its JSON line.  ``BENCH_PLATFORM``
    overrides the probe entirely.
    """
    import subprocess

    forced = os.environ.get("BENCH_PLATFORM")
    import jax

    if forced:
        jax.config.update("jax_platforms", forced)
        return forced
    try:
        probe = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=PLATFORM_PROBE_TIMEOUT, capture_output=True)
        if probe.returncode == 0:
            return None  # default platform is healthy
        sys.stderr.write(probe.stderr.decode(errors="replace")[-2000:])
    except subprocess.TimeoutExpired:
        pass
    print("bench: default JAX platform unusable; falling back to CPU",
          file=sys.stderr)
    jax.config.update("jax_platforms", "cpu")
    return "cpu"


def _pandas_q1(li: pd.DataFrame) -> float:
    t0 = time.perf_counter()
    d = li[li["l_shipdate"] <= pd.Timestamp("1998-09-02")].copy()
    d["disc_price"] = d["l_extendedprice"] * (1 - d["l_discount"])
    d["charge"] = d["disc_price"] * (1 + d["l_tax"])
    d.groupby(["l_returnflag", "l_linestatus"]).agg(
        sum_qty=("l_quantity", "sum"), sum_base_price=("l_extendedprice", "sum"),
        sum_disc_price=("disc_price", "sum"), sum_charge=("charge", "sum"),
        avg_qty=("l_quantity", "mean"), avg_price=("l_extendedprice", "mean"),
        avg_disc=("l_discount", "mean"), count_order=("l_quantity", "count"),
    ).reset_index().sort_values(["l_returnflag", "l_linestatus"])
    return time.perf_counter() - t0


def _pandas_q6(li: pd.DataFrame) -> float:
    t0 = time.perf_counter()
    d = li[(li["l_shipdate"] >= pd.Timestamp("1994-01-01"))
           & (li["l_shipdate"] < pd.Timestamp("1995-01-01"))
           & (li["l_discount"].between(0.05, 0.07))
           & (li["l_quantity"] < 24)]
    (d["l_extendedprice"] * d["l_discount"]).sum()
    return time.perf_counter() - t0


def _pandas_q3(cu, od, li) -> float:
    t0 = time.perf_counter()
    c = cu[cu["c_mktsegment"] == "BUILDING"]
    o = od[od["o_orderdate"] < pd.Timestamp("1995-03-15")]
    l = li[li["l_shipdate"] > pd.Timestamp("1995-03-15")]
    m = c.merge(o, left_on="c_custkey", right_on="o_custkey").merge(
        l, left_on="o_orderkey", right_on="l_orderkey")
    m["revenue"] = m["l_extendedprice"] * (1 - m["l_discount"])
    m.groupby(["l_orderkey", "o_orderdate", "o_shippriority"])["revenue"].sum() \
        .reset_index().nlargest(10, "revenue")
    return time.perf_counter() - t0


def main():
    _ensure_usable_platform()
    # NOTE: no persistent compilation cache here — AOT deserialization is
    # not reliable on the tunneled TPU backend (FAILED_PRECONDITION at
    # execution time); compiles happen in-process per run.
    from benchmarks.tpch import QUERIES, generate_tpch
    from dask_sql_tpu import Context

    data = generate_tpch(SF)
    n_lineitem = len(data["lineitem"])

    c = Context()
    for name, frame in data.items():
        c.create_table(name, frame)

    queries = {1: QUERIES[1], 6: QUERIES[6], 3: QUERIES[3]}

    import jax

    # warmup (compilation) then measure
    for q in queries.values():
        c.sql(q)
    times = {}
    for qid, q in queries.items():
        best = float("inf")
        for _ in range(REPS):
            t0 = time.perf_counter()
            # end-to-end: SQL text to host pandas frame (matches what the
            # pandas baseline below measures); small results ride the
            # compiled executor's single-fetch host cache
            c.sql(q, return_futures=False)
            best = min(best, time.perf_counter() - t0)
        times[qid] = best

    # pandas baseline (single-threaded host — the reference's per-partition
    # execution substrate)
    li, cu, od = data["lineitem"], data["customer"], data["orders"]
    p_times = {1: min(_pandas_q1(li) for _ in range(REPS)),
               6: min(_pandas_q6(li) for _ in range(REPS)),
               3: min(_pandas_q3(cu, od, li) for _ in range(REPS))}

    total = sum(times.values())
    rows_processed = 3 * n_lineitem  # each query scans lineitem once
    throughput = rows_processed / total
    pandas_total = sum(p_times.values())
    vs_baseline = pandas_total / total  # >1 = faster than baseline

    print(json.dumps({
        "metric": "tpch_q1_q3_q6_groupby_join_throughput",
        "value": round(throughput, 1),
        "unit": "rows/sec/chip",
        "vs_baseline": round(vs_baseline, 3),
        "detail": {
            "sf": SF, "lineitem_rows": n_lineitem,
            "engine_sec": {str(k): round(v, 4) for k, v in times.items()},
            "pandas_sec": {str(k): round(v, 4) for k, v in p_times.items()},
        },
    }))


def _run_with_watchdog():
    """Run the benchmark in a child with a hard deadline.

    The tunneled TPU can wedge mid-run (observed: 90+ minutes of silence
    with no exception); the platform probe only guards initialization. The
    parent re-runs on CPU if the child misses the deadline or dies without
    emitting the JSON line, so this script ALWAYS prints its metric.
    """
    import subprocess

    deadline = float(os.environ.get("BENCH_RUN_TIMEOUT", "1800"))
    env = dict(os.environ, BENCH_CHILD="1")
    try:
        proc = subprocess.run([sys.executable, os.path.abspath(__file__)],
                              env=env, timeout=deadline,
                              capture_output=True, text=True)
        out = proc.stdout
    except subprocess.TimeoutExpired as e:
        print(f"bench: TPU run exceeded {deadline}s; falling back to CPU",
              file=sys.stderr)
        out = ""
    if '"metric"' in out:
        sys.stdout.write(out)
        return
    env = dict(os.environ, BENCH_CHILD="1", BENCH_PLATFORM="cpu")
    proc = subprocess.run([sys.executable, os.path.abspath(__file__)],
                          env=env, timeout=deadline, capture_output=True,
                          text=True)
    sys.stdout.write(proc.stdout)
    if '"metric"' not in proc.stdout:
        sys.stderr.write(proc.stderr[-2000:])
        raise SystemExit(1)


if __name__ == "__main__":
    if os.environ.get("BENCH_CHILD") == "1":
        main()
    else:
        _run_with_watchdog()
