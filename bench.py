"""Driver benchmark: all 22 TPC-H queries through the SQL engine on TPU.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "detail": {...}}

The workload is the BASELINE.md primary metric: the Q1-Q22 geomean wall-clock
over generated TPC-H data, end-to-end through Context.sql (SQL text to host
pandas frame).  ``vs_baseline`` is the geomean speedup against single-threaded
pandas executing hand-written implementations of the same 22 queries on the
same host (benchmarks/pandas_tpch.py) — the reference's single-partition
execution substrate IS pandas, and BASELINE.md publishes no absolute numbers.
``detail`` records the platform the engine actually ran on, per-query times,
and device-memory stats, so the result can't silently hide a CPU fallback.
"""
import json
import math
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


SF = float(os.environ.get("BENCH_SF", "1.0"))
REPS = int(os.environ.get("BENCH_REPS", "3"))
# SAME rep count for the baseline by default: best-of-3 engine vs a single
# cold pandas sample would systematically inflate vs_baseline
PANDAS_REPS = int(os.environ.get("BENCH_PANDAS_REPS", str(REPS)))
WARMUP_THREADS = int(os.environ.get("BENCH_WARMUP_THREADS", "8"))
PLATFORM_PROBE_TIMEOUT = float(os.environ.get("BENCH_PLATFORM_TIMEOUT", "180"))


def _ensure_usable_platform():
    """Pin JAX to a platform that actually initializes.

    The default platform may be a tunneled TPU whose backend init can hang
    indefinitely if the tunnel is down; probing in a subprocess with a timeout
    guarantees bench.py always emits its JSON line.  ``BENCH_PLATFORM``
    overrides the probe entirely.
    """
    import subprocess

    forced = os.environ.get("BENCH_PLATFORM")
    import jax

    if forced:
        jax.config.update("jax_platforms", forced)
        return forced
    try:
        probe = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=PLATFORM_PROBE_TIMEOUT, capture_output=True)
        if probe.returncode == 0:
            return None  # default platform is healthy
        sys.stderr.write(probe.stderr.decode(errors="replace")[-2000:])
    except subprocess.TimeoutExpired:
        pass
    print("bench: default JAX platform unusable; falling back to CPU",
          file=sys.stderr)
    jax.config.update("jax_platforms", "cpu")
    return "cpu"


def _geomean(xs):
    return math.exp(sum(math.log(x) for x in xs) / len(xs))


def main():
    forced_cpu = _ensure_usable_platform() == "cpu"
    # NOTE: no persistent compilation cache here — AOT deserialization is
    # not reliable on the tunneled TPU backend (FAILED_PRECONDITION at
    # execution time); compiles happen in-process per run.
    from benchmarks.tpch import QUERIES, generate_tpch
    from benchmarks.pandas_tpch import PANDAS_QUERIES
    from dask_sql_tpu import Context

    global SF
    if forced_cpu and "BENCH_SF" not in os.environ:
        # tunnel-down fallback: the engine is TPU-first and the host has one
        # core — a smaller SF keeps the fallback inside the watchdog while
        # still covering all 22 queries (platform is recorded either way)
        SF = float(os.environ.get("BENCH_FALLBACK_SF", "0.1"))

    t0 = time.perf_counter()
    data = generate_tpch(SF)
    gen_sec = time.perf_counter() - t0
    n_lineitem = len(data["lineitem"])

    t0 = time.perf_counter()
    c = Context()
    for name, frame in data.items():
        c.create_table(name, frame)
    load_sec = time.perf_counter() - t0

    import jax
    platform = jax.devices()[0].platform

    qids = sorted(QUERIES)
    only = os.environ.get("BENCH_QUERIES")
    if only:
        qids = [int(x) for x in only.split(",")]

    # warmup = compilation. Compiles overlap across threads (tracing holds
    # the GIL but the XLA backend compile releases it), which matters on the
    # tunneled TPU where a single compile is minutes.
    t0 = time.perf_counter()
    if WARMUP_THREADS > 1 and len(qids) > 1:
        from concurrent.futures import ThreadPoolExecutor
        with ThreadPoolExecutor(min(WARMUP_THREADS, len(qids))) as pool:
            list(pool.map(lambda q: c.sql(QUERIES[q], return_futures=False),
                          qids))
    else:
        for q in qids:
            c.sql(QUERIES[q], return_futures=False)
    warmup_sec = time.perf_counter() - t0

    times = {}
    for qid in qids:
        best = float("inf")
        for _ in range(REPS):
            t0 = time.perf_counter()
            # end-to-end: SQL text to host pandas frame (matches what the
            # pandas baseline below measures); small results ride the
            # compiled executor's single-fetch host cache
            c.sql(QUERIES[qid], return_futures=False)
            best = min(best, time.perf_counter() - t0)
        times[qid] = best

    # pandas baseline (single-threaded host — the reference's per-partition
    # execution substrate), hand-written per query, oracle-validated against
    # the engine in tests/integration/test_pandas_oracle.py
    p_times = {}
    for qid in qids:
        best = float("inf")
        for _ in range(PANDAS_REPS):
            t0 = time.perf_counter()
            PANDAS_QUERIES[qid](data)
            best = min(best, time.perf_counter() - t0)
        p_times[qid] = best

    geo_e = _geomean(list(times.values()))
    geo_p = _geomean(list(p_times.values()))
    wins = sum(1 for q in qids if times[q] < p_times[q])

    mem = {}
    try:
        stats = jax.local_devices()[0].memory_stats() or {}
        for k in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
            if k in stats:
                mem[k] = int(stats[k])
    except Exception:
        pass

    from dask_sql_tpu.physical import compiled

    print(json.dumps({
        "metric": "tpch_q1_q22_geomean_wall",
        "value": round(geo_e, 4),
        "unit": "s (geomean over 22 queries, lower is better)",
        "vs_baseline": round(geo_p / geo_e, 3),
        "detail": {
            "sf": SF,
            "platform": platform,
            "lineitem_rows": n_lineitem,
            "queries": len(qids),
            "engine_wins": wins,
            "engine_sec": {str(k): round(v, 4) for k, v in times.items()},
            "pandas_sec": {str(k): round(v, 4) for k, v in p_times.items()},
            "pandas_geomean_sec": round(geo_p, 4),
            "gen_sec": round(gen_sec, 1),
            "load_sec": round(load_sec, 1),
            "warmup_compile_sec": round(warmup_sec, 1),
            "compiled_stats": dict(compiled.stats),
            "device_memory": mem,
        },
    }))


def _run_with_watchdog():
    """Run the benchmark in a child with a hard deadline.

    The tunneled TPU can wedge mid-run (observed: 90+ minutes of silence
    with no exception); the platform probe only guards initialization. The
    parent re-runs on CPU if the child misses the deadline or dies without
    emitting the JSON line, so this script ALWAYS prints its metric.
    """
    import subprocess

    deadline = float(os.environ.get("BENCH_RUN_TIMEOUT", "3000"))
    env = dict(os.environ, BENCH_CHILD="1")
    try:
        proc = subprocess.run([sys.executable, os.path.abspath(__file__)],
                              env=env, timeout=deadline,
                              capture_output=True, text=True)
        out = proc.stdout
        if '"metric"' not in out:
            sys.stderr.write(proc.stderr[-3000:])
    except subprocess.TimeoutExpired:
        print(f"bench: TPU run exceeded {deadline}s; falling back to CPU",
              file=sys.stderr)
        out = ""
    if '"metric"' in out:
        sys.stdout.write(out)
        return
    env = dict(os.environ, BENCH_CHILD="1", BENCH_PLATFORM="cpu")
    # the CPU rerun after a TPU timeout must itself fit the deadline
    env.setdefault("BENCH_SF", os.environ.get("BENCH_FALLBACK_SF", "0.1"))
    proc = subprocess.run([sys.executable, os.path.abspath(__file__)],
                          env=env, timeout=deadline, capture_output=True,
                          text=True)
    sys.stdout.write(proc.stdout)
    if '"metric"' not in proc.stdout:
        sys.stderr.write(proc.stderr[-2000:])
        raise SystemExit(1)


if __name__ == "__main__":
    if os.environ.get("BENCH_CHILD") == "1":
        main()
    else:
        _run_with_watchdog()
