"""Driver benchmark: all 22 TPC-H queries through the SQL engine on TPU.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "detail": {...}}

The workload is the BASELINE.md primary metric: the Q1-Q22 geomean wall-clock
over generated TPC-H data, end-to-end through Context.sql (SQL text to host
pandas frame).  ``vs_baseline`` is the geomean speedup against single-threaded
pandas executing hand-written implementations of the same 22 queries on the
same host (benchmarks/pandas_tpch.py) — the reference's single-partition
execution substrate IS pandas, and BASELINE.md publishes no absolute numbers.

Resilience design (the tunneled TPU can hang at init for 25+ minutes or
wedge mid-run with no exception — both observed):

- the platform probe runs in a subprocess with a timeout, RETRIES once,
  and falls back to CPU only after both attempts fail;
- queries run in STAGES, each stage a separate child process with its own
  slice of the remaining time budget, cheap-compile/high-value queries
  first; each completed query is written to a progress file immediately,
  so a wedge loses at most the rest of one stage and partial TPU numbers
  are always recorded;
- generated data is cached on disk (feather) once and memory-mapped by
  every stage child, so per-stage process isolation does not re-pay
  generation.

``detail`` records the platform each query actually ran on, per-query
times, compile stats, and device-memory stats, so the result can't
silently hide a CPU fallback or a partial run.
"""
import json
import math
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

SF = float(os.environ.get("BENCH_SF", "1.0"))
REPS = int(os.environ.get("BENCH_REPS", "3"))
# SAME rep count for the baseline by default: best-of-3 engine vs a single
# cold pandas sample would systematically inflate vs_baseline
PANDAS_REPS = int(os.environ.get("BENCH_PANDAS_REPS", str(REPS)))
WARMUP_THREADS = int(os.environ.get("BENCH_WARMUP_THREADS", "8"))
PLATFORM_PROBE_TIMEOUT = float(os.environ.get("BENCH_PLATFORM_TIMEOUT", "150"))
TOTAL_BUDGET = float(os.environ.get("BENCH_RUN_TIMEOUT", "2800"))

# stage order: cheap compiles + headline queries first, so a wedge later
# still leaves a meaningful recorded subset
STAGES = [
    [6, 1, 3, 12, 14, 19],
    [4, 5, 10, 15, 20, 22],
    [2, 11, 13, 16, 17, 18],
    [7, 8, 9, 21],
]


def _stages_covering(all_qids):
    """STAGES plus an overflow stage for any query id not hardcoded above —
    a query added to benchmarks.tpch.QUERIES is never silently dropped."""
    listed = {q for s in STAGES for q in s}
    extra = sorted(q for q in all_qids if q not in listed)
    stages = [list(s) for s in STAGES] + ([extra] if extra else [])
    return [[q for q in s if q in all_qids] for s in stages]


def _geomean(xs):
    return math.exp(sum(math.log(x) for x in xs) / len(xs))


def _probe_platform():
    """Decide the platform once, in the parent.  Returns "default" when the
    image's default (the tunneled TPU) initializes, else "cpu"."""
    import subprocess

    forced = os.environ.get("BENCH_PLATFORM")
    if forced:
        return forced
    for attempt in (1, 2):
        try:
            probe = subprocess.run(
                [sys.executable, "-c", "import jax; jax.devices()"],
                timeout=PLATFORM_PROBE_TIMEOUT, capture_output=True)
            if probe.returncode == 0:
                return "default"
            sys.stderr.write(probe.stderr.decode(errors="replace")[-1500:])
        except subprocess.TimeoutExpired:
            print(f"bench: platform probe attempt {attempt} timed out "
                  f"after {PLATFORM_PROBE_TIMEOUT}s", file=sys.stderr)
    print("bench: default JAX platform unusable; falling back to CPU",
          file=sys.stderr)
    return "cpu"


def _cache_data(sf: float, cache_dir: str):
    from benchmarks.tpch import generate_tpch

    t0 = time.perf_counter()
    data = generate_tpch(sf)
    for name, frame in data.items():
        frame.to_feather(os.path.join(cache_dir, f"{name}.feather"))
    return time.perf_counter() - t0, len(data["lineitem"])


def _load_data(cache_dir: str):
    import pandas as pd

    data = {}
    for fn in os.listdir(cache_dir):
        if fn.endswith(".feather"):
            data[fn[:-8]] = pd.read_feather(os.path.join(cache_dir, fn))
    return data


def _stage_main():
    """Child: run BENCH_STAGE_QUERIES against the cached data, appending one
    JSON line per completed query to the progress file."""
    platform = os.environ.get("BENCH_PLATFORM_CHOICE", "default")
    import jax

    if platform != "default":
        jax.config.update("jax_platforms", platform)
    from benchmarks.tpch import QUERIES
    from dask_sql_tpu import Context

    qids = [int(x) for x in os.environ["BENCH_STAGE_QUERIES"].split(",")]
    progress_path = os.environ["BENCH_PROGRESS"]
    data = _load_data(os.environ["BENCH_DATA_DIR"])

    c = Context()
    t0 = time.perf_counter()
    for name, frame in data.items():
        c.create_table(name, frame)
    load_sec = time.perf_counter() - t0
    real_platform = jax.devices()[0].platform

    def emit(rec):
        with open(progress_path, "a") as f:
            f.write(json.dumps(rec) + "\n")
            f.flush()

    # warmup = compilation; compiles overlap across threads (tracing holds
    # the GIL but the backend compile releases it), which matters on the
    # tunneled TPU where a single compile can take minutes
    t0 = time.perf_counter()
    if WARMUP_THREADS > 1 and len(qids) > 1:
        from concurrent.futures import ThreadPoolExecutor
        with ThreadPoolExecutor(min(WARMUP_THREADS, len(qids))) as pool:
            list(pool.map(lambda q: c.sql(QUERIES[q], return_futures=False),
                          qids))
    else:
        for q in qids:
            c.sql(QUERIES[q], return_futures=False)
    warmup_sec = time.perf_counter() - t0

    from dask_sql_tpu.physical import compiled

    for qid in qids:
        best = float("inf")
        for _ in range(REPS):
            t0 = time.perf_counter()
            # end-to-end: SQL text to host pandas frame (matches what the
            # pandas baseline measures)
            c.sql(QUERIES[qid], return_futures=False)
            best = min(best, time.perf_counter() - t0)
        emit({"q": qid, "sec": round(best, 4), "platform": real_platform})

    mem = {}
    try:
        stats = jax.local_devices()[0].memory_stats() or {}
        for k in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
            if k in stats:
                mem[k] = int(stats[k])
    except Exception:
        pass
    # the axon backend exposes no allocator stats; account for at least the
    # resident table arrays so device_memory is never silently empty
    try:
        tbl_bytes = 0
        for entry in c.schema[c.schema_name].tables.values():
            tbl = getattr(entry, "table", None)
            for col in getattr(tbl, "columns", []):
                tbl_bytes += int(col.data.nbytes)
                if col.mask is not None:
                    tbl_bytes += int(col.mask.nbytes)
        mem.setdefault("table_bytes_resident", tbl_bytes)
    except Exception:
        pass
    emit({"stage_done": True, "load_sec": round(load_sec, 1),
          "warmup_sec": round(warmup_sec, 1), "device_memory": mem,
          "compiled_stats": dict(compiled.stats)})


def main():
    import subprocess

    t_start = time.perf_counter()
    platform = _probe_platform()
    if platform == "cpu" and "BENCH_SF" not in os.environ:
        # tunnel-down fallback: the engine is TPU-first and the host may
        # have one core — a smaller SF keeps the fallback inside the
        # watchdog while still covering all 22 queries (platform is
        # recorded either way)
        sf = float(os.environ.get("BENCH_FALLBACK_SF", "0.1"))
    else:
        sf = SF

    workdir = os.environ.get("BENCH_WORKDIR") or tempfile.mkdtemp(
        prefix="bench_tpch_")
    data_dir = os.path.join(workdir, "data")
    os.makedirs(data_dir, exist_ok=True)
    progress = os.path.join(workdir, "progress.jsonl")
    open(progress, "w").close()
    gen_sec, n_lineitem = _cache_data(sf, data_dir)

    from benchmarks.tpch import QUERIES
    qids = sorted(QUERIES)
    only = os.environ.get("BENCH_QUERIES")
    if only:
        only_set = {int(x) for x in only.split(",")}
        qids = [q for q in qids if q in only_set]
    stages = [s for s in _stages_covering(qids) if s]

    def run_stages(platform_choice, stage_lists, stage_data_dir,
                   budget_end):
        stage_meta = []
        # STABLE (cross-invocation) compile + caps caches: an XLA program
        # costs ~40-200 s to compile over the tunneled TPU but loads from
        # the persistent cache in ~0.3 s, and a capacity-escalation
        # recompile learned once should never be paid again — so a repeat
        # bench run (or one primed by an earlier run on the same host)
        # skips straight to steady state.  Cold runs still work: the
        # stage layout records partial results as compiles land.
        uid = os.getuid() if hasattr(os, "getuid") else 0
        cache_root = os.path.join(
            tempfile.gettempdir(),
            f"dsql_bench_cache_{platform_choice}_u{uid}")
        os.makedirs(cache_root, mode=0o700, exist_ok=True)
        if hasattr(os, "getuid") and os.stat(cache_root).st_uid != uid:
            # someone else pre-created the path: don't trust (or feed) a
            # foreign program cache — fall back to a private dir
            cache_root = tempfile.mkdtemp(prefix="dsql_bench_cache_")
        env_base = dict(os.environ, BENCH_STAGE="1",
                        BENCH_DATA_DIR=stage_data_dir,
                        BENCH_PROGRESS=progress,
                        BENCH_PLATFORM_CHOICE=platform_choice,
                        BENCH_SF=str(sf))
        env_base.setdefault("DSQL_XLA_CACHE",
                            os.path.join(cache_root, "xla"))
        env_base.setdefault("DSQL_CAPS_FILE",
                            os.path.join(cache_root, "caps.json"))
        for i, stage in enumerate(stage_lists):
            remaining = budget_end - time.perf_counter()
            if remaining < 60:
                print(f"bench: budget exhausted before stage {i}",
                      file=sys.stderr)
                stage_meta.append({"stage": i, "error": "budget"})
                continue
            # even split of what's left over the remaining stages
            slice_s = remaining / (len(stage_lists) - i)
            env = dict(env_base,
                       BENCH_STAGE_QUERIES=",".join(map(str, stage)))
            try:
                proc = subprocess.run(
                    [sys.executable, os.path.abspath(__file__)],
                    env=env, timeout=slice_s, capture_output=True, text=True)
                if proc.returncode != 0:
                    sys.stderr.write(proc.stderr[-2000:])
                    stage_meta.append({"stage": i,
                                       "error": f"rc={proc.returncode}"})
            except subprocess.TimeoutExpired:
                print(f"bench: stage {i} ({stage}) exceeded its "
                      f"{slice_s:.0f}s slice; moving on with partial "
                      "results", file=sys.stderr)
                stage_meta.append({"stage": i, "error": "timeout"})
        return stage_meta

    def collect():
        times, platforms, mem, cstats = {}, set(), {}, {}
        load_sec = warmup_sec = 0.0
        with open(progress) as f:
            for line in f:
                rec = json.loads(line)
                if "q" in rec:
                    times[rec["q"]] = rec["sec"]
                    platforms.add(rec["platform"])
                elif rec.get("stage_done"):
                    load_sec += rec.get("load_sec", 0)
                    warmup_sec += rec.get("warmup_sec", 0)
                    for k, v in (rec.get("device_memory") or {}).items():
                        mem[k] = max(mem.get(k, 0), v)
                    for k, v in (rec.get("compiled_stats") or {}).items():
                        cstats[k] = cstats.get(k, 0) + v
        return times, platforms, mem, cstats, load_sec, warmup_sec

    stage_meta = run_stages(platform, stages, data_dir,
                            t_start + TOTAL_BUDGET)
    times, platforms, mem, cstats, load_sec, warmup_sec = collect()
    if not times and platform == "default":
        # the tunnel wedged past the probe: salvage the round on CPU at the
        # fallback scale factor with its OWN budget rather than record
        # nothing (the TPU-scale data on a small host would just re-wedge)
        print("bench: no TPU queries completed; rerunning stages on CPU",
              file=sys.stderr)
        sf = float(os.environ.get("BENCH_FALLBACK_SF", "0.1"))
        salvage_dir = os.path.join(workdir, "data_salvage")
        os.makedirs(salvage_dir, exist_ok=True)
        gen2, n_lineitem = _cache_data(sf, salvage_dir)
        gen_sec += gen2
        data_dir = salvage_dir
        salvage = float(os.environ.get("BENCH_SALVAGE_TIMEOUT", "600"))
        stage_meta += run_stages("cpu", stages, salvage_dir,
                                 time.perf_counter() + salvage)
        times, platforms, mem, cstats, load_sec, warmup_sec = collect()

    done = sorted(times)
    missing = [q for q in qids if q not in times]
    if not done:
        print(json.dumps({"metric": "tpch_q1_q22_geomean_wall", "value": -1,
                          "unit": "s", "vs_baseline": 0,
                          "detail": {"error": "no queries completed",
                                     "stages": stage_meta}}))
        return

    # pandas baseline (single-threaded host — the reference's per-partition
    # execution substrate), hand-written per query, oracle-validated against
    # the engine in tests/integration/test_pandas_oracle.py
    from benchmarks.pandas_tpch import PANDAS_QUERIES
    data = _load_data(data_dir)
    p_times = {}
    # the baseline gets a HARD deadline so the metric line always appears
    # even when the engine stages consumed the whole budget: past it, no
    # further baseline query starts, and vs_baseline covers the subset
    p_deadline = time.perf_counter() + float(
        os.environ.get("BENCH_PANDAS_TIMEOUT", "600"))
    for qid in done:
        if time.perf_counter() > p_deadline:
            break
        fn = PANDAS_QUERIES.get(qid)
        if fn is None:
            continue  # engine-only query: vs_baseline covers `based` anyway
        best = float("inf")
        for _ in range(PANDAS_REPS):
            t0 = time.perf_counter()
            fn(data)
            best = min(best, time.perf_counter() - t0)
            if time.perf_counter() > p_deadline:
                break
        p_times[qid] = best

    geo_e = _geomean([times[q] for q in done])
    based = [q for q in done if q in p_times]
    geo_p = _geomean([p_times[q] for q in based]) if based else 0.0
    ratio = (_geomean([p_times[q] / times[q] for q in based])
             if based else 0.0)
    wins = sum(1 for q in based if times[q] < p_times[q])

    print(json.dumps({
        "metric": "tpch_q1_q22_geomean_wall",
        "value": round(geo_e, 4),
        "unit": "s (geomean over completed queries, lower is better)",
        "vs_baseline": round(ratio, 3),
        "detail": {
            "sf": sf,
            "platform": "/".join(sorted(platforms)),
            "lineitem_rows": n_lineitem,
            "queries": len(done),
            "missing_queries": missing,
            "stage_errors": stage_meta,
            "engine_wins": wins,
            "engine_sec": {str(k): round(times[k], 4) for k in done},
            "pandas_sec": {str(k): round(p_times[k], 4) for k in based},
            "pandas_geomean_sec": round(geo_p, 4),
            "gen_sec": round(gen_sec, 1),
            "load_sec": round(load_sec, 1),
            "warmup_compile_sec": round(warmup_sec, 1),
            "compiled_stats": cstats,
            "device_memory": mem,
        },
    }))


if __name__ == "__main__":
    if os.environ.get("BENCH_STAGE") == "1":
        _stage_main()
    else:
        main()
