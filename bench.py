"""Driver benchmark: all 22 TPC-H queries through the SQL engine on TPU.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "detail": {...}}

The line is printed twice — once bare (legacy parsers) and once behind the
``DSQL_BENCH_RESULT `` sentinel prefix on its own line — and written to
``bench_result.json`` in the work dir (override: ``BENCH_RESULTS_FILE``):
interleaved ANSI/log output mangled the bare line in r05 ("parsed": null),
and a sentinel + file artifact survive any amount of log noise.

The workload is the BASELINE.md primary metric: the Q1-Q22 geomean wall-clock
over generated TPC-H data, end-to-end through Context.sql (SQL text to host
pandas frame).  ``vs_baseline`` is the geomean speedup against single-threaded
pandas executing hand-written implementations of the same 22 queries on the
same host (benchmarks/pandas_tpch.py) — the reference's single-partition
execution substrate IS pandas, and BASELINE.md publishes no absolute numbers.

Budget design (round 5 — round 4 set the budget ABOVE the driver's observed
~1800 s kill and was SIGTERMed mid-run: the partial emitted, but 6 queries,
the compiled stats and the quiesced re-measure were lost.  The budget must
fit inside the driver's window, not test it):

- ONE absolute deadline is computed at entry (``BENCH_RUN_TIMEOUT``, default
  1700 s — conservatively inside the driver's observed ~1800 s kill window);
- the pandas baseline runs FIRST (it is cheap and cannot wedge), so engine
  trouble can never erase the comparison;
- engine queries run in ONE child process (the SF1 host->device transfer over
  the tunneled TPU costs ~2 min, so per-stage process isolation would pay it
  repeatedly); the child journals every completed query to a progress file
  and retires itself at its own deadline, and the parent restarts a child on
  the remaining queries only while enough budget remains;
- emission is structurally guaranteed: a watchdog thread fires just before
  the deadline, SIGTERM/SIGINT are handled, and an atexit hook is the last
  resort — all funnel into one idempotent emitter that reads the progress
  journal, so being killed mid-run still yields a parsed partial result.

Compile latency (40-200 s/program cold over the tunneled TPU) is managed by
the persistent XLA cache + learned-caps file under a STABLE path, so a bench
run primed by an earlier run on the same host loads programs in ~0.3 s.
``detail`` records the platform each query ran on, per-query times, compile
stats and cold/warm cache evidence, so the result can't silently hide a CPU
fallback or a partial run.
"""
import atexit
import json
import math
import os
import signal
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

SF = float(os.environ.get("BENCH_SF", "1.0"))
REPS = int(os.environ.get("BENCH_REPS", "3"))
# SAME rep count for the baseline by default: best-of-3 engine vs a single
# cold pandas sample would systematically inflate vs_baseline
PANDAS_REPS = int(os.environ.get("BENCH_PANDAS_REPS", str(REPS)))
WARMUP_THREADS = int(os.environ.get("BENCH_WARMUP_THREADS", "8"))
PLATFORM_PROBE_TIMEOUT = float(os.environ.get("BENCH_PLATFORM_TIMEOUT", "120"))
# the watchdog + SIGTERM handler guarantee the metric line even when the
# caller kills first — but a SIGTERM partial LOSES the stage_done record
# (compiled stats, device memory) and the quiesced re-measure, so the
# budget must finish INSIDE the driver's observed ~1800 s kill window
TOTAL_BUDGET = float(os.environ.get("BENCH_RUN_TIMEOUT", "1700"))
PANDAS_BUDGET = float(os.environ.get("BENCH_PANDAS_TIMEOUT", "420"))
EMIT_MARGIN = float(os.environ.get("BENCH_EMIT_MARGIN", "25"))
# minimum budget worth starting an engine child with: one table transfer
# (~130 s at SF1 over the tunnel) plus at least one compile+measure
MIN_CHILD_BUDGET = float(os.environ.get("BENCH_MIN_CHILD_BUDGET", "240"))

# priority order: cheap compiles + headline queries first, so an engine child
# that dies mid-run still leaves the most meaningful recorded subset
PRIORITY = [6, 1, 3, 12, 14, 19, 4, 5, 10, 15, 20, 22,
            2, 11, 13, 16, 17, 18, 7, 8, 9, 21]


def _order(all_qids):
    """PRIORITY first, then any query id not hardcoded above — a query added
    to benchmarks.tpch.QUERIES is never silently dropped."""
    extra = sorted(q for q in all_qids if q not in PRIORITY)
    return [q for q in PRIORITY if q in all_qids] + extra


def _geomean(xs):
    return math.exp(sum(math.log(x) for x in xs) / len(xs))


def _pctile(xs, q):
    """Nearest-rank percentile of a non-empty list."""
    s = sorted(xs)
    return s[min(int(round(q / 100.0 * (len(s) - 1))), len(s) - 1)]


def _probe_platform():
    """Decide the platform once, in the parent.  Returns "default" when the
    image's default (the tunneled TPU) initializes, else "cpu"."""
    import subprocess

    forced = os.environ.get("BENCH_PLATFORM")
    if forced:
        return forced
    for attempt in (1, 2):
        try:
            probe = subprocess.run(
                [sys.executable, "-c", "import jax; jax.devices()"],
                timeout=PLATFORM_PROBE_TIMEOUT, capture_output=True)
            if probe.returncode == 0:
                return "default"
            sys.stderr.write(probe.stderr.decode(errors="replace")[-1500:])
        except subprocess.TimeoutExpired:
            print(f"bench: platform probe attempt {attempt} timed out "
                  f"after {PLATFORM_PROBE_TIMEOUT}s", file=sys.stderr)
    print("bench: default JAX platform unusable; falling back to CPU",
          file=sys.stderr)
    return "cpu"


def _cache_data(sf: float, cache_dir: str):
    from benchmarks.tpch import generate_tpch

    t0 = time.perf_counter()
    data = generate_tpch(sf)
    for name, frame in data.items():
        frame.to_feather(os.path.join(cache_dir, f"{name}.feather"))
    return time.perf_counter() - t0, len(data["lineitem"])


def _load_data(cache_dir: str):
    import pandas as pd

    data = {}
    for fn in os.listdir(cache_dir):
        if fn.endswith(".feather"):
            data[fn[:-8]] = pd.read_feather(os.path.join(cache_dir, fn))
    return data


def _stage_main():
    """Child: run BENCH_STAGE_QUERIES against the cached data, appending one
    JSON line per completed query to the progress file, retiring itself
    cleanly at BENCH_CHILD_DEADLINE (unix seconds)."""
    platform = os.environ.get("BENCH_PLATFORM_CHOICE", "default")
    deadline = float(os.environ.get("BENCH_CHILD_DEADLINE", "0")) or None
    import jax

    if platform != "default":
        jax.config.update("jax_platforms", platform)
    from benchmarks.tpch import QUERIES
    from dask_sql_tpu import Context

    qids = [int(x) for x in os.environ["BENCH_STAGE_QUERIES"].split(",")]
    progress_path = os.environ["BENCH_PROGRESS"]
    data = _load_data(os.environ["BENCH_DATA_DIR"])

    # the RESULT cache (runtime/result_cache.py) must not contaminate the
    # cold measurement: a repeated rep would replay the materialized result
    # in ~1 ms and the "best of REPS" would measure the cache, not the
    # engine.  Measurement runs with it off; the warm-repeat pass below
    # re-arms it to record hit-rate + warm latency as a SEPARATE metric.
    cache_mb = os.environ.get("DSQL_RESULT_CACHE_MB")
    os.environ["DSQL_RESULT_CACHE_MB"] = "0"
    # tiered execution must not contaminate the measurement either: a
    # first arrival served on the eager tier would record the eager path,
    # not the compiled engine (DSQL_EAGER_FALLBACK=0 already disables the
    # tier; this pins it for explicit-eager configs too).  The program
    # STORE stays armed: store loads ARE the engine's cold path now.
    os.environ.setdefault("DSQL_TIERED", "0")
    # the workload manager (runtime/scheduler.py, 4 slots by default) must
    # not throttle the 8-thread warmup pool: a compile that takes minutes
    # over the tunnel would blow the admission-queue timeout and lose the
    # query.  Measurement runs with it off; the burst pass below re-arms
    # it to record queue-time percentiles as a SEPARATE metric.
    os.environ["DSQL_MAX_CONCURRENT_QUERIES"] = "0"

    c = Context()
    t0 = time.perf_counter()
    for name, frame in data.items():
        c.create_table(name, frame)
    load_sec = time.perf_counter() - t0
    del data
    real_platform = jax.devices()[0].platform

    def left():
        return float("inf") if deadline is None else deadline - time.time()

    def emit(rec):
        with open(progress_path, "a") as f:
            f.write(json.dumps(rec) + "\n")
            f.flush()

    if os.environ.get("BENCH_WARM_RESTART") == "1":
        # RESTART-WARM mode: this is a FRESH process pointed at the
        # program store the measurement child populated — every query
        # should load its stage executables with zero XLA compiles.  One
        # run per query, journaled, plus the store-hit evidence the
        # parent folds into program_store_hit_rate / warm_start_sec.
        from dask_sql_tpu.physical import compiled as _cmp

        t_w = time.perf_counter()
        for qid in qids:
            if left() < 10:
                break
            try:
                t0r = time.perf_counter()
                c.sql(QUERIES[qid], return_futures=False)
                emit({"restart_q": qid,
                      "sec": round(time.perf_counter() - t0r, 4),
                      "platform": real_platform})
            except Exception as e:
                emit({"restart_fail": qid, "error": repr(e)[:200]})
        snap = dict(_cmp.stats)
        emit({"restart_done": True,
              "warm_start_sec": round(time.perf_counter() - t_w, 2),
              "program_store_hits": snap.get("program_store_hits", 0),
              "program_store_errors": snap.get("program_store_errors", 0),
              "compiles": snap.get("compiles", 0)})
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(0)

    if os.environ.get("BENCH_SHARD_SCALING") == "1":
        # SHARD-SCALING mode: the scan/agg-shaped queries (Q1/Q6) on the
        # single-device engine vs row-sharded over the full mesh through
        # the explicit SPMD executor (parallel/spmd.py) — the multi-chip
        # speedup evidence for the BENCH_r*.json trajectory.  On a
        # CPU-only host the mesh is the 8-virtual-device dry-run analogue;
        # spmd_served certifies the sharded path (not a silent fallback)
        # produced the numbers.
        from dask_sql_tpu.parallel.mesh import default_mesh
        from dask_sql_tpu.runtime import telemetry as _stel

        mesh = default_mesh()
        n_dev = int(mesh.devices.size)
        if n_dev < 2:
            emit({"shard_scaling_skip": f"only {n_dev} device(s)"})
            os._exit(0)
        dist = Context(mesh=mesh)
        for name, frame in _load_data(os.environ["BENCH_DATA_DIR"]).items():
            dist.create_table(name, frame)
        reps = int(os.environ.get("BENCH_SHARD_REPS", "3"))
        scaling = {}
        for qid in (1, 6):
            if left() < 20:
                break
            try:
                c.sql(QUERIES[qid], return_futures=False)     # warm 1-dev
                dist.sql(QUERIES[qid], return_futures=False)  # warm mesh
                c0 = _stel.REGISTRY.counters()
                single = sharded = float("inf")
                for _ in range(reps):
                    t0r = time.perf_counter()
                    c.sql(QUERIES[qid], return_futures=False)
                    single = min(single, time.perf_counter() - t0r)
                    t0r = time.perf_counter()
                    dist.sql(QUERIES[qid], return_futures=False)
                    sharded = min(sharded, time.perf_counter() - t0r)
                c1 = _stel.REGISTRY.counters()
                served = (c1.get("spmd_queries", 0)
                          - c0.get("spmd_queries", 0))
                scaling[str(qid)] = {
                    "single_sec": round(single, 4),
                    "sharded_sec": round(sharded, 4),
                    "speedup": round(single / max(sharded, 1e-9), 3),
                    "devices": n_dev,
                    "spmd_served": served >= reps,
                }
            except Exception as e:
                emit({"shard_scaling_fail": qid, "error": repr(e)[:200]})
        emit({"shard_scaling": scaling})
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(0)

    if os.environ.get("BENCH_OOC_CHILD") == "1":
        # OUT-OF-CORE mode (parent opts in with BENCH_OOC=1): lineitem and
        # orders re-registered CHUNKED (8 batches each) so Q1/Q6 stream
        # per-batch and Q3's chunked-x-chunked join runs grace-hash
        # partitioned through the spill store — the evidence that queries
        # over tables exceeding the device budget complete, stay correct
        # against the resident engine, and bound their device footprint.
        import pandas as _opd

        from dask_sql_tpu.runtime import spill as _spill_mod
        from dask_sql_tpu.runtime import telemetry as _otel

        def _frames_match(a, b) -> bool:
            try:
                cols = list(a.columns)
                _opd.testing.assert_frame_equal(
                    a.sort_values(cols).reset_index(drop=True),
                    b.sort_values(cols).reset_index(drop=True),
                    check_dtype=False, rtol=1e-6, atol=1e-6)
                return True
            except Exception:  # noqa: BLE001 - any mismatch is "no"
                return False

        ooc = Context()
        data = _load_data(os.environ["BENCH_DATA_DIR"])
        for name, frame in data.items():
            if name in ("lineitem", "orders"):
                ooc.create_table(name, frame, chunked=True,
                                 batch_rows=max(len(frame) // 8, 1))
            else:
                ooc.create_table(name, frame)
        del data
        store = _spill_mod.get_store()
        results = {}
        for qid in (1, 6, 3):
            if left() < 20:
                break
            try:
                c0x = _otel.REGISTRY.counters()
                t0r = time.perf_counter()
                got = ooc.sql(QUERIES[qid], return_futures=False)
                sec = time.perf_counter() - t0r
                ref = c.sql(QUERIES[qid], return_futures=False)
                c1x = _otel.REGISTRY.counters()

                def dlt(k):
                    return c1x.get(k, 0) - c0x.get(k, 0)

                results[str(qid)] = {
                    "sec": round(sec, 4),
                    "match": _frames_match(got, ref),
                    "spill_partitions": dlt("spill_partitions"),
                    "spill_bytes": dlt("spill_bytes_host")
                    + dlt("spill_bytes_disk"),
                    "stream_batches": dlt("stream_batches"),
                }
            except Exception as e:
                emit({"ooc_fail": qid, "error": repr(e)[:200]})
        cs = _otel.REGISTRY.counters()
        emit({"ooc": {
            "queries": results,
            "ooc_completed": bool(results) and all(
                r["match"] for r in results.values()),
            "spill_bytes": int(cs.get("spill_bytes_host", 0)
                               + cs.get("spill_bytes_disk", 0)),
            "spill_partitions": int(cs.get("spill_partitions", 0)),
            "peak_device_bytes": store.stats()["peak_device_bytes"],
        }})
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(0)

    if os.environ.get("BENCH_MV_CHILD") == "1":
        # MATERIALIZED-VIEW mode (parent opts in with BENCH_MV=1): a
        # SUM/AVG/COUNT group-by view over lineitem, one warm-up append
        # (pays the one-time partial/merge plan compiles), then a
        # 1k-row append with the maintained refresh timed against a full
        # recompute of the defining query — the O(delta) maintenance
        # evidence for the metrics JSON, plus the refresh hit-rate from
        # the mv_* counters and an exactness check of the served view
        # against the recomputed answer.
        import pandas as _mpd

        from dask_sql_tpu.runtime import telemetry as _mtel

        # maintained state is a result-cache tenant: the cache-off pin
        # above (cold-measurement hygiene) would silently disable the
        # whole subsystem, so this mode re-arms the budget
        os.environ["DSQL_RESULT_CACHE_MB"] = cache_mb if cache_mb else "256"
        MV_SQL = ("SELECT l_returnflag, l_linestatus, "
                  "SUM(l_quantity) AS sum_qty, "
                  "SUM(l_extendedprice) AS sum_price, "
                  "AVG(l_discount) AS avg_disc, COUNT(*) AS n "
                  "FROM lineitem GROUP BY l_returnflag, l_linestatus")

        def _mv_match(a, b) -> bool:
            try:
                cols = list(a.columns)
                _mpd.testing.assert_frame_equal(
                    a.sort_values(cols).reset_index(drop=True),
                    b.sort_values(cols).reset_index(drop=True),
                    check_dtype=False, rtol=1e-6, atol=1e-6)
                return True
            except Exception:  # noqa: BLE001 - any mismatch is "no"
                return False

        mv_rec = {}
        try:
            li = _mpd.read_feather(os.path.join(
                os.environ["BENCH_DATA_DIR"], "lineitem.feather"))
            c0m = _mtel.REGISTRY.counters()
            c.sql(f"CREATE MATERIALIZED VIEW bench_mv AS {MV_SQL}")
            c.sql("SELECT * FROM bench_mv", return_futures=False)
            # warm-up append + refresh: the first refresh compiles the
            # delta partial / state merge shapes once; the steady-state
            # claim is about maintenance work, not compiler latency
            c.append_rows("lineitem", li.sample(n=1000, random_state=7))
            c.sql("REFRESH MATERIALIZED VIEW bench_mv")
            c.sql(MV_SQL, return_futures=False)

            delta = li.sample(n=1000, random_state=11)
            c.append_rows("lineitem", delta)
            t0r = time.perf_counter()
            c.sql("REFRESH MATERIALIZED VIEW bench_mv")
            refresh_sec = time.perf_counter() - t0r
            served = c.sql("SELECT * FROM bench_mv", return_futures=False)
            # the append bumped lineitem's epoch, so this recompute is a
            # result-cache miss and measures the real defining query
            t0r = time.perf_counter()
            recomputed = c.sql(MV_SQL, return_futures=False)
            recompute_sec = time.perf_counter() - t0r
            c1m = _mtel.REGISTRY.counters()

            def dltm(k):
                return int(c1m.get(k, 0) - c0m.get(k, 0))

            inc = dltm("mv_refresh_incremental")
            full = dltm("mv_refresh_full")
            mv_rec = {
                "refresh_sec": round(refresh_sec, 4),
                "recompute_sec": round(recompute_sec, 4),
                "speedup": round(recompute_sec / max(refresh_sec, 1e-9), 2),
                "delta_rows": int(len(delta)),
                "base_rows": int(len(li)),
                "mv_refresh_incremental": inc,
                "mv_refresh_full": full,
                "mv_serves": dltm("mv_serves"),
                "mv_deltas_recorded": dltm("mv_deltas_recorded"),
                # fraction of refreshes maintained in O(delta) rather
                # than recomputed — the number the trajectory watches
                "mv_hit_rate": round(inc / max(inc + full, 1), 3),
                "match": _mv_match(served, recomputed),
            }
        except Exception as e:
            mv_rec = {"error": repr(e)[:300]}
        emit({"mv": mv_rec})
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(0)

    if os.environ.get("BENCH_INGEST_CHILD") == "1":
        # CONTINUOUS-INGESTION mode (parent opts in with BENCH_INGEST=1):
        # WAL-armed 500-row appends into lineitem interleaved with reads
        # of a maintained aggregate view and a COUNT(DISTINCT) view —
        # journals sustained appends/sec, read p50/p99 beside the writer,
        # the max observed staleness (pending delta age + rows), and the
        # served-vs-recomputed exactness verdict (runtime/ingest.py +
        # runtime/delta.py).
        import tempfile as _itmp

        import pandas as _ipd

        from dask_sql_tpu.runtime import telemetry as _itel

        # maintained view state is a result-cache tenant (see the MV mode
        # above), and the WAL dir arms the ingest write path lazily
        os.environ["DSQL_RESULT_CACHE_MB"] = cache_mb if cache_mb else "256"
        os.environ["DSQL_INGEST_DIR"] = _itmp.mkdtemp(
            prefix="dsql_bench_ingest_")
        ING_SQL = ("SELECT l_returnflag, l_linestatus, "
                   "SUM(l_quantity) AS sum_qty, "
                   "SUM(l_extendedprice) AS sum_price, COUNT(*) AS n "
                   "FROM lineitem GROUP BY l_returnflag, l_linestatus")
        CD_SQL = "SELECT COUNT(DISTINCT l_suppkey) AS nd FROM lineitem"

        def _ing_match(a, b) -> bool:
            try:
                cols = list(a.columns)
                _ipd.testing.assert_frame_equal(
                    a.sort_values(cols).reset_index(drop=True),
                    b.sort_values(cols).reset_index(drop=True),
                    check_dtype=False, rtol=1e-6, atol=1e-6)
                return True
            except Exception:  # noqa: BLE001 - any mismatch is "no"
                return False

        rec_ing = {}
        try:
            li = _ipd.read_feather(os.path.join(
                os.environ["BENCH_DATA_DIR"], "lineitem.feather"))
            c.sql(f"CREATE MATERIALIZED VIEW bench_ing AS {ING_SQL}")
            c.sql(f"CREATE MATERIALIZED VIEW bench_cd AS {CD_SQL}")
            # warm-up: pay the one-time delta-plan compiles before timing
            c.append_rows("lineitem", li.sample(n=500, random_state=5))
            c.sql("SELECT * FROM bench_ing", return_futures=False)
            c.sql("SELECT nd FROM bench_cd", return_futures=False)

            c0i = _itel.REGISTRY.counters()
            rounds = int(os.environ.get("BENCH_INGEST_ROUNDS", "30"))
            batch_n = int(os.environ.get("BENCH_INGEST_BATCH", "500"))
            append_sec = 0.0
            appended = 0
            lat_ms = []
            stale_max = 0.0
            pend_max = 0
            for i in range(rounds):
                if left() < 30:
                    break
                delta = li.sample(n=batch_n, random_state=100 + i)
                t0i = time.perf_counter()
                c.append_rows("lineitem", delta)
                append_sec += time.perf_counter() - t0i
                appended += batch_n
                g = _itel.REGISTRY.gauges()
                stale_max = max(stale_max,
                                float(g.get("mv_staleness_s", 0.0)))
                pend_max = max(pend_max, int(g.get("mv_pending_rows", 0)))
                sql_r = ("SELECT * FROM bench_ing" if i % 2 == 0
                         else "SELECT nd FROM bench_cd")
                t0i = time.perf_counter()
                c.sql(sql_r, return_futures=False)
                lat_ms.append((time.perf_counter() - t0i) * 1e3)
            served = c.sql("SELECT * FROM bench_ing", return_futures=False)
            recomputed = c.sql(ING_SQL, return_futures=False)
            c1i = _itel.REGISTRY.counters()

            def dlti(k):
                return int(c1i.get(k, 0) - c0i.get(k, 0))

            lat_ms.sort()

            def pct(p):
                if not lat_ms:
                    return None
                return round(lat_ms[min(int(len(lat_ms) * p),
                                        len(lat_ms) - 1)], 2)

            rec_ing = {
                "batches": dlti("ingest_batches_committed"),
                "rows_appended": appended,
                "appends_per_sec": round(
                    appended / max(append_sec, 1e-9), 1),
                "read_p50_ms": pct(0.50),
                "read_p99_ms": pct(0.99),
                "staleness_max_s": round(stale_max, 3),
                "pending_rows_max": pend_max,
                "wal_bytes": int(_itel.REGISTRY.gauges().get(
                    "ingest_wal_bytes", 0)),
                "backpressure_rejects": dlti("ingest_backpressure_rejects"),
                "mv_refresh_incremental": dlti("mv_refresh_incremental"),
                "mv_refresh_full": dlti("mv_refresh_full"),
                "match": _ing_match(served, recomputed),
            }
        except Exception as e:
            rec_ing = {"error": repr(e)[:300]}
        emit({"ingest": rec_ing})
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(0)

    if os.environ.get("BENCH_AUTOPILOT_CHILD") == "1":
        # AUTOPILOT mode (parent opts in with BENCH_AUTOPILOT=1): the
        # unattended-vs-hand-tuned comparison.  A hand-tuned operator
        # pre-creates a matview and queries it by name; the unattended
        # workload just repeats its aggregate and lets the autopilot
        # discover, materialize and maintain it.  Both pay the same
        # append-then-read rounds; the journaled ratio is the price of
        # leaving the tuning to the advisor (~1.0 = converged).
        import pandas as _apd

        from dask_sql_tpu.runtime import telemetry as _atel

        # maintained state is a result-cache tenant (see the MV mode
        # above): re-arm the budget the cold-measurement pin zeroed
        os.environ["DSQL_RESULT_CACHE_MB"] = cache_mb if cache_mb else "256"
        TUNED_SQL = ("SELECT l_returnflag, l_linestatus, "
                     "SUM(l_quantity) AS sum_qty, COUNT(*) AS n "
                     "FROM lineitem GROUP BY l_returnflag, l_linestatus")
        AUTO_SQL = ("SELECT l_linestatus, "
                    "SUM(l_extendedprice) AS sum_price, "
                    "AVG(l_discount) AS avg_disc, COUNT(*) AS n "
                    "FROM lineitem GROUP BY l_linestatus")
        rec_ap = {}
        try:
            li = _apd.read_feather(os.path.join(
                os.environ["BENCH_DATA_DIR"], "lineitem.feather"))
            # untuned reference: one full recompute of the aggregate
            t0a = time.perf_counter()
            c.sql(AUTO_SQL, return_futures=False)
            recompute_sec = time.perf_counter() - t0a

            # hand-tuned: operator-created view, queried by name; the
            # warm-up append pays the one-time delta-plan compiles
            c.sql(f"CREATE MATERIALIZED VIEW bench_ap AS {TUNED_SQL}")
            c.append_rows("lineitem", li.sample(n=1000, random_state=3))
            c.sql("SELECT * FROM bench_ap", return_futures=False)
            tuned = []
            for r in range(3):
                if left() < 30:
                    break
                c.append_rows("lineitem",
                              li.sample(n=1000, random_state=20 + r))
                t0a = time.perf_counter()
                c.sql("SELECT * FROM bench_ap", return_futures=False)
                tuned.append(time.perf_counter() - t0a)

            # unattended: arm the advisor, repeat the aggregate until it
            # is the top candidate (the second run is a cache hit whose
            # count-only envelope still accrues), tick, then pay the
            # same append-then-read rounds served from the auto view
            os.environ["DSQL_HISTORY_FILE"] = os.path.join(
                os.environ["BENCH_DATA_DIR"], "autopilot_history.jsonl")
            os.environ["DSQL_AUTOPILOT"] = "1"
            os.environ["DSQL_AUTOPILOT_INTERVAL_S"] = "0"
            os.environ["DSQL_AUTOPILOT_MIN_HITS"] = "2"
            from dask_sql_tpu.runtime import autopilot as _ap
            c0a = _atel.REGISTRY.counters()
            c.sql(AUTO_SQL, return_futures=False)
            c.sql(AUTO_SQL, return_futures=False)
            _ap.tick(c)
            unattended = []
            served = None
            for r in range(3):
                if left() < 30:
                    break
                c.append_rows("lineitem",
                              li.sample(n=1000, random_state=40 + r))
                t0a = time.perf_counter()
                served = c.sql(AUTO_SQL, return_futures=False)
                unattended.append(time.perf_counter() - t0a)
            # exactness: the served answer vs a from-scratch recompute
            # with the advisor disarmed (epoch already bumped, so this
            # is a genuine cache miss)
            os.environ["DSQL_AUTOPILOT"] = "0"
            recomputed = c.sql(AUTO_SQL, return_futures=False)
            os.environ["DSQL_AUTOPILOT"] = "1"
            cols = list(recomputed.columns)
            try:
                _apd.testing.assert_frame_equal(
                    served.sort_values(cols).reset_index(drop=True),
                    recomputed.sort_values(cols).reset_index(drop=True),
                    check_dtype=False, rtol=1e-6, atol=1e-6)
                match = True
            except Exception:  # noqa: BLE001 - any mismatch is "no"
                match = False
            c1a = _atel.REGISTRY.counters()

            def dlta(k):
                return int(c1a.get(k, 0) - c0a.get(k, 0))

            tg = _geomean(tuned) if tuned else 0.0
            ug = _geomean(unattended) if unattended else 0.0
            rec_ap = {
                "recompute_sec": round(recompute_sec, 4),
                "tuned_geomean_sec": round(tg, 4),
                "unattended_geomean_sec": round(ug, 4),
                "vs_tuned_geomean": (round(ug / tg, 3) if tg > 0
                                     else None),
                "auto_views": _ap.engine_section()["managedViews"],
                "autopilot_mv_creates": dlta("autopilot_mv_creates"),
                "autopilot_mv_serves": dlta("autopilot_mv_serves"),
                "match": match,
            }
        except Exception as e:
            rec_ap = {"error": repr(e)[:300]}
        emit({"autopilot": rec_ap})
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(0)

    if os.environ.get("BENCH_FLEET_CHILD") == "1":
        # FLEET mode (parent opts in with BENCH_FLEET=1): two server
        # REPLICAS on one shared DSQL_FLEET_DIR + a FRESH shared
        # DSQL_PROGRAM_STORE, driven through a Zipf multi-tenant
        # parameterized burst over the wire.  Journals per-tenant SLO
        # attainment from the merged fleet plane, the fleet-wide
        # plan-cache hit rate, and the cross-replica warm serves —
        # replica B must answer shapes replica A compiled with ZERO
        # compiles of its own.
        import subprocess
        import tempfile as _ftmp
        import urllib.request as _furl

        import numpy as _fnp

        fleet_root = _ftmp.mkdtemp(prefix="bench_fleet_")
        fleet_dir = os.path.join(fleet_root, "fleet")
        store_dir = os.path.join(fleet_root, "programs")
        os.makedirs(store_dir, exist_ok=True)
        server_src = (
            "import os, time\n"
            "import pandas as pd\n"
            "from dask_sql_tpu import Context\n"
            "c = Context()\n"
            "c.create_table('lineitem', pd.read_feather(os.path.join(\n"
            "    os.environ['BENCH_DATA_DIR'], 'lineitem.feather')))\n"
            "srv = c.run_server(host='127.0.0.1', port=0, blocking=False)\n"
            "print(f'PORT {srv.server_port}', flush=True)\n"
            "while True:\n"
            "    time.sleep(0.5)\n"
        )

        def _fleet_spawn(rid):
            # FRESH XLA cache: the pass proves warmth through the program
            # store, and a bench-warmed shared DSQL_XLA_CACHE poisons it —
            # serialize_executable on a cache-served CPU executable emits
            # symbol references instead of embedded code, so the other
            # replica's deserialize dies with "Symbols not found"
            env = dict(os.environ, DSQL_FLEET_DIR=fleet_dir,
                       DSQL_REPLICA_ID=rid, DSQL_FLEET_BEAT_S="0.2",
                       DSQL_PROGRAM_STORE=store_dir,
                       DSQL_XLA_CACHE=os.path.join(fleet_root, "xla"),
                       DSQL_RESULT_CACHE_MB="0",
                       DSQL_MAX_CONCURRENT_QUERIES="0",
                       DSQL_TIERED="0")
            # per-replica rings must come from the fleet arm, not the
            # bench-wide history file every other pass shares
            for k in ("DSQL_EVENTS", "DSQL_EVENTS_FILE",
                      "DSQL_HISTORY_FILE", "BENCH_STAGE"):
                env.pop(k, None)
            p = subprocess.Popen([sys.executable, "-c", server_src],
                                 env=env, stdout=subprocess.PIPE,
                                 stderr=subprocess.PIPE)
            line = p.stdout.readline().decode().strip()
            if not line.startswith("PORT "):
                p.kill()
                raise RuntimeError(
                    f"fleet replica {rid} died: "
                    f"{p.stderr.read().decode()[-300:]}")
            return p, f"http://127.0.0.1:{line.split()[1]}"

        def _fleet_req(url, body=None, headers=None):
            req = _furl.Request(
                url, data=body.encode() if body is not None else None,
                headers=headers or {})
            with _furl.urlopen(req, timeout=120) as r:
                return json.loads(r.read() or b"null")

        def _fleet_run(base, sql_body, tenant):
            payload = _fleet_req(
                f"{base}/v1/statement", sql_body,
                headers={"Content-Type": "application/json",
                         "X-DSQL-Tenant": tenant,
                         "X-DSQL-Priority": "interactive"})
            while "nextUri" in payload:
                payload = _fleet_req(payload["nextUri"])
            return payload

        def _fleet_metric(base, name):
            with _furl.urlopen(f"{base}/metrics", timeout=60) as r:
                for ln in r.read().decode().splitlines():
                    if not ln.startswith("#") \
                            and ln.split("{")[0].split(" ")[0] == name:
                        return float(ln.rsplit(" ", 1)[1])
            return 0.0

        fleet_rec, procs = {}, []
        try:
            pa, base_a = _fleet_spawn("bench-a")
            procs.append(pa)
            pb, base_b = _fleet_spawn("bench-b")
            procs.append(pb)
            tpl = ("SELECT l_returnflag, SUM(l_extendedprice) AS s, "
                   "COUNT(*) AS n FROM lineitem WHERE l_quantity > ? "
                   "GROUP BY l_returnflag ORDER BY l_returnflag")
            distinct = [float(v) for v in
                        _fnp.linspace(1.0, 45.0, 12).round(2)]
            # replica A pays the one compile for the shape...
            _fleet_run(base_a, json.dumps(
                {"sql": tpl, "params": [distinct[0]]}), "tenant-0")
            rng = _fnp.random.RandomState(31)
            lit_ranks = _fnp.clip(rng.zipf(1.2, size=48), 1,
                                  len(distinct)) - 1
            ten_ranks = _fnp.clip(rng.zipf(1.3, size=48), 1, 8) - 1
            execs = 0
            # ...then the Zipf mix lands on BOTH replicas: hot tenants,
            # a literal long tail, every B-side execution warm-served
            for i, (lr, tr) in enumerate(zip(lit_ranks, ten_ranks)):
                if left() < 30:
                    break
                base = base_b if i % 2 else base_a
                _fleet_run(base, json.dumps(
                    {"sql": tpl, "params": [distinct[int(lr)]]}),
                    f"tenant-{int(tr)}")
                execs += 1
            time.sleep(0.5)                 # let the final beats land
            snap = _fleet_req(f"{base_a}/v1/fleet")
            compiles_b = _fleet_metric(base_b, "dsql_compiles_total")
            hits_b = _fleet_metric(base_b,
                                   "dsql_program_store_hits_total")
            plan_hits = sum(_fleet_metric(b, "dsql_param_plan_hits_total")
                            for b in (base_a, base_b))
            fleet_rec = {
                "replicas": len(snap["replicas"]),
                "alive": snap["totals"]["alive"],
                "burst_executions": execs + 1,
                "tenant_slo_attainment": snap["slo"].get("tenants") or None,
                "plan_cache_hit_rate": round(
                    plan_hits / max(execs + 1, 1), 3),
                "warm_serves": snap["totals"]["warmServes"],
                "replica_b_compiles": compiles_b,
                "replica_b_store_hits": hits_b,
                # the shared-warmth verdict: B executed half the burst
                # without compiling anything
                "cross_replica_warm": bool(compiles_b == 0 and hits_b > 0),
            }
        except Exception as e:
            fleet_rec = {"error": repr(e)[:300]}
        finally:
            for p in procs:
                p.terminate()
            for p in procs:
                try:
                    p.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    p.kill()
        emit({"fleet": fleet_rec})
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(0)

    # warmup = compilation; compiles overlap across threads (tracing holds
    # the GIL but the backend compile releases it), which matters on the
    # tunneled TPU where a single cold compile can take minutes.  Each
    # query's compile wall-time is journaled: with the persistent XLA cache
    # primed this is the warm-load evidence (~sub-second), cold it is the
    # true compile cost.
    compiled_ok = set()
    lock = threading.Lock()

    warm_t0 = time.perf_counter()
    last_warm_done = [0.0]

    # expensive programs (many fused join/agg pipelines) compile through a
    # shared remote helper that gets OOM-killed when several land at once
    # (r4: the 6 join-heavy queries all wedged) — heavy plans take a
    # 2-permit semaphore so at most two of them compile concurrently while
    # light plans keep the full thread-pool width
    heavy_sem = threading.Semaphore(
        int(os.environ.get("BENCH_HEAVY_COMPILES", "2")))

    def _is_heavy(q) -> bool:
        try:
            from dask_sql_tpu.physical.compiled import _heavy_count
            from dask_sql_tpu.sql.parser import parse_sql
            stmt = parse_sql(QUERIES[q])[0]
            return _heavy_count(c._get_plan(stmt.query)) >= 4
        except Exception:
            return False

    compile_started = set()

    def warm_one(q):
        # journal the START too: a query missing from the final artifact can
        # then be classified as in-flight-at-kill vs never-started
        emit({"warm_start": q})
        t0 = time.perf_counter()
        if _is_heavy(q):
            with heavy_sem:
                with lock:
                    compile_started.add(q)
                c.sql(QUERIES[q], return_futures=False)
        else:
            with lock:
                compile_started.add(q)
            c.sql(QUERIES[q], return_futures=False)
        dt = time.perf_counter() - t0
        with lock:
            compiled_ok.add(q)
            last_warm_done[0] = time.perf_counter() - warm_t0
        emit({"warm_q": q, "sec": round(dt, 3)})
        # first_arrival: latency of the very FIRST submission of this query
        # in this bench run (the parent keeps the earliest record across
        # children) — against a cold program store it is the compile wall,
        # against a primed one it is the store-load + execute cost
        emit({"first_arrival": q, "sec": round(dt, 3)})

    def learn_split_hint(q):
        """Persist the engine's "split this plan" hint for a query whose
        whole-plan compile the remote helper silently lost — the NEXT
        child (default config) then compiles it as small programs, while
        queries that never got a compile attempt keep their standard
        whole-plan configuration."""
        try:
            from dask_sql_tpu.ops.pallas_kernels import _strategy_on_tpu
            from dask_sql_tpu.physical import compiled as _cm
            from dask_sql_tpu.sql.parser import parse_sql

            plan = c._get_plan(parse_sql(QUERIES[q])[0].query)
            scans = []
            key = (_cm._fp_plan(plan, c, scans), _cm._fp_inputs(scans),
                   bool(_strategy_on_tpu()))
            _cm._learned_caps_put(key, {**_cm._learned_caps_get(key),
                                        "__split__": 1})
            return True
        except Exception as e:
            emit({"hint_fail": q, "error": repr(e)[:200]})
            return False

    t0 = warm_t0
    futs = {}
    if WARMUP_THREADS > 1 and len(qids) > 1:
        from concurrent.futures import ThreadPoolExecutor
        # do NOT pool.shutdown(wait=True) anywhere: a thread wedged in a
        # tunnel compile must not hang the child — the os._exit at the
        # bottom reaps everything
        pool = ThreadPoolExecutor(min(WARMUP_THREADS, len(qids)))
        futs = {q: pool.submit(warm_one, q) for q in qids}
    else:
        for q in qids:
            if left() < 20:
                break
            try:
                warm_one(q)
            except Exception as e:
                emit({"warm_fail": q, "error": repr(e)[:300]})

    from dask_sql_tpu.physical import compiled

    # measure-as-compiled INSURANCE pass: one contended rep per query as
    # soon as its warmup lands, while the remaining compiles keep
    # overlapping in the pool.  These numbers are systematically OVERSTATED
    # (the tunnel is saturated by concurrent compiles) — they exist so a
    # killed run still has every compiled query on record; the quiesced
    # pass below produces the real measurement and _emit_locked keeps the
    # minimum per query.
    measured, failed = set(), set()
    warmup_sec = 0.0
    # a compile request the remote helper silently dropped (OOM-killed
    # server side) never raises AND never lands — without a wedge timeout
    # one such query consumes the whole child budget and starves the
    # retry children (this is exactly how r4 lost its 6 queries)
    wedge_timeout = float(os.environ.get("BENCH_WEDGE_TIMEOUT", "420"))
    last_progress = [time.perf_counter()]
    try:
        while left() > 15:
            for q, f in list(futs.items()):
                if q not in failed and f.done() \
                        and f.exception() is not None:
                    failed.add(q)
                    last_progress[0] = time.perf_counter()
                    emit({"warm_fail": q,
                          "error": repr(f.exception())[:300]})
            # sample the all-done flag BEFORE the ready snapshot: the last
            # warmup can land between the two, and checking in this order
            # guarantees one more loop pass sees it in compiled_ok
            all_done = bool(futs) and all(f.done() for f in futs.values())
            with lock:
                ready = [q for q in qids
                         if q in compiled_ok and q not in measured]
                if last_warm_done[0] + warm_t0 > last_progress[0]:
                    last_progress[0] = last_warm_done[0] + warm_t0
            if not ready:
                if len(measured) + len(failed) >= len(qids) or all_done:
                    break
                if not futs:
                    break
                if time.perf_counter() - last_progress[0] > wedge_timeout:
                    # declare wedged ONLY the stragglers whose compile
                    # actually STARTED (queries queued behind the pool or
                    # the heavy semaphore made no attempt and must not
                    # inherit a failure): mark them, persist the engine's
                    # split hint for each so the next child — running the
                    # standard config — compiles THEM as small programs
                    # and everything else whole, then move on to the
                    # quiesced pass
                    with lock:
                        pending = [q for q, f in futs.items()
                                   if not f.done() and q in compile_started
                                   and q not in compiled_ok]
                    for q in pending:
                        failed.add(q)
                        learn_split_hint(q)
                        emit({"warm_fail": q,
                              "error": f"wedged: no warmup progress in "
                                       f"{wedge_timeout:.0f}s (remote "
                                       f"compile presumed lost; split "
                                       f"hint learned)"})
                    break
                time.sleep(2)
                continue
            for qid in ready:
                if left() < 15:
                    break
                try:
                    t0r = time.perf_counter()
                    # end-to-end: SQL text to host pandas frame (matches
                    # what the pandas baseline measures)
                    c.sql(QUERIES[qid], return_futures=False)
                    sec = time.perf_counter() - t0r
                except Exception as e:
                    # one transient execute failure must not abort the
                    # loop (and with it every remaining query's insurance
                    # record AND the quiesced pass)
                    measured.add(qid)  # quiesced pass retries it
                    emit({"measure_fail": qid, "error": repr(e)[:200]})
                    continue
                measured.add(qid)
                emit({"q": qid, "sec": round(sec, 4),
                      "platform": real_platform})
        # wall time until the LAST warmup landed (measurement overlaps it)
        warmup_sec = last_warm_done[0] or (time.perf_counter() - t0)

        # QUIESCED re-measure: every compile has landed (or failed), the
        # tunnel is idle — these are the numbers that stand.  Per-query
        # wall breakdown (host planning vs device round trip vs host
        # decode) is journaled with the best rep, so every recorded time
        # names its own bottleneck.
        for qid in sorted(measured):
            if left() < 25:
                break
            best, bd = float("inf"), None
            try:
                for _ in range(REPS):
                    t0r = time.perf_counter()
                    c.sql(QUERIES[qid], return_futures=False)
                    sec = time.perf_counter() - t0r
                    if sec < best:
                        best = sec
                        t = getattr(c, "last_timings", None) or {}
                        bd = {k: round(v, 1) for k, v in t.items()}
                    if left() < 20:
                        break
                # one extra DSQL_TIME_DEVICE rep: splits the exec wall
                # into device dispatch+compute vs host materialize (it
                # costs an extra device sync, so it never contaminates
                # the recorded best — its split just joins the breakdown)
                if left() > 30 and "DSQL_TIME_DEVICE" not in os.environ:
                    os.environ["DSQL_TIME_DEVICE"] = "1"
                    try:
                        c.sql(QUERIES[qid], return_futures=False)
                        t = getattr(c, "last_timings", None) or {}
                        for k in ("device_ms", "materialize_ms"):
                            if k in t and bd is not None:
                                bd[k] = round(t[k], 1)
                    finally:
                        del os.environ["DSQL_TIME_DEVICE"]
            except Exception as e:
                # a tunnel hiccup here must not cost the stage_done record
                # — every number is already journaled
                emit({"requiesce_fail": qid, "error": repr(e)[:200]})
                continue
            # per-query adaptive operator choices (runtime/statistics.py):
            # the report collects record_choice lines from the span tree,
            # so the journal names the variant every published time ran on
            try:
                from dask_sql_tpu.runtime import telemetry as _tl
                rep = _tl.last_report()
                ops = list(getattr(rep, "operators", ()) or ())
            except Exception:
                ops = []
            emit({"q": qid, "sec": round(best, 4),
                  "platform": real_platform, "quiesced": True,
                  "breakdown": bd, "operators": ops})

        # WARM-REPEAT pass: result cache armed, each measured query run
        # twice — run 1 populates, run 2 must be a full-query hit.  The
        # warm latency and hit verdict are journaled per query so cache
        # hit-rate lands in the metrics JSON without ever touching the
        # cold numbers above.
        os.environ["DSQL_RESULT_CACHE_MB"] = cache_mb if cache_mb else "256"
        for qid in sorted(measured):
            if left() < 20:
                break
            try:
                c.sql(QUERIES[qid], return_futures=False)  # populate
                t0r = time.perf_counter()
                c.sql(QUERIES[qid], return_futures=False)
                sec = time.perf_counter() - t0r
                rep = getattr(c, "last_report", None)
                rc = dict(getattr(rep, "cache", None) or {})
                emit({"warm_hit": qid, "sec": round(sec, 4),
                      "hit": bool(rc.get("hit")), "tier": rc.get("tier")})
            except Exception as e:
                emit({"warm_hit_fail": qid, "error": repr(e)[:200]})

        # CONCURRENT-BURST pass: the workload manager armed with 2 slots
        # and a 4-deep queue, 8 mixed-priority threads re-running warm
        # (already-compiled) queries at once.  Journals one record per
        # burst query — admitted (with its measured queue time) or
        # rejected — so admission_reject_rate and queue-time percentiles
        # land in the metrics JSON without touching the cold numbers.
        if measured and left() > 30:
            os.environ["DSQL_RESULT_CACHE_MB"] = "0"
            os.environ["DSQL_MAX_CONCURRENT_QUERIES"] = "2"
            os.environ["DSQL_QUEUE_DEPTH"] = "4"
            os.environ["DSQL_QUEUE_TIMEOUT_MS"] = "120000"
            # the watchtower rides the burst: per-class SLO attainment
            # over the one scheduler-armed, mixed-priority window is the
            # number the BENCH_r06 headline journals
            os.environ["DSQL_EVENTS"] = "1"
            try:
                from dask_sql_tpu.runtime import resilience as _resil
                from dask_sql_tpu.runtime import telemetry as _tl
                burst_qids = (sorted(measured) * 8)[:8]
                block = threading.Barrier(len(burst_qids), timeout=60)
                block_lock = threading.Lock()

                def burst_one(slot, qid):
                    prio = "interactive" if slot % 2 == 0 else "batch"
                    rec = {"burst": qid, "slot": slot, "priority": prio}
                    try:
                        blick = time.perf_counter()
                        block.wait()
                        c.sql(QUERIES[qid], return_futures=False,
                              priority=prio)
                        rep = _tl.last_report()
                        rec["outcome"] = "ok"
                        rec["sec"] = round(time.perf_counter() - blick, 4)
                        rec["queued_ms"] = round(
                            (rep.phases.get("queued") if rep else 0) or 0,
                            3)
                    except _resil.AdmissionRejected as e:
                        rec["outcome"] = "rejected"
                        rec["error"] = repr(e)[:200]
                    except Exception as e:
                        rec["outcome"] = "error"
                        rec["error"] = repr(e)[:200]
                    with block_lock:
                        emit(rec)

                bthreads = [threading.Thread(target=burst_one, args=(s, q))
                            for s, q in enumerate(burst_qids)]
                for t in bthreads:
                    t.start()
                for t in bthreads:
                    t.join(timeout=150)
                from dask_sql_tpu.runtime import events as _ev
                emit({"slo_attainment": {
                    r["class"]: r["attainment"] for r in _ev.slo_rows()
                    if r["total"] > 0}})
            except Exception as e:
                emit({"burst_fail": True, "error": repr(e)[:200]})
            finally:
                os.environ["DSQL_MAX_CONCURRENT_QUERIES"] = "0"
                os.environ["DSQL_EVENTS"] = "0"

        # PARAM-MIX pass (ISSUE 16): a Zipf-distributed client mix of one
        # query SHAPE with many distinct literals — the dominant
        # production pattern parameterized plan identity exists for.
        # Journals compiles vs distinct literals (the sublinearity proof:
        # one shape compiles once however many literals arrive) and the
        # plan-cache hit rate the headline publishes.
        if left() > 20:
            os.environ["DSQL_RESULT_CACHE_MB"] = "0"
            try:
                import numpy as np

                from dask_sql_tpu.runtime import telemetry as _tl
                tpl = ("SELECT l_returnflag, SUM(l_extendedprice) AS s, "
                       "COUNT(*) AS n FROM lineitem WHERE l_quantity > ? "
                       "GROUP BY l_returnflag ORDER BY l_returnflag")
                rng = np.random.RandomState(23)
                distinct = [float(v) for v in
                            np.linspace(1.0, 45.0, 12).round(2)]
                # Zipf rank-frequency over the distinct literals: a few
                # hot values, a long tail — rank r drawn w.p. ∝ 1/r^1.2
                ranks = np.clip(rng.zipf(1.2, size=36), 1,
                                len(distinct)) - 1
                pm0 = _tl.REGISTRY.counters()
                execs = 0
                for r in ranks:
                    c.sql(tpl, params=[distinct[int(r)]],
                          return_futures=False)
                    execs += 1
                pm1 = _tl.REGISTRY.counters()
                emit({"param_mix": {
                    "distinct_literals": len(set(int(r) for r in ranks)),
                    "executions": execs,
                    "compiles": pm1["compiles"] - pm0["compiles"],
                    "param_plans": (pm1["param_plans"]
                                    - pm0["param_plans"]),
                    "param_plan_hits": (pm1["param_plan_hits"]
                                        - pm0["param_plan_hits"]),
                    "param_plan_misses": (pm1["param_plan_misses"]
                                          - pm0["param_plan_misses"]),
                }})
            except Exception as e:
                emit({"param_mix_fail": True, "error": repr(e)[:200]})

        # ESTIMATE-ERROR journal: for every measured query, the byte error
        # of the scan-bytes heuristic vs the flight recorder's measured
        # history against the EWMA'd actual working set — the evidence that
        # the feedback loop shrinks memory-broker reservations.  Envelope-
        # level admission estimates (est_source from the burst pass, the
        # only scheduler-armed window) land alongside.
        if measured and left() > 10:
            try:
                from dask_sql_tpu.runtime import flight_recorder as _fr
                from dask_sql_tpu.runtime import scheduler as _sched
                from dask_sql_tpu.runtime import telemetry as _tl
                from dask_sql_tpu.sql.parser import parse_sql as _ps
                if _fr.enabled():
                    err = {"heuristic": [], "history": []}
                    for qid in sorted(measured):
                        plan = c._get_plan(_ps(QUERIES[qid])[0].query)
                        fp = _fr.plan_fingerprint(plan, c)
                        st = _fr.get_stats(fp) if fp else None
                        actual = float((st or {}).get("bytes") or 0.0)
                        if actual <= 0:
                            continue
                        heur = float(_sched.estimate_plan_bytes(plan, c))
                        err["heuristic"].append(
                            abs(heur - actual) / actual)
                        hist = _fr.plan_history_bytes(plan, c)
                        if hist:
                            err["history"].append(
                                abs(hist - actual) / actual)
                    by_src = {}
                    for ev in _fr.read_events(kind="query"):
                        src = ev.get("est_source")
                        m = ev.get("measured_bytes") or 0
                        if src and m > 0 and ev.get("est_bytes"):
                            by_src.setdefault(src, []).append(
                                abs(ev["est_bytes"] - m) / m)
                    emit({"estimate_error": {
                              k: round(sum(v) / len(v), 4) if v else None
                              for k, v in err.items()},
                          "estimate_error_admitted": {
                              k: round(sum(v) / len(v), 4)
                              for k, v in by_src.items()},
                          "estimate_from_history":
                              _tl.REGISTRY.get("estimate_from_history")})
            except Exception as e:
                emit({"estimate_error_fail": True,
                      "error": repr(e)[:200]})
    finally:
        # stage_done must survive anything the loops above throw: it
        # carries the compile stats and memory evidence for the artifact
        mem = {}
        try:
            # sum across ALL local devices: a mesh run that only reads
            # device[0] under-reports HBM by the device count
            for dev in jax.local_devices():
                stats = dev.memory_stats() or {}
                for k in ("bytes_in_use", "peak_bytes_in_use",
                          "bytes_limit"):
                    if k in stats:
                        mem[k] = mem.get(k, 0) + int(stats[k])
        except Exception:
            pass
        # the axon backend exposes no allocator stats; account for at
        # least the resident table arrays so device_memory is never
        # silently empty
        try:
            tbl_bytes = 0
            for entry in c.schema[c.schema_name].tables.values():
                tbl = getattr(entry, "table", None)
                for col in getattr(tbl, "columns", []):
                    tbl_bytes += int(col.data.nbytes)
                    if col.mask is not None:
                        tbl_bytes += int(col.mask.nbytes)
            mem.setdefault("table_bytes_resident", tbl_bytes)
        except Exception:
            pass
        # adaptive-dispatch counters (operator_choice_* + the stats
        # cap-hint/scheduler-source evidence) ride the stage_done record
        opc = {}
        try:
            from dask_sql_tpu.runtime import telemetry as _tl
            for k, v in _tl.REGISTRY.counters().items():
                if (k.startswith("operator_choice_")
                        or k in ("stats_cap_hints", "estimate_from_stats",
                                 "stats_tables_collected")):
                    opc[k] = int(v)
        except Exception:
            pass
        emit({"stage_done": True, "load_sec": round(load_sec, 1),
              "warmup_sec": round(warmup_sec, 1), "device_memory": mem,
              "compiled_stats": dict(compiled.stats),
              "operator_counters": opc})
        sys.stdout.flush()
        sys.stderr.flush()
    os._exit(0)  # don't join wedged warmup threads


def main():
    import subprocess

    t_start = time.monotonic()
    deadline = t_start + TOTAL_BUDGET

    state = {
        "progress": None, "qids": [], "sf": SF, "n_lineitem": 0,
        "gen_sec": 0.0, "platform_choice": "?", "stage_meta": [],
        "emitted": False, "child": None,
    }
    emit_lock = threading.Lock()

    def _kill_child():
        """Emergency exits must not orphan an engine child wedged in a
        tunnel compile — it would hold the TPU and poison the next run."""
        proc = state.get("child")
        if proc is not None and proc.poll() is None:
            try:
                proc.kill()
            except OSError:
                pass

    def emit_final(reason=None):
        """Idempotent: compute the metric line from the progress journal and
        print it.  Callable from the watchdog thread, signal handlers,
        atexit, or the happy path — whoever gets there first wins.  The
        lock is held through the PRINT: a second caller (watchdog about to
        os._exit) must block until the line is fully out, or the exit
        could truncate it mid-write."""
        if state.get("emitting_thread") == threading.get_ident():
            # re-entered from a signal handler interrupting our own print:
            # returning lets the interrupted emission complete
            return
        # block TERM/INT for the duration on the main thread: a handler
        # firing between lock acquisition and the marker assignment would
        # re-enter emit_final and deadlock on the non-reentrant lock
        is_main = threading.current_thread() is threading.main_thread()
        old_mask = None
        if is_main:
            try:
                old_mask = signal.pthread_sigmask(
                    signal.SIG_BLOCK, {signal.SIGTERM, signal.SIGINT})
            except (ValueError, OSError):
                pass
        try:
            with emit_lock:
                if state["emitted"]:
                    return
                state["emitting_thread"] = threading.get_ident()
                try:
                    _emit_locked(reason)
                    state["emitted"] = True
                finally:
                    state["emitting_thread"] = None
                    if state.get("die_after_emit"):
                        os._exit(0)
        finally:
            if old_mask is not None:
                signal.pthread_sigmask(signal.SIG_SETMASK, old_mask)

    def _emit_locked(reason):
        times, p_times, platforms = {}, {}, set()
        warm_times, mem, cstats = {}, {}, {}
        started, warm_fails, breakdowns, quiesced = set(), {}, {}, set()
        warm_hits = {}
        bursts = []
        query_ops, op_counters = {}, {}
        first_arrival, restart_times, restart_info = {}, {}, {}
        est_err, est_err_admitted, est_from_hist = {}, {}, None
        slo_att = None
        param_mix = None
        shard_scaling = None
        ooc_evidence = None
        mv_evidence = None
        autopilot_evidence = None
        fleet_evidence = None
        ingest_evidence = None
        load_sec = warmup_sec = 0.0
        try:
            with open(state["progress"]) as f:
                for line in f:
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if "q" in rec:
                        prev = times.get(rec["q"])
                        if prev is None or rec["sec"] < prev:
                            times[rec["q"]] = rec["sec"]
                            if rec.get("operators"):
                                # variant attribution follows the best rec
                                query_ops[rec["q"]] = rec["operators"]
                        if rec.get("breakdown"):
                            # breakdowns keep their own minimum over the
                            # records that carry one: a faster record
                            # WITHOUT a breakdown must not leave a stale
                            # split attributed to the published time
                            bprev = breakdowns.get(rec["q"])
                            if bprev is None or rec["sec"] < bprev[0]:
                                breakdowns[rec["q"]] = (rec["sec"],
                                                        rec["breakdown"])
                        platforms.add(rec["platform"])
                        if rec.get("quiesced"):
                            quiesced.add(rec["q"])
                    elif "pq" in rec:
                        p_times[rec["pq"]] = rec["sec"]
                    elif "burst" in rec:
                        bursts.append(rec)
                    elif "warm_hit" in rec:
                        warm_hits[rec["warm_hit"]] = {
                            "sec": rec["sec"], "hit": bool(rec.get("hit")),
                            "tier": rec.get("tier")}
                    elif "warm_q" in rec:
                        warm_times[rec["warm_q"]] = rec["sec"]
                    elif "first_arrival" in rec:
                        # keep the EARLIEST record: retries in later
                        # children are not "first" arrivals
                        first_arrival.setdefault(rec["first_arrival"],
                                                 rec["sec"])
                    elif "restart_q" in rec:
                        restart_times[rec["restart_q"]] = rec["sec"]
                    elif rec.get("restart_done"):
                        restart_info = rec
                    elif "shard_scaling" in rec:
                        shard_scaling = rec["shard_scaling"] or None
                    elif "shard_scaling_skip" in rec:
                        shard_scaling = {"skipped":
                                         rec["shard_scaling_skip"]}
                    elif "ooc" in rec:
                        ooc_evidence = rec["ooc"] or None
                    elif "mv" in rec:
                        mv_evidence = rec["mv"] or None
                    elif "autopilot" in rec:
                        autopilot_evidence = rec["autopilot"] or None
                    elif "fleet" in rec:
                        fleet_evidence = rec["fleet"] or None
                    elif "ingest" in rec:
                        ingest_evidence = rec["ingest"] or None
                    elif "slo_attainment" in rec:
                        slo_att = rec["slo_attainment"] or None
                    elif "param_mix" in rec:
                        param_mix = rec["param_mix"] or None
                    elif "estimate_error" in rec:
                        est_err = rec["estimate_error"] or {}
                        est_err_admitted = \
                            rec.get("estimate_error_admitted") or {}
                        est_from_hist = rec.get("estimate_from_history")
                    elif "warm_start" in rec:
                        started.add(rec["warm_start"])
                    elif "warm_fail" in rec:
                        q = rec["warm_fail"]
                        n, _ = warm_fails.get(q, (0, ""))
                        warm_fails[q] = (n + 1, rec.get("error", ""))
                    elif rec.get("stage_done"):
                        load_sec += rec.get("load_sec", 0)
                        warmup_sec += rec.get("warmup_sec", 0)
                        for k, v in (rec.get("device_memory") or {}).items():
                            mem[k] = max(mem.get(k, 0), v)
                        for k, v in (rec.get("compiled_stats") or {}).items():
                            cstats[k] = cstats.get(k, 0) + v
                        for k, v in (rec.get("operator_counters")
                                     or {}).items():
                            op_counters[k] = op_counters.get(k, 0) + v
        except Exception:
            pass
        done = sorted(times)
        qids = state["qids"] or sorted(set(done) | set(p_times))
        missing = [q for q in qids if q not in times]
        # every absent query names its own cause: the artifact must never
        # read as "no problems" while silently short of queries
        missing_detail = {}
        for q in missing:
            n, err = warm_fails.get(q, (0, ""))
            if n:
                missing_detail[str(q)] = {
                    "warm_failures": n, "last_error": err[:300],
                    "status": ("failed-twice (real verdict)" if n >= 2
                               else "failed-once (retryable)")}
            elif q in warm_times:
                missing_detail[str(q)] = {
                    "status": "compiled ok, never measured (out of time)"}
            elif q in started:
                missing_detail[str(q)] = {
                    "status": "warmup in flight when time ran out"}
            else:
                missing_detail[str(q)] = {"status": "never started"}
        # schema-versioned headline: the handful of numbers every consumer
        # (scripts/perf_sentinel.py, the BENCH_r*.json trajectory) compares
        # across runs without spelunking through detail
        fa_vals = list(first_arrival.values())
        headline = {
            "schema": 1,
            "first_arrival_sec": (round(_geomean(fa_vals), 4)
                                  if fa_vals else None),
            "program_store_hit_rate": (
                round(restart_info["program_store_hits"]
                      / max(restart_info["program_store_hits"]
                            + restart_info["compiles"], 1), 3)
                if restart_info else None),
            "vs_pandas_geomean": None,
            "warm_exec_geomean_sec": None,
            "compile_errors": int(cstats.get("compile_errors", 0)),
            # watchtower SLO attainment per priority class over the
            # concurrent-burst pass (the one scheduler-armed window);
            # None when the burst never ran
            "slo_attainment": slo_att,
            # parameterized plan identity (ISSUE 16): fraction of the
            # Zipf param-mix executions served by an already-compiled
            # program of their shape; None when the mix never ran
            "param_plan_hit_rate": (
                round(param_mix["param_plan_hits"]
                      / max(param_mix["executions"], 1), 3)
                if param_mix else None),
            # fleet plane (ISSUE 18, BENCH_FLEET=1): cross-replica warm
            # serves off the shared program store and the fleet-wide
            # plan-cache hit rate over the multi-replica Zipf burst;
            # None when the fleet pass never ran
            "fleet_warm_serves": (fleet_evidence or {}).get("warm_serves"),
            "fleet_plan_cache_hit_rate":
                (fleet_evidence or {}).get("plan_cache_hit_rate"),
            # autopilot (ISSUE 19, BENCH_AUTOPILOT=1): the unattended
            # workload's steady-state geomean over the hand-tuned one
            # (~1.0 = the advisor converged to the operator's setup);
            # None when the pass never ran
            "autopilot_vs_tuned_geomean":
                (autopilot_evidence or {}).get("vs_tuned_geomean"),
        }
        if not done:
            out = {"metric": "tpch_q1_q22_geomean_wall", "value": -1,
                   "unit": "s", "vs_baseline": 0,
                   "headline": headline,
                   "detail": {"error": "no engine queries completed",
                              "reason": reason,
                              "sf": state["sf"],
                              "platform_choice": state["platform_choice"],
                              "pandas_sec": {str(k): round(v, 4)
                                             for k, v in p_times.items()},
                              "stages": state["stage_meta"]}}
        else:
            ok_b = [b for b in bursts if b.get("outcome") == "ok"
                    and b.get("queued_ms") is not None]
            burst_queue = None
            if ok_b:
                q_ms = [b["queued_ms"] for b in ok_b]
                burst_queue = {
                    "p50": round(_pctile(q_ms, 50), 1),
                    "p90": round(_pctile(q_ms, 90), 1),
                    "by_class": {
                        p: round(_pctile([b["queued_ms"] for b in ok_b
                                          if b.get("priority") == p], 50), 1)
                        for p in ("interactive", "batch")
                        if any(b.get("priority") == p for b in ok_b)},
                }
            geo_e = _geomean([times[q] for q in done])
            based = [q for q in done if q in p_times]
            geo_p = _geomean([p_times[q] for q in based]) if based else 0.0
            ratio = (_geomean([p_times[q] / times[q] for q in based])
                     if based else 0.0)
            wins = sum(1 for q in based if times[q] < p_times[q])
            headline["vs_pandas_geomean"] = round(ratio, 3)
            headline["warm_exec_geomean_sec"] = round(geo_e, 4)
            out = {
                "metric": "tpch_q1_q22_geomean_wall",
                "value": round(geo_e, 4),
                "unit": "s (geomean over completed queries, lower is better)",
                "vs_baseline": round(ratio, 3),
                "headline": headline,
                "detail": {
                    "sf": state["sf"],
                    "platform": "/".join(sorted(platforms)),
                    "lineitem_rows": state["n_lineitem"],
                    "queries": len(done),
                    "missing_queries": missing,
                    "missing_detail": missing_detail,
                    "quiesced_queries": sorted(quiesced),
                    "reason": reason,
                    "stage_errors": state["stage_meta"],
                    "engine_wins": wins,
                    "engine_sec": {str(k): round(times[k], 4) for k in done},
                    "query_breakdown_ms": {str(k): breakdowns[k][1]
                                           for k in sorted(breakdowns)},
                    "pandas_sec": {str(k): round(p_times[k], 4)
                                   for k in sorted(p_times)},
                    "pandas_geomean_sec": round(geo_p, 4),
                    # the PR-10 success metric spelled out: geomean of
                    # per-query pandas/engine speedups (same number as
                    # vs_baseline; >1.0 = the engine beats pandas warm)
                    "vs_pandas_geomean": round(ratio, 3),
                    # adaptive-dispatch evidence (runtime/statistics.py):
                    # which variant each published time ran on, and the
                    # operator_choice_* counter totals across the run
                    "query_operators": {str(k): query_ops[k]
                                        for k in sorted(query_ops)},
                    "operator_choice": op_counters or None,
                    "warm_or_compile_sec_per_query":
                        {str(k): warm_times[k] for k in sorted(warm_times)},
                    # tiered-execution / program-store evidence: latency of
                    # each query's very first submission (cold store = the
                    # compile wall; primed store = store-load + execute)...
                    "first_arrival_sec": {str(k): first_arrival[k]
                                          for k in sorted(first_arrival)},
                    # ...and the restart-warm pass: a FRESH process against
                    # the populated DSQL_PROGRAM_STORE (zero-compile proof)
                    "restart_warm_sec": {str(k): restart_times[k]
                                         for k in sorted(restart_times)},
                    "warm_start_sec": restart_info.get("warm_start_sec"),
                    # multi-chip evidence (parallel/spmd.py): Q1/Q6 wall
                    # time single-device vs row-sharded over the mesh,
                    # with spmd_served certifying the sharded path ran
                    "shard_scaling": shard_scaling,
                    # out-of-core evidence (runtime/spill.py +
                    # physical/morsel.py): chunked Q1/Q6/Q3 completed and
                    # matched the resident engine, with spill traffic and
                    # the spill store's peak device occupancy
                    "ooc": ooc_evidence,
                    # incremental-view evidence (runtime/matview.py,
                    # BENCH_MV=1): maintained refresh vs full recompute
                    # of the defining query after a 1k-row append into
                    # lineitem, with the mv refresh hit-rate and the
                    # served-vs-recomputed exactness verdict
                    "mv": mv_evidence,
                    # autopilot evidence (runtime/autopilot.py,
                    # BENCH_AUTOPILOT=1): unattended vs hand-tuned
                    # append-then-read rounds, the advisor's auto-created
                    # views/serves, and the exactness verdict
                    "autopilot": autopilot_evidence,
                    # fleet-plane evidence (runtime/fleet.py,
                    # BENCH_FLEET=1): two replicas on one fleet dir +
                    # program store under a Zipf multi-tenant burst —
                    # per-tenant SLO attainment, replica B's zero-compile
                    # warm serves, and the fleet plan-cache hit rate
                    "fleet": fleet_evidence,
                    # continuous-ingestion evidence (runtime/ingest.py,
                    # BENCH_INGEST=1): WAL-armed appends beside maintained
                    # view reads — appends/sec, read p50/p99, the max
                    # observed staleness, and the exactness verdict
                    "ingest": ingest_evidence,
                    "program_store_hit_rate": (
                        round(restart_info["program_store_hits"]
                              / max(restart_info["program_store_hits"]
                                    + restart_info["compiles"], 1), 3)
                        if restart_info else None),
                    # result-cache evidence from the warm-repeat pass: the
                    # 2nd run of each query with the cache armed (cold
                    # numbers above always run cache-off)
                    "warm_hit_sec": {str(k): warm_hits[k]["sec"]
                                     for k in sorted(warm_hits)},
                    "result_cache_hit_rate": (
                        round(sum(1 for v in warm_hits.values() if v["hit"])
                              / len(warm_hits), 3) if warm_hits else None),
                    # workload-manager evidence from the concurrent-burst
                    # pass (2-slot scheduler, 8 mixed-priority threads):
                    # the fraction the admission controller turned away,
                    # and queue-time percentiles for the admitted rest
                    # sublinearity proof (ISSUE 16): a Zipf client mix of
                    # one query shape with many distinct literals — the
                    # compile count must track SHAPES (1), not literals
                    "compiles_vs_distinct_literals": param_mix,
                    "admission_reject_rate": (
                        round(sum(1 for b in bursts
                                  if b.get("outcome") == "rejected")
                              / len(bursts), 3) if bursts else None),
                    "burst_queue_time_ms": burst_queue,
                    # estimate-feedback evidence (runtime/flight_recorder):
                    # mean |estimated - actual| / actual working-set bytes
                    # per estimate source — "history" shrinking under
                    # "heuristic" is the loop closing — plus admission-time
                    # envelope errors and the estimate_from_history count
                    "estimate_error_by_source": est_err or None,
                    "estimate_error_admitted": est_err_admitted or None,
                    "estimate_from_history": est_from_hist,
                    "gen_sec": round(state["gen_sec"], 1),
                    "load_sec": round(load_sec, 1),
                    "warmup_compile_sec": round(warmup_sec, 1),
                    "compiled_stats": cstats,
                    # stage-program cache effectiveness across the run:
                    # hits / (hits + compiles), the number every perf PR
                    # watches in the BENCH_r*.json trajectory
                    "stage_cache_hit_rate": (
                        round(cstats.get("stage_hits", 0)
                              / (cstats.get("stage_hits", 0)
                                 + cstats.get("stage_compiles", 0)), 3)
                        if (cstats.get("stage_hits", 0)
                            + cstats.get("stage_compiles", 0)) else None),
                    "device_memory": mem,
                    "budget_sec": TOTAL_BUDGET,
                    "elapsed_sec": round(time.monotonic() - t_start, 1),
                },
            }
        line = json.dumps(out)
        # results FILE first: it survives even a truncated stdout.  The
        # write is atomic (tmp + replace) so a kill mid-emit can't leave a
        # half-written artifact.
        results_path = os.environ.get("BENCH_RESULTS_FILE")
        if not results_path and state["progress"]:
            results_path = os.path.join(
                os.path.dirname(state["progress"]), "bench_result.json")
        if not results_path:
            # the metrics object must ALWAYS land in a file: r05's artifact
            # read "parsed": null because the bare stdout line was fished
            # out of a mangled log tail
            results_path = os.path.join(os.getcwd(), "bench_result.json")
        if results_path:
            try:
                tmp = f"{results_path}.tmp{os.getpid()}"
                with open(tmp, "w") as f:
                    f.write(line + "\n")
                os.replace(tmp, results_path)
            except OSError:
                pass
        # leading newline forces the bare line out of any partial log line;
        # the sentinel copy is immune to interleaved ANSI/log output
        sys.stdout.flush()
        print("\n" + line, flush=True)
        print("DSQL_BENCH_RESULT " + line, flush=True)

    def _die(signum, frame):
        _kill_child()
        if state.get("emitting_thread") == threading.get_ident():
            # the signal interrupted our own in-progress emission: mark it
            # and let the print finish (the finally above exits for us)
            state["die_after_emit"] = True
            return
        emit_final(reason=f"signal {signum}")
        os._exit(0)

    signal.signal(signal.SIGTERM, _die)
    signal.signal(signal.SIGINT, _die)
    atexit.register(lambda: emit_final(reason="atexit"))

    workdir = os.environ.get("BENCH_WORKDIR") or tempfile.mkdtemp(
        prefix="bench_tpch_")
    data_dir = os.path.join(workdir, "data")
    os.makedirs(data_dir, exist_ok=True)
    progress = os.path.join(workdir, "progress.jsonl")
    open(progress, "w").close()
    state["progress"] = progress

    # the watchdog is armed BEFORE any expensive step: from here on the
    # metric line prints no matter where time runs out
    watchdog = threading.Timer(
        max(deadline - EMIT_MARGIN - time.monotonic(), 1.0),
        lambda: (emit_final(reason="watchdog"), _kill_child(),
                 os._exit(0)))
    watchdog.daemon = True
    watchdog.start()

    platform = _probe_platform()
    state["platform_choice"] = platform
    if platform == "cpu" and "BENCH_SF" not in os.environ:
        # tunnel-down fallback: the engine is TPU-first and the host may
        # have one core — a smaller SF keeps the fallback inside the
        # watchdog while still covering all 22 queries (platform is
        # recorded either way)
        sf = float(os.environ.get("BENCH_FALLBACK_SF", "0.1"))
    else:
        sf = SF
    state["sf"] = sf

    gen_sec, n_lineitem = _cache_data(sf, data_dir)
    state["gen_sec"] = gen_sec
    state["n_lineitem"] = n_lineitem

    from benchmarks.tpch import QUERIES
    qids = sorted(QUERIES)
    only = os.environ.get("BENCH_QUERIES")
    if only:
        only_set = {int(x) for x in only.split(",")}
        qids = [q for q in qids if q in only_set]
    qids = _order(qids)
    state["qids"] = sorted(qids)

    # ---- pandas baseline FIRST (cheap, cannot wedge): single-threaded
    # host pandas, hand-written per query, oracle-validated against the
    # engine in tests/integration/test_pandas_oracle.py
    from benchmarks.pandas_tpch import PANDAS_QUERIES
    data = _load_data(data_dir)
    p_deadline = min(time.monotonic() + PANDAS_BUDGET,
                     deadline - EMIT_MARGIN - 10)
    with open(progress, "a") as pf:
        for qid in qids:
            if time.monotonic() > p_deadline:
                break
            fn = PANDAS_QUERIES.get(qid)
            if fn is None:
                continue
            best = float("inf")
            try:
                for _ in range(PANDAS_REPS):
                    t0 = time.perf_counter()
                    fn(data)
                    best = min(best, time.perf_counter() - t0)
                    if time.monotonic() > p_deadline:
                        break
            except Exception as e:
                # one broken baseline query must not cost the whole bench
                print(f"bench: pandas baseline q{qid} failed: {e!r}",
                      file=sys.stderr)
                continue
            pf.write(json.dumps({"pq": qid, "sec": round(best, 4)}) + "\n")
            pf.flush()
    del data

    # ---- engine: one child (table transfer is paid once); restart on the
    # remaining queries only while enough budget remains
    uid = os.getuid() if hasattr(os, "getuid") else 0
    cache_root = os.path.join(tempfile.gettempdir(),
                              f"dsql_bench_cache_{platform}_u{uid}")
    os.makedirs(cache_root, mode=0o700, exist_ok=True)
    if hasattr(os, "getuid") and os.stat(cache_root).st_uid != uid:
        # someone else pre-created the path: don't trust (or feed) a
        # foreign program cache — fall back to a private dir
        cache_root = tempfile.mkdtemp(prefix="dsql_bench_cache_")
    env_base = dict(os.environ, BENCH_STAGE="1",
                    BENCH_DATA_DIR=data_dir,
                    BENCH_PROGRESS=progress,
                    BENCH_PLATFORM_CHOICE=platform,
                    BENCH_SF=str(sf))
    # never eager-fallback in the engine child: over the tunneled TPU the
    # eager path is thousands of ~100 ms round trips that wedge the whole
    # run behind one broken program — fail fast, journal warm_fail, move on
    env_base.setdefault("DSQL_EAGER_FALLBACK", "0")
    env_base.setdefault("DSQL_XLA_CACHE", os.path.join(cache_root, "xla"))
    env_base.setdefault("DSQL_CAPS_FILE",
                        os.path.join(cache_root, "caps.json"))
    # persistent program store (runtime/program_store.py): the measurement
    # child populates it, the restart-warm child below proves a fresh
    # process serves every query with zero XLA compiles, and a bench run
    # primed by an earlier run on this host starts warm outright
    env_base.setdefault("DSQL_PROGRAM_STORE",
                        os.path.join(cache_root, "programs"))
    # flight recorder (runtime/flight_recorder.py): the measurement child
    # leaves per-query envelopes + operator statistics, so the burst pass
    # estimates its admissions from MEASURED history and the child can
    # journal estimate-vs-actual byte error against the scan-bytes guess
    env_base.setdefault("DSQL_HISTORY_FILE",
                        os.path.join(cache_root, "history.jsonl"))

    def journal_state():
        """(measured set, warm-failure counts) from the progress file."""
        got, failed = set(), {}
        with open(progress) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if "q" in rec:
                    got.add(rec["q"])
                elif "warm_fail" in rec:
                    failed[rec["warm_fail"]] = \
                        failed.get(rec["warm_fail"], 0) + 1
        return got, failed

    attempt = 0
    max_attempts = int(os.environ.get("BENCH_MAX_CHILDREN", "3"))
    # per-attempt DSQL_SPLIT_HEAVY schedule ("-" = engine default).  The
    # primary splitting mechanism is the engine's learned per-plan hint
    # (wedged/failed compiles persist "__split__" into the caps file, so
    # retry children split exactly the guilty plans and nothing else);
    # this env schedule is the LAST-RESORT hammer for a final child when
    # hints could not be written.  Measured on the tunneled TPU (r5):
    # Q3's whole program never returns from the remote helper, split=2
    # SIGSEGVs it, split=1 compiles in ~290 s and runs.
    split_schedule = os.environ.get("BENCH_SPLIT_SCHEDULE", "-,-,1").split(",")
    while attempt < max_attempts:
        got, failed = journal_state()
        # compile failures over the tunnel are often TRANSIENT (the remote
        # helper gets OOM-killed under load), and wedge-detected stragglers
        # deserve a smaller-program retry — a strike earned at a higher
        # split threshold must not bar the retry at a lower one, so a
        # query stays retryable while its failure count <= attempt number
        remaining_q = [q for q in qids
                       if q not in got and failed.get(q, 0) <= attempt]
        budget_left = deadline - EMIT_MARGIN - time.monotonic()
        if not remaining_q or budget_left < MIN_CHILD_BUDGET:
            break
        child_deadline_ts = time.time() + budget_left - 10
        env = dict(env_base,
                   BENCH_STAGE_QUERIES=",".join(map(str, remaining_q)),
                   BENCH_CHILD_DEADLINE=str(child_deadline_ts))
        split = (split_schedule[attempt] if attempt < len(split_schedule)
                 else split_schedule[-1])
        if split.strip() not in ("", "-"):
            env["DSQL_SPLIT_HEAVY"] = split.strip()
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True)
        state["child"] = proc  # emergency exits kill it (no orphans)
        try:
            _, err = proc.communicate(timeout=budget_left)
            if proc.returncode != 0:
                sys.stderr.write(err[-2000:])
                state["stage_meta"].append(
                    {"attempt": attempt, "error": f"rc={proc.returncode}"})
            # a clean exit does NOT end the loop: the child may have
            # retired at its deadline or given up on failed warmups — the
            # while condition relaunches on whatever queries remain, and
            # exits when none do
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.communicate()  # reap: no zombie + closed pipe FDs
            print(f"bench: engine child {attempt} exceeded its "
                  f"{budget_left:.0f}s budget; collecting partials",
                  file=sys.stderr)
            state["stage_meta"].append({"attempt": attempt,
                                        "error": "timeout"})
        finally:
            state["child"] = None
        attempt += 1

    # salvage INSIDE the budget (the r3 version ran past it, which is what
    # killed BENCH_r03): if the tunnel passed the probe but every engine
    # child wedged, record engine-on-CPU numbers on the same data with
    # whatever budget remains — partial engine numbers beat none
    # gate on MEASURED queries only: TPU warm failures don't predict CPU
    # failure, so warm_fail records must not suppress the salvage
    salvage_left = deadline - EMIT_MARGIN - time.monotonic()
    if (platform == "default" and salvage_left > MIN_CHILD_BUDGET
            and not any(q in journal_state()[0] for q in qids)):
        print("bench: no TPU queries completed; salvaging on CPU within "
              f"the remaining {salvage_left:.0f}s", file=sys.stderr)
        env = dict(env_base, BENCH_PLATFORM_CHOICE="cpu",
                   BENCH_STAGE_QUERIES=",".join(map(str, qids)),
                   BENCH_CHILD_DEADLINE=str(time.time() + salvage_left - 10))
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True)
        state["child"] = proc
        try:
            proc.communicate(timeout=salvage_left)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.communicate()  # reap
            state["stage_meta"].append({"attempt": "cpu_salvage",
                                        "error": "timeout"})
        finally:
            state["child"] = None

    # RESTART-WARM pass: a FRESH process against the populated program
    # store re-runs the measured queries — the cross-process warm-start
    # evidence (program_store_hit_rate, warm_start_sec, per-query
    # restart_warm_sec) without touching the cold numbers above
    restart_left = deadline - EMIT_MARGIN - time.monotonic()
    got_now = sorted(journal_state()[0])
    if got_now and restart_left > 60:
        env = dict(env_base, BENCH_WARM_RESTART="1",
                   BENCH_STAGE_QUERIES=",".join(map(str, got_now)),
                   BENCH_CHILD_DEADLINE=str(time.time() + restart_left - 10))
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True)
        state["child"] = proc
        try:
            proc.communicate(timeout=restart_left)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.communicate()  # reap
            state["stage_meta"].append({"attempt": "restart_warm",
                                        "error": "timeout"})
        finally:
            state["child"] = None

    # SHARD-SCALING pass: Q1/Q6 single-device vs row-sharded over the
    # device mesh through the explicit SPMD executor.  The XLA_FLAGS
    # default gives a CPU-only host its 8-virtual-device mesh; a real
    # multi-chip host keeps its own devices.
    scaling_left = deadline - EMIT_MARGIN - time.monotonic()
    if scaling_left > 60:
        env = dict(env_base, BENCH_SHARD_SCALING="1",
                   BENCH_STAGE_QUERIES="1,6",
                   BENCH_CHILD_DEADLINE=str(time.time() + scaling_left - 10))
        env.setdefault("XLA_FLAGS",
                       "--xla_force_host_platform_device_count=8")
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True)
        state["child"] = proc
        try:
            proc.communicate(timeout=scaling_left)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.communicate()  # reap
            state["stage_meta"].append({"attempt": "shard_scaling",
                                        "error": "timeout"})
        finally:
            state["child"] = None

    # OUT-OF-CORE pass (opt-in: BENCH_OOC=1): chunked Q1/Q6/Q3 through the
    # streaming + grace-hash spill path, checked against the resident
    # engine — journals ooc_completed / spill_bytes / peak_device_bytes
    ooc_left = deadline - EMIT_MARGIN - time.monotonic()
    if os.environ.get("BENCH_OOC") == "1" and ooc_left > 60:
        env = dict(env_base, BENCH_OOC_CHILD="1",
                   BENCH_STAGE_QUERIES="1,6,3",
                   BENCH_CHILD_DEADLINE=str(time.time() + ooc_left - 10))
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True)
        state["child"] = proc
        try:
            proc.communicate(timeout=ooc_left)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.communicate()  # reap
            state["stage_meta"].append({"attempt": "ooc",
                                        "error": "timeout"})
        finally:
            state["child"] = None

    # MATERIALIZED-VIEW pass (opt-in: BENCH_MV=1): an aggregate view over
    # lineitem maintained through a 1k-row append — journals refresh_sec
    # vs recompute_sec, the mv refresh hit-rate, and the served-vs-
    # recomputed exactness verdict (runtime/matview.py)
    mv_left = deadline - EMIT_MARGIN - time.monotonic()
    if os.environ.get("BENCH_MV") == "1" and mv_left > 60:
        env = dict(env_base, BENCH_MV_CHILD="1",
                   BENCH_STAGE_QUERIES="1",
                   BENCH_CHILD_DEADLINE=str(time.time() + mv_left - 10))
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True)
        state["child"] = proc
        try:
            proc.communicate(timeout=mv_left)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.communicate()  # reap
            state["stage_meta"].append({"attempt": "mv",
                                        "error": "timeout"})
        finally:
            state["child"] = None

    # AUTOPILOT pass (opt-in: BENCH_AUTOPILOT=1): unattended convergence
    # vs a hand-tuned matview under the same append-then-read rounds —
    # journals the unattended-vs-tuned geomean ratio the perf sentinel
    # shows as an informational row (runtime/autopilot.py)
    ap_left = deadline - EMIT_MARGIN - time.monotonic()
    if os.environ.get("BENCH_AUTOPILOT") == "1" and ap_left > 60:
        env = dict(env_base, BENCH_AUTOPILOT_CHILD="1",
                   BENCH_STAGE_QUERIES="1",
                   BENCH_CHILD_DEADLINE=str(time.time() + ap_left - 10))
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True)
        state["child"] = proc
        try:
            proc.communicate(timeout=ap_left)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.communicate()  # reap
            state["stage_meta"].append({"attempt": "autopilot",
                                        "error": "timeout"})
        finally:
            state["child"] = None

    # FLEET pass (opt-in: BENCH_FLEET=1): two server replicas on one
    # shared DSQL_FLEET_DIR + fresh shared program store, a Zipf
    # multi-tenant parameterized burst split across them — journals
    # per-tenant SLO attainment off the merged fleet plane, the
    # fleet-wide plan-cache hit rate, and the cross-replica warm-serve
    # verdict (replica B answers A's shapes with zero compiles)
    fleet_left = deadline - EMIT_MARGIN - time.monotonic()
    if os.environ.get("BENCH_FLEET") == "1" and fleet_left > 60:
        env = dict(env_base, BENCH_FLEET_CHILD="1",
                   BENCH_STAGE_QUERIES="1",
                   BENCH_CHILD_DEADLINE=str(time.time() + fleet_left - 10))
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True)
        state["child"] = proc
        try:
            proc.communicate(timeout=fleet_left)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.communicate()  # reap
            state["stage_meta"].append({"attempt": "fleet",
                                        "error": "timeout"})
        finally:
            state["child"] = None

    # CONTINUOUS-INGESTION pass (opt-in: BENCH_INGEST=1): WAL-armed
    # appends interleaved with maintained-view reads — journals sustained
    # appends/sec x read p99 x max staleness, plus the exactness verdict
    # of the served view vs a recompute (runtime/ingest.py)
    ing_left = deadline - EMIT_MARGIN - time.monotonic()
    if os.environ.get("BENCH_INGEST") == "1" and ing_left > 60:
        env = dict(env_base, BENCH_INGEST_CHILD="1",
                   BENCH_STAGE_QUERIES="1",
                   BENCH_CHILD_DEADLINE=str(time.time() + ing_left - 10))
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True)
        state["child"] = proc
        try:
            proc.communicate(timeout=ing_left)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.communicate()  # reap
            state["stage_meta"].append({"attempt": "ingest",
                                        "error": "timeout"})
        finally:
            state["child"] = None

    watchdog.cancel()
    emit_final(reason="complete")


if __name__ == "__main__":
    if os.environ.get("BENCH_STAGE") == "1":
        _stage_main()
    else:
        main()
