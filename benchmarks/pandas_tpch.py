"""Hand-written pandas implementations of all 22 TPC-H queries.

This is the benchmark BASELINE: the reference executes queries as pandas
operations on dataframe partitions (dask_sql lowers Calcite plans onto
dd.DataFrame — single-partition execution IS pandas), so single-threaded
pandas on the same host is the honest stand-in for the reference's
per-partition substrate (BASELINE.md publishes no absolute numbers).

The implementations are written independently from the engine (no shared
code below the DataFrame API), so tests can also use them as a second
differential oracle against the SQLite one: agreement of three independent
executors (engine / sqlite / pandas) on 22 queries is strong evidence.

Parameter values match benchmarks/tpch.py QUERIES verbatim.
"""
from __future__ import annotations

import pandas as pd

_TS = pd.Timestamp


def _sql_sum(s):
    """SQL SUM over zero rows is NULL, not 0 (pandas' .sum() says 0)."""
    return s.sum() if len(s) else float("nan")


def q1(d):
    li = d["lineitem"]
    # narrow before copying: materializing all 16 columns of the ~98%
    # selectivity filter tripled the runtime at SF 1
    x = li.loc[li["l_shipdate"] <= _TS("1998-09-02"),
               ["l_returnflag", "l_linestatus", "l_quantity",
                "l_extendedprice", "l_discount", "l_tax"]].copy()
    x["disc_price"] = x["l_extendedprice"] * (1 - x["l_discount"])
    x["charge"] = x["disc_price"] * (1 + x["l_tax"])
    out = x.groupby(["l_returnflag", "l_linestatus"], as_index=False).agg(
        sum_qty=("l_quantity", "sum"),
        sum_base_price=("l_extendedprice", "sum"),
        sum_disc_price=("disc_price", "sum"),
        sum_charge=("charge", "sum"),
        avg_qty=("l_quantity", "mean"),
        avg_price=("l_extendedprice", "mean"),
        avg_disc=("l_discount", "mean"),
        count_order=("l_quantity", "count"))
    return out.sort_values(["l_returnflag", "l_linestatus"],
                           ignore_index=True)


def q2(d):
    p, s, ps = d["part"], d["supplier"], d["partsupp"]
    n, r = d["nation"], d["region"]
    eu = n.merge(r[r["r_name"] == "EUROPE"], left_on="n_regionkey",
                 right_on="r_regionkey")
    s_eu = s.merge(eu, left_on="s_nationkey", right_on="n_nationkey")
    ps_eu = ps.merge(s_eu, left_on="ps_suppkey", right_on="s_suppkey")
    min_cost = ps_eu.groupby("ps_partkey")["ps_supplycost"].min()
    pf = p[(p["p_size"] == 15) & p["p_type"].str.endswith("BRASS")]
    m = ps_eu.merge(pf, left_on="ps_partkey", right_on="p_partkey")
    m = m[m["ps_supplycost"] == m["ps_partkey"].map(min_cost)]
    out = m[["s_acctbal", "s_name", "n_name", "p_partkey", "p_mfgr",
             "s_address", "s_phone", "s_comment"]]
    return out.sort_values(
        ["s_acctbal", "n_name", "s_name", "p_partkey"],
        ascending=[False, True, True, True], ignore_index=True).head(100)


def q3(d):
    cu, od, li = d["customer"], d["orders"], d["lineitem"]
    c = cu[cu["c_mktsegment"] == "BUILDING"]
    o = od[od["o_orderdate"] < _TS("1995-03-15")]
    l = li[li["l_shipdate"] > _TS("1995-03-15")]
    m = c.merge(o, left_on="c_custkey", right_on="o_custkey").merge(
        l, left_on="o_orderkey", right_on="l_orderkey")
    m["revenue"] = m["l_extendedprice"] * (1 - m["l_discount"])
    g = m.groupby(["l_orderkey", "o_orderdate", "o_shippriority"],
                  as_index=False)["revenue"].sum()
    g = g.sort_values(["revenue", "o_orderdate"], ascending=[False, True],
                      ignore_index=True).head(10)
    return g[["l_orderkey", "revenue", "o_orderdate", "o_shippriority"]]


def q4(d):
    od, li = d["orders"], d["lineitem"]
    o = od[(od["o_orderdate"] >= _TS("1993-07-01"))
           & (od["o_orderdate"] < _TS("1993-10-01"))]
    late = li[li["l_commitdate"] < li["l_receiptdate"]]
    o = o[o["o_orderkey"].isin(late["l_orderkey"])]
    out = o.groupby("o_orderpriority", as_index=False).agg(
        order_count=("o_orderkey", "count"))
    return out.sort_values("o_orderpriority", ignore_index=True)


def q5(d):
    cu, od, li = d["customer"], d["orders"], d["lineitem"]
    s, n, r = d["supplier"], d["nation"], d["region"]
    asia = n.merge(r[r["r_name"] == "ASIA"], left_on="n_regionkey",
                   right_on="r_regionkey")
    o = od[(od["o_orderdate"] >= _TS("1994-01-01"))
           & (od["o_orderdate"] < _TS("1995-01-01"))]
    m = (o.merge(cu, left_on="o_custkey", right_on="c_custkey")
          .merge(li, left_on="o_orderkey", right_on="l_orderkey")
          .merge(s, left_on="l_suppkey", right_on="s_suppkey"))
    m = m[m["c_nationkey"] == m["s_nationkey"]]
    m = m.merge(asia, left_on="s_nationkey", right_on="n_nationkey")
    m["revenue"] = m["l_extendedprice"] * (1 - m["l_discount"])
    out = m.groupby("n_name", as_index=False)["revenue"].sum()
    return out.sort_values("revenue", ascending=False, ignore_index=True)


def q6(d):
    li = d["lineitem"]
    x = li[(li["l_shipdate"] >= _TS("1994-01-01"))
           & (li["l_shipdate"] < _TS("1995-01-01"))
           & (li["l_discount"] >= 0.05) & (li["l_discount"] <= 0.07)
           & (li["l_quantity"] < 24)]
    return pd.DataFrame(
        {"revenue": [_sql_sum(x["l_extendedprice"] * x["l_discount"])]})


def q7(d):
    s, li, od = d["supplier"], d["lineitem"], d["orders"]
    cu, n = d["customer"], d["nation"]
    fr_ge = n[n["n_name"].isin(["FRANCE", "GERMANY"])]
    l = li[(li["l_shipdate"] >= _TS("1995-01-01"))
           & (li["l_shipdate"] <= _TS("1996-12-31"))]
    m = (l.merge(s, left_on="l_suppkey", right_on="s_suppkey")
          .merge(fr_ge.rename(columns=lambda c: c + "_1"),
                 left_on="s_nationkey", right_on="n_nationkey_1")
          .merge(od, left_on="l_orderkey", right_on="o_orderkey")
          .merge(cu, left_on="o_custkey", right_on="c_custkey")
          .merge(fr_ge.rename(columns=lambda c: c + "_2"),
                 left_on="c_nationkey", right_on="n_nationkey_2"))
    m = m[((m["n_name_1"] == "FRANCE") & (m["n_name_2"] == "GERMANY"))
          | ((m["n_name_1"] == "GERMANY") & (m["n_name_2"] == "FRANCE"))]
    m = m.rename(columns={"n_name_1": "supp_nation",
                          "n_name_2": "cust_nation"})
    m["l_year"] = m["l_shipdate"].dt.year
    m["volume"] = m["l_extendedprice"] * (1 - m["l_discount"])
    out = m.groupby(["supp_nation", "cust_nation", "l_year"],
                    as_index=False).agg(revenue=("volume", "sum"))
    return out.sort_values(["supp_nation", "cust_nation", "l_year"],
                           ignore_index=True)


def q8(d):
    p, s, li, od = d["part"], d["supplier"], d["lineitem"], d["orders"]
    cu, n, r = d["customer"], d["nation"], d["region"]
    am = n.merge(r[r["r_name"] == "AMERICA"], left_on="n_regionkey",
                 right_on="r_regionkey")
    pf = p[p["p_type"] == "ECONOMY ANODIZED STEEL"]
    o = od[(od["o_orderdate"] >= _TS("1995-01-01"))
           & (od["o_orderdate"] <= _TS("1996-12-31"))]
    m = (li.merge(pf, left_on="l_partkey", right_on="p_partkey")
           .merge(o, left_on="l_orderkey", right_on="o_orderkey")
           .merge(cu, left_on="o_custkey", right_on="c_custkey")
           .merge(am[["n_nationkey"]], left_on="c_nationkey",
                  right_on="n_nationkey")
           .merge(s, left_on="l_suppkey", right_on="s_suppkey")
           .merge(n[["n_nationkey", "n_name"]].rename(
                columns={"n_nationkey": "nk2", "n_name": "nation"}),
                left_on="s_nationkey", right_on="nk2"))
    m["o_year"] = m["o_orderdate"].dt.year
    m["volume"] = m["l_extendedprice"] * (1 - m["l_discount"])
    m["brazil"] = m["volume"].where(m["nation"] == "BRAZIL", 0.0)
    g = m.groupby("o_year", as_index=False).agg(
        num=("brazil", "sum"), den=("volume", "sum"))
    g["mkt_share"] = g["num"] / g["den"]
    return g[["o_year", "mkt_share"]].sort_values(
        "o_year", ignore_index=True)


def q9(d):
    p, s, li = d["part"], d["supplier"], d["lineitem"]
    ps, od, n = d["partsupp"], d["orders"], d["nation"]
    pf = p[p["p_name"].str.contains("green", regex=False)]
    m = (li.merge(pf[["p_partkey"]], left_on="l_partkey",
                  right_on="p_partkey")
           .merge(s[["s_suppkey", "s_nationkey"]], left_on="l_suppkey",
                  right_on="s_suppkey")
           .merge(ps[["ps_partkey", "ps_suppkey", "ps_supplycost"]],
                  left_on=["l_partkey", "l_suppkey"],
                  right_on=["ps_partkey", "ps_suppkey"])
           .merge(od[["o_orderkey", "o_orderdate"]], left_on="l_orderkey",
                  right_on="o_orderkey")
           .merge(n[["n_nationkey", "n_name"]], left_on="s_nationkey",
                  right_on="n_nationkey"))
    m["o_year"] = m["o_orderdate"].dt.year
    m["amount"] = (m["l_extendedprice"] * (1 - m["l_discount"])
                   - m["ps_supplycost"] * m["l_quantity"])
    out = m.rename(columns={"n_name": "nation"}).groupby(
        ["nation", "o_year"], as_index=False).agg(
            sum_profit=("amount", "sum"))
    return out.sort_values(["nation", "o_year"], ascending=[True, False],
                           ignore_index=True)


def q10(d):
    cu, od, li, n = d["customer"], d["orders"], d["lineitem"], d["nation"]
    o = od[(od["o_orderdate"] >= _TS("1993-10-01"))
           & (od["o_orderdate"] < _TS("1994-01-01"))]
    l = li[li["l_returnflag"] == "R"]
    m = (cu.merge(o, left_on="c_custkey", right_on="o_custkey")
           .merge(l, left_on="o_orderkey", right_on="l_orderkey")
           .merge(n, left_on="c_nationkey", right_on="n_nationkey"))
    m["revenue"] = m["l_extendedprice"] * (1 - m["l_discount"])
    g = m.groupby(["c_custkey", "c_name", "c_acctbal", "c_phone", "n_name",
                   "c_address", "c_comment"], as_index=False)["revenue"].sum()
    g = g.sort_values("revenue", ascending=False, ignore_index=True).head(20)
    return g[["c_custkey", "c_name", "revenue", "c_acctbal", "n_name",
              "c_address", "c_phone", "c_comment"]]


def _q11_values(d):
    ps, s, n = d["partsupp"], d["supplier"], d["nation"]
    de = s.merge(n[n["n_name"] == "GERMANY"], left_on="s_nationkey",
                 right_on="n_nationkey")
    m = ps.merge(de[["s_suppkey"]], left_on="ps_suppkey",
                 right_on="s_suppkey")
    m = m.assign(value=m["ps_supplycost"] * m["ps_availqty"])
    return m


def q11(d):
    m = _q11_values(d)
    total = m["value"].sum() * 0.0001
    g = m.groupby("ps_partkey", as_index=False)["value"].sum()
    g = g[g["value"] > total]
    return g.sort_values("value", ascending=False, ignore_index=True)


def q12(d):
    od, li = d["orders"], d["lineitem"]
    l = li[li["l_shipmode"].isin(["MAIL", "SHIP"])
           & (li["l_commitdate"] < li["l_receiptdate"])
           & (li["l_shipdate"] < li["l_commitdate"])
           & (li["l_receiptdate"] >= _TS("1994-01-01"))
           & (li["l_receiptdate"] < _TS("1995-01-01"))]
    m = l.merge(od, left_on="l_orderkey", right_on="o_orderkey")
    hi = m["o_orderpriority"].isin(["1-URGENT", "2-HIGH"])
    m = m.assign(high_line=hi.astype("int64"),
                 low_line=(~hi).astype("int64"))
    out = m.groupby("l_shipmode", as_index=False).agg(
        high_line_count=("high_line", "sum"),
        low_line_count=("low_line", "sum"))
    return out.sort_values("l_shipmode", ignore_index=True)


def q13(d):
    cu, od = d["customer"], d["orders"]
    o = od[~od["o_comment"].str.contains("special.*requests", regex=True)]
    m = cu.merge(o[["o_custkey", "o_orderkey"]], left_on="c_custkey",
                 right_on="o_custkey", how="left")
    g = m.groupby("c_custkey")["o_orderkey"].count().rename("c_count")
    out = g.groupby(g).size().rename("custdist").reset_index()
    out.columns = ["c_count", "custdist"]
    return out.sort_values(["custdist", "c_count"], ascending=[False, False],
                           ignore_index=True)


def q14(d):
    li, p = d["lineitem"], d["part"]
    l = li[(li["l_shipdate"] >= _TS("1995-09-01"))
           & (li["l_shipdate"] < _TS("1995-10-01"))]
    m = l.merge(p[["p_partkey", "p_type"]], left_on="l_partkey",
                right_on="p_partkey")
    rev = m["l_extendedprice"] * (1 - m["l_discount"])
    promo = rev.where(m["p_type"].str.startswith("PROMO"), 0.0)
    return pd.DataFrame(
        {"promo_revenue": [100.0 * promo.sum() / rev.sum()]})


def q15(d):
    li, s = d["lineitem"], d["supplier"]
    l = li[(li["l_shipdate"] >= _TS("1996-01-01"))
           & (li["l_shipdate"] < _TS("1996-04-01"))].copy()
    l["rev"] = l["l_extendedprice"] * (1 - l["l_discount"])
    r0 = l.groupby("l_suppkey", as_index=False).agg(
        total_revenue=("rev", "sum"))
    mx = r0["total_revenue"].max()
    m = s.merge(r0[r0["total_revenue"] == mx], left_on="s_suppkey",
                right_on="l_suppkey")
    out = m[["s_suppkey", "s_name", "s_address", "s_phone", "total_revenue"]]
    return out.sort_values("s_suppkey", ignore_index=True)


def q16(d):
    ps, p, s = d["partsupp"], d["part"], d["supplier"]
    bad = s[s["s_comment"].str.contains("Customer.*Complaints", regex=True)]
    pf = p[(p["p_brand"] != "Brand#45")
           & ~p["p_type"].str.startswith("MEDIUM POLISHED")
           & p["p_size"].isin([49, 14, 23, 45, 19, 3, 36, 9])]
    m = ps.merge(pf, left_on="ps_partkey", right_on="p_partkey")
    m = m[~m["ps_suppkey"].isin(bad["s_suppkey"])]
    out = m.groupby(["p_brand", "p_type", "p_size"], as_index=False).agg(
        supplier_cnt=("ps_suppkey", "nunique"))
    return out.sort_values(["supplier_cnt", "p_brand", "p_type", "p_size"],
                           ascending=[False, True, True, True],
                           ignore_index=True)


def q17(d):
    li, p = d["lineitem"], d["part"]
    pf = p[(p["p_brand"] == "Brand#23") & (p["p_container"] == "MED BOX")]
    m = li.merge(pf[["p_partkey"]], left_on="l_partkey",
                 right_on="p_partkey")
    # correlated threshold uses ALL lineitems of the part, not the joined
    # subset (same table, so the merge result is exactly lineitem-of-part)
    thresh = 0.2 * m.groupby("l_partkey")["l_quantity"].transform("mean")
    x = m[m["l_quantity"] < thresh]
    return pd.DataFrame({"avg_yearly": [_sql_sum(x["l_extendedprice"]) / 7.0]})


def q18(d):
    cu, od, li = d["customer"], d["orders"], d["lineitem"]
    big = li.groupby("l_orderkey")["l_quantity"].sum()
    big = big[big > 300]
    o = od[od["o_orderkey"].isin(big.index)]
    m = (cu.merge(o, left_on="c_custkey", right_on="o_custkey")
           .merge(li, left_on="o_orderkey", right_on="l_orderkey"))
    g = m.groupby(["c_name", "c_custkey", "o_orderkey", "o_orderdate",
                   "o_totalprice"], as_index=False).agg(
        total_qty=("l_quantity", "sum"))
    return g.sort_values(["o_totalprice", "o_orderdate"],
                         ascending=[False, True],
                         ignore_index=True).head(100)


def q19(d):
    li, p = d["lineitem"], d["part"]
    l = li[li["l_shipmode"].isin(["AIR", "AIR REG"])
           & (li["l_shipinstruct"] == "DELIVER IN PERSON")]
    m = l.merge(p, left_on="l_partkey", right_on="p_partkey")
    c1 = ((m["p_brand"] == "Brand#12")
          & m["p_container"].isin(["SM CASE", "SM BOX", "SM PACK", "SM PKG"])
          & m["l_quantity"].between(1, 11) & m["p_size"].between(1, 5))
    c2 = ((m["p_brand"] == "Brand#23")
          & m["p_container"].isin(["MED BAG", "MED BOX", "MED PKG",
                                   "MED PACK"])
          & m["l_quantity"].between(10, 20) & m["p_size"].between(1, 10))
    c3 = ((m["p_brand"] == "Brand#34")
          & m["p_container"].isin(["LG CASE", "LG BOX", "LG PACK", "LG PKG"])
          & m["l_quantity"].between(20, 30) & m["p_size"].between(1, 15))
    x = m[c1 | c2 | c3]
    return pd.DataFrame(
        {"revenue": [_sql_sum(x["l_extendedprice"] * (1 - x["l_discount"]))]})


def q20(d):
    s, n, ps = d["supplier"], d["nation"], d["partsupp"]
    p, li = d["part"], d["lineitem"]
    ivory = p[p["p_name"].str.startswith("ivory")]
    l = li[(li["l_shipdate"] >= _TS("1994-01-01"))
           & (li["l_shipdate"] < _TS("1995-01-01"))]
    shipped = l.groupby(["l_partkey", "l_suppkey"], as_index=False).agg(
        qty=("l_quantity", "sum"))
    m = ps.merge(ivory[["p_partkey"]], left_on="ps_partkey",
                 right_on="p_partkey")
    m = m.merge(shipped, left_on=["ps_partkey", "ps_suppkey"],
                right_on=["l_partkey", "l_suppkey"], how="left")
    # no 1994 shipments => NULL comparison is false in SQL: keep inner rows
    m = m[m["ps_availqty"] > 0.5 * m["qty"]]
    ca = s.merge(n[n["n_name"] == "CANADA"], left_on="s_nationkey",
                 right_on="n_nationkey")
    out = ca[ca["s_suppkey"].isin(m["ps_suppkey"])][["s_name", "s_address"]]
    return out.sort_values("s_name", ignore_index=True)


def q21(d):
    s, li, od, n = d["supplier"], d["lineitem"], d["orders"], d["nation"]
    sa = s.merge(n[n["n_name"] == "SAUDI ARABIA"], left_on="s_nationkey",
                 right_on="n_nationkey")
    of = od[od["o_orderstatus"] == "F"]
    # per order: number of distinct suppliers overall and among late lines
    # (drop_duplicates+size ~3x faster than groupby.nunique at SF 1)
    nsupp = (li[["l_orderkey", "l_suppkey"]].drop_duplicates()
             .groupby("l_orderkey").size())
    late = li[li["l_receiptdate"] > li["l_commitdate"]]
    nsupp_late = (late[["l_orderkey", "l_suppkey"]].drop_duplicates()
                  .groupby("l_orderkey").size())
    l1 = late.merge(sa[["s_suppkey", "s_name"]], left_on="l_suppkey",
                    right_on="s_suppkey")
    l1 = l1.merge(of[["o_orderkey"]], left_on="l_orderkey",
                  right_on="o_orderkey")
    # EXISTS l2: another supplier in the order; NOT EXISTS l3: no OTHER
    # supplier was late in the order
    l1 = l1[(l1["l_orderkey"].map(nsupp).fillna(0) > 1)
            & (l1["l_orderkey"].map(nsupp_late).fillna(0) == 1)]
    out = l1.groupby("s_name", as_index=False).agg(
        numwait=("l_orderkey", "count"))
    return out.sort_values(["numwait", "s_name"], ascending=[False, True],
                           ignore_index=True).head(100)


def q22(d):
    cu, od = d["customer"], d["orders"]
    codes = ["13", "31", "23", "29", "30", "18", "17"]
    cc = cu["c_phone"].str[:2]
    pool = cu[cc.isin(codes)]
    avg_bal = pool[pool["c_acctbal"] > 0.0]["c_acctbal"].mean()
    x = pool[(pool["c_acctbal"] > avg_bal)
             & ~pool["c_custkey"].isin(od["o_custkey"])].copy()
    x["cntrycode"] = x["c_phone"].str[:2]
    out = x.groupby("cntrycode", as_index=False).agg(
        numcust=("c_custkey", "count"), totacctbal=("c_acctbal", "sum"))
    return out.sort_values("cntrycode", ignore_index=True)


PANDAS_QUERIES = {i: globals()[f"q{i}"] for i in range(1, 23)}
