"""TPC-H data generator (dbgen-shaped, numpy) and query texts.

Generates the 8 TPC-H tables with dbgen's schema, key relationships and
cardinalities (scale-factor relative), with value distributions shaped like
dbgen's — for throughput benchmarking of the engine, not for validating
official answer sets.  Correctness is covered by the sqlite differential
oracle in tests/ (the reference's strategy: semantics from oracles, SURVEY §6).
"""
from __future__ import annotations

import numpy as np
import pandas as pd

_REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
_NATIONS = [
    ("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1), ("CANADA", 1),
    ("EGYPT", 4), ("ETHIOPIA", 0), ("FRANCE", 3), ("GERMANY", 3),
    ("INDIA", 2), ("INDONESIA", 2), ("IRAN", 4), ("IRAQ", 4),
    ("JAPAN", 2), ("JORDAN", 4), ("KENYA", 0), ("MOROCCO", 0),
    ("MOZAMBIQUE", 0), ("PERU", 1), ("CHINA", 2), ("ROMANIA", 3),
    ("SAUDI ARABIA", 4), ("VIETNAM", 2), ("RUSSIA", 3),
    ("UNITED KINGDOM", 3), ("UNITED STATES", 1),
]
_SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "MACHINERY", "HOUSEHOLD"]
_PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]
_SHIPMODES = ["REG AIR", "AIR", "RAIL", "SHIP", "TRUCK", "MAIL", "FOB"]
_INSTRUCTS = ["DELIVER IN PERSON", "COLLECT COD", "NONE", "TAKE BACK RETURN"]
_TYPES = [f"{a} {b} {c}" for a in ("STANDARD", "SMALL", "MEDIUM", "LARGE",
                                   "ECONOMY", "PROMO")
          for b in ("ANODIZED", "BURNISHED", "PLATED", "POLISHED", "BRUSHED")
          for c in ("TIN", "NICKEL", "BRASS", "STEEL", "COPPER")]
_CONTAINERS = [f"{a} {b}" for a in ("SM", "LG", "MED", "JUMBO", "WRAP")
               for b in ("CASE", "BOX", "BAG", "JAR", "PKG", "PACK", "CAN", "DRUM")]

_D = lambda s: (pd.Timestamp(s) - pd.Timestamp("1970-01-01")).days  # noqa: E731


def _tag(prefix: str, nums: np.ndarray, width: int) -> np.ndarray:
    """Vectorized f"{prefix}{num:0{width}d}" (dbgen-style names); the
    per-element Python loop dominated generation time at SF>=1."""
    return (prefix + pd.Series(nums).astype(str).str.zfill(width)).to_numpy()


def _blank(n: int) -> np.ndarray:
    return np.full(n, "", dtype=object)


def generate_tpch(sf: float = 0.01, seed: int = 0,
                  small_only: bool = False) -> dict:
    """Returns {table_name: pandas.DataFrame} for the 8 TPC-H tables.

    ``small_only=True`` skips orders+lineitem (the ~95% of the bytes):
    piecewise large-scale generation (generate_orders_lineitem_piece)
    needs the dimension tables without paying a full-SF fact build.
    """
    rng = np.random.RandomState(seed)
    n_part = max(int(200_000 * sf), 50)
    n_supp = max(int(10_000 * sf), 10)
    n_cust = max(int(150_000 * sf), 30)
    n_ord = max(int(1_500_000 * sf), 150)
    n_nation = len(_NATIONS)

    region = pd.DataFrame({
        "r_regionkey": np.arange(5), "r_name": _REGIONS,
        "r_comment": ["" for _ in range(5)],
    })
    nation = pd.DataFrame({
        "n_nationkey": np.arange(n_nation),
        "n_name": [n for n, _ in _NATIONS],
        "n_regionkey": [r for _, r in _NATIONS],
        "n_comment": ["" for _ in range(n_nation)],
    })
    supplier = pd.DataFrame({
        "s_suppkey": np.arange(1, n_supp + 1),
        "s_name": _tag("Supplier#", np.arange(1, n_supp + 1), 9),
        "s_address": _tag("addr", np.arange(n_supp), 0),
        "s_nationkey": rng.randint(0, n_nation, n_supp),
        "s_phone": _tag("", np.arange(n_supp), 10),
        "s_acctbal": np.round(rng.uniform(-999.99, 9999.99, n_supp), 2),
        "s_comment": _blank(n_supp),
    })
    part = pd.DataFrame({
        "p_partkey": np.arange(1, n_part + 1),
        "p_name": rng.choice(["ivory blue", "green navy", "red linen",
                              "metallic olive", "antique puff"], n_part),
        "p_mfgr": _tag("Manufacturer#", np.arange(n_part) % 5 + 1, 0),
        # dbgen brands are "Brand#MN" with independent M,N in 1..5 — Q17/Q19
        # filter on Brand#23/12/34, which must actually exist in the data
        "p_brand": _tag("Brand#", (np.arange(n_part) % 5 + 1) * 10
                        + (np.arange(n_part) // 5) % 5 + 1, 0),
        "p_type": rng.choice(_TYPES, n_part),
        "p_size": rng.randint(1, 51, n_part),
        "p_container": rng.choice(_CONTAINERS, n_part),
        "p_retailprice": np.round(900 + (np.arange(1, n_part + 1) % 1000) / 10.0
                                  + 100 * (np.arange(1, n_part + 1) % 10), 2),
        "p_comment": _blank(n_part),
    })
    n_ps = n_part * 4
    # dbgen invariant: (ps_partkey, ps_suppkey) is a primary key — each part
    # gets 4 DISTINCT suppliers via a strided formula, and lineitem picks
    # its supplier from the part's four (so l_partkey/l_suppkey pairs exist
    # in partsupp; Q9's two-key join depends on both properties)
    _ps_step = max(n_supp // 4, 1)

    def _psupp(partkey, i):
        return (partkey - 1 + i * _ps_step) % n_supp + 1

    partsupp = pd.DataFrame({
        "ps_partkey": np.repeat(np.arange(1, n_part + 1), 4),
        "ps_suppkey": _psupp(np.repeat(np.arange(1, n_part + 1), 4),
                             np.tile(np.arange(4), n_part)),
        "ps_availqty": rng.randint(1, 10_000, n_ps),
        "ps_supplycost": np.round(rng.uniform(1.0, 1000.0, n_ps), 2),
        "ps_comment": _blank(n_ps),
    })
    c_nationkey = rng.randint(0, n_nation, n_cust)
    customer = pd.DataFrame({
        "c_custkey": np.arange(1, n_cust + 1),
        "c_name": _tag("Customer#", np.arange(1, n_cust + 1), 9),
        "c_address": _tag("addr", np.arange(n_cust), 0),
        "c_nationkey": c_nationkey,
        # dbgen phones start with the country code nationkey+10 (10..34):
        # Q22 filters SUBSTRING(c_phone,1,2) IN ('13','31',...) and must
        # actually select customers
        "c_phone": _tag(pd.Series(c_nationkey + 10).astype(str) + "-",
                        np.arange(n_cust), 8),
        "c_acctbal": np.round(rng.uniform(-999.99, 9999.99, n_cust), 2),
        "c_mktsegment": rng.choice(_SEGMENTS, n_cust),
        "c_comment": _blank(n_cust),
    })
    if small_only:
        return {
            "region": region, "nation": nation, "supplier": supplier,
            "part": part, "partsupp": partsupp, "customer": customer,
        }
    o_dates = rng.randint(_D("1992-01-01"), _D("1998-08-02"), n_ord)
    # dbgen: customers with custkey % 3 == 0 never place orders — Q22's
    # NOT EXISTS(orders) anti-join needs a real population to select
    o_custkey = rng.randint(1, n_cust + 1, n_ord)
    o_custkey = o_custkey + (o_custkey % 3 == 0)
    o_custkey = np.where(o_custkey > n_cust, 1, o_custkey)
    orders = pd.DataFrame({
        "o_orderkey": np.arange(1, n_ord + 1) * 4,  # dbgen sparse keys
        "o_custkey": o_custkey,
        "o_orderstatus": rng.choice(["F", "O", "P"], n_ord, p=[0.49, 0.49, 0.02]),
        "o_totalprice": np.round(rng.uniform(800.0, 600_000.0, n_ord), 2),
        "o_orderdate": pd.to_datetime(o_dates, unit="D"),
        "o_orderpriority": rng.choice(_PRIORITIES, n_ord),
        "o_clerk": _tag("Clerk#", np.arange(n_ord) % 1000, 9),
        "o_shippriority": np.zeros(n_ord, dtype=np.int64),
        "o_comment": _blank(n_ord),
    })
    lines_per_order = rng.randint(1, 8, n_ord)
    n_li = int(lines_per_order.sum())
    li_order = np.repeat(orders["o_orderkey"].to_numpy(), lines_per_order)
    li_odate = np.repeat(o_dates, lines_per_order)
    ship_delay = rng.randint(1, 122, n_li)
    ship = li_odate + ship_delay
    commit = li_odate + rng.randint(30, 91, n_li)
    receipt = ship + rng.randint(1, 31, n_li)
    returnflag = np.where(receipt <= _D("1995-06-17"),
                          rng.choice(["R", "A"], n_li), "N")
    lineitem = pd.DataFrame({
        "l_orderkey": li_order,
        "l_partkey": (li_partkey := rng.randint(1, n_part + 1, n_li)),
        "l_suppkey": _psupp(li_partkey, rng.randint(0, 4, n_li)),
        "l_linenumber": np.arange(n_li) - np.repeat(np.cumsum(lines_per_order) - lines_per_order, lines_per_order) + 1,
        "l_quantity": rng.randint(1, 51, n_li).astype(np.float64),
        "l_extendedprice": np.round(rng.uniform(900.0, 105_000.0, n_li), 2),
        "l_discount": np.round(rng.randint(0, 11, n_li) / 100.0, 2),
        "l_tax": np.round(rng.randint(0, 9, n_li) / 100.0, 2),
        "l_returnflag": returnflag,
        "l_linestatus": np.where(ship > _D("1995-06-17"), "O", "F"),
        "l_shipdate": pd.to_datetime(ship, unit="D"),
        "l_commitdate": pd.to_datetime(commit, unit="D"),
        "l_receiptdate": pd.to_datetime(receipt, unit="D"),
        "l_shipinstruct": rng.choice(_INSTRUCTS, n_li),
        "l_shipmode": rng.choice(_SHIPMODES, n_li),
        "l_comment": _blank(n_li),
    })
    return {
        "region": region, "nation": nation, "supplier": supplier,
        "part": part, "partsupp": partsupp, "customer": customer,
        "orders": orders, "lineitem": lineitem,
    }


QUERIES = {
    1: """
        SELECT l_returnflag, l_linestatus,
               SUM(l_quantity) AS sum_qty,
               SUM(l_extendedprice) AS sum_base_price,
               SUM(l_extendedprice * (1 - l_discount)) AS sum_disc_price,
               SUM(l_extendedprice * (1 - l_discount) * (1 + l_tax)) AS sum_charge,
               AVG(l_quantity) AS avg_qty,
               AVG(l_extendedprice) AS avg_price,
               AVG(l_discount) AS avg_disc,
               COUNT(*) AS count_order
        FROM lineitem
        WHERE l_shipdate <= DATE '1998-09-02'
        GROUP BY l_returnflag, l_linestatus
        ORDER BY l_returnflag, l_linestatus
    """,
    3: """
        SELECT l_orderkey,
               SUM(l_extendedprice * (1 - l_discount)) AS revenue,
               o_orderdate, o_shippriority
        FROM customer, orders, lineitem
        WHERE c_mktsegment = 'BUILDING'
          AND c_custkey = o_custkey
          AND l_orderkey = o_orderkey
          AND o_orderdate < DATE '1995-03-15'
          AND l_shipdate > DATE '1995-03-15'
        GROUP BY l_orderkey, o_orderdate, o_shippriority
        ORDER BY revenue DESC, o_orderdate
        LIMIT 10
    """,
    5: """
        SELECT n_name,
               SUM(l_extendedprice * (1 - l_discount)) AS revenue
        FROM customer, orders, lineitem, supplier, nation, region
        WHERE c_custkey = o_custkey
          AND l_orderkey = o_orderkey
          AND l_suppkey = s_suppkey
          AND c_nationkey = s_nationkey
          AND s_nationkey = n_nationkey
          AND n_regionkey = r_regionkey
          AND r_name = 'ASIA'
          AND o_orderdate >= DATE '1994-01-01'
          AND o_orderdate < DATE '1995-01-01'
        GROUP BY n_name
        ORDER BY revenue DESC
    """,
    6: """
        SELECT SUM(l_extendedprice * l_discount) AS revenue
        FROM lineitem
        WHERE l_shipdate >= DATE '1994-01-01'
          AND l_shipdate < DATE '1995-01-01'
          AND l_discount BETWEEN 0.05 AND 0.07
          AND l_quantity < 24
    """,
    9: """
        SELECT nation, o_year, SUM(amount) AS sum_profit
        FROM (
            SELECT n_name AS nation,
                   EXTRACT(YEAR FROM o_orderdate) AS o_year,
                   l_extendedprice * (1 - l_discount)
                     - ps_supplycost * l_quantity AS amount
            FROM part, supplier, lineitem, partsupp, orders, nation
            WHERE s_suppkey = l_suppkey
              AND ps_suppkey = l_suppkey
              AND ps_partkey = l_partkey
              AND p_partkey = l_partkey
              AND o_orderkey = l_orderkey
              AND s_nationkey = n_nationkey
              AND p_name LIKE '%green%'
        ) AS profit
        GROUP BY nation, o_year
        ORDER BY nation, o_year DESC
    """,
    10: """
        SELECT c_custkey, c_name,
               SUM(l_extendedprice * (1 - l_discount)) AS revenue,
               c_acctbal, n_name, c_address, c_phone, c_comment
        FROM customer, orders, lineitem, nation
        WHERE c_custkey = o_custkey
          AND l_orderkey = o_orderkey
          AND o_orderdate >= DATE '1993-10-01'
          AND o_orderdate < DATE '1994-01-01'
          AND l_returnflag = 'R'
          AND c_nationkey = n_nationkey
        GROUP BY c_custkey, c_name, c_acctbal, c_phone, n_name, c_address, c_comment
        ORDER BY revenue DESC
        LIMIT 20
    """,
    12: """
        SELECT l_shipmode,
               SUM(CASE WHEN o_orderpriority = '1-URGENT'
                         OR o_orderpriority = '2-HIGH' THEN 1 ELSE 0 END) AS high_line_count,
               SUM(CASE WHEN o_orderpriority <> '1-URGENT'
                        AND o_orderpriority <> '2-HIGH' THEN 1 ELSE 0 END) AS low_line_count
        FROM orders, lineitem
        WHERE o_orderkey = l_orderkey
          AND l_shipmode IN ('MAIL', 'SHIP')
          AND l_commitdate < l_receiptdate
          AND l_shipdate < l_commitdate
          AND l_receiptdate >= DATE '1994-01-01'
          AND l_receiptdate < DATE '1995-01-01'
        GROUP BY l_shipmode
        ORDER BY l_shipmode
    """,
    14: """
        SELECT 100.00 * SUM(CASE WHEN p_type LIKE 'PROMO%'
                                 THEN l_extendedprice * (1 - l_discount)
                                 ELSE 0 END) / SUM(l_extendedprice * (1 - l_discount)) AS promo_revenue
        FROM lineitem, part
        WHERE l_partkey = p_partkey
          AND l_shipdate >= DATE '1995-09-01'
          AND l_shipdate < DATE '1995-10-01'
    """,
    2: """
        SELECT s_acctbal, s_name, n_name, p_partkey, p_mfgr, s_address,
               s_phone, s_comment
        FROM part, supplier, partsupp, nation, region
        WHERE p_partkey = ps_partkey
          AND s_suppkey = ps_suppkey
          AND p_size = 15
          AND p_type LIKE '%BRASS'
          AND s_nationkey = n_nationkey
          AND n_regionkey = r_regionkey
          AND r_name = 'EUROPE'
          AND ps_supplycost = (
                SELECT MIN(ps_supplycost)
                FROM partsupp, supplier, nation, region
                WHERE p_partkey = ps_partkey
                  AND s_suppkey = ps_suppkey
                  AND s_nationkey = n_nationkey
                  AND n_regionkey = r_regionkey
                  AND r_name = 'EUROPE')
        ORDER BY s_acctbal DESC, n_name, s_name, p_partkey
        LIMIT 100
    """,
    4: """
        SELECT o_orderpriority, COUNT(*) AS order_count
        FROM orders
        WHERE o_orderdate >= DATE '1993-07-01'
          AND o_orderdate < DATE '1993-10-01'
          AND EXISTS (
                SELECT * FROM lineitem
                WHERE l_orderkey = o_orderkey
                  AND l_commitdate < l_receiptdate)
        GROUP BY o_orderpriority
        ORDER BY o_orderpriority
    """,
    7: """
        SELECT supp_nation, cust_nation, l_year, SUM(volume) AS revenue
        FROM (
            SELECT n1.n_name AS supp_nation, n2.n_name AS cust_nation,
                   EXTRACT(YEAR FROM l_shipdate) AS l_year,
                   l_extendedprice * (1 - l_discount) AS volume
            FROM supplier, lineitem, orders, customer, nation n1, nation n2
            WHERE s_suppkey = l_suppkey
              AND o_orderkey = l_orderkey
              AND c_custkey = o_custkey
              AND s_nationkey = n1.n_nationkey
              AND c_nationkey = n2.n_nationkey
              AND ((n1.n_name = 'FRANCE' AND n2.n_name = 'GERMANY')
                OR (n1.n_name = 'GERMANY' AND n2.n_name = 'FRANCE'))
              AND l_shipdate BETWEEN DATE '1995-01-01' AND DATE '1996-12-31'
        ) AS shipping
        GROUP BY supp_nation, cust_nation, l_year
        ORDER BY supp_nation, cust_nation, l_year
    """,
    8: """
        SELECT o_year,
               SUM(CASE WHEN nation = 'BRAZIL' THEN volume ELSE 0 END)
                 / SUM(volume) AS mkt_share
        FROM (
            SELECT EXTRACT(YEAR FROM o_orderdate) AS o_year,
                   l_extendedprice * (1 - l_discount) AS volume,
                   n2.n_name AS nation
            FROM part, supplier, lineitem, orders, customer,
                 nation n1, nation n2, region
            WHERE p_partkey = l_partkey
              AND s_suppkey = l_suppkey
              AND l_orderkey = o_orderkey
              AND o_custkey = c_custkey
              AND c_nationkey = n1.n_nationkey
              AND n1.n_regionkey = r_regionkey
              AND r_name = 'AMERICA'
              AND s_nationkey = n2.n_nationkey
              AND o_orderdate BETWEEN DATE '1995-01-01' AND DATE '1996-12-31'
              AND p_type = 'ECONOMY ANODIZED STEEL'
        ) AS all_nations
        GROUP BY o_year
        ORDER BY o_year
    """,
    11: """
        SELECT ps_partkey, SUM(ps_supplycost * ps_availqty) AS value
        FROM partsupp, supplier, nation
        WHERE ps_suppkey = s_suppkey
          AND s_nationkey = n_nationkey
          AND n_name = 'GERMANY'
        GROUP BY ps_partkey
        HAVING SUM(ps_supplycost * ps_availqty) > (
                SELECT SUM(ps_supplycost * ps_availqty) * 0.0001
                FROM partsupp, supplier, nation
                WHERE ps_suppkey = s_suppkey
                  AND s_nationkey = n_nationkey
                  AND n_name = 'GERMANY')
        ORDER BY value DESC
    """,
    13: """
        SELECT c_count, COUNT(*) AS custdist
        FROM (
            SELECT c_custkey, COUNT(o_orderkey) AS c_count
            FROM customer LEFT OUTER JOIN orders
              ON c_custkey = o_custkey AND o_comment NOT LIKE '%special%requests%'
            GROUP BY c_custkey
        ) AS c_orders
        GROUP BY c_count
        ORDER BY custdist DESC, c_count DESC
    """,
    15: """
        WITH revenue0 AS (
            SELECT l_suppkey AS supplier_no,
                   SUM(l_extendedprice * (1 - l_discount)) AS total_revenue
            FROM lineitem
            WHERE l_shipdate >= DATE '1996-01-01'
              AND l_shipdate < DATE '1996-04-01'
            GROUP BY l_suppkey
        )
        SELECT s_suppkey, s_name, s_address, s_phone, total_revenue
        FROM supplier, revenue0
        WHERE s_suppkey = supplier_no
          AND total_revenue = (SELECT MAX(total_revenue) FROM revenue0)
        ORDER BY s_suppkey
    """,
    16: """
        SELECT p_brand, p_type, p_size, COUNT(DISTINCT ps_suppkey) AS supplier_cnt
        FROM partsupp, part
        WHERE p_partkey = ps_partkey
          AND p_brand <> 'Brand#45'
          AND p_type NOT LIKE 'MEDIUM POLISHED%'
          AND p_size IN (49, 14, 23, 45, 19, 3, 36, 9)
          AND ps_suppkey NOT IN (
                SELECT s_suppkey FROM supplier
                WHERE s_comment LIKE '%Customer%Complaints%')
        GROUP BY p_brand, p_type, p_size
        ORDER BY supplier_cnt DESC, p_brand, p_type, p_size
    """,
    17: """
        SELECT SUM(l_extendedprice) / 7.0 AS avg_yearly
        FROM lineitem, part
        WHERE p_partkey = l_partkey
          AND p_brand = 'Brand#23'
          AND p_container = 'MED BOX'
          AND l_quantity < (
                SELECT 0.2 * AVG(l_quantity)
                FROM lineitem
                WHERE l_partkey = p_partkey)
    """,
    19: """
        SELECT SUM(l_extendedprice * (1 - l_discount)) AS revenue
        FROM lineitem, part
        WHERE (p_partkey = l_partkey AND p_brand = 'Brand#12'
               AND p_container IN ('SM CASE', 'SM BOX', 'SM PACK', 'SM PKG')
               AND l_quantity >= 1 AND l_quantity <= 11
               AND p_size BETWEEN 1 AND 5
               AND l_shipmode IN ('AIR', 'AIR REG')
               AND l_shipinstruct = 'DELIVER IN PERSON')
           OR (p_partkey = l_partkey AND p_brand = 'Brand#23'
               AND p_container IN ('MED BAG', 'MED BOX', 'MED PKG', 'MED PACK')
               AND l_quantity >= 10 AND l_quantity <= 20
               AND p_size BETWEEN 1 AND 10
               AND l_shipmode IN ('AIR', 'AIR REG')
               AND l_shipinstruct = 'DELIVER IN PERSON')
           OR (p_partkey = l_partkey AND p_brand = 'Brand#34'
               AND p_container IN ('LG CASE', 'LG BOX', 'LG PACK', 'LG PKG')
               AND l_quantity >= 20 AND l_quantity <= 30
               AND p_size BETWEEN 1 AND 15
               AND l_shipmode IN ('AIR', 'AIR REG')
               AND l_shipinstruct = 'DELIVER IN PERSON')
    """,
    20: """
        SELECT s_name, s_address
        FROM supplier, nation
        WHERE s_suppkey IN (
                SELECT ps_suppkey FROM partsupp
                WHERE ps_partkey IN (
                        SELECT p_partkey FROM part WHERE p_name LIKE 'ivory%')
                  AND ps_availqty > (
                        SELECT 0.5 * SUM(l_quantity)
                        FROM lineitem
                        WHERE l_partkey = ps_partkey
                          AND l_suppkey = ps_suppkey
                          AND l_shipdate >= DATE '1994-01-01'
                          AND l_shipdate < DATE '1995-01-01'))
          AND s_nationkey = n_nationkey
          AND n_name = 'CANADA'
        ORDER BY s_name
    """,
    21: """
        SELECT s_name, COUNT(*) AS numwait
        FROM supplier, lineitem l1, orders, nation
        WHERE s_suppkey = l1.l_suppkey
          AND o_orderkey = l1.l_orderkey
          AND o_orderstatus = 'F'
          AND l1.l_receiptdate > l1.l_commitdate
          AND EXISTS (
                SELECT * FROM lineitem l2
                WHERE l2.l_orderkey = l1.l_orderkey
                  AND l2.l_suppkey <> l1.l_suppkey)
          AND NOT EXISTS (
                SELECT * FROM lineitem l3
                WHERE l3.l_orderkey = l1.l_orderkey
                  AND l3.l_suppkey <> l1.l_suppkey
                  AND l3.l_receiptdate > l3.l_commitdate)
          AND s_nationkey = n_nationkey
          AND n_name = 'SAUDI ARABIA'
        GROUP BY s_name
        ORDER BY numwait DESC, s_name
        LIMIT 100
    """,
    22: """
        SELECT cntrycode, COUNT(*) AS numcust, SUM(c_acctbal) AS totacctbal
        FROM (
            SELECT SUBSTRING(c_phone FROM 1 FOR 2) AS cntrycode, c_acctbal
            FROM customer
            WHERE SUBSTRING(c_phone FROM 1 FOR 2) IN
                    ('13', '31', '23', '29', '30', '18', '17')
              AND c_acctbal > (
                    SELECT AVG(c_acctbal) FROM customer
                    WHERE c_acctbal > 0.00
                      AND SUBSTRING(c_phone FROM 1 FOR 2) IN
                            ('13', '31', '23', '29', '30', '18', '17'))
              AND NOT EXISTS (
                    SELECT * FROM orders WHERE o_custkey = c_custkey)
        ) AS custsale
        GROUP BY cntrycode
        ORDER BY cntrycode
    """,
    18: """
        SELECT c_name, c_custkey, o_orderkey, o_orderdate, o_totalprice,
               SUM(l_quantity) AS total_qty
        FROM customer, orders, lineitem
        WHERE o_orderkey IN (
                SELECT l_orderkey FROM lineitem
                GROUP BY l_orderkey HAVING SUM(l_quantity) > 300)
          AND c_custkey = o_custkey
          AND o_orderkey = l_orderkey
        GROUP BY c_name, c_custkey, o_orderkey, o_orderdate, o_totalprice
        ORDER BY o_totalprice DESC, o_orderdate
        LIMIT 100
    """,
}


def generate_orders_lineitem_piece(sf: float, piece: int, n_pieces: int,
                                   seed: int = 0):
    """One horizontal slice of the orders+lineitem pair at scale ``sf``.

    Generating SF>=10 in one shot holds a ~10 GB lineitem frame (plus the
    encoder's copies) in RAM — the r3 SF-10 certification peaked at 27 GB
    because of exactly that.  Slices keep the dbgen invariants that matter:
    sparse orderkeys (k*4) partitioned across pieces, o_custkey %3 hole
    (Q22), the partsupp supplier formula (Q9), and per-order 1-7 lineitems.
    Each piece uses its own seeded stream, so pieces are independent of
    n_pieces only in SHAPE, not values — a piecewise dataset is its own
    dataset (consistent across queries, not equal to generate_tpch(sf))."""
    n_part = max(int(200_000 * sf), 50)
    n_supp = max(int(10_000 * sf), 10)
    n_cust = max(int(150_000 * sf), 30)
    n_ord = max(int(1_500_000 * sf), 150)
    lo = (n_ord * piece) // n_pieces
    hi = (n_ord * (piece + 1)) // n_pieces
    n_o = hi - lo
    rng = np.random.RandomState((seed * 7919 + piece * 104729 + 13) % (1 << 31))
    _ps_step = max(n_supp // 4, 1)

    def _psupp(partkey, i):
        return (partkey - 1 + i * _ps_step) % n_supp + 1

    o_dates = rng.randint(_D("1992-01-01"), _D("1998-08-02"), n_o)
    o_custkey = rng.randint(1, n_cust + 1, n_o)
    o_custkey = o_custkey + (o_custkey % 3 == 0)
    o_custkey = np.where(o_custkey > n_cust, 1, o_custkey)
    okeys = (np.arange(lo, hi) + 1) * 4
    orders = pd.DataFrame({
        "o_orderkey": okeys,
        "o_custkey": o_custkey,
        "o_orderstatus": rng.choice(["F", "O", "P"], n_o,
                                    p=[0.49, 0.49, 0.02]),
        "o_totalprice": np.round(rng.uniform(800.0, 600_000.0, n_o), 2),
        "o_orderdate": pd.to_datetime(o_dates, unit="D"),
        "o_orderpriority": rng.choice(_PRIORITIES, n_o),
        "o_clerk": _tag("Clerk#", np.arange(lo, hi) % 1000, 9),
        "o_shippriority": np.zeros(n_o, dtype=np.int64),
        "o_comment": _blank(n_o),
    })
    lines_per_order = rng.randint(1, 8, n_o)
    n_li = int(lines_per_order.sum())
    li_order = np.repeat(okeys, lines_per_order)
    li_odate = np.repeat(o_dates, lines_per_order)
    ship = li_odate + rng.randint(1, 122, n_li)
    commit = li_odate + rng.randint(30, 91, n_li)
    receipt = ship + rng.randint(1, 31, n_li)
    returnflag = np.where(receipt <= _D("1995-06-17"),
                          rng.choice(["R", "A"], n_li), "N")
    li_partkey = rng.randint(1, n_part + 1, n_li)
    lineitem = pd.DataFrame({
        "l_orderkey": li_order,
        "l_partkey": li_partkey,
        "l_suppkey": _psupp(li_partkey, rng.randint(0, 4, n_li)),
        "l_linenumber": np.arange(n_li) - np.repeat(
            np.cumsum(lines_per_order) - lines_per_order,
            lines_per_order) + 1,
        "l_quantity": rng.randint(1, 51, n_li).astype(np.float64),
        "l_extendedprice": np.round(rng.uniform(900.0, 105_000.0, n_li), 2),
        "l_discount": np.round(rng.randint(0, 11, n_li) / 100.0, 2),
        "l_tax": np.round(rng.randint(0, 9, n_li) / 100.0, 2),
        "l_returnflag": returnflag,
        "l_linestatus": np.where(ship > _D("1995-06-17"), "O", "F"),
        "l_shipdate": pd.to_datetime(ship, unit="D"),
        "l_commitdate": pd.to_datetime(commit, unit="D"),
        "l_receiptdate": pd.to_datetime(receipt, unit="D"),
        "l_shipinstruct": rng.choice(_INSTRUCTS, n_li),
        "l_shipmode": rng.choice(_SHIPMODES, n_li),
        "l_comment": _blank(n_li),
    })
    return orders, lineitem
