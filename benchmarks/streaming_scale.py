"""Streaming x mesh at scale: the SF-10 out-of-core + distributed proof.

The reference's execution model is out-of-core AND distributed by
construction (partitioned dask dataframes over a cluster,
/root/reference/dask_sql/input_utils/convert.py:38-62).  Our equivalent is
``create_table(chunked=True)`` composed with ``Context(mesh=...)``: each host
batch is row-sharded over the mesh, the per-batch compiled program runs as a
GSPMD program, and partials merge by aggregate algebra
(physical/streaming.py).  This script certifies that composition at a scale
factor far above anything resident-in-HBM testing covers:

    python benchmarks/streaming_scale.py          # SF 10, Q1/Q3/Q5/Q6/Q9
    STREAM_SCALE_SF=3 python benchmarks/streaming_scale.py

Round-4 redesign — the certifier itself is now out-of-core (the r3 run
peaked at 27 GB RSS and died incomplete because generator + oracle both
held the whole SF-10 dataset):

- data is generated in PIECES (benchmarks/tpch.py
  generate_orders_lineitem_piece) and appended to parquet on disk; no full
  lineitem frame ever exists in this process;
- the engine ingests lineitem with ``ChunkedSource.from_parquet`` (two-pass
  row-group streaming; holds encoded columnar batches, not pandas objects);
- the pandas oracle runs per query in a SUBPROCESS that loads only the
  lineitem columns that query touches, writes its expected frame to disk,
  and exits — oracle memory is returned to the OS before the engine runs.

Equality oracle: the hand-written pandas implementations
(benchmarks/pandas_tpch.py) — an independent host implementation, itself
oracle-tested against the engine (tests/integration/test_pandas_oracle.py).
The engine's own resident path is NOT the oracle here: an 8-thread GSPMD
program on this 1-core host spends minutes per collective rendezvous.

At SF >= 3 the run writes the certification artifact STREAMING_r04.json at
the repo root (per-query wall seconds, batch count/bytes, equality
verdicts, peak RSS); smaller SFs are smoke runs and write
/tmp/streaming_smoke.json so they can never clobber a certification.  The
streaming memory claim is the DEVICE working set: at most one
~BATCH_ROWS-row batch resident at a time versus the full table a resident
run uploads; ``process_peak_rss_gb`` additionally bounds the HOST side now
that generation and oracle are piecewise/subprocessed.
"""
import json
import os
import resource
import subprocess
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

SF = float(os.environ.get("STREAM_SCALE_SF", "10"))
QIDS = [int(q) for q in os.environ.get("STREAM_SCALE_QUERIES",
                                       "1,3,5,6,9").split(",")]
BATCH_ROWS = int(os.environ.get("STREAM_SCALE_BATCH_ROWS", str(4 << 20)))
N_PIECES = int(os.environ.get("STREAM_SCALE_PIECES",
                              str(max(1, int(2 * SF)))))
DATA_DIR = os.environ.get("STREAM_SCALE_DATA",
                          os.path.join(tempfile.gettempdir(),
                                       f"stream_scale_sf{SF:g}"))
OUT = (os.path.join(_REPO, "STREAMING_r05.json")
       if SF >= 3 else "/tmp/streaming_smoke.json")
# prior rounds' artifacts: resumable accumulation reads these too (same
# SF/rows/batch check as any resume source), so a new round re-certifies
# only what it must
_PRIOR = [os.path.join(_REPO, "STREAMING_r04.json"),
          os.path.join(_REPO, "STREAMING_r04.json.partial")]

# lineitem columns each oracle query touches (loading all 16 at SF 10 is
# the difference between a 4 GB and a 10 GB oracle subprocess)
_LI_COLS = {
    1: ["l_returnflag", "l_linestatus", "l_quantity", "l_extendedprice",
        "l_discount", "l_tax", "l_shipdate"],
    3: ["l_orderkey", "l_extendedprice", "l_discount", "l_shipdate"],
    5: ["l_orderkey", "l_suppkey", "l_extendedprice", "l_discount"],
    6: ["l_shipdate", "l_discount", "l_quantity", "l_extendedprice"],
    9: ["l_orderkey", "l_partkey", "l_suppkey", "l_extendedprice",
        "l_discount", "l_quantity"],
}


def _rss_gb() -> float:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6


def _gen_to_parquet():
    """Piecewise generation straight to parquet; peak RSS = one piece."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    from benchmarks.tpch import generate_orders_lineitem_piece, generate_tpch

    os.makedirs(DATA_DIR, exist_ok=True)
    # marker carries the generation parameters: a rerun with a different
    # piece count (or SF) must regenerate, not silently certify old data
    marker = os.path.join(DATA_DIR, "COMPLETE")
    stamp = f"sf={SF:g} pieces={N_PIECES}"
    if os.path.exists(marker) and open(marker).read() == stamp:
        return
    for fn in os.listdir(DATA_DIR):
        if fn.endswith(".parquet") or fn == "COMPLETE":
            os.remove(os.path.join(DATA_DIR, fn))
    # dimension tables at full SF (customer 1.5M, part 2M, supplier 100k at
    # SF 10 — a few hundred MB); small_only skips the 10 GB fact build that
    # blew the r3 certification's RSS
    small = generate_tpch(SF, small_only=True)
    for name in ("region", "nation", "supplier", "part", "partsupp",
                 "customer"):
        small[name].to_parquet(os.path.join(DATA_DIR, f"{name}.parquet"))
    small.clear()
    writers = {}
    for piece in range(N_PIECES):
        orders, lineitem = generate_orders_lineitem_piece(SF, piece,
                                                          N_PIECES)
        for name, frame in (("orders", orders), ("lineitem", lineitem)):
            tbl = pa.Table.from_pandas(frame, preserve_index=False)
            if name not in writers:
                writers[name] = pq.ParquetWriter(
                    os.path.join(DATA_DIR, f"{name}.parquet"), tbl.schema)
            writers[name].write_table(tbl)
        del orders, lineitem
        print(f"gen piece {piece + 1}/{N_PIECES} rss={_rss_gb():.1f}GB",
              flush=True)
    for w in writers.values():
        w.close()
    with open(marker, "w") as f:
        f.write(stamp)


def _oracle_main(qid: int, out_path: str):
    """Subprocess: pandas oracle for one query over the parquet data,
    loading only the lineitem columns that query touches."""
    import pandas as pd

    from benchmarks.pandas_tpch import PANDAS_QUERIES

    data = {}
    for name in ("region", "nation", "supplier", "part", "partsupp",
                 "customer", "orders"):
        p = os.path.join(DATA_DIR, f"{name}.parquet")
        if os.path.exists(p):
            data[name] = pd.read_parquet(p)
    cols = _LI_COLS.get(qid)
    data["lineitem"] = pd.read_parquet(
        os.path.join(DATA_DIR, "lineitem.parquet"), columns=cols)
    t0 = time.perf_counter()
    want = PANDAS_QUERIES[qid](data)
    sec = time.perf_counter() - t0
    want.reset_index(drop=True).to_feather(out_path)
    print(json.dumps({"pandas_sec": round(sec, 2),
                      "oracle_rss_gb": round(_rss_gb(), 2)}), flush=True)


def _frames_equal(a, b) -> bool:
    import numpy as np
    import pandas as pd

    if len(a) != len(b) or list(a.columns) != list(b.columns):
        return False
    a = a.reset_index(drop=True)
    b = b.reset_index(drop=True)
    for col in a.columns:
        av, bv = a[col], b[col]
        if pd.api.types.is_float_dtype(av) or pd.api.types.is_float_dtype(bv):
            if not np.allclose(av.astype(float), bv.astype(float),
                               rtol=1e-6, atol=1e-9, equal_nan=True):
                return False
        elif not (av.astype(str).to_numpy() == bv.astype(str).to_numpy()).all():
            return False
    return True


def main():
    os.environ.setdefault("XLA_FLAGS",
                          "--xla_force_host_platform_device_count=8")
    # persistent XLA cache: the 8-device GSPMD programs cost minutes each
    # to compile on this host — a rerun (or a crash-restart) must not
    # re-pay them.  The dir name carries a CPU-feature fingerprint (same
    # scheme as tests/conftest.py): XLA:CPU AOT executables are micro-arch
    # specific, and /tmp can survive into a round that runs on a DIFFERENT
    # machine — loading a foreign executable warns "could lead to
    # execution errors such as SIGILL" and sometimes does exactly that.
    import hashlib as _hashlib
    try:
        with open("/proc/cpuinfo") as _f:
            _flags = "".join(sorted(l for l in _f if l.startswith("flags")))
        _cpu_fp = _hashlib.blake2b(_flags.encode(),
                                   digest_size=4).hexdigest()
    except OSError:
        _cpu_fp = "nocpuinfo"
    os.environ.setdefault(
        "DSQL_XLA_CACHE",
        os.path.join(tempfile.gettempdir(),
                     f"dsql_stream_scale_xla_{_cpu_fp}"))
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", 8)
    except AttributeError:
        pass  # jax < 0.5: the XLA_FLAGS fallback above covers it

    import pandas as pd

    from benchmarks.tpch import QUERIES
    from dask_sql_tpu import Context
    from dask_sql_tpu.io.chunked import ChunkedSource
    from dask_sql_tpu.parallel.mesh import default_mesh

    t0 = time.perf_counter()
    _gen_to_parquet()
    gen_sec = time.perf_counter() - t0

    mesh = default_mesh()
    chunked = Context(mesh=mesh)
    t0 = time.perf_counter()
    source = ChunkedSource.from_parquet(
        os.path.join(DATA_DIR, "lineitem.parquet"), batch_rows=BATCH_ROWS)
    chunked.create_table("lineitem", source, chunked=True,
                         batch_rows=BATCH_ROWS)
    for name in ("region", "nation", "supplier", "part", "partsupp",
                 "customer", "orders"):
        chunked.create_table(
            name, pd.read_parquet(os.path.join(DATA_DIR,
                                               f"{name}.parquet")))
    load_sec = time.perf_counter() - t0
    li_rows = source.n_rows
    n_batches = source.n_batches
    li_bytes = sum(
        d.nbytes + (m.nbytes if m is not None else 0)
        for b in source.batches for d, m in b)

    # RESUME: fold in queries certified by a previous (complete or partial)
    # run over the SAME data — the virtual-mesh GSPMD execution runs at
    # simulator speed on this 1-core host, so one process may not fit every
    # query inside a caller's timeout; accumulation is what makes the
    # artifact completable at all
    results = {}
    for prev in [OUT, OUT + ".partial"] + _PRIOR:
        try:
            with open(prev) as f:
                d = json.load(f)
            if (d.get("sf") == SF and d.get("lineitem_rows") == li_rows
                    and d.get("batch_rows") == BATCH_ROWS):
                for k, v in d.get("queries", {}).items():
                    if "error" not in v:
                        results.setdefault(int(k), v)
        except (OSError, ValueError):
            pass
    # STREAM_SCALE_FORCE=6,... : drop these from the resume set so a query
    # whose prior number should improve (engine change) re-certifies fresh
    for q in os.environ.get("STREAM_SCALE_FORCE", "").split(","):
        if q.strip():
            results.pop(int(q), None)
    if results:
        print(f"resuming with prior results for {sorted(results)}",
              flush=True)

    def _write(done=False):
        artifact = {
            "metric": "streaming_mesh_scale",
            "sf": SF,
            "mesh_devices": int(mesh.devices.size),
            "lineitem_rows": li_rows,
            "lineitem_host_bytes": li_bytes,
            "batch_rows": BATCH_ROWS,
            "n_batches": n_batches,
            "n_gen_pieces": N_PIECES,
            "batch_device_bytes_approx": int(li_bytes / max(n_batches, 1)),
            "gen_sec": round(gen_sec, 1),
            "load_sec": round(load_sec, 1),
            "oracle": "benchmarks/pandas_tpch.py per-query subprocess over "
                      "parquet (column-pruned); itself oracle-tested in "
                      "tests/integration/test_pandas_oracle.py",
            "queries": {str(k): v for k, v in results.items()},
            "complete": done,
            "all_equal": bool(results) and all(r.get("equal")
                                               for r in results.values()),
            "process_peak_rss_gb": round(_rss_gb(), 2),
        }
        # in-flight progress goes to a sidecar; OUT itself is only ever
        # replaced by a complete run, so an interrupted rerun can't
        # overwrite a previous certification with a partial result
        path = OUT if done else OUT + ".partial"
        with open(path, "w") as f:
            json.dump(artifact, f, indent=1)
        if done:
            try:
                os.remove(OUT + ".partial")
            except OSError:
                pass
        return artifact

    for qid in QIDS:
        if qid in results:
            continue
        rec = {}
        try:
            want_path = os.path.join(DATA_DIR, f"oracle_q{qid}.feather")
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--oracle",
                 str(qid), want_path],
                capture_output=True, text=True, timeout=3600,
                env=dict(os.environ, JAX_PLATFORMS="cpu"))
            if proc.returncode != 0:
                raise RuntimeError(f"oracle rc={proc.returncode}: "
                                   f"{proc.stderr[-400:]}")
            rec.update(json.loads(proc.stdout.strip().splitlines()[-1]))
            want = pd.read_feather(want_path)

            t0 = time.perf_counter()
            got = chunked.sql(QUERIES[qid], return_futures=False)
            rec["chunked_sec"] = round(time.perf_counter() - t0, 2)
            got.columns = [c.lower() for c in got.columns]
            want.columns = [c.lower() for c in want.columns]
            for col in got.columns:
                if got[col].dtype.kind == "M":
                    got[col] = got[col].dt.strftime("%Y-%m-%d")
                if col in want.columns and want[col].dtype.kind == "M":
                    want[col] = want[col].dt.strftime("%Y-%m-%d")
            srt = list(want.columns)
            rec["equal"] = _frames_equal(
                want.sort_values(srt, ignore_index=True),
                got[srt].sort_values(srt, ignore_index=True))
            rec["rows_out"] = len(got)
        except Exception as e:  # record, keep going
            rec["error"] = f"{type(e).__name__}: {e}"[:300]
        rec["process_rss_gb"] = round(_rss_gb(), 2)
        results[qid] = rec
        _write()
        print(f"Q{qid}: {rec}", flush=True)

    artifact = _write(done=True)
    print(json.dumps({"metric": "streaming_mesh_scale",
                      "value": artifact["all_equal"],
                      "detail": OUT}))


if __name__ == "__main__":
    if len(sys.argv) > 2 and sys.argv[1] == "--oracle":
        _oracle_main(int(sys.argv[2]), sys.argv[3])
    else:
        main()
