"""Streaming x mesh at scale: the SF-10 out-of-core + distributed proof.

The reference's execution model is out-of-core AND distributed by
construction (partitioned dask dataframes over a cluster,
/root/reference/dask_sql/input_utils/convert.py:38-62).  Our equivalent is
``create_table(chunked=True)`` composed with ``Context(mesh=...)``: each host
batch is row-sharded over the mesh, the per-batch compiled program runs as a
GSPMD program, and partials merge by aggregate algebra
(physical/streaming.py).  This script certifies that composition at a scale
factor far above anything resident-in-HBM testing covers:

    python benchmarks/streaming_scale.py          # SF 10, Q1/Q3/Q5/Q6/Q9
    STREAM_SCALE_SF=3 python benchmarks/streaming_scale.py

Equality oracle: the hand-written pandas implementations
(benchmarks/pandas_tpch.py) — an independent host implementation, itself
oracle-tested against the engine (tests/integration/test_pandas_oracle.py).
The engine's own resident path is NOT the oracle here: an 8-thread GSPMD
program on this 1-core host spends minutes per collective rendezvous.

At SF >= 3 the run writes the certification artifact STREAMING_r03.json at
the repo root (per-query wall seconds, batch count/bytes, equality
verdicts); smaller SFs are smoke runs and write /tmp/streaming_smoke.json
so they can never clobber a certification.  The streaming memory claim is
the DEVICE working set: at most one ~BATCH_ROWS-row batch resident at a
time (``batch_device_bytes_approx``) versus the full table a resident run
uploads (``lineitem_host_bytes``); ``process_peak_rss_gb`` is the whole
host process — generator and pandas oracle included — recorded only for
ops visibility, not as an out-of-core proof.
"""
import json
import os
import resource
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)

import numpy as np
import pandas as pd

from benchmarks.tpch import QUERIES, generate_tpch
from dask_sql_tpu import Context

SF = float(os.environ.get("STREAM_SCALE_SF", "10"))
QIDS = [int(q) for q in os.environ.get("STREAM_SCALE_QUERIES",
                                       "1,3,5,6,9").split(",")]
BATCH_ROWS = int(os.environ.get("STREAM_SCALE_BATCH_ROWS", str(4 << 20)))
OUT = (os.path.join(os.path.dirname(os.path.dirname(
           os.path.abspath(__file__))), "STREAMING_r03.json")
       if SF >= 3 else "/tmp/streaming_smoke.json")


def _rss_gb() -> float:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6


def _frames_equal(a: pd.DataFrame, b: pd.DataFrame) -> bool:
    if len(a) != len(b) or list(a.columns) != list(b.columns):
        return False
    a = a.reset_index(drop=True)
    b = b.reset_index(drop=True)
    for col in a.columns:
        av, bv = a[col], b[col]
        if pd.api.types.is_float_dtype(av) or pd.api.types.is_float_dtype(bv):
            if not np.allclose(av.astype(float), bv.astype(float),
                               rtol=1e-6, atol=1e-9, equal_nan=True):
                return False
        elif not (av.astype(str).to_numpy() == bv.astype(str).to_numpy()).all():
            return False
    return True


def main():
    t0 = time.perf_counter()
    data = generate_tpch(SF)
    gen_sec = time.perf_counter() - t0
    li_rows = len(data["lineitem"])
    li_bytes = int(data["lineitem"].memory_usage(deep=False).sum())

    from benchmarks.pandas_tpch import PANDAS_QUERIES
    from dask_sql_tpu.parallel.mesh import default_mesh

    mesh = default_mesh()
    mesh_devices = int(mesh.devices.size)
    chunked = Context(mesh=mesh)
    t0 = time.perf_counter()
    for name, frame in data.items():
        if name == "lineitem":
            chunked.create_table(name, frame, chunked=True,
                                 batch_rows=BATCH_ROWS)
        else:
            chunked.create_table(name, frame)
    load_sec = time.perf_counter() - t0
    n_batches = -(-li_rows // BATCH_ROWS)

    results = {}

    def _write(done=False):
        artifact = {
            "metric": "streaming_mesh_scale",
            "sf": SF,
            "mesh_devices": mesh_devices,
            "lineitem_rows": li_rows,
            "lineitem_host_bytes": li_bytes,
            "batch_rows": BATCH_ROWS,
            "n_batches": n_batches,
            "batch_device_bytes_approx": int(li_bytes / max(n_batches, 1)),
            "gen_sec": round(gen_sec, 1),
            "load_sec": round(load_sec, 1),
            "oracle": "benchmarks/pandas_tpch.py (independent host impl; "
                      "itself oracle-tested against the engine in "
                      "tests/integration/test_pandas_oracle.py)",
            "queries": {str(k): v for k, v in results.items()},
            "complete": done,
            "all_equal": bool(results) and all(r.get("equal")
                                               for r in results.values()),
            # whole-process RSS (generator + pandas oracle included): ops
            # visibility only — the out-of-core claim is the device working
            # set, batch_device_bytes_approx vs lineitem_host_bytes
            "process_peak_rss_gb": round(_rss_gb(), 2),
        }
        # in-flight progress goes to a sidecar; OUT itself is only ever
        # replaced by a complete run, so an interrupted rerun can't
        # overwrite a previous certification with a partial result
        path = OUT if done else OUT + ".partial"
        with open(path, "w") as f:
            json.dump(artifact, f, indent=1)
        if done:
            try:
                os.remove(OUT + ".partial")
            except OSError:
                pass
        return artifact

    for qid in QIDS:
        rec = {}
        try:
            # pandas is the equality oracle: an 8-thread GSPMD program on a
            # 1-core host spends minutes in collective rendezvous, so the
            # resident engine as oracle would measure the simulator, not us
            t0 = time.perf_counter()
            want = PANDAS_QUERIES[qid](data)
            rec["pandas_sec"] = round(time.perf_counter() - t0, 2)
            t0 = time.perf_counter()
            got = chunked.sql(QUERIES[qid], return_futures=False)
            rec["chunked_sec"] = round(time.perf_counter() - t0, 2)
            got.columns = [c.lower() for c in got.columns]
            want.columns = [c.lower() for c in want.columns]
            for col in got.columns:
                if got[col].dtype.kind == "M":
                    got[col] = got[col].dt.strftime("%Y-%m-%d")
                if col in want.columns and want[col].dtype.kind == "M":
                    want[col] = want[col].dt.strftime("%Y-%m-%d")
            srt = list(want.columns)
            rec["equal"] = _frames_equal(
                want.sort_values(srt, ignore_index=True),
                got[srt].sort_values(srt, ignore_index=True))
            rec["rows_out"] = len(got)
        except Exception as e:  # record, keep going
            rec["error"] = f"{type(e).__name__}: {e}"[:300]
        rec["process_rss_gb"] = round(_rss_gb(), 2)
        results[qid] = rec
        _write()
        print(f"Q{qid}: {rec}", flush=True)

    artifact = _write(done=True)
    print(json.dumps({"metric": "streaming_mesh_scale",
                      "value": artifact["all_equal"],
                      "detail": OUT}))


if __name__ == "__main__":
    main()
