"""Dictionary-cliff benchmark: LIKE over increasing string cardinality.

VERDICT r1 weak-point 4: the dictionary walk is host-bound — fine at TPC-H
cardinalities, a cliff at ~1M distinct values (Q13's comment column).  This
script measures a Q13-shaped predicate (`o_comment NOT LIKE
'%special%requests%'`) end-to-end through Context.sql at several distinct
counts, for each of the three bitmap strategies:

- regex:      per-entry Python regex (the r1 path)
- vectorized: np.strings chunk kernels (host, C loops)
- device:     padded bytes-matrix chunk matching on the accelerator

Usage: python benchmarks/string_cliff.py   (prints one JSON line per cell)
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def _make_comments(n_rows: int, n_distinct: int, seed: int = 0) -> np.ndarray:
    rng = np.random.RandomState(seed)
    words = np.array(["special", "requests", "pending", "furious", "ironic",
                      "deposits", "accounts", "packages", "theodolites"])
    parts = words[rng.randint(0, len(words), (n_distinct, 4))]
    distinct = np.array([" ".join(row) + f" #{i}"
                         for i, row in enumerate(parts)], dtype=object)
    return distinct[rng.randint(0, n_distinct, n_rows)]


def main():
    import jax
    if os.environ.get("BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])

    import pandas as pd

    from dask_sql_tpu import Context
    from dask_sql_tpu.ops import strings_fast
    from dask_sql_tpu.physical.rex import ops as rex_ops

    n_rows = int(os.environ.get("CLIFF_ROWS", "2000000"))
    reps = int(os.environ.get("CLIFF_REPS", "3"))
    query = ("SELECT COUNT(*) AS n FROM t "
             "WHERE c NOT LIKE '%special%requests%'")

    for n_distinct in (1_000, 30_000, 1_000_000):
        df = pd.DataFrame({"c": _make_comments(n_rows, n_distinct)})
        ctx = Context()
        ctx.create_table("t", df)

        for strategy in ("regex", "vectorized", "device",
                         "device_compiled"):
            if strategy == "regex":
                # force the r1 path: disable both fast bitmaps
                patch = {"like_bitmap_vectorized": lambda *a: None,
                         "threshold": 1 << 62}
            elif strategy == "vectorized":
                patch = {"threshold": 1 << 62}
            else:
                patch = {"threshold": 0}
            compiled_run = strategy == "device_compiled"
            saved = (strings_fast.like_bitmap_vectorized,
                     strings_fast.DEVICE_STRING_THRESHOLD)
            if "like_bitmap_vectorized" in patch:
                strings_fast.like_bitmap_vectorized = \
                    patch["like_bitmap_vectorized"]
            strings_fast.DEVICE_STRING_THRESHOLD = patch["threshold"]
            # ops.py imports names at call time from the module, so the
            # patch above is what the engine sees
            try:
                if not compiled_run:
                    os.environ["DSQL_COMPILE"] = "0"  # eager: per-QUERY cost
                ctx.sql(query)  # warm (dictionary matrix build for device)
                best = float("inf")
                for _ in range(reps):
                    t0 = time.perf_counter()
                    ctx.sql(query, return_futures=False)
                    best = min(best, time.perf_counter() - t0)
            finally:
                os.environ.pop("DSQL_COMPILE", None)
                (strings_fast.like_bitmap_vectorized,
                 strings_fast.DEVICE_STRING_THRESHOLD) = saved
            print(json.dumps({
                "metric": "like_notlike_wall", "n_distinct": n_distinct,
                "n_rows": n_rows, "strategy": strategy,
                "sec": round(best, 4),
            }), flush=True)


if __name__ == "__main__":
    main()
