"""Third differential oracle: all 22 TPC-H queries, engine vs independent
pandas implementations (benchmarks/pandas_tpch.py).

The sqlite oracle (test_tpch.py) already judges the engine; the pandas
implementations are ALSO the benchmark baseline, so this test pins both at
once — a wrong baseline would make bench.py's vs_baseline meaningless, and a
third independently-written executor agreeing on all 22 queries is the
reference's compatibility-suite strategy scaled up
(/root/reference/tests/integration/test_compatibility.py strategy: same
query, independent engines, equal frames).
"""
import numpy as np
import pandas as pd
import pytest

from benchmarks.pandas_tpch import PANDAS_QUERIES
from benchmarks.tpch import QUERIES, generate_tpch
from dask_sql_tpu import Context


@pytest.fixture(scope="module")
def tpch():
    data = generate_tpch(0.02, seed=7)
    c = Context()
    for name, frame in data.items():
        c.create_table(name, frame)
    return c, data


def _normalize(df: pd.DataFrame) -> pd.DataFrame:
    out = df.copy().reset_index(drop=True)
    for col in out.columns:
        s = out[col]
        if pd.api.types.is_datetime64_any_dtype(s):
            out[col] = pd.to_datetime(s)
        elif pd.api.types.is_float_dtype(s):
            out[col] = s.astype(np.float64).round(6)
        elif pd.api.types.is_bool_dtype(s):
            out[col] = s.astype(bool)
        elif pd.api.types.is_integer_dtype(s):
            out[col] = s.astype(np.int64)
        else:
            out[col] = s.astype(str)
    return out


@pytest.mark.parametrize("qid", sorted(QUERIES))
def test_engine_matches_pandas(tpch, qid):
    c, data = tpch
    eng = c.sql(QUERIES[qid], return_futures=False)
    ref = PANDAS_QUERIES[qid](data)
    assert len(eng.columns) == len(ref.columns), (
        f"Q{qid}: column count {list(eng.columns)} vs {list(ref.columns)}")
    # compare positionally: both follow the SELECT list order
    ref = ref.rename(columns=dict(zip(ref.columns, eng.columns)))
    eng_n, ref_n = _normalize(eng), _normalize(ref)
    cols = list(eng_n.columns)
    eng_n = eng_n.sort_values(cols, ignore_index=True)
    ref_n = ref_n.sort_values(cols, ignore_index=True)
    pd.testing.assert_frame_equal(eng_n, ref_n, check_dtype=False,
                                  rtol=1e-5, atol=1e-6)
