"""UDF / custom aggregation tests (reference: tests/integration/test_function.py)."""
import numpy as np
import pandas as pd
import pytest

from tests.conftest import assert_eq


def test_custom_function(c, df):
    def f(x):
        return x**2

    c.register_function(f, "f", [("x", np.float64)], np.float64)
    result = c.sql("SELECT F(b) AS f FROM df")
    assert_eq(result, pd.DataFrame({"f": df["b"] ** 2}))


def test_custom_function_two_args(c, df):
    def f(x, y):
        return x + y

    c.register_function(f, "f", [("x", np.float64), ("y", np.float64)], np.float64)
    result = c.sql("SELECT F(a, b) AS f FROM df")
    assert_eq(result, pd.DataFrame({"f": df["a"] + df["b"]}))


def test_custom_function_row_udf(c, df_simple):
    def f(row):
        return row["a0"] + row["a1"]

    c.register_function(f, "rowf", [("x", np.int64), ("y", np.float64)],
                        np.float64, row_udf=True)
    result = c.sql("SELECT rowf(a, b) AS f FROM df_simple")
    assert_eq(result, pd.DataFrame({"f": df_simple["a"] + df_simple["b"]}))


def test_replace_function(c, df):
    def f(x):
        return x

    def g(x):
        return x + 1

    c.register_function(f, "h", [("x", np.float64)], np.float64)
    with pytest.raises(ValueError):
        c.register_function(g, "h", [("x", np.float64)], np.float64)
    c.register_function(g, "h", [("x", np.float64)], np.float64, replace=True)
    result = c.sql("SELECT h(b) AS f FROM df")
    assert_eq(result, pd.DataFrame({"f": df["b"] + 1}))


def test_custom_aggregation(c, user_table_1):
    def f(s):
        return s.max() - s.min()

    c.register_aggregation(f, "span", [("x", np.int64)], np.int64)
    result = c.sql(
        "SELECT user_id, span(b) AS s FROM user_table_1 GROUP BY user_id")
    g = user_table_1.groupby("user_id")["b"]
    expected = (g.max() - g.min()).reset_index().rename(columns={"b": "s"})
    expected.columns = ["user_id", "s"]
    assert_eq(result, expected, check_row_order=False)


def test_udf_with_literal(c, df):
    def addn(x, n):
        return x + n

    c.register_function(addn, "addn", [("x", np.float64), ("n", np.int64)], np.float64)
    result = c.sql("SELECT addn(b, 2) AS f FROM df")
    assert_eq(result, pd.DataFrame({"f": df["b"] + 2}))


def test_unknown_function_raises(c, df):
    from dask_sql_tpu.utils import ParsingException
    with pytest.raises(ParsingException):
        c.sql("SELECT nosuchfunction(b) FROM df")
