"""TABLESAMPLE tests (reference: tests/integration/test_sample.py)."""
import pytest


def test_sample_bernoulli(c, df):
    result = c.sql(
        "SELECT * FROM df TABLESAMPLE BERNOULLI (30) REPEATABLE (42)").to_pandas()
    # statistically ~30% of 700 rows; generous bounds like the reference
    assert 100 < len(result) < 350
    # repeatable: same seed -> same rows
    result2 = c.sql(
        "SELECT * FROM df TABLESAMPLE BERNOULLI (30) REPEATABLE (42)").to_pandas()
    assert len(result) == len(result2)


def test_sample_system(c, df):
    result = c.sql(
        "SELECT * FROM df TABLESAMPLE SYSTEM (50) REPEATABLE (7)").to_pandas()
    assert 0 <= len(result) <= len(df)


def test_sample_full(c, df):
    result = c.sql(
        "SELECT * FROM df TABLESAMPLE BERNOULLI (100) REPEATABLE (1)").to_pandas()
    assert len(result) == len(df)
