"""EXPLAIN ANALYZE + QueryReport integration tests.

Pins the observability surface the ISSUE 3 acceptance criteria name:
EXPLAIN ANALYZE over a join+groupby returns a plan tree where EVERY
executed node carries wall-time and row counts, and every Context.sql call
attaches a QueryReport whose invariants (phase sums <= wall, stage spans
matching the stage_graphs counter) hold.
"""
import os

import pandas as pd
import pytest

from dask_sql_tpu import Context


@pytest.fixture
def ctx():
    c = Context()
    c.create_table("t", pd.DataFrame({
        "a": [1, 2, 3, 1, 2, 1], "k": [10, 20, 30, 10, 20, 30]}))
    c.create_table("u", pd.DataFrame({
        "k": [10, 20, 30], "name": list("xyz")}))
    return c


JOIN_GROUPBY = ("SELECT name, SUM(a) AS s FROM t "
                "JOIN u ON t.k = u.k GROUP BY name")


def test_explain_analyze_annotates_every_executed_node(ctx):
    out = ctx.sql("EXPLAIN ANALYZE " + JOIN_GROUPBY, return_futures=False)
    lines = list(out["PLAN"])
    plan_lines = [l for l in lines if not l.startswith("--")]
    # join + groupby plan: scan x2, join, aggregate at minimum
    assert len(plan_lines) >= 4
    assert any("LogicalJoin" in l for l in plan_lines)
    assert any("LogicalAggregate" in l for l in plan_lines)
    for line in plan_lines:
        assert "rows=" in line, f"node missing row count: {line}"
        assert "time=" in line and "ms" in line, \
            f"node missing wall time: {line}"
        assert "self=" in line
    # summary trailer names the run
    assert any(l.startswith("-- analyzed:") and "wall=" in l
               for l in lines)


def test_explain_analyze_row_counts_are_real(ctx):
    out = ctx.sql("EXPLAIN ANALYZE " + JOIN_GROUPBY, return_futures=False)
    lines = list(out["PLAN"])
    # 3 distinct names -> the aggregate (and the root) output 3 rows
    agg = next(l for l in lines if "LogicalAggregate" in l)
    assert "rows=3" in agg
    # the join output carries all 6 probe rows
    join = next(l for l in lines if "LogicalJoin" in l)
    assert "rows=6" in join
    trailer = next(l for l in lines if l.startswith("-- analyzed:"))
    assert "rows_out=3" in trailer


def test_explain_analyze_tier_line(ctx):
    """The ``-- tier:`` trailer mirrors ``-- cache:``: the execution tier
    a PLAIN run of this plan would answer on (the analyzed run itself is
    always eager, per-node instrumentation being the point)."""
    out = ctx.sql("EXPLAIN ANALYZE " + JOIN_GROUPBY, return_futures=False)
    lines = list(out["PLAN"])
    tier_line = next(l for l in lines if l.startswith("-- tier:"))
    tier = tier_line.split()[2]
    assert tier in ("eager", "compiled", "eager-compiling", "compiled-cold")
    # tests pin tiering off and DSQL_COMPILE stays on: a cold plan would
    # pay the compile on arrival
    if os.environ.get("DSQL_COMPILE") != "0":
        assert tier in ("compiled", "compiled-cold")
    assert any(l.startswith("-- cache:") for l in lines)  # both trailers


def test_plain_explain_unchanged(ctx):
    out = ctx.sql("EXPLAIN " + JOIN_GROUPBY, return_futures=False)
    lines = list(out["PLAN"])
    assert any("LogicalJoin" in l for l in lines)
    assert not any("rows=" in l or "time=" in l for l in lines)


def test_explain_analyze_python_parser_gate(ctx):
    """EXPLAIN ANALYZE must parse regardless of the native parser (whose
    grammar predates ANALYZE) — the parse_sql gate routes it to the
    Python parser."""
    from dask_sql_tpu.sql import parser as P

    stmts = P.parse_sql("EXPLAIN ANALYZE SELECT 1 + 1")
    assert len(stmts) == 1
    assert type(stmts[0]).__name__ == "ExplainStatement"
    assert stmts[0].analyze is True
    stmts = P.parse_sql("EXPLAIN SELECT 1 + 1")
    assert stmts[0].analyze is False


# ---------------------------------------------------------------------------
# QueryReport invariants
# ---------------------------------------------------------------------------

def test_query_report_attached_and_invariants(ctx):
    df = ctx.sql(JOIN_GROUPBY, return_futures=False)
    rep = ctx.last_report
    assert rep is not None
    assert rep.query == JOIN_GROUPBY
    assert rep.wall_ms > 0
    # the top-level phases partition the wall: their sum can never exceed it
    top = sum(rep.phases.get(k, 0.0)
              for k in ("parse", "plan", "execute", "fetch"))
    assert top <= rep.wall_ms + 1e-6
    # nested phases are bounded by their parent
    assert rep.phases.get("compile", 0.0) + rep.phases.get(
        "materialize", 0.0) <= rep.phases.get("execute", 0.0) + 1e-6
    assert rep.rows_out == len(df)
    assert rep.bytes_out > 0


def test_query_report_cache_hit_second_run(ctx):
    if os.environ.get("DSQL_COMPILE") == "0":
        pytest.skip("asserts compiled-path spans")
    ctx.sql(JOIN_GROUPBY, return_futures=False)
    ctx.sql(JOIN_GROUPBY, return_futures=False)
    rep = ctx.last_report
    assert rep.counters.get("hits", 0) >= 1
    assert "compiles" not in rep.counters  # steady state: no new compile
    # the cache hit is annotated on a span in the tree
    assert any(s.attrs.get("cache_hit") for s in rep.root.walk())


def test_query_report_stage_spans_match_stage_graphs(ctx, monkeypatch):
    """Report invariant: the span tree records exactly as many stage_graph
    spans as the stage_graphs counter delta, and at least 2 stages per
    graph (a 1-stage partition would have run whole)."""
    if os.environ.get("DSQL_COMPILE") == "0":
        pytest.skip("asserts compiled-path spans")
    monkeypatch.setenv("DSQL_STAGE_HEAVY", "1")
    c = Context()
    c.create_table("t", pd.DataFrame({
        "a": [1, 2, 3, 1, 2, 1], "k": [10, 20, 30, 10, 20, 30]}))
    c.create_table("u", pd.DataFrame({
        "k": [10, 20, 30], "name": list("xyz")}))
    c.sql(JOIN_GROUPBY, return_futures=False)
    rep = c.last_report
    graphs = rep.counters.get("stage_graphs", 0)
    assert graphs >= 1, "DSQL_STAGE_HEAVY=1 must stage a join+groupby plan"
    assert rep.span_count("stage_graph") == graphs
    assert rep.span_count("stage") >= 2


def test_report_survives_query_error(ctx):
    with pytest.raises(Exception):
        ctx.sql("SELECT * FROM missing_table", return_futures=False)
    rep = ctx.last_report
    assert rep is not None
    assert rep.root.attrs.get("error")


def test_last_timings_carries_phase_split(ctx):
    ctx.sql(JOIN_GROUPBY, return_futures=False)
    t = ctx.last_timings
    for key in ("parse_ms", "plan_ms", "exec_ms", "fetch_ms"):
        assert key in t
    if os.environ.get("DSQL_COMPILE") != "0" and "compile_ms" in t:
        assert t["compile_ms"] <= t["exec_ms"] + 1e-6


def test_explain_analyze_returns_meta_table(ctx):
    """EXPLAIN ANALYZE is plain SQL returning a meta Table with a PLAN
    column — the shape the server's wire encoder (and any client) already
    understands."""
    table = ctx.sql("EXPLAIN ANALYZE SELECT a FROM t WHERE a > 1")
    assert table.names == ["PLAN"]
    assert table.num_rows >= 2
