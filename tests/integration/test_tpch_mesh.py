"""All 22 TPC-H queries under Context(mesh=) on the 8-device CPU mesh.

The reference runs its ENTIRE suite against an external distributed
scheduler behind one env switch
(/root/reference/tests/integration/fixtures.py:291-302); the SPMD analogue
is: the same compiled programs, traced over row-sharded inputs, execute as
GSPMD programs over the mesh and must produce results identical to the
single-device path — for every TPC-H shape (outer joins, windows, string
group keys, multi-join snowflakes), not a toy subset.
"""
import numpy as np
import pandas as pd
import pytest

from benchmarks.tpch import QUERIES, generate_tpch
from dask_sql_tpu import Context
from dask_sql_tpu.parallel.mesh import default_mesh
from dask_sql_tpu.physical import compiled


@pytest.fixture(scope="module")
def contexts():
    mesh = default_mesh()
    if mesh.devices.size < 2:
        pytest.skip("needs a multi-device mesh")
    data = generate_tpch(0.01, seed=11)
    plain = Context()
    dist = Context(mesh=mesh)
    for name, frame in data.items():
        plain.create_table(name, frame)
        dist.create_table(name, frame)
    return plain, dist


def _norm(df: pd.DataFrame) -> pd.DataFrame:
    out = df.copy().reset_index(drop=True)
    for col in out.columns:
        s = out[col]
        if pd.api.types.is_float_dtype(s):
            out[col] = s.astype(np.float64).round(6)
    return out


@pytest.mark.parametrize("qid", sorted(QUERIES))
def test_tpch_on_mesh_matches_single_device(contexts, qid, monkeypatch):
    # force the TPU join strategy (merge join): it is what executes on a
    # real TPU mesh, and it is the only strategy covering Q21's anti-join
    # residual — the certification must be of the TPU program under GSPMD
    from dask_sql_tpu.ops import pallas_kernels
    monkeypatch.setattr(pallas_kernels, "_on_tpu", lambda: True)
    plain, dist = contexts
    want = plain.sql(QUERIES[qid], return_futures=False)
    before = compiled.stats["compiles"] + compiled.stats["hits"]
    before_fb = compiled.stats["fallbacks"]
    got = dist.sql(QUERIES[qid], return_futures=False)
    # the SPMD compiled program must be the execution vehicle: a fallback
    # here would mean the mesh path silently ran eager on gathered data
    assert compiled.stats["compiles"] + compiled.stats["hits"] > before
    assert compiled.stats["fallbacks"] == before_fb
    want_n, got_n = _norm(want), _norm(got)
    cols = list(want_n.columns)
    pd.testing.assert_frame_equal(
        got_n.sort_values(cols, ignore_index=True),
        want_n.sort_values(cols, ignore_index=True),
        check_dtype=False, rtol=1e-5, atol=1e-6)
