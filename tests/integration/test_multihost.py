"""Multi-host (multi-process) mesh execution over the DCN analogue.

The reference's CI runs its suite against an external scheduler + worker
pair (/root/reference/.github/docker-compose.yaml:1-17,
/root/reference/tests/integration/fixtures.py:291-297).  The SPMD analogue
here is ``parallel.mesh.init_multihost`` → ``jax.distributed.initialize``:
every host runs the same driver, the mesh spans all hosts' devices, and XLA
routes collectives across processes (gloo on CPU under test; ICI/DCN on real
TPU pods).  This test launches TWO real processes on localhost, each with 4
virtual CPU devices, builds the 8-device global mesh in each, runs a
compiled aggregate+join query through ``Context(mesh=...)`` on BOTH, and
checks the answer equals the single-host result — exercising the
init_multihost path that had never executed before round 4 (VERDICT r3
item 6).
"""
import json
import os
import socket
import subprocess
import sys
import textwrap

import pytest

_WORKER = textwrap.dedent("""
    import json, os, sys
    pid = int(sys.argv[1]); nproc = int(sys.argv[2]); port = sys.argv[3]
    out_path = sys.argv[4]
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")

    from dask_sql_tpu.parallel.mesh import init_multihost
    mesh = init_multihost(coordinator_address=f"127.0.0.1:{port}",
                          num_processes=nproc, process_id=pid)
    assert mesh.devices.size == 8, mesh.devices

    import numpy as np, pandas as pd
    from dask_sql_tpu import Context

    rng = np.random.RandomState(3)  # SAME data in every process (SPMD)
    n = 1000
    orders = pd.DataFrame({"okey": np.arange(n),
                           "cust": rng.randint(0, 37, n),
                           "amount": np.round(rng.uniform(1, 100, n), 2)})
    cust = pd.DataFrame({"ckey": np.arange(37),
                         "seg": rng.choice(["A", "B", "C"], 37)})
    c = Context(mesh=mesh)
    c.create_table("orders", orders)
    c.create_table("cust", cust)
    q = ("SELECT seg, COUNT(*) AS n, SUM(amount) AS s "
         "FROM orders JOIN cust ON cust = ckey "
         "GROUP BY seg ORDER BY seg")
    got = c.sql(q, return_futures=False)
    with open(out_path, "w") as f:
        json.dump({"seg": [str(x) for x in got["seg"]],
                   "n": [int(x) for x in got["n"]],
                   "s": [round(float(x), 2) for x in got["s"]]}, f)
""")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_mesh_query(tmp_path):
    # no pytest-timeout in this image: the 540 s communicate() below is the
    # hang bound, and a wedged pair is killed there
    port = _free_port()
    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    repo_root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    outs = [tmp_path / "out0.json", tmp_path / "out1.json"]
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(pid), "2", str(port),
             str(outs[pid])],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True)
        for pid in (0, 1)
    ]
    logs = []
    for p in procs:
        try:
            stdout, stderr = p.communicate(timeout=540)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multihost worker timed out")
        logs.append((p.returncode, stdout[-1000:], stderr[-2000:]))
    for rc, so, se in logs:
        assert rc == 0, f"worker failed rc={rc}\n{so}\n{se}"

    # expected result from plain single-process pandas (same seeded data)
    import numpy as np
    import pandas as pd

    rng = np.random.RandomState(3)
    n = 1000
    orders = pd.DataFrame({"okey": np.arange(n),
                           "cust": rng.randint(0, 37, n),
                           "amount": np.round(rng.uniform(1, 100, n), 2)})
    cust = pd.DataFrame({"ckey": np.arange(37),
                         "seg": rng.choice(["A", "B", "C"], 37)})
    joined = orders.merge(cust, left_on="cust", right_on="ckey")
    want = (joined.groupby("seg").agg(n=("okey", "size"),
                                      s=("amount", "sum"))
            .reset_index().sort_values("seg"))

    for out in outs:
        got = json.loads(out.read_text())
        assert got["seg"] == [str(x) for x in want["seg"]]
        assert got["n"] == [int(x) for x in want["n"]]
        for a, b in zip(got["s"], want["s"]):
            assert abs(a - float(b)) < 0.05, (got["s"], list(want["s"]))
