"""Out-of-core morsel execution (physical/morsel.py + runtime/spill.py).

The shape under test is the one physical/streaming.py refuses: a plan
whose streamed path meets a SECOND chunked table.  The grace-hash join
partitions both chunked sides on host into spill runs, joins partition
pairs on device with the ordinary compiled join, and pipelines any
GROUP BY above through the streaming combine algebra — so the whole
query completes with the device holding one partition pair at a time.

Every test checks against a pandas oracle and asserts spill hygiene:
runs freed after the query, counters advanced only when the grace path
actually ran.
"""
import numpy as np
import pandas as pd
import pytest

from dask_sql_tpu import Context
from dask_sql_tpu.physical.streaming import StreamingUnsupported
from dask_sql_tpu.runtime import spill as spill_mod
from dask_sql_tpu.runtime import telemetry as tel

N_FACT = 20_000
N_DIM = 6_000
BATCH = 2_048  # 20000 % 2048 != 0: the short-final-batch path is always on


def _norm(df: pd.DataFrame) -> pd.DataFrame:
    out = df.copy()
    for col in out.columns:
        if out[col].dtype.kind in "iuf":
            out[col] = out[col].astype("float64").round(6)
    return (out.sort_values(list(out.columns), na_position="last")
               .reset_index(drop=True))


def _assert_frames(got, want):
    pd.testing.assert_frame_equal(_norm(got), _norm(want),
                                  check_dtype=False, rtol=1e-6, atol=1e-9)


def _data(seed=0):
    rng = np.random.default_rng(seed)
    key = rng.integers(0, N_DIM, N_FACT).astype("float64")
    key[rng.random(N_FACT) < 0.03] = np.nan  # NULL join keys on the fact
    fact = pd.DataFrame({
        "fk": key,
        "val": np.round(rng.random(N_FACT) * 100, 3),
        "tag": rng.choice(["r", "g", "b"], N_FACT),
    })
    dim = pd.DataFrame({
        "dk": np.arange(N_DIM),  # int64 vs the fact's float64 keys
        "grp": rng.choice(["north", "south", "east", "west"], N_DIM),
        "w": np.round(rng.random(N_DIM) * 10, 3),
    })
    return fact, dim


@pytest.fixture
def ooc_ctx(monkeypatch, tmp_path):
    monkeypatch.setenv("DSQL_SPILL_MB", "64")
    monkeypatch.setenv("DSQL_SPILL_DIR", str(tmp_path))
    spill_mod.reset_store()
    fact, dim = _data()
    ctx = Context()
    ctx.create_table("fact", fact, chunked=True, batch_rows=BATCH)
    ctx.create_table("dim", dim, chunked=True, batch_rows=BATCH)
    yield ctx, fact, dim
    spill_mod.reset_store()


def test_two_chunked_join_group_by(ooc_ctx):
    ctx, fact, dim = ooc_ctx
    c0 = tel.REGISTRY.counters()
    got = ctx.sql(
        "SELECT dim.grp AS grp, SUM(fact.val * dim.w) AS s, COUNT(*) AS n "
        "FROM fact JOIN dim ON fact.fk = dim.dk GROUP BY dim.grp",
        return_futures=False)
    j = fact.merge(dim, left_on="fk", right_on="dk")  # NaN keys dropped
    want = (j.assign(x=j.val * j.w)
             .groupby("grp", as_index=False)
             .agg(s=("x", "sum"), n=("x", "size")))
    _assert_frames(got, want)
    c1 = tel.REGISTRY.counters()
    assert c1.get("morsel_joins", 0) > c0.get("morsel_joins", 0)
    assert c1.get("spill_partitions", 0) > c0.get("spill_partitions", 0)
    # hygiene: every grace run freed once the query materialized
    stats = spill_mod.get_store().stats()
    assert stats["runs"] == 0
    assert stats["host_bytes"] == 0 and stats["disk_bytes"] == 0


def test_join_without_group_by(ooc_ctx):
    ctx, fact, dim = ooc_ctx
    got = ctx.sql(
        "SELECT fact.tag AS tag, dim.grp AS grp, fact.val AS val "
        "FROM fact JOIN dim ON fact.fk = dim.dk WHERE dim.w > 9.0",
        return_futures=False)
    j = fact.merge(dim, left_on="fk", right_on="dk")
    want = j[j.w > 9.0][["tag", "grp", "val"]]
    _assert_frames(got, want)
    assert spill_mod.get_store().stats()["runs"] == 0


def test_string_equi_key(ooc_ctx, monkeypatch, tmp_path):
    # string join keys hash by VALUE: the two tables' dictionaries differ
    rng = np.random.default_rng(3)
    left = pd.DataFrame({
        "s": rng.choice(["aa", "bb", "cc", "dd"], 5000),
        "v": rng.random(5000),
    })
    right = pd.DataFrame({
        "s": rng.choice(["bb", "cc", "dd", "ee", "ff"], 3000),
        "u": rng.random(3000),
    })
    ctx = Context()
    ctx.create_table("l", left, chunked=True, batch_rows=700)
    ctx.create_table("r", right, chunked=True, batch_rows=700)
    got = ctx.sql(
        "SELECT l.s AS s, SUM(l.v + r.u) AS t FROM l "
        "JOIN r ON l.s = r.s GROUP BY l.s", return_futures=False)
    j = left.merge(right, on="s")
    want = j.assign(t=j.v + j.u).groupby("s", as_index=False).agg(
        t=("t", "sum"))
    _assert_frames(got, want)


def test_aggregate_side_defers_to_iterative(ooc_ctx):
    # TPC-H Q17 shape: a join side containing an AGGREGATE over a chunked
    # scan is NOT row-local — per-batch partitioning would average each
    # batch separately.  The grace path must decline so the iterative
    # one-subtree-at-a-time strategy lowers the subquery first (regression:
    # grace hijacked Q17 and returned per-batch averages).
    ctx, fact, dim = ooc_ctx
    c0 = tel.REGISTRY.counters()
    got = ctx.sql(
        "SELECT SUM(fact.val) AS s FROM fact JOIN "
        "(SELECT tag AS t, AVG(val) AS a FROM fact GROUP BY tag) AS sub "
        "ON fact.tag = sub.t WHERE fact.val < sub.a",
        return_futures=False)
    avg = fact.groupby("tag")["val"].transform("mean")
    want = pd.DataFrame({"s": [fact.val[fact.val < avg].sum()]})
    _assert_frames(got, want)
    c1 = tel.REGISTRY.counters()
    assert c1.get("morsel_joins", 0) == c0.get("morsel_joins", 0)


def test_spilled_marker_on_query_report(ooc_ctx):
    ctx, fact, dim = ooc_ctx
    ctx.sql("SELECT COUNT(*) AS n FROM fact JOIN dim ON fact.fk = dim.dk",
            return_futures=False)
    report = ctx.last_report
    assert report is not None and report.spilled
    assert report.to_dict()["spilled"] is True
    # a plain chunked scan does NOT carry the marker
    ctx.sql("SELECT SUM(val) AS s FROM fact", return_futures=False)
    assert not ctx.last_report.spilled


def test_spill_disabled_restores_unsupported(monkeypatch, tmp_path):
    monkeypatch.setenv("DSQL_SPILL_MB", "0")
    monkeypatch.setenv("DSQL_SPILL_DIR", str(tmp_path))
    spill_mod.reset_store()
    fact, dim = _data()
    ctx = Context()
    ctx.create_table("fact", fact, chunked=True, batch_rows=BATCH)
    ctx.create_table("dim", dim, chunked=True, batch_rows=BATCH)
    c0 = tel.REGISTRY.counters()
    with pytest.raises(StreamingUnsupported):
        ctx.sql("SELECT COUNT(*) AS n FROM fact "
                "JOIN dim ON fact.fk = dim.dk", return_futures=False)
    # single-chunked streaming is untouched by the kill switch
    got = ctx.sql("SELECT tag, SUM(val) AS s FROM fact GROUP BY tag",
                  return_futures=False)
    want = fact.groupby("tag", as_index=False).agg(s=("val", "sum"))
    _assert_frames(got, want)
    c1 = tel.REGISTRY.counters()
    assert c1.get("spill_partitions", 0) == c0.get("spill_partitions", 0)
    spill_mod.reset_store()


def test_tiny_host_budget_disk_round_trip(monkeypatch, tmp_path):
    # 1 MB host budget + ~2.5 MB of partition payload: runs must round-trip
    # through the disk tier mid-join and the answer must not notice
    monkeypatch.setenv("DSQL_SPILL_MB", "1")
    monkeypatch.setenv("DSQL_SPILL_DIR", str(tmp_path))
    spill_mod.reset_store()
    rng = np.random.default_rng(9)
    n = 50_000
    fact = pd.DataFrame({
        "fk": rng.integers(0, N_DIM, n),
        "val": rng.random(n),
        "e1": rng.random(n), "e2": rng.random(n), "e3": rng.random(n),
    })
    _, dim = _data(seed=9)
    ctx = Context()
    ctx.create_table("fact", fact, chunked=True, batch_rows=8192)
    ctx.create_table("dim", dim, chunked=True, batch_rows=BATCH)
    c0 = tel.REGISTRY.counters()
    got = ctx.sql(
        "SELECT dim.grp AS grp, SUM(fact.val) AS s, SUM(fact.e1) AS s1 "
        "FROM fact JOIN dim ON fact.fk = dim.dk GROUP BY dim.grp",
        return_futures=False)
    j = fact.merge(dim, left_on="fk", right_on="dk")
    want = j.groupby("grp", as_index=False).agg(s=("val", "sum"),
                                                s1=("e1", "sum"))
    _assert_frames(got, want)
    c1 = tel.REGISTRY.counters()
    assert c1.get("spill_flushes", 0) > c0.get("spill_flushes", 0)
    stats = spill_mod.get_store().stats()
    assert stats["runs"] == 0 and stats["disk_bytes"] == 0
    spill_mod.reset_store()
