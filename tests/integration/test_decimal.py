"""Exact decimal aggregation (VERDICT r1 item 6).

DECIMAL(p<=18, s<=9) SUM/AVG accumulate in scaled int64 — order-independent
(bit-stable across runs and row orders) and exactly equal to true decimal
arithmetic, where the reference's f64 fold (mappings.py:64) drifts.
Storage stays f64 (values with <=15 significant digits round-trip f64
uniquely, so comparisons/grouping are already exact); only the ACCUMULATION
changes representation.
"""
import decimal

import numpy as np
import pandas as pd
import pytest

from dask_sql_tpu import Context


@pytest.fixture()
def c():
    return Context()


def test_cast_sum_is_exact(c):
    # 0.1 is the classic f64 repeating binary fraction: naive f64 summation
    # of 1000 copies gives 99.9999999999986; exact decimal gives 100.0
    c.create_table("t", pd.DataFrame({"x": [0.1] * 1000}))
    r = c.sql("SELECT SUM(CAST(x AS DECIMAL(10, 1))) AS s FROM t",
              return_futures=False)
    assert float(r["s"][0]) == 100.0
    naive = c.sql("SELECT SUM(x) AS s FROM t", return_futures=False)
    # document why the decimal path exists (pairwise f64 may or may not
    # drift depending on the reduction shape; the decimal result is EXACT
    # by construction either way)
    assert abs(float(naive["s"][0]) - 100.0) < 1e-9


def test_decimal_object_ingestion(c):
    d = decimal.Decimal
    df = pd.DataFrame({"g": ["a", "b", "a", "b"],
                       "m": [d("1.01"), d("2.02"), d("3.03"), None]})
    c.create_table("t", df)
    entry = c.schema["root"].tables["t"]
    col = entry.table.column("m")
    assert col.stype.name == "DECIMAL" and col.stype.scale == 2
    r = c.sql("SELECT g, SUM(m) AS s, AVG(m) AS a FROM t GROUP BY g "
              "ORDER BY g", return_futures=False)
    assert float(r["s"][0]) == 4.04        # 1.01 + 3.03, exact
    assert float(r["s"][1]) == 2.02
    assert float(r["a"][0]) == 2.02


def test_bit_stable_across_row_orders(c):
    # cents that sum to an exact dollar amount; f64 accumulation order
    # changes the bits, int64 accumulation cannot
    rng = np.random.RandomState(0)
    cents = rng.randint(1, 100000, 50000)
    vals = cents / 100.0
    want = decimal.Decimal(int(cents.sum())) / 100

    sums = set()
    for seed in range(3):
        order = np.random.RandomState(seed).permutation(len(vals))
        ctx = Context()
        ctx.create_table("t", pd.DataFrame({"x": vals[order]}))
        r = ctx.sql("SELECT SUM(CAST(x AS DECIMAL(12, 2))) AS s FROM t",
                    return_futures=False)
        sums.add(float(r["s"][0]).hex())
    assert len(sums) == 1, f"not bit-stable: {sums}"
    assert float.fromhex(next(iter(sums))) == float(want)


def test_grouped_exactness_vs_python_decimal(c):
    d = decimal.Decimal
    rng = np.random.RandomState(1)
    g = rng.randint(0, 7, 5000)
    cents = rng.randint(-500000, 500000, 5000)
    df = pd.DataFrame({"g": g, "x": cents / 100.0})
    c.create_table("t", df)
    r = c.sql("SELECT g, SUM(CAST(x AS DECIMAL(14, 2))) AS s FROM t "
              "GROUP BY g ORDER BY g", return_futures=False)
    for gi in range(7):
        want = d(int(cents[g == gi].sum())) / 100
        got = d(repr(float(r["s"][gi])))
        assert got == want, (gi, got, want)


def test_decimal_compiled_and_eager_agree(c):
    import os

    df = pd.DataFrame({"g": ["x", "y"] * 500, "m": [0.1, 0.3] * 500})
    c.create_table("t", df)
    q = ("SELECT g, SUM(CAST(m AS DECIMAL(10, 1))) AS s FROM t GROUP BY g "
         "ORDER BY g")
    comp = c.sql(q, return_futures=False)
    os.environ["DSQL_COMPILE"] = "0"
    try:
        eager = c.sql(q, return_futures=False)
    finally:
        del os.environ["DSQL_COMPILE"]
    assert comp["s"].tolist() == eager["s"].tolist() == [50.0, 150.0]


def test_large_precision_falls_back_to_f64(c):
    # DECIMAL(38, 10) is outside the exact-int64 envelope: documented f64
    from dask_sql_tpu.types import decimal as mk, exact_decimal_scale

    assert exact_decimal_scale(mk(38, 10)) is None
    # p>15 stores values that can't be exact in the f64 mantissa: declined
    assert exact_decimal_scale(mk(18, 2)) is None
    assert exact_decimal_scale(mk(15, 2)) == 2
    assert exact_decimal_scale(mk(12, 0)) == 0


def test_mixed_and_nonfinite_object_columns_keep_generic_path(c):
    d = decimal.Decimal
    # mixed Decimal + float: NOT typed DECIMAL (no crash, generic path)
    c.create_table("mx", pd.DataFrame({"x": np.array([d("1.5"), 2.5],
                                                     dtype=object)}))
    col = c.schema["root"].tables["mx"].table.column("x")
    assert col.stype.name != "DECIMAL"
    # non-finite Decimal: same
    c.create_table("nf", pd.DataFrame({"x": np.array([d("NaN"), d("1")],
                                                     dtype=object)}))
    assert c.schema["root"].tables["nf"].table.column("x").stype.name != "DECIMAL"
    # scale > 9: typed DECIMAL(38, s) but NOT quantized (f64 fallback)
    c.create_table("hs", pd.DataFrame({
        "x": np.array([d("0.0123456789012"), d("1")], dtype=object)}))
    col = c.schema["root"].tables["hs"].table.column("x")
    assert col.stype.name == "DECIMAL" and col.stype.scale == 13
    from dask_sql_tpu.types import exact_decimal_scale
    assert exact_decimal_scale(col.stype) is None
