"""End-to-end autopilot (runtime/autopilot.py): a repeat-query workload
auto-materializes its hot aggregate and serves the repeat oracle-exactly
across a base-table append; a forced-skew grace join records a re-plan
hint that flips the next execution's partitioning; the ``autopilot``
fault site degrades the advisor to a journaled no-op without ever
touching query results."""
import numpy as np
import pandas as pd
import pytest

from dask_sql_tpu import Context
from dask_sql_tpu.runtime import faults
from dask_sql_tpu.runtime import spill as spill_mod
from dask_sql_tpu.runtime import telemetry as tel


@pytest.fixture()
def ap(tmp_path, monkeypatch):
    monkeypatch.setenv("DSQL_AUTOPILOT", "1")
    monkeypatch.setenv("DSQL_AUTOPILOT_INTERVAL_S", "0")   # explicit ticks
    monkeypatch.setenv("DSQL_AUTOPILOT_MIN_HITS", "2")
    monkeypatch.setenv("DSQL_HISTORY_FILE", str(tmp_path / "hist.jsonl"))
    monkeypatch.setenv("DSQL_RESULT_CACHE_MB", "64")
    from dask_sql_tpu.runtime import autopilot as ap_mod
    ap_mod._reset_for_tests()
    yield ap_mod
    ap_mod._reset_for_tests()


def _norm(df: pd.DataFrame) -> pd.DataFrame:
    out = df.copy()
    for col in out.columns:
        if out[col].dtype.kind in "iuf":
            out[col] = out[col].astype("float64").round(6)
    return (out.sort_values(list(out.columns), na_position="last")
               .reset_index(drop=True))


def _assert_frames(got, want):
    pd.testing.assert_frame_equal(_norm(got), _norm(want),
                                  check_dtype=False, rtol=1e-6, atol=1e-9)


# ---------------------------------------------------------------------------
# matview loop: repeat workload -> auto-materialized -> served oracle-exact
# ---------------------------------------------------------------------------

def test_repeat_workload_auto_materializes_and_serves(ap):
    ctx = Context()
    base = pd.DataFrame({
        "a": [1, 2, 3, 1, 2, 3] * 50,
        "b": [float(i) for i in range(300)],
    })
    ctx.create_table("t", base)
    sql = "SELECT a, SUM(b) AS s FROM t GROUP BY a"

    for _ in range(3):
        got = ctx.sql(sql).to_pandas()
    _assert_frames(got, base.groupby("a", as_index=False)["b"].sum()
                   .rename(columns={"b": "s"}))

    assert ap.tick(ctx)["created"] == 1
    assert any(r["action"] == "mv_create" for r in ap.journal_rows())

    # a base-table append invalidates the result cache (epoch bump); the
    # repeat is answered from the maintained view, refreshed O(delta)
    extra = pd.DataFrame({"a": [1, 1], "b": [1000.0, 2000.0]})
    ctx.append_rows("t", extra)
    serves_before = tel.REGISTRY.get("autopilot_mv_serves") or 0
    got = ctx.sql(sql).to_pandas()
    assert (tel.REGISTRY.get("autopilot_mv_serves") or 0) == serves_before + 1
    oracle = (pd.concat([base, extra], ignore_index=True)
              .groupby("a", as_index=False)["b"].sum()
              .rename(columns={"b": "s"}))
    _assert_frames(got, oracle)


def test_kill_switch_runs_baseline(ap, monkeypatch):
    monkeypatch.setenv("DSQL_AUTOPILOT", "0")
    ctx = Context()
    ctx.create_table("t", pd.DataFrame({"a": [1, 2, 2], "b": [1.0, 2.0, 3.0]}))
    sql = "SELECT a, SUM(b) AS s FROM t GROUP BY a"
    for _ in range(3):
        got = ctx.sql(sql).to_pandas()
    _assert_frames(got, pd.DataFrame({"a": [1, 2], "s": [1.0, 5.0]}))
    assert ap.tick(ctx) == {}
    assert ap.journal_rows() == []
    assert ap.engine_section()["managedViews"] == []


# ---------------------------------------------------------------------------
# adaptive re-planning: forced skew -> hint -> next run repartitions finer
# ---------------------------------------------------------------------------

def test_forced_skew_join_flips_partitioning_next_run(ap, tmp_path,
                                                      monkeypatch):
    monkeypatch.setenv("DSQL_AUTOPILOT_SKEW", "1.5")
    monkeypatch.setenv("DSQL_SPILL_MB", "64")
    monkeypatch.setenv("DSQL_SPILL_DIR", str(tmp_path / "spill"))
    spill_mod.reset_store()
    rng = np.random.default_rng(7)
    n_fact, n_dim = 6_000, 1_000
    key = rng.integers(0, n_dim, n_fact).astype("float64")
    key[rng.random(n_fact) < 0.9] = 3.0        # 90% of rows on one key
    fact = pd.DataFrame({"fk": key,
                         "val": np.round(rng.random(n_fact) * 100, 3)})
    dim = pd.DataFrame({"dk": np.arange(n_dim),
                        "w": np.round(rng.random(n_dim) * 10, 3)})
    ctx = Context()
    ctx.create_table("fact", fact, chunked=True, batch_rows=512)
    ctx.create_table("dim", dim, chunked=True, batch_rows=512)
    sql = ("SELECT SUM(fact.val * dim.w) AS s, COUNT(*) AS n "
           "FROM fact JOIN dim ON fact.fk = dim.dk")
    j = fact.merge(dim, left_on="fk", right_on="dk")
    oracle = pd.DataFrame({"s": [(j.val * j.w).sum()], "n": [len(j)]})

    def _grace_partitions():
        rep = tel.last_report()
        for s in rep.root.walk():
            if s.name == "grace_join":
                return int(s.attrs["partitions"])
        raise AssertionError("no grace_join span — the grace path did "
                             "not run")

    # run 1: skewed, unhinted -> trips DSQL_AUTOPILOT_SKEW, records a hint
    _assert_frames(ctx.sql(sql, return_futures=False), oracle)
    p1 = _grace_partitions()
    recs = [r for r in ap.journal_rows() if r["action"] == "hint_record"]
    assert len(recs) == 1 and "skew_ratio=" in recs[0]["trigger"]
    fp = recs[0]["fingerprint"]
    assert ap.get_hint(fp)["hints"] == {"partitions": p1 * 2}

    # run 2: the hint flips the NEXT execution's partitioning — and the
    # hinted plan still matches the pandas oracle exactly
    _assert_frames(ctx.sql(sql, return_futures=False), oracle)
    assert _grace_partitions() == p1 * 2
    rep = tel.last_report()
    assert any(s.attrs.get("autopilot_hinted") for s in rep.root.walk())
    # the hinted run was judged against its recorded baseline
    verdicts = [r for r in ap.journal_rows()
                if r["action"] in ("hint_verdict", "hint_strike",
                                   "hint_revert")]
    assert verdicts and verdicts[-1]["fingerprint"] == fp
    spill_mod.reset_store()


# ---------------------------------------------------------------------------
# chaos: the advisor may stall, never break a query
# ---------------------------------------------------------------------------

def test_fault_autopilot_degrades_to_noop_never_wrong_results(ap):
    ctx = Context()
    base = pd.DataFrame({"a": [1, 2, 3] * 40,
                         "b": [float(i) for i in range(120)]})
    ctx.create_table("t", base)
    sql = "SELECT a, SUM(b) AS s FROM t GROUP BY a"
    oracle = (base.groupby("a", as_index=False)["b"].sum()
              .rename(columns={"b": "s"}))
    with faults.inject("autopilot:1+"):
        for _ in range(3):
            _assert_frames(ctx.sql(sql).to_pandas(), oracle)
        out = ap.tick(ctx)
        assert out == {"faulted": True}
        assert ap.tick(ctx) == {"faulted": True}
    rows = ap.journal_rows()
    assert [r["action"] for r in rows[-2:]] == ["tick_fault", "tick_fault"]
    assert ap.engine_section()["managedViews"] == []
    # faults cleared: the same context recovers on the next tick
    assert ap.tick(ctx)["created"] == 1
    _assert_frames(ctx.sql(sql).to_pandas(), oracle)
