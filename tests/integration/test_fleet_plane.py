"""Fleet plane end-to-end: two REAL child replica processes writing
into one shared DSQL_FLEET_DIR, merged ordering + composite-cursor
monotonicity read back by the parent, and the server surface
(/v1/fleet, /v1/events?fleet=1, /metrics replica label, 404 when
disarmed)."""
import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

# what each child replica runs: arm the fleet, publish a handful of
# events that share one trace id, heartbeat, exit 0
_CHILD = """
import os, sys, time
from dask_sql_tpu.runtime import fleet, events
rid = os.environ["DSQL_REPLICA_ID"]
assert fleet.ensure_armed()
for i in range(int(sys.argv[1])):
    events.publish("child.tick", trace=sys.argv[2],
                   detail={"i": i, "rid": rid})
    time.sleep(0.01)
fleet.write_heartbeat_now()
print(fleet.replica_id())
"""


def _spawn_child(fleet_dir, rid, n_events, trace):
    env = {k: v for k, v in os.environ.items()
           if not k.startswith("DSQL_")}
    env.update({
        "JAX_PLATFORMS": "cpu",
        "DSQL_FLEET_DIR": str(fleet_dir),
        "DSQL_REPLICA_ID": rid,
        "DSQL_FLEET_BEAT_S": "0.2",
    })
    return subprocess.Popen(
        [sys.executable, "-c", _CHILD, str(n_events), trace],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE)


@pytest.fixture()
def fleet_env(tmp_path, monkeypatch):
    monkeypatch.setenv("DSQL_FLEET_DIR", str(tmp_path))
    monkeypatch.setenv("DSQL_FLEET_BEAT_S", "0.2")
    monkeypatch.setenv("DSQL_REPLICA_ID", "r-parent")
    for key in ("DSQL_EVENTS", "DSQL_EVENTS_FILE", "DSQL_HISTORY_FILE"):
        monkeypatch.delenv(key, raising=False)
    from dask_sql_tpu.runtime import events
    from dask_sql_tpu.runtime import fleet as fl
    fl._reset_for_tests()
    events._reset_for_tests()
    yield tmp_path, fl
    fl._reset_for_tests()
    events._reset_for_tests()
    for key in ("DSQL_EVENTS", "DSQL_EVENTS_FILE", "DSQL_HISTORY_FILE"):
        os.environ.pop(key, None)


def test_two_child_replicas_merge_and_cursor(fleet_env):
    tmp_path, fleet = fleet_env
    p1 = _spawn_child(tmp_path, "r-one", 5, "trace-x")
    p2 = _spawn_child(tmp_path, "r-two", 5, "trace-x")
    for p in (p1, p2):
        out, err = p.communicate(timeout=300)
        assert p.returncode == 0, err.decode()
    # both heartbeats registered (children exited < TTL ago → alive)
    reps = {r["replica"]: r for r in fleet.read_replicas()}
    assert {"r-one", "r-two"} <= set(reps)
    # merged stream: globally timestamp-ordered, per-replica seq order
    # preserved, one trace id stitched across both replicas
    rows = fleet.merged_events_rows()
    assert len(rows) == 10
    assert [r["unix"] for r in rows] == sorted(r["unix"] for r in rows)
    for rid in ("r-one", "r-two"):
        seqs = [r["seq"] for r in rows if r["replica"] == rid]
        assert seqs == sorted(seqs) and len(seqs) == 5
    assert {r["replica"] for r in rows if r["trace"] == "trace-x"} == \
        {"r-one", "r-two"}
    # composite cursor walks the same 10 events exactly once, in order
    seen, cursor = [], ""
    while True:
        batch, cursor = fleet.read_merged_since(cursor, limit=3)
        if not batch:
            break
        seen.extend(batch)
    assert [(r["replica"], r["seq"]) for r in seen] == \
        [(r["replica"], r["seq"]) for r in rows]


def test_dead_child_expires_from_registry(fleet_env):
    tmp_path, fleet = fleet_env
    p = _spawn_child(tmp_path, "r-brief", 1, "t")
    out, err = p.communicate(timeout=300)
    assert p.returncode == 0, err.decode()
    assert any(r["replica"] == "r-brief" for r in fleet.read_replicas())
    # past the TTL the killed replica reads as dead, without deletion
    deadline = time.time() + 3 * fleet.ttl_s()
    while time.time() < deadline:
        rows = [r for r in fleet.read_replicas()
                if r["replica"] == "r-brief"]
        if rows and not rows[0]["alive"]:
            break
        time.sleep(0.1)
    assert rows and rows[0]["alive"] is False


# ---------------------------------------------------------------------------
# the server surface
# ---------------------------------------------------------------------------

@pytest.fixture()
def fleet_server(fleet_env):
    tmp_path, fleet = fleet_env
    from dask_sql_tpu.context import Context
    from dask_sql_tpu.server.app import run_server
    context = Context()
    context.create_table("t", {"a": np.arange(8, dtype=np.int64)})
    srv = run_server(context=context, host="127.0.0.1", port=0,
                     blocking=False)
    yield f"http://127.0.0.1:{srv.server_port}", tmp_path, fleet
    srv.shutdown()


def _get(url):
    with urllib.request.urlopen(url) as r:
        return json.loads(r.read())


def test_v1_fleet_snapshot_reconciles_with_engine(fleet_server):
    base, tmp_path, fleet = fleet_server
    snap = _get(f"{base}/v1/fleet")
    for key in ("dir", "replica", "replicas", "totals", "slo"):
        assert key in snap, key
    assert snap["replica"] == "r-parent"
    rows = {r["replica"]: r for r in snap["replicas"]}
    assert rows["r-parent"]["alive"] is True
    engine = _get(f"{base}/v1/engine")
    assert engine["fleet"]["replica"] == "r-parent"
    assert engine["fleet"]["dir"] == str(tmp_path)
    # the parent's heartbeat row agrees with its own /v1/engine
    assert rows["r-parent"]["pid"] == engine["pid"]


def test_v1_events_fleet_mode_composite_cursor(fleet_server):
    base, tmp_path, fleet = fleet_server
    from dask_sql_tpu.runtime import events
    events.publish("srv.alpha", trace="t-s", detail={})
    events.publish("srv.beta", trace="t-s", detail={})
    req = urllib.request.Request(f"{base}/v1/events?fleet=1&limit=1")
    with urllib.request.urlopen(req) as r:
        lines = [json.loads(x) for x in r.read().splitlines() if x]
        cur1 = r.headers["X-DSQL-Cursor"]
    assert len(lines) == 1 and lines[0]["replica"] == "r-parent"
    assert ":" in cur1                 # composite replica:seq cursor
    req = urllib.request.Request(
        f"{base}/v1/events?fleet=1&cursor={urllib.parse.quote(cur1)}")
    with urllib.request.urlopen(req) as r:
        lines2 = [json.loads(x) for x in r.read().splitlines() if x]
        cur2 = r.headers["X-DSQL-Cursor"]
    types = [x["type"] for x in lines2]
    assert lines[0]["type"] not in types        # no replay past cursor
    assert "srv.beta" in types
    assert fleet.parse_cursor(cur2)["r-parent"] >= \
        fleet.parse_cursor(cur1)["r-parent"]


def test_metrics_carry_replica_label(fleet_server):
    base, _, _ = fleet_server
    with urllib.request.urlopen(f"{base}/metrics") as r:
        body = r.read().decode()
    lines = [ln for ln in body.splitlines()
             if ln and not ln.startswith("#")]
    assert lines
    assert all('replica="r-parent"' in ln for ln in lines), \
        [ln for ln in lines if 'replica="r-parent"' not in ln][:3]


# ---------------------------------------------------------------------------
# disarmed: 404 + unlabeled wire
# ---------------------------------------------------------------------------

@pytest.fixture()
def plain_server(monkeypatch):
    monkeypatch.delenv("DSQL_FLEET_DIR", raising=False)
    from dask_sql_tpu.context import Context
    from dask_sql_tpu.server.app import run_server
    context = Context()
    context.create_table("t", {"a": np.arange(4, dtype=np.int64)})
    srv = run_server(context=context, host="127.0.0.1", port=0,
                     blocking=False)
    yield f"http://127.0.0.1:{srv.server_port}"
    srv.shutdown()


def test_v1_fleet_404_when_disarmed(plain_server):
    with pytest.raises(urllib.error.HTTPError) as exc:
        urllib.request.urlopen(f"{plain_server}/v1/fleet")
    assert exc.value.code == 404


def test_metrics_unlabeled_when_disarmed(plain_server):
    with urllib.request.urlopen(f"{plain_server}/metrics") as r:
        body = r.read().decode()
    assert 'replica="' not in body
