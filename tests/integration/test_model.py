"""SQL-driven ML tests (reference: tests/integration/test_model.py)."""
import os
import pickle
import tempfile

import numpy as np
import pandas as pd
import pytest

sklearn = pytest.importorskip("sklearn")


@pytest.fixture()
def training_df(c):
    rng = np.random.RandomState(42)
    n = 200
    df = pd.DataFrame({
        "x": rng.uniform(-5, 5, n),
        "y": rng.uniform(-5, 5, n),
    })
    df["target"] = (df["x"] * 2 + df["y"] > 0).astype(np.int64)
    c.create_table("timeseries", df)
    return df


def test_create_model(c, training_df):
    c.sql("""
        CREATE MODEL my_model WITH (
            model_class = 'sklearn.linear_model.LogisticRegression',
            target_column = 'target'
        ) AS (SELECT x, y, target FROM timeseries)
    """)
    assert "my_model" in c.schema[c.schema_name].models
    model, columns = c.schema[c.schema_name].models["my_model"]
    assert columns == ["x", "y"]
    assert hasattr(model, "predict")


def test_predict(c, training_df):
    c.sql("""
        CREATE MODEL my_model WITH (
            model_class = 'sklearn.linear_model.LogisticRegression',
            target_column = 'target'
        ) AS (SELECT x, y, target FROM timeseries)
    """)
    result = c.sql("""
        SELECT * FROM PREDICT(MODEL my_model, SELECT x, y FROM timeseries)
    """).to_pandas()
    assert "target" in result.columns
    assert len(result) == len(training_df)
    # sanity: mostly matches the trained labels (separable data)
    acc = (result["target"] == training_df["target"]).mean()
    assert acc > 0.9


def test_show_and_describe_models(c, training_df):
    c.sql("""
        CREATE MODEL my_model WITH (
            model_class = 'sklearn.linear_model.LogisticRegression',
            target_column = 'target'
        ) AS (SELECT x, y, target FROM timeseries)
    """)
    models = c.sql("SHOW MODELS").to_pandas()
    assert "my_model" in list(models["Models"])
    desc = c.sql("DESCRIBE MODEL my_model").to_pandas()
    assert "training_columns" in list(desc["Params"])


def test_drop_model(c, training_df):
    with pytest.raises(RuntimeError):
        c.sql("DROP MODEL no_model")
    c.sql("DROP MODEL IF EXISTS no_model")
    c.sql("""
        CREATE MODEL my_model WITH (
            model_class = 'sklearn.linear_model.LogisticRegression',
            target_column = 'target'
        ) AS (SELECT x, y, target FROM timeseries)
    """)
    c.sql("DROP MODEL my_model")
    assert "my_model" not in c.schema[c.schema_name].models


def test_replace_and_if_not_exists(c, training_df):
    q = """
        CREATE MODEL my_model WITH (
            model_class = 'sklearn.linear_model.LogisticRegression',
            target_column = 'target'
        ) AS (SELECT x, y, target FROM timeseries)
    """
    c.sql(q)
    with pytest.raises(RuntimeError):
        c.sql(q)
    c.sql(q.replace("CREATE MODEL", "CREATE MODEL IF NOT EXISTS"))
    c.sql(q.replace("CREATE MODEL", "CREATE OR REPLACE MODEL"))


def test_create_experiment(c, training_df):
    result = c.sql("""
        CREATE EXPERIMENT exp WITH (
            model_class = 'sklearn.linear_model.LogisticRegression',
            experiment_class = 'sklearn.model_selection.GridSearchCV',
            tune_parameters = (C = ARRAY [0.1, 1.0]),
            target_column = 'target'
        ) AS (SELECT x, y, target FROM timeseries)
    """)
    assert "exp" in c.schema[c.schema_name].models
    assert result is not None
    df = result.to_pandas()
    assert "mean_test_score" in df.columns


def test_export_model(c, training_df):
    c.sql("""
        CREATE MODEL my_model WITH (
            model_class = 'sklearn.linear_model.LogisticRegression',
            target_column = 'target'
        ) AS (SELECT x, y, target FROM timeseries)
    """)
    with tempfile.TemporaryDirectory() as d:
        pkl = os.path.join(d, "model.pkl")
        c.sql(f"EXPORT MODEL my_model WITH (format = 'pickle', location = '{pkl}')")
        with open(pkl, "rb") as f:
            model = pickle.load(f)
        assert hasattr(model, "predict")

        joblib = pytest.importorskip("joblib")
        jbl = os.path.join(d, "model.joblib")
        c.sql(f"EXPORT MODEL my_model WITH (format = 'joblib', location = '{jbl}')")
        assert hasattr(joblib.load(jbl), "predict")

    with pytest.raises(NotImplementedError):
        c.sql("EXPORT MODEL my_model WITH (format = 'onnx', location = 'x.onnx')")


def test_ml_experiment_requires_class(c, training_df):
    with pytest.raises(AttributeError):
        c.sql("""
            CREATE EXPERIMENT failing WITH (target_column = 'target')
            AS (SELECT x, y, target FROM timeseries)
        """)


def test_predict_on_expression_query(c, training_df):
    c.sql("""
        CREATE MODEL my_model WITH (
            model_class = 'sklearn.linear_model.LinearRegression',
            target_column = 'target'
        ) AS (SELECT x, y, target FROM timeseries)
    """)
    result = c.sql("""
        SELECT AVG(target) AS avg_pred
        FROM PREDICT(MODEL my_model, SELECT x, y FROM timeseries)
    """).to_pandas()
    assert 0.0 <= result["avg_pred"][0] <= 1.0
