"""Long chaos-soak variant (the 45 s CI gate lives in scripts/ci_local.sh;
this is the extended rehearsal, excluded from tier-1 via the ``slow``
marker).  Runs in a subprocess so the soak's env arming (scheduler slots,
fault probabilities, quarantine file) can never leak into the suite."""
import os
import subprocess
import sys

import pytest

_ROOT = os.path.join(os.path.dirname(__file__), "..", "..")


@pytest.mark.slow
def test_chaos_soak_long():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("DSQL_FAULT_INJECT", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "scripts", "chaos_soak.py"),
         "--budget-s", "120", "--clients", "6", "--p", "0.08"],
        env=env, cwd=_ROOT, capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, (
        f"long chaos soak failed:\n{proc.stdout[-4000:]}\n"
        f"{proc.stderr[-4000:]}")
