"""SQLite differential-oracle tests.

The reference's most important test pattern (SURVEY §4): run the same SQL
through the engine and through in-memory sqlite3 and compare frames
(/root/reference/tests/integration/test_compatibility.py:22-67, with
make_rand_df seeded generators).
"""
import numpy as np
import pandas as pd
import pytest

from tests.conftest import eq_sqlite, make_rand_df


def test_basic_select():
    a = make_rand_df(30, a=int, b=float, c=str)
    eq_sqlite("SELECT a, b, c FROM a", a=a)
    eq_sqlite("SELECT a+1 AS a1, b*2 AS b2 FROM a", a=a)


def test_where():
    a = make_rand_df(30, a=(int, 5), b=(float, 5), c=(str, 5))
    eq_sqlite("SELECT * FROM a WHERE a < 5", a=a)
    eq_sqlite("SELECT * FROM a WHERE a < 5 AND b > 2", a=a)
    eq_sqlite("SELECT * FROM a WHERE a IS NULL OR b > 2", a=a)
    eq_sqlite("SELECT * FROM a WHERE c IS NOT NULL", a=a)


def test_arithmetic():
    a = make_rand_df(20, a=int, b=float)
    eq_sqlite("SELECT a+b AS x, a-b AS y, a*b AS z, b/2 AS w FROM a", a=a)
    eq_sqlite("SELECT -a AS na, ABS(a-5) AS ab FROM a", a=a)


def test_case_when():
    a = make_rand_df(30, a=(int, 5), b=(float, 5))
    eq_sqlite(
        """SELECT CASE WHEN a IS NULL THEN -1 WHEN a < 5 THEN a*10 ELSE b END AS x
           FROM a""", a=a)


def test_group_by_agg():
    a = make_rand_df(50, a=(int, 10), b=(float, 10), c=(str, 10))
    eq_sqlite(
        """SELECT c, SUM(a) AS sa, COUNT(*) AS n, COUNT(a) AS ca,
                  AVG(b) AS ab, MIN(a) AS mi, MAX(a) AS ma
           FROM a GROUP BY c""", a=a)


def test_group_by_multiple_keys():
    a = make_rand_df(60, a=(int, 10), c=(str, 10), d=(str, 10))
    eq_sqlite("SELECT c, d, COUNT(*) AS n, SUM(a) AS s FROM a GROUP BY c, d", a=a)


def test_distinct():
    a = make_rand_df(50, a=(int, 10), c=(str, 10))
    eq_sqlite("SELECT DISTINCT a, c FROM a", a=a)
    eq_sqlite("SELECT COUNT(DISTINCT a) AS n FROM a", a=a)


def test_order_by_limit():
    a = make_rand_df(40, a=(int, 5), b=float, c=(str, 5))
    eq_sqlite("SELECT * FROM a ORDER BY b LIMIT 10", check_row_order=True, a=a)
    eq_sqlite("SELECT * FROM a ORDER BY a NULLS FIRST, b DESC LIMIT 10",
              check_row_order=True, a=a)
    eq_sqlite("SELECT * FROM a ORDER BY c NULLS LAST, b LIMIT 5 OFFSET 3",
              check_row_order=True, a=a)


def test_join_inner():
    a = make_rand_df(30, k=int, va=float)
    b = make_rand_df(20, k=int, vb=float)
    eq_sqlite("SELECT a.k, va, vb FROM a JOIN b ON a.k = b.k", a=a, b=b)


def test_join_left():
    a = make_rand_df(30, k=(int, 5), va=float)
    b = make_rand_df(20, k=(int, 3), vb=float)
    eq_sqlite("SELECT a.k, va, vb FROM a LEFT JOIN b ON a.k = b.k", a=a, b=b)


def test_join_multi_key():
    a = make_rand_df(40, k1=int, k2=(str, 5), va=float)
    b = make_rand_df(30, k1=int, k2=(str, 5), vb=float)
    eq_sqlite(
        """SELECT a.k1, a.k2, va, vb FROM a
           JOIN b ON a.k1 = b.k1 AND a.k2 = b.k2""", a=a, b=b)


def test_union_compat():
    a = make_rand_df(20, a=int, b=str)
    b = make_rand_df(20, a=int, b=str)
    eq_sqlite("SELECT * FROM a UNION SELECT * FROM b", a=a, b=b)
    eq_sqlite("SELECT * FROM a UNION ALL SELECT * FROM b", a=a, b=b)
    eq_sqlite("SELECT * FROM a EXCEPT SELECT * FROM b", a=a, b=b)
    eq_sqlite("SELECT * FROM a INTERSECT SELECT * FROM b", a=a, b=b)


def test_in_subquery():
    a = make_rand_df(30, k=int, v=float)
    b = make_rand_df(10, k=int)
    eq_sqlite("SELECT * FROM a WHERE k IN (SELECT k FROM b)", a=a, b=b)
    eq_sqlite("SELECT * FROM a WHERE k NOT IN (SELECT k FROM b)", a=a, b=b)


def test_scalar_subquery_compat():
    a = make_rand_df(30, k=int, v=float)
    eq_sqlite("SELECT * FROM a WHERE v > (SELECT AVG(v) FROM a)", a=a)


def test_having_compat():
    a = make_rand_df(50, g=(str, 5), v=float)
    eq_sqlite(
        "SELECT g, SUM(v) AS s FROM a GROUP BY g HAVING COUNT(*) > 5", a=a)


def test_string_funcs_compat():
    a = make_rand_df(30, s=(str, 5))
    eq_sqlite("SELECT UPPER(s) AS u, LOWER(s) AS l, LENGTH(s) AS n FROM a", a=a)
    eq_sqlite("SELECT * FROM a WHERE s LIKE 's1%'", a=a)


def test_cte_compat():
    a = make_rand_df(30, k=int, v=float)
    eq_sqlite(
        """WITH big AS (SELECT * FROM a WHERE v > 5),
                agg AS (SELECT k, COUNT(*) AS n FROM big GROUP BY k)
           SELECT * FROM agg""", a=a)


def test_outer_order_limit_over_setop_and_raw():
    """ORDER BY/LIMIT/OFFSET outside CTE+set-op or parenthesized bodies must
    apply exactly once (regression: OFFSET was applied twice)."""
    a = pd.DataFrame({"x": [1, 2, 3, 4, 5]})
    eq_sqlite(
        "WITH c AS (SELECT x FROM a) "
        "SELECT x FROM c UNION ALL SELECT 99 ORDER BY 1 LIMIT 3 OFFSET 1",
        a=a)
    eq_sqlite("SELECT x FROM a UNION SELECT x + 10 FROM a ORDER BY 1 LIMIT 4",
              a=a)
    # sqlite cannot parse these two shapes; assert directly
    from dask_sql_tpu import Context
    c = Context()
    c.create_table("a", a)
    got = c.sql("VALUES (3), (1), (2) ORDER BY 1 LIMIT 2").to_pandas()
    assert got.iloc[:, 0].tolist() == [1, 2]
    got = c.sql("(SELECT x FROM a ORDER BY x DESC LIMIT 4) LIMIT 2").to_pandas()
    assert sorted(got["x"].tolist()) == [4, 5]


def test_window_compat():
    a = make_rand_df(30, g=(str, 3), v=float)
    eq_sqlite(
        """SELECT g, v,
                  ROW_NUMBER() OVER (PARTITION BY g ORDER BY v) AS r,
                  SUM(v) OVER (PARTITION BY g ORDER BY v) AS s
           FROM a""", a=a)


def test_complex_query():
    a = make_rand_df(60, g=(str, 10), k=int, v=(float, 10))
    b = make_rand_df(20, k=int, w=float)
    eq_sqlite(
        """SELECT a.g, COUNT(*) AS n, SUM(a.v * b.w) AS dot
           FROM a JOIN b ON a.k = b.k
           WHERE a.v IS NOT NULL
           GROUP BY a.g
           HAVING COUNT(*) > 1
           ORDER BY dot DESC
           LIMIT 5""", check_row_order=False, a=a, b=b)
