"""SQLite differential-oracle tests.

The reference's most important test pattern (SURVEY §4): run the same SQL
through the engine and through in-memory sqlite3 and compare frames
(/root/reference/tests/integration/test_compatibility.py:22-67, with
make_rand_df seeded generators).
"""
import numpy as np
import pandas as pd
import pytest

from tests.conftest import eq_sqlite, make_rand_df


def test_basic_select():
    a = make_rand_df(30, a=int, b=float, c=str)
    eq_sqlite("SELECT a, b, c FROM a", a=a)
    eq_sqlite("SELECT a+1 AS a1, b*2 AS b2 FROM a", a=a)


def test_where():
    a = make_rand_df(30, a=(int, 5), b=(float, 5), c=(str, 5))
    eq_sqlite("SELECT * FROM a WHERE a < 5", a=a)
    eq_sqlite("SELECT * FROM a WHERE a < 5 AND b > 2", a=a)
    eq_sqlite("SELECT * FROM a WHERE a IS NULL OR b > 2", a=a)
    eq_sqlite("SELECT * FROM a WHERE c IS NOT NULL", a=a)


def test_arithmetic():
    a = make_rand_df(20, a=int, b=float)
    eq_sqlite("SELECT a+b AS x, a-b AS y, a*b AS z, b/2 AS w FROM a", a=a)
    eq_sqlite("SELECT -a AS na, ABS(a-5) AS ab FROM a", a=a)


def test_case_when():
    a = make_rand_df(30, a=(int, 5), b=(float, 5))
    eq_sqlite(
        """SELECT CASE WHEN a IS NULL THEN -1 WHEN a < 5 THEN a*10 ELSE b END AS x
           FROM a""", a=a)


def test_group_by_agg():
    a = make_rand_df(50, a=(int, 10), b=(float, 10), c=(str, 10))
    eq_sqlite(
        """SELECT c, SUM(a) AS sa, COUNT(*) AS n, COUNT(a) AS ca,
                  AVG(b) AS ab, MIN(a) AS mi, MAX(a) AS ma
           FROM a GROUP BY c""", a=a)


def test_group_by_multiple_keys():
    a = make_rand_df(60, a=(int, 10), c=(str, 10), d=(str, 10))
    eq_sqlite("SELECT c, d, COUNT(*) AS n, SUM(a) AS s FROM a GROUP BY c, d", a=a)


def test_distinct():
    a = make_rand_df(50, a=(int, 10), c=(str, 10))
    eq_sqlite("SELECT DISTINCT a, c FROM a", a=a)
    eq_sqlite("SELECT COUNT(DISTINCT a) AS n FROM a", a=a)


def test_order_by_limit():
    a = make_rand_df(40, a=(int, 5), b=float, c=(str, 5))
    eq_sqlite("SELECT * FROM a ORDER BY b LIMIT 10", check_row_order=True, a=a)
    eq_sqlite("SELECT * FROM a ORDER BY a NULLS FIRST, b DESC LIMIT 10",
              check_row_order=True, a=a)
    eq_sqlite("SELECT * FROM a ORDER BY c NULLS LAST, b LIMIT 5 OFFSET 3",
              check_row_order=True, a=a)


def test_join_inner():
    a = make_rand_df(30, k=int, va=float)
    b = make_rand_df(20, k=int, vb=float)
    eq_sqlite("SELECT a.k, va, vb FROM a JOIN b ON a.k = b.k", a=a, b=b)


def test_join_left():
    a = make_rand_df(30, k=(int, 5), va=float)
    b = make_rand_df(20, k=(int, 3), vb=float)
    eq_sqlite("SELECT a.k, va, vb FROM a LEFT JOIN b ON a.k = b.k", a=a, b=b)


def test_join_multi_key():
    a = make_rand_df(40, k1=int, k2=(str, 5), va=float)
    b = make_rand_df(30, k1=int, k2=(str, 5), vb=float)
    eq_sqlite(
        """SELECT a.k1, a.k2, va, vb FROM a
           JOIN b ON a.k1 = b.k1 AND a.k2 = b.k2""", a=a, b=b)


def test_union_compat():
    a = make_rand_df(20, a=int, b=str)
    b = make_rand_df(20, a=int, b=str)
    eq_sqlite("SELECT * FROM a UNION SELECT * FROM b", a=a, b=b)
    eq_sqlite("SELECT * FROM a UNION ALL SELECT * FROM b", a=a, b=b)
    eq_sqlite("SELECT * FROM a EXCEPT SELECT * FROM b", a=a, b=b)
    eq_sqlite("SELECT * FROM a INTERSECT SELECT * FROM b", a=a, b=b)


def test_in_subquery():
    a = make_rand_df(30, k=int, v=float)
    b = make_rand_df(10, k=int)
    eq_sqlite("SELECT * FROM a WHERE k IN (SELECT k FROM b)", a=a, b=b)
    eq_sqlite("SELECT * FROM a WHERE k NOT IN (SELECT k FROM b)", a=a, b=b)


def test_scalar_subquery_compat():
    a = make_rand_df(30, k=int, v=float)
    eq_sqlite("SELECT * FROM a WHERE v > (SELECT AVG(v) FROM a)", a=a)


def test_having_compat():
    a = make_rand_df(50, g=(str, 5), v=float)
    eq_sqlite(
        "SELECT g, SUM(v) AS s FROM a GROUP BY g HAVING COUNT(*) > 5", a=a)


def test_string_funcs_compat():
    a = make_rand_df(30, s=(str, 5))
    eq_sqlite("SELECT UPPER(s) AS u, LOWER(s) AS l, LENGTH(s) AS n FROM a", a=a)
    eq_sqlite("SELECT * FROM a WHERE s LIKE 's1%'", a=a)


def test_cte_compat():
    a = make_rand_df(30, k=int, v=float)
    eq_sqlite(
        """WITH big AS (SELECT * FROM a WHERE v > 5),
                agg AS (SELECT k, COUNT(*) AS n FROM big GROUP BY k)
           SELECT * FROM agg""", a=a)


def test_outer_order_limit_over_setop_and_raw():
    """ORDER BY/LIMIT/OFFSET outside CTE+set-op or parenthesized bodies must
    apply exactly once (regression: OFFSET was applied twice)."""
    a = pd.DataFrame({"x": [1, 2, 3, 4, 5]})
    eq_sqlite(
        "WITH c AS (SELECT x FROM a) "
        "SELECT x FROM c UNION ALL SELECT 99 ORDER BY 1 LIMIT 3 OFFSET 1",
        a=a)
    eq_sqlite("SELECT x FROM a UNION SELECT x + 10 FROM a ORDER BY 1 LIMIT 4",
              a=a)
    # sqlite cannot parse these two shapes; assert directly
    from dask_sql_tpu import Context
    c = Context()
    c.create_table("a", a)
    got = c.sql("VALUES (3), (1), (2) ORDER BY 1 LIMIT 2").to_pandas()
    assert got.iloc[:, 0].tolist() == [1, 2]
    got = c.sql("(SELECT x FROM a ORDER BY x DESC LIMIT 4) LIMIT 2").to_pandas()
    assert sorted(got["x"].tolist()) == [4, 5]


def test_window_compat():
    a = make_rand_df(30, g=(str, 3), v=float)
    eq_sqlite(
        """SELECT g, v,
                  ROW_NUMBER() OVER (PARTITION BY g ORDER BY v) AS r,
                  SUM(v) OVER (PARTITION BY g ORDER BY v) AS s
           FROM a""", a=a)


def test_complex_query():
    a = make_rand_df(60, g=(str, 10), k=int, v=(float, 10))
    b = make_rand_df(20, k=int, w=float)
    eq_sqlite(
        """SELECT a.g, COUNT(*) AS n, SUM(a.v * b.w) AS dot
           FROM a JOIN b ON a.k = b.k
           WHERE a.v IS NOT NULL
           GROUP BY a.g
           HAVING COUNT(*) > 1
           ORDER BY dot DESC
           LIMIT 5""", check_row_order=False, a=a, b=b)


# ---------------------------------------------------------------------------
# randomized scenario classes mirroring the rest of the reference suite
# (test_compatibility.py:98-920): dedup, in/between, cross join, typed agg
# matrices, window frames, nested queries, CTE integration
# ---------------------------------------------------------------------------

def test_drop_duplicates_rand():
    a = make_rand_df(100, a=int, b=(str, 30))
    eq_sqlite("SELECT DISTINCT b, a FROM a", a=a)
    eq_sqlite("SELECT DISTINCT a FROM a", a=a)


def test_order_by_no_limit_rand():
    a = make_rand_df(100, a=(int, 40), b=(str, 40))
    eq_sqlite("SELECT * FROM a ORDER BY a NULLS FIRST, b NULLS LAST",
              check_row_order=True, a=a)


def test_in_between_rand():
    a = make_rand_df(50, a=(int, 10), b=(str, 10))
    eq_sqlite("SELECT * FROM a WHERE a IN (2, 4, 6)", a=a)
    eq_sqlite("SELECT * FROM a WHERE a BETWEEN 3 AND 7", a=a)
    eq_sqlite("SELECT * FROM a WHERE a NOT BETWEEN 3 AND 7", a=a)


def test_join_cross_rand():
    a = make_rand_df(10, a=int, b=(str, 3))
    b = make_rand_df(5, c=float, d=(int, 2))
    eq_sqlite("SELECT * FROM a CROSS JOIN b", a=a, b=b)


def test_agg_count_typed_rand():
    a = make_rand_df(
        100, a=int, b=str, c=float, d=(int, 50), e=(str, 50), f=(float, 50))
    eq_sqlite(
        """
        SELECT a, b, COUNT(c) AS c_ct, COUNT(d) AS d_ct, COUNT(e) AS e_ct,
               COUNT(f) AS f_ct, COUNT(*) AS n
        FROM a GROUP BY a, b
        """, a=a)


def test_agg_sum_avg_typed_rand():
    a = make_rand_df(100, a=int, b=str, c=float, d=(int, 50), f=(float, 50))
    eq_sqlite(
        """
        SELECT a, b, SUM(c) AS sc, SUM(d) AS sd, SUM(f) AS sf,
               AVG(c) AS ac, AVG(d) AS ad, AVG(f) AS af
        FROM a GROUP BY a, b
        """, a=a)
    eq_sqlite("SELECT SUM(c) AS sc, AVG(d) AS ad FROM a", a=a)


def test_agg_min_max_typed_rand():
    a = make_rand_df(
        100, a=int, b=str, c=float, d=(int, 50), e=(str, 50), f=(float, 50))
    eq_sqlite(
        """
        SELECT a, b, MIN(c) AS mc, MAX(c) AS xc, MIN(d) AS md, MAX(d) AS xd,
               MIN(e) AS me, MAX(e) AS xe, MIN(f) AS mf, MAX(f) AS xf
        FROM a GROUP BY a, b
        """, a=a)
    eq_sqlite("SELECT MIN(c) AS mc, MAX(e) AS xe FROM a", a=a)


def test_window_row_number_rand():
    a = make_rand_df(10, a=int, b=(float, 5))
    eq_sqlite(
        """
        SELECT *,
            ROW_NUMBER() OVER (ORDER BY a ASC, b DESC NULLS FIRST) AS a1,
            ROW_NUMBER() OVER (ORDER BY a ASC, b ASC NULLS LAST) AS a2,
            ROW_NUMBER() OVER (PARTITION BY a ORDER BY b DESC NULLS FIRST) AS a3
        FROM a
        ORDER BY a, b NULLS FIRST
        """, check_row_order=True, a=a)


def test_window_row_number_partition_rand():
    a = make_rand_df(100, a=(int, 50), b=(str, 50), c=(int, 30), e=float)
    eq_sqlite(
        """
        SELECT *,
            ROW_NUMBER() OVER (ORDER BY a ASC NULLS LAST, b DESC NULLS FIRST, e) AS a1,
            ROW_NUMBER() OVER (PARTITION BY a, c ORDER BY b DESC NULLS LAST, e) AS a2
        FROM a
        ORDER BY a NULLS FIRST, b NULLS FIRST, c NULLS FIRST, e
        """, check_row_order=True, a=a)


def test_window_sum_avg_frames_rand():
    a = make_rand_df(100, a=float, b=(int, 50), c=(str, 50))
    for func in ["SUM", "AVG"]:
        eq_sqlite(
            f"""
            SELECT a, b,
                {func}(b) OVER () AS a1,
                {func}(b) OVER (PARTITION BY c) AS a2,
                {func}(b+a) OVER (PARTITION BY c, b) AS a3,
                {func}(b+a) OVER (PARTITION BY b ORDER BY a NULLS FIRST
                    ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW) AS a4,
                {func}(b+a) OVER (PARTITION BY b ORDER BY a DESC NULLS FIRST
                    ROWS BETWEEN 2 PRECEDING AND CURRENT ROW) AS a5
            FROM a
            ORDER BY a NULLS FIRST, b NULLS FIRST, c NULLS FIRST
            """, a=a)


def test_window_irregular_frames_rand():
    a = make_rand_df(100, a=float, b=(int, 50), c=(str, 50))
    eq_sqlite(
        """
        SELECT a, b,
            SUM(b) OVER (PARTITION BY b ORDER BY a DESC NULLS FIRST
                ROWS BETWEEN 2 PRECEDING AND 1 PRECEDING) AS a6,
            SUM(b) OVER (PARTITION BY b ORDER BY a DESC NULLS FIRST
                ROWS BETWEEN 2 PRECEDING AND 1 FOLLOWING) AS a7,
            SUM(b) OVER (PARTITION BY b ORDER BY a DESC NULLS FIRST
                ROWS BETWEEN 2 PRECEDING AND UNBOUNDED FOLLOWING) AS a8
        FROM a
        ORDER BY a NULLS FIRST, b NULLS FIRST, c NULLS FIRST
        """, a=a)


def test_window_min_max_rand():
    a = make_rand_df(100, a=float, b=(int, 50), c=(str, 50))
    for func in ["MIN", "MAX"]:
        eq_sqlite(
            f"""
            SELECT a, b,
                {func}(b) OVER () AS a1,
                {func}(b) OVER (PARTITION BY c) AS a2,
                {func}(b+a) OVER (PARTITION BY b ORDER BY a NULLS FIRST
                    ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW) AS a4
            FROM a
            ORDER BY a NULLS FIRST, b NULLS FIRST, c NULLS FIRST
            """, a=a)


def test_window_count_rand():
    a = make_rand_df(100, a=float, b=(int, 50), c=(str, 50))
    eq_sqlite(
        """
        SELECT a, b,
            COUNT(b) OVER () AS a1,
            COUNT(b) OVER (PARTITION BY c) AS a2,
            COUNT(b) OVER (PARTITION BY b ORDER BY a NULLS FIRST
                ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW) AS a4
        FROM a
        ORDER BY a NULLS FIRST, b NULLS FIRST, c NULLS FIRST
        """, a=a)


def test_nested_query_rand():
    a = make_rand_df(100, a=(int, 40), b=(str, 40), c=(float, 40))
    eq_sqlite(
        """
        SELECT b, AVG(c) AS cc FROM
            (SELECT * FROM a WHERE a >= 2) t
        GROUP BY b
        """, a=a)


def test_integration_cte_join_rand():
    a = make_rand_df(100, a=int, b=str, c=float, d=int, e=bool, f=str, h=float)
    eq_sqlite(
        """
        WITH
            a1 AS (SELECT a+1 AS a, b, c FROM a),
            a2 AS (SELECT a, MAX(b) AS b_max, AVG(c) AS c_avg FROM a GROUP BY a),
            a3 AS (SELECT d+2 AS d, f, h FROM a WHERE e)
        SELECT a1.a, b, c, b_max, c_avg, f, h FROM a1
            INNER JOIN a2 ON a1.a = a2.a
            LEFT JOIN a3 ON a1.a = a3.d
        ORDER BY a1.a NULLS FIRST, b NULLS FIRST, c NULLS FIRST,
                 f NULLS FIRST, h NULLS FIRST
        """, check_row_order=True, a=a)


# ---------------------------------------------------------------------------
# r2 additions: the reference scenario classes VERDICT r1 flagged as missing
# (test_compatibility.py:98-920): randomized nullable joins over many key
# types, ORDER BY NULL permutations at scale, randomized INTERSECT/EXCEPT,
# and the agg-over-empty-group edge matrix
# ---------------------------------------------------------------------------

def test_join_nullable_int_keys_rand():
    a = make_rand_df(60, k=(int, 20), va=float)
    b = make_rand_df(40, k=(int, 15), vb=float)
    # NULL keys join nothing (inner) / NULL-extend (left) — both oracles
    eq_sqlite("SELECT a.k, va, vb FROM a JOIN b ON a.k = b.k", a=a, b=b)
    eq_sqlite("SELECT a.k, va, vb FROM a LEFT JOIN b ON a.k = b.k", a=a, b=b)


def test_join_nullable_string_keys_rand():
    a = make_rand_df(60, k=(str, 20), va=float)
    b = make_rand_df(40, k=(str, 15), vb=float)
    eq_sqlite("SELECT a.k, va, vb FROM a JOIN b ON a.k = b.k", a=a, b=b)
    eq_sqlite("SELECT a.k, va, vb FROM a LEFT JOIN b ON a.k = b.k", a=a, b=b)


def test_join_nullable_float_keys_rand():
    a = make_rand_df(50, k=(float, 15), va=int)
    b = make_rand_df(30, k=(float, 10), vb=int)
    eq_sqlite("SELECT a.k, va, vb FROM a JOIN b ON a.k = b.k", a=a, b=b)


def test_join_nullable_bool_keys_rand():
    a = make_rand_df(30, k=(bool, 8), va=float)
    b = make_rand_df(20, k=(bool, 5), vb=float)
    eq_sqlite("SELECT a.k, va, vb FROM a JOIN b ON a.k = b.k", a=a, b=b)


def test_join_mixed_nullable_multi_key_rand():
    a = make_rand_df(80, k1=(int, 25), k2=(str, 25), va=float)
    b = make_rand_df(60, k1=(int, 20), k2=(str, 20), vb=float)
    eq_sqlite(
        """SELECT a.k1, a.k2, va, vb FROM a
           JOIN b ON a.k1 = b.k1 AND a.k2 = b.k2""", a=a, b=b)
    eq_sqlite(
        """SELECT a.k1, a.k2, va, vb FROM a
           LEFT JOIN b ON a.k1 = b.k1 AND a.k2 = b.k2""", a=a, b=b)


def test_order_by_null_permutations_at_scale():
    a = make_rand_df(300, a=(int, 100), b=(str, 100), c=(float, 100))
    for mods in ("a NULLS FIRST, b NULLS FIRST, c NULLS FIRST",
                 "a NULLS LAST, b NULLS FIRST, c NULLS LAST",
                 "a DESC NULLS FIRST, b NULLS LAST, c DESC NULLS LAST",
                 "a DESC NULLS LAST, b DESC NULLS FIRST, c NULLS FIRST"):
        eq_sqlite(f"SELECT * FROM a ORDER BY {mods}",
                  check_row_order=True, a=a)


def test_intersect_except_rand():
    a = make_rand_df(60, x=(int, 10), y=(str, 10))
    b = make_rand_df(60, x=(int, 10), y=(str, 10))
    eq_sqlite("SELECT x, y FROM a INTERSECT SELECT x, y FROM b", a=a, b=b)
    eq_sqlite("SELECT x, y FROM a EXCEPT SELECT x, y FROM b", a=a, b=b)
    eq_sqlite("SELECT x FROM a EXCEPT SELECT x FROM b", a=a, b=b)
    eq_sqlite("SELECT y FROM a INTERSECT SELECT y FROM b", a=a, b=b)


def test_agg_over_empty_group_matrix():
    a = make_rand_df(40, g=(str, 10), i=(int, 10), f=(float, 10), s=(str, 15))
    # empty input (WHERE FALSE): global aggs -> one row of NULLs/zero
    eq_sqlite(
        """SELECT SUM(i) AS si, AVG(f) AS af, MIN(s) AS ms, MAX(i) AS xi,
                  COUNT(i) AS ci, COUNT(*) AS n
           FROM a WHERE i > 1000""", a=a)
    # groups whose every member is NULL in the aggregated column
    eq_sqlite(
        """SELECT g, SUM(i) AS si, AVG(f) AS af, COUNT(i) AS ci,
                  COUNT(*) AS n, MIN(f) AS mf, MAX(s) AS xs
           FROM a GROUP BY g""", a=a)
    # HAVING over an empty grouping
    eq_sqlite(
        """SELECT g, COUNT(*) AS n FROM a WHERE i > 1000
           GROUP BY g HAVING COUNT(*) > 0""", a=a)


def test_self_join_rand():
    a = make_rand_df(40, k=(int, 10), v=float)
    eq_sqlite(
        """SELECT x.k, x.v AS xv, y.v AS yv
           FROM a x JOIN a y ON x.k = y.k WHERE x.v < y.v""", a=a)


def test_anti_semi_rand():
    a = make_rand_df(60, k=(int, 15), v=float)
    b = make_rand_df(30, k=(int, 10))
    eq_sqlite("SELECT * FROM a WHERE EXISTS "
              "(SELECT 1 FROM b WHERE b.k = a.k)", a=a, b=b)
    eq_sqlite("SELECT * FROM a WHERE NOT EXISTS "
              "(SELECT 1 FROM b WHERE b.k = a.k)", a=a, b=b)
